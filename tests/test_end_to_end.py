"""End-to-end slice tests: par+tim -> residuals -> WLS fit.

(reference test patterns: tests/test_B1855.py-style golden comparison —
here golden = self-consistency of simulate->fit since no external
TEMPO outputs can exist in this offline environment; plus
tests/test_fitter.py-style recovery checks.)
"""

import copy
import os
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

import pint_tpu
from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.fitter import WLSFitter, DownhillWLSFitter
from pint_tpu.simulation import make_fake_toas_fromMJDs, make_fake_toas_uniform

EXAMPLES = os.path.join(os.path.dirname(pint_tpu.__file__), "data", "examples")

PAR = """
PSR TEST1
RAJ 17:48:52.75 1
DECJ -20:21:29.0 1
F0 61.485476554 1
F1 -1.181e-15 1
PEPOCH 53750
POSEPOCH 53750
DM 223.9 1
"""


@pytest.fixture(scope="module")
def model():
    return get_model(PAR)


@pytest.fixture(scope="module")
def toas(model):
    mjds = np.linspace(53000, 54500, 40)
    freqs = np.where(np.arange(40) % 2, 1400.0, 430.0)
    return make_fake_toas_fromMJDs(mjds, model, error_us=1.0, freq_mhz=freqs,
                                   obs="gbt", add_noise=True, seed=42)


def test_simulation_zero_residuals(model):
    t = make_fake_toas_uniform(53100, 54400, 25, model, error_us=1.0,
                               obs="gbt", add_noise=False)
    r = Residuals(t, model)
    # zero-residual iteration should leave < 1 ns
    assert r.rms_weighted() < 1e-9


def test_residual_chi2_sane(model, toas):
    r = Residuals(toas, model)
    assert 0.3 < r.reduced_chi2 < 3.0


def test_design_matrix_matches_finite_difference(model, toas):
    prep = model.prepare(toas)
    M, labels = prep.designmatrix()
    base = np.asarray(prep._phase_continuous(prep.params0))
    for pname, eps in [("DM", 1e-6), ("DECJ", 1e-9)]:
        m2 = copy.deepcopy(model)
        par = getattr(m2, pname)
        par.value = par.value + eps
        p2 = m2.prepare(toas)
        fd = (np.asarray(p2._phase_continuous(p2.params0)) - base) / eps
        ad = np.asarray(M[:, labels.index(pname)])
        np.testing.assert_allclose(fd, ad, rtol=1e-4,
                                   atol=1e-4 * np.abs(ad).max())


def test_wls_recovers_perturbation(model, toas):
    m2 = copy.deepcopy(model)
    m2.F0.value += 1e-9
    m2.F1.value += 2e-17
    m2.DM.value += 1e-3
    m2.RAJ.value += 2e-7
    m2.DECJ.value += 2e-7
    f = DownhillWLSFitter(toas, m2)
    f.fit_toas()
    assert f.resids.reduced_chi2 < 2.0
    for p in ["F0", "F1", "DM", "RAJ", "DECJ"]:
        true = getattr(model, p).value
        fit = getattr(f.model, p).value
        unc = getattr(f.model, p).uncertainty
        assert abs(fit - true) < 5 * unc, f"{p} off by {(fit-true)/unc:.1f} sigma"


def test_wls_uncertainty_scale(model, toas):
    f = WLSFitter(toas, copy.deepcopy(model))
    f.fit_toas()
    # F0 uncertainty should be roughly sigma_phase/(F0 span scale):
    # 1 us over 1500 days at 40 TOAs -> ~1e-13 Hz
    unc = f.model.F0.uncertainty
    assert 1e-14 < unc < 1e-11


def test_example_files_fit():
    m = get_model(os.path.join(EXAMPLES, "NGC6440E.par"))
    from pint_tpu.toa import get_TOAs

    t = get_TOAs(os.path.join(EXAMPLES, "NGC6440E.tim"))
    assert len(t) == 62
    f = WLSFitter(t, m)
    f.fit_toas()
    assert f.resids.reduced_chi2 < 1.6
    summary = f.get_summary()
    assert "Chi2" in summary and "F0" in summary
    # post-fit summaries list strong parameter correlations
    assert "correlations" in summary  # F0/F1 are correlated here


def test_parfile_roundtrip(model):
    s = model.as_parfile()
    m2 = get_model(s)
    assert set(m2.free_params) == set(model.free_params)
    assert m2.F0.value == pytest.approx(model.F0.value, rel=1e-14)
    assert m2.RAJ.value == pytest.approx(model.RAJ.value, abs=1e-12)
    assert m2.PEPOCH.day == model.PEPOCH.day


def test_tim_roundtrip(model, toas, tmp_path):
    p = tmp_path / "out.tim"
    toas.write_TOA_file(p)
    from pint_tpu.toa import get_TOAs

    t2 = get_TOAs(p)
    assert len(t2) == len(toas)
    np.testing.assert_array_equal(t2.day, toas.day)
    np.testing.assert_allclose(t2.sec, toas.sec, atol=1e-7)  # 16-digit MJD ~ 0.1 ns
    np.testing.assert_allclose(t2.error_us, toas.error_us, atol=1e-3)


def test_phase_connection_across_span(model):
    """Pulse numbering must be exact across a decade gap."""
    t = make_fake_toas_uniform(50000, 58000, 30, model, error_us=1.0,
                               obs="gbt", add_noise=False)
    r = Residuals(t, model)
    assert r.rms_weighted() < 1e-9


def test_kitchen_sink_parfile_roundtrip():
    """Every component's parameters must survive par -> model ->
    as_parfile -> model (the par file is the checkpoint; SURVEY.md
    section 5). One model carrying nearly every component class."""
    import warnings

    import numpy as np

    from pint_tpu.models import get_model

    par = """
PSR SINK
RAJ 04:37:15.8 1
DECJ -47:15:09.1 1
PMRA 121.4 1
PMDEC -71.5 1
PX 6.4 1
POSEPOCH 55000
F0 173.687946 1
F1 -1.728e-15 1
F2 1e-26
PEPOCH 55000
DM 2.64 1
DM1 1e-4
DMEPOCH 55000
DMX_0001 1e-3 1
DMXR1_0001 54900
DMXR2_0001 55100
NE_SW 7.9 1
CORRECT_TROPOSPHERE Y
PLANET_SHAPIRO Y
BINARY ELL1
PB 5.741 1
A1 3.3667 1
TASC 54501.4671 1
EPS1 1.9e-5 1
EPS2 -1.4e-5 1
M2 0.224 1
SINI 0.674 1
GLEP_1 55300
GLPH_1 0.2
GLF0_1 1e-8
GLF1_1 -1e-16
GLF0D_1 2e-8
GLTD_1 100
WAVEEPOCH 55000
WAVE_OM 0.005
WAVE1 0.01 -0.02
FD1 1e-5 1
FD2 -2e-6
SIFUNC 2
IFUNC1 54950 1e-6
IFUNC2 55400 -2e-6
PHOFF 0.1 1
TZRMJD 55000.123
TZRSITE gbt
TZRFRQ 1400
JUMP -fe L-wide 1e-5 1
DMJUMP -fe L-wide 1e-3 1
EFAC -fe L-wide 1.1
EQUAD -fe L-wide 0.3
ECORR -fe L-wide 0.7
RNAMP 1e-14
RNIDX -3.2
TNREDC 20
"""
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # no unrecognized-line warnings
        m = get_model(par)
    # uncertainties are model state (post-fit par files carry them)
    m.F0.uncertainty = 3.2e-13
    m.DM.uncertainty = 1.5e-5
    m.PB.uncertainty = 4e-9
    txt = m.as_parfile()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        m2 = get_model(txt)
    assert set(m2.components) == set(m.components), (
        set(m.components) ^ set(m2.components))
    for p in m.params:
        a, b = getattr(m, p), getattr(m2, p, None)
        assert b is not None, f"param {p} lost in round trip"
        if a.value is None or a.kind in ("str", "bool", "func"):
            continue
        if a.kind == "pair":  # WAVEn sin/cos pairs: element-wise
            assert np.allclose(np.asarray(a.value, float),
                               np.asarray(b.value, float),
                               rtol=0, atol=1e-12), (p, a.value, b.value)
            continue
        try:
            av, bv = float(a.value), float(b.value)
        except (TypeError, ValueError):
            continue
        assert np.isclose(av, bv, rtol=0, atol=max(1e-12, 1e-10 * abs(av))), \
            (p, av, bv)
        assert a.frozen == b.frozen, f"fit flag of {p} flipped"
        if a.uncertainty is not None:  # uncertainties are state too
            assert b.uncertainty is not None, f"uncertainty of {p} dropped"
            assert np.isclose(a.uncertainty, b.uncertainty, rtol=1e-4), p


def test_whitened_resids_and_lnlikelihood():
    """Whitened residuals have ~unit variance on well-modeled data and
    lnlikelihood = -(chi2 + sum log 2 pi sigma^2)/2, maximized at the
    true parameters (reference: Residuals.calc_whitened_resids /
    lnlikelihood)."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_uniform

    par = ("PSR TLNL\nRAJ 7:30:00\nDECJ -2:00:00\nF0 340.1 1\n"
           "F1 -1e-15 1\nPEPOCH 55500\nDM 12.5 1\nEFAC -f L 1.4\n")
    m = get_model(par)
    t = make_fake_toas_uniform(55000, 56000, 120, m, error_us=1.0,
                               add_noise=True, flags={"f": "L"}, seed=12)
    r = Residuals(t, m)
    w = np.asarray(r.calc_whitened_resids())
    assert 0.7 < w.std() < 1.3  # EFAC 1.4 accounted for in whitening
    sigma = np.asarray(r.prepared.scaled_sigma_us()) * 1e-6
    expect = -0.5 * (r.chi2 + np.sum(np.log(2 * np.pi * sigma**2)))
    assert abs(r.lnlikelihood() - expect) < 1e-9
    # worse parameters give lower likelihood
    m2 = get_model(par)
    m2.F0.value += 3e-9
    assert Residuals(t, m2).lnlikelihood() < r.lnlikelihood()


def test_postfit_parfile_carries_fit_stats():
    """Post-fit par files record START/FINISH/NTOA/TRES/CHI2
    (reference: Fitter.update_model)."""
    m = get_model(os.path.join(EXAMPLES, "NGC6440E.par"))
    from pint_tpu.toa import get_TOAs

    t = get_TOAs(os.path.join(EXAMPLES, "NGC6440E.tim"))
    f = WLSFitter(t, m)
    f.fit_toas()
    par = f.model.as_parfile()
    for key in ("START", "FINISH", "NTOA", "TRES", "CHI2"):
        assert f"\n{key} " in par or par.startswith(f"{key} "), key
    m2 = get_model(par)
    assert m2.NTOA.value == 62
    assert abs(m2.TRES.value - f.resids.rms_weighted() * 1e6) < 1e-6
    assert abs(m2.START.value - t.get_mjds().min()) < 1e-6
    # refit from the stats-carrying par: stats update, no duplication
    f2 = WLSFitter(t, m2)
    f2.fit_toas()
    assert f2.model.as_parfile().count("NTOA") == 1


def test_glitch_parameter_recovery():
    """Inject a glitch (phase jump + frequency step + decaying term),
    simulate, perturb, and refit: the glitch parameters come back
    within a few sigma. (reference pattern: tests/test_glitch.py —
    upstream checks glitch fitting on TOAs spanning the epoch.)"""
    import copy

    from pint_tpu.fitter import DownhillWLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR GLREC\nRAJ 08:35:20.6\nDECJ -45:10:34.8\n"
           "F0 11.194565 1\nF1 -1.567e-11 1\nPEPOCH 55500\nDM 67.99\n"
           "GLEP_1 55500.0\nGLPH_1 0.0 1\nGLF0_1 2.5e-6 1\n"
           "GLF1_1 -1.2e-14 1\nGLF0D_1 1.1e-7 1\nGLTD_1 120.0\n")
    m_true = get_model(par)
    rng = np.random.default_rng(17)
    mjds = np.sort(rng.uniform(55000, 56000, 220))
    t = make_fake_toas_fromMJDs(mjds, m_true, error_us=20.0,
                                freq_mhz=1400.0, obs="parkes",
                                add_noise=True, seed=17)
    # start within phase coherence (|dGLF0|*span < ~0.2 cycles, as a
    # real glitch fit would after pulse numbering); tens of sigma off
    m_fit = copy.deepcopy(m_true)
    m_fit.GLF0_1.value = 2.497e-6
    m_fit.GLF1_1.value = -1.19e-14
    m_fit.GLF0D_1.value = 1.0e-7
    f = DownhillWLSFitter(t, m_fit)
    f.fit_toas()
    for p, true_val in (("GLF0_1", 2.5e-6), ("GLF1_1", -1.2e-14),
                        ("GLF0D_1", 1.1e-7)):
        got = getattr(f.model, p).value
        sig = getattr(f.model, p).uncertainty
        assert abs(got - true_val) < 5 * sig, (p, got, true_val, sig)
        assert abs(got - true_val) < 0.2 * abs(true_val), (p, got)
    assert f.resids.chi2 / f.resids.dof < 1.6
