"""Pallas kernel mirrors vs their jnp reference implementations.

The TPU kernels run in interpreter mode here (conftest forces the CPU
backend), which executes the same kernel logic; the real-chip speedup
is measured by benchmarks/kernel_bench.py.
"""

import numpy as np
import pytest

from pint_tpu.kernels import harmonic_sums_jnp, harmonic_sums_pallas


@pytest.mark.parametrize("n,m", [(1000, 5), (200_000, 20), (8192, 1)])
def test_harmonic_sums_matches_jnp(n, m):
    rng = np.random.default_rng(42)
    ph = rng.random(n)
    c0, s0 = harmonic_sums_jnp(ph, m)
    c1, s1 = harmonic_sums_pallas(ph, m, interpret=True)
    # f32 block accumulation: absolute error ~n * 2^-24-class
    tol = max(4e-8 * n, 1e-4)
    assert np.abs(np.asarray(c0) - np.asarray(c1)).max() < tol
    assert np.abs(np.asarray(s0) - np.asarray(s1)).max() < tol


def test_harmonic_sums_weighted_and_padding():
    """Weights flow through, and the block padding contributes zero
    (n deliberately NOT a multiple of the 8192-photon block)."""
    rng = np.random.default_rng(1)
    n = 8192 * 3 + 517
    ph = rng.random(n)
    w = rng.random(n)
    c0, s0 = harmonic_sums_jnp(ph, 8, w)
    c1, s1 = harmonic_sums_pallas(ph, 8, weights=w, interpret=True)
    tol = 4e-8 * n
    assert np.abs(np.asarray(c0) - np.asarray(c1)).max() < tol
    assert np.abs(np.asarray(s0) - np.asarray(s1)).max() < tol


def test_block_grams_pallas_matches_jnp(pallas_interpret):
    """The seggram Pallas kernel (interpret mode on CPU) against its
    f64 jnp reference: f32 block products, <= 1e-6 relative."""
    from pint_tpu.kernels.seggram import block_grams_jnp, block_grams_pallas

    rng = np.random.default_rng(11)
    n, k, block = 256, 21, 32  # k deliberately NOT lane-aligned
    x = rng.normal(size=(n, k))
    ref = np.asarray(block_grams_jnp(x, block))
    out = np.asarray(block_grams_pallas(x, block,
                                        interpret=pallas_interpret))
    assert out.shape == ref.shape
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() <= 1e-6 * scale


def test_fused_block_gls_pallas_matches_jnp(pallas_interpret):
    """The fused whiten+Gram kernel (interpret mode on CPU) against
    the f64 fused reference: same augmented-tile factorization, f32
    in-kernel whitening and MXU accumulation, <= 1e-6 relative."""
    from pint_tpu.kernels.fusedgls import (augment, fused_block_gls_jnp,
                                           fused_block_gls_pallas)

    rng = np.random.default_rng(12)
    n, k, block = 192, 9, 32
    x = rng.normal(size=(n, k))
    r = rng.normal(size=n)
    winv = 1.0 / rng.uniform(0.5, 2.0, n)
    aug = np.asarray(augment(x, r, winv))
    ref = np.asarray(fused_block_gls_jnp(aug, block))
    out = np.asarray(fused_block_gls_pallas(aug, block,
                                            interpret=pallas_interpret))
    assert out.shape == ref.shape
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() <= 1e-6 * scale


def test_fused_segment_gls_interpret_end_to_end(pallas_interpret):
    """The full fused segment pass — Pallas kernel (interpreted on
    CPU) + f64 segment reduction — against the f64 reference and a
    dense per-segment recomputation."""
    from pint_tpu.kernels import fused_segment_gls, fused_segment_gls_jnp

    rng = np.random.default_rng(13)
    n, k, block, n_seg = 256, 7, 16, 3
    x = rng.normal(size=(n, k))
    r = rng.normal(size=n)
    winv = 1.0 / rng.uniform(0.5, 2.0, n)
    block_seg = (np.arange(n // block) % n_seg).astype(np.int32)
    a_ref, b_ref, rnr_ref = (np.asarray(v) for v in fused_segment_gls_jnp(
        x, r, winv, block_seg, n_seg, block))
    # dense recomputation per segment
    owner = np.repeat(block_seg, block)
    for s in range(n_seg):
        m = owner == s
        mw = x[m] * winv[m][:, None]
        zw = r[m] * winv[m]
        assert np.allclose(a_ref[s], mw.T @ mw, rtol=0, atol=1e-12)
        assert np.allclose(b_ref[s], mw.T @ zw, rtol=0, atol=1e-12)
        assert np.isclose(rnr_ref[s], zw @ zw, rtol=0, atol=1e-12)
    # mixed dispatch through the (interpreted) kernel: f32 tolerance
    a_mx, b_mx, rnr_mx = (np.asarray(v) for v in fused_segment_gls(
        x, r, winv, block_seg, n_seg, block, precision="mixed",
        interpret=pallas_interpret))
    scale = np.abs(a_ref).max()
    assert np.abs(a_mx - a_ref).max() <= 1e-6 * scale
    assert np.abs(b_mx - b_ref).max() <= 1e-6 * np.abs(b_ref).max()
    assert np.abs(rnr_mx - rnr_ref).max() <= 1e-6 * np.abs(rnr_ref).max()
    # f64 dispatch is the reference bit-for-bit
    a64, b64, rnr64 = (np.asarray(v) for v in fused_segment_gls(
        x, r, winv, block_seg, n_seg, block, precision="f64"))
    assert np.array_equal(a64, a_ref)
    assert np.array_equal(b64, b_ref)
    assert np.array_equal(rnr64, rnr_ref)


def test_fused_pallas_fallback_is_visible(monkeypatch, caplog):
    """A failing Pallas dispatch must fall back to the f32 emulation
    AND leave a trail: counter bump, flight-recorder note, one log
    warning — never a silent except/pass."""
    import logging

    from pint_tpu.kernels import fallback as fb
    from pint_tpu.kernels import fusedgls
    from pint_tpu.obs import RECORDER, REGISTRY

    def boom(*a, **kw):
        raise RuntimeError("mosaic lowering unavailable")

    monkeypatch.setattr(fusedgls, "fused_segment_gls_pallas", boom)
    # logging_setup.setup() (run by any earlier CLI-script test) pins
    # propagate=False on the "pint_tpu" logger, which would strand the
    # fallback warning below caplog's root handler
    monkeypatch.setattr(logging.getLogger("pint_tpu"), "propagate", True)
    fb.reset_warned_for_tests()
    before = REGISTRY.counter(fb.COUNTER_NAME).value
    rng = np.random.default_rng(14)
    n, k, block = 64, 5, 16
    x = rng.normal(size=(n, k))
    r = rng.normal(size=n)
    winv = np.ones(n)
    block_seg = np.zeros(n // block, np.int32)
    with caplog.at_level(logging.WARNING,
                         logger="pint_tpu.kernels.fallback"):
        a, b, rnr = fusedgls.fused_segment_gls(
            x, r, winv, block_seg, 1, block,
            precision="mixed", interpret=True)
    assert REGISTRY.counter(fb.COUNTER_NAME).value == before + 1
    assert any("fell back" in r.getMessage() for r in caplog.records)
    # the result is the f32 emulation, not garbage
    a_ref, b_ref, _ = fusedgls.fused_segment_gls_f32_jnp(
        x, r, winv, block_seg, 1, block)
    assert np.array_equal(np.asarray(a), np.asarray(a_ref))
    assert np.array_equal(np.asarray(b), np.asarray(b_ref))
    # warn-once: a second identical failure is counted, not re-logged
    caplog.clear()
    with caplog.at_level(logging.WARNING,
                         logger="pint_tpu.kernels.fallback"):
        fusedgls.fused_segment_gls(x, r, winv, block_seg, 1, block,
                                   precision="mixed", interpret=True)
    assert REGISTRY.counter(fb.COUNTER_NAME).value == before + 2
    assert not any("fell back" in r.getMessage()
                   for r in caplog.records)
    # the flight recorder carries the kernel name + reason
    notes = [e for e in RECORDER.events()
             if e.get("what") == "pallas_fallback"]
    assert notes and "fusedgls" in notes[-1]["kernel"]
    assert "mosaic lowering unavailable" in notes[-1]["reason"]


def test_z2m_h_test_through_kernel_path():
    """End statistic: H-test of a pulsed signal is unchanged (to stat
    noise) whichever path computes the harmonic sums."""
    from pint_tpu.eventstats import hm, z2m

    rng = np.random.default_rng(7)
    n = 100_000
    ph = np.concatenate([(rng.normal(0.3, 0.05, n // 4)) % 1.0,
                         rng.random(3 * n // 4)])
    h = float(hm(ph, m=20))
    z = np.asarray(z2m(ph, m=4))
    assert h > 1000  # strongly pulsed
    assert z.shape == (4,) and np.all(np.diff(z) >= 0)
