"""Pallas kernel mirrors vs their jnp reference implementations.

The TPU kernels run in interpreter mode here (conftest forces the CPU
backend), which executes the same kernel logic; the real-chip speedup
is measured by benchmarks/kernel_bench.py.
"""

import numpy as np
import pytest

from pint_tpu.kernels import harmonic_sums_jnp, harmonic_sums_pallas


@pytest.mark.parametrize("n,m", [(1000, 5), (200_000, 20), (8192, 1)])
def test_harmonic_sums_matches_jnp(n, m):
    rng = np.random.default_rng(42)
    ph = rng.random(n)
    c0, s0 = harmonic_sums_jnp(ph, m)
    c1, s1 = harmonic_sums_pallas(ph, m, interpret=True)
    # f32 block accumulation: absolute error ~n * 2^-24-class
    tol = max(4e-8 * n, 1e-4)
    assert np.abs(np.asarray(c0) - np.asarray(c1)).max() < tol
    assert np.abs(np.asarray(s0) - np.asarray(s1)).max() < tol


def test_harmonic_sums_weighted_and_padding():
    """Weights flow through, and the block padding contributes zero
    (n deliberately NOT a multiple of the 8192-photon block)."""
    rng = np.random.default_rng(1)
    n = 8192 * 3 + 517
    ph = rng.random(n)
    w = rng.random(n)
    c0, s0 = harmonic_sums_jnp(ph, 8, w)
    c1, s1 = harmonic_sums_pallas(ph, 8, weights=w, interpret=True)
    tol = 4e-8 * n
    assert np.abs(np.asarray(c0) - np.asarray(c1)).max() < tol
    assert np.abs(np.asarray(s0) - np.asarray(s1)).max() < tol


def test_z2m_h_test_through_kernel_path():
    """End statistic: H-test of a pulsed signal is unchanged (to stat
    noise) whichever path computes the harmonic sums."""
    from pint_tpu.eventstats import hm, z2m

    rng = np.random.default_rng(7)
    n = 100_000
    ph = np.concatenate([(rng.normal(0.3, 0.05, n // 4)) % 1.0,
                         rng.random(3 * n // 4)])
    h = float(hm(ph, m=20))
    z = np.asarray(z2m(ph, m=4))
    assert h > 1000  # strongly pulsed
    assert z.shape == (4,) and np.all(np.diff(z) >= 0)
