"""Headless interactive-fitting tests (reference pattern: pintk logic
tested without Tk via pintk/pulsar.py)."""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.pintk import InteractivePulsar
from pint_tpu.residuals import CombinedResiduals, Residuals
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR TESTK
RAJ 08:15:00.0
DECJ 02:30:00.0
F0 88.5 1
F1 -2e-16 1
PEPOCH 55200
DM 11.0 1
"""


@pytest.fixture(scope="module")
def session():
    m = get_model(PAR)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55400, 50), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=8)
    m2 = get_model(PAR)
    m2.F0.value += 2e-9
    return InteractivePulsar(m2, t)


def test_fit_undo_reset(session):
    r_pre = session.resids_us()
    f = session.fit()
    assert f.resids.reduced_chi2 < 2.0
    assert session.fitted
    r_post = session.resids_us()
    assert np.abs(r_post).std() < np.abs(r_pre).std()
    session.undo()
    np.testing.assert_allclose(session.resids_us(), r_pre)
    session.fit()
    session.reset()
    assert not session.fitted
    np.testing.assert_allclose(session.resids_us(), r_pre)


def test_selection_and_jump(session):
    session.reset()
    session.select_mjd_range(55200, 55400)
    n_sel = int(session.selected.sum())
    assert 0 < n_sel < 50
    name = session.add_jump_to_selection()
    assert name in session.model.params
    # A JUMP is a uniform time offset on the selected set. Weighted-mean
    # subtraction redistributes it across ALL residuals (selected shift by
    # JUMP*(1-w), unselected by -JUMP*w with w the selected weight
    # fraction), so "only the selected move" is not the invariant; the
    # *relative* shift between the two sets is exactly the JUMP value.
    jump_s = 1e-4
    getattr(session.model, name).value = jump_s
    r = session.resids_us()
    session.remove_jump(name)
    r0 = session.resids_us()
    delta = r - r0
    sel = session.selected
    shift_sel = delta[sel].mean()
    shift_unsel = delta[~sel].mean()
    # uniform within each group...
    assert np.abs(delta[sel] - shift_sel).max() < 1e-3   # us
    assert np.abs(delta[~sel] - shift_unsel).max() < 1e-3
    # ...and separated by exactly the jump (sign per convention)
    assert abs(abs(shift_sel - shift_unsel) - jump_s * 1e6) < 1e-2
    assert name not in session.model.params
    with pytest.raises(KeyError):
        session.remove_jump("JUMP99")


def test_random_models(session):
    session.reset()
    session.fit()
    spread = session.random_models(n_models=10, seed=1)
    assert spread.shape == (10, 50)
    assert np.isfinite(spread).all()


def test_combined_residuals(session):
    r1 = Residuals(session.toas, session.model)
    c = CombinedResiduals([r1, r1])
    assert c.chi2 == pytest.approx(2 * r1.chi2)
    assert c.dof == 2 * r1.dof
    assert len(c.calc_time_resids()) == 100


def test_func_parameter():
    from pint_tpu.derived_quantities import mass_function
    from pint_tpu.models.parameter import funcParameter

    par = PAR + "BINARY ELL1\nPB 1.2 1\nA1 2.0 1\nTASC 55201.0 1\nEPS1 0\nEPS2 0\n"
    m = get_model(par)
    comp = m.components["BinaryELL1"]
    fp = funcParameter("FMASS", lambda pb, a1: float(mass_function(pb, a1)),
                       ("PB", "A1"), units="Msun")
    comp.add_param(fp)
    assert fp.value == pytest.approx(float(mass_function(1.2, 2.0)))
    with pytest.raises(AttributeError):
        fp.value = 3.0
    assert fp.as_parfile_line() == ""
