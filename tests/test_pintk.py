"""Headless interactive-fitting tests (reference pattern: pintk logic
tested without Tk via pintk/pulsar.py)."""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.pintk import InteractivePulsar
from pint_tpu.residuals import CombinedResiduals, Residuals
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR TESTK
RAJ 08:15:00.0
DECJ 02:30:00.0
F0 88.5 1
F1 -2e-16 1
PEPOCH 55200
DM 11.0 1
"""


@pytest.fixture(scope="module")
def session():
    m = get_model(PAR)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55400, 50), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=8)
    m2 = get_model(PAR)
    m2.F0.value += 2e-9
    return InteractivePulsar(m2, t)


def test_fit_undo_reset(session):
    r_pre = session.resids_us()
    f = session.fit()
    assert f.resids.reduced_chi2 < 2.0
    assert session.fitted
    r_post = session.resids_us()
    assert np.abs(r_post).std() < np.abs(r_pre).std()
    session.undo()
    np.testing.assert_allclose(session.resids_us(), r_pre)
    session.fit()
    session.reset()
    assert not session.fitted
    np.testing.assert_allclose(session.resids_us(), r_pre)


def test_selection_and_jump(session):
    session.reset()
    session.select_mjd_range(55200, 55400)
    n_sel = int(session.selected.sum())
    assert 0 < n_sel < 50
    name = session.add_jump_to_selection()
    assert name in session.model.params
    # A JUMP is a uniform time offset on the selected set. Weighted-mean
    # subtraction redistributes it across ALL residuals (selected shift by
    # JUMP*(1-w), unselected by -JUMP*w with w the selected weight
    # fraction), so "only the selected move" is not the invariant; the
    # *relative* shift between the two sets is exactly the JUMP value.
    jump_s = 1e-4
    getattr(session.model, name).value = jump_s
    r = session.resids_us()
    session.remove_jump(name)
    r0 = session.resids_us()
    delta = r - r0
    sel = session.selected
    shift_sel = delta[sel].mean()
    shift_unsel = delta[~sel].mean()
    # uniform within each group...
    assert np.abs(delta[sel] - shift_sel).max() < 1e-3   # us
    assert np.abs(delta[~sel] - shift_unsel).max() < 1e-3
    # ...and separated by exactly the jump (sign per convention)
    assert abs(abs(shift_sel - shift_unsel) - jump_s * 1e6) < 1e-2
    assert name not in session.model.params
    with pytest.raises(KeyError):
        session.remove_jump("JUMP99")


def test_random_models(session):
    session.reset()
    session.fit()
    spread = session.random_models(n_models=10, seed=1)
    assert spread.shape == (10, 50)
    assert np.isfinite(spread).all()


def test_combined_residuals(session):
    r1 = Residuals(session.toas, session.model)
    c = CombinedResiduals([r1, r1])
    assert c.chi2 == pytest.approx(2 * r1.chi2)
    assert c.dof == 2 * r1.dof
    assert len(c.calc_time_resids()) == 100


def test_func_parameter():
    from pint_tpu.derived_quantities import mass_function
    from pint_tpu.models.parameter import funcParameter

    par = PAR + "BINARY ELL1\nPB 1.2 1\nA1 2.0 1\nTASC 55201.0 1\nEPS1 0\nEPS2 0\n"
    m = get_model(par)
    comp = m.components["BinaryELL1"]
    fp = funcParameter("FMASS", lambda pb, a1: float(mass_function(pb, a1)),
                       ("PB", "A1"), units="Msun")
    comp.add_param(fp)
    assert fp.value == pytest.approx(float(mass_function(1.2, 2.0)))
    with pytest.raises(AttributeError):
        fp.value = 3.0
    assert fp.as_parfile_line() == ""


def test_delete_restore_toas():
    m = get_model(PAR)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55400, 40), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=3)
    s = InteractivePulsar(get_model(PAR), t)
    # lo a hair under 55000: the zero-residual iteration leaves the
    # first UTC MJD ~4e-8 d below its nominal grid point
    s.select_mjd_range(54999.9, 55100)
    n_sel = int(s.selected.sum())
    assert n_sel > 0
    s.delete_selected()
    assert len(s.toas) == 40 - n_sel
    assert (s.toas.get_mjds() > 55100).all()
    # fit still works on the reduced set
    s.fit()
    s.restore_all_toas()
    assert len(s.toas) == 40
    with pytest.raises(ValueError):
        s.delete_selected()  # nothing selected after restore


def test_phase_wraps_shift_residuals():
    """Adding a phase wrap to a block of TOAs moves their tracked
    residuals by exactly one turn (reference: Pulsar.add_phase_wrap)."""
    m = get_model(PAR)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55400, 30), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=False, seed=4)
    s = InteractivePulsar(get_model(PAR), t)
    pn = s.compute_pulse_numbers()
    assert np.all(np.diff(pn) > 0)
    s.select_mjd_range(55300, 55500)
    sel = s.selected.copy()
    r0 = Residuals(s.toas, s.model, track_mode="use_pulse_numbers",
                   subtract_mean=False)
    ph0 = np.asarray(r0.calc_phase_resids())
    s.add_phase_wrap(-1)
    r1 = Residuals(s.toas, s.model, track_mode="use_pulse_numbers",
                   subtract_mean=False)
    ph1 = np.asarray(r1.calc_phase_resids())
    assert np.allclose((ph1 - ph0)[sel], 1.0, atol=1e-9)
    assert np.allclose((ph1 - ph0)[~sel], 0.0, atol=1e-9)


def test_color_modes():
    m = get_model(PAR)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55400, 20), m,
                                error_us=1.0,
                                freq_mhz=np.where(np.arange(20) % 2, 1440.0,
                                                  820.0),
                                obs="gbt", add_noise=True, seed=5)
    s = InteractivePulsar(get_model(PAR), t)
    assert set(s.color_categories("freq")) == {"700-1000", "1000-1800"}
    assert set(s.color_categories("obs")) == {"gbt"}
    assert set(s.color_categories("error")) <= {"above-median", "below-median"}
    s.select(np.arange(20) < 5)
    cats = s.color_categories("selected")
    assert (cats[:5] == "selected").all() and (cats[5:] == "unselected").all()
    s.add_jump_to_selection()
    jc = s.color_categories("jump")
    assert (jc[:5] == "pintk_1").all() and (jc[5:] == "unjumped").all()
    with pytest.raises(ValueError):
        s.color_categories("nope")


def test_fitbox_and_paredit(tmp_path):
    m = get_model(PAR)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55400, 25), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=6)
    s = InteractivePulsar(get_model(PAR), t)
    s.set_fit_params(["F0"])
    assert s.model.free_params == ["F0"]
    # paredit: apply an edited par with a different DM, history grows
    edited = PAR.replace("DM 11.0 1", "DM 12.5 1")
    s.apply_parfile(edited)
    assert s.model.DM.value == pytest.approx(12.5)
    s.undo()
    assert s.model.DM.value == pytest.approx(11.0)
    # write out par + tim and reload
    s.write_par(tmp_path / "out.par")
    s.write_tim(tmp_path / "out.tim")
    from pint_tpu.models import get_model_and_toas

    m2, t2 = get_model_and_toas(str(tmp_path / "out.par"),
                                str(tmp_path / "out.tim"))
    assert len(t2) == 25
    assert m2.F0.value == pytest.approx(s.model.F0.value)


@pytest.mark.parametrize("bin_name,extra", [
    ("ELL1", "PB 1.2 1\nA1 2.0\nTASC 55000\nEPS1 1e-7\nEPS2 0\n"),
    ("DD", "PB 10 1\nA1 5.0\nT0 55000\nECC 0.3\nOM 90\nM2 0.3\nSINI 0.9\n"),
    ("DDK", "PB 10 1\nA1 5.0\nT0 55000\nECC 0.3\nOM 90\nM2 0.3\nKIN 70\n"
            "KOM 30\nPX 1.2\nPMRA 5\nPMDEC -3\n"),
    ("ELL1H", "PB 1.2 1\nA1 2.0\nTASC 55000\nEPS1 1e-7\nEPS2 0\nH3 1e-7\n"
              "H4 4e-8\n"),
])
def test_binary_parfile_roundtrip(bin_name, extra):
    """as_parfile must emit the BINARY selector line: the par file IS
    the checkpoint (reference: TimingModel.as_parfile; SURVEY.md 5
    checkpoint/resume)."""
    par = (f"PSR T\nRAJ 1:0:0\nDECJ 2:0:0\nF0 100 1\nPEPOCH 55000\n"
           f"DM 10\nBINARY {bin_name}\n{extra}")
    m = get_model(par)
    m2 = get_model(m.as_parfile())
    assert ([c for c in m2.components if c.startswith("Binary")]
            == [c for c in m.components if c.startswith("Binary")])
    assert m2.PB.value == pytest.approx(m.PB.value)


def test_angle_formatting_carry():
    """1:0:0 must print as 01:00:00..., never 00:59:60... (integer
    tick formatting), and round-trip exactly."""
    m = get_model("PSR T\nRAJ 1:0:0\nDECJ -0:0:30\nF0 100\nPEPOCH 55000\n"
                  "DM 10\n")
    txt = m.as_parfile()
    raj = next(l for l in txt.splitlines() if l.startswith("RAJ"))
    assert "01:00:00" in raj and ":60" not in raj
    m2 = get_model(txt)
    assert m2.RAJ.value == pytest.approx(m.RAJ.value, abs=1e-15)
    assert m2.DECJ.value == pytest.approx(m.DECJ.value, abs=1e-15)


def test_paredit_clears_fit_state():
    """apply_parfile drops last_fit and fitted; undo restores them
    consistently (review finding: stale last_fit fed random_models)."""
    m = get_model(PAR)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55400, 20), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=9)
    s = InteractivePulsar(get_model(PAR), t)
    s.fit()
    assert s.fitted and s.last_fit is not None
    s.apply_parfile(PAR.replace("DM 11.0 1", "DM 11.3 1"))
    assert not s.fitted and s.last_fit is None
    with pytest.raises(RuntimeError):
        s.random_models()
    s.undo()  # back to the post-fit model
    assert s.fitted
    s.undo()  # back to the initial model
    assert not s.fitted and s.last_fit is None


def test_phase_wrap_after_delete_restore():
    """Partial pn stamping from a delete/compute/restore cycle must
    trigger a recompute, not a KeyError (review finding)."""
    m = get_model(PAR)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55400, 20), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=False, seed=10)
    s = InteractivePulsar(get_model(PAR), t)
    s.select(np.arange(20) < 5)
    s.delete_selected()
    s.select(np.ones(15, dtype=bool))
    s.add_phase_wrap(0)  # stamps pn on the 15 survivors only
    s.restore_all_toas()
    s.select(np.arange(20) < 5)  # restored TOAs: no pn yet
    s.add_phase_wrap(2)
    for i in range(5):
        assert "pn" in s.toas.flags[i]


def test_free_params_setter_validates_first():
    m = get_model(PAR)
    before = m.free_params
    with pytest.raises(KeyError):
        m.free_params = ["F0", "NOT_A_PARAM"]
    assert m.free_params == before  # untouched on failure


def test_harmonic_sums_batched_input_uses_jnp_path():
    """2-D phases must never silently co-add through the raveling
    pallas kernel (review finding): the dispatcher is 1-D-only."""
    from pint_tpu.kernels import harmonics

    called = {"pallas": False}
    orig = harmonics.harmonic_sums_pallas

    def spy(*a, **k):
        called["pallas"] = True
        return orig(*a, **k)

    harmonics.harmonic_sums_pallas = spy
    old_backend = harmonics._tpu_backend
    harmonics._tpu_backend = lambda: True  # pretend we're on TPU
    try:
        ph2d = np.random.default_rng(0).random((4, 70000))
        with pytest.raises(Exception):
            # jnp path's broadcasting rejects 2-D input loudly on every
            # backend -- exactly the parity the dispatcher must keep
            np.asarray(harmonics.harmonic_sums(ph2d, 3)[0])
        assert not called["pallas"]
    finally:
        harmonics.harmonic_sums_pallas = orig
        harmonics._tpu_backend = old_backend


def test_pintk_gui_headless_guard(tmp_path):
    """Without a display the GUI refuses with a pointer to the
    scriptable session (the widget layer is untestable here; its logic
    is pure delegation to InteractivePulsar, which this file tests)."""
    import os
    import subprocess
    import sys

    import pint_tpu.pintk_gui  # importable without a display

    par = tmp_path / "g.par"
    par.write_text("PSR TGUI\nF0 100.0 1\nPEPOCH 55000\nDM 10\n"
                   "RAJ 1:00:00\nDECJ 2:00:00\n")
    tim = tmp_path / "g.tim"
    tim.write_text("FORMAT 1\nfake 1400.0 55000.1 1.0 gbt\n")
    env = {k: v for k, v in os.environ.items() if k != "DISPLAY"}
    r = subprocess.run(
        [sys.executable, "-m", "pint_tpu.scripts.pintk", str(par), str(tim)],
        capture_output=True, text=True, env=env,
        cwd=str(__import__("pathlib").Path(__file__).resolve().parents[1]))
    assert r.returncode == 1
    assert "InteractivePulsar" in r.stderr


def test_pintk_gui_plotting_logic_headless():
    """The GUI's redraw path (label masks, selection ring, random-model
    spread) runs against the tested session with a stub canvas — all
    the non-widget logic of PlkGui is covered without a display."""
    import types

    import matplotlib

    matplotlib.use("Agg")
    from matplotlib.figure import Figure

    import pint_tpu.pintk_gui as G
    from pint_tpu.models import get_model
    from pint_tpu.pintk import InteractivePulsar
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    m = get_model("PSR TGUI2\nRAJ 1:00:00\nDECJ 2:00:00\nF0 100.0 1\n"
                  "F1 -1e-15 1\nPEPOCH 55300\nDM 10 1\n")
    t = make_fake_toas_fromMJDs(
        np.linspace(55000, 55600, 40), m, error_us=1.0,
        freq_mhz=np.where(np.arange(40) % 2, 800.0, 1400.0),
        obs="gbt", add_noise=True, seed=1)
    s = InteractivePulsar(m, t)
    gui = object.__new__(G.PlkGui)  # no Tk: wire only what redraw needs
    gui.session = s
    gui.fig = Figure()
    gui.ax = gui.fig.add_subplot(111)
    gui.canvas = types.SimpleNamespace(draw_idle=lambda: None)
    gui.status = types.SimpleNamespace(config=lambda **kw: None)
    gui.show_random = types.SimpleNamespace(get=lambda: False)
    gui.xaxis = types.SimpleNamespace(get=lambda: "mjd")
    for mode in ("default", "obs", "freq", "jump"):
        gui.colormode = types.SimpleNamespace(get=lambda m=mode: m)
        gui.redraw()
    s.select_mjd_range(55100, 55300)
    s.fit()
    gui.show_random = types.SimpleNamespace(get=lambda: True)
    gui.redraw()
    # every x-axis mode draws (orbital phase absent: isolated pulsar)
    gui.show_random = types.SimpleNamespace(get=lambda: False)
    for xmode in s.x_axis_choices():
        gui.xaxis = types.SimpleNamespace(get=lambda m=xmode: m)
        gui.redraw()
    # selection ring drawn: one line beyond the errorbar sets
    assert any(ln.get_label() == "selected" for ln in gui.ax.lines)


def test_xvals_modes():
    """x-axis quantities for the plk plot (reference: plk.py x-axis
    dropdown): serial/year/day-of-year/frequency/error, and orbital
    phase only for binary models."""
    m = get_model(PAR)
    # include MJD 51544.5 (J2000.0, year 2000.0) and 58849.25
    # (2020-01-01, day-of-year 1.25)
    mjds = np.array([51544.5, 55000.0, 58849.25])
    t = make_fake_toas_fromMJDs(mjds, m, error_us=2.0, freq_mhz=700.0,
                                obs="gbt", iterations=1)
    s = InteractivePulsar(get_model(PAR), t)
    np.testing.assert_allclose(s.xvals("serial"), [0, 1, 2])
    assert abs(s.xvals("year")[0] - 2000.0) < 1e-9
    # zero-residual iteration shifts TOA times by ~ms: loose tolerance
    assert abs(s.xvals("day of year")[2] - 1.25) < 1e-6
    np.testing.assert_allclose(s.xvals("frequency"), 700.0)
    np.testing.assert_allclose(s.xvals("TOA error"), 2.0)
    np.testing.assert_allclose(s.xvals("mjd"), t.get_mjds())
    assert "orbital phase" not in s.x_axis_choices()
    with pytest.raises(ValueError, match="x-axis"):
        s.xvals("nope")

    bin_par = PAR + ("BINARY ELL1\nPB 3.0\nA1 5.0\nTASC 55001.0\n"
                     "EPS1 1e-6\nEPS2 1e-6\n")
    mb = get_model(bin_par)
    tb = make_fake_toas_fromMJDs(np.linspace(55000, 55100, 20), mb,
                                 error_us=1.0, freq_mhz=1400.0,
                                 obs="gbt", iterations=1)
    sb = InteractivePulsar(get_model(bin_par), tb)
    assert "orbital phase" in sb.x_axis_choices()
    ph = sb.xvals("orbital phase")
    assert ph.shape == (20,) and np.all((ph >= 0) & (ph < 1))


def test_session_whitened_resids():
    """Session.whitened_resids: unit-scatter after a GLS fit with red
    noise (the realization is subtracted), and refuses before any fit."""
    import numpy as np
    import pytest

    from pint_tpu.fitter import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.pintk import InteractivePulsar
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TPKW\nRAJ 6:00:00\nDECJ 10:00:00\nF0 200.0 1\nF1 -1e-14 1\n"
           "PEPOCH 55500\nDM 10.0\nTNREDAMP -12.5\nTNREDGAM 3.5\nTNREDC 12\n")
    m = get_model(par)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 56000, 120), m,
                                error_us=0.5, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, add_correlated_noise=True,
                                seed=7)
    s = InteractivePulsar(m, t, fitter_factory=GLSFitter)
    with pytest.raises(ValueError, match="fit"):
        s.whitened_resids()
    s.fit(maxiter=3)
    w = s.whitened_resids()
    raw = s.resids_us() / (np.asarray(
        s.last_fit.resids.prepared.scaled_sigma_us()))
    assert w.std() < 1.4  # realization subtracted: ~unit variance
    assert raw.std() > 1.5 * w.std()  # raw is red-noise inflated
