"""Native C++ host-kernel equivalence tests.

The C++ library (native/src/host_kernels.cpp) mirrors the numpy
implementations term-for-term; these tests assert both paths agree to
machine precision and that the loader degrades gracefully.
"""

import numpy as np
import pytest

import pint_tpu.native as native
from pint_tpu.mjd import Epochs


@pytest.fixture(scope="module")
def lib():
    lb = native.get_lib()
    if lb is None:
        pytest.skip("native library unavailable (no g++?)")
    return lb


def _numpy_only(monkeypatch):
    """Force the numpy paths regardless of the built library."""
    monkeypatch.setattr(native, "_LIB", False)


def test_tdb_minus_tt_equivalence(lib, monkeypatch):
    rng = np.random.default_rng(0)
    day = rng.integers(44000, 61000, 500).astype(np.int64)
    sec = rng.uniform(0, 86400, 500)
    tt = Epochs(day, sec, "tt")
    got = native.tdb_minus_tt(tt.day, tt.sec)
    from pint_tpu.timescales import tdb_minus_tt_series

    _numpy_only(monkeypatch)
    expected = tdb_minus_tt_series(tt)
    # both are ~1.6 ms amplitude; require < 1 ps agreement
    np.testing.assert_allclose(got, expected, rtol=0, atol=1e-12)
    assert np.abs(got).max() > 1e-4  # sanity: series actually evaluated


def test_itrf_to_gcrs_equivalence(lib, monkeypatch):
    from pint_tpu.earth.erfa_lite import gcrs_posvel_from_itrf

    rng = np.random.default_rng(1)
    day = rng.integers(50000, 61000, 300).astype(np.int64)
    sec = rng.uniform(0, 86400, 300)
    utc = Epochs(day, sec, "utc")
    itrf = np.array([882589.65, -4924872.32, 3943729.348])  # GBT
    pos_n, vel_n = gcrs_posvel_from_itrf(itrf, utc)  # native path
    _numpy_only(monkeypatch)
    pos_p, vel_p = gcrs_posvel_from_itrf(itrf, utc)  # numpy path
    # sub-micrometer agreement on Earth-radius vectors
    np.testing.assert_allclose(pos_n, pos_p, rtol=0, atol=1e-6)
    np.testing.assert_allclose(vel_n, vel_p, rtol=0, atol=1e-10)
    r = np.linalg.norm(pos_n, axis=1)
    assert np.all(np.abs(r - np.linalg.norm(itrf)) < 1e-3)  # rotation only


def test_cheby_posvel_equivalence(lib):
    """Native Chebyshev vs direct numpy recurrence on synthetic records."""
    rng = np.random.default_rng(2)
    n, ncoef = 200, 12
    rsize = 2 + 3 * ncoef
    rec = np.zeros((n, rsize))
    rec[:, 0] = rng.uniform(0, 1e8, n)     # mid
    rec[:, 1] = rng.uniform(1e4, 1e5, n)   # radius
    rec[:, 2:] = rng.normal(0, 1e3, (n, 3 * ncoef))
    et = rec[:, 0] + rng.uniform(-1, 1, n) * rec[:, 1]
    pos, vel = native.cheby_posvel(et, rec, ncoef, 2)
    s = (et - rec[:, 0]) / rec[:, 1]
    T = np.zeros((ncoef, n))
    dT = np.zeros((ncoef, n))
    T[0], T[1] = 1.0, s
    dT[1] = 1.0
    for k in range(2, ncoef):
        T[k] = 2 * s * T[k - 1] - T[k - 2]
        dT[k] = 2 * T[k - 1] + 2 * s * dT[k - 1] - dT[k - 2]
    for axis in range(3):
        c = rec[:, 2 + axis * ncoef: 2 + (axis + 1) * ncoef]
        np.testing.assert_allclose(pos[:, axis], np.einsum("nk,kn->n", c, T),
                                   rtol=1e-13)
        np.testing.assert_allclose(vel[:, axis],
                                   np.einsum("nk,kn->n", c, dT) / rec[:, 1],
                                   rtol=1e-12)


def test_era_gast_absolute_anchors():
    """Pin ERA/GMST to published absolute values (catches the classic
    missing-half-day JD-fraction error, which shifts both by exactly pi).

    Anchors: ERA at J2000.0 (UT1 JD 2451545.0) = 2*pi*0.7790572732640
    (IERS Conventions); GMST at 2000-01-01 00:00 UT1 = 6h 39m 52.2626s
    (Astronomical Almanac 2000).
    """
    from pint_tpu.earth.erfa_lite import era, gast

    # J2000.0 noon: MJD 51544.5 -> day 51544, sec 43200
    ut1 = Epochs(np.array([51544]), np.array([43200.0]), "ut1")
    got = float(era(ut1)[0])
    expected = 2 * np.pi * 0.7790572732640
    assert abs(got - expected) < 1e-9, (got, expected)

    # 2000-01-01 00:00 UT1: GMST = 6.664520 h = 99.9678 deg
    ut1b = Epochs(np.array([51544]), np.array([0.0]), "ut1")
    T = ((51544 - 51544) - 0.5 + 0.0 / 86400.0) / 36525.0
    theta = float(gast(ut1b, np.array([T]))[0])
    gmst_deg = np.rad2deg(theta)
    # gast includes the equation of the equinoxes (~ -0.004 deg in 2000)
    assert abs(gmst_deg - 99.9678) < 0.02, gmst_deg


def test_loader_disable_env(monkeypatch):
    monkeypatch.setenv("PINT_TPU_NO_NATIVE", "1")
    monkeypatch.setattr(native, "_LIB", None)
    assert native.get_lib() is None
    assert native.tdb_minus_tt(np.array([55000]), np.array([0.0])) is None
    monkeypatch.setattr(native, "_LIB", None)  # reset for other tests


def test_native_speedup(lib):
    """The native earth-rotation kernel should beat numpy comfortably
    on per-TOA batches (it is the host hot path for photon loads)."""
    import time

    from pint_tpu.earth import erfa_lite

    n = 20000
    rng = np.random.default_rng(3)
    utc = Epochs(rng.integers(50000, 61000, n).astype(np.int64),
                 rng.uniform(0, 86400, n), "utc")
    itrf = np.array([882589.65, -4924872.32, 3943729.348])
    def best_of(k, fn):
        ts_ = []
        for _ in range(k):
            t0 = time.perf_counter()
            fn()
            ts_.append(time.perf_counter() - t0)
        return min(ts_)

    run = lambda: erfa_lite.gcrs_posvel_from_itrf(itrf, utc)  # noqa: E731
    run()  # warm both paths' imports
    t_native = best_of(3, run)
    M = native._LIB
    try:
        native._LIB = False
        t_numpy = best_of(3, run)
    finally:
        native._LIB = M
    # generous bound: regression signal without timing-noise flakes
    assert t_native < 2.0 * t_numpy, (t_native, t_numpy)
