"""Native C++ host-kernel equivalence tests.

The C++ library (native/src/host_kernels.cpp) mirrors the numpy
implementations term-for-term; these tests assert both paths agree to
machine precision and that the loader degrades gracefully.
"""

import numpy as np
import pytest

import pint_tpu.native as native
from pint_tpu.mjd import Epochs


@pytest.fixture(scope="module")
def lib():
    lb = native.get_lib()
    if lb is None:
        pytest.skip("native library unavailable (no g++?)")
    return lb


def _numpy_only(monkeypatch):
    """Force the numpy paths regardless of the built library."""
    monkeypatch.setattr(native, "_LIB", False)


def test_tdb_minus_tt_equivalence(lib, monkeypatch):
    rng = np.random.default_rng(0)
    # in-coverage epochs plus far out-of-coverage ones (MJD 15000 /
    # 90000, |T| ~ 1 cy): the fit-window clamp on the secular terms
    # must match bit-for-bit between the C++ and numpy paths
    day = np.concatenate([
        rng.integers(44000, 61000, 400),
        rng.integers(15000, 40000, 50),
        rng.integers(64000, 90000, 50)]).astype(np.int64)
    sec = rng.uniform(0, 86400, 500)
    tt = Epochs(day, sec, "tt")
    got = native.tdb_minus_tt(tt.day, tt.sec)
    from pint_tpu.timescales import tdb_minus_tt_series

    _numpy_only(monkeypatch)
    expected = tdb_minus_tt_series(tt)
    # both are ~1.6 ms amplitude; require < 1 ps agreement
    np.testing.assert_allclose(got, expected, rtol=0, atol=1e-12)
    assert np.abs(got).max() > 1e-4  # sanity: series actually evaluated


def test_itrf_to_gcrs_equivalence(lib, monkeypatch):
    from pint_tpu.earth.erfa_lite import gcrs_posvel_from_itrf

    rng = np.random.default_rng(1)
    day = rng.integers(50000, 61000, 300).astype(np.int64)
    sec = rng.uniform(0, 86400, 300)
    utc = Epochs(day, sec, "utc")
    itrf = np.array([882589.65, -4924872.32, 3943729.348])  # GBT
    pos_n, vel_n = gcrs_posvel_from_itrf(itrf, utc)  # native path
    _numpy_only(monkeypatch)
    pos_p, vel_p = gcrs_posvel_from_itrf(itrf, utc)  # numpy path
    # sub-micrometer agreement on Earth-radius vectors
    np.testing.assert_allclose(pos_n, pos_p, rtol=0, atol=1e-6)
    np.testing.assert_allclose(vel_n, vel_p, rtol=0, atol=1e-10)
    r = np.linalg.norm(pos_n, axis=1)
    assert np.all(np.abs(r - np.linalg.norm(itrf)) < 1e-3)  # rotation only


def test_cheby_posvel_equivalence(lib):
    """Native Chebyshev vs direct numpy recurrence on synthetic records."""
    rng = np.random.default_rng(2)
    n, ncoef = 200, 12
    rsize = 2 + 3 * ncoef
    rec = np.zeros((n, rsize))
    rec[:, 0] = rng.uniform(0, 1e8, n)     # mid
    rec[:, 1] = rng.uniform(1e4, 1e5, n)   # radius
    rec[:, 2:] = rng.normal(0, 1e3, (n, 3 * ncoef))
    et = rec[:, 0] + rng.uniform(-1, 1, n) * rec[:, 1]
    pos, vel = native.cheby_posvel(et, rec, ncoef, 2)
    s = (et - rec[:, 0]) / rec[:, 1]
    T = np.zeros((ncoef, n))
    dT = np.zeros((ncoef, n))
    T[0], T[1] = 1.0, s
    dT[1] = 1.0
    for k in range(2, ncoef):
        T[k] = 2 * s * T[k - 1] - T[k - 2]
        dT[k] = 2 * T[k - 1] + 2 * s * dT[k - 1] - dT[k - 2]
    for axis in range(3):
        c = rec[:, 2 + axis * ncoef: 2 + (axis + 1) * ncoef]
        np.testing.assert_allclose(pos[:, axis], np.einsum("nk,kn->n", c, T),
                                   rtol=1e-13)
        np.testing.assert_allclose(vel[:, axis],
                                   np.einsum("nk,kn->n", c, dT) / rec[:, 1],
                                   rtol=1e-12)


def test_era_gast_absolute_anchors():
    """Pin ERA/GMST to published absolute values (catches the classic
    missing-half-day JD-fraction error, which shifts both by exactly pi).

    Anchors: ERA at J2000.0 (UT1 JD 2451545.0) = 2*pi*0.7790572732640
    (IERS Conventions); GMST at 2000-01-01 00:00 UT1 = 6h 39m 52.2626s
    (Astronomical Almanac 2000).
    """
    from pint_tpu.earth.erfa_lite import era, gast

    # J2000.0 noon: MJD 51544.5 -> day 51544, sec 43200
    ut1 = Epochs(np.array([51544]), np.array([43200.0]), "ut1")
    got = float(era(ut1)[0])
    expected = 2 * np.pi * 0.7790572732640
    assert abs(got - expected) < 1e-9, (got, expected)

    # 2000-01-01 00:00 UT1: GMST = 6.664520 h = 99.9678 deg
    ut1b = Epochs(np.array([51544]), np.array([0.0]), "ut1")
    T = ((51544 - 51544) - 0.5 + 0.0 / 86400.0) / 36525.0
    theta = float(gast(ut1b, np.array([T]))[0])
    gmst_deg = np.rad2deg(theta)
    # gast includes the equation of the equinoxes (~ -0.004 deg in 2000)
    assert abs(gmst_deg - 99.9678) < 0.02, gmst_deg


def test_loader_disable_env(monkeypatch):
    monkeypatch.setenv("PINT_TPU_NO_NATIVE", "1")
    monkeypatch.setattr(native, "_LIB", None)
    assert native.get_lib() is None
    assert native.tdb_minus_tt(np.array([55000]), np.array([0.0])) is None
    monkeypatch.setattr(native, "_LIB", None)  # reset for other tests


def test_native_speedup(lib):
    """The native earth-rotation kernel should beat numpy comfortably
    on per-TOA batches (it is the host hot path for photon loads)."""
    import time

    from pint_tpu.earth import erfa_lite

    n = 20000
    rng = np.random.default_rng(3)
    utc = Epochs(rng.integers(50000, 61000, n).astype(np.int64),
                 rng.uniform(0, 86400, n), "utc")
    itrf = np.array([882589.65, -4924872.32, 3943729.348])
    def best_of(k, fn):
        ts_ = []
        for _ in range(k):
            t0 = time.perf_counter()
            fn()
            ts_.append(time.perf_counter() - t0)
        return min(ts_)

    run = lambda: erfa_lite.gcrs_posvel_from_itrf(itrf, utc)  # noqa: E731
    run()  # warm both paths' imports
    t_native = best_of(3, run)
    M = native._LIB
    try:
        native._LIB = False
        t_numpy = best_of(3, run)
    finally:
        native._LIB = M
    # generous bound: regression signal without timing-noise flakes
    assert t_native < 2.0 * t_numpy, (t_native, t_numpy)


# ---- native tim parser (pt_parse_tim_t2) ----

def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_parse_tim_native_matches_python(lib, tmp_path):
    """Native FORMAT-1 parser is column-equal to the Python parser,
    including exact MJD split, flag pairs, valueless flags, and the
    implicit name flag (reference: toa.py::read_toa_file semantics)."""
    from pint_tpu.toa import TOAs, _read_tim_native, read_tim_file

    text = (
        "FORMAT 1\n"
        "# a comment\n"
        "C  old-style comment\n"
        "psr1 1400.000001 54321.1234567890123456789 1.250 gbt -fe L-wide -be GUPPI\n"
        "psr2 800.5 50000.0 3.0 AO -pn -3 -empty -to -1.5\n"
        "weird 1e3 59999.9999999999999 0.5 @ -name custom -j\n"
        "MODE 1\n"
        "bad_line_not_enough_tokens 1400\n"
        "psr3 inf 42.5 1.0 bat\n"
    )
    p = _write(tmp_path, "mix.tim", text)
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("ignore")
        tn = _read_tim_native(p)
        toalist, commands = read_tim_file(p)
    tp = TOAs(toalist)
    assert tn is not None and len(tn) == len(tp) == 4
    assert np.array_equal(tn.day, tp.day)
    assert np.array_equal(tn.sec, tp.sec)  # bit-exact MJD split
    assert np.array_equal(tn.freq_mhz, tp.freq_mhz)
    assert np.array_equal(tn.error_us, tp.error_us)
    assert list(tn.obs.astype(str)) == list(tp.obs.astype(str))
    assert tn.flags == tp.flags
    assert tn.commands == commands


def test_parse_tim_native_mjd_precision(lib, tmp_path):
    """MJD strings of every practical digit count split identically to
    mjd.py::parse_mjd_string (longdouble path)."""
    from pint_tpu.mjd import parse_mjd_string
    from pint_tpu.toa import _read_tim_native

    rng = np.random.default_rng(7)
    mjds = []
    for nd in range(0, 20):
        d = rng.integers(40000, 61000)
        frac = "".join(str(rng.integers(0, 10)) for _ in range(nd))
        mjds.append(f"{d}.{frac}" if nd else str(d))
    lines = "FORMAT 1\n" + "".join(
        f"t 1400.0 {m} 1.0 gbt\n" for m in mjds)
    p = _write(tmp_path, "prec.tim", lines)
    tn = _read_tim_native(p)
    for i, m in enumerate(mjds):
        day, sec = parse_mjd_string(m)
        assert tn.day[i] == day
        assert tn.sec[i] == sec, (m, tn.sec[i], sec)


def test_parse_tim_native_falls_back_on_stateful(lib, tmp_path):
    """Stateful commands (TIME/EFAC/INCLUDE/...) and princeton files
    must hand off to the Python parser; get_TOAs output is identical
    either way."""
    from pint_tpu import native
    from pint_tpu.toa import _read_tim_native, get_TOAs

    stateful = ("FORMAT 1\nTIME 0.5\n"
                "psr1 1400.0 54321.5 1.0 @\n")
    p = _write(tmp_path, "stateful.tim", stateful)
    assert _read_tim_native(p) is None  # C++ detected TIME -> fallback
    t = get_TOAs(p)  # python path applies the TIME offset
    assert abs(t.sec[0] - (43200.0 + 0.5)) < 1e-9

    princeton_like = "a    some_info 1400.000 54000.123456789     1.00\n"
    p2 = _write(tmp_path, "princeton.tim", princeton_like)
    assert _read_tim_native(p2) is None  # no FORMAT 1 -> fallback

    # a plain file gives identical TOAs through both paths
    plain = _write(tmp_path, "plain.tim",
                   "FORMAT 1\npsrA 1440.0 55123.25 2.0 gbt -fe Rcvr1_2\n")
    t_native = get_TOAs(plain)
    saved = native._LIB
    try:
        native._LIB = False  # force python parser
        t_py = get_TOAs(plain)
    finally:
        native._LIB = saved
    assert np.array_equal(t_native.day, t_py.day)
    assert np.array_equal(t_native.sec, t_py.sec)
    assert t_native.flags == t_py.flags
    assert np.allclose(t_native.tdb.sec, t_py.tdb.sec)


def test_parse_tim_native_non_ascii_and_crlf(lib, tmp_path):
    """CRLF and bare-CR files parse identically to Python's universal
    newlines; any non-ASCII content (unicode whitespace/digits change
    str.split()/float() semantics) hands off to the Python parser."""
    from pint_tpu.toa import TOAs, _read_tim_native, read_tim_file

    text = ("FORMAT 1\r\n"
            "psr1 1400.0 54321.5 1.0 gbt -be X\r\n"
            "MODE 1\r\n"
            "psr2 800.0 54400.5 2.0 ao -fe L-wide\r\n")
    p = tmp_path / "crlf.tim"
    p.write_bytes(text.encode())
    tn = _read_tim_native(str(p))
    toalist, commands = read_tim_file(str(p))
    tp = TOAs(toalist)
    assert tn is not None and tn.flags == tp.flags
    assert tn.flags[1] == {"fe": "L-wide", "name": "psr2"}
    assert tn.commands == commands == ["FORMAT 1", "MODE 1"]

    # bare-\r (old-Mac) endings: same TOA set as python, not 0 TOAs
    p2 = tmp_path / "cr.tim"
    p2.write_bytes(b"FORMAT 1\rpsr1 1400.0 55000.5 1.0 gbt\r")
    tn2 = _read_tim_native(str(p2))
    toalist2, _ = read_tim_file(str(p2))
    assert tn2 is not None and len(tn2) == len(toalist2) == 1
    assert tn2.sec[0] == 43200.0

    # non-ASCII flag value: python parser owns it, results identical
    p3 = tmp_path / "uni.tim"
    p3.write_bytes("FORMAT 1\npsr1 1400.0 54321.5 1.0 gbt -tel "
                   "Effelsbergé\n".encode())
    assert _read_tim_native(str(p3)) is None
    from pint_tpu.toa import get_TOAs

    t3 = get_TOAs(str(p3))
    assert t3.flags[0]["tel"] == "Effelsbergé"


def test_has_flags_consumers_see_native_flags(lib, tmp_path):
    """auto_fitter wideband detection and get_event_weights must see
    flags that are still packed in _flags_raw (lazy native path)."""
    from pint_tpu.event_toas import get_event_weights
    from pint_tpu.toa import _read_tim_native

    text = ("FORMAT 1\n"
            "p 1400.0 54321.5 1.0 @ -weight 0.5 -pp_dm 10.1 -pp_dme 0.1\n"
            "p 1400.0 54322.5 1.0 @ -weight 0.25 -pp_dm 10.2 -pp_dme 0.1\n")
    p = tmp_path / "wb.tim"
    p.write_text(text)
    t = _read_tim_native(str(p))
    assert t._flags is None and t._flags_raw is not None  # still packed
    w = get_event_weights(t)
    assert w is not None and np.allclose(w, [0.5, 0.25])

    t2 = _read_tim_native(str(p))
    assert t2.has_flags()
    assert any("pp_dm" in f for f in t2.flags)


def test_parse_tim_native_bare_cr_many_toas(lib, tmp_path):
    """Bare-CR files with MANY TOAs: output buffers must be sized for
    CR-terminated lines too (regression: 50-TOA bare-CR file overran
    the arrays and corrupted the heap), and the commands list must
    match the Python parser's universal-newline splitting."""
    from pint_tpu.toa import TOAs, _read_tim_native, read_tim_file

    lines = ["FORMAT 1"]
    for i in range(50):
        lines.append(f"p{i} 1400.0 {55000 + i}.5 1.0 gbt -fe L-wide")
    lines.append("MODE 1")
    p = tmp_path / "crmany.tim"
    p.write_bytes("\r".join(lines).encode() + b"\r")
    tn = _read_tim_native(str(p))
    toalist, commands = read_tim_file(str(p))
    tp = TOAs(toalist)
    assert tn is not None and len(tn) == len(tp) == 50
    assert np.array_equal(tn.day, tp.day)
    assert np.array_equal(tn.sec, tp.sec)
    assert tn.flags == tp.flags
    assert tn.commands == commands == ["FORMAT 1", "MODE 1"]


def test_parse_tim_native_nan_paren_and_unicode_comment(lib, tmp_path):
    """strtod's nan(seq) form is not a python float (flag-key parity),
    and a non-ASCII comment must NOT forfeit the native fast path."""
    from pint_tpu.toa import TOAs, _read_tim_native, read_tim_file

    text = ("FORMAT 1\n"
            "# commentaire réduit — unicode stays commentary\n"
            "p1 1400.0 55000.5 1.0 gbt -x -nan(q) -y 2\n")
    p = tmp_path / "nanq.tim"
    p.write_bytes(text.encode())
    tn = _read_tim_native(str(p))
    toalist, _ = read_tim_file(str(p))
    tp = TOAs(toalist)
    assert tn is not None  # unicode comment did not force fallback
    assert tn.flags == tp.flags
    assert tn.flags[0]["x"] == "" and "nan(q)" in tn.flags[0]


def test_full_chain_equivalence_ns(lib, monkeypatch, tmp_path):
    """Full-pipeline C++-vs-Python equivalence at ns tolerance
    (VERDICT r2 next-step 9): build TOAs at randomized epochs over a
    30-yr span, run the COMPLETE chain (tim parse -> UTC->TT->TDB ->
    site->GCRS posvel -> every delay -> residual seconds) once with
    the native kernels and once with the numpy mirrors, and require
    the resulting per-TOA delays to agree below 1 ns. This is the
    independent-axis check the per-routine tests above cannot give:
    any divergence anywhere in the chain surfaces here in seconds."""
    import copy

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    rng = np.random.default_rng(11)
    par = ("PSR CHAIN\nRAJ 04:37:15.8\nDECJ -47:15:09.1\n"
           "PMRA 121.4\nPMDEC -71.5\nPX 6.4\nPOSEPOCH 55000\n"
           "F0 173.6879458\nF1 -1.728e-15\nPEPOCH 55000\nDM 2.64\n"
           "BINARY ELL1\nPB 5.7410459\nA1 3.3666870\nTASC 54501.4671\n"
           "EPS1 1.9e-5\nEPS2 -1.4e-5\nM2 0.224\nSINI 0.68\n")
    mjds = np.sort(rng.uniform(50000, 61000, 300))

    def chain_delay():
        m = get_model(par)
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0,
                                    freq_mhz=1400.0, obs="gbt",
                                    add_noise=False, iterations=0)
        return np.asarray(m.prepare(t).delay())

    d_native = chain_delay()
    _numpy_only(monkeypatch)
    d_numpy = chain_delay()
    np.testing.assert_allclose(d_native, d_numpy, rtol=0, atol=1e-9)
    assert np.ptp(d_native) > 1.0  # sanity: real delays flowed through
