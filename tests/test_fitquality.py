"""Numerics observatory (pint_tpu.obs.fitquality + obs.drift): probe
math units, ledger accounting, the fit_quality SLO five-pack and its
check_report gate, the pinned synthetic drift fixture (alarm round is
deterministic), checkpoint/restore re-anchor semantics, and the two
product contracts — a probed 68-pulsar fleet refit is bitwise
identical to an unprobed one with <1% warm-refit overhead, and an
injected solver divergence produces a correctly-attributed
``fit_anomaly`` flight dump naming the pulsar, the probe, and the
baseline it violated."""

import json
import math
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu import obs
from pint_tpu.obs import drift as obs_drift
from pint_tpu.obs import fitquality
from pint_tpu.obs import recorder as obs_recorder
from pint_tpu.obs.fitquality import (FitQualityLedger, chi2_zscore,
                                     check_report, condition_from_covn,
                                     fit_quality_slos,
                                     record_fit_batch, residual_moments)
from pint_tpu.resilience import FaultPoint, inject


@pytest.fixture(autouse=True)
def _clean_fitq():
    """Every test starts and ends with probing off, an empty process
    ledger, tracing off, and no flight-dump directory (module-global
    state, same hygiene as tests/test_obs.py)."""
    fitquality.disable()
    fitquality.reset()
    obs.disable()
    obs.reset()
    obs_recorder.RECORDER.reset()
    obs_recorder.RECORDER.dump_dir = None
    yield
    fitquality.disable()
    fitquality.reset()
    obs.disable()
    obs.reset()
    obs_recorder.RECORDER.reset()
    obs_recorder.RECORDER.dump_dir = None


# -- probe math ------------------------------------------------------


def test_chi2_zscore_center_tail_and_guards():
    # chi2 == dof sits near the distribution center
    assert abs(chi2_zscore(100.0, 100)) < 0.2
    # a 2x-inflated chi2 is far out in the tail
    assert chi2_zscore(200.0, 100) > 5.0
    # deflated chi2 goes negative
    assert chi2_zscore(40.0, 100) < -4.0
    # vectorized, with NaN guards for dof<=0 and non-finite chi2
    z = chi2_zscore([100.0, np.nan, 50.0], [100.0, 100.0, 0.0])
    assert abs(z[0]) < 0.2
    assert math.isnan(z[1]) and math.isnan(z[2])


def test_condition_from_covn_eigenvalue_spread():
    # covn is the inverse normalized Gram: eigenvalue ratio IS the
    # Gram's condition number. diag(1, 4) -> 4.
    assert condition_from_covn(np.diag([1.0, 4.0])) == pytest.approx(4.0)
    # stacked (P, k, k) input -> per-pulsar vector
    stack = np.stack([np.eye(2), np.diag([1.0, 100.0])])
    cond = condition_from_covn(stack)
    assert cond.shape == (2,)
    assert cond[0] == pytest.approx(1.0)
    assert cond[1] == pytest.approx(100.0)
    # semidefinite block -> inf; non-finite lane -> NaN
    assert math.isinf(condition_from_covn(np.diag([1.0, 0.0])))
    assert math.isnan(condition_from_covn(np.full((2, 2), np.nan)))


def test_residual_moments_known_vectors():
    m = residual_moments(np.array([1.0, -1.0]))
    assert m["n"] == 2
    assert m["mean"] == pytest.approx(0.0)
    assert m["std"] == pytest.approx(1.0)
    assert m["n_outliers"] == 0
    # one 10-sigma point is an outlier at the default 3.5 threshold
    rw = np.concatenate([np.zeros(50), [10.0]])
    assert residual_moments(rw)["n_outliers"] == 1
    # non-finite entries are dropped, not folded in
    assert residual_moments([np.nan, np.inf, 0.5])["n"] == 1
    empty = residual_moments([])
    assert empty["n"] == 0 and empty["mean"] is None


# -- ledger ----------------------------------------------------------


def test_ledger_counters_latest_wins_and_worst_case():
    led = FitQualityLedger()
    led.record("A", {"chi2_z": -2.0, "condition": 10.0, "relres": 1e-8})
    led.record("B", {"chi2_z": 1.0, "condition": 300.0,
                     "diverged": True})
    # re-record of A: latest record wins, counters accumulate
    led.record("A", {"chi2_z": 0.5, "condition": 20.0})
    snap = led.snapshot()
    assert snap["counters"]["fits"] == 3
    assert snap["counters"]["diverged"] == 1
    assert snap["n_pulsars"] == 2
    assert snap["max_abs_chi2_z"] == pytest.approx(2.0)
    assert snap["max_condition"] == pytest.approx(300.0)
    assert snap["max_relres"] == pytest.approx(1e-8)
    assert led.get("A")["chi2_z"] == 0.5
    # non-finite values never fold into the worst-case aggregates
    led.record("C", {"chi2_z": np.nan, "condition": np.inf})
    assert led.snapshot()["max_condition"] == pytest.approx(300.0)


def test_ledger_annotate_and_fallback_accounting():
    led = FitQualityLedger()
    led.record("A", {"chi2_z": 0.1, "fell_back": False})
    led.annotate("A", moments={"n": 24, "n_outliers": 0})
    rec = led.get("A")
    assert rec["moments"]["n"] == 24
    assert rec["chi2_z"] == 0.1  # annotate merges, never replaces
    # fallbacks count at the DECISION, once per affected label --
    # record()ing the f64 re-run must not double-book
    led.note_fallback(["A", "B"])
    led.record("A", {"chi2_z": 0.1, "fell_back": True})
    snap = led.snapshot()
    assert snap["counters"]["fallbacks"] == 2
    assert snap["counters"]["fits"] == 2
    led.reset()
    empty = led.snapshot()
    assert empty["counters"]["fits"] == 0 and empty["n_pulsars"] == 0


def test_record_fit_batch_probes_divergence_and_dump(tmp_path):
    rec = obs_recorder.FlightRecorder(dump_dir=str(tmp_path))
    led = FitQualityLedger()
    summary = record_fit_batch(
        ["P0", "P1", "P2"], [44.0, np.nan, 90.0], 44.0,
        covn=np.stack([np.eye(3)] * 3), relres=2e-9,
        method="gls", precision="mixed", maxiter=2,
        diverged=[1], ledger=led, source="unit", recorder=rec)
    assert summary["fitq_n"] == 3
    assert summary["fitq_diverged"] == 1
    assert summary["fitq_max_abs_chi2_z"] > 3.0  # P2's inflated chi2
    p0, p1 = led.get("P0"), led.get("P1")
    assert p0["dof"] == 44.0 and p0["reduced_chi2"] == pytest.approx(1.0)
    assert p0["relres"] == pytest.approx(2e-9)  # scalar broadcast
    assert p0["method"] == "gls" and p0["precision"] == "mixed"
    # the NaN lane stays None (not a huge finite impostor) + diverged
    assert p1["chi2"] is None and p1["chi2_z"] is None
    assert p1["diverged"] is True
    assert led.snapshot()["counters"]["diverged"] == 1
    assert led.snapshot()["probe_wall_s"] > 0.0
    # the diverged lane dumped a fit_anomaly naming pulsar + probe +
    # the baseline a healthy whitened chi2 should have sat at
    dumps = sorted(tmp_path.glob("flight_*_fit_anomaly.json"))
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "fit_anomaly"
    ctx = doc["context"]
    assert ctx["pulsar"] == "P1"
    assert ctx["probe"] == "chi2_whitened"
    assert ctx["baseline"] == 44.0
    assert ctx["source"] == "unit"


# -- SLO five-pack + report gate -------------------------------------


def _healthy_snapshot(**over):
    snap = {"counters": {"fits": 100, "fallbacks": 1, "diverged": 0,
                         "drift_alarms": 0},
            "max_abs_chi2_z": 2.1, "max_condition": 5e4,
            "max_relres": 3e-9, "probe_wall_s": 0.001,
            "n_pulsars": 68, "pulsars": {}}
    snap.update(over)
    return snap


def test_fit_quality_slo_five_pack_reads_both_snapshot_shapes():
    specs = {s.name: s for s in fit_quality_slos()}
    assert set(specs) == {"fitq_chi2_z", "fitq_fallback",
                          "fitq_divergence", "fitq_condition",
                          "fitq_drift", "gw_coherence"}
    bare = _healthy_snapshot()
    engine = {"requests": 10, "fit_quality": bare}  # serve snapshot
    for snap in (bare, engine):
        assert specs["fitq_chi2_z"].value(snap) == 2.1
        assert specs["fitq_condition"].value(snap) == 5e4
        assert specs["fitq_fallback"].bad(snap) == 1
        assert specs["fitq_fallback"].total(snap) == 100
        assert specs["fitq_drift"].bad(snap) == 0
        # pair-coherence counters are absent from pre-gw snapshots:
        # the SLO must read them as 0/0, not KeyError
        assert specs["gw_coherence"].bad(snap) == 0
        assert specs["gw_coherence"].total(snap) == 0
    # every budget must stay alertable by the fast burn window
    for s in fit_quality_slos():
        assert 1.0 / s.budget > 14.0


def test_check_report_pass_and_violations():
    ok = check_report(_healthy_snapshot())
    assert ok["ok"] and ok["violations"] == []
    assert ok["checked"]["fits"] == 100
    # vacuous pass: nothing ran, nothing degraded
    assert check_report({})["ok"]
    # chi2 inflation -> chi2_z violation (the doctor-fail fixture)
    bad = check_report(_healthy_snapshot(max_abs_chi2_z=42.0))
    assert not bad["ok"]
    assert [v["probe"] for v in bad["violations"]] == ["chi2_z"]
    # excess fallback rate and any drift alarm each trip their check
    rates = check_report(_healthy_snapshot(
        counters={"fits": 100, "fallbacks": 30, "diverged": 5,
                  "drift_alarms": 2}))
    probes = {v["probe"] for v in rates["violations"]}
    assert probes == {"fallback_rate", "divergence_rate",
                      "drift_alarms"}
    # engine-shaped snapshots gate identically
    assert not check_report(
        {"fit_quality": _healthy_snapshot(max_condition=1e15)})["ok"]


# -- drift sentinels -------------------------------------------------

# The pinned synthetic drift fixture: 10 rounds at 1.0 then a step to
# 5.0. With min_n=4 the EWMA is ready from round 4 on, the constant
# series keeps z == 0 (sigma has a relative floor, so no 0/0), and
# round 10's step is the first non-zero z -- a huge one -> the alarm
# round is exactly 10, deterministically.
PINNED_STEP_ROUND = 10


def test_pinned_drift_fixture_alarm_round(tmp_path):
    obs_recorder.RECORDER.dump_dir = str(tmp_path)
    led = FitQualityLedger()
    board = obs_drift.DriftBoard(min_n=4, ledger=led)
    alarm_rounds = []
    for rnd in range(PINNED_STEP_ROUND + 2):
        val = 1.0 if rnd < PINNED_STEP_ROUND else 5.0
        alarms = board.observe("J0030+0451", {"reduced_chi2": val},
                              slot="s0")
        if alarms:
            alarm_rounds.append(rnd)
    assert alarm_rounds[0] == PINNED_STEP_ROUND
    alarm = board.observe("J0030+0451", {"reduced_chi2": 5.0})
    # EWMA keeps adapting toward the new level; the episode alarmed
    assert board.alarms >= 1
    assert led.snapshot()["counters"]["drift_alarms"] == board.alarms
    # each alarm dumped a fit_anomaly naming pulsar/probe/baseline
    dumps = sorted(tmp_path.glob("flight_*_fit_anomaly.json"))
    assert dumps
    ctx = json.load(open(dumps[0]))["context"]
    assert ctx["pulsar"] == "J0030+0451"
    assert ctx["probe"] == "reduced_chi2"
    assert ctx["baseline"] == pytest.approx(1.0)
    assert ctx["observed"] == 5.0
    assert ctx["source"] == "drift"
    assert ctx["slot"] == "s0"
    del alarm


def test_constant_series_never_alarms():
    # successive refits of identical data are bitwise-constant; the
    # sigma floor keeps that from collapsing to zero variance and
    # alarming on the first ulp of float noise
    board = obs_drift.DriftBoard(min_n=4, ledger=FitQualityLedger())
    for _ in range(50):
        assert board.observe("A", {"param.F0": 150.318}) == []
    assert board.alarms == 0


def test_drift_state_roundtrip_reanchors_without_alarm_storm():
    led = FitQualityLedger()
    board = obs_drift.DriftBoard(min_n=4, k=0.5, h=6.0, ledger=led)
    # warmup with real spread so the sentinel learns sigma ~0.01
    for v in (1.01, 0.99, 1.02, 0.98, 1.00, 1.01, 0.99, 1.00):
        assert board.observe("A", {"reduced_chi2": v}) == []
    # half-accumulated simmer: same-signed ~1.5-sigma steps build
    # CUSUM evidence (S+ ~ 1.9) without firing
    for _ in range(4):
        assert board.observe("A", {"reduced_chi2": 1.015}) == []
    assert board.alarms == 0
    sent = board._sentinels[("A", "reduced_chi2")]
    assert sent.cusum.pos > 1.0  # evidence really is mid-accumulation
    state = json.loads(json.dumps(board.state_dict()))  # JSON-safe
    restored = obs_drift.DriftBoard(ledger=led)
    restored.load_state_dict(state)
    assert restored.snapshot()["series"] == 1
    # a restore must NOT replay the half-accumulated evidence: steady
    # observations near the learned baseline stay quiet
    for _ in range(20):
        assert restored.observe("A", {"reduced_chi2": 1.005}) == []
    assert restored.alarms == 0
    # ... but a real persisting drift still fires after the restore
    fired = False
    for _ in range(30):
        if restored.observe("A", {"reduced_chi2": 1.06}):
            fired = True
            break
    assert fired


def test_drift_state_kind_version_validation():
    sent = obs_drift.DriftSentinel()
    with pytest.raises(ValueError):
        sent.load_state_dict({"kind": "Banana", "version": 1})
    with pytest.raises(ValueError):
        sent.load_state_dict({"kind": "DriftSentinel", "version": 99})
    board = obs_drift.DriftBoard()
    with pytest.raises(ValueError):
        board.load_state_dict(sent.state_dict())  # wrong kind


def test_drift_board_series_cap_and_fit_drift_values():
    board = obs_drift.DriftBoard(max_series=2,
                                 ledger=FitQualityLedger())
    board.observe("A", {"p0": 1.0, "p1": 2.0, "p2": 3.0})
    snap = board.snapshot()
    assert snap["series"] == 2
    assert snap["dropped_series"] == 1
    vals = obs_drift.fit_drift_values(
        [1.5, -2e-16], [0.1, 1e-18], 1.02, names=["F0", "F1"])
    assert vals["reduced_chi2"] == 1.02
    assert vals["param.F0"] == 1.5 and vals["sigma.F1"] == 1e-18
    # None / non-finite probe values are skipped, not crashed on
    assert board.observe("A", {"p0": None, "p1": np.nan}) == []


# -- fleet contract: bitwise + <1% overhead (ISSUE acceptance) -------


def test_fleet_refit_bitwise_with_probes_and_under_1pct_overhead():
    """The traced-fleet product contract at realistic scale: probing a
    68-pulsar batched GLS refit changes NOTHING (bitwise-identical
    parameters, chi2, covariance) and its self-timed probe wall stays
    under 1% of the warm refit. Probe cost scales with pulsar count
    (host numpy per pulsar) while fit wall scales with TOA count, so
    the contract is pinned here at 68x400 -- toy fleets (6x48) would
    show probe/fit ratios the contract never promises."""
    import sys

    import jax

    sys.path.insert(0, "/root/repo")
    try:
        from bench import build_batch
    finally:
        sys.path.remove("/root/repo")
    from pint_tpu.parallel import PTABatch

    models, toas = build_batch(68, 400)
    pta = PTABatch(models, toas)
    pta.gls_fit(maxiter=2)  # compile + warm
    off = float("inf")
    for _ in range(3):
        t0 = obs.clock.now()
        x, chi2, cov = pta.gls_fit(maxiter=2)
        jax.block_until_ready(chi2)
        off = min(off, obs.clock.now() - t0)
    fitquality.reset()
    fitquality.enable()
    try:
        n_probed = 3
        probe_walls = []
        prev_wall = 0.0
        for _ in range(n_probed):
            x2, chi2_2, cov2 = pta.gls_fit(maxiter=2)
            jax.block_until_ready(chi2_2)
            wall = fitquality.FITQ.snapshot()["probe_wall_s"]
            probe_walls.append(wall - prev_wall)
            prev_wall = wall
        snap = fitquality.FITQ.snapshot()
    finally:
        fitquality.disable()
    assert np.array_equal(np.asarray(x), np.asarray(x2))
    assert np.array_equal(np.asarray(chi2), np.asarray(chi2_2))
    assert np.array_equal(np.asarray(cov), np.asarray(cov2))
    assert snap["counters"]["fits"] == 68 * n_probed
    assert snap["n_pulsars"] == 68
    # min-of-3 on both sides: the steady-state probe tax vs the warm
    # refit, neither contaminated by one-off warmup or scheduler noise
    probe = min(probe_walls)
    assert probe < 0.01 * off, (
        "probe wall %.6fs is %.2f%% of the %.4fs warm refit"
        % (probe, 100 * probe / off, off))
    # the probes saw real numbers, not placeholder Nones
    assert snap["max_abs_chi2_z"] is not None
    assert snap["max_condition"] is not None


def test_solver_diverge_chaos_dumps_attributed_anomaly(tmp_path):
    """Injected solver divergence (the resilience fault point that
    NaNs a lane's chi2 exactly where a real blow-up surfaces) must
    produce a fit_anomaly flight dump attributing the divergence to
    the right pulsar, probe, and baseline."""
    from pint_tpu.parallel import PTAFleet
    from pint_tpu.scripts.pint_serve_bench import build_serve_fleet

    models, toas = build_serve_fleet(sizes=(48,), per_combo=2, seed=5)
    fleet = PTAFleet(models, toas, bucket_floor=32)
    obs_recorder.RECORDER.dump_dir = str(tmp_path)
    fitquality.reset()
    fitquality.enable()
    try:
        with inject(FaultPoint("solver_diverge", count=1,
                               payload={"lanes": [1]})):
            fleet.fit(method="gls", maxiter=2)
    finally:
        fitquality.disable()
    snap = fitquality.FITQ.snapshot()
    assert snap["counters"]["diverged"] >= 1
    dumps = sorted(tmp_path.glob("flight_*_fit_anomaly.json"))
    assert dumps, "injected divergence produced no flight dump"
    ctx = json.load(open(dumps[0]))["context"]
    assert ctx["pulsar"] == "SRV1"
    assert ctx["probe"] == "chi2_whitened"
    # 48 TOAs - 3 free params - offset column = 44 expected chi2
    assert ctx["baseline"] == 44.0
    assert ctx["source"].startswith("pta.")
    # the diverged pulsar's ledger record carries the flag
    assert fitquality.FITQ.get("SRV1")["diverged"] is True


# -- serve engine integration ----------------------------------------


def _serve_pulsar(i, n_toa=24):
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = (f"PSR SRVQ{i}\nRAJ 12:0{i}:00.0\nDECJ 10:00:00.0\n"
           f"F0 3{i}1.25 1\nF1 -4e-16 1\nPEPOCH 55500\nDM 12.{i} 1\n")
    m = get_model(par)
    rng = np.random.default_rng(7 + i)
    mjds = np.sort(rng.uniform(54500, 56500, n_toa))
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0,
                                freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=7 + i,
                                iterations=0)
    return m, t


def test_serve_attach_fit_quality_snapshot_state_and_slo(tmp_path):
    from pint_tpu.serve import FitRequest, ServeEngine

    pulsars = [_serve_pulsar(0), _serve_pulsar(1)]
    eng = ServeEngine(max_batch=2, max_latency_s=1e9, bucket_floor=32)
    board = eng.attach_fit_quality(slo=True, min_n=3)
    assert fitquality.enabled()
    # the fit_quality five-pack joined the burn-rate monitor
    names = {s.name for s in eng._slo_monitor.specs}
    assert {"fitq_chi2_z", "fitq_fallback", "fitq_divergence",
            "fitq_condition", "fitq_drift"} <= names
    for _ in range(5):  # successive refits feed the drift sentinels
        r0 = eng.submit(FitRequest(*pulsars[0], maxiter=2))
        r1 = eng.submit(FitRequest(*pulsars[1], maxiter=2))
        assert r0.status == "ok" and r1.status == "ok"
    snap = eng.snapshot()
    fq = snap["fit_quality"]
    assert fq["counters"]["fits"] >= 10
    assert fq["drift"]["series"] > 0
    assert fq["drift"]["alarms"] == 0  # boring fleet stays boring
    # checkpoint -> JSON -> restore into a FRESH engine: the board
    # re-anchors (baselines carried, CUSUM evidence not) and further
    # steady refits raise no alarm storm
    state = json.loads(json.dumps(eng.state_dict()))
    assert state["kind"] == "ServeEngineState"
    eng2 = ServeEngine(max_batch=2, max_latency_s=1e9,
                       bucket_floor=32)
    eng2.load_state_dict(state)
    assert (eng2._fitq_board.snapshot()["series"]
            == board.snapshot()["series"])
    for _ in range(3):
        eng2.submit(FitRequest(*pulsars[0], maxiter=2))
        eng2.submit(FitRequest(*pulsars[1], maxiter=2))
    assert eng2._fitq_board.alarms == 0
    with pytest.raises(ValueError):
        eng2.load_state_dict({"kind": "Nope", "version": 1})
    # Prometheus exposition carries the fitq gauges
    reg = eng2.export_metrics()
    text = obs.prometheus_text(registry=reg)
    assert "fitq_counters_fits" in text
    assert "fitq_drift_series" in text


# -- CLI: fitq + doctor ----------------------------------------------


def test_fitq_cli_gates_on_snapshot(tmp_path, capsys):
    from pint_tpu.obs.__main__ import main

    good = tmp_path / "good.json"
    good.write_text(json.dumps(_healthy_snapshot()))
    assert main(["fitq", str(good)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["report"]["ok"]
    assert out["ledger"]["counters"]["fits"] == 100
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_healthy_snapshot(max_abs_chi2_z=42.0)))
    assert main(["fitq", str(bad)]) == 1
    out = json.loads(capsys.readouterr().out)
    assert [v["probe"] for v in out["report"]["violations"]] \
        == ["chi2_z"]


def test_doctor_cli_passes_on_repo_history(capsys):
    """doctor over the repo's real BENCH trajectory: the shipped
    budgets must hold on the shipped history (regress section), and
    with no fitq snapshot the fitq section simply doesn't run."""
    from pint_tpu.obs.__main__ import main

    rc = main(["doctor", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0, out["failures"]
    assert out["ok"]
    assert out["sections"]["regress"]["ok"]
    assert "fitq" not in out["sections"]


def test_doctor_cli_fails_on_chi2_inflation_fixture(tmp_path,
                                                    capsys):
    from pint_tpu.obs.__main__ import main

    fixture = tmp_path / "inflated.json"
    fixture.write_text(json.dumps(
        _healthy_snapshot(max_abs_chi2_z=42.0)))
    rc = main(["doctor", "--json", "--fitq-snapshot", str(fixture)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["failures"] == ["fitq"]
    assert not out["sections"]["fitq"]["ok"]
    probes = [v["probe"]
              for v in out["sections"]["fitq"]["violations"]]
    assert probes == ["chi2_z"]
