"""Statistical calibration of fitter uncertainties: parameter pulls.

Simulate many independent noise realizations, fit each, and check that
(fitted - true) / sigma_fitted is a unit normal per parameter and that
chi2 follows its expected distribution. This is the test that catches
a wrong covariance normalization (sigma off by sqrt(2), missing EFAC
in the weights, ...) that residual-level tests cannot see.
(reference pattern: SURVEY.md section 4 pattern 3 — upstream pins GLS
uncertainties against known NANOGrav noise runs; with no external runs
available the calibration is checked against the simulator instead,
which is an independent code path from the fitters.)

Runtime note: all realizations share one compiled program via the
process-global structure cache; the loop is host-prep-bound.
"""

import copy
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.fitter import GLSFitter, WLSFitter
from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = ("PSR CALIB\nRAJ 11:45:01.0\nDECJ -20:30:00.0\n"
       "F0 245.4261196 1\nF1 -5.31e-16 1\nPEPOCH 55500\nDM 24.9 1\n")


def _pulls(par, n_real, n_toa, fitter_cls, add_correlated=False,
           maxiter=2, seed0=100):
    m0 = get_model(par)
    truth = {p: getattr(m0, p).value for p in ("F0", "F1", "DM")}
    rng = np.random.default_rng(7)
    mjds = np.sort(rng.uniform(55000, 56000, n_toa))
    freqs = np.where(np.arange(n_toa) % 2, 1400.0, 800.0)
    pulls = {p: [] for p in truth}
    chi2s = []
    dof = None
    for k in range(n_real):
        m = copy.deepcopy(m0)
        t = make_fake_toas_fromMJDs(
            mjds, m, error_us=1.0, freq_mhz=freqs, obs="gbt",
            add_noise=True, add_correlated_noise=add_correlated,
            seed=seed0 + k)
        f = fitter_cls(t, m)
        f.fit_toas(maxiter=maxiter)
        for p in truth:
            sigma = getattr(f.model, p).uncertainty
            assert sigma and np.isfinite(sigma), (p, sigma)
            pulls[p].append((getattr(f.model, p).value - truth[p]) / sigma)
        chi2s.append(float(f.resids.chi2))
        dof = f.resids.dof
    return {p: np.array(v) for p, v in pulls.items()}, np.array(chi2s), dof


def _check_unit_normal(pulls, n_real):
    # std of the sample std for N(0,1) is ~1/sqrt(2K); allow 4-sigma-ish
    lo, hi = 1 - 4 / np.sqrt(2 * n_real), 1 + 4 / np.sqrt(2 * n_real)
    for p, v in pulls.items():
        assert abs(v.mean()) < 4 / np.sqrt(n_real), (p, v.mean())
        assert lo < v.std(ddof=1) < hi, \
            f"{p}: pull std {v.std(ddof=1):.3f} outside [{lo:.2f},{hi:.2f}]"


def test_wls_pull_distribution_white_noise():
    """WLS with pure white noise: pulls unit-normal, chi2 ~ chi2(dof)."""
    n_real = 48
    pulls, chi2s, dof = _pulls(PAR, n_real, 150, WLSFitter)
    _check_unit_normal(pulls, n_real)
    # mean chi2 = dof +- 4*sqrt(2*dof/K)
    assert abs(chi2s.mean() - dof) < 4 * np.sqrt(2 * dof / n_real), \
        (chi2s.mean(), dof)


def test_gls_pull_distribution_efac_equad():
    """GLS under EFAC+EQUAD: the whitened solve must propagate the
    scaled errors into sigma — a missing EFAC shows up as pull std
    ~1.3 here."""
    par = PAR + "EFAC -f L-wide 1.3\nEQUAD -f L-wide 0.8\n"
    n_real = 40
    m0 = get_model(par)
    truth = {p: getattr(m0, p).value for p in ("F0", "F1", "DM")}
    rng = np.random.default_rng(7)
    n_toa = 120
    mjds = np.sort(rng.uniform(55000, 56000, n_toa))
    freqs = np.where(np.arange(n_toa) % 2, 1400.0, 800.0)
    pulls = {p: [] for p in truth}
    for k in range(n_real):
        m = copy.deepcopy(m0)
        # flags set at creation so the mask-selected EFAC/EQUAD apply
        # to the noise draw AND the fit
        t2 = make_fake_toas_fromMJDs(
            mjds, m, error_us=1.0, freq_mhz=freqs, obs="gbt",
            add_noise=True, seed=300 + k,
            flags={"f": "L-wide"})
        f = GLSFitter(t2, m)
        f.fit_toas(maxiter=2)
        for p in truth:
            sigma = getattr(f.model, p).uncertainty
            pulls[p].append((getattr(f.model, p).value - truth[p]) / sigma)
    pulls = {p: np.array(v) for p, v in pulls.items()}
    _check_unit_normal(pulls, n_real)


def test_wideband_pull_distribution():
    """WidebandTOAFitter: pulls stay unit-normal when the DM data
    stream (per-TOA -pp_dm/-pp_dme measurements) joins the fit — a
    mis-weighted DM block would decalibrate the DM sigma first."""
    from pint_tpu.fitter import WidebandTOAFitter

    n_real = 36
    m0 = get_model(PAR)
    truth = {p: getattr(m0, p).value for p in ("F0", "F1", "DM")}
    rng = np.random.default_rng(11)
    n_toa = 100
    mjds = np.sort(rng.uniform(55000, 56000, n_toa))
    freqs = np.where(np.arange(n_toa) % 2, 1400.0, 800.0)
    pulls = {p: [] for p in truth}
    for k in range(n_real):
        m = copy.deepcopy(m0)
        t = make_fake_toas_fromMJDs(
            mjds, m, error_us=1.0, freq_mhz=freqs, obs="gbt",
            add_noise=True, seed=500 + k, wideband=True,
            dm_error_pccm3=2e-4)
        f = WidebandTOAFitter(t, m)
        f.fit_toas(maxiter=2)
        for p in truth:
            sigma = getattr(f.model, p).uncertainty
            assert sigma and np.isfinite(sigma), (p, sigma)
            pulls[p].append((getattr(f.model, p).value - truth[p]) / sigma)
    pulls = {p: np.array(v) for p, v in pulls.items()}
    _check_unit_normal(pulls, n_real)


def test_gls_pull_distribution_ecorr_rednoise():
    """GLS under ECORR + power-law red noise (both marginalized via
    the Woodbury basis): spin/DM pulls must stay unit-normal when the
    simulator draws correlated noise from the same model. This is the
    end-to-end check of the ECORR epoch quantization AND the red-noise
    Fourier-basis weights — a wrong basis normalization inflates or
    deflates every sigma here."""
    par = (PAR + "EFAC -f L-wide 1.1\nEQUAD -f L-wide 0.5\n"
           "ECORR -f L-wide 0.9\nRNAMP 1e-14\nRNIDX -3.5\nTNREDC 15\n")
    n_real = 32
    m0 = get_model(par)
    truth = {p: getattr(m0, p).value for p in ("F0", "F1", "DM")}
    rng = np.random.default_rng(13)
    n_epochs = 40
    days = np.sort(rng.uniform(55000, 56000, n_epochs))
    # 4 TOAs clustered per epoch so ECORR has real blocks
    mjds = np.sort(np.concatenate(
        [days + j * 0.4 / 86400.0 for j in range(4)]))
    freqs = np.tile([800.0, 1400.0, 800.0, 1400.0], n_epochs)
    pulls = {p: [] for p in truth}
    for k in range(n_real):
        m = copy.deepcopy(m0)
        t = make_fake_toas_fromMJDs(
            mjds, m, error_us=1.0, freq_mhz=freqs, obs="gbt",
            add_noise=True, add_correlated_noise=True, seed=700 + k,
            flags={"f": "L-wide"})
        f = GLSFitter(t, m)
        f.fit_toas(maxiter=2)
        for p in truth:
            sigma = getattr(f.model, p).uncertainty
            assert sigma and np.isfinite(sigma), (p, sigma)
            pulls[p].append((getattr(f.model, p).value - truth[p]) / sigma)
    pulls = {p: np.array(v) for p, v in pulls.items()}
    _check_unit_normal(pulls, n_real)
