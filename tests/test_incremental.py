"""Incremental streaming refits (ISSUE 20): kernel bit-identity,
delta-chain durability, and the serve append path.

The load-bearing contract is parity by CONSTRUCTION: the incremental
normal state folds the same block Grams through the same sequential
left fold as the from-scratch comparator, so accumulators — and the
parameters solved from them — are bitwise identical, not merely
close. Escalation (drift alarm / solver divergence) must likewise be
bitwise what a fresh registration on the merged dataset produces.
"""

import os

import numpy as np
import pytest

from pint_tpu.kernels import incremental as inc


def _rows(rng, n, k):
    X = rng.standard_normal((n, k))
    r = rng.standard_normal(n) * 1e-6
    winv = rng.uniform(0.5, 2.0, n) * 1e6
    return X, r, winv


def _chunks(seed=0, n_base=3000, k=6, appends=(5, 64, 17)):
    rng = np.random.default_rng(seed)
    out = [_rows(rng, n_base, k)]
    out.extend(_rows(rng, n, k) for n in appends)
    return out


# -- kernel bit-identity ------------------------------------------------


def test_incremental_accumulators_bitwise_vs_scratch():
    chunks = _chunks()
    k = chunks[0][0].shape[1]
    q = np.full(k, 1e-6)
    state = inc.build_normal(*chunks[0], q=q)
    for X, r, winv in chunks[1:]:
        state.append(X, r, winv)
    A0, b0, rNr = inc.scratch_normal(chunks, block=1024)
    assert np.array_equal(np.asarray(state.A0), np.asarray(A0))
    assert np.array_equal(np.asarray(state.b), np.asarray(b0))
    assert np.array_equal(np.asarray(state.rNr), np.asarray(rNr))


def test_incremental_parity_budget_on_clone_append():
    """The bench parity scenario and its acceptance gate: a state
    cloned from persisted accumulators (L=None) appends one block
    and must match the scratch refit within the floored relative
    parity budget (incremental_parity_max_rel <= 1e-15)."""
    chunks = _chunks(seed=3, appends=(64,))
    k = chunks[0][0].shape[1]
    q = np.full(k, 1e-6)
    base = inc.build_normal(*chunks[0], q=q)
    state = inc.IncrementalNormal(base.A0, base.b, base.rNr, q=base.q)
    state.append(*chunks[1])
    dx_i, chi2_i, info_i = state.solve()
    dx_s, chi2_s, _st, info_s = inc.scratch_refit(chunks, q=q)
    assert info_i["solver"] == info_s["solver"]
    ref = np.asarray(dx_s)
    den = np.maximum(
        np.abs(ref),
        np.finfo(np.float64).eps * max(float(np.max(np.abs(ref))),
                                       1e-300))
    assert np.max(np.abs(np.asarray(dx_i) - ref) / den) <= 1e-15
    assert abs(chi2_i - chi2_s) <= 1e-12 * max(abs(chi2_s), 1e-300)


def test_incremental_multi_append_parity_vs_scratch_refit():
    """Chained chol_update appends solve through the rank-r-updated
    factor, which may differ from a fresh factor by ULPs in the
    smallest components — gate on the norm-scaled parity budget."""
    chunks = _chunks(seed=3)
    k = chunks[0][0].shape[1]
    q = np.full(k, 1e-6)
    state = inc.build_normal(*chunks[0], q=q)
    for X, r, winv in chunks[1:]:
        state.append(X, r, winv)
    dx_i, chi2_i, info_i = state.solve()
    dx_s, chi2_s, _st, info_s = inc.scratch_refit(chunks, q=q)
    assert info_i["solver"] == info_s["solver"]
    ref = np.asarray(dx_s)
    scale = max(float(np.max(np.abs(ref))), 1e-300)
    assert np.max(np.abs(np.asarray(dx_i) - ref)) <= 1e-15 * scale
    assert abs(chi2_i - chi2_s) <= 1e-12 * max(abs(chi2_s), 1e-300)


def test_chol_update_factorizes_the_updated_normal():
    rng = np.random.default_rng(7)
    chunks = _chunks(seed=7, appends=(32,))
    k = chunks[0][0].shape[1]
    state = inc.build_normal(*chunks[0], q=np.full(k, 1e-3))
    X, r, winv = chunks[1]
    state.append(X, r, winv)
    L = np.asarray(state.L)
    A = np.asarray(state.A)
    assert np.allclose(L @ L.T, A, rtol=1e-10, atol=1e-10 * np.abs(A).max())
    dx, _, info = state.solve()
    assert info["solver"] == "chol_update"
    assert info["relres"] <= 1e-12
    ref = np.linalg.solve(A, np.asarray(state.b))
    den = np.maximum(np.abs(ref), 1e-30)
    assert np.max(np.abs(np.asarray(dx) - ref) / den) < 1e-9
    del rng


def test_append_survives_unfactorable_normal():
    """An indefinite A (no Cholesky factor exists) must route
    through the eigh fallback, and the NEXT append must not crash
    on the absent factor (the L-None guard re-attempts a fresh
    refactor instead of rank-updating nothing)."""
    rng = np.random.default_rng(11)
    k = 6
    state = inc.IncrementalNormal(-np.eye(k), np.ones(k), 1.0,
                                  q=np.zeros(k))
    Xa, ra, wa = _rows(rng, 8, k)
    # a small append keeps A indefinite: chol stays impossible
    state.append(Xa * 1e-8, ra, wa * 1e-12)
    dx, chi2, info = state.solve()
    assert info["solver"] == "eigh_refresh"
    assert np.all(np.isfinite(np.asarray(dx)))
    state.append(Xa * 1e-8, ra, wa * 1e-12)  # must not raise on L=None
    dx2, _, info2 = state.solve()
    assert info2["solver"] == "eigh_refresh"
    assert np.all(np.isfinite(np.asarray(dx2)))


def test_delta_gram_pallas_interpret_matches_f64(pallas_interpret):
    X, r, winv = _rows(np.random.default_rng(5), 24, 6)
    ref = np.asarray(inc.delta_gram(X, r, winv, precision="f64"))
    got = np.asarray(inc.delta_gram(X, r, winv, precision="mixed",
                                    interpret=pallas_interpret))
    scale = max(np.abs(ref).max(), 1.0)
    assert np.allclose(got, ref, atol=5e-5 * scale)


# -- delta store --------------------------------------------------------


def _arrays(rng, n=16, k=5):
    X, r, winv = _rows(rng, n, k)
    return {"X": X, "r": r, "winv": winv}


def test_delta_chain_roundtrip_and_replay(tmp_path):
    from pint_tpu.store import DeltaStore

    ds = DeltaStore(tmp_path)
    rng = np.random.default_rng(0)
    base = "base-sig"
    a1, a2 = _arrays(rng), _arrays(rng)
    tip1, rep1 = ds.append("J0000+0000", base, a1, rid="req-0")
    assert not rep1
    tip2, rep2 = ds.append("J0000+0000", tip1, a2, rid="req-1")
    assert not rep2 and tip2 != tip1
    # crash replay of the newest link: same rid + payload -> no new
    # segment, existing tip returned
    tip2b, rep2b = ds.append("J0000+0000", tip1, a2, rid="req-1")
    assert rep2b and tip2b == tip2
    assert ds.counters()["replays"] == 1
    chain = ds.load_chain("J0000+0000", base)
    assert [sig for sig, _ in chain] == [tip1, tip2]
    for (_, got), want in zip(chain, (a1, a2)):
        for name in ("X", "r", "winv"):
            assert np.array_equal(got[name], want[name])
    assert ds.scan() == {"segments": 2, "valid": 2,
                         "corrupt_or_stale": 0,
                         "bytes": ds.scan()["bytes"]}


def test_delta_append_rejects_diverged_parent(tmp_path):
    from pint_tpu.store import DeltaStore

    ds = DeltaStore(tmp_path)
    rng = np.random.default_rng(1)
    tip, _ = ds.append("J1", "base", _arrays(rng), rid="r0")
    with pytest.raises(ValueError, match="chain"):
        ds.append("J1", "not-the-tip", _arrays(rng), rid="r1")


def test_delta_chain_invalidates_corrupt_suffix(tmp_path):
    from pint_tpu.store import DeltaStore

    ds = DeltaStore(tmp_path)
    rng = np.random.default_rng(2)
    tip1, _ = ds.append("J2", "base", _arrays(rng), rid="r0")
    tip2, _ = ds.append("J2", tip1, _arrays(rng), rid="r1")
    tip3, _ = ds.append("J2", tip2, _arrays(rng), rid="r2")
    paths = ds._chain_paths("J2")
    with open(paths[1], "r+b") as fh:  # corrupt the middle segment
        fh.seek(40)
        fh.write(b"\xff\xff\xff\xff")
    with pytest.warns(UserWarning, match="delta chain broken"):
        chain = ds.load_chain("J2", "base")
    # verified prefix only; the corrupt segment AND its successor die
    assert [sig for sig, _ in chain] == [tip1]
    assert ds.scan()["segments"] == 1


def test_delta_prewarm_stages_verified_chain(tmp_path):
    from pint_tpu.store import DeltaStore

    ds = DeltaStore(tmp_path)
    rng = np.random.default_rng(3)
    tip, _ = ds.append("J3", "base", _arrays(rng), rid="r0")
    t = ds.prewarm([("J3", "base")], background=True)
    if t is not None:
        t.join(timeout=30)
    chain = ds.load_chain("J3", "base")
    assert [sig for sig, _ in chain] == [tip]
    assert ds.counters()["prewarm_hits"] == 1


def test_delta_append_over_unreadable_tip_invalidates_visibly(tmp_path):
    """An unreadable newest segment must not let append publish a link
    whose on-disk predecessor can never verify (load_chain would later
    delete the committed link silently).  append invalidates the torn
    tip loudly and chains onto the newest verified predecessor."""
    from pint_tpu.store import DeltaStore

    ds = DeltaStore(tmp_path)
    rng = np.random.default_rng(21)
    t1, _ = ds.append("J9", "base", _arrays(rng), rid="r0")
    t2, _ = ds.append("J9", t1, _arrays(rng), rid="r1")
    os.truncate(ds._chain_paths("J9")[-1], 6)  # tear the newest segment
    a3 = _arrays(rng)
    with pytest.warns(UserWarning, match="delta chain broken"):
        t3, replay = ds.append("J9", t1, a3, rid="r2")
    assert not replay and t3 not in (t1, t2)
    # the published segment verifies end to end: nothing left for
    # load_chain to silently drop
    chain = ds.load_chain("J9", "base")
    assert [sig for sig, _ in chain] == [t1, t3]
    assert ds.scan()["corrupt_or_stale"] == 0
    # a caller whose in-memory tip WAS the torn segment diverges loudly
    with pytest.raises(ValueError, match="chain tip"):
        ds.append("J9", t2, _arrays(rng), rid="r3")


def test_delta_scan_keeps_concurrent_corruption_counts(tmp_path,
                                                       monkeypatch):
    """scan() must count corruption locally: a concurrent reader's
    corrupt increment landing mid-scan survives instead of being
    clobbered by a snapshot/restore of the shared counters."""
    from pint_tpu.store import DeltaStore

    ds = DeltaStore(tmp_path)
    rng = np.random.default_rng(22)
    ds.append("J10", "base", _arrays(rng), rid="r0")
    os.truncate(ds._chain_paths("J10")[0], 6)
    orig = DeltaStore._load_verified
    fired = []

    def racing(self, path, count=True):
        # simulate a load_chain on another thread landing a corruption
        # count while scan's verification loop is mid-flight
        if not count and not fired:
            fired.append(True)
            self._note_bad("corrupt")
        return orig(self, path, count=count)

    monkeypatch.setattr(DeltaStore, "_load_verified", racing)
    rep = ds.scan()
    assert rep["segments"] == 1 and rep["corrupt_or_stale"] == 1
    # the concurrent increment survives; scan itself added none
    assert ds.counters()["corrupt"] == 1


# -- streaming lanes ----------------------------------------------------


_PAR = """\
PSR TSTR0
RAJ 11:00:00.0
DECJ 8:00:00.0
F0 289.5 1
F1 -3.2e-16 1
PEPOCH 55500
DM 15.0 1
"""


def _lane_fixture(seed=0, n_base=48, chunk_sizes=(6, 8), psr="TSTR0"):
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    rng = np.random.default_rng(seed)
    model = get_model(_PAR.replace("TSTR0", psr))
    base = make_fake_toas_fromMJDs(
        np.sort(rng.uniform(54800, 56000, n_base)), model,
        error_us=1.0, freq_mhz=1400.0, obs="gbt", add_noise=True,
        seed=seed)
    chunks, lo = [], 56000.0
    for i, n in enumerate(chunk_sizes):
        mj = np.sort(rng.uniform(lo, lo + 10.0, n))
        lo += 10.0
        chunks.append(make_fake_toas_fromMJDs(
            mj, model, error_us=1.0, freq_mhz=1400.0, obs="gbt",
            add_noise=True, seed=seed + 100 + i))
    return model, base, chunks


def _merge(base, chunks):
    from pint_tpu.toa import merge_TOAs

    return merge_TOAs([base] + list(chunks))


def test_streaming_rounds_then_escalation_bitwise_vs_fresh():
    """R rounds of incremental appends, then a drift-triggered full
    refit: the escalated lane must be bitwise what a FRESH
    registration on the merged dataset produces (the escalation
    bit-identity contract)."""
    from pint_tpu.obs.drift import DriftSentinel
    from pint_tpu.serve.streaming import StreamingRefitter

    model, base, chunks = _lane_fixture(seed=4, chunk_sizes=(6, 8, 5))
    # min_n=1, tiny trip: the LAST append's drift statistic always
    # alarms, forcing the escalation path deterministically
    sr = StreamingRefitter()
    sr.register(model, base,
                sentinel=DriftSentinel(min_n=1, z_trip=1e-12))
    results = [sr.append(model, c, rid=f"r{i}")
               for i, c in enumerate(chunks)]
    final = results[-1]
    assert final["escalated"] and final["solver"] == "full_refit"
    assert sr.counters()["escalated"] >= 1

    model2, base2, chunks2 = _lane_fixture(seed=4,
                                           chunk_sizes=(6, 8, 5))
    fresh = StreamingRefitter()
    fresh.register(model2, _merge(base2, chunks2))
    lane_f = fresh.lane(model2)
    x_f, chi2_f, _ = fresh._solve(lane_f)
    assert np.array_equal(final["x"], x_f)
    assert final["chi2"] == chi2_f


def test_streaming_solver_diverge_quarantines_and_escalates():
    """An injected solver_diverge mid-append must complete the
    request with a full-refit value — bitwise the fresh-registration
    result — never propagate the quarantined incremental solve."""
    from pint_tpu.resilience import faultinject
    from pint_tpu.serve.streaming import StreamingRefitter

    model, base, chunks = _lane_fixture(seed=9, chunk_sizes=(7,))
    sr = StreamingRefitter()
    sr.register(model, base)
    with faultinject.inject("solver_diverge"):
        with pytest.warns(UserWarning, match="escalated"):
            out = sr.append(model, chunks[0], rid="r0")
    assert out["escalated"]
    assert out["escalation_reason"] == "solver_diverge"

    model2, base2, chunks2 = _lane_fixture(seed=9, chunk_sizes=(7,))
    fresh = StreamingRefitter()
    fresh.register(model2, _merge(base2, chunks2))
    x_f, chi2_f, _ = fresh._solve(fresh.lane(model2))
    assert np.array_equal(out["x"], x_f)
    assert out["chi2"] == chi2_f


def test_streaming_incremental_stays_on_fast_path():
    from pint_tpu.serve.streaming import StreamingRefitter

    model, base, chunks = _lane_fixture(seed=6)
    sr = StreamingRefitter()
    sr.register(model, base)
    for i, c in enumerate(chunks):
        out = sr.append(model, c, rid=f"r{i}")
        assert not out["escalated"]
        assert out["solver"] in ("chol_update", "eigh_refresh")
        assert np.all(np.isfinite(out["x"]))
    assert sr.counters() == {"lanes": 1, "appends": len(chunks),
                             "escalated": 0, "replayed": 0}


def test_streaming_unregistered_lane_raises_keyerror():
    from pint_tpu.serve.streaming import StreamingRefitter

    model, base, chunks = _lane_fixture(seed=5, chunk_sizes=(4,))
    with pytest.raises(KeyError, match="no streaming lane"):
        StreamingRefitter().append(model, chunks[0], rid="r0")


def test_streaming_chain_replay_bitwise_across_restart(tmp_path):
    """Process-restart durability: a second refitter over the same
    delta store re-registers the lane, replays the persisted chain,
    and solves to bitwise the first process's answer."""
    from pint_tpu.store import DeltaStore
    from pint_tpu.serve.streaming import StreamingRefitter

    model, base, chunks = _lane_fixture(seed=8)
    sr1 = StreamingRefitter(deltas=DeltaStore(tmp_path))
    sr1.register(model, base)
    out1 = None
    for i, c in enumerate(chunks):
        out1 = sr1.append(model, c, rid=f"r{i}")

    model2, base2, _ = _lane_fixture(seed=8)
    sr2 = StreamingRefitter(deltas=DeltaStore(tmp_path))
    sr2.register(model2, base2)
    assert sr2.counters()["replayed"] == len(chunks)
    lane2 = sr2.lane(model2)
    assert lane2.tip == out1["chain"]
    x2, chi2_2, _ = sr2._solve(lane2)
    assert np.array_equal(out1["x"], x2)
    assert out1["chi2"] == chi2_2


def test_streaming_escalation_rechains_deltas_and_appends_resume(
        tmp_path):
    """Escalation on a delta-backed lane re-roots the persisted chain
    at the merged base: the old segments are invalidated visibly and
    the NEXT append must succeed on a fresh chain (previously it hit
    the parent-divergence guard and bricked the lane)."""
    from pint_tpu.resilience import faultinject
    from pint_tpu.store import DeltaStore
    from pint_tpu.serve.streaming import StreamingRefitter

    model, base, chunks = _lane_fixture(seed=15, chunk_sizes=(6, 5, 4))
    ds = DeltaStore(tmp_path)
    sr = StreamingRefitter(deltas=ds)
    sr.register(model, base)
    out1 = sr.append(model, chunks[0], rid="r0")
    assert not out1["escalated"] and ds.scan()["segments"] == 1

    with faultinject.inject("solver_diverge"):
        with pytest.warns(UserWarning, match="escalated"):
            out2 = sr.append(model, chunks[1], rid="r1")
    assert out2["escalated"]
    lane = sr.lane(model)
    # old chain deleted, lane re-rooted at the merged base signature
    assert ds.scan()["segments"] == 0
    assert lane.tip == lane.base_signature

    out3 = sr.append(model, chunks[2], rid="r2")
    assert not out3["escalated"]
    assert np.all(np.isfinite(out3["x"]))
    chain = ds.load_chain(lane.key, lane.base_signature)
    assert [sig for sig, _ in chain] == [out3["chain"]]

    # same math as an escalated delta-less lane: the re-root is pure
    # bookkeeping, never a numeric fork
    model2, base2, chunks2 = _lane_fixture(seed=15,
                                           chunk_sizes=(6, 5, 4))
    ref = StreamingRefitter()
    ref.register(model2, base2)
    ref.append(model2, chunks2[0], rid="r0")
    with faultinject.inject("solver_diverge"):
        with pytest.warns(UserWarning, match="escalated"):
            ref.append(model2, chunks2[1], rid="r1")
    out3_ref = ref.append(model2, chunks2[2], rid="r2")
    assert np.array_equal(out3["x"], out3_ref["x"])
    assert out3["chi2"] == out3_ref["chi2"]


def test_streaming_escalation_after_replay_keeps_chain_and_rows(
        tmp_path):
    """A lane restored via chain replay holds its replayed rows only as
    accumulators: escalating it must refresh in place — keeping the
    persisted chain and every replayed row — not merge base+chunks
    (which would silently drop the replayed rows and delete their
    durable segments)."""
    from pint_tpu.resilience import faultinject
    from pint_tpu.store import DeltaStore
    from pint_tpu.serve.streaming import StreamingRefitter

    model, base, chunks = _lane_fixture(seed=16,
                                        chunk_sizes=(5, 4, 6, 4, 5))
    sr1 = StreamingRefitter(deltas=DeltaStore(tmp_path))
    sr1.register(model, base)
    for i, c in enumerate(chunks[:2]):
        sr1.append(model, c, rid=f"r{i}")

    model2, base2, chunks2 = _lane_fixture(seed=16,
                                           chunk_sizes=(5, 4, 6, 4, 5))
    ds2 = DeltaStore(tmp_path)
    sr2 = StreamingRefitter(deltas=ds2)
    sr2.register(model2, base2)
    lane = sr2.lane(model2)
    assert lane.replayed_segments == 2
    out3 = sr2.append(model2, chunks2[2], rid="r2")
    assert not out3["escalated"]
    n_rows = lane.n_appended

    with faultinject.inject("solver_diverge"):
        with pytest.warns(UserWarning, match="escalated"):
            out4 = sr2.append(model2, chunks2[3], rid="r3")
    assert out4["escalated"]
    # chain intact (2 replayed + 2 live segments), tip NOT re-rooted
    assert ds2.scan()["segments"] == 4
    assert lane.tip == out4["chain"] != lane.base_signature
    # every replayed row still in the state
    assert out4["n_appended"] == lane.n_appended > n_rows
    assert np.all(np.isfinite(out4["x"]))

    # and the next append chains cleanly onto the surviving tip
    out5 = sr2.append(model2, chunks2[4], rid="r4")
    assert not out5["escalated"]
    assert ds2.scan()["segments"] == 5


def test_streaming_concurrent_lanes_lock_discipline(tmp_path):
    """Appends on independent lanes run under per-lane locks: two
    threads hammer two lanes while lockcheck instrumentation records
    attribute writes and acquisition order.  No unsynchronized write,
    no refitter-lock-held -> lane-lock edge (the inversion that would
    re-serialize all lanes), and the observed edge set stays acyclic."""
    import threading

    from lockcheck import (assert_no_violations, find_cycle, instrument,
                           record_order)

    from pint_tpu.store import DeltaStore
    from pint_tpu.serve.streaming import (StreamingLane,
                                          StreamingRefitter)

    ma, base_a, chunks_a = _lane_fixture(seed=17, chunk_sizes=(4, 5, 4))
    mb, base_b, chunks_b = _lane_fixture(seed=18, chunk_sizes=(5, 4, 5),
                                         psr="TSTR1")
    ds = DeltaStore(tmp_path)
    sr = StreamingRefitter(deltas=ds)
    sr.register(ma, base_a)
    sr.register(mb, base_b)
    lane_a, lane_b = sr.lane(ma), sr.lane(mb)

    errors = []

    def worker(model, chunks, tag):
        try:
            for i, c in enumerate(chunks):
                out = sr.append(model, c, rid=f"{tag}-{i}")
                assert np.all(np.isfinite(out["x"]))
        except Exception as exc:  # pragma: no cover - surfaced below
            errors.append(exc)

    ref_violations, lane_violations = [], []
    specs = [(sr, "StreamingRefitter._lock"),
             (lane_a, "StreamingLane._lock"),
             (lane_b, "StreamingLane._lock"),
             (ds, "DeltaStore._lock")]
    with instrument(StreamingRefitter, ref_violations,
                    dict_attrs=("lanes",), instances=(sr,)):
        with instrument(StreamingLane, lane_violations,
                        instances=(lane_a, lane_b)):
            with record_order(*specs) as rec:
                threads = [
                    threading.Thread(target=worker,
                                     args=(ma, chunks_a, "a")),
                    threading.Thread(target=worker,
                                     args=(mb, chunks_b, "b")),
                ]
                for th in threads:
                    th.start()
                for th in threads:
                    th.join()

    assert not errors, errors
    assert_no_violations(ref_violations)
    assert_no_violations(lane_violations)
    assert sr.counters()["appends"] == len(chunks_a) + len(chunks_b)
    assert sr.counters()["escalated"] == 0

    runtime = rec.edge_set()
    assert find_cycle(runtime) is None
    # the refitter lock is never held across per-lane work — the old
    # global-serialization edge must not reappear
    assert ("StreamingRefitter._lock",
            "StreamingLane._lock") not in runtime
    # per-lane work publishes its delta segment while holding the lane
    # lock: the one-way ordering the registry documents
    assert ("StreamingLane._lock", "DeltaStore._lock") in runtime


# -- serve engine integration ------------------------------------------


def test_engine_append_requests_end_to_end(tmp_path):
    from pint_tpu.serve import AppendToasRequest, ServeEngine

    model, base, chunks = _lane_fixture(seed=10)
    eng = ServeEngine(durable_dir=os.fspath(tmp_path))
    eng.register_append_lane(model, base)
    for i, c in enumerate(chunks):
        res = eng.submit(AppendToasRequest(model, c))
        assert res.status == "ok"
        assert res.telemetry["kind"] == "append"
        assert np.all(np.isfinite(res.value["x"]))
    snap = eng.snapshot()
    assert snap["counters"].get("appends") == len(chunks)
    # second engine over the same durable dir: nothing pending (all
    # committed), chain replays into the re-registered lane
    eng.journal.close()
    eng2 = ServeEngine(durable_dir=os.fspath(tmp_path))
    model2, base2, _ = _lane_fixture(seed=10)
    eng2.register_append_lane(model2, base2)
    rep = eng2.recover()
    assert rep["n_replayed"] == 0
    assert eng2.streaming.counters()["replayed"] == len(chunks)
    assert eng2.deltas.scan()["corrupt_or_stale"] == 0
    eng2.journal.close()


def test_engine_append_unregistered_lane_rejected(tmp_path):
    from pint_tpu.serve import AppendToasRequest, ServeEngine

    model, base, chunks = _lane_fixture(seed=12, chunk_sizes=(4,))
    eng = ServeEngine(durable_dir=os.fspath(tmp_path))
    res = eng.submit(AppendToasRequest(model, chunks[0]))
    assert res.status == "rejected"
    assert res.reason == "lane_unregistered"
    eng.journal.close()


def test_engine_recovers_pending_append_exactly_once(tmp_path):
    """A journaled-but-uncommitted append (the crash window between
    intake sync and commit) must replay on recover() and land the
    same chain the live path would have."""
    from pint_tpu.serve import AppendToasRequest, ServeEngine

    model, base, chunks = _lane_fixture(seed=13, chunk_sizes=(5, 5))
    eng = ServeEngine(durable_dir=os.fspath(tmp_path))
    eng.register_append_lane(model, base)
    live = eng.submit(AppendToasRequest(model, chunks[0]))
    assert live.status == "ok"
    # simulate the crash: journal the second append's intake without
    # executing it, as the dead process's WAL would have
    pending = AppendToasRequest(model, chunks[1])
    eng.journal.record_intake(pending)
    eng.journal.sync()
    eng.journal.close()

    model2, base2, _ = _lane_fixture(seed=13, chunk_sizes=(5, 5))
    eng2 = ServeEngine(durable_dir=os.fspath(tmp_path))
    eng2.register_append_lane(model2, base2)
    rep = eng2.recover()
    assert rep["n_replayed"] == 1
    (rid, res), = rep["replayed"].items()
    assert rid == pending.request_id and res.status == "ok"
    assert eng2.deltas.scan() ["valid"] == 2
    # idempotent: a second recover finds everything committed
    rep2 = eng2.recover()
    assert rep2["n_replayed"] == 0
    eng2.journal.close()


# -- GW lattice incremental consumer -----------------------------------


def test_regrid_append_bitwise_vs_full_regrid():
    from pint_tpu.gw.residuals import GWInputs, regrid, regrid_append

    rng = np.random.default_rng(14)
    labels = ["A", "B"]
    pos = np.eye(3)[:2]
    times = [np.sort(rng.uniform(54000, 55000, 40)) for _ in range(2)]
    resid = [rng.standard_normal(40) * 1e-7 for _ in range(2)]
    weights = [rng.uniform(1e12, 2e12, 40) for _ in range(2)]
    base = regrid(GWInputs(labels, pos, times, resid, weights),
                  lattice_days=30.0)
    # appended epochs past the window: the lattice must GROW
    t_new = np.sort(rng.uniform(55000, 55400, 12))
    r_new = rng.standard_normal(12) * 1e-7
    w_new = rng.uniform(1e12, 2e12, 12)
    grown = regrid_append(base, "B", t_new, r_new, w_new)
    assert grown.n_cells > base.n_cells

    full = regrid(GWInputs(
        labels, pos,
        [times[0], np.concatenate([times[1], t_new])],
        [resid[0], np.concatenate([resid[1], r_new])],
        [weights[0], np.concatenate([weights[1], w_new])]),
        lattice_days=30.0,
        t0=float(base.t_cells[0] - 15.0),
        t1=float(grown.t_cells[-1] - 15.0))
    assert np.array_equal(grown.w, full.w)
    assert np.array_equal(grown.u, full.u)
    assert np.array_equal(grown.z, full.z)

    with pytest.raises(ValueError, match="forward in time"):
        regrid_append(base, "A", [53000.0], [0.0], [1.0])
    with pytest.raises(KeyError):
        regrid_append(base, "NOPE", [], [], [])
