"""Unified observability layer (pint_tpu.obs): tracer core + thread
semantics, nearest-rank percentile byte-compat, registry absorb,
Prometheus / Chrome trace-event golden formats, the flight recorder's
auto-dump on injected device loss and breaker trips, trace-id
threading through retries, and the two product contracts — a traced
fleet fit is bitwise identical to an untraced one, and the disabled
span path is a sub-percent tax on a warm fit."""

import json
import os
import threading

import numpy as np
import pytest

from pint_tpu import obs
from pint_tpu.models import get_model
from pint_tpu.obs import clock as obs_clock
from pint_tpu.obs import recorder as obs_recorder
from pint_tpu.obs import trace as obs_trace
from pint_tpu.obs.export import chrome_trace, flight_spans
from pint_tpu.obs.metricsreg import (Registry, percentile, prom_name,
                                     prometheus_text, summary)
from pint_tpu.resilience import FaultPoint, inject
from pint_tpu.simulation import make_fake_toas_fromMJDs


@pytest.fixture(autouse=True)
def _clean_obs():
    """Every test starts and ends with tracing off, empty rings, and
    no dump directory (module-global tracer/recorder state)."""
    obs.disable()
    obs.reset()
    obs_recorder.RECORDER.reset()
    obs_recorder.RECORDER.dump_dir = None
    yield
    obs.disable()
    obs.reset()
    obs_recorder.RECORDER.reset()
    obs_recorder.RECORDER.dump_dir = None


# -- tracer core -----------------------------------------------------


def test_disabled_span_is_shared_noop():
    assert not obs.enabled()
    sp = obs_trace.span("anything", key=("won't", "be", "seen"))
    assert sp is obs_trace.NOOP_SPAN
    with sp as inner:
        assert inner is obs_trace.NOOP_SPAN
        inner.set(extra=1)  # no-op, no error
    assert obs.spans() == []
    assert obs_trace.current_trace_id() is None


def test_span_nesting_parent_child_and_trace():
    obs.enable()
    with obs_trace.span("root") as r:
        with obs_trace.span("child") as c:
            assert c.trace_id == r.trace_id
            assert c.parent_id == r.span_id
            assert obs_trace.current_trace_id() == r.trace_id
    recs = {s["name"]: s for s in obs.spans()}
    assert recs["child"]["parent"] == recs["root"]["span"]
    assert recs["child"]["trace"] == recs["root"]["trace"]
    assert recs["child"]["t1"] >= recs["child"]["t0"]
    assert recs["root"]["status"] == "ok"


def test_span_error_status_and_attr():
    obs.enable()
    with pytest.raises(ValueError):
        with obs_trace.span("boom"):
            raise ValueError("nope")
    (rec,) = obs.spans()
    assert rec["status"] == "error"
    assert rec["attrs"]["error"] == "ValueError"


def test_cross_thread_trace_adoption():
    obs.enable()
    seen = {}

    def worker(tid):
        with obs_trace.span("worker", trace_id=tid):
            seen["tid"] = obs_trace.current_trace_id()

    with obs_trace.span("root") as r:
        th = threading.Thread(target=worker, args=(r.trace_id,))
        th.start()
        th.join()
    assert seen["tid"] == r.trace_id
    recs = {s["name"]: s for s in obs.spans()}
    assert recs["worker"]["trace"] == recs["root"]["trace"]
    # a worker WITHOUT the explicit id starts a fresh trace
    th2 = threading.Thread(target=lambda: worker(None))
    th2.start()
    th2.join()
    assert seen["tid"] != recs["root"]["trace"]


def test_ring_capacity_bounds_spans():
    obs.enable(capacity=4)
    for i in range(10):
        with obs_trace.span("s%d" % i):
            pass
    names = [s["name"] for s in obs.spans()]
    assert names == ["s6", "s7", "s8", "s9"]
    obs.enable(capacity=8192)  # restore the default ring for peers


# -- percentile / summary byte-compat --------------------------------


def _nearest_rank_reference(values, q):
    """The exact expression serve/metrics.py shipped before the obs
    unification — the contract the shared helper must preserve."""
    if not values:
        return None
    v = sorted(values)
    idx = min(len(v) - 1, max(0, -(-int(q) * len(v) // 100) - 1))
    return v[idx]


def test_percentile_matches_old_serve_implementation():
    rng = np.random.default_rng(7)
    for n in (1, 2, 3, 7, 50, 100, 101):
        vals = [float(x) for x in rng.uniform(0, 10, n)]
        for q in (0, 1, 50, 90, 99, 100):
            assert percentile(vals, q) == \
                _nearest_rank_reference(vals, q)
    assert percentile([], 50) is None


def test_serve_metrics_percentile_is_the_shared_helper():
    from pint_tpu.serve import metrics as serve_metrics

    assert serve_metrics.percentile is percentile


def test_summary_shape():
    s = summary([3.0, 1.0, 2.0])
    assert s["count"] == 3 and s["min"] == 1.0 and s["max"] == 3.0
    assert s["p50"] == 2.0 and s["p99"] == 3.0
    empty = summary([])
    assert empty["count"] == 0 and empty["p50"] is None


# -- metrics registry ------------------------------------------------


def test_registry_absorb_types_and_snapshot():
    reg = Registry()
    reg.absorb({"requests": 12, "hit_rate": 0.75, "alive": True,
                "lat_s": [0.1, 0.2, 0.3],
                "cache": {"hits": 9, "misses": 3}}, prefix="serve.")
    snap = reg.snapshot()
    assert snap["counters"]["serve.requests"] == 12
    assert snap["counters"]["serve.cache.hits"] == 9
    assert snap["gauges"]["serve.hit_rate"] == 0.75
    assert snap["gauges"]["serve.alive"] == 1
    assert snap["histograms"]["serve.lat_s"]["count"] == 3
    assert snap["histograms"]["serve.lat_s"]["p50"] == 0.2
    json.loads(reg.to_json())  # snapshot is JSON-clean


def test_serve_telemetry_exports_to_registry():
    from pint_tpu.serve.metrics import ServeTelemetry

    tel = ServeTelemetry()
    tel.incr("flushes", 3)
    tel.record(status="ok", total_s=0.05, queue_wait_s=0.01,
               pack_s=0.01, compile_s=None, execute_s=0.03)
    reg = Registry()
    tel.export_to_registry(registry=reg)
    snap = reg.snapshot()
    assert snap["counters"]["serve.counters.flushes"] == 3
    assert snap["counters"]["serve.requests"] == 1


def test_prometheus_text_golden_format():
    reg = Registry()
    reg.counter("serve.requests").inc(5)
    reg.gauge("mesh.alive lanes").set(None)
    h = reg.histogram("serve.total_s")
    for v in (0.1, 0.2, 0.4):
        h.record(v)
    text = prometheus_text(registry=reg)
    lines = text.splitlines()
    assert "# TYPE pint_tpu_serve_requests counter" in lines
    assert "pint_tpu_serve_requests 5" in lines
    # name sanitization + None -> NaN
    assert "# TYPE pint_tpu_mesh_alive_lanes gauge" in lines
    assert "pint_tpu_mesh_alive_lanes NaN" in lines
    assert "# TYPE pint_tpu_serve_total_s summary" in lines
    assert 'pint_tpu_serve_total_s{quantile="0.50"} 0.2' in lines
    assert "pint_tpu_serve_total_s_count 3" in lines
    assert text.endswith("\n")
    assert prom_name("a.b-c d") == "pint_tpu_a_b_c_d"


# -- chrome trace exporter -------------------------------------------


def test_chrome_trace_golden_format(tmp_path):
    obs.enable()
    with obs_trace.span("fleet.fit", n_psr=2):
        with obs_trace.span("fleet.pack", bucket=("k", 256)):
            pass
    doc = chrome_trace(obs.spans())
    assert doc["displayTimeUnit"] == "ms"
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    ms = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {e["name"] for e in xs} == {"fleet.fit", "fleet.pack"}
    assert any(e["name"] == "process_name" for e in ms)
    assert any(e["name"] == "thread_name" for e in ms)
    for e in xs:
        assert e["pid"] == 1 and e["ts"] >= 0 and e["dur"] >= 0
    pack = next(e for e in xs if e["name"] == "fleet.pack")
    assert pack["args"]["parent"] is not None
    # the file round-trip must survive tuple-valued attrs (raw site
    # values, stringified only at export)
    path = obs.write_chrome_trace(str(tmp_path / "trace.json"))
    loaded = json.load(open(path))
    assert loaded["traceEvents"]


# -- flight recorder -------------------------------------------------


def test_fault_firings_land_in_flight_ring():
    from pint_tpu.resilience import faultinject

    with inject(FaultPoint("toa_nan", rate=1.0)):
        assert faultinject.fire("toa_nan", request=3) is not None
    faults = [e for e in obs_recorder.RECORDER.events()
              if e["kind"] == "fault"]
    assert faults and faults[-1]["point"] == "toa_nan"
    assert faults[-1]["ctx"]["request"] == 3


def test_dump_noop_without_dir_but_event_noted():
    path = obs_recorder.RECORDER.dump("breaker_trip", key="k")
    assert path is None
    evs = [e for e in obs_recorder.RECORDER.events()
           if e["kind"] == "event" and e["what"] == "dump"]
    assert evs and evs[-1]["reason"] == "breaker_trip"


def test_breaker_trip_writes_flight_dump(tmp_path):
    from pint_tpu.resilience.retry import CircuitBreaker

    obs_recorder.configure(dump_dir=str(tmp_path))
    br = CircuitBreaker(threshold=1, cooldown_s=10.0)
    assert br.record_failure(("slot", 256)) is True
    dumps = obs_recorder.RECORDER.dumps
    assert len(dumps) == 1 and "breaker_trip" in dumps[0]
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "breaker_trip"
    assert doc["context"]["key"] == "('slot', 256)"
    assert doc["context"]["why"] == "failure_streak"


def test_flight_dump_contains_recent_spans_and_roundtrips(tmp_path):
    obs.enable()
    obs_recorder.configure(dump_dir=str(tmp_path))
    with obs_trace.span("serve.flush", slot=("a", 1)):
        pass
    path = obs_recorder.RECORDER.dump("device_lost", lane=2)
    doc = json.load(open(path))
    spans = flight_spans(doc)
    assert [s["name"] for s in spans] == ["serve.flush"]
    assert chrome_trace(spans)["traceEvents"]  # converter accepts it


# -- trace-id threading through retries ------------------------------


def test_with_retries_joins_callers_trace():
    from pint_tpu.resilience.retry import BackoffPolicy, with_retries

    obs.enable()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise TimeoutError("transient")
        return "ok"

    with obs_trace.span("serve.flush") as root:
        out = with_retries(flaky, BackoffPolicy(max_attempts=3, seed=1),
                           sleep=lambda s: None,
                           trace_id=obs_trace.current_trace_id())
    assert out == "ok"
    attempts = [s for s in obs.spans() if s["name"] == "retry.attempt"]
    assert [a["attrs"]["attempt"] for a in attempts] == [0, 1]
    assert {a["trace"] for a in attempts} == {root.trace_id}
    assert attempts[0]["status"] == "error"
    assert attempts[1]["status"] == "ok"


# -- product contracts on a real fleet -------------------------------


def _tiny_fleet_pulsars():
    """2 structures (spin-only -> WLS, EFAC/EQUAD/ECORR -> GLS)."""
    rng = np.random.default_rng(0)
    models, toas_list = [], []
    for i in range(2):
        par = (f"PSR OB{i}\nRAJ 1{i}:00:00.0\nDECJ {4 + i}:30:00.0\n"
               f"F0 {150 + 10 * i}.5 1\nF1 -{2 + i}e-16 1\n"
               f"PEPOCH 55500\nDM {9 + i}.5 1\n")
        m = get_model(par)
        mjds = np.sort(rng.uniform(55000, 56000, 24 + 4 * i))
        toas_list.append(make_fake_toas_fromMJDs(
            mjds, m, error_us=1.0, freq_mhz=1400.0, obs="gbt",
            add_noise=True, seed=i))
        models.append(m)
    for i in range(2):
        par = (f"PSR OBN{i}\nRAJ 0{2 * i}:30:00.0\n"
               f"DECJ {7 + i}:00:00.0\n"
               f"F0 {310 + 4 * i}.25 1\nF1 -{2 + i}e-16 1\n"
               f"PEPOCH 55500\nDM {12 + i}.3 1\n"
               "EFAC -f L-wide 1.2\nEQUAD -f L-wide 0.5\n"
               "ECORR -f L-wide 0.9\n")
        m = get_model(par)
        epoch_days = np.linspace(55000, 56000, 10 + 2 * i)
        mjds = np.concatenate(
            [d + np.arange(3) * 0.5 / 86400.0 for d in epoch_days])
        t = make_fake_toas_fromMJDs(
            mjds, m, error_us=1.0, freq_mhz=np.full(len(mjds), 1400.0),
            obs="gbt", add_noise=True, seed=100 + i)
        for f in t.flags:
            f["f"] = "L-wide"
        models.append(m)
        toas_list.append(t)
    return models, toas_list


@pytest.fixture(scope="module")
def tiny_fleet():
    from pint_tpu.parallel import PTAFleet

    models, toas_list = _tiny_fleet_pulsars()
    fleet = PTAFleet(models, toas_list, pipeline=True)
    fleet.fit(method="auto", maxiter=2)  # compile + warm
    return fleet


def test_traced_fleet_fit_bitwise_equal_and_phases(tiny_fleet):
    x0, c0, v0 = tiny_fleet.fit(method="auto", maxiter=2)
    obs.enable()
    try:
        x1, c1, v1 = tiny_fleet.fit(method="auto", maxiter=2)
    finally:
        obs.disable()
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    for a, b in zip(x0, x1):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(v0, v1):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    names = {s["name"] for s in obs.spans()}
    # warm fit: dispatch + execute per bucket under one fleet.fit root
    assert {"fleet.fit", "fleet.dispatch", "fleet.execute"} <= names
    fits = [s for s in obs.spans() if s["name"] == "fleet.fit"]
    execs = [s for s in obs.spans() if s["name"] == "fleet.execute"]
    assert len(execs) == len(tiny_fleet.group_indices)
    assert {e["trace"] for e in execs} == {fits[0]["trace"]}


def test_cold_traced_fleet_covers_all_phases(tmp_path):
    from pint_tpu.parallel import PTAFleet

    models, toas_list = _tiny_fleet_pulsars()
    obs.enable()
    try:
        fleet = PTAFleet(models, toas_list, pipeline=True)
        fleet.fit(method="auto", maxiter=2)
    finally:
        obs.disable()
    names = {s["name"] for s in obs.spans()}
    assert {"fleet.host_prep", "fleet.pack", "fleet.compile",
            "fleet.dispatch", "fleet.execute", "fleet.fit",
            "aot.trace", "aot.backend_compile"} <= names
    # the exported timeline is valid Chrome trace-event JSON with one
    # row per participating thread (prep pool, compile pool, caller)
    path = obs.write_chrome_trace(str(tmp_path / "fleet.json"))
    doc = json.load(open(path))
    threads = {e["tid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert len(threads) >= 2


def test_disabled_span_overhead_under_one_percent(tiny_fleet):
    """The disabled-path contract: span() call sites cost so little
    that the spans a warm fleet fit would emit amount to < 1% of the
    fit wall. Measured as (per-call disabled span cost) x (spans one
    traced fit emits) vs the untraced fit wall — the product form is
    robust to CI timer jitter where diffing two fit walls is not."""
    assert not obs.enabled()
    n_calls = 20000
    t0 = obs_clock.now()
    for _ in range(n_calls):
        with obs_trace.span("x", a=1):
            pass
    per_call = (obs_clock.now() - t0) / n_calls

    fit_s = float("inf")
    for _ in range(2):
        t0 = obs_clock.now()
        tiny_fleet.fit(method="auto", maxiter=2)
        fit_s = min(fit_s, obs_clock.now() - t0)

    obs.reset()
    obs.enable()
    try:
        tiny_fleet.fit(method="auto", maxiter=2)
        spans_per_fit = len(obs.spans())
    finally:
        obs.disable()
    assert spans_per_fit > 0
    overhead = per_call * spans_per_fit
    assert overhead < 0.01 * fit_s, (per_call, spans_per_fit, fit_s)


def test_fleetmesh_device_loss_writes_flight_dump(tmp_path,
                                                  device_mesh):
    """The acceptance artifact: an injected device_loss chaos run
    leaves a flight dump naming the lost lane, the fault point, and
    the re-sharded buckets, with the fault firing in the ring."""
    from pint_tpu.parallel import FleetMesh

    obs_recorder.configure(dump_dir=str(tmp_path))
    obs.enable()
    try:
        models, toas_list = _tiny_fleet_pulsars()
        fm = FleetMesh(models, toas_list, collective_timeout_s=None)
        with inject(FaultPoint("device_loss", rate=1.0,
                               payload={"lane": 0})):
            fm.fit(method="auto", maxiter=2)
    finally:
        obs.disable()
    dumps = [p for p in obs_recorder.RECORDER.dumps
             if "device_lost" in p]
    assert dumps, obs_recorder.RECORDER.dumps
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "device_lost"
    ctx = doc["context"]
    assert ctx["source"] == "fleetmesh"
    assert ctx["lane"] == 0
    assert ctx["fault_point"] == "device_loss"
    assert ctx["resharded"], ctx  # the stolen buckets are named
    kinds = {e["kind"] for e in doc["events"]}
    assert "fault" in kinds      # the injected firing itself
    assert "event" in kinds      # the work_steal ledger entries
    steals = [e for e in doc["events"]
              if e["kind"] == "event" and e.get("what") == "work_steal"]
    assert steals and steals[0]["from_lane"] == 0
