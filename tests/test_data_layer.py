"""Data-layer tests: tim parsing, observatories, ephemeris sanity.

(reference test patterns: tests/test_toa_reader.py, tests/test_observatory.py,
tests/test_ephemeris.py equivalents.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.mjd import Epochs
from pint_tpu.toa import read_tim_file, TOAs, get_TOAs
from pint_tpu.observatory import get_observatory, list_observatories
from pint_tpu.ephemeris import objPosVel_wrt_SSB


def test_tim_tempo2_format(tmp_path):
    p = tmp_path / "t.tim"
    p.write_text(
        "FORMAT 1\n"
        "# a comment\n"
        "fake 1440.0 55000.1234567890123 1.5 gbt -f L-wide -be GUPPI\n"
        "fake 430.0 55001.5 2.5 ao\n"
    )
    toas, commands = read_tim_file(str(p))
    assert len(toas) == 2
    assert toas[0].freq_mhz == 1440.0
    assert toas[0].obs == "gbt"
    assert toas[0].flags["f"] == "L-wide"
    assert toas[0].flags["be"] == "GUPPI"
    assert toas[1].error_us == 2.5
    assert toas[1].day == 55001 and toas[1].sec == pytest.approx(43200.0)


def test_tim_commands(tmp_path):
    p = tmp_path / "t.tim"
    p.write_text(
        "FORMAT 1\n"
        "EFAC 2.0\n"
        "a 1440.0 55000.5 1.0 gbt\n"
        "EFAC 1.0\n"
        "SKIP\n"
        "b 1440.0 55001.5 1.0 gbt\n"
        "NOSKIP\n"
        "TIME 0.5\n"
        "cc 1440.0 55002.5 1.0 gbt\n"
    )
    toas, _ = read_tim_file(str(p))
    assert len(toas) == 2
    assert toas[0].error_us == 2.0
    assert toas[1].sec == pytest.approx(43200.5)


def test_tim_include(tmp_path):
    inc = tmp_path / "inc.tim"
    inc.write_text("FORMAT 1\nx 1440.0 55003.5 1.0 gbt\n")
    p = tmp_path / "t.tim"
    p.write_text(f"FORMAT 1\nINCLUDE {inc.name}\ny 1440.0 55004.5 1.0 gbt\n")
    toas, _ = read_tim_file(str(p))
    assert len(toas) == 2


def test_observatory_registry():
    gbt = get_observatory("gbt")
    assert np.linalg.norm(gbt.itrf_xyz) == pytest.approx(6.37e6, rel=0.01)
    assert get_observatory("1") is gbt  # tempo code alias
    assert get_observatory("GBT") is gbt
    bat = get_observatory("@")
    assert bat.timescale == "tdb"
    assert "parkes" in list_observatories()
    with pytest.raises(KeyError):
        get_observatory("not-a-telescope")


def test_barycentered_toas_skip_clock_and_geometry():
    from pint_tpu.toa import TOA

    t = TOAs([TOA(55000, 43200.0, obs="barycenter")])
    t.apply_clock_corrections()
    t.compute_TDBs()
    # barycenter TOAs are already TDB: day/sec unchanged
    assert t.tdb.day[0] == 55000
    assert t.tdb.sec[0] == pytest.approx(43200.0)
    t.compute_posvels()
    assert np.allclose(t.ssb_obs.pos, 0.0)


def test_ephemeris_earth_orbit():
    t = Epochs(np.arange(54000, 54370, 10), np.zeros(37), "tdb")
    e = objPosVel_wrt_SSB("earth", t)
    s = objPosVel_wrt_SSB("sun", t)
    d_au = np.linalg.norm(e.pos - s.pos, axis=1) / 1.495978707e11
    assert 0.975 < d_au.min() < 0.985
    assert 1.013 < d_au.max() < 1.022
    speed = np.linalg.norm(e.vel, axis=1)
    assert 2.88e4 < speed.min() and speed.max() < 3.06e4


def test_observatory_diurnal_motion():
    from pint_tpu.earth import gcrs_posvel_from_itrf

    gbt = get_observatory("gbt")
    utc = Epochs(np.full(25, 55000), np.linspace(0, 86400, 25), "utc")
    pos, vel = gcrs_posvel_from_itrf(gbt.itrf_xyz, utc)
    # one sidereal-ish rotation: start/end nearly aligned
    assert np.linalg.norm(pos[0] - pos[-1]) < 1.2e5  # ~4 min sidereal lag
    assert np.abs(np.linalg.norm(pos, axis=1) - 6.37e6).max() < 2e4
    v = np.linalg.norm(vel, axis=1)
    assert np.allclose(v, v[0], rtol=3e-3)


def test_toas_summary_and_select(tmp_path):
    p = tmp_path / "t.tim"
    p.write_text(
        "FORMAT 1\n"
        "a 1440.0 55000.5 1.0 gbt\n"
        "b 430.0 55001.5 2.0 ao\n"
        "cc 1440.0 55002.5 1.5 gbt\n"
    )
    t = get_TOAs(str(p))
    s = t.get_summary()
    assert "Number of TOAs: 3" in s
    sub = t.mask(t.freq_mhz > 1000)
    assert len(sub) == 2
    assert all(o == "gbt" for o in sub.obs)


def test_parkes_tim_format():
    """Parkes/Jodrell fixed-column tim format round-trips through the
    parser (reference: toa.py parkes branch)."""
    import os
    import tempfile

    from pint_tpu.toa import read_tim_file

    def parkes_line(freq, mjd_str, err, obs_code, phase_off="0.0"):
        line = " NAME" + " " * 20
        line = line[:25] + f"{freq:9.4f}" + f" {mjd_str:<20}"
        line = line + f"{phase_off:>8}" + f"{err:8.3f}" + " " * 8 + obs_code
        return line

    with tempfile.NamedTemporaryFile("w", suffix=".tim", delete=False) as f:
        f.write(parkes_line(1400.0, "55000.123456789012", 2.5, "7") + "\n")
        f.write(parkes_line(3100.0, "55010.987654321098", 1.25, "7") + "\n")
        path = f.name
    try:
        toas, cmds = read_tim_file(path)
    finally:
        os.unlink(path)
    assert len(toas) == 2
    assert toas[0].obs == "7"
    assert toas[0].freq_mhz == 1400.0
    assert toas[0].error_us == 2.5
    assert toas[0].day == 55000
    assert toas[0].sec == pytest.approx(0.123456789012 * 86400.0, abs=1e-6)
    assert toas[1].error_us == 1.25


def test_emin_emax_commands():
    """EMIN/EMAX drop TOAs outside the error window
    (reference: toa.py EMIN/EMAX handling)."""
    import os
    import tempfile

    from pint_tpu.toa import read_tim_file

    body = (
        "FORMAT 1\n"
        "a 1400.0 55000.1 0.5 gbt\n"
        "b 1400.0 55001.1 2.0 gbt\n"
        "EMIN 1.0\n"
        "c 1400.0 55002.1 0.5 gbt\n"   # dropped: err < 1.0
        "d 1400.0 55003.1 2.0 gbt\n"
        "EMAX 3.0\n"
        "e 1400.0 55004.1 5.0 gbt\n"   # dropped: err > 3.0
        "f 1400.0 55005.1 2.5 gbt\n"
        "EMIN 0\nEMAX 0\n"
        "g 1400.0 55006.1 9.0 gbt\n"   # window reset
    )
    with tempfile.NamedTemporaryFile("w", suffix=".tim", delete=False) as f:
        f.write(body)
        path = f.name
    try:
        toas, _ = read_tim_file(path)
    finally:
        os.unlink(path)
    names = [t.flags["name"] for t in toas]
    assert names == ["a", "b", "d", "f", "g"]


def test_bare_dmx_line_recognized():
    """A bare 'DMX <value>' par line (legacy bin-width marker) must not
    produce an unrecognized-line warning."""
    import warnings as w

    from pint_tpu.models import get_model

    par = ("PSR TDX\nRAJ 10:00:00.0\nDECJ 10:00:00.0\nF0 100.0 1\n"
           "PEPOCH 55000\nDM 20.0 1\nDMX 6.5\n"
           "DMX_0001 1e-4 1\nDMXR1_0001 55000\nDMXR2_0001 55100\n")
    with w.catch_warnings(record=True) as rec:
        w.simplefilter("always")
        m = get_model(par)
    assert not any("unrecognized" in str(r.message) for r in rec)
    assert m.DMX.value == pytest.approx(6.5)
    assert m.unrecognized == {}


def test_expanded_observatory_registry():
    """~40 ground sites with tempo site-code aliases resolve."""
    from pint_tpu.observatory import get_observatory, list_observatories

    names = list_observatories()
    assert len(names) >= 40
    for alias, expect in [("mwa", "mwa"), ("most", "most"),
                          ("jbmk2", "jodrell_mk2"), ("h1", "lho"),
                          ("tm65", "tianma65"), ("o8", "onsala"),
                          ("tr", "torun"), ("pks", "parkes"),
                          ("aro", "algonquin"), ("mc", "medicina")]:
        o = get_observatory(alias)
        assert o.name == expect, (alias, o.name)
        assert np.linalg.norm(o.itrf_xyz) > 6.3e6  # on the Earth


def test_get_toas_honors_model_clock_directive():
    """The par CLOCK line picks the BIPM realization
    (reference: get_TOAs model plumbing)."""
    from pint_tpu.models import get_model
    from pint_tpu.toa import get_TOAs

    base = ("PSR TCLK\nRAJ 01:00:00\nDECJ 01:00:00\nF0 100\nPEPOCH 55000\n"
            "DM 1\n")
    tim = "FORMAT 1\na 1400.0 55000.5 1.0 gbt\n"
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        timf = os.path.join(d, "t.tim")
        open(timf, "w").write(tim)
        m1 = get_model(base + "CLOCK TT(BIPM2015)\n")
        t1 = get_TOAs(timf, model=m1)
        assert t1.include_bipm and t1.bipm_version == "BIPM2015"
        m2 = get_model(base + "CLOCK TT(TAI)\n")
        t2 = get_TOAs(timf, model=m2)
        assert not t2.include_bipm
        m3 = get_model(base)  # no CLOCK line: defaults hold
        t3 = get_TOAs(timf, model=m3)
        assert t3.include_bipm and t3.bipm_version == "BIPM2019"
        m4 = get_model(base + "CLOCK UNCORR\n")
        t4 = get_TOAs(timf, model=m4)
        assert not t4.include_bipm and not t4.include_gps
        assert not t4.include_site_clock
        assert np.all(t4.clock_corr_s == 0.0)  # truly raw TOAs
        import pytest, warnings as w
        m5 = get_model(base + "CLOCK TT(PTB)\n")
        with pytest.warns(UserWarning, match="unrecognized CLOCK"):
            get_TOAs(timf, model=m5)


def test_tim_jump_blocks_become_params(tmp_path):
    """Each tim JUMP...JUMP block gets a distinct flag and converts to
    its own fittable JUMP parameter (reference: tim JUMP command ->
    -tim_jump flags -> PhaseJump params)."""
    from pint_tpu.models import get_model
    from pint_tpu.models.jump import jump_flags_to_params
    from pint_tpu.toa import get_TOAs

    tim = ("FORMAT 1\n"
           "t1 1400.0 55000.5 1.0 gbt\n"
           "JUMP\n"
           "t2 1400.0 55001.5 1.0 gbt\n"
           "t3 1400.0 55002.5 1.0 gbt\n"
           "JUMP\n"
           "t4 1400.0 55003.5 1.0 gbt\n"
           "JUMP\n"
           "t5 1400.0 55004.5 1.0 gbt\n"
           "JUMP\n")
    p = tmp_path / "j.tim"
    p.write_text(tim)
    t = get_TOAs(str(p))
    tags = [f.get("tim_jump") for f in t.flags]
    assert tags == [None, "1", "1", None, "2"]
    m = get_model("PSR TJ\nRAJ 01:00:00\nDECJ 01:00:00\nF0 100 1\n"
                  "PEPOCH 55002\nDM 1\n")
    created = jump_flags_to_params(t, m)
    assert created == ["JUMP1", "JUMP2"]
    comp = m.components["PhaseJump"]
    assert getattr(m, "JUMP1").key == "-tim_jump"
    # masks select exactly the flagged groups
    m1 = getattr(m, "JUMP1").resolve_mask(t)
    m2 = getattr(m, "JUMP2").resolve_mask(t)
    assert list(m1) == [False, True, True, False, False]
    assert list(m2) == [False, False, False, False, True]
    # idempotent
    assert jump_flags_to_params(t, m) == []


def test_tim_command_state_shared_with_includes(tmp_path):
    """INCLUDE executes inline: TIME offsets and open JUMP blocks in
    the parent apply inside the include, and jump indices stay
    globally distinct (reference: read_toa_file shared command state)."""
    from pint_tpu.toa import read_tim_file

    (tmp_path / "child.tim").write_text(
        "t3 1400.0 55010.5 1.0 gbt\n"
        "JUMP\n"
        "t4 1400.0 55011.5 1.0 gbt\n"
        "JUMP\n")
    (tmp_path / "parent.tim").write_text(
        "FORMAT 1\n"
        "TIME 0.25\n"
        "JUMP\n"
        "t1 1400.0 55000.5 1.0 gbt\n"
        "JUMP\n"
        "INCLUDE child.tim\n"
        "t5 1400.0 55020.5 1.0 gbt\n")
    toas, cmds = read_tim_file(str(tmp_path / "parent.tim"))
    assert [t.flags["name"] for t in toas] == ["t1", "t3", "t4", "t5"]
    # TIME applies everywhere, including the included file
    assert all(abs(t.sec - 43200.25) < 1e-9 for t in toas)
    tags = [t.flags.get("tim_jump") for t in toas]
    # parent block -> "1"; child's own block -> "2"; others unjumped
    assert tags == ["1", None, "2", None]
    # FORMAT 1 carries into the child (it parsed as tempo2)
    assert toas[2].flags["name"] == "t4"


def test_toas_select_unselect_stack(tmp_path):
    """Stateful select/unselect with nesting (reference:
    toa.py::TOAs.select/unselect): each select subsets in place,
    each unselect restores the previous state exactly."""
    from pint_tpu.toa import TOAs

    t = TOAs.from_arrays(np.arange(55000, 55020), np.linspace(0, 600, 20),
                         error_us=1.0, freq_mhz=1400.0, obs="gbt")
    for i, f in enumerate(t.flags):
        f["grp"] = "A" if i < 12 else "B"
    n0 = len(t)
    t.select(np.array([f["grp"] == "A" for f in t.flags]))
    assert len(t) == 12
    t.select(t.get_mjds() < 55006)
    assert len(t) == 6
    # flag edits while selected must NOT leak into the restored state
    t.flags[0]["cut"] = "snr"
    t.unselect()
    assert len(t) == 12 and all(f["grp"] == "A" for f in t.flags)
    assert "cut" not in t.flags[0]
    t.unselect()
    assert len(t) == n0
    with pytest.raises(ValueError):
        t.unselect()
    # clock-chain configuration survives select (mask used to reset it)
    t.include_site_clock = False
    t.bipm_version = "BIPM2015"
    t.select(t.get_mjds() < 55010)
    assert t.include_site_clock is False and t.bipm_version == "BIPM2015"


def test_compute_pulse_numbers_roundtrip(tmp_path):
    """compute_pulse_numbers sets -pn flags that survive a tim write/
    reload and drive use_pulse_numbers tracking (reference:
    TOAs.compute_pulse_numbers + TRACK -2)."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_fromMJDs
    from pint_tpu.toa import get_TOAs

    m = get_model("PSR TPN\nRAJ 6:00:00\nDECJ 10:00:00\nF0 200.0 1\n"
                  "PEPOCH 55000\nDM 10.0\n")
    t = make_fake_toas_fromMJDs(np.linspace(54900, 55100, 25), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=8)
    pn = t.compute_pulse_numbers(m)
    assert np.isfinite(pn).all() and (pn == np.round(pn)).all()
    out = tmp_path / "pn.tim"
    t.write_TOA_file(str(out))
    t2 = get_TOAs(str(out), usepickle=False)
    np.testing.assert_array_equal(t2.get_pulse_numbers(), pn)
    # tracked residuals agree with nearest-integer residuals here (the
    # model is the one that defined the pulse numbers)
    r_track = np.asarray(Residuals(t2, m, track_mode="use_pulse_numbers",
                                   subtract_mean=False).calc_time_resids())
    r_near = np.asarray(Residuals(t2, m, track_mode="nearest",
                                  subtract_mean=False).calc_time_resids())
    np.testing.assert_allclose(r_track, r_near, atol=1e-12)
