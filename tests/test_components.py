"""Per-component unit tests for the breadth components.

(reference test patterns: tests/test_glitch.py, tests/test_wave.py,
tests/test_FD.py, tests/test_ifunc.py, tests/test_solar_wind.py,
tests/test_troposphere_delay.py — construct small inline-par models,
check delay/phase behavior and fit recovery, and cross-check the
jacfwd design matrix against numerical differentiation.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.fitter import WLSFitter
from pint_tpu.simulation import make_fake_toas_fromMJDs

BASE = """
PSR TESTC
RAJ 05:00:00.0
DECJ 15:00:00.0
F0 100.0 1
F1 -1e-14 1
PEPOCH 55500
DM 20.0
"""


def _toas(model, n=60, lo=55000, hi=56000, seed=1, **kw):
    rng = np.random.default_rng(seed)
    mjds = np.sort(rng.uniform(lo, hi, n))
    freqs = np.where(np.arange(n) % 2, 1400.0, 430.0)
    return make_fake_toas_fromMJDs(mjds, model, error_us=1.0, freq_mhz=freqs,
                                   obs="gbt", add_noise=False, **kw)


def test_glitch_phase_step():
    m = get_model(BASE + "GLEP_1 55500.5\nGLPH_1 0.3\n")
    assert "Glitch" in m.components
    toas = _toas(get_model(BASE))  # simulate from glitch-free model
    prep = m.prepare(toas)
    fn = prep.residual_vector_fn(subtract_mean=False)
    ph = np.asarray(fn(prep.vector_from_params())) * 100.0  # phase cycles
    mjds = toas.get_mjds()
    pre = ph[mjds < 55500.5]
    post = ph[mjds > 55500.5]
    assert np.all(np.abs(pre) < 1e-6)
    np.testing.assert_allclose(post, 0.3, atol=1e-6)


def test_glitch_fit_recovery():
    true = get_model(BASE + "GLEP_1 55400\nGLF0_1 3e-9\n")
    toas = _toas(true, n=120, seed=3)
    fit = get_model(BASE + "GLEP_1 55400\nGLF0_1 0 1\n")
    fit.free_params = ["GLF0_1"]
    f = WLSFitter(toas, fit)
    f.fit_toas()
    assert abs(f.model.GLF0_1.value - 3e-9) < 1e-11


def test_wave_roundtrip_and_recovery():
    par = BASE + "WAVEEPOCH 55500\nWAVE_OM 0.01\nWAVE1 1e-5 -2e-5\nWAVE2 5e-6 0\n"
    m = get_model(par)
    assert "Wave" in m.components
    assert m.WAVE1.value == (1e-5, -2e-5)
    toas = _toas(m)
    r = Residuals(toas, m)
    assert r.rms_weighted() < 1e-9  # simulation consistent with model
    # par round trip preserves wave terms
    m2 = get_model(m.as_parfile())
    assert m2.WAVE2.value[0] == pytest.approx(5e-6)


def test_wavex_delay():
    par = BASE + "WXEPOCH 55500\nWXFREQ_0001 0.005\nWXSIN_0001 1e-5 1\nWXCOS_0001 -3e-6 1\n"
    m = get_model(par)
    toas = _toas(get_model(BASE))
    prep = m.prepare(toas)
    d = np.asarray(prep.delay())
    base_prep = get_model(BASE).prepare(toas)
    d0 = np.asarray(base_prep.delay())
    extra = d - d0
    assert np.max(np.abs(extra)) > 1e-6
    assert np.max(np.abs(extra)) < 2e-5


def test_fd_delay_scales_with_logfreq():
    m = get_model(BASE + "FD1 1e-5 1\nFD2 -2e-6\n")
    toas = _toas(get_model(BASE))
    prep = m.prepare(toas)
    d = np.asarray(prep.delay()) - np.asarray(get_model(BASE).prepare(toas).delay())
    lf = np.log(np.asarray(prep.batch.freq_mhz) / 1000.0)
    expect = 1e-5 * lf - 2e-6 * lf**2
    np.testing.assert_allclose(d, expect, atol=1e-12)


def test_fd_fit_recovery():
    true = get_model(BASE + "FD1 2e-5\n")
    toas = _toas(true, n=100, seed=7)
    fit = get_model(BASE + "FD1 0 1\n")
    fit.free_params = ["FD1"]
    f = WLSFitter(toas, fit)
    f.fit_toas()
    assert abs(f.model.FD1.value - 2e-5) < 2e-6


def test_ifunc_linear_interp():
    par = BASE + ("SIFUNC 2\nIFUNC1 55000 0\nIFUNC2 55500 1e-4\n"
                  "IFUNC3 56000 0\n")
    m = get_model(par)
    toas = _toas(get_model(BASE))
    prep = m.prepare(toas)
    fn = prep.residual_vector_fn(subtract_mean=False)
    ph = np.asarray(fn(prep.vector_from_params())) * 100.0  # cycles
    mjds = toas.get_mjds()
    # tent profile peaking at F0 * 1e-4 = 1e-2 cycles at the central node
    expect = 1e-2 * np.clip(1.0 - np.abs(mjds - 55500) / 500.0, 0.0, None)
    np.testing.assert_allclose(ph, expect, atol=2e-4)


def test_phase_offset_is_offset_column():
    m = get_model(BASE + "PHOFF 0.1 1\n")
    assert "PhaseOffset" in m.components
    toas = _toas(get_model(BASE))
    prep = m.prepare(toas)
    M, labels = prep.designmatrix()
    # PHOFF free -> implicit Offset column dropped
    assert "Offset" not in labels
    assert "PHOFF" in labels


def test_solar_wind_elongation_dependence():
    m = get_model(BASE + "NE_SW 10.0\n")
    assert "SolarWindDispersion" in m.components
    toas = _toas(get_model(BASE), n=80, lo=55000, hi=55365)
    prep = m.prepare(toas)
    d = np.asarray(prep.delay()) - np.asarray(get_model(BASE).prepare(toas).delay())
    # solar wind delay is positive and varies over the year
    assert np.all(d > 0)
    assert d.max() / d.min() > 1.5


def test_solar_wind_fit_recovery():
    true = get_model(BASE + "NE_SW 8.0\n")
    toas = _toas(true, n=150, lo=55000, hi=55730, seed=5)
    fit = get_model(BASE + "NE_SW 0 1\n")
    fit.free_params = ["NE_SW", "F0", "F1"]
    f = WLSFitter(toas, fit)
    f.fit_toas(maxiter=3)
    assert abs(f.model.NE_SW.value - 8.0) < 0.5


def test_troposphere_delay_magnitude():
    m = get_model(BASE + "CORRECT_TROPOSPHERE Y\n")
    assert "TroposphereDelay" in m.components
    toas = _toas(get_model(BASE), n=50)
    prep = m.prepare(toas)
    d = np.asarray(prep.delay()) - np.asarray(get_model(BASE).prepare(toas).delay())
    # zenith hydrostatic ~7.7 ns; mapped delays larger, bounded by ~12x at 5 deg
    assert np.all(d > 5e-9)
    assert np.all(d < 2e-7)


def test_delay_jump():
    from pint_tpu.models.jump import DelayJump

    m = get_model(BASE)
    dj = DelayJump()
    m.add_component(dj)
    dj.add_jump(key="freq", key_value=("1000", "2000"), value=1e-5)
    toas = _toas(get_model(BASE))
    prep = m.prepare(toas)
    d = np.asarray(prep.delay()) - np.asarray(get_model(BASE).prepare(toas).delay())
    hi_freq = np.asarray(prep.batch.freq_mhz) > 1000
    np.testing.assert_allclose(d[hi_freq], 1e-5, atol=1e-15)
    np.testing.assert_allclose(d[~hi_freq], 0.0, atol=1e-15)


def test_design_matrix_matches_numeric():
    """jacfwd columns vs central differences for the new components
    (reference: d_phase_d_param_num cross-checks)."""
    par = BASE + "GLEP_1 55400\nGLF0_1 1e-8 1\nNE_SW 5 1\nFD1 1e-5 1\n"
    m = get_model(par)
    m.free_params = ["GLF0_1", "NE_SW", "FD1"]
    toas = _toas(get_model(BASE), n=40)
    prep = m.prepare(toas)
    M, labels = prep.designmatrix()
    fn, _ = prep.designmatrix_fn()
    x0 = np.asarray(prep.vector_from_params())
    phase_fn = lambda x: np.asarray(
        prep._jit("phasec_num", prep._phase_continuous)(prep.params_with_vector(x)))
    for j, name in enumerate(labels):
        if name == "Offset":
            continue
        h = max(abs(x0[labels.index(name) - 1]) * 1e-4, 1e-12)
        xp = x0.copy(); xp[labels.index(name) - 1] += h
        xm = x0.copy(); xm[labels.index(name) - 1] -= h
        num = (phase_fn(xp) - phase_fn(xm)) / (2 * h)
        col = np.asarray(M[:, j])
        scale = max(np.max(np.abs(num)), 1e-30)
        np.testing.assert_allclose(col / scale, num / scale, atol=5e-5)


def test_fdjump_masked_delay_both_conventions():
    """FD<n>JUMP adds value*log(nu/GHz)^n seconds only on mask-selected
    TOAs; FDJUMPLOG N switches to the linear tempo2 basis (reference:
    fdjump.py::FDJump). Both FD1JUMP and FDJUMP1 spellings parse."""
    toas = _toas(get_model(BASE))
    freqs = np.asarray(toas.freq_mhz)
    sel = (freqs >= 1000) & (freqs <= 1500)
    lf = np.log(freqs / 1000.0)

    m = get_model(BASE + "FD1JUMP freq 1000 1500 3e-5 1\n"
                  "FDJUMP2 freq 1000 1500 -1e-5\n")
    assert "FDJump" in m.components
    assert m.FDJUMPLOG.value is True
    d = (np.asarray(m.prepare(toas).delay())
         - np.asarray(get_model(BASE).prepare(toas).delay()))
    expect = sel * (3e-5 * lf - 1e-5 * lf**2)
    np.testing.assert_allclose(d, expect, atol=1e-14)

    m2 = get_model(BASE + "FDJUMPLOG N\nFD1JUMP freq 1000 1500 3e-5\n")
    d2 = (np.asarray(m2.prepare(toas).delay())
          - np.asarray(get_model(BASE).prepare(toas).delay()))
    np.testing.assert_allclose(d2, sel * 3e-5 * (freqs / 1000.0), atol=1e-14)


def test_fdjump_fit_recovery_and_roundtrip():
    true = get_model(BASE + "FD1JUMP freq 1000 1500 2e-5\n")
    toas = _toas(true, n=100, seed=7)
    fit = get_model(BASE + "FD1JUMP freq 1000 1500 0 1\n")
    fit.free_params = ["FD1JUMP1"]
    f = WLSFitter(toas, fit)
    f.fit_toas()
    assert abs(f.model.FD1JUMP1.value - 2e-5) < 2e-6
    # par round-trip preserves the mask, value, and basis convention
    text = f.model.as_parfile()
    assert "FD1JUMP" in text and "freq 1000 1500" in text
    m2 = get_model(text)
    assert abs(m2.FD1JUMP1.value - f.model.FD1JUMP1.value) < 1e-12
    d1 = np.asarray(f.model.prepare(toas).delay())
    d2 = np.asarray(m2.prepare(toas).delay())
    np.testing.assert_allclose(d1, d2, atol=1e-13)
