"""Design-matrix cross-checks: jacfwd columns vs central finite
differences of the phase, for every fittable parameter of a
kitchen-sink model.

(reference pattern: SURVEY.md section 4 pattern 2 — upstream checks
analytic derivatives against d_phase_d_param_num central differences in
per-component tests; here the jacfwd graph IS the analytic derivative,
and the finite difference is the independent check.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

import jax

from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_fromMJDs

KITCHEN_SINK = """
PSR TESTDERIV
RAJ 04:37:15.9
DECJ -47:15:09.1 1
PMRA 121.4 1
PMDEC -71.5 1
PX 6.4 1
POSEPOCH 55300
F0 173.6879 1
F1 -1.728e-15 1
F2 1e-26 1
PEPOCH 55300
DM 2.64 1
DM1 0.001 1
DMEPOCH 55300
NE_SW 4.0 1
BINARY ELL1
PB 5.741 1
A1 3.3667 1
TASC 55301.0 1
EPS1 1.9e-5 1
EPS2 -8e-6 1
M2 0.224 1
SINI 0.674 1
FD1 1e-5 1
FD2 -4e-6 1
GLEP_1 55400
GLPH_1 0.01 1
GLF0_1 1e-8 1
GLF1_1 -1e-16 1
GLF0D_1 1e-8 1
GLTD_1 50 1
WAVE_OM 0.015
WAVE1 0.0001 -0.00005
CM 0.01 1
TNCHROMIDX 4
PHOFF 0.01 1
"""

# relative finite-difference step per parameter family; absolute value
# used when the parameter is zero
STEPS = {
    "F0": 1e-9, "F1": 1e-3, "F2": 1e-2, "DM": 1e-6, "DM1": 1e-3,
    "RAJ": 1e-9, "DECJ": 1e-9, "PMRA": 1e-4, "PMDEC": 1e-4, "PX": 1e-4,
    "PB": 1e-9, "A1": 1e-8, "TASC": 1e-9, "EPS1": 1e-3, "EPS2": 1e-3,
    "M2": 1e-4, "SINI": 1e-4, "NE_SW": 1e-4, "FD1": 1e-3, "FD2": 1e-3,
    "GLPH_1": 1e-3, "GLF0_1": 1e-3, "GLF1_1": 1e-3, "GLF0D_1": 1e-3,
    "GLTD_1": 1e-4, "CM": 1e-3, "PHOFF": 1e-3,
}

# absolute step floors for parameters whose design column is tiny (the
# central-difference cancellation noise eps*|phase|/h would otherwise
# swamp the column); all of these enter the delay (near-)linearly, so a
# large step stays in the linear regime
ABS_STEP_MIN = {"CM": 1.0, "NE_SW": 1.0, "PX": 0.1, "M2": 0.02,
                "SINI": 0.005}


@pytest.fixture(scope="module")
def prepared_sink():
    m = get_model(KITCHEN_SINK)
    n = 120
    mjds = np.linspace(55000, 55600, n)
    freqs = np.tile([700.0, 1400.0, 3000.0], n // 3)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                obs="gbt", add_noise=False)
    prepared = m.prepare(t)
    return m, prepared


def test_every_free_param_has_nonzero_column(prepared_sink):
    m, prepared = prepared_sink
    dm_fn, labels = prepared.designmatrix_fn()
    x0 = prepared.vector_from_params()
    M = np.asarray(dm_fn(x0))
    names = [n for n, _, _ in prepared.free_param_map()]
    assert M.shape[1] == len(names) + (1 if labels[0] == "Offset" else 0)
    off = 1 if labels[0] == "Offset" else 0
    for j, name in enumerate(names):
        col = M[:, off + j]
        assert np.any(col != 0), f"zero design column for {name}"
        assert np.all(np.isfinite(col)), f"non-finite column for {name}"


def test_jacfwd_matches_finite_differences(prepared_sink):
    """Each design column equals the central difference of the phase
    with respect to that parameter (relative tolerance 2e-5 on column
    norm — finite differencing noise dominates at that level)."""
    m, prepared = prepared_sink
    dm_fn, labels = prepared.designmatrix_fn()
    off = 1 if labels[0] == "Offset" else 0
    x0 = np.asarray(prepared.vector_from_params())
    M = np.asarray(dm_fn(prepared.vector_from_params()))
    phase_fn = jax.jit(
        lambda x: prepared._phase_continuous(prepared.params_with_vector(x)))
    names = [n for n, _, _ in prepared.free_param_map()]
    failures = []
    for j, name in enumerate(names):
        rel = STEPS.get(name)
        if rel is None:
            continue
        h = abs(x0[j]) * rel if x0[j] != 0 else rel
        h = max(h, ABS_STEP_MIN.get(name, 0.0))
        xp, xm = x0.copy(), x0.copy()
        xp[j] += h
        xm[j] -= h
        dnum = (np.asarray(phase_fn(xp)) - np.asarray(phase_fn(xm))) / (2 * h)
        dana = M[:, off + j]
        scale = max(np.abs(dnum).max(), np.abs(dana).max())
        err = np.abs(dana - dnum).max() / scale
        # SINI: the Shapiro -2r ln(1 - s sin phi) curvature contributes
        # O(h^2 f''/f') ~ 1e-4 at the step that clears the fd noise
        tol = 2e-4 if name == "SINI" else 2e-5
        if err > tol:
            failures.append((name, err))
    assert not failures, f"jacfwd vs numeric mismatch: {failures}"


def test_astrometry_position_derivatives(prepared_sink):
    """RAJ/DECJ design columns have annual structure with the Roemer
    amplitude scale: |d(phase)/d(angle)| ~ F0 * AU/c * cos(dec)."""
    m, prepared = prepared_sink
    dm_fn, labels = prepared.designmatrix_fn()
    off = 1 if labels[0] == "Offset" else 0
    names = [n for n, _, _ in prepared.free_param_map()]
    M = np.asarray(dm_fn(prepared.vector_from_params()))
    j = names.index("DECJ")
    col = M[:, off + j]  # cycles per radian
    # bound: < F0 * 499 s (AU light time) cycles/rad, > 1% of it
    bound = 173.7 * 499.0
    assert np.abs(col).max() < bound
    assert np.abs(col).max() > 0.01 * bound


def test_bt_piecewise_piece_derivative_columns():
    """T0X/A1X piece columns: jacfwd vs central differences, and zero
    outside the piece window (the gather must not leak)."""
    par = ("PSR TDPW\nRAJ 10:00:00\nDECJ 20:00:00\nF0 150.0 1\n"
           "PEPOCH 55300\nDM 5.0\nBINARY BT_piecewise\n"
           "PB 8.0\nA1 12.0 1\nT0 55300 1\nECC 0.12\nOM 45.0\n"
           "T0X_0001 55300.0001 1\nA1X_0001 12.01 1\n"
           "XR1_0001 55350\nXR2_0001 55450\n")
    m = get_model(par)
    mjds = np.linspace(55300, 55500, 90)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False)
    prepared = m.prepare(t)
    dm_fn, labels = prepared.designmatrix_fn()
    off = 1 if labels[0] == "Offset" else 0
    x0 = np.asarray(prepared.vector_from_params())
    M = np.asarray(dm_fn(prepared.vector_from_params()))
    phase_fn = jax.jit(
        lambda x: prepared._phase_continuous(prepared.params_with_vector(x)))
    names = [n for n, _, _ in prepared.free_param_map()]
    win = (t.get_mjds() >= 55350) & (t.get_mjds() < 55450)
    for name, rel in (("T0X_0001", 1e-11), ("A1X_0001", 1e-8)):
        j = names.index(name)
        h = abs(x0[j]) * rel
        xp, xm = x0.copy(), x0.copy()
        xp[j] += h
        xm[j] -= h
        dnum = (np.asarray(phase_fn(xp)) - np.asarray(phase_fn(xm))) / (2 * h)
        dana = M[:, off + j]
        scale = max(np.abs(dnum).max(), np.abs(dana).max())
        assert np.abs(dana - dnum).max() / scale < 2e-5, name
        # mean subtraction spreads a constant over all TOAs; the
        # *variation* must live only inside the window
        outside = dana[~win]
        assert np.ptp(outside) < 1e-6 * np.ptp(dana), name


def test_swx_dm_derivative_columns():
    """SWXDM piece columns: jacfwd vs central differences (the
    window-normalized geometry factor is itself param-independent, so
    the column must be exactly linear in SWXDM)."""
    par = ("PSR TDSWX\nRAJ 10:00:00\nDECJ 20:00:00\nF0 150.0 1\n"
           "PEPOCH 55300\nDM 5.0\nNE_SW 4.0\n"
           "SWXDM_0001 0.002 1\nSWXR1_0001 55300\nSWXR2_0001 55400\n"
           "SWXP_0001 2.2\n")
    m = get_model(par)
    mjds = np.linspace(55250, 55450, 60)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False)
    prepared = m.prepare(t)
    dm_fn, labels = prepared.designmatrix_fn()
    off = 1 if labels[0] == "Offset" else 0
    x0 = np.asarray(prepared.vector_from_params())
    M = np.asarray(dm_fn(prepared.vector_from_params()))
    phase_fn = jax.jit(
        lambda x: prepared._phase_continuous(prepared.params_with_vector(x)))
    names = [n for n, _, _ in prepared.free_param_map()]
    j = names.index("SWXDM_0001")
    h = 0.001
    xp, xm = x0.copy(), x0.copy()
    xp[j] += h
    xm[j] -= h
    dnum = (np.asarray(phase_fn(xp)) - np.asarray(phase_fn(xm))) / (2 * h)
    dana = M[:, off + j]
    scale = max(np.abs(dnum).max(), np.abs(dana).max())
    assert scale > 0
    assert np.abs(dana - dnum).max() / scale < 2e-5


def test_swp_derivative_matches_finite_difference():
    """d(phase)/d(SWP) under SWM 1 — the only parameter whose
    derivative flows through the tanh-sinh cos-power quadrature —
    matches central finite differences (autodiff-vs-numeric pattern,
    upstream test_derivative_utils analog for SWM 1)."""
    m = get_model("PSR DSWP\nRAJ 05:00:00\nDECJ 02:00:00\nF0 200.0 1\n"
                  "PEPOCH 55300\nDM 10.0\nSWM 1\nNE_SW 12.0\nSWP 2.4 1\n")
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55365, 80), m,
                                error_us=1.0, obs="gbt", iterations=0)
    prepared = m.prepare(t)
    dm_fn, labels = prepared.designmatrix_fn()
    names = [n for n, _, _ in prepared.free_param_map()]
    j = names.index("SWP")
    off = 1 if labels[0] == "Offset" else 0
    x0 = np.asarray(prepared.vector_from_params())
    M = np.asarray(dm_fn(prepared.vector_from_params()))
    phase_fn = jax.jit(
        lambda x: prepared._phase_continuous(prepared.params_with_vector(x)))
    # larger step than the generic battery: the SWP column is tiny
    # (~1.6e-4 cycles per unit index), so differencing the ~cycles-
    # scale phase at h=1e-5 is dominated by f64 cancellation noise
    h = 3e-3
    xp, xm = x0.copy(), x0.copy()
    xp[j] += h
    xm[j] -= h
    dnum = (np.asarray(phase_fn(xp)) - np.asarray(phase_fn(xm))) / (2 * h)
    dana = M[:, off + j]
    scale = max(np.abs(dnum).max(), np.abs(dana).max())
    assert scale > 0
    assert np.abs(dana - dnum).max() / scale < 2e-4
