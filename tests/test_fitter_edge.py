"""Fitter edge cases: frozen params, exact degeneracies, simulation
noise statistics, random-model spread.

(reference patterns: tests/test_fitter.py degenerate/frozen handling,
tests/test_fake_toas.py statistics upstream.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.fitter import WLSFitter, GLSFitter
from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_fromMJDs

BASE = """
PSR TESTEDGE
RAJ 12:10:00.0
DECJ 09:00:00.0
F0 218.8 1
F1 -4e-16 1
PEPOCH 55300
DM 15.0 1
"""


def _toas(m, n=100, error_us=1.0, seed=1, **kw):
    mjds = np.linspace(55000, 55600, n)
    f = np.where(np.arange(n) % 2, 800.0, 1400.0)
    return make_fake_toas_fromMJDs(mjds, m, error_us=error_us, freq_mhz=f,
                                   obs="gbt", add_noise=True, seed=seed, **kw)


def test_frozen_param_does_not_move():
    m = get_model(BASE.replace("F1 -4e-16 1", "F1 -4e-16"))
    assert "F1" not in m.free_params
    t = _toas(m)
    f = WLSFitter(t, m)
    f.fit_toas(maxiter=2)
    assert f.model.F1.value == -4e-16
    assert f.model.F1.uncertainty is None
    # design matrix carries only the free columns (+offset)
    dm = f.get_designmatrix()
    assert "F1" not in dm.param_names
    assert "F0" in dm.param_names


def test_exactly_degenerate_jumps_dropped_not_exploded():
    """Two JUMPs selecting the SAME TOA subset are exactly degenerate
    with each other; the threshold cut must zero one combination
    instead of producing a huge anticorrelated pair."""
    par = BASE + "JUMP -f L 0.0 1\nJUMP -f L 0.0 1\n"
    m = get_model(par)
    t = _toas(m)
    for i, fl in enumerate(t.flags):
        fl["f"] = "L" if i < 50 else "R"
    f = WLSFitter(t, m)
    chi2 = f.fit_toas(maxiter=2)
    assert np.isfinite(chi2)
    j1 = f.model.JUMP1.value
    j2 = f.model.JUMP2.value
    # the degenerate difference direction is cut: neither jump runs away
    assert abs(j1) < 1e-3 and abs(j2) < 1e-3


def test_white_noise_statistics():
    """add_noise=True produces residuals with chi2/dof ~ 1."""
    m = get_model(BASE)
    chis = []
    for seed in range(5):
        t = _toas(m, n=200, seed=seed)
        r = Residuals(t, m)
        chis.append(float(r.chi2) / (len(t) - 1))
    mean_red = np.mean(chis)
    # 5x199 dof: expect 1 +/- ~0.045; allow 4 sigma
    assert 0.8 < mean_red < 1.2, mean_red


def test_efac_scales_noise_draw_and_chi2():
    """EFAC both scales the simulated noise and the sigma used in chi2,
    so reduced chi2 stays ~1 while raw residual rms doubles."""
    par = BASE + "EFAC -f L 2.0\n"
    m = get_model(par)
    mjds = np.linspace(55000, 55600, 300)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=4)
    for fl in t.flags:
        fl["f"] = "L"
    # re-resolve masks after editing flags by re-preparing via Residuals
    r = Residuals(t, m)
    red = float(r.chi2) / (len(t) - 1)
    rms_us = float(np.std(np.asarray(r.time_resids))) * 1e6
    # the draw was made with EFAC applied at simulation time IF flags
    # were set pre-draw; here flags were set after, so the draw is 1 us
    # and scaled sigma is 2 us -> reduced chi2 ~ 0.25
    assert red < 0.5
    assert rms_us < 1.5


def test_random_models_spread_tracks_covariance():
    from pint_tpu.simulation import calculate_random_models

    m = get_model(BASE)
    t = _toas(m, n=150)
    f = WLSFitter(t, m)
    f.fit_toas(maxiter=2)
    dphase = np.asarray(calculate_random_models(f, t, n_models=40, seed=9))
    assert dphase.shape[0] == 40
    # spread grows toward the span edges (F1 uncertainty dominates)
    spread = dphase.std(axis=0)
    mid = spread[len(spread) // 2]
    edge = max(spread[0], spread[-1])
    assert edge > mid
    assert np.all(np.isfinite(spread))


def test_gls_tol_early_stop_matches_full():
    par = BASE + "RNAMP 5e-15\nRNIDX -3\nTNREDC 8\n"
    m1 = get_model(par)
    m2 = get_model(par)
    t = _toas(m1, n=120)
    f1 = GLSFitter(t, m1)
    c1 = f1.fit_toas(maxiter=10, tol=1e-10)
    f2 = GLSFitter(t, m2)
    c2 = f2.fit_toas(maxiter=10)
    assert c1 == pytest.approx(c2, rel=1e-6)
    assert f1.model.F0.value == pytest.approx(f2.model.F0.value, abs=1e-12)


def test_fit_metrics_surface():
    """Every plain fit exposes a metrics dict (SURVEY section 5:
    tracing/observability): prepare time, per-iteration wall times,
    backend, device memory."""
    m = get_model(BASE)
    t = _toas(m, n=60)
    f = WLSFitter(t, m)
    f.fit_toas(maxiter=2)
    mt = f.metrics
    assert mt["backend"] in ("cpu", "tpu")
    assert len(mt["iteration_s"]) == 2
    assert mt["total_s"] >= sum(mt["iteration_s"])
    assert mt["n_toas"] == 60

    par = BASE + "RNAMP 5e-15\nRNIDX -3\nTNREDC 4\n"
    mg = get_model(par)
    fg = GLSFitter(t, mg)
    fg.fit_toas(maxiter=2)
    assert len(fg.metrics["iteration_s"]) == 2


def test_pta_metrics_surface():
    from pint_tpu.parallel import PTABatch

    models, toas_list = [], []
    for i in range(3):
        par = BASE.replace("TESTEDGE", f"PM{i}")
        m = get_model(par)
        models.append(m)
        toas_list.append(_toas(m, n=40, seed=i))
    pta = PTABatch(models, toas_list)
    pta.wls_fit(maxiter=2)
    assert pta.metrics["includes_compile"] is True
    assert pta.metrics["n_pulsars"] == 3
    pta.wls_fit(maxiter=2)
    assert pta.metrics["includes_compile"] is False
    assert pta.metrics["fit_wall_s"] > 0


def test_metrics_on_downhill_and_wideband():
    from pint_tpu.fitter import DownhillWLSFitter, WidebandTOAFitter

    m = get_model(BASE)
    t = _toas(m, n=60)
    f = DownhillWLSFitter(t, m)
    f.fit_toas(maxiter=5)
    assert f.metrics["n_toas"] == 60 and f.metrics["iteration_s"]

    # wideband: give the TOAs DM measurements via flags
    mw = get_model(BASE)
    tw = _toas(mw, n=40)
    for fl in tw.flags:
        fl["pp_dm"] = "15.0"
        fl["pp_dme"] = "1e-3"
    fw = WidebandTOAFitter(tw, mw)
    fw.fit_toas(maxiter=2)
    assert fw.metrics["iteration_s"] and fw.metrics["total_s"] > 0


def test_compile_cache_reuse_and_structure_isolation():
    """The process-global compile cache must (a) serve repeat fits of
    the same model structure with ZERO new compilations — the
    change-par-and-refit latency contract — and (b) key distinct
    trace-time parameterizations (DDH H4/H3 vs H3/STIGMA) separately."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.models.timing_model import _GLOBAL_FNS
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.simulation import make_fake_toas_uniform

    par = ("PSR TCACHE\nRAJ 2:00:00\nDECJ 8:00:00\nF0 173.9 1\n"
           "F1 -1e-15 1\nPEPOCH 55400\nDM 7.0 1\n")
    m = get_model(par)
    t = make_fake_toas_uniform(55000, 55800, 40, m, error_us=1.0,
                               add_noise=True, seed=6)
    WLSFitter(t, m).fit_toas(maxiter=2)
    n0 = len(_GLOBAL_FNS)
    for _ in range(2):  # in-place refits of the now-updated model
        chi2 = WLSFitter(t, m).fit_toas(maxiter=2)
    assert len(_GLOBAL_FNS) == n0, "refit of same structure recompiled"
    assert np.isfinite(chi2)
    # distinct parameterizations (value PRESENCE) must not share keys
    ddh = ("BINARY DDH\nPB 1.5\nA1 3.0\nECC 0.01\nOM 30\nT0 55400\n")
    m_h4 = get_model(par + ddh + "H3 1e-7\nH4 8e-8\n")
    m_st = get_model(par + ddh + "H3 1e-7\nSTIGMA 0.8\n")
    k_h4 = m_h4.prepare(t)._structure_key()
    k_st = m_st.prepare(t)._structure_key()
    assert k_h4 != k_st
    # freezing a param after prepare() must change the key (a stale
    # key would overlay a shorter x onto the old free-param slots)
    pt = m.prepare(t)
    k_before = pt._structure_key()
    m.F1.frozen = True
    try:
        assert pt._structure_key() != k_before
    finally:
        m.F1.frozen = False


def test_ftest_add_params_refit():
    """ftest_add_params: freeing a parameter the data needs gives a
    tiny p-value; freeing a useless one gives a large p-value
    (reference: Fitter.ftest add/refit semantics)."""
    import copy

    import numpy as np

    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    # truth has F2; the base fit freezes it at zero
    true = get_model("PSR TFTA\nRAJ 6:00:00\nDECJ 10:00:00\nF0 200.0 1\n"
                     "F1 -1e-13 1\nF2 1e-23 1\nPEPOCH 55500\nDM 10.0\n")
    t = make_fake_toas_fromMJDs(np.linspace(55000, 56000, 60), true,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=5)
    base = get_model("PSR TFTA\nRAJ 6:00:00\nDECJ 10:00:00\nF0 200.0 1\n"
                     "F1 -1e-13 1\nF2 0 0\nPEPOCH 55500\nDM 10.0\n")
    f = WLSFitter(t, base)
    f.fit_toas(maxiter=5)
    res = f.ftest_add_params("F2")
    assert res["p_value"] < 1e-6  # F2 is really in the data
    assert abs(res["fitter"].model.F2.value - 1e-23) \
        < 5 * res["fitter"].model.F2.uncertainty
    # a pointless parameter: DM1 on dispersionless-noise data
    base2 = get_model("PSR TFTB\nRAJ 6:00:00\nDECJ 10:00:00\nF0 200.0 1\n"
                      "F1 -1e-13 1\nPEPOCH 55500\nDM 10.0\nDM1 0 0\n")
    t2 = make_fake_toas_fromMJDs(np.linspace(55000, 56000, 60), base2,
                                 error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                 add_noise=True, seed=6)
    f2 = WLSFitter(t2, base2)
    f2.fit_toas(maxiter=5)
    res2 = f2.ftest_add_params("DM1")
    assert res2["p_value"] > 0.01
    # already-free and unknown params are rejected loudly
    import pytest

    with pytest.raises(ValueError):
        f.ftest_add_params("F0")
    with pytest.raises(KeyError):
        f.ftest_add_params("GLEP_7")
    with pytest.raises(KeyError):
        f.ftest_add_params("START")  # top-level params are not fittable
    # unfitted baseline refused (prefit chi2 would fake significance)
    f_raw = WLSFitter(t, base)
    with pytest.raises(ValueError, match="fit_toas"):
        f_raw.ftest_add_params("F2")
