"""Crash-safety suite for the durable serving stack (ISSUE 10).

Covers the layers bottom-up: the shared atomic-write helpers
(pint_tpu.durable), the CRC-framed write-ahead request journal and its
torn-tail recovery, the persisted executable cache's corrupt/stale
degrade paths, single-artifact checkpoint rotation, the unified
save/restore_serve_state snapshot, in-process replay idempotence
(committed results never re-emitted, pending requests re-run
bit-identically) — and, as the acceptance capstone, a real SIGKILL
matrix: a serving subprocess is killed mid-flush at EVERY named kill
site in faultinject.KILL_SITES, restarted, and recovery is asserted to
lose nothing, duplicate nothing, and replay bit-identically against a
fault-free reference run.
"""

import os
import pickle
import struct
import types
import warnings
import zlib

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.durable import (atomic_replace, atomic_write_bytes,
                              atomic_write_json, atomic_write_text,
                              fsync_dir)
from pint_tpu.checkpoint import FitCheckpointer
from pint_tpu.models import get_model
from pint_tpu.resilience import FaultPoint, disarm, inject
from pint_tpu.resilience.faultinject import KILL_SITES
from pint_tpu.serve import (FitRequest, PersistentExecutableCache,
                            RequestJournal, ServeEngine,
                            restore_serve_state, result_digest,
                            save_serve_state)
from pint_tpu.serve import journal as journal_mod
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR CRSH{i}
RAJ 10:0{i}:00.0
DECJ 8:30:00.0
F0 31{i}.25 1
F1 -2e-16 1
PEPOCH 55500
DM 13.{i} 1
"""


def _pulsar(i=0, n_toa=24, seed=0):
    m = get_model(PAR.format(i=i))
    rng = np.random.default_rng(seed + i)
    mjds = np.sort(rng.uniform(54500, 56500, n_toa))
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=seed + i,
                                iterations=0)
    return m, t


@pytest.fixture(scope="module")
def pulsar():
    return _pulsar(0, 24)


@pytest.fixture(autouse=True)
def _disarm_everything():
    yield
    disarm()


def _req(rid):
    """A picklable stand-in request for journal-only tests."""
    return types.SimpleNamespace(request_id=rid)


# -- atomic write helpers --------------------------------------------


def test_atomic_write_bytes_publishes_whole_or_nothing(tmp_path):
    dst = tmp_path / "artifact.bin"
    assert atomic_write_bytes(dst, b"generation-1") == str(dst)
    assert dst.read_bytes() == b"generation-1"
    atomic_write_bytes(dst, b"generation-2")
    assert dst.read_bytes() == b"generation-2"
    # a failed write never touches the destination and leaves no temp
    with pytest.raises(TypeError):
        atomic_write_bytes(dst, "not-bytes")
    assert dst.read_bytes() == b"generation-2"
    assert [p.name for p in tmp_path.iterdir()] == ["artifact.bin"]


def test_atomic_write_text_and_json(tmp_path):
    t = tmp_path / "note.txt"
    atomic_write_text(t, "héllo")
    assert t.read_text(encoding="utf-8") == "héllo"
    j = tmp_path / "doc.json"
    atomic_write_json(j, {"a": [1, 2]}, sort_keys=True)
    assert j.read_text() == '{"a": [1, 2]}'


def test_atomic_replace_rotation(tmp_path):
    new = tmp_path / "snap.new"
    cur = tmp_path / "snap"
    cur.write_bytes(b"old")
    new.write_bytes(b"new")
    atomic_replace(new, cur)
    assert cur.read_bytes() == b"new" and not new.exists()
    fsync_dir(tmp_path)          # best-effort, must not raise
    fsync_dir(tmp_path / "gone")  # missing dir degrades silently


# -- request journal: framing, commit point, torn tails --------------


def test_journal_commit_is_the_delivery_point(tmp_path):
    j = RequestJournal(tmp_path)
    j.record_intake(_req("a"))
    j.record_intake(_req("b"))
    j.record_commit("a", "ok", value={"x": 1.0})
    j.close()

    rep = RequestJournal(tmp_path).replay()
    assert set(rep.committed) == {"a"}
    assert rep.committed["a"]["value"] == {"x": 1.0}
    assert [r["rid"] for r in rep.pending] == ["b"]
    assert rep.torn_truncated == 0


def test_journal_replay_dedups_reintaken_requests(tmp_path):
    j = RequestJournal(tmp_path)
    j.record_intake(_req("a"))
    j.record_intake(_req("a"))  # a replayed request re-journals intake
    j.close()
    rep = RequestJournal(tmp_path).replay()
    assert [r["rid"] for r in rep.pending] == ["a"]


def test_journal_torn_tail_truncated_and_prefix_replays(tmp_path):
    j = RequestJournal(tmp_path)
    j.record_intake(_req("a"))
    j.record_commit("a", "ok", value=None)
    j.record_intake(_req("b"))
    j.close()
    good_size = os.path.getsize(j.path)
    # a power cut mid-frame: half a valid frame's bytes land
    payload = pickle.dumps({"t": "intake", "rid": "c"})
    frame = (journal_mod.MAGIC
             + struct.pack("<II", len(payload), zlib.crc32(payload))
             + payload)
    with open(j.path, "ab") as fh:
        fh.write(frame[:len(frame) // 2])

    j2 = RequestJournal(tmp_path)
    with pytest.warns(UserWarning, match="torn"):
        rep = j2.replay()
    # the torn record was never acknowledged: dropping it is correct
    assert rep.torn_truncated == len(frame) // 2
    assert os.path.getsize(j2.path) == good_size
    assert set(rep.committed) == {"a"}
    assert [r["rid"] for r in rep.pending] == ["b"]
    # the log is writable again after truncation
    j2.record_intake(_req("d"))
    j2.close()
    rep2 = RequestJournal(tmp_path).replay()
    assert [r["rid"] for r in rep2.pending] == ["b", "d"]


def test_journal_torn_write_fault_point(tmp_path):
    j = RequestJournal(tmp_path)
    j.record_intake(_req("a"))
    with inject(FaultPoint("journal_torn_write", count=1,
                           payload={"frac": 0.4})):
        j.record_intake(_req("torn"))  # only 40% of the frame lands
    j.close()
    with pytest.warns(UserWarning, match="torn"):
        rep = RequestJournal(tmp_path).replay()
    assert rep.torn_truncated > 0
    assert [r["rid"] for r in rep.pending] == ["a"]


# -- persisted executable cache: corrupt/stale degrade ---------------


def _write_pex(pc, key, programs=None, identity=None):
    """Hand-build a framed .pex file the way store() would."""
    from pint_tpu.serve import excache as ex

    payload = pickle.dumps({
        "identity": identity if identity is not None else pc.identity(key),
        "programs": programs or {}})
    blob = (ex.PERSIST_MAGIC
            + ex._PERSIST_HEADER.pack(len(payload), zlib.crc32(payload))
            + payload)
    path = pc._path(key)
    atomic_write_bytes(path, blob)
    return path


def test_excache_bad_magic_warns_deletes_recompiles(tmp_path):
    pc = PersistentExecutableCache(tmp_path)
    path = pc._path("k")
    atomic_write_bytes(path, b"JUNKJUNKJUNKJUNK")
    with pytest.warns(UserWarning, match="unusable"):
        assert pc.load("k") is None
    assert not os.path.exists(path)  # deleted: next store starts clean
    assert pc.counters()["corrupt"] == 1


def test_excache_crc_mismatch_warns_and_degrades(tmp_path):
    pc = PersistentExecutableCache(tmp_path)
    path = _write_pex(pc, "k")
    pc._damage(path)  # the on-disk bitrot the CRC exists to catch
    with pytest.warns(UserWarning, match="CRC mismatch"):
        assert pc.load("k") is None
    assert not os.path.exists(path)
    assert pc.counters()["corrupt"] == 1


def test_excache_stale_identity_refused(tmp_path):
    pc = PersistentExecutableCache(tmp_path)
    ident = pc.identity("k")
    ident["jax_version"] = "0.0.0"  # a build upgrade happened
    path = _write_pex(pc, "k", identity=ident)
    with pytest.warns(UserWarning, match="stale"):
        assert pc.load("k") is None
    assert not os.path.exists(path)
    assert pc.counters()["stale"] == 1


def test_excache_prewarm_discards_corrupt_survivors(tmp_path):
    pc = PersistentExecutableCache(tmp_path)
    good = _write_pex(pc, "good")
    bad = _write_pex(pc, "bad")
    pc._damage(bad)
    with pytest.warns(UserWarning, match="CRC mismatch"):
        pc.prewarm(background=False)
    assert not os.path.exists(bad)
    # the valid entry is staged and served as a prewarm hit
    assert pc.load("good") == {}
    assert pc.counters()["prewarm_hits"] == 1
    assert good in pc._prewarmed or pc.counters()["loads"] == 1


def test_excache_store_load_roundtrip_with_corrupt_fault(pulsar,
                                                         tmp_path):
    """End-to-end through the real fault point: compile a tiny AOT
    program, persist it, let ``executable_cache_corrupt`` damage the
    store, and watch the loader degrade to recompile — then a clean
    store round-trips to live callables."""
    from pint_tpu.parallel import PTABatch

    m, t = pulsar
    pta = PTABatch([m], [t])
    pta.aot_compile("wls", maxiter=2)
    fns = dict(pta._fns)

    pc = PersistentExecutableCache(tmp_path / "damaged")
    with inject(FaultPoint("executable_cache_corrupt")):
        assert pc.store("k", fns) >= 1
    with pytest.warns(UserWarning, match="CRC mismatch"):
        assert pc.load("k") is None  # warn + recompile, never crash

    pc2 = PersistentExecutableCache(tmp_path / "clean")
    n = pc2.store("k", fns)
    assert n >= 1
    out = pc2.load("k")
    assert out is not None and len(out) == n
    for fn in out.values():
        assert callable(fn)


# -- checkpoint single-artifact snapshots ----------------------------


def test_checkpoint_writes_one_artifact_no_sidecar(tmp_path):
    ckpt = FitCheckpointer(tmp_path)
    ckpt.save("fit", {"x": np.arange(4.0), "iter": 1})
    names = sorted(p.name for p in tmp_path.iterdir())
    assert not any(n.endswith(".meta.json") for n in names)
    out = ckpt.restore("fit")
    assert int(out["iter"]) == 1


def test_checkpoint_rotation_clears_stale_legacy_prev(tmp_path):
    # a pre-single-artifact layout left a sidecar next to fit.prev; the
    # unit rotation must clear the WHOLE .prev generation before
    # rotating, or a fallback restore could pair a new .prev.npz with
    # a stale sidecar from another generation
    stale = tmp_path / "fit.prev.meta.json"
    stale.write_text("{}")
    ckpt = FitCheckpointer(tmp_path)
    ckpt.save("fit", {"x": np.arange(4.0), "iter": 1})
    ckpt.save("fit", {"x": np.arange(4.0), "iter": 2})  # rotates
    assert not stale.exists()
    out = ckpt.restore("fit")
    assert int(out["iter"]) == 2


# -- unified serve-state snapshot ------------------------------------


def test_serve_state_roundtrip_restores_policy(tmp_path):
    eng = ServeEngine(max_batch=1, max_latency_s=1e9, bucket_floor=32,
                      durable_dir=tmp_path)
    eng.breaker.trip(("fit", 32, "gls"))
    save_serve_state(eng)
    eng.journal.close()

    fresh = ServeEngine(max_batch=1, max_latency_s=1e9, bucket_floor=32,
                        durable_dir=tmp_path)
    restored = restore_serve_state(fresh, tmp_path)
    assert restored is not None and "breaker" in restored
    assert fresh.breaker.open_count() == 1
    assert fresh.breaker.state(("fit", 32, "gls")) != "closed"
    fresh.journal.close()


def test_restore_serve_state_fresh_start_is_none(tmp_path):
    eng = ServeEngine(max_batch=1, max_latency_s=1e9, bucket_floor=32)
    assert restore_serve_state(eng, tmp_path) is None


# -- in-process replay idempotence -----------------------------------


def test_recover_replays_pending_bit_identically(pulsar, tmp_path):
    m, t = pulsar

    def req(rid):
        return FitRequest(m, t, method="wls", maxiter=2,
                          request_id=rid)

    # fault-free reference digest for the pending request
    ref_eng = ServeEngine(max_batch=1, max_latency_s=1e9,
                          bucket_floor=32)
    ref = ref_eng.run_stream([req("ref")])[0]
    assert ref.status == "ok"
    ref_digest = result_digest(ref.value)

    # a dead process's journal: r0 committed (sentinel value so a
    # re-run would be visible), r1 accepted but never delivered
    ddir = tmp_path / "durable"
    j = RequestJournal(ddir)
    j.record_intake(req("r0"))
    j.record_commit("r0", "ok", value={"marker": 1.0})
    j.record_intake(req("r1"))
    j.close()

    eng = ServeEngine(max_batch=1, max_latency_s=1e9, bucket_floor=32,
                      durable_dir=ddir)
    rep = eng.recover()
    # committed results come back from the journal, never the fit path
    assert rep["n_committed"] == 1
    assert rep["committed"]["r0"]["value"] == {"marker": 1.0}
    # the pending request re-ran, bit-identically to the reference
    assert rep["n_replayed"] == 1
    replayed = rep["replayed"]["r1"]
    assert replayed.status == "ok"
    assert result_digest(replayed.value) == ref_digest

    # idempotent: a second recover finds everything committed
    rep2 = eng.recover()
    assert rep2["n_replayed"] == 0
    assert set(rep2["committed"]) == {"r0", "r1"}
    eng.journal.close()


# -- the acceptance capstone: SIGKILL at every named site ------------


def test_sigkill_matrix_exactly_once(tmp_path):
    """SIGKILL a real serving subprocess mid-flush at every named kill
    site, restart it, and assert the exactly-once contract: no
    journaled intake is lost, no committed result is re-delivered,
    every replayed result matches the fault-free reference digest
    bit-for-bit. The cold/warm latency SLO is exercised at bench scale
    (bench.py kill-chaos stage); here the fixture is sized for CI so
    only the correctness half is bounded."""
    from pint_tpu.scripts.pint_serve_bench import run_kill_chaos

    report = run_kill_chaos(
        sites=KILL_SITES, ntoa=128, lanes=2, maxiter=2, method="wls",
        structure=0, seed=3, workdir=str(tmp_path),
        ratio_bound=float("inf"), child_timeout_s=300.0)
    assert report["reference_ok"], report
    assert set(report["sites"]) == set(KILL_SITES)
    for site, entry in report["sites"].items():
        assert entry["killed"], (site, entry)       # SIGKILL landed
        assert entry["ok"], (site, entry)
        assert entry["lost"] == 0 and entry["duplicated"] == 0
        assert entry["digest_mismatches"] == 0
        if site == "excache_store":
            # the store died: recovery must recompile, not crash
            assert entry["recompiles"] >= 1
        else:
            # warm persisted cache: recovery never recompiles
            assert entry["recompiles"] == 0
    # the mid-commit tear leaves a torn tail the journal truncates
    assert report["sites"]["mid_commit"]["torn_truncated"] > 0
    # SIGKILL just before the pack-store publish leaves no torn
    # artifact: the restarted process saw a clean miss (zero
    # corrupt-CRC loads), rebuilt live, and re-published an entry
    # that verifies end to end
    sw = report["sites"]["store_write"]
    assert sw["store_ok"], sw
    assert sw["store_counters"]["corrupt"] == 0
    assert sw["store_scan"]["corrupt_or_stale"] == 0
    assert sw["store_scan"]["valid"] >= 1
    # at least one site stranded genuinely pending work to replay
    assert report["replayed"] > 0
    assert report["ok"], report
