"""Edge-case battery: the accumulated scar tissue upstream carries in
~200 test files, rebuilt here as targeted cases (VERDICT r3 item 7).
Each test names its upstream analog. Areas: tim INCLUDE pathologies,
leap-second-day TOAs, inline-command/maskParameter interplay,
degenerate fits, pickle-cache invalidation, TCB conversion scaling.
"""

import os

import numpy as np
import pytest

from pint_tpu.mjd import Epochs
from pint_tpu.models import get_model
from pint_tpu.toa import get_TOAs, read_tim_file

PAR_MIN = ("PSR EDGE1\nRAJ 05:00:00\nDECJ 10:00:00\nF0 100.0 1\n"
           "F1 -1e-15 1\nPEPOCH 55000\nDM 10.0 1\n")


def _write_tim(path, body, fmt="FORMAT 1\n"):
    path.write_text(fmt + body)
    return str(path)


def _toaline(mjd="55000.1234567890123", err="1.0", freq="1400.0",
             obs="gbt", extra=""):
    return f" fake {freq} {mjd} {err} {obs}{extra}\n"


# ---------------------------------------------------------------------------
# tim INCLUDE pathologies (reference: toa.py::read_toa_file recursion,
# upstream tests/test_toa_reader.py)
# ---------------------------------------------------------------------------

class TestIncludePathology:
    def test_include_cycle_raises_not_hangs(self, tmp_path):
        a, b = tmp_path / "a.tim", tmp_path / "b.tim"
        a.write_text(f"FORMAT 1\nINCLUDE {b}\n")
        b.write_text(f"FORMAT 1\nINCLUDE {a}\n")
        with pytest.raises(RuntimeError, match="recursion"):
            read_tim_file(str(a))

    def test_self_include_raises(self, tmp_path):
        a = tmp_path / "a.tim"
        a.write_text(f"FORMAT 1\nINCLUDE {a}\n")
        with pytest.raises(RuntimeError, match="recursion"):
            read_tim_file(str(a))

    def test_deep_but_legal_nesting(self, tmp_path):
        # 9 levels: under the depth-10 limit, all TOAs collected
        files = [tmp_path / f"f{i}.tim" for i in range(9)]
        for i, f in enumerate(files):
            body = _toaline(mjd=f"5500{i}.5")
            if i + 1 < len(files):
                body += f"INCLUDE {files[i + 1]}\n"
            f.write_text("FORMAT 1\n" + body)
        toas, _ = read_tim_file(str(files[0]))
        assert len(toas) == 9

    def test_missing_include_raises(self, tmp_path):
        a = tmp_path / "a.tim"
        a.write_text(f"FORMAT 1\nINCLUDE {tmp_path}/nope.tim\n")
        with pytest.raises((FileNotFoundError, OSError)):
            read_tim_file(str(a))

    def test_include_relative_to_parent_dir(self, tmp_path):
        # upstream resolves INCLUDE relative to the including file
        sub = tmp_path / "sub"
        sub.mkdir()
        (sub / "child.tim").write_text("FORMAT 1\n" + _toaline())
        parent = tmp_path / "parent.tim"
        parent.write_text("FORMAT 1\nINCLUDE sub/child.tim\n")
        toas, _ = read_tim_file(str(parent))
        assert len(toas) == 1

    def test_time_offset_crosses_include(self, tmp_path):
        # TIME in the parent applies inside the INCLUDEd file
        # (reference: inline-execution semantics, toa.py docstring)
        child = tmp_path / "c.tim"
        child.write_text("FORMAT 1\n" + _toaline(mjd="55001.5"))
        parent = tmp_path / "p.tim"
        parent.write_text(
            f"FORMAT 1\nTIME 1.0\n{_toaline(mjd='55000.5')}"
            f"INCLUDE {child}\nTIME -1.0\n{_toaline(mjd='55002.5')}")
        toas, _ = read_tim_file(str(parent))
        assert len(toas) == 3
        secs = [t.sec for t in toas]
        assert secs[0] == pytest.approx(43201.0)  # +1 s TIME offset
        assert secs[1] == pytest.approx(43201.0)  # still active in child
        assert secs[2] == pytest.approx(43200.0)  # popped back


# ---------------------------------------------------------------------------
# leap-second-day TOAs (reference: pulsar_mjd.py; upstream
# tests/test_pulsar_mjd.py)
# ---------------------------------------------------------------------------

class TestLeapSecondDay:
    def test_elapsed_tai_across_leap_boundary(self):
        # 2016-12-31 (MJD 57753) carried a leap second: two TOAs one
        # nominal UTC second apart across midnight are TWO SI seconds
        # apart in TAI
        from pint_tpu import timescales as ts

        before = Epochs(np.array([57753]), np.array([86399.5]), "utc")
        after = Epochs(np.array([57754]), np.array([0.5]), "utc")
        d = ts.utc_to_tai(after).normalized()
        b = ts.utc_to_tai(before).normalized()
        elapsed = (d.day[0] - b.day[0]) * 86400.0 + (d.sec[0] - b.sec[0])
        assert elapsed == pytest.approx(2.0, abs=1e-9)

    def test_tai_minus_utc_steps_exactly_at_boundary(self):
        from pint_tpu.timescales import tai_minus_utc

        assert tai_minus_utc(np.array([57753]))[0] == 36
        assert tai_minus_utc(np.array([57754]))[0] == 37

    def test_toa_on_leap_day_full_chain(self, tmp_path):
        # a TOA late on a leap-second day survives the full
        # tim -> TDB -> posvel chain with finite results
        tim = _write_tim(tmp_path / "leap.tim",
                         _toaline(mjd="57753.9999884")
                         + _toaline(mjd="57754.0000116"))
        m = get_model(PAR_MIN)
        t = get_TOAs(tim, model=m, usepickle=False)
        assert np.isfinite(t.tdb.sec).all()
        assert np.isfinite(t.ssb_obs.pos).all()
        # TDB elapsed time carries the extra SI second too
        el = (t.tdb.day[1] - t.tdb.day[0]) * 86400.0 \
            + (t.tdb.sec[1] - t.tdb.sec[0])
        assert el == pytest.approx(86400.0 * 0.0000232 + 1.0, abs=1e-3)

    def test_fit_with_leap_day_in_span(self):
        # simulate across the 2016-12-31 leap second and refit: the
        # leap must not leave a phase-jump artifact (exact-delta
        # arithmetic uses elapsed TT, not raw MJD labels)
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.simulation import make_fake_toas_fromMJDs

        m = get_model("PSR LEAP1\nRAJ 5:0:0\nDECJ 10:0:0\nF0 100.0 1\n"
                      "F1 -1e-15 1\nPEPOCH 57753\nDM 10.0\n")
        mjds = np.sort(np.concatenate([
            np.linspace(57700, 57753.9, 20), np.linspace(57754.1, 57800, 20)]))
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, obs="gbt",
                                    add_noise=True, seed=1, iterations=2)
        f = WLSFitter(t, m)
        f.fit_toas()
        assert float(f.resids.chi2) / len(mjds) < 3.0


# ---------------------------------------------------------------------------
# inline commands vs maskParameters (reference: toa.py commands +
# timing_model maskParameter; upstream tests/test_toa_flag_commands.py)
# ---------------------------------------------------------------------------

class TestInlineCommandInterplay:
    def test_inline_efac_scales_errors_at_load(self, tmp_path):
        tim = _write_tim(tmp_path / "e.tim",
                         _toaline(err="1.0")
                         + "EFAC 2.5\n" + _toaline(mjd="55001.5", err="1.0")
                         + "EFAC 1.0\n" + _toaline(mjd="55002.5", err="1.0"))
        toas, _ = read_tim_file(tim)
        errs = [t.error_us for t in toas]
        assert errs == pytest.approx([1.0, 2.5, 1.0])

    def test_inline_equad_adds_in_quadrature(self, tmp_path):
        tim = _write_tim(tmp_path / "q.tim",
                         "EQUAD 3.0\n" + _toaline(err="4.0"))
        toas, _ = read_tim_file(tim)
        assert toas[0].error_us == pytest.approx(5.0)  # sqrt(16+9)

    def test_inline_efac_composes_with_model_efac(self, tmp_path):
        # tim EFAC scales the raw error; model EFAC (maskParameter)
        # scales again in the fit sigma — upstream applies both
        tim = _write_tim(tmp_path / "c.tim",
                         "EFAC 2.0\n"
                         + _toaline(err="1.0", extra=" -f L-wide")
                         + _toaline(mjd="55010.5", err="1.0",
                                    extra=" -f L-wide"))
        m = get_model(PAR_MIN + "EFAC -f L-wide 3.0\n")
        t = get_TOAs(tim, model=m, usepickle=False)
        assert t.error_us == pytest.approx([2.0, 2.0])
        from pint_tpu.residuals import Residuals

        r = Residuals(t, m)
        sig = np.asarray(r.prepared.scaled_sigma_us())
        np.testing.assert_allclose(sig, [6.0, 6.0], rtol=1e-12)
        # raw 1.0 us x 2 (tim EFAC, at load) x 3 (model EFAC, in sigma)

    def test_emin_filters_small_errors(self, tmp_path):
        tim = _write_tim(tmp_path / "m.tim",
                         "EMIN 0.5\n" + _toaline(err="0.3")
                         + _toaline(mjd="55001.5", err="1.0"))
        toas, _ = read_tim_file(tim)
        assert len(toas) == 1 and toas[0].error_us == pytest.approx(1.0)

    def test_skip_noskip_blocks(self, tmp_path):
        tim = _write_tim(tmp_path / "s.tim",
                         _toaline() + "SKIP\n"
                         + _toaline(mjd="55001.5") + "NOSKIP\n"
                         + _toaline(mjd="55002.5"))
        toas, _ = read_tim_file(tim)
        assert len(toas) == 2

    def test_tim_jump_creates_flag_groups(self, tmp_path):
        # JUMP ... JUMP blocks label TOAs; the builder materializes one
        # JUMP parameter per group (reference: tim-JUMP semantics)
        tim = _write_tim(tmp_path / "j.tim",
                         _toaline() + "JUMP\n"
                         + _toaline(mjd="55001.5") + "JUMP\n"
                         + _toaline(mjd="55002.5"))
        m = get_model(PAR_MIN)
        t = get_TOAs(tim, model=m, usepickle=False)
        flags = [f.get("tim_jump") for f in t.flags]
        assert flags[0] is None and flags[1] is not None
        assert flags[2] is None

    def test_mode_zero_warns_or_unweights(self, tmp_path):
        # MODE 0 (unweighted) must parse without crashing
        tim = _write_tim(tmp_path / "m0.tim", "MODE 0\n" + _toaline())
        toas, _ = read_tim_file(tim)
        assert len(toas) == 1

    def test_phase_command_adds_pulse_offset(self, tmp_path):
        # PHASE n shifts subsequent pulse numbering (tempo semantics)
        tim = _write_tim(tmp_path / "p.tim",
                         _toaline() + "PHASE 1\n"
                         + _toaline(mjd="55000.2234567890123"))
        toas, cmds = read_tim_file(tim)
        assert len(toas) == 2
        ph = [t.flags.get("phase_offset") for t in toas]
        assert ph[0] is None and float(ph[1]) == 1.0


# ---------------------------------------------------------------------------
# degenerate fits (reference: fitter.py guard rails; upstream
# tests/test_fitter.py rank-deficiency cases)
# ---------------------------------------------------------------------------

class TestDegenerateFits:
    def _toas(self, m, n=20, seed=0, span=(55000, 55300)):
        from pint_tpu.simulation import make_fake_toas_fromMJDs

        rng = np.random.default_rng(seed)
        mjds = np.sort(rng.uniform(*span, n))
        return make_fake_toas_fromMJDs(mjds, m, error_us=1.0, obs="gbt",
                                       add_noise=True, seed=seed,
                                       iterations=1)

    def test_all_frozen_fit_offset_only(self):
        # no free params: the fit solves only the implicit phase
        # offset and must neither crash nor mutate frozen values
        # (upstream WLSFitter has the same offset-only behavior)
        from pint_tpu.fitter import WLSFitter

        m = get_model(PAR_MIN.replace("F0 100.0 1", "F0 100.0")
                      .replace("F1 -1e-15 1", "F1 -1e-15")
                      .replace("DM 10.0 1", "DM 10.0"))
        assert m.free_params == []
        t = self._toas(m)
        f = WLSFitter(t, m)
        f.fit_toas()
        assert np.isfinite(float(f.resids.chi2))
        assert f.model.F0.value == 100.0
        assert f.model.DM.value == 10.0

    def test_single_toa_fit_does_not_crash(self):
        from pint_tpu.fitter import WLSFitter

        m = get_model(PAR_MIN.replace("F1 -1e-15 1", "F1 -1e-15")
                      .replace("DM 10.0 1", "DM 10.0"))
        t = self._toas(m, n=1)
        f = WLSFitter(t, m)
        try:
            f.fit_toas(maxiter=1)
            assert np.isfinite(f.model.F0.value)  # fitted model, no NaN
        except (ValueError, RuntimeError):
            pass  # refusing is also acceptable; hanging/NaN is not

    def test_rank_deficient_jump_all_toas(self):
        # a JUMP covering every TOA is perfectly degenerate with the
        # phase offset; the SVD threshold must zero the null direction
        # and keep the fit finite (upstream: GLSFitter handles via SVD)
        from pint_tpu.fitter import WLSFitter

        m = get_model(PAR_MIN + "JUMP -f L-wide 0.0 1\n")
        t = self._toas(m)
        for fl in t.flags:
            fl["f"] = "L-wide"  # every TOA in the jump
        f = WLSFitter(t, m)
        f.fit_toas()
        assert np.isfinite(float(f.resids.chi2))
        for p in f.model.free_params:
            assert np.isfinite(getattr(f.model, p).value)

    def test_duplicate_epoch_toas(self):
        # identical MJDs (e.g. simultaneous multi-band) must not break
        # the fit or the ECORR epoch quantization
        from pint_tpu.fitter import GLSFitter
        from pint_tpu.simulation import make_fake_toas_fromMJDs

        m = get_model(PAR_MIN + "ECORR -f L-wide 0.5\n")
        mjds = np.repeat(np.linspace(55000, 55200, 8), 3)
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, obs="gbt",
                                    add_noise=True, seed=2, iterations=1)
        for fl in t.flags:
            fl["f"] = "L-wide"
        f = GLSFitter(t, m)
        f.fit_toas()
        assert np.isfinite(float(f.resids.chi2))

    def test_frozen_subset_matches_param_count(self):
        from pint_tpu.fitter import WLSFitter

        m = get_model(PAR_MIN)  # F0, F1, DM free
        t = self._toas(m)
        f = WLSFitter(t, m)
        f.fit_toas()
        assert set(f.model.free_params) == {"F0", "F1", "DM"}
        cov = np.asarray(f.parameter_covariance_matrix)
        assert cov.shape[0] >= 3 and np.isfinite(np.diag(cov)).all()


# ---------------------------------------------------------------------------
# pickle-cache invalidation matrix (reference: toa.py cache keys;
# upstream tests/test_toa_pickle.py)
# ---------------------------------------------------------------------------

class TestPickleCacheInvalidation:
    def _setup(self, tmp_path, body=None):
        tim = _write_tim(tmp_path / "c.tim",
                         body or (_toaline() + _toaline(mjd="55010.5")))
        m = get_model(PAR_MIN)
        return tim, m

    def test_cache_roundtrip_identical(self, tmp_path):
        tim, m = self._setup(tmp_path)
        t1 = get_TOAs(tim, model=m, usepickle=True)
        t2 = get_TOAs(tim, model=m, usepickle=True)  # cache hit
        np.testing.assert_array_equal(t1.day, t2.day)
        np.testing.assert_array_equal(t1.sec, t2.sec)
        np.testing.assert_allclose(t1.ssb_obs.pos, t2.ssb_obs.pos)

    def test_tim_edit_busts_cache(self, tmp_path):
        tim, m = self._setup(tmp_path)
        t1 = get_TOAs(tim, model=m, usepickle=True)
        with open(tim, "a") as fh:
            fh.write(_toaline(mjd="55020.5"))
        t2 = get_TOAs(tim, model=m, usepickle=True)
        assert len(t2) == len(t1) + 1

    def test_included_file_edit_busts_cache(self, tmp_path):
        child = tmp_path / "child.tim"
        child.write_text("FORMAT 1\n" + _toaline(mjd="55005.5"))
        tim = _write_tim(tmp_path / "c.tim",
                         _toaline() + f"INCLUDE {child}\n")
        m = get_model(PAR_MIN)
        t1 = get_TOAs(tim, model=m, usepickle=True)
        child.write_text("FORMAT 1\n" + _toaline(mjd="55005.5")
                         + _toaline(mjd="55006.5"))
        t2 = get_TOAs(tim, model=m, usepickle=True)
        assert len(t2) == len(t1) + 1

    def test_ephem_change_busts_cache(self, tmp_path):
        tim, m = self._setup(tmp_path)
        t1 = get_TOAs(tim, model=m, usepickle=True)
        t2 = get_TOAs(tim, ephem="analytic-test", usepickle=True)
        # different settings key -> fresh computation, not the pickle
        assert t1.ephem != t2.ephem

    def test_physics_rev_busts_cache(self, tmp_path, monkeypatch):
        import pint_tpu.toa as toa_mod

        tim, m = self._setup(tmp_path)
        get_TOAs(tim, model=m, usepickle=True)
        monkeypatch.setattr(toa_mod, "_PHYSICS_REV",
                            toa_mod._PHYSICS_REV + 1000)
        # must recompute (no stale posvels from the old physics era);
        # equality of values is fine — identity of the code path is what
        # the key protects, proven by the key changing
        k1 = toa_mod._pickle_settings_key("de440s", False, True, True,
                                          "BIPM2019")
        monkeypatch.setattr(toa_mod, "_PHYSICS_REV",
                            toa_mod._PHYSICS_REV + 1)
        k2 = toa_mod._pickle_settings_key("de440s", False, True, True,
                                          "BIPM2019")
        assert k1 != k2

    def test_corrupted_pickle_reparses_gracefully(self, tmp_path):
        """A truncated/garbage cache file must be treated as a miss (a
        crash here would make the cache a liability on any unclean
        shutdown)."""
        tim, m = self._setup(tmp_path)
        t1 = get_TOAs(tim, model=m, usepickle=True)
        cache = tim + ".pickle.gz"
        import os
        assert os.path.exists(cache)
        with open(cache, "wb") as fh:
            fh.write(b"\x1f\x8b garbage not a pickle")
        t2 = get_TOAs(tim, model=m, usepickle=True)  # silent re-parse
        np.testing.assert_array_equal(t1.day, t2.day)
        np.testing.assert_array_equal(t1.sec, t2.sec)

    def test_explicit_picklefile_without_filename(self, tmp_path):
        """TOAs built from arrays (no source file) cache only via an
        explicit picklefile, and load back unvalidated."""
        from pint_tpu.toa import TOAs, load_pickle, save_pickle

        t = TOAs.from_arrays(np.array([55000], dtype=np.int64),
                             np.array([43200.0]), error_us=np.array([1.0]),
                             freq_mhz=np.array([1400.0]),
                             obs=np.array(["gbt"], dtype=object))
        with pytest.raises(ValueError, match="picklefile"):
            save_pickle(t)
        pf = str(tmp_path / "arr.pickle.gz")
        save_pickle(t, picklefile=pf)
        back = load_pickle(None, picklefile=pf)
        assert back is not None and len(back) == 1
        assert back.day[0] == 55000

    def test_bipm_setting_in_cache_key(self, tmp_path):
        import pint_tpu.toa as toa_mod

        k1 = toa_mod._pickle_settings_key("de440s", False, True, True,
                                          "BIPM2019")
        k2 = toa_mod._pickle_settings_key("de440s", False, True, True,
                                          "BIPM2021")
        k3 = toa_mod._pickle_settings_key("de440s", False, True, False,
                                          "BIPM2019")
        assert len({k1, k2, k3}) == 3


# ---------------------------------------------------------------------------
# TCB conversion (reference: models/tcb_conversion.py; upstream
# tests/test_tcb.py)
# ---------------------------------------------------------------------------

class TestTCBConversion:
    PAR_TCB = ("PSR TCB1\nRAJ 05:00:00\nDECJ 10:00:00\nF0 100.0 1\n"
               "F1 -1e-15 1\nPEPOCH 55000\nDM 10.0 1\nUNITS TCB\n")

    def test_tcb_raises_by_default(self):
        with pytest.raises(ValueError, match="TCB"):
            get_model(self.PAR_TCB)

    def test_tcb_converted_f0_scaling(self):
        from pint_tpu.models.tcb_conversion import IFTE_K

        with pytest.warns(UserWarning, match="TCB"):
            m = get_model(self.PAR_TCB, allow_tcb=True)
        # TDB seconds are LONGER than TCB seconds (TCB ticks faster),
        # so rates measured per TDB second are higher: F0 *= K, F1 *= K^2
        # (reference: tcb_conversion.py::scale_parameter dim=+1/+2)
        assert m.F0.value == pytest.approx(100.0 * IFTE_K, rel=1e-14)
        assert m.F1.value == pytest.approx(-1e-15 * IFTE_K**2, rel=1e-12)
        assert m.UNITS.value == "TDB"

    def test_tcb_dm_scaling(self):
        from pint_tpu.models.tcb_conversion import IFTE_K

        with pytest.warns(UserWarning, match="TCB"):
            m = get_model(self.PAR_TCB, allow_tcb=True)
        # DM carries one net 1/time dimension through the dispersion
        # constant convention: DM *= K, same sense as F0
        # (reference: tcb_conversion.py::scale_parameter dim=+1)
        assert m.DM.value == pytest.approx(10.0 * IFTE_K, rel=1e-12)

    def test_tcb_raw_keeps_values(self):
        m = get_model(self.PAR_TCB, allow_tcb="raw")
        assert m.F0.value == 100.0
        assert m.UNITS.value == "TCB"

    def test_tcb_roundtrip_through_parfile(self):
        with pytest.warns(UserWarning, match="TCB"):
            m = get_model(self.PAR_TCB, allow_tcb=True)
        m2 = get_model(m.as_parfile())  # now TDB: loads cleanly
        assert m2.F0.value == pytest.approx(m.F0.value, rel=1e-15)
        assert m2.UNITS.value == "TDB"

    def test_si_units_treated_as_tcb(self):
        with pytest.warns(UserWarning, match="TCB"):
            m = get_model(self.PAR_TCB.replace("UNITS TCB", "UNITS SI"),
                          allow_tcb=True)
        from pint_tpu.models.tcb_conversion import IFTE_K

        assert m.F0.value == pytest.approx(100.0 * IFTE_K, rel=1e-14)


# ---------------------------------------------------------------------------
# tim format pathologies (reference: upstream tests/test_toa_reader.py)
# ---------------------------------------------------------------------------

class TestTimPathologies:
    def test_empty_tim_loads_zero_toas(self, tmp_path):
        # the documented contract: an empty tim yields a len-0 TOAs
        # object (callers decide whether that is an error), and the
        # posvel chain must tolerate the empty arrays
        tim = _write_tim(tmp_path / "e.tim", "")
        m = get_model(PAR_MIN)
        t = get_TOAs(tim, model=m, usepickle=False)
        assert len(t) == 0

    def test_comment_and_blank_lines_skipped(self, tmp_path):
        tim = _write_tim(tmp_path / "c.tim",
                         "C comment line\n# hash comment\n\n"
                         + _toaline())
        toas, _ = read_tim_file(tim)
        assert len(toas) == 1

    def test_crlf_line_endings(self, tmp_path):
        body = "FORMAT 1\r\n" + _toaline().rstrip("\n") + "\r\n"
        p = tmp_path / "w.tim"
        p.write_bytes(body.encode())
        toas, _ = read_tim_file(str(p))
        assert len(toas) == 1

    def test_negative_and_huge_flag_values(self, tmp_path):
        tim = _write_tim(tmp_path / "f.tim",
                         _toaline(extra=" -pn -3 -be WIDEBAND_1 -snr 1e8"))
        toas, _ = read_tim_file(tim)
        assert toas[0].flags["pn"] == "-3"
        assert toas[0].flags["be"] == "WIDEBAND_1"

    def test_high_precision_mjd_preserved(self, tmp_path):
        # 1e-13 day = 8.6 ns: the int-day + float-sec split must hold it
        tim = _write_tim(tmp_path / "p.tim",
                         _toaline(mjd="55000.1234567890123"))
        toas, _ = read_tim_file(tim)
        frac = toas[0].sec / 86400.0
        assert frac == pytest.approx(0.1234567890123, abs=1e-13)

    def test_obs_alias_resolution(self, tmp_path):
        # tempo site codes / aliases resolve to canonical names
        tim = _write_tim(tmp_path / "o.tim", _toaline(obs="1"))
        m = get_model(PAR_MIN)
        t = get_TOAs(tim, model=m, usepickle=False)
        assert np.isfinite(t.ssb_obs.pos).all()

    def test_unknown_observatory_raises(self, tmp_path):
        tim = _write_tim(tmp_path / "u.tim", _toaline(obs="notascope"))
        m = get_model(PAR_MIN)
        with pytest.raises(KeyError):
            get_TOAs(tim, model=m, usepickle=False)
