"""Hand-derivable absolute physics anchors.

(VERDICT round 1, missing #8: with no reference tree or golden TEMPO
files on disk, the suite needs anchors derivable from published
formulas/constants by hand — values a reviewer can check with a
calculator. Complements tests/test_precision_budget.py's time-scale
and ephemeris anchors.)
"""

import numpy as np
import pytest

pytestmark = pytest.mark.filterwarnings("ignore")

from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR_BASE = """
PSR ANCHOR
RAJ 06:00:00.0
DECJ 00:00:00.0
F0 100.0
PEPOCH 55500
DM 0.0
"""


def _delay_of(par, mjds, freq=1400.0, comp_name=None, obs="coe"):
    """Total delay [s] per TOA; with comp_name, only that component."""
    import jax.numpy as jnp

    m = get_model(par)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freq,
                                obs=obs, add_noise=False, iterations=0)
    prep = m.prepare(t)
    if comp_name is None:
        return np.asarray(prep.delay())
    comp = m.components[comp_name]
    accum = jnp.zeros(len(t))
    return np.asarray(comp.delay(prep.params0, prep.batch, prep.prep, accum))


def test_dispersion_delay_absolute():
    """DM delay = DMconst * DM / nu^2 with DMconst = 1/2.41e-4
    MHz^2 s cm^3/pc (the fixed tempo convention): DM=10, 1400 MHz
    -> 4.149378/1.96 ms * 10."""
    par = PAR_BASE.replace("DM 0.0", "DM 10.0")
    d = _delay_of(par, np.array([55500.0]), freq=1400.0,
                  comp_name="DispersionDM")
    expected = (1.0 / 2.41e-4) * 10.0 / 1400.0**2
    assert d[0] == pytest.approx(expected, rel=1e-12)
    assert expected == pytest.approx(2.1170e-2, rel=1e-4)  # calculator check


def test_roemer_delay_annual_amplitude():
    """A pulsar ON the ecliptic (the equinox point RA 0h Dec 0) sees
    the Roemer delay swing +-1 AU/c = +-499.005 s over the year
    (orbital eccentricity allows 2% slack). A source 23.4 deg off the
    plane (RA 6h Dec 0) must show the cos(beta)-reduced swing."""
    par_ecl = PAR_BASE.replace("RAJ 06:00:00.0", "RAJ 00:00:00.0")
    mjds = np.linspace(55000, 55365, 200)
    d = _delay_of(par_ecl, mjds, comp_name="AstrometryEquatorial")
    au_c = 499.00478384
    assert d.max() == pytest.approx(au_c, rel=0.02)
    assert d.min() == pytest.approx(-au_c, rel=0.02)
    d6 = _delay_of(PAR_BASE, mjds, comp_name="AstrometryEquatorial")
    assert d6.max() == pytest.approx(au_c * np.cos(np.radians(23.44)),
                                     rel=0.03)


def test_parallax_delay_amplitude():
    """Parallax timing delay amplitude = (r_E cos beta)^2 / (2 c d):
    for PX = 1 mas (d = 1 kpc) and an ecliptic-pole-ish geometry the
    scale is 1.21 us x cos^2(beta). Use the known formula directly
    against the component's peak-to-peak."""
    par = PAR_BASE + "PX 1.0\n"
    mjds = np.linspace(55000, 55365, 160)
    d_px = (_delay_of(par, mjds, comp_name="AstrometryEquatorial")
            - _delay_of(PAR_BASE, mjds, comp_name="AstrometryEquatorial"))
    AU = 1.495978707e11
    c = 2.99792458e8
    d_m = 3.0856775814913673e19  # 1 kpc
    # ecliptic-plane source: projected r_E sweeps 0..1 AU, delay
    # = rho^2/(2cd) with rho the transverse offset; amplitude bound:
    amp = AU**2 / (2 * c * d_m)
    assert amp == pytest.approx(1.21e-6, rel=0.01)
    ptp = d_px.max() - d_px.min()
    assert 0.4 * amp < ptp <= 1.05 * amp


def test_binary_einstein_delay_amplitude():
    """GAMMA produces a gamma*sin(E) term: peak-to-peak Einstein delay
    = 2*GAMMA at e->0 (BT model, other terms differenced away)."""
    gamma = 2e-4
    base = PAR_BASE + ("BINARY BT\nPB 10.0\nA1 0.0\nT0 55200\nECC 0.001\n"
                       "OM 0.0\n")
    par = base + f"GAMMA {gamma}\n"
    mjds = np.linspace(55200, 55210, 200)  # one full orbit
    d = (_delay_of(par, mjds, comp_name="BinaryBT")
         - _delay_of(base, mjds, comp_name="BinaryBT"))
    assert d.max() - d.min() == pytest.approx(2 * gamma, rel=1e-2)


def test_shapiro_delay_logarithmic_peak():
    """Companion Shapiro delay at superior conjunction minus its value
    a quarter-orbit away: Delta = -2 r ln((1-s sin phi)) form; for
    M2 = 0.5 Msun, SINI = 0.999 the r scale is 2 G M2/c^3 = 2.46 us."""
    m2 = 0.5
    base = PAR_BASE + ("BINARY DD\nPB 10.0\nA1 10.0\nT0 55200\nECC 1e-6\n"
                       "OM 0.0\n")
    par = base + f"M2 {m2}\nSINI 0.999\n"
    mjds = np.linspace(55200.0, 55210.0, 4001)
    d = (_delay_of(par, mjds, comp_name="BinaryDD")
         - _delay_of(base, mjds, comp_name="BinaryDD"))
    r_s = 4.925490947e-6 * m2  # T_sun * M2
    # peak-to-peak over the orbit: -2r ln(1-s sinphi) range for s=0.999
    s = 0.999
    expected_ptp = -2 * r_s * (np.log(1 - s) - np.log(1 + s))
    assert d.max() - d.min() == pytest.approx(expected_ptp, rel=0.05)


def test_solar_wind_one_au_column():
    """NE_SW = 4 cm^-3 at 90 deg elongation: DM contribution =
    n0 * 1 AU * (pi/2) / pi ... the standard geometry gives
    DM_sw = n0 * AU * theta/sin(theta) with theta the sun angle; at
    elongation 90 deg DM = n0 * AU * (pi/2). Check against the
    component through the full chain."""
    par = PAR_BASE + "NE_SW 4.0\n"
    # RAJ 06:00 source: sun at RA ~6h around Dec 21 solstice -> near
    # conjunction; around equinox (Mar) elongation ~90 deg. Use the
    # dates only to pick geometry; anchor via the formula itself.
    from pint_tpu.models.solar_wind import SolarWindDispersion  # noqa: F401

    mjds = np.array([55276.0])  # ~Mar 21: RA_sun ~0h, source 6h -> ~90 deg
    d = _delay_of(par, mjds, freq=1400.0, comp_name="SolarWindDispersion")
    AU_pc = 1.495978707e11 / 3.0856775814913673e16  # AU in pc
    dm_expected = 4.0 * AU_pc * (np.pi / 2.0)  # pc cm^-3
    delay_expected = (1.0 / 2.41e-4) * dm_expected / 1400.0**2
    assert d[0] == pytest.approx(delay_expected, rel=0.05)


# ---------------------------------------------------------------------------
# Published-value anchors (VERDICT r2 next-step 9): PK parameters of the
# best-timed double neutron stars, computed from the published MASSES via
# the production DDGR code path and asserted against the published MEASURED
# values at <=0.1%-class tolerances. A 0.1% physics regression in the
# orbital-dynamics chain (Kepler frequency, TSUN_S, eccentricity handling,
# the PK relations) breaks these.
# ---------------------------------------------------------------------------


def _ddgr_pk(pb_days, ecc, a1_ls, mtot, m2):
    """Derived PK params (omdot deg/yr, gamma s, pbdot s/s, sini) from
    (MTOT, M2) through BinaryDDGR._gr_params — the code the design
    matrix differentiates, not a test-local reimplementation."""
    par = PAR_BASE + (f"BINARY DDGR\nPB {pb_days!r} 1\nA1 {a1_ls!r} 1\n"
                      f"T0 55100.0 1\nECC {ecc!r} 1\nOM 90.0 1\n"
                      f"MTOT {mtot!r}\nM2 {m2!r}\n")
    m = get_model(par)
    comp = m.components["BinaryDDGR"]
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55010, 5), m,
                                error_us=1.0, freq_mhz=1400.0, obs="coe",
                                add_noise=False, iterations=0)
    prepared = m.prepare(t)
    params = {k: np.asarray(v) for k, v in prepared.params0.items()}
    gr = comp._gr_params(params, prepared.prep)
    n_orb = 2 * np.pi / (pb_days * 86400.0)
    omdot_degyr = (float(gr["k"]) * n_orb * (365.25 * 86400.0)
                   / np.deg2rad(1.0))
    return (omdot_degyr, float(gr["GAMMA"]), float(gr["PBDOT"]),
            float(gr["SINI"]))


def test_double_pulsar_pk_anchors():
    """J0737-3039A (Kramer et al. 2006, Science 314, 97): masses
    mA=1.3381, mB=1.2489 Msun predict the MEASURED PK values:
    omdot = 16.89947(68) deg/yr, gamma = 0.3856(26) ms,
    Pbdot(GR) = -1.24787(13)e-12, s = 0.99974(-39/+16)."""
    omdot, gam, pbdot, sini = _ddgr_pk(
        pb_days=0.10225156248, ecc=0.0877775, a1_ls=1.415032,
        mtot=1.3381 + 1.2489, m2=1.2489)
    assert omdot == pytest.approx(16.89947, rel=1e-3)
    assert gam == pytest.approx(0.3856e-3, rel=1e-2)
    assert pbdot == pytest.approx(-1.24787e-12, rel=2e-3)
    assert sini == pytest.approx(0.99974, rel=5e-4)


def test_hulse_taylor_pk_anchors():
    """B1913+16 (Weisberg, Nice & Taylor 2010, ApJ 722, 1030): masses
    m1=1.4398, m2=1.3886 Msun were DERIVED from omdot+gamma, so the
    GR chain must reproduce omdot = 4.226598(5) deg/yr and
    gamma = 4.2992(8) ms essentially exactly; Pbdot(GR) =
    -2.40253e-12 (the classic GW-emission prediction)."""
    omdot, gam, pbdot, _ = _ddgr_pk(
        pb_days=0.322997448911, ecc=0.6171334, a1_ls=2.341782,
        mtot=1.4398 + 1.3886, m2=1.3886)
    assert omdot == pytest.approx(4.226598, rel=5e-4)
    assert gam == pytest.approx(4.2992e-3, rel=1e-3)
    assert pbdot == pytest.approx(-2.40253e-12, rel=1e-3)


def test_b1534_pk_anchors():
    """B1534+12 (Fonseca, Stairs & Thorsett 2014, ApJ 787, 82):
    mp=1.3330, mc=1.3455 Msun (the companion NS is the heavier one);
    measured omdot = 1.7557950(19) deg/yr, gamma = 2.0708(5) ms."""
    omdot, gam, _, _ = _ddgr_pk(
        pb_days=0.420737298879, ecc=0.2736775, a1_ls=3.7294636,
        mtot=1.3330 + 1.3455, m2=1.3455)
    assert omdot == pytest.approx(1.7557950, rel=5e-4)
    assert gam == pytest.approx(2.0708e-3, rel=1e-3)


def test_j0437_shklovskii_kinematic_anchor():
    """J0437-4715 (Verbiest et al. 2008, ApJ 679, 675): the measured
    orbital period derivative Pbdot = 3.73(6)e-12 is almost entirely
    the Shklovskii term mu^2 d/c * Pb — so cleanly that the paper
    inverts it for a kinematic distance. With mu = 140.914 mas/yr,
    d = 156.3 pc (PX 6.396 mas), Pb = 5.7410459 d the production
    shklovskii_factor must land on the measured value."""
    from pint_tpu.derived_quantities import shklovskii_factor

    pb_s = 5.7410459 * 86400.0
    pbdot_shk = shklovskii_factor(140.914, 0.1563) * pb_s
    assert pbdot_shk == pytest.approx(3.73e-12, rel=0.02)


def test_b1913_mass_function_anchor():
    """B1913+16 mass function: f(m) = 0.13217 Msun from
    Pb = 0.322997448911 d, a1 = 2.341782 ls (Weisberg, Nice & Taylor
    2010, ApJ 722, 1030, table 2) — pins G, Msun, and the a^3/Pb^2
    plumbing in one published number."""
    from pint_tpu.derived_quantities import mass_function

    f = mass_function(0.322997448911, 2.341782)
    assert f == pytest.approx(0.13217, rel=1e-4)


def test_crab_spin_derived_anchors():
    """Crab pulsar (B0531+21) textbook values (Lyne & Graham-Smith;
    P = 33.392 ms, Pdot = 4.21e-13 at the 1994-era epoch): the derived
    spin quantities must land on the published
    characteristic age ~1260 yr, surface field ~3.8e12 G, and
    spin-down luminosity ~4.5e38 erg/s (I = 1e45 g cm^2)."""
    from pint_tpu.derived_quantities import (pulsar_B, pulsar_age,
                                             pulsar_edot)

    p, pd = 33.392e-3, 4.21e-13
    f0, f1 = 1.0 / p, -pd / p**2
    age_yr = pulsar_age(f0, f1)  # returns years
    assert age_yr == pytest.approx(p / (2 * pd) / 86400.0 / 365.25,
                                   rel=1e-12)  # n=3 braking definition
    assert age_yr == pytest.approx(1257.0, rel=0.02)
    assert pulsar_B(f0, f1) == pytest.approx(3.8e12, rel=0.03)
    # pulsar_edot returns SI watts: 4.5e38 erg/s = 4.5e31 W
    assert pulsar_edot(f0, f1) == pytest.approx(4.5e31, rel=0.05)


def test_j1614_shapiro_range_anchor():
    """J1614-2230 (Demorest et al. 2010, Nature 467, 1081):
    mc = 0.500 Msun, i = 89.17 deg. The Shapiro RANGE parameter is
    r = T_sun * mc = 2.4628 us; the near-edge-on geometry amplifies
    it to a peak-to-trough range 2r ln((1+s)/(1-s)) ~ 48.5 us (the
    published 'two-solar-mass pulsar' detection signal), and the
    packaged DD binary must reproduce that range from M2/SINI."""
    import numpy as np

    T_SUN_US = 4.925490947
    mc, inc = 0.500, np.radians(89.17)
    r_us = T_SUN_US * mc
    assert r_us == pytest.approx(2.4627, rel=1e-3)
    s = np.sin(inc)
    peak_us = 2.0 * r_us * np.log((1.0 + s) / (1.0 - s))
    assert peak_us == pytest.approx(48.5, rel=0.01)
    # and the packaged binary model reproduces that peak: ELL1H-free
    # DD with M2/SINI at superior conjunction
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    m = get_model(
        "PSR J1614M\nRAJ 16:14:36.5\nDECJ -22:30:31\nF0 317.378 1\n"
        "PEPOCH 55000\nDM 34.5\nBINARY DD\nPB 8.6866194196\n"
        "A1 11.2911975\nT0 55000.0\nECC 1.3e-6\nOM 175.0\n"
        f"M2 {mc}\nSINI {s}\n")
    mjds = np.linspace(55000.0, 55000.0 + 8.6866194196, 4001)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, obs="gbt",
                                iterations=0)
    # Shapiro part = binary delay with (M2, SINI) minus the same
    # orbit with M2 = 0 (delay_breakdown keeps the full-chain context)
    d_with = m.delay_breakdown(t)["BinaryDD"]
    m0 = get_model(m.as_parfile().replace(f"M2", "#M2")
                   .replace("SINI", "#SINI"))
    d_without = m0.delay_breakdown(t)["BinaryDD"]
    shap = np.asarray(d_with) - np.asarray(d_without)
    span_us = (shap.max() - shap.min()) * 1e6
    assert span_us == pytest.approx(peak_us, rel=0.05)


def test_solar_limb_shapiro_published_magnitude():
    """Published worked example (Lorimer & Kramer handbook ch. 2 /
    Backer & Hellings 1986): the solar Shapiro delay for a ray grazing
    the solar limb is ~113 us larger than at quadrature —
    Delta = -2 T_sun ln(1 - cos theta), theta_limb = R_sun/AU =
    4.652e-3 rad -> 112.6 us (commonly quoted as "~120 us at the
    limb"). Also pins the published constant T_sun = GM_sun/c^3 =
    4.925490947 us (tempo/tempo2/PINT convention)."""
    import jax.numpy as jnp

    from pint_tpu.constants import AU_LS, TSUN_S
    from pint_tpu.models.solar_system_shapiro import SolarSystemShapiro

    assert TSUN_S == pytest.approx(4.925490947e-6, rel=1e-9)
    theta_limb = 696_000.0 / 149_597_870.7  # R_sun / AU [rad]
    n = jnp.asarray([0.0, 0.0, 1.0])  # pulsar direction
    # Sun 1 AU from observer, at limb elongation vs at quadrature
    def sun_at(theta):
        return AU_LS * jnp.asarray(
            [jnp.sin(theta), 0.0, jnp.cos(theta)])[None, :]

    d_limb = float(SolarSystemShapiro._body_delay(
        sun_at(theta_limb), n, TSUN_S)[0])
    d_quad = float(SolarSystemShapiro._body_delay(
        sun_at(jnp.pi / 2), n, TSUN_S)[0])
    delta_us = (d_limb - d_quad) * 1e6
    assert delta_us == pytest.approx(112.6, abs=1.5)
