"""Labeled matrix object tests (reference: pint_matrix.py tests —
DesignMatrix/CovarianceMatrix labels, units, combination)."""

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.pint_matrix import (CovarianceMatrix, DesignMatrix,
                                  PintMatrix,
                                  combine_design_matrices_by_param,
                                  combine_design_matrices_by_quantity)
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR TESTM
RAJ 11:00:00.0
DECJ 11:00:00.0
F0 250.0 1
F1 -3e-16 1
PEPOCH 55300
DM 21.0 1
"""


def _model_toas():
    m = get_model(PAR)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55600, 40), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=9)
    return m, t


def test_pint_matrix_segment_validation():
    m = np.zeros((3, 2))
    ok = PintMatrix(m, [[("rows", "s", (0, 3))],
                        [("a", "", (0, 1)), ("b", "", (1, 2))]])
    assert ok.labels(1) == ["a", "b"]
    assert ok.get_label(1, "b") == ("b", "", (1, 2))
    with pytest.raises(ValueError):
        PintMatrix(m, [[("rows", "s", (0, 2))], []])  # rows not covered
    with pytest.raises(ValueError):
        PintMatrix(m, [[("rows", "s", (1, 3))], []])  # gap at start
    with pytest.raises(KeyError):
        ok.get_label(1, "zz")


def test_design_matrix_from_prepared_labels_units():
    m, t = _model_toas()
    prepared = m.prepare(t)
    dm = DesignMatrix.from_prepared(prepared, m)
    assert dm.param_names[0] == "Offset"
    assert set(dm.param_names[1:]) == set(m.free_params)
    i = dm.param_names.index("F0")
    assert dm.param_units[i] == "s/(Hz)"
    assert dm.shape == (40, 1 + len(m.free_params))
    # F0 column of the time design matrix ~ -dt (phase/F0 scaling):
    # magnitude should be of order the data span in seconds / F0... just
    # check finite and nonzero
    col = np.asarray(dm.matrix[:, i])
    assert np.isfinite(col).all() and np.abs(col).max() > 0


def test_covariance_correlation_roundtrip():
    cov = np.array([[4.0, 1.0], [1.0, 9.0]])
    c = CovarianceMatrix(cov, ["A", "B"], ["s", "Hz"])
    np.testing.assert_allclose(c.sigmas(), [2.0, 3.0])
    corr = c.to_correlation()
    np.testing.assert_allclose(np.diag(corr.matrix), 1.0)
    np.testing.assert_allclose(corr.matrix[0, 1], 1.0 / 6.0)
    assert corr.param_names == ["A", "B"] if hasattr(corr, "param_names") \
        else corr.labels(0) == ["A", "B"]


def test_combine_by_quantity_union_and_zeros():
    import jax.numpy as jnp

    m1 = DesignMatrix(jnp.ones((3, 2)), "toa", "s", ["Offset", "F0"],
                      ["s", "s/(Hz)"])
    m2 = DesignMatrix(2 * jnp.ones((2, 2)), "dm", "pc cm^-3",
                      ["F0", "DM"], ["pc cm^-3/(Hz)", "pc cm^-3/(pc cm^-3)"])
    c = combine_design_matrices_by_quantity([m1, m2])
    assert c.param_names == ["Offset", "F0", "DM"]
    assert c.shape == (5, 3)
    M = np.asarray(c.matrix)
    # toa rows: zero DM column; dm rows: zero Offset column
    np.testing.assert_allclose(M[:3, 2], 0.0)
    np.testing.assert_allclose(M[3:, 0], 0.0)
    np.testing.assert_allclose(M[3:, 1], 2.0)
    assert c.get_label(0, "dm")[2] == (3, 5)


def test_combine_by_quantity_unit_conflict():
    import jax.numpy as jnp

    m1 = DesignMatrix(jnp.ones((2, 1)), "toa", "s", ["DM"], ["s/(pc cm^-3)"])
    m2 = DesignMatrix(jnp.ones((2, 1)), "dm", "pc cm^-3", ["DM"],
                      ["pc cm^-3/(Hz)"])
    with pytest.raises(ValueError):
        combine_design_matrices_by_quantity([m1, m2])


def test_combine_by_param():
    import jax.numpy as jnp

    m1 = DesignMatrix(jnp.ones((4, 1)), "toa", "s", ["F0"], ["s/(Hz)"])
    m2 = DesignMatrix(jnp.ones((4, 2)), "toa", "s", ["DM", "PX"],
                      ["s/(pc cm^-3)", "s/(mas)"])
    c = combine_design_matrices_by_param([m1, m2])
    assert c.param_names == ["F0", "DM", "PX"]
    assert c.shape == (4, 3)
    with pytest.raises(ValueError):
        combine_design_matrices_by_param([m1, m1])  # duplicate F0


def test_fitter_exposes_labeled_covariance():
    from pint_tpu.fitter import WLSFitter

    m, t = _model_toas()
    f = WLSFitter(t, m)
    f.fit_toas()
    cov = f.covariance_matrix
    assert isinstance(cov, CovarianceMatrix)
    assert cov.param_names == list(f.model.free_params)
    i = cov.param_names.index("F0")
    assert cov.sigmas()[i] == pytest.approx(f.model.F0.uncertainty)
    corr = f.correlation_matrix
    np.testing.assert_allclose(np.diag(corr.matrix), 1.0, atol=1e-12)
    assert np.abs(np.asarray(corr.matrix)).max() <= 1.0 + 1e-9
