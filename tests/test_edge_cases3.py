"""Edge-case battery 3: wideband pathologies + window-parameter
semantics (VERDICT r4 item 6 — the remaining scar tissue).

(reference test patterns: tests/test_wideband.py + upstream
tests/test_dmefac_dmequad.py, tests/test_dmxrange_add_sub.py,
tests/test_widebandTOA_fitting.py.)
"""

import copy
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.fitter import WidebandTOAFitter
from pint_tpu.residuals import WidebandTOAResiduals
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR TESTW3
RAJ 12:00:00.0
DECJ 15:00:00.0
F0 218.8 1
F1 -4e-16 1
PEPOCH 55500
DM 15.99 1
"""


def _wb_toas(model, dm_true=15.99, seed=2, n=50, dme="1e-4",
             receiver_split=False):
    rng = np.random.default_rng(seed)
    mjds = np.linspace(55000, 56000, n)
    t = make_fake_toas_fromMJDs(mjds, model, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=seed)
    for i, f in enumerate(t.flags):
        f["pp_dm"] = f"{dm_true + rng.standard_normal() * 1e-4:.8f}"
        f["pp_dme"] = dme
        if receiver_split:
            f["fe"] = "RCVR_A" if i % 2 == 0 else "RCVR_B"
    return t


# ---------------------------------------------------------------------------
# DMEFAC / DMEQUAD on wideband DM uncertainties
# (reference: noise_model.py::ScaleDmError; upstream
# tests/test_dmefac_dmequad.py)
# ---------------------------------------------------------------------------

class TestScaleDmError:
    def test_dmefac_scales_dm_chi2(self):
        m0 = get_model(PAR)
        t = _wb_toas(m0)
        chi2_0 = WidebandTOAResiduals(t, m0).dm.chi2
        m2 = get_model(PAR + "DMEFAC -fe RCVR_A 2.0\n")
        t2 = _wb_toas(m2)
        for f in t2.flags:
            f["fe"] = "RCVR_A"  # every TOA in the mask
        chi2_2 = WidebandTOAResiduals(t2, m2).dm.chi2
        # identical data, errors doubled -> chi2 / 4
        assert chi2_2 == pytest.approx(chi2_0 / 4.0, rel=1e-9)

    def test_dmequad_adds_in_quadrature(self):
        dme, dmequad = 1e-4, 3e-4
        m = get_model(PAR + f"DMEQUAD -fe RCVR_A {dmequad}\n")
        t = _wb_toas(m)
        for f in t.flags:
            f["fe"] = "RCVR_A"
        wb = WidebandTOAResiduals(t, m)
        expected = np.hypot(dme, dmequad)
        np.testing.assert_allclose(wb.dm.dm_error, expected, rtol=1e-12)

    def test_dmefac_dmequad_combined_formula(self):
        dme, dmefac, dmequad = 2e-4, 1.5, 1e-4
        m = get_model(PAR + f"DMEFAC -fe RCVR_A {dmefac}\n"
                      f"DMEQUAD -fe RCVR_A {dmequad}\n")
        t = _wb_toas(m, dme=repr(dme))
        for f in t.flags:
            f["fe"] = "RCVR_A"
        wb = WidebandTOAResiduals(t, m)
        expected = np.sqrt((dmefac * dme) ** 2 + dmequad ** 2)
        np.testing.assert_allclose(wb.dm.dm_error, expected, rtol=1e-12)

    def test_dmefac_mask_scoped_to_receiver(self):
        m = get_model(PAR + "DMEFAC -fe RCVR_A 3.0\n")
        t = _wb_toas(m, receiver_split=True)
        wb = WidebandTOAResiduals(t, m)
        is_a = np.array([f["fe"] == "RCVR_A" for f in t.flags])
        np.testing.assert_allclose(wb.dm.dm_error[is_a], 3e-4, rtol=1e-12)
        np.testing.assert_allclose(wb.dm.dm_error[~is_a], 1e-4, rtol=1e-12)

    def test_dmefac_scales_fitted_dm_uncertainty(self):
        """Uniform DMEFAC k leaves the wideband DM estimate put but
        scales its uncertainty ~k (single-frequency TOAs: only the DM
        measurements constrain DM)."""
        m1 = get_model(PAR)
        f1 = WidebandTOAFitter(_wb_toas(m1, dm_true=15.9905), m1)
        f1.fit_toas(maxiter=3)
        m2 = get_model(PAR + "DMEFAC -fe RCVR_A 2.0\n")
        t2 = _wb_toas(m2, dm_true=15.9905)
        for f in t2.flags:
            f["fe"] = "RCVR_A"
        f2 = WidebandTOAFitter(t2, m2)
        f2.fit_toas(maxiter=3)
        assert f2.model.DM.value == pytest.approx(f1.model.DM.value,
                                                  abs=3e-5)
        assert (f2.model.DM.uncertainty
                == pytest.approx(2.0 * f1.model.DM.uncertainty, rel=0.05))

    def test_dmjump_recovery_with_uniform_dmefac(self):
        """The DMJUMP/DMEFAC interplay: a DMEFAC covering every TOA
        leaves the fitted DMJUMP point estimate in place (both
        receivers reweighted equally) and scales its uncertainty ~2x."""
        dmoff = 8e-4

        def build(extra=""):
            m = get_model(PAR + "DMJUMP -fe RCVR_B 0.0 1\n" + extra)
            t = _wb_toas(m, receiver_split=True, seed=5)
            for f in t.flags:
                f["all"] = "1"
                if f["fe"] == "RCVR_B":
                    f["pp_dm"] = repr(float(f["pp_dm"]) + dmoff)
            fit = WidebandTOAFitter(t, m)
            fit.fit_toas(maxiter=3)
            p = next(p for p in fit.model.free_params
                     if p.startswith("DMJUMP"))
            return (getattr(fit.model, p).value,
                    getattr(fit.model, p).uncertainty)

        v1, u1 = build()
        v2, u2 = build("DMEFAC -all 1 2.0\n")
        # sign convention: the jump enters the MODEL DM negated
        # (residuals.wideband_dm_model), so absorbing a +dmoff
        # measurement offset needs DMJUMP = -dmoff
        assert v1 == pytest.approx(-dmoff, abs=1e-4)
        assert v2 == pytest.approx(v1, abs=1e-4)
        assert u2 == pytest.approx(2.0 * u1, rel=0.1)

    def test_nonpositive_pp_dme_excluded_not_infinite(self):
        m = get_model(PAR)
        t = _wb_toas(m)
        t.flags[3]["pp_dme"] = "0.0"
        t.flags[7]["pp_dme"] = "-1e-4"
        with pytest.warns(UserWarning, match="non-positive"):
            wb = WidebandTOAResiduals(t, m)
        assert not wb.dm.valid[3] and not wb.dm.valid[7]
        assert wb.dm.valid.sum() == len(t) - 2
        assert np.isfinite(wb.dm.chi2)
        fit = WidebandTOAFitter(t, copy.deepcopy(m))
        fit.fit_toas(maxiter=2)
        assert np.isfinite(fit.chi2_whitened)


# ---------------------------------------------------------------------------
# DMX window semantics (reference: dispersion_model.py::DispersionDMX;
# upstream tests/test_dmxrange_add_sub.py)
# ---------------------------------------------------------------------------

DMX_PAR = PAR + """DMX_0001 1e-3 1
DMXR1_0001 55000
DMXR2_0001 55400
DMX_0002 -5e-4 1
DMXR1_0002 55600
DMXR2_0002 56100
"""


class TestDMXWindows:
    def test_gap_toas_see_base_dm_only(self):
        m = get_model(DMX_PAR)
        mjds = np.array([55500.0, 55500.5])  # in the gap
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0,
                                    freq_mhz=np.array([800.0, 1600.0]),
                                    obs="gbt", add_noise=False)
        dm = m.total_dm(t)
        np.testing.assert_allclose(dm, 15.99, rtol=1e-12)

    def test_overlapping_windows_warn_and_add(self):
        par = PAR + ("DMX_0001 1e-3 1\nDMXR1_0001 55000\nDMXR2_0001 55500\n"
                     "DMX_0002 4e-4 1\nDMXR1_0002 55400\nDMXR2_0002 56000\n")
        with pytest.warns(UserWarning, match="overlap"):
            m = get_model(par)
        mjds = np.array([55200.0, 55450.0, 55800.0])
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                    obs="gbt", add_noise=False)
        dm = m.total_dm(t) - 15.99
        np.testing.assert_allclose(
            dm, [1e-3, 1.4e-3, 4e-4], rtol=1e-9)

    def test_empty_window_fit_does_not_crash(self):
        """A DMX window containing zero TOAs is a degenerate design
        column: the fit must drop it (zero update) instead of blowing
        up, and still fit the populated window."""
        from pint_tpu.fitter import WLSFitter

        m = get_model(DMX_PAR)
        rng = np.random.default_rng(8)
        mjds = np.sort(rng.uniform(55600, 56090, 40))  # window 2 only
        freqs = np.where(np.arange(40) % 2, 800.0, 1600.0)
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                    obs="gbt", add_noise=True, seed=8)
        f = WLSFitter(t, copy.deepcopy(m))
        chi2 = f.fit_toas(maxiter=2)
        assert np.isfinite(chi2)
        # empty window: parameter unmoved
        assert f.model.DMX_0001.value == pytest.approx(1e-3, abs=1e-12)
        # populated window: fitted with finite uncertainty
        assert f.model.DMX_0002.uncertainty is not None
        assert np.isfinite(f.model.DMX_0002.uncertainty)

    def test_reversed_window_raises(self):
        from pint_tpu.models.timing_model import MissingParameter

        par = PAR + ("DMX_0001 1e-3 1\nDMXR1_0001 55500\n"
                     "DMXR2_0001 55000\n")
        with pytest.raises(MissingParameter, match="DMX_0001"):
            get_model(par)

    def test_dmx_recovery_through_wideband_fit(self):
        """Injected per-window DM offsets are recovered by the wideband
        fitter from the DM measurements."""
        m_true = get_model(DMX_PAR)
        rng = np.random.default_rng(3)
        mjds = np.sort(np.concatenate([rng.uniform(55000, 55390, 25),
                                       rng.uniform(55600, 56090, 25)]))
        t = make_fake_toas_fromMJDs(mjds, m_true, error_us=1.0,
                                    freq_mhz=1400.0, obs="gbt",
                                    add_noise=True, seed=3)
        dm_model = m_true.total_dm(t)
        for f, dmv in zip(t.flags, dm_model):
            f["pp_dm"] = repr(float(dmv + rng.standard_normal() * 1e-4))
            f["pp_dme"] = "1e-4"
        # freeze the global DM: with every TOA inside some window, a
        # free DM is exactly degenerate with a common shift of all DMX
        # offsets, and the split between them is arbitrary
        m_fit = get_model(DMX_PAR.replace("DMX_0001 1e-3", "DMX_0001 0.0")
                          .replace("DMX_0002 -5e-4", "DMX_0002 0.0")
                          .replace("DM 15.99 1", "DM 15.99"))
        fit = WidebandTOAFitter(t, m_fit)
        fit.fit_toas(maxiter=3)
        assert fit.model.DMX_0001.value == pytest.approx(1e-3, abs=1e-4)
        assert fit.model.DMX_0002.value == pytest.approx(-5e-4, abs=1e-4)


# ---------------------------------------------------------------------------
# SWX window semantics (reference: solar_wind_dispersion.py::
# SolarWindDispersionX)
# ---------------------------------------------------------------------------

class TestSWXWindows:
    BASE = (PAR + "NE_SW 8.0\n")

    def _toas(self, m, mjds):
        return make_fake_toas_fromMJDs(
            np.asarray(mjds, float), m, error_us=1.0, freq_mhz=800.0,
            obs="gbt", add_noise=False)

    def _dm(self, m, t):
        return m.total_dm(t)

    def test_gap_toas_fall_back_to_ne_sw(self):
        par = self.BASE + ("SWXDM_0001 2.0 1\nSWXR1_0001 55100\n"
                           "SWXR2_0001 55200\n")
        m = get_model(par)
        m_base = get_model(self.BASE)
        t = self._toas(m, [55050.0, 55300.0])  # both outside the window
        np.testing.assert_allclose(self._dm(m, t), self._dm(m_base, t),
                                   rtol=1e-12)

    def test_inside_window_base_wind_suppressed(self):
        par = self.BASE + ("SWXDM_0001 0.0 1\nSWXR1_0001 55100\n"
                           "SWXR2_0001 55200\n")
        m = get_model(par)
        t = self._toas(m, [55150.0])
        # SWXDM=0 inside the window: NO solar wind at all (the base
        # NE_SW applies only outside every window — upstream semantics)
        assert self._dm(m, t)[0] == pytest.approx(15.99, rel=1e-12)

    def test_overlapping_windows_sum(self):
        par = self.BASE + (
            "SWXDM_0001 1.0 1\nSWXR1_0001 55100\nSWXR2_0001 55300\n"
            "SWXDM_0002 2.0 1\nSWXR1_0002 55200\nSWXR2_0002 55400\n")
        m = get_model(par)
        par1 = self.BASE + ("SWXDM_0001 1.0 1\nSWXR1_0001 55100\n"
                            "SWXR2_0001 55300\n")
        par2 = self.BASE + ("SWXDM_0001 2.0 1\nSWXR1_0001 55200\n"
                            "SWXR2_0001 55400\n")
        t_probe = [55250.0]  # in BOTH windows
        dm_both = self._dm(m, self._toas(m, t_probe))[0] - 15.99
        m1, m2 = get_model(par1), get_model(par2)
        d1 = self._dm(m1, self._toas(m1, t_probe))[0] - 15.99
        d2 = self._dm(m2, self._toas(m2, t_probe))[0] - 15.99
        # overlap: window contributions ADD, base suppressed once.
        # Each window normalizes by its own in-window geometry max,
        # and those maxima move when the window range changes — so
        # compare against single-window models with the same ranges.
        assert dm_both == pytest.approx(d1 + d2, rel=1e-6)


def test_abutting_windows_boundary_toa_warns_at_pack():
    """Inclusive-inclusive windows: a TOA at the exact shared boundary
    of abutting DMX bins is in BOTH masks; pack() reports it (validate
    cannot — it has no TOAs, and abutting bins alone are legal)."""
    par = PAR + ("DMX_0001 1e-3 1\nDMXR1_0001 55000\nDMXR2_0001 55400\n"
                 "DMX_0002 4e-4 1\nDMXR1_0002 55400\nDMXR2_0002 56000\n")
    m = get_model(par)  # abutting, not overlapping: no validate warning
    # the simulation's internal prepare() is the first pack — the
    # warning fires there
    # iterations=0 keeps the nominal MJDs exact — the zero-residual
    # iteration would nudge the boundary TOA off 55400.0 (and with 0
    # iterations nothing prepares/packs until total_dm below)
    t = make_fake_toas_fromMJDs(np.array([55200.0, 55400.0]), m,
                                error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False,
                                iterations=0)
    with pytest.warns(UserWarning, match="more than one DMX window"):
        dm = m.total_dm(t) - 15.99
    # boundary TOA gets both offsets (the behavior the warning names)
    np.testing.assert_allclose(dm, [1e-3, 1.4e-3], rtol=1e-9)
