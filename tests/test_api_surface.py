"""API-surface audit: every symbol the migration guide advertises must
actually exist, and the advertised method names must be present on the
objects that claim them. Parses docs/migrating_from_pint.md so the doc
and the code cannot silently drift apart (doc rot has been a recurring
review finding). (reference role: PINT's API stability is enforced by
its sheer test volume; here the advertised-surface contract is pinned
explicitly.)
"""

import importlib
import os
import re

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
DOC = os.path.join(HERE, "..", "docs", "migrating_from_pint.md")

# module.attr pairs the mapping column advertises (parsed loosely, then
# checked strictly here)
EXPECTED = [
    ("pint_tpu.models", ["get_model", "get_model_and_toas"]),
    ("pint_tpu.toa", ["get_TOAs", "TOAs", "merge_TOAs"]),
    ("pint_tpu.residuals", ["Residuals", "WidebandTOAResiduals"]),
    ("pint_tpu.fitter", ["WLSFitter", "GLSFitter", "WidebandTOAFitter",
                         "DownhillWLSFitter", "DownhillGLSFitter",
                         "WidebandDownhillFitter", "WidebandLMFitter",
                         "PowellFitter", "auto_fitter"]),
    ("pint_tpu.simulation", ["make_fake_toas_uniform",
                             "make_fake_toas_fromMJDs",
                             "calculate_random_models"]),
    ("pint_tpu.gridutils", ["grid_chisq"]),
    ("pint_tpu.polycos", ["Polycos"]),
    ("pint_tpu.derived_quantities", ["mass_funct", "companion_mass",
                                     "pulsar_age", "pulsar_B",
                                     "shklovskii_factor"]),
    ("pint_tpu.eventstats", ["hm", "hmw", "z2m", "sf_hm", "h2sig"]),
    ("pint_tpu.templates", ["LCTemplate"]),
    ("pint_tpu.event_toas", ["load_event_TOAs", "load_Fermi_TOAs",
                             "load_NICER_TOAs", "load_RXTE_TOAs",
                             "load_XMM_TOAs", "load_NuSTAR_TOAs",
                             "load_Swift_TOAs", "calc_lat_weights"]),
    ("pint_tpu.mcmc_fitter", ["MCMCFitter", "MCMCFitterBinnedTemplate",
                              "CompositeMCMCFitter"]),
    ("pint_tpu.bayesian", ["BayesianTiming"]),
    ("pint_tpu.utils", ["taylor_horner", "dmxparse", "dmx_ranges",
                        "FTest", "akaike_information_criterion",
                        "bayesian_information_criterion", "p_to_f",
                        "ELL1_check", "wavex_setup",
                        "translate_wave_to_wavex"]),
    ("pint_tpu.pint_matrix", ["DesignMatrix", "CovarianceMatrix"]),
    ("pint_tpu.pintk", []),
    ("pint_tpu.pintk_gui", []),
]

CLI_SCRIPTS = ["pintempo", "zima", "photonphase", "fermiphase",
               "event_optimize", "event_optimize_multiple", "pintbary",
               "tcb2tdb", "compare_parfiles", "convert_parfile",
               "t2binary2pint", "pintpublish"]

MODEL_METHODS = ["get_barycentric_toas", "orbital_phase", "total_dm",
                 "d_phase_d_toa", "as_parfile", "compare",
                 "delay_breakdown"]
TOAS_METHODS = ["select", "unselect", "mask", "adjust_times",
                "get_mjds", "compute_pulse_numbers", "write_TOA_file"]
FITTER_METHODS = ["fit_toas", "print_summary", "get_summary",
                  "get_derived_params", "ftest_add_params"]


@pytest.mark.parametrize("modname,attrs", EXPECTED,
                         ids=[m for m, _ in EXPECTED])
def test_advertised_symbols_exist(modname, attrs):
    mod = importlib.import_module(modname)
    missing = [a for a in attrs if not hasattr(mod, a)]
    assert not missing, f"{modname} missing advertised: {missing}"


def test_cli_scripts_exist_and_have_main():
    for name in CLI_SCRIPTS:
        mod = importlib.import_module(f"pint_tpu.scripts.{name}")
        assert callable(getattr(mod, "main", None)), name


def test_advertised_methods_exist():
    from pint_tpu.fitter import Fitter
    from pint_tpu.models.timing_model import TimingModel
    from pint_tpu.toa import TOAs

    for meth in MODEL_METHODS:
        assert callable(getattr(TimingModel, meth, None)), meth
    for meth in TOAS_METHODS:
        assert callable(getattr(TOAs, meth, None)), meth
    for meth in FITTER_METHODS:
        assert callable(getattr(Fitter, meth, None)), meth


def test_doc_mapping_rows_resolve():
    """Every `pint_tpu.something` dotted path in the mapping table's
    second column resolves to a real module or attribute."""
    txt = open(DOC).read()
    section = txt.split("## API mapping")[1].split("## Component")[0]
    paths = set(re.findall(r"`(pint_tpu(?:\.\w+)+)", section))
    bad = []
    for p in sorted(paths):
        parts = p.rstrip(".").split(".")
        for cut in range(len(parts), 0, -1):
            try:
                obj = importlib.import_module(".".join(parts[:cut]))
                break
            except ImportError:
                continue
        else:
            bad.append(p)
            continue
        for attr in parts[cut:]:
            obj = getattr(obj, attr, None)
            if obj is None:
                bad.append(p)
                break
    assert not bad, f"doc-advertised paths that do not resolve: {bad}"
