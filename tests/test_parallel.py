"""Multi-pulsar batch + sharding tests on the virtual 8-device CPU mesh.

(the reference has no distributed tests — SURVEY.md section 4; this is
the TPU-era equivalent: vmapped PTA fits and TOA-axis shard_map on
xla_force_host_platform_device_count=8.)
"""

import copy
import warnings

import numpy as np

warnings.simplefilter("ignore")

import jax

from pint_tpu.models import get_model
from pint_tpu.parallel import PTABatch, make_mesh
from pint_tpu.simulation import make_fake_toas_fromMJDs


def _batch(n_psr=4, base_toas=30, perturb=True):
    rng = np.random.default_rng(0)
    models, toas_list, truths = [], [], []
    for i in range(n_psr):
        par = (f"PSR FK{i}\nRAJ 1{i % 10}:00:00.0\nDECJ {5 + i}:30:00.0\n"
               f"F0 {200 + 10 * i}.5 1\nF1 -{3 + i}e-16 1\nPEPOCH 55500\n"
               f"DM {10 + i}.5 1\n")
        m = get_model(par)
        n = base_toas + 5 * i  # ragged counts exercise padding
        mjds = np.sort(rng.uniform(55000, 56000, n))
        freqs = np.where(np.arange(n) % 2, 1400.0, 800.0)
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                    obs="gbt", add_noise=True, seed=i)
        truths.append(m.F0.value)
        if perturb:
            m = copy.deepcopy(m)
            m.F0.value += 1e-9
            m.DM.value += 1e-4
        models.append(m)
        toas_list.append(t)
    return models, toas_list, truths


def test_pta_batch_matches_single_pulsar_fit():
    """The vmapped batch fit must agree with per-pulsar WLSFitter."""
    from pint_tpu.fitter import WLSFitter

    models, toas_list, truths = _batch(3)
    pta = PTABatch(models, toas_list)
    x, chi2, cov = pta.wls_fit(maxiter=3)
    x = np.asarray(x)
    for i in range(3):
        f = WLSFitter(toas_list[i], copy.deepcopy(models[i]))
        f.fit_toas(maxiter=3)
        assert abs(x[i, 0] - f.model.F0.value) < 1e-3 * f.model.F0.uncertainty
        assert abs(x[i, 2] - f.model.DM.value) < 1e-3 * f.model.DM.uncertainty


def test_pta_batch_sharded_over_mesh():
    assert len(jax.devices()) >= 8
    models, toas_list, truths = _batch(8)
    mesh = make_mesh(8)
    pta = PTABatch(models, toas_list, mesh=mesh)
    x, chi2, cov = pta.wls_fit(maxiter=3)
    chi2 = np.asarray(chi2)
    assert np.isfinite(chi2).all()
    dofs = pta.n_toas - len(pta.free_map()) - 1
    assert (chi2 / dofs < 2.5).all()
    # recovered F0 within 5 sigma of truth
    x = np.asarray(x)
    cov = np.asarray(cov)
    for i in range(8):
        assert abs(x[i, 0] - truths[i]) < 5 * np.sqrt(cov[i, 0, 0])


def test_residuals_padding_inert():
    """Padded TOAs must not influence the fit."""
    models, toas_list, _ = _batch(2, base_toas=25)  # 25 and 30 toas
    pta = PTABatch(models, toas_list)
    r, mask = pta.time_residuals()
    r = np.asarray(r)
    assert mask.shape == r.shape
    assert mask[0].sum() == 25 and mask[1].sum() == 30
    assert np.isfinite(r[mask]).all()


def _noise_batch(n_psr=3, n_epochs=18, per_epoch=3, harmonics=None, seed=7):
    """Pulsars with EFAC/EQUAD/ECORR (+optionally ragged red noise):
    clustered epochs so ECORR quantization produces real columns, with
    per-pulsar epoch counts ragged to exercise basis padding."""
    rng = np.random.default_rng(seed)
    models, toas_list = [], []
    for i in range(n_psr):
        par = (f"PSR NZ{i}\nRAJ 0{(2 * i) % 10}:30:00.0\nDECJ {8 + i}:00:00.0\n"
               f"F0 {310 + 4 * i}.25 1\nF1 -{2 + i}e-16 1\nPEPOCH 55500\n"
               f"DM {12 + i}.3 1\n"
               "EFAC -f L-wide 1.2\nEQUAD -f L-wide 0.5\n"
               "ECORR -f L-wide 0.9\n")
        if harmonics:
            par += f"RNAMP 1e-14\nRNIDX -3.2\nTNREDC {harmonics[i]}\n"
        m = get_model(par)
        ne = n_epochs + 2 * i  # ragged epoch (and thus basis) counts
        epoch_days = np.linspace(55000, 56000, ne)
        mjds = np.concatenate(
            [d + np.arange(per_epoch) * 0.5 / 86400.0 for d in epoch_days])
        freqs = np.full(len(mjds), 1400.0)
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                    obs="gbt", add_noise=True, seed=100 + i)
        for f in t.flags:
            f["f"] = "L-wide"
        models.append(m)
        toas_list.append(t)
    return models, toas_list


def test_pta_gls_matches_single_pulsar_gls():
    """Batched GLS (augmented-prior SVD) must agree with the
    single-pulsar GLSFitter (eigh-based Woodbury) per pulsar."""
    from pint_tpu.fitter import GLSFitter

    models, toas_list = _noise_batch(3)
    pta = PTABatch([copy.deepcopy(m) for m in models], toas_list)
    x, chi2, cov = pta.gls_fit(maxiter=2)
    x = np.asarray(x)
    assert len(pta.diverged) == 0
    for i in range(3):
        f = GLSFitter(toas_list[i], copy.deepcopy(models[i]))
        f.fit_toas(maxiter=2)
        fmap = pta.free_map()
        for j, (pname, _, _) in enumerate(fmap):
            par = getattr(f.model, pname)
            assert abs(x[i, j] - par.value) <= \
                max(1e-2 * (par.uncertainty or 1e-12), 1e-15), \
                (i, pname, x[i, j], par.value)


def test_pta_gls_ragged_rednoise_bases():
    """Per-pulsar harmonic counts differ -> zero-padded basis columns
    must be inert (finite result, chi2 comparable to WLS-with-noise)."""
    models, toas_list = _noise_batch(3, harmonics=[10, 14, 12])
    pta = PTABatch(models, toas_list)
    x, chi2, cov = pta.gls_fit(maxiter=2)
    assert np.isfinite(np.asarray(chi2)).all()
    assert len(pta.diverged) == 0
    assert np.isfinite(np.asarray(x)).all()


def test_pta_fault_isolation_poisoned_pulsar():
    """One poisoned pulsar (zero TOA errors -> NaN whitening) must not
    corrupt the other lanes; it is reported and restored to x0."""
    import warnings as w

    models, toas_list, _ = _batch(8)
    # clean reference run
    pta_ref = PTABatch([copy.deepcopy(m) for m in models],
                       toas_list)
    x_ref, chi2_ref, _ = pta_ref.wls_fit(maxiter=3)
    # poison pulsar 3
    bad = copy.deepcopy(toas_list)
    bad[3].error_us = np.zeros_like(bad[3].error_us)
    pta = PTABatch([copy.deepcopy(m) for m in models], bad)
    with w.catch_warnings(record=True) as rec:
        w.simplefilter("always")
        x, chi2, cov = pta.wls_fit(maxiter=3)
    assert list(pta.diverged) == [3]
    assert any("diverged" in str(r.message) for r in rec)
    x, x_ref = np.asarray(x), np.asarray(x_ref)
    # other lanes bitwise-unaffected by lane 3's NaNs
    for i in [0, 1, 2, 4, 5, 6, 7]:
        np.testing.assert_allclose(x[i], x_ref[i], rtol=1e-12)
    # poisoned lane restored to its starting vector
    np.testing.assert_allclose(x[3], np.asarray(pta._x0())[3], rtol=0,
                               atol=0)


def test_toa_axis_shard_map():
    from pint_tpu.parallel.toa_shard import sharded_chi2
    from jax.sharding import Mesh

    models, toas_list, _ = _batch(1, base_toas=64, perturb=False)
    model, toas = models[0], toas_list[0]
    prepared = model.prepare(toas)
    mesh = Mesh(np.array(jax.devices()[:8]), axis_names=("toa",))
    static = {k: prepared.prep[k] for k in ("planet_shapiro", "orb_mode_fb")
              if k in prepared.prep}
    prep = {k: v for k, v in prepared.prep.items()
            if k not in ("T_ld", "pepoch_day", "pepoch_sec") and k not in static}
    chi2_sharded = float(sharded_chi2(model, static, mesh, prepared.params0,
                                      prepared.batch, prep))
    # compare against the unsharded residual chi2
    from pint_tpu.residuals import Residuals

    chi2_ref = Residuals(toas, model, prepared=prepared).chi2
    assert abs(chi2_sharded - chi2_ref) < 1e-6 * max(1.0, chi2_ref)


def test_gls_ecorr_marginalization_matches_dense():
    """The analytic per-epoch Sherman-Morrison ECORR elimination must
    equal the dense append-U-columns solve exactly (same Woodbury
    identity, ~10x fewer normal-equation FLOPs)."""
    import copy

    from pint_tpu.models import get_model
    from pint_tpu.parallel import PTABatch
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    rng = np.random.default_rng(21)
    models, toas_list = [], []
    for i in range(3):
        par = (f"PSR SM{i}\nRAJ {9 + i}:00:00.0\nDECJ {3 * i}:00:00.0\n"
               f"F0 {310 + 5 * i}.5 1\nF1 -{3 + i}e-16 1\nPEPOCH 55500\n"
               f"DM {9 + i}.1 1\nEFAC -f L-wide 1.1\nECORR -f L-wide 0.7\n"
               "RNAMP 2e-14\nRNIDX -3.3\nTNREDC 10\n")
        m = get_model(par)
        days = np.sort(rng.uniform(55000, 56000, 25 + 5 * i))
        mjds = np.sort(np.concatenate(
            [days + kk * 0.4 / 86400 for kk in range(3)]))
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                    obs="gbt", add_noise=True,
                                    add_correlated_noise=True, seed=i)
        for f in t.flags:
            f["f"] = "L-wide"
        models.append(m)
        toas_list.append(t)
    pta_a = PTABatch([copy.deepcopy(m) for m in models], toas_list)
    pta_b = PTABatch([copy.deepcopy(m) for m in models], toas_list)
    x0 = np.asarray(pta_a._x0())
    xd, c2d, covd = pta_a.gls_fit(maxiter=2, ecorr_mode="dense")
    xm, c2m, covm = pta_b.gls_fit(maxiter=2, ecorr_mode="auto")
    # compare the UPDATES, not absolute values: demanding sub-ulp
    # agreement of F0 ~ 310 between two algorithms is meaningless
    np.testing.assert_allclose(np.asarray(xm) - x0, np.asarray(xd) - x0,
                               rtol=1e-9, atol=1e-18)
    np.testing.assert_allclose(np.asarray(c2m), np.asarray(c2d), rtol=1e-12)
    # covariance diagonals (the quoted uncertainties) agree tightly
    dd = np.sqrt(np.diagonal(np.asarray(covd), axis1=1, axis2=2))
    dm = np.sqrt(np.diagonal(np.asarray(covm), axis1=1, axis2=2))
    np.testing.assert_allclose(dm, dd, rtol=1e-6)


def test_gls_marginalization_guards():
    """Overlapping ECORR masks and zero-epoch batches must use the
    exact dense path (review findings: argmax breaks disjointness /
    empty argmax crashes); bogus modes raise."""
    import copy

    import pytest

    from pint_tpu.models import get_model
    from pint_tpu.parallel import PTABatch
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    rng = np.random.default_rng(30)
    # overlapping masks: flag mask + mjd-range mask both catch TOAs
    par = ("PSR OV0\nRAJ 09:00:00.0\nDECJ 03:00:00.0\nF0 310.5 1\n"
           "PEPOCH 55500\nDM 9.1 1\nECORR -f L-wide 0.7\n"
           "ECORR mjd 55000 56000 0.5\n")
    m = get_model(par)
    days = np.sort(rng.uniform(55000, 56000, 20))
    mjds = np.sort(np.concatenate([days + kk * 0.4 / 86400
                                   for kk in range(3)]))
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=1)
    for f in t.flags:
        f["f"] = "L-wide"
    pta = PTABatch([copy.deepcopy(m)], [t])
    U = np.asarray(pta.prep["ecorr_U"])[0]
    assert (U.sum(axis=1) > 1).any()  # genuinely overlapping
    xa, ca, _ = pta.gls_fit(maxiter=2, ecorr_mode="auto")
    pta2 = PTABatch([copy.deepcopy(m)], [t])
    xd, cd, _ = pta2.gls_fit(maxiter=2, ecorr_mode="dense")
    np.testing.assert_allclose(np.asarray(ca), np.asarray(cd), rtol=1e-12)

    # zero epochs: every quantization group a singleton
    par2 = ("PSR OV1\nRAJ 09:00:00.0\nDECJ 03:00:00.0\nF0 310.5 1\n"
            "PEPOCH 55500\nDM 9.1 1\nECORR 0.7\n")
    m2 = get_model(par2)
    t2 = make_fake_toas_fromMJDs(np.linspace(55000, 56000, 30), m2,
                                 error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                 add_noise=True, seed=2)
    pta3 = PTABatch([m2], [t2])
    assert pta3.prep["ecorr_owner"].shape[-1] == 0
    x3, c3, _ = pta3.gls_fit(maxiter=2)  # must not crash

    with pytest.raises(ValueError, match="ecorr_mode"):
        pta3.gls_fit(ecorr_mode="marginalize")


def test_sharded_single_pulsar_gls_matches_fitter():
    """TOA-axis-sharded GLS (sequence-parallel path) equals the
    single-device GLSFitter on the same pulsar: the psum'd normal
    equations are exact regardless of row placement, including ECORR
    epochs straddling shard boundaries."""
    import numpy as np

    from pint_tpu.fitter import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.parallel.toa_shard import sharded_gls_fit
    from pint_tpu.simulation import make_fake_toas_fromMJDs
    from jax.sharding import Mesh
    import jax

    par = ("PSR TSHARD\nRAJ 11:00:00\nDECJ 05:00:00\nF0 301.2 1\n"
           "F1 -3e-16 1\nPEPOCH 55400\nDM 21.0 1\n"
           "EFAC -f L 1.15\nECORR -f L 0.7\n"
           "RNAMP 8e-15\nRNIDX -3.2\nTNREDC 6\n")
    m = get_model(par)
    rng = np.random.default_rng(3)
    # 61 epochs x 2 = 122 TOAs: 122 % 8 != 0, so the _pad_single
    # sentinel-padding branch is genuinely exercised
    days = np.sort(rng.uniform(55000, 55800, 61))
    mjds = np.sort(np.concatenate([days, days + 1.0 / 86400.0]))
    freqs = np.where(np.arange(len(mjds)) % 2, 1400.0, 800.0)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                obs="gbt", add_noise=True, seed=3,
                                iterations=1)
    for fl in t.flags:
        fl["f"] = "L"
    mesh = Mesh(np.array(jax.devices()[:8]), axis_names=("toa",))
    assert len(t) % 8 != 0
    x_sh, chi2_sh, cov_sh = sharded_gls_fit(m, t, mesh, maxiter=2)

    ref = GLSFitter(t, get_model(par))
    ref.fit_toas(maxiter=2)
    names = [n for n, _, _ in get_model(par).prepare(t).free_param_map()]
    # same free-param order as the reference prepared mapping
    x_ref = np.array([getattr(ref.model, n).value for n in names])
    # F0/F1/DM recovered identically (n=120 doesn't divide 8 evenly ->
    # padding rows active too)
    np.testing.assert_allclose(x_sh, x_ref, rtol=1e-9, atol=1e-18)
    assert np.isfinite(chi2_sh)
    # covariance diagonal agrees with the fitter's uncertainties
    unc_ref = np.array([getattr(ref.model, n).uncertainty for n in names])
    np.testing.assert_allclose(np.sqrt(np.diag(cov_sh)), unc_ref, rtol=1e-6)
    # mixed precision on the sharded path: per-shard f32 Gram + psum'd
    # f64 refinement reproduces the f64 parameters to <= 1e-9
    x_mx, chi2_mx, cov_mx = sharded_gls_fit(m, t, mesh, maxiter=2,
                                            precision="mixed")
    np.testing.assert_allclose(x_mx, x_sh, rtol=1e-9, atol=1e-18)
    assert abs(chi2_mx - chi2_sh) <= 1e-9 * abs(chi2_sh)
    np.testing.assert_allclose(np.sqrt(np.diag(cov_mx)),
                               np.sqrt(np.diag(cov_sh)), rtol=1e-4)


def test_ptafleet_mixed_structure_integration():
    """North-star integration: a mini-PTA with heterogeneous models
    (isolated MSP, ELL1 binary + ECORR/red noise, DD binary), simulated
    with correlated noise, bucketed by PTAFleet and refit — every
    pulsar's spin parameters recover within uncertainties."""
    from pint_tpu.parallel import PTAFleet

    pars = [
        ("PSR MIX0\nRAJ 04:37:15\nDECJ -47:15:09\nF0 173.688 1\n"
         "F1 -1.7e-15 1\nPEPOCH 55400\nDM 2.64 1\n"),
        ("PSR MIX1\nRAJ 19:09:47\nDECJ -37:44:14\nF0 339.3157 1\n"
         "F1 -1.6e-15 1\nPEPOCH 55400\nDM 10.39 1\nBINARY ELL1\n"
         "PB 1.533449 1\nA1 1.89799 1\nTASC 55401.0 1\nEPS1 2e-8 1\n"
         "EPS2 -8e-8 1\nEFAC -f L 1.1\nECORR -f L 0.5\n"
         "RNAMP 3e-15\nRNIDX -3.0\nTNREDC 5\n"),
        ("PSR MIX2\nRAJ 19:15:28\nDECJ 16:06:27\nF0 16.94 1\n"
         "F1 -2.5e-15 1\nPEPOCH 55400\nDM 168.77 1\nBINARY DD\n"
         "PB 0.322997 1\nA1 2.3418 1\nECC 0.6171 1\nOM 292.54 1\n"
         "T0 55401.0 1\n"),
    ]
    rng = np.random.default_rng(0)
    models, toas_list, true_f0 = [], [], []
    for k, par in enumerate(pars):
        true = get_model(par)
        # pairs 1 s apart: inside the 2 s ECORR quantization window, so
        # each pair is a real epoch; flags at creation so the "-f L"
        # masks match during the correlated-noise draw
        days = np.sort(rng.uniform(55000, 55800, 60))
        mjds = np.sort(np.concatenate([days, days + 1.0 / 86400.0]))
        t = make_fake_toas_fromMJDs(
            mjds, true, error_us=1.0,
            freq_mhz=np.where(np.arange(len(mjds)) % 2, 800.0, 1400.0),
            obs="gbt", add_noise=True, flags={"f": "L"},
            add_correlated_noise=(k == 1), seed=k, iterations=2)
        start = get_model(par)
        start.F0.value += 2e-10  # perturb so the fit has work
        models.append(start)
        toas_list.append(t)
        true_f0.append(true.F0.value)
    # the ECORR pulsar's epoch basis must be live (one epoch per pair)
    prep1 = models[1].prepare(toas_list[1]).prep
    assert prep1["ecorr_owner"].shape[0] == 60
    fleet = PTAFleet(models, toas_list)
    assert len(fleet.batches) == 3  # three distinct structures
    xs, chi2s, covs = fleet.fit(method="auto", maxiter=3)
    assert not fleet.diverged
    fmaps = fleet.free_maps()
    # recovered spin frequencies within 5 sigma of truth, per pulsar
    for k in range(3):
        assert np.isfinite(chi2s[k]), f"pulsar {k}"
        names = [n for n, _, _ in fmaps[k]]
        i_f0 = names.index("F0")
        f0_fit = xs[k][i_f0]
        f0_sig = np.sqrt(covs[k][i_f0, i_f0])
        assert abs(f0_fit - true_f0[k]) < 5 * f0_sig + 1e-12, \
            (k, f0_fit, true_f0[k], f0_sig)


def test_pta_batch_2d_pulsar_toa_mesh():
    """A (pulsar, toa) 2-D mesh reproduces the unsharded fit: pulsar
    DP combined with TOA-axis sequence sharding (SURVEY 2.2 mesh
    axes), GSPMD inserting the cross-TOA collectives."""
    from pint_tpu.parallel import make_mesh2d

    # uniform 48-TOA pulsars: the padded TOA axis (48) splits exactly
    # 2-way, so the batch leaves really shard over the toa mesh axis
    rng = np.random.default_rng(7)
    models, toas_list = [], []
    for i in range(4):
        par = (f"PSR TD{i}\nRAJ 1{i}:00:00.0\nDECJ {6 + i}:30:00.0\n"
               f"F0 {150 + 9 * i}.25 1\nF1 -{2 + i}e-16 1\nPEPOCH 55500\n"
               f"DM {9 + i}.5 1\n")
        m = get_model(par)
        mjds = np.sort(rng.uniform(55000, 56000, 48))
        freqs = np.where(np.arange(48) % 2, 1400.0, 800.0)
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                    obs="gbt", add_noise=True, seed=10 + i)
        m2 = copy.deepcopy(m)
        m2.F0.value += 1e-9
        models.append(m2)
        toas_list.append(t)
    ref = PTABatch([copy.deepcopy(m) for m in models], toas_list)
    x_ref, chi2_ref, cov_ref = ref.wls_fit(maxiter=3)
    mesh = make_mesh2d(4, 2)
    pta = PTABatch([copy.deepcopy(m) for m in models], toas_list,
                   mesh=mesh)
    from jax.sharding import PartitionSpec as P

    spec = pta.batch.tdb_sec.sharding.spec
    assert tuple(spec) == ("pulsar", "toa"), spec  # really 2-D sharded
    x, chi2, cov = pta.wls_fit(maxiter=3)
    np.testing.assert_allclose(np.asarray(chi2), np.asarray(chi2_ref),
                               rtol=1e-9)
    np.testing.assert_allclose(np.asarray(x), np.asarray(x_ref),
                               rtol=0, atol=1e-12)
    xg, chi2g, covg = pta.gls_fit(maxiter=1)
    refg = PTABatch([copy.deepcopy(m) for m in models], toas_list)
    xg_ref, chi2g_ref, _ = refg.gls_fit(maxiter=1)
    np.testing.assert_allclose(np.asarray(chi2g), np.asarray(chi2g_ref),
                               rtol=1e-9)


def test_distributed_single_process_init():
    """initialize_distributed exercises the REAL jax.distributed
    runtime in its single-process form (coordinator = self), then a
    psum over the global mesh — the code path a multi-host fleet runs,
    minus the extra hosts (SURVEY 2.2 communication backend). Runs in
    a subprocess so the test session's backend state stays untouched."""
    import os
    import subprocess
    import sys

    code = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from pint_tpu.parallel.distributed import (global_pulsar_mesh,
                                           initialize_distributed,
                                           process_pulsar_slice)
pid, nproc = initialize_distributed(coordinator_address="localhost:8497",
                                    num_processes=1, process_id=0)
assert (pid, nproc) == (0, 1), (pid, nproc)
# idempotent
assert initialize_distributed() == (0, 1)
assert process_pulsar_slice(10) == slice(0, 10)
assert process_pulsar_slice(10, process_id=1, num_processes=3) == slice(4, 8)
assert process_pulsar_slice(10, process_id=2, num_processes=3) == slice(8, 10)
mesh = global_pulsar_mesh()
assert mesh.devices.size == 4
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
x = jax.device_put(jnp.arange(8.0), NamedSharding(mesh, P("pulsar")))
total = jax.jit(lambda v: jnp.sum(v))(x)
assert float(total) == 28.0
print("DIST-OK")
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert "DIST-OK" in out.stdout, out.stderr[-2000:]


def _dist_fleet(n_psr=4, n_toa=40):
    """Deterministic uniform-shape fleet every process can rebuild
    identically (equal TOA counts: assemble_global_batch requires
    identical padded shapes across processes). Carries the full noise
    stack (EFAC+ECORR+red noise, clustered epochs) so the distributed
    GLS exercises the real Woodbury path, not a degenerate one."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    rng = np.random.default_rng(42)
    models, toas_list = [], []
    for i in range(n_psr):
        par = (f"PSR DF{i}\nRAJ 0{2 * i}:30:00.0\nDECJ {10 + i}:00:00.0\n"
               f"F0 {180 + 7 * i}.25 1\nF1 -{2 + i}e-16 1\nPEPOCH 55500\n"
               f"DM {12 + i}.0 1\n"
               "EFAC -f L-wide 1.1\nECORR -f L-wide 0.7\n"
               "RNAMP 1e-14\nRNIDX -3.0\nTNREDC 8\n")
        m = get_model(par)
        days = np.sort(rng.uniform(55000, 56000, n_toa // 2))
        mjds = np.sort(np.concatenate([days, days + 30.0 / 86400.0]))
        freqs = np.where(np.arange(n_toa) % 2, 1400.0, 800.0)
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                    obs="gbt", add_noise=True, seed=100 + i,
                                    flags={"f": "L-wide"})
        models.append(m)
        toas_list.append(t)
    return models, toas_list


_DIST_WORKER = '''
import os, sys
pid, port, outdir = int(sys.argv[1]), sys.argv[2], sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import warnings; warnings.simplefilter("ignore")
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from pint_tpu.parallel import PTABatch
from pint_tpu.parallel.distributed import (assemble_global_batch,
    initialize_distributed, process_pulsar_slice)
pid_, nproc = initialize_distributed(
    coordinator_address="127.0.0.1:" + port, num_processes=2,
    process_id=pid)
assert (pid_, nproc) == (pid, 2), (pid_, nproc)
assert len(jax.local_devices()) == 2 and len(jax.devices()) == 4

{builder_src}

models, toas_list = _dist_fleet()
sl = process_pulsar_slice(len(models))
assert sl == slice(2 * pid, 2 * pid + 2), sl
local = PTABatch(models[sl], toas_list[sl])
pta = assemble_global_batch(local)
x, chi2, cov = pta.wls_fit(maxiter=3)
# _pull replicated the global result: every process sees all 4 pulsars
assert np.asarray(x).shape[0] == 4, np.asarray(x).shape
# full-noise GLS over the same global mesh: the distributed Woodbury
# (ECORR quantization + red-noise basis) as ONE cross-process program
xg, chi2g, covg = pta.gls_fit(maxiter=2)
assert np.asarray(xg).shape[0] == 4, np.asarray(xg).shape
np.savez(os.path.join(outdir, f"proc{{pid}}.npz"), x=np.asarray(x),
         chi2=np.asarray(chi2), cov=np.asarray(cov),
         xg=np.asarray(xg), chi2g=np.asarray(chi2g),
         covg=np.asarray(covg))
print("DIST2-OK", pid)
'''


def test_distributed_two_process_fit(tmp_path):
    """REAL multi-process DCN path (VERDICT r2 next-step 8): two CPU
    processes, coordinator on localhost, each packs its
    process_pulsar_slice and assembles the global batch with
    assemble_global_batch; the jitted WLS fit runs as ONE SPMD program
    over the 4-device global mesh, and the replicating result pull is
    a genuine cross-process all-gather. Both processes' results must
    agree with each other and with a single-process fit of the same
    fleet."""
    import inspect
    import os
    import socket
    import subprocess
    import sys
    import textwrap

    import pytest

    # single-process reference in THIS session (8-device CPU mesh)
    models, toas_list = _dist_fleet()
    ref = PTABatch([copy.deepcopy(m) for m in models], toas_list)
    x_ref, chi2_ref, cov_ref = ref.wls_fit(maxiter=3)
    xg_ref, chi2g_ref, _ = ref.gls_fit(maxiter=2)

    builder_src = textwrap.dedent(inspect.getsource(_dist_fleet))
    code = _DIST_WORKER.replace("{builder_src}", builder_src) \
                       .replace("{{pid}}", "{pid}")
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))

    def _spawn_pair():
        with socket.socket() as s:  # free localhost coordinator port
            s.bind(("127.0.0.1", 0))
            port = str(s.getsockname()[1])
        procs = [subprocess.Popen(
            [sys.executable, "-c", code, str(pid), port, str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True) for pid in (0, 1)]
        return [p.communicate(timeout=420) for p in procs]

    # one retry: under heavy host load the coordination-service
    # handshake between worker startups can time out spuriously
    outs = _spawn_pair()
    if not all(f"DIST2-OK {pid}" in out for pid, (out, _) in enumerate(outs)):
        outs = _spawn_pair()
    if any("Multiprocess computations aren't implemented on the CPU "
           "backend" in err for _, err in outs):
        pytest.skip("this jaxlib's CPU backend has no cross-process "
                    "collectives; the DCN path needs TPU or a "
                    "multiprocess-capable CPU build")
    for pid, (out, err) in enumerate(outs):
        assert f"DIST2-OK {pid}" in out, (pid, out[-500:], err[-3000:])

    r0 = np.load(tmp_path / "proc0.npz")
    r1 = np.load(tmp_path / "proc1.npz")
    # both processes hold the identical replicated global result
    np.testing.assert_array_equal(r0["x"], r1["x"])
    np.testing.assert_array_equal(r0["chi2"], r1["chi2"])
    np.testing.assert_array_equal(r0["xg"], r1["xg"])
    # and it matches the single-process fit bit-for-bit-ish (same
    # program, different mesh layout -> tiny reduction-order noise)
    np.testing.assert_allclose(r0["x"], np.asarray(x_ref),
                               rtol=1e-10, atol=0)
    np.testing.assert_allclose(r0["chi2"], np.asarray(chi2_ref), rtol=1e-8)
    np.testing.assert_allclose(r0["cov"], np.asarray(cov_ref), rtol=1e-6,
                               atol=1e-300)
    # distributed full-noise GLS (Woodbury across processes) agrees too
    np.testing.assert_allclose(r0["xg"], np.asarray(xg_ref),
                               rtol=1e-8, atol=1e-12)
    np.testing.assert_allclose(r0["chi2g"], np.asarray(chi2g_ref),
                               rtol=1e-6)


def test_checkpointed_pta_fit_resumes(tmp_path):
    """A chunked, snapshotted PTA fit reproduces the direct fit, and a
    fresh batch resumes from the snapshot instead of restarting."""
    from pint_tpu.checkpoint import checkpointed_pta_fit

    models, toas_list, _ = _batch(3)
    direct = PTABatch([copy.deepcopy(m) for m in models], toas_list)
    x_ref, chi2_ref, _ = direct.wls_fit(maxiter=3)

    pta = PTABatch([copy.deepcopy(m) for m in models], toas_list)
    x, chi2, cov = checkpointed_pta_fit(pta, str(tmp_path), every=1,
                                        maxiter=3, method="wls")
    np.testing.assert_allclose(np.asarray(x), np.asarray(x_ref),
                               rtol=0, atol=1e-12)
    assert cov is not None
    # fresh batch + exhausted snapshot: returns saved state, no refit
    pta2 = PTABatch([copy.deepcopy(m) for m in models], toas_list)
    x2, chi2_2, cov2 = checkpointed_pta_fit(pta2, str(tmp_path), every=1,
                                            maxiter=3, method="wls")
    assert cov2 is None
    np.testing.assert_allclose(np.asarray(x2), np.asarray(x), rtol=0,
                               atol=0)
    # and extending maxiter continues FROM the snapshot
    x3, chi2_3, cov3 = checkpointed_pta_fit(pta2, str(tmp_path), every=1,
                                            maxiter=4, method="wls")
    assert cov3 is not None and np.isfinite(np.asarray(chi2_3)).all()


def test_fleet_pow2_toa_bucketing():
    """toa_bucket="pow2" splits a same-structure ragged fleet into
    size buckets: less padding, identical per-pulsar results."""
    from pint_tpu.parallel import PTAFleet

    models, toas_list, _ = _batch(4, base_toas=30)
    # make the raggedness span a pow2 boundary: pulsar 3 gets ~600 TOAs
    big_m = copy.deepcopy(models[0])
    rng = np.random.default_rng(9)
    mjds = np.sort(rng.uniform(55000, 56000, 600))
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    big_t = make_fake_toas_fromMJDs(
        mjds, big_m, error_us=1.0,
        freq_mhz=np.where(np.arange(600) % 2, 1400.0, 800.0), obs="gbt",
        add_noise=True, seed=77)
    models = [copy.deepcopy(m) for m in models] + [big_m]
    toas_list = toas_list + [big_t]

    flat = PTAFleet([copy.deepcopy(m) for m in models], toas_list)
    assert len(flat.batches) == 1  # same structure: one batch, max-padded
    fleet = PTAFleet([copy.deepcopy(m) for m in models], toas_list,
                     toa_bucket="pow2")
    assert len(fleet.batches) == 2  # 256-bucket + 1024-bucket
    assert fleet.padding_ratio < flat.padding_ratio
    x_flat, chi2_flat, _ = flat.fit(method="wls", maxiter=3)
    x_b, chi2_b, _ = fleet.fit(method="wls", maxiter=3)
    for i in range(len(models)):
        np.testing.assert_allclose(x_b[i], x_flat[i], rtol=1e-8)


def test_pta_pack_state_roundtrip():
    """from_packed(pack_state()) reproduces the fit bit-for-bit —
    the packed-fleet cache the full-scale bench stage relies on."""
    models, toas_list, _ = _batch(3)
    pta = PTABatch([copy.deepcopy(m) for m in models], toas_list)
    x_ref, chi2_ref, cov_ref = pta.wls_fit(maxiter=2)
    state = pta.pack_state()
    # simulate a disk round-trip
    import pickle

    state = pickle.loads(pickle.dumps(state))
    pta2 = PTABatch.from_packed(models[0], state)
    assert pta2.free_map() == pta.free_map()
    x2, chi2_2, cov2 = pta2.wls_fit(maxiter=2)
    np.testing.assert_array_equal(np.asarray(x2), np.asarray(x_ref))
    np.testing.assert_array_equal(np.asarray(chi2_2), np.asarray(chi2_ref))
    np.testing.assert_array_equal(np.asarray(cov2), np.asarray(cov_ref))


def test_fleet_splitk_optimal_bucketing():
    """toa_bucket="split2": the DP threshold split gives <=2 programs
    per structure, beats one-program padding, and returns per-pulsar
    results identical to the unbucketed fleet. The DP itself is
    checked against brute force on random count sets."""
    from pint_tpu.parallel import PTAFleet

    # DP vs brute force over all single thresholds (k=2)
    rng = np.random.default_rng(5)
    for _ in range(20):
        counts = rng.integers(50, 5000, rng.integers(3, 12))
        c = np.sort(counts)
        n = len(c)
        bounds = PTAFleet.optimal_split_bounds(counts, 2)
        area = sum(len([x for x in c if (x <= bounds[0] if j == 0 else
                                         bounds[0] < x <= bounds[-1])])
                   * bounds[min(j, len(bounds) - 1)]
                   for j in range(len(bounds)))
        brute = min((int(np.sum(np.where(c <= c[k - 1], c[k - 1], c[-1])))
                     if k else n * int(c[-1]))
                    for k in range(n))
        assert area == brute, (counts, bounds, area, brute)

    models, toas_list, _ = _batch(4, base_toas=30)
    big_m = copy.deepcopy(models[0])
    mjds = np.sort(rng.uniform(55000, 56000, 600))
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    big_t = make_fake_toas_fromMJDs(
        mjds, big_m, error_us=1.0,
        freq_mhz=np.where(np.arange(600) % 2, 1400.0, 800.0), obs="gbt",
        add_noise=True, seed=78)
    models = [copy.deepcopy(m) for m in models] + [big_m]
    toas_list = toas_list + [big_t]

    flat = PTAFleet([copy.deepcopy(m) for m in models], toas_list)
    fleet = PTAFleet([copy.deepcopy(m) for m in models], toas_list,
                     toa_bucket="split2")
    assert len(fleet.batches) == 2
    assert fleet.padding_ratio < flat.padding_ratio
    x_flat, chi2_flat, _ = flat.fit(method="wls", maxiter=3)
    x_b, chi2_b, _ = fleet.fit(method="wls", maxiter=3)
    for i in range(len(models)):
        np.testing.assert_allclose(x_b[i], x_flat[i], rtol=1e-8)

    import pytest

    with pytest.raises(ValueError, match="split"):
        PTAFleet(models, toas_list, toa_bucket="split0")
    with pytest.raises(ValueError, match="toa_bucket"):
        PTAFleet(models, toas_list, toa_bucket="banana")
