"""Multi-pulsar batch + sharding tests on the virtual 8-device CPU mesh.

(the reference has no distributed tests — SURVEY.md section 4; this is
the TPU-era equivalent: vmapped PTA fits and TOA-axis shard_map on
xla_force_host_platform_device_count=8.)
"""

import copy
import warnings

import numpy as np

warnings.simplefilter("ignore")

import jax

from pint_tpu.models import get_model
from pint_tpu.parallel import PTABatch, make_mesh
from pint_tpu.simulation import make_fake_toas_fromMJDs


def _batch(n_psr=4, base_toas=30, perturb=True):
    rng = np.random.default_rng(0)
    models, toas_list, truths = [], [], []
    for i in range(n_psr):
        par = (f"PSR FK{i}\nRAJ 1{i % 10}:00:00.0\nDECJ {5 + i}:30:00.0\n"
               f"F0 {200 + 10 * i}.5 1\nF1 -{3 + i}e-16 1\nPEPOCH 55500\n"
               f"DM {10 + i}.5 1\n")
        m = get_model(par)
        n = base_toas + 5 * i  # ragged counts exercise padding
        mjds = np.sort(rng.uniform(55000, 56000, n))
        freqs = np.where(np.arange(n) % 2, 1400.0, 800.0)
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                    obs="gbt", add_noise=True, seed=i)
        truths.append(m.F0.value)
        if perturb:
            m = copy.deepcopy(m)
            m.F0.value += 1e-9
            m.DM.value += 1e-4
        models.append(m)
        toas_list.append(t)
    return models, toas_list, truths


def test_pta_batch_matches_single_pulsar_fit():
    """The vmapped batch fit must agree with per-pulsar WLSFitter."""
    from pint_tpu.fitter import WLSFitter

    models, toas_list, truths = _batch(3)
    pta = PTABatch(models, toas_list)
    x, chi2, cov = pta.wls_fit(maxiter=3)
    x = np.asarray(x)
    for i in range(3):
        f = WLSFitter(toas_list[i], copy.deepcopy(models[i]))
        f.fit_toas(maxiter=3)
        assert abs(x[i, 0] - f.model.F0.value) < 1e-3 * f.model.F0.uncertainty
        assert abs(x[i, 2] - f.model.DM.value) < 1e-3 * f.model.DM.uncertainty


def test_pta_batch_sharded_over_mesh():
    assert len(jax.devices()) >= 8
    models, toas_list, truths = _batch(8)
    mesh = make_mesh(8)
    pta = PTABatch(models, toas_list, mesh=mesh)
    x, chi2, cov = pta.wls_fit(maxiter=3)
    chi2 = np.asarray(chi2)
    assert np.isfinite(chi2).all()
    dofs = pta.n_toas - len(pta.free_map()) - 1
    assert (chi2 / dofs < 2.5).all()
    # recovered F0 within 5 sigma of truth
    x = np.asarray(x)
    cov = np.asarray(cov)
    for i in range(8):
        assert abs(x[i, 0] - truths[i]) < 5 * np.sqrt(cov[i, 0, 0])


def test_residuals_padding_inert():
    """Padded TOAs must not influence the fit."""
    models, toas_list, _ = _batch(2, base_toas=25)  # 25 and 30 toas
    pta = PTABatch(models, toas_list)
    r, mask = pta.time_residuals()
    r = np.asarray(r)
    assert mask.shape == r.shape
    assert mask[0].sum() == 25 and mask[1].sum() == 30
    assert np.isfinite(r[mask]).all()


def test_toa_axis_shard_map():
    from pint_tpu.parallel.toa_shard import sharded_chi2
    from jax.sharding import Mesh

    models, toas_list, _ = _batch(1, base_toas=64, perturb=False)
    model, toas = models[0], toas_list[0]
    prepared = model.prepare(toas)
    mesh = Mesh(np.array(jax.devices()[:8]), axis_names=("toa",))
    static = {k: prepared.prep[k] for k in ("planet_shapiro", "orb_mode_fb")
              if k in prepared.prep}
    prep = {k: v for k, v in prepared.prep.items()
            if k not in ("T_ld", "pepoch_day", "pepoch_sec") and k not in static}
    chi2_sharded = float(sharded_chi2(model, static, mesh, prepared.params0,
                                      prepared.batch, prep))
    # compare against the unsharded residual chi2
    from pint_tpu.residuals import Residuals

    chi2_ref = Residuals(toas, model, prepared=prepared).chi2
    assert abs(chi2_sharded - chi2_ref) < 1e-6 * max(1.0, chi2_ref)
