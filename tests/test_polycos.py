"""Polycos tests (reference test pattern: tests/test_polycos.py —
generate from a model, verify phase prediction against the full model,
round-trip through the TEMPO file format)."""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.polycos import Polycos, _model_abs_phase

PAR = """
PSR POLYTEST
RAJ 05:00:00.0
DECJ 20:00:00.0
F0 29.946923 1
F1 -3.77535e-10 1
PEPOCH 55555
DM 56.77
"""


@pytest.fixture(scope="module")
def model():
    return get_model(PAR)


@pytest.fixture(scope="module")
def polycos(model):
    return Polycos.generate_polycos(model, 55555.0, 55555.25, obs="gbt",
                                    segLength=60, ncoeff=12)


def test_segment_count(polycos):
    # 0.25 d / 60 min = 6 segments
    assert len(polycos.entries) == 6


def test_phase_prediction_matches_model(model, polycos):
    rng = np.random.default_rng(3)
    mjds = 55555.0 + 0.25 * rng.random(16)
    pi_ref, pf_ref = _model_abs_phase(model, mjds, "gbt", 1400.0)
    pi_pc, pf_pc = polycos.eval_abs_phase(mjds)
    dphi = (pi_pc - pi_ref).astype(float) + (pf_pc - pf_ref)
    # reference targets ~1e-8 cycles; Chebyshev fit over 60-min segments
    assert np.max(np.abs(dphi)) < 1e-7


def test_spin_freq_close_to_f0(model, polycos):
    mjds = np.array([55555.05, 55555.15])
    f = polycos.eval_spin_freq(mjds)
    # topocentric frequency differs from F0 by Doppler ~1e-4 fractional
    assert np.allclose(f, model.F0.value, rtol=1e-4)
    assert not np.allclose(f, model.F0.value, rtol=1e-9)


def test_polyco_file_roundtrip(tmp_path, polycos):
    path = tmp_path / "polyco.dat"
    polycos.write_polyco_file(path)
    back = Polycos.read_polyco_file(path)
    assert len(back.entries) == len(polycos.entries)
    mjds = np.array([55555.03, 55555.21])
    pi1, pf1 = polycos.eval_abs_phase(mjds)
    pi2, pf2 = back.eval_abs_phase(mjds)
    dphi = (pi2 - pi1).astype(float) + (pf2 - pf1)
    # rphase stored to 1e-6 cycles in the text format
    assert np.max(np.abs(dphi)) < 2e-6


def test_out_of_span_raises(polycos):
    with pytest.raises(ValueError):
        polycos.eval_abs_phase([55560.0])


def test_negative_rphase_roundtrip(tmp_path):
    # phases before the anchor are negative: the signed-decimal RPHASE
    # field must round-trip (external readers parse it as one number)
    from pint_tpu.polycos import PolycoEntry, Polycos

    e = PolycoEntry(55000.0, 60, -12345, 0.6789, 30.0, 3,
                    [0.0, 1e-8, 1e-12])
    pc = Polycos([e])
    path = tmp_path / "neg.dat"
    pc.write_polyco_file(path)
    # the written field must equal the true signed value
    line2 = open(path).read().splitlines()[1].split()[0]
    assert float(line2) == pytest.approx(-12345 + 0.6789, abs=1e-6)
    back = Polycos.read_polyco_file(path)
    b = back.entries[0]
    got = b.rphase_int + b.rphase_frac
    assert got == pytest.approx(-12345 + 0.6789, abs=1e-6)


def test_eval_phase_wrapped(polycos):
    ph = polycos.eval_phase(np.linspace(55555.01, 55555.24, 10))
    assert np.all(np.abs(ph) <= 0.5)
