"""Shape-plan optimizer (parallel/shapeplan.py) and the segment-packed
GLS path it drives: planner geometry properties (exact coverage,
alignment, pack/budget limits, the 670k padding target), packed-vs-
per-lane GLS agreement on a 68-pulsar fixture, segment-mask isolation,
fault-injection parity, pack-state round-trips, the masked segment-sum
Gram kernel (kernels/seggram.py), the serve layer's planned width
ladder, and the pure precision-verdict rule extracted from
_resolve_precision."""

import os
import sys
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.parallel import PTABatch, PTAFleet
from pint_tpu.parallel.pta import fleet_aot_compile  # noqa: F401
from pint_tpu.parallel.shapeplan import (align_up, ladder_width,
                                         plan_shapes, pow2_width,
                                         ShapePlan)
from pint_tpu.resilience import FaultPoint, inject

from test_fleet_pipeline import _noise_pulsars

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- planner geometry (pure host) ------------------------------------


def _ragged_counts(n_psr=68, total=670_000, seed=7):
    """The bench's deterministic NANOGrav-15yr-like ragged counts
    (bench.py::_ragged_counts), reproduced here so the planner's
    full-scale acceptance property is tested without importing the
    bench module."""
    rng = np.random.default_rng(seed)
    c = rng.lognormal(np.log(8000.0), 0.9, n_psr)
    for _ in range(3):
        c = np.clip(c * (total / c.sum()), 600, 30000)
    return np.sort(c.astype(int))[::-1]


def test_align_and_ladder_helpers():
    assert align_up(1, 256) == 256
    assert align_up(256, 256) == 256
    assert align_up(257, 256) == 512
    assert pow2_width(300, floor=256) == 512
    assert pow2_width(10, floor=256) == 256
    assert ladder_width(100, (128, 512)) == 128
    assert ladder_width(200, (128, 512)) == 512
    # above the ladder: pow2 fallback
    assert ladder_width(600, (128, 512)) == 1024


def test_plan_covers_every_pulsar_exactly_once():
    counts = [7, 900, 33, 33, 120, 5000, 64, 8]
    plan = plan_shapes(counts, quantum=32, max_pack=4,
                       compile_budget=3, min_width=64)
    assert sorted(plan.indices()) == list(range(len(counts)))
    # each segment's width fits its pulsar and respects the quantum
    for b in plan.buckets:
        for r in b.rows:
            assert sum(s.width for s in r.segments) == b.width
            for s in r.segments:
                assert s.width >= s.n_toas
                assert s.n_toas == counts[s.index]
            # alignment: every segment except the tail-absorbing last
            # one is an exact quantum multiple
            for s in r.segments[:-1]:
                assert s.width % 32 == 0


def test_plan_respects_max_pack_and_budget():
    counts = [10] * 40
    plan = plan_shapes(counts, quantum=16, max_pack=3,
                       compile_budget=2, min_width=48)
    assert plan.n_programs <= 2
    for b in plan.buckets:
        for r in b.rows:
            assert len(r.segments) <= 3
    # max_pack=1 degenerates to one pulsar per row
    plan1 = plan_shapes(counts, quantum=16, max_pack=1,
                        compile_budget=2, min_width=16)
    for b in plan1.buckets:
        for r in b.rows:
            assert len(r.segments) == 1
    assert sorted(plan1.indices()) == list(range(40))


def test_plan_signature_stable_and_geometry_sensitive():
    counts = [100, 200, 300]
    a = plan_shapes(counts, quantum=32, compile_budget=2, min_width=64)
    b = plan_shapes(counts, quantum=32, compile_budget=2, min_width=64)
    assert a.signature() == b.signature()
    c = plan_shapes(counts, quantum=64, compile_budget=2, min_width=64)
    assert a.signature() != c.signature()
    assert a.signature().startswith("plan-")


def test_plan_full_scale_meets_padding_and_compile_targets():
    """The fused-pipeline acceptance numbers, as a host-only property:
    the 670k bench workload plans to <= 4 programs at <= 1.05 padding
    (down from x1.092 before the quantum-ladder search; the pow2
    ladder needs 6 programs for x1.46 on the same counts)."""
    counts = _ragged_counts()
    plan = plan_shapes([int(c) for c in counts])
    assert plan.n_programs <= 4
    assert plan.padding_ratio <= 1.05
    assert sorted(plan.indices()) == list(range(len(counts)))
    pow2_area = sum(pow2_width(int(c)) for c in counts)
    assert pow2_area / counts.sum() > plan.padding_ratio


def test_plan_quantum_ladder_ragged_tail():
    """Finer-quantum ladder properties: (a) on a tail-heavy fixture
    where every pulsar sits just above a coarse-quantum multiple, the
    ladder picks a finer alignment and roughly halves the padding;
    (b) on random ragged counts the ladder never does worse than
    planning at the requested quantum alone; (c) geometry invariants
    (exact coverage, requested-quantum signature stability) hold for
    whatever quantum the search picks."""
    from pint_tpu.parallel.shapeplan import _plan_for_quantum

    # (a) constructed ragged tail: 260 TOAs is 4 over a 256 multiple,
    # so coarse-only padding is x1.97 while the 32-quantum ladder
    # entry fits a 288-wide row at x1.11
    counts = [260] * 12
    plan = plan_shapes(counts, quantum=256, max_pack=1,
                       compile_budget=2, min_width=32)
    _, coarse = _plan_for_quantum(counts, 256, 1, 2, 32, 1.05)
    coarse_ratio = sum(b.padded_area for b in coarse) / sum(counts)
    assert coarse_ratio > 1.9
    assert plan.padding_ratio < 1.2
    assert plan.quantum == 256  # signature keeps the REQUESTED quantum
    # (b)+(c) random ragged tails: ladder <= coarse-only, coverage
    # exact, widths aligned to some ladder quantum
    for seed in range(5):
        rng = np.random.default_rng(seed)
        c = [int(x) for x in rng.integers(40, 4000, 24)]
        p = plan_shapes(c, quantum=256, max_pack=4, compile_budget=3,
                        min_width=64)
        _, cb = _plan_for_quantum(c, 256, 4, 3, 64, 1.05)
        coarse_ratio = sum(b.padded_area for b in cb) / sum(c)
        assert p.padding_ratio <= coarse_ratio + 1e-12, seed
        assert sorted(p.indices()) == list(range(24))
        assert all(any(w % q == 0 for q in (256, 128, 96, 64, 32))
                   for w in p.widths)


def test_plan_invalid_inputs():
    with pytest.raises(ValueError):
        plan_shapes([])
    with pytest.raises(ValueError):
        plan_shapes([0, 10])
    with pytest.raises(ValueError):
        plan_shapes([10], compile_budget=0)


def test_bucket_renumbered_matches_indices_order():
    plan = plan_shapes([5, 6, 7, 8, 9], quantum=4, max_pack=3,
                       compile_budget=1, min_width=16)
    for b in plan.buckets:
        rn = b.renumbered()
        flat = [s.index for r in rn.rows for s in r.segments]
        assert flat == list(range(len(flat)))
        # geometry unchanged
        assert [s.width for r in rn.rows for s in r.segments] == \
            [s.width for r in b.rows for s in r.segments]


# -- packed GLS correctness ------------------------------------------


@pytest.fixture(scope="module")
def packed_fixture():
    """Four noise pulsars packed 3-to-a-row by the planner, plus the
    packed batch and the per-pulsar order it was built in."""
    models, toas = _noise_pulsars(4)
    counts = [len(t) for t in toas]
    plan = plan_shapes(counts, quantum=16, max_pack=3,
                       compile_budget=1, min_width=128)
    assert len(plan.buckets) == 1
    bucket = plan.buckets[0]
    assert max(len(r.segments) for r in bucket.rows) > 1  # really packs
    order = bucket.indices()
    pb = PTABatch([models[i] for i in order], [toas[i] for i in order],
                  plan=bucket.renumbered())
    return models, toas, order, pb


def test_packed_matches_sequential_per_pulsar(packed_fixture):
    models, toas, order, pb = packed_fixture
    xp, chip, covp = (np.asarray(a) for a in pb.gls_fit(maxiter=2))
    for lane, i in enumerate(order):
        b1 = PTABatch([models[i]], [toas[i]])
        x1, c1, v1 = b1.gls_fit(maxiter=2)
        x1 = np.asarray(x1)[0]
        rel = np.max(np.abs(xp[lane] - x1)
                     / np.maximum(np.abs(x1), 1e-300))
        assert rel <= 1e-15, (i, rel)
        relchi = abs(float(chip[lane]) - float(np.asarray(c1)[0])) \
            / abs(float(np.asarray(c1)[0]))
        assert relchi <= 1e-12


def test_packed_segment_masks_do_not_leak(packed_fixture):
    """Corrupting one pulsar's TOA uncertainties must leave every
    co-packed pulsar's parameters BITWISE unchanged: the jnp.where
    owner masks and segment sums make cross-segment contamination
    structurally impossible, not just small."""
    models, toas, order, pb = packed_fixture
    x_ref = np.asarray(pb.gls_fit(maxiter=2)[0])
    victim = order[0]
    import copy

    toas2 = [copy.deepcopy(t) for t in toas]
    toas2[victim].error_us = np.asarray(toas2[victim].error_us) * 10.0
    plan = plan_shapes([len(t) for t in toas], quantum=16, max_pack=3,
                       compile_budget=1, min_width=128)
    bucket = plan.buckets[0]
    pb2 = PTABatch([models[i] for i in order],
                   [toas2[i] for i in order],
                   plan=bucket.renumbered())
    x2 = np.asarray(pb2.gls_fit(maxiter=2)[0])
    for lane, i in enumerate(order):
        if i == victim:
            assert not np.array_equal(x2[lane], x_ref[lane])
        else:
            assert np.array_equal(x2[lane], x_ref[lane]), i


def test_packed_scope_guards(packed_fixture):
    models, toas, order, pb = packed_fixture
    with pytest.raises(RuntimeError):
        pb.wls_fit(maxiter=2)
    # precision="mixed" needs the fused kernel program: the classic
    # (fused=False) packed path stays f64-only
    with pytest.raises(ValueError):
        pb.gls_fit(maxiter=2, precision="mixed", fused=False)
    with pytest.raises(RuntimeError):
        pb.time_residuals()
    with pytest.raises(RuntimeError):
        pb.phases()
    # auto resolves to f64 without a probe on the classic packed path
    assert pb._resolve_precision("auto", fused=False) == "f64"


def test_packed_mixed_fused_matches_sequential(packed_fixture):
    """The fused mixed path — f32 in-kernel block Gram as the eigh
    preconditioner, exact f64 RHS, f64 iterative refinement — must
    still land within 1e-15 of the sequential per-pulsar f64 fit."""
    models, toas, order, pb = packed_fixture
    xm = np.asarray(pb.gls_fit(maxiter=2, precision="mixed")[0])
    for lane, i in enumerate(order):
        b1 = PTABatch([models[i]], [toas[i]])
        x1 = np.asarray(b1.gls_fit(maxiter=2)[0])[0]
        rel = np.max(np.abs(xm[lane] - x1)
                     / np.maximum(np.abs(x1), 1e-300))
        assert rel <= 1e-15, (i, rel)


def test_packed_classic_path_bitwise_matches_fused(packed_fixture):
    """fused=False keeps the pre-fused packed program as an unchanged
    f64 reference; the fused default's parameters must agree with it
    BITWISE (the hoisted noise build and whitening produce identical
    floats). chi2 regroups the rNr reduction inside the augmented
    Gram, so it may differ in the last ulp."""
    models, toas, order, pb = packed_fixture
    xf, cf, _ = pb.gls_fit(maxiter=2)
    xc, cc, _ = pb.gls_fit(maxiter=2, fused=False)
    assert np.array_equal(np.asarray(xf), np.asarray(xc))
    relc = np.max(np.abs(np.asarray(cf) - np.asarray(cc))
                  / np.abs(np.asarray(cc)))
    assert relc <= 1e-12


def test_packed_pack_state_round_trip(packed_fixture):
    models, toas, order, pb = packed_fixture
    x1, c1, _ = pb.gls_fit(maxiter=2)
    st = pb.pack_state()
    pb2 = PTABatch.from_packed(models[order[0]], st)
    assert pb2.n_pulsars == pb.n_pulsars
    x2, c2, _ = pb2.gls_fit(maxiter=2)
    assert np.array_equal(np.asarray(x1), np.asarray(x2))
    assert np.array_equal(np.asarray(c1), np.asarray(c2))
    # start-vector round trip through the packed slot layout
    pb2.set_start_vector(np.asarray(x2))
    x3 = np.asarray(pb2.gls_fit(maxiter=2)[0])
    assert np.all(np.isfinite(x3))


def test_plan_rejects_conflicting_kwargs(packed_fixture):
    models, toas, order, _ = packed_fixture
    plan = plan_shapes([len(t) for t in toas], quantum=16, max_pack=3,
                       compile_budget=1, min_width=128)
    bucket = plan.buckets[0].renumbered()
    with pytest.raises(ValueError):
        PTABatch([models[i] for i in order],
                 [toas[i] for i in order], plan=bucket, pad_toas=256)


# -- 68-pulsar planned fleet vs per-lane fleet -----------------------


@pytest.fixture(scope="module")
def fleet_68():
    return _noise_pulsars(68, n_epochs=8, per_epoch=3)


def test_fleet_plan_matches_per_lane_fleet_68(fleet_68):
    """The acceptance fixture: 68 ragged noise pulsars fit through
    the planner's packed layout agree with the per-pulsar-lane
    (structure-bucketed) fleet to <= 1e-15 relative."""
    models, toas = fleet_68
    ref = PTAFleet(models, toas)
    xr, cr, _ = ref.fit(maxiter=2)
    fleet = PTAFleet(models, toas, toa_bucket="plan", plan_quantum=32,
                     plan_max_pack=8, plan_compile_budget=2,
                     plan_min_width=128)
    assert fleet.padding_ratio < 1.5  # really packing, not padding out
    xp, cp, _ = fleet.fit(maxiter=2)
    for a, b in zip(xp, xr):
        a, b = np.asarray(a), np.asarray(b)
        rel = np.max(np.abs(a - b) / np.maximum(np.abs(b), 1e-300))
        assert rel <= 1e-15, rel
    relc = np.max(np.abs(np.asarray(cp) - np.asarray(cr))
                  / np.abs(np.asarray(cr)))
    assert relc <= 1e-12


def test_fleet_plan_pipelined_bitwise_and_fault_parity(fleet_68):
    models, toas = fleet_68
    models, toas = models[:6], toas[:6]
    fleet = PTAFleet(models, toas, toa_bucket="plan", plan_quantum=16,
                     plan_max_pack=3, plan_compile_budget=1,
                     plan_min_width=128)
    x1, c1, _ = fleet.fit(maxiter=2)
    fleet2 = PTAFleet(models, toas, toa_bucket="plan", plan_quantum=16,
                      plan_max_pack=3, plan_compile_budget=1,
                      plan_min_width=128, pipeline=True)
    x2, c2, _ = fleet2.fit(maxiter=2, pipeline=True)
    assert all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(x2, x1))
    assert np.array_equal(np.asarray(c2), np.asarray(c1))
    # solver_diverge on a packed fleet isolates the PER-PULSAR lane.
    # FFD packing reorders pulsars within the row-block, so the
    # injected packed lane maps back to SOME original index — exactly
    # one pulsar may diverge, and its vector must be restored finite.
    with inject(FaultPoint("solver_diverge", count=1,
                           payload={"lanes": [1]})):
        xd, cd, _ = fleet.fit(maxiter=2)
    assert len(fleet.diverged) == 1
    victim = fleet.diverged[0]
    assert np.all(np.isfinite(np.asarray(xd[victim])))
    for i, (a, b) in enumerate(zip(xd, x1)):
        if i != victim:
            assert np.array_equal(np.asarray(a), np.asarray(b))


def test_fleet_plan_mixed_fused_and_fault_parity(fleet_68):
    """Mixed precision through the fused packed program at fleet
    scope: <= 1e-15 against the per-lane f64 fleet, and a
    solver_diverge injection still isolates exactly one pulsar and
    restores it finite (the f64 refit fallback preserves the fused
    program choice)."""
    models, toas = fleet_68
    models, toas = models[:6], toas[:6]
    ref = PTAFleet(models, toas)
    xr, _, _ = ref.fit(maxiter=2)
    fleet = PTAFleet(models, toas, toa_bucket="plan", plan_quantum=16,
                     plan_max_pack=3, plan_compile_budget=1,
                     plan_min_width=128)
    xm, _, _ = fleet.fit(maxiter=2, precision="mixed")
    for a, b in zip(xm, xr):
        a, b = np.asarray(a), np.asarray(b)
        rel = np.max(np.abs(a - b) / np.maximum(np.abs(b), 1e-300))
        assert rel <= 1e-15, rel
    with inject(FaultPoint("solver_diverge", count=1,
                           payload={"lanes": [1]})):
        xd, _, _ = fleet.fit(maxiter=2, precision="mixed")
    assert len(fleet.diverged) == 1
    victim = fleet.diverged[0]
    assert np.all(np.isfinite(np.asarray(xd[victim])))
    for i, (a, b) in enumerate(zip(xd, xr)):
        if i != victim:
            a, b = np.asarray(a), np.asarray(b)
            rel = np.max(np.abs(a - b) / np.maximum(np.abs(b), 1e-300))
            assert rel <= 1e-15, (i, rel)


def test_fleet_plan_kwarg_validation(fleet_68):
    models, toas = fleet_68
    with pytest.raises(ValueError):
        PTAFleet(models[:2], toas[:2], toa_bucket="banana")


# -- masked segment-sum Gram kernel ----------------------------------


def test_segment_gram_jnp_matches_dense_reference():
    from pint_tpu.kernels.seggram import segment_gram, segment_gram_jnp

    rng = np.random.default_rng(3)
    n, k, block = 96, 7, 8
    x = rng.normal(size=(n, k))
    block_seg = np.repeat(np.arange(3), 4).astype(np.int32)  # 12 blocks
    out = np.asarray(segment_gram_jnp(x, block_seg, 3, block))
    ref = np.zeros((3, k, k))
    owner = np.repeat(block_seg, block)
    for s in range(3):
        xs = x[owner == s]
        ref[s] = xs.T @ xs
    assert np.allclose(out, ref, rtol=0, atol=1e-12)
    # the dispatcher's f64 path is the jnp path bit-for-bit
    out2 = np.asarray(segment_gram(x, block_seg, 3, block,
                                   precision="f64"))
    assert np.array_equal(out, out2)


def test_segment_gram_mixed_falls_back_off_tpu():
    """On CPU the Pallas TPU kernel is unavailable; precision="mixed"
    must silently fall back to the jnp path (f32 accumulate happens
    inside gls_gram upstream, not here)."""
    import jax

    from pint_tpu.kernels.seggram import segment_gram, segment_gram_jnp

    if jax.devices()[0].platform == "tpu":
        pytest.skip("fallback path is the non-TPU branch")
    rng = np.random.default_rng(4)
    x = rng.normal(size=(64, 5))
    block_seg = np.arange(8).astype(np.int32) % 2
    a = np.asarray(segment_gram(x, block_seg, 2, 8, precision="mixed"))
    b = np.asarray(segment_gram_jnp(x, block_seg, 2, 8))
    assert np.allclose(a, b, rtol=0, atol=1e-12)


# -- serve: planned width ladder -------------------------------------


def test_serve_planned_ladder_and_prewarm():
    from pint_tpu.serve import FitRequest, ServeEngine

    models, toas = _noise_pulsars(2)
    plan = plan_shapes([len(t) for t in toas], quantum=16, max_pack=1,
                       compile_budget=2, min_width=32)
    eng = ServeEngine(max_batch=2, plan=plan)
    n0 = len(toas[0])
    assert eng.batcher.bucket_for(n0) in plan.widths
    assert eng.batcher.bucket_for(10_000) == pow2_width(10_000)
    n = eng.prewarm_ladder(FitRequest(models[0], toas[0], maxiter=2))
    assert n == sum(1 for w in plan.widths if w >= n0)
    # exec keys carry the plan signature and a steady-state submit of
    # a prewarmed shape dispatches warm
    assert all(k[-1] == plan.signature() for k in eng.cache.keys())
    r0 = eng.submit(FitRequest(models[0], toas[0], maxiter=2))
    r1 = eng.submit(FitRequest(models[0], toas[0], maxiter=2))
    eng.drain()
    assert r0.status == "ok", (r0.status, r0.reason)
    assert not r0.telemetry["cold"]
    # served params match the offline batch path
    xb = np.asarray(PTABatch([models[0]], [toas[0]])
                    .gls_fit(maxiter=2)[0])[0]
    rel = np.max(np.abs(r0.value["x"] - xb)
                 / np.maximum(np.abs(xb), 1e-300))
    assert rel <= 1e-12


def test_serve_prewarm_ladder_requires_plan():
    from pint_tpu.serve import FitRequest, ServeEngine

    models, toas = _noise_pulsars(1)
    eng = ServeEngine(max_batch=2)
    with pytest.raises(ValueError):
        eng.prewarm_ladder(FitRequest(models[0], toas[0], maxiter=2))


# -- precision verdict (pure rule behind precision="auto") -----------


def test_precision_verdict_rule():
    """gls_mixed_speedup 0.768 on CPU is exactly this rule firing:
    mixed ran SLOWER than f64, so auto must keep f64. The rule is
    pure so the regression is testable without a device probe."""
    v = PTABatch._precision_verdict
    # measured mixed slowdown (the CPU case): f64 wins
    assert v({"f64": 1.0, "mixed": 1.3}, False) == "f64"
    # mixed strictly faster and healthy: mixed wins
    assert v({"f64": 1.0, "mixed": 0.7}, False) == "mixed"
    # ties go to f64 (equal speed never justifies the precision risk)
    assert v({"f64": 1.0, "mixed": 1.0}, False) == "f64"
    # a failed refinement diagnostic vetoes mixed even when faster
    assert v({"f64": 1.0, "mixed": 0.5}, True) == "f64"


# -- bench MFU plumbing ----------------------------------------------


def test_bench_peak_flops_table_and_override(monkeypatch):
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    monkeypatch.delenv("PINT_TPU_PEAK_FLOPS", raising=False)
    # the CPU entry exists, so CPU rounds report a real MFU number
    assert bench._peak_flops("cpu") and bench._peak_flops("cpu") > 0
    assert bench._mfu(1e9, 1.0, "cpu") is not None
    monkeypatch.setenv("PINT_TPU_PEAK_FLOPS", "2e12")
    assert bench._peak_flops("cpu") == 2e12
    assert bench._mfu(2e10, 1.0, "cpu") == pytest.approx(1.0)
    # unparseable override falls back to the table, never raises
    monkeypatch.setenv("PINT_TPU_PEAK_FLOPS", "fast")
    assert bench._peak_flops("tpu") == 1.97e14
