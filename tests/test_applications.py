"""Applications-layer tests: derived quantities, event statistics,
grid search.

(reference test patterns: tests/test_derived_quantities.py,
tests/test_eventstats.py, tests/test_gridutils.py.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu import derived_quantities as dq
from pint_tpu import eventstats
from pint_tpu.gridutils import grid_chisq, grid_chisq_derived
from pint_tpu.fitter import WLSFitter
from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_fromMJDs


# ---------------- derived quantities ----------------


def test_p_to_f_roundtrip():
    f, fd = dq.p_to_f(*dq.p_to_f(0.016, 1e-20))
    assert f == pytest.approx(0.016)
    assert fd == pytest.approx(1e-20)


def test_mass_function_j1909():
    # J1909-3744: Pb=1.533449 d, x=1.89799 ls -> f ~ 0.00312 Msun
    f = dq.mass_function(1.533449, 1.89799)
    assert f == pytest.approx(3.12e-3, rel=0.02)


def test_companion_mass_consistency():
    mc = dq.companion_mass(1.533449, 1.89799, sini=0.998, mp=1.45)
    # solving forward must reproduce the mass function
    f = dq.mass_funct2(1.45, mc, 0.998)
    assert f == pytest.approx(dq.mass_function(1.533449, 1.89799), rel=1e-10)
    assert 0.15 < mc < 0.30  # known ~0.21 Msun


def test_pulsar_mass_inverts_companion_mass():
    mc = dq.companion_mass(10.0, 5.0, sini=0.9, mp=1.6)
    mp = dq.pulsar_mass(10.0, 5.0, mc, 0.9)
    assert mp == pytest.approx(1.6, rel=1e-8)


def test_age_b_edot_crab_scale():
    # Crab-like: F0=29.946923, F1=-3.77535e-10
    f0, f1 = 29.946923, -3.77535e-10
    assert dq.pulsar_age(f0, f1) == pytest.approx(1256, rel=0.01)  # ~1.26 kyr
    assert dq.pulsar_B(f0, f1) == pytest.approx(3.78e12, rel=0.01)
    assert dq.pulsar_edot(f0, f1) == pytest.approx(4.46e31, rel=0.01)  # W


def test_gr_pk_params_hulse_taylor():
    # PSR B1913+16: Pb=0.322997 d, e=0.6171, mp=1.438, mc=1.390
    mp, mc, pb, e = 1.438, 1.390, 0.322997448918, 0.6171338
    assert dq.omdot(mp, mc, pb, e) == pytest.approx(4.226, rel=5e-3)  # deg/yr
    assert dq.gamma(mp, mc, pb, e) == pytest.approx(4.29e-3, rel=2e-2, abs=0)  # s
    assert dq.pbdot(mp, mc, pb, e) == pytest.approx(-2.40e-12, rel=2e-2, abs=0)


def test_shklovskii():
    # mu=10 mas/yr at 1 kpc: mu^2 d/c ~ 2.43e-19 1/s
    a = dq.shklovskii_factor(10.0, 1.0)
    assert a == pytest.approx(2.429e-19, rel=0.01, abs=0)


# ---------------- event statistics ----------------


def test_z2m_uniform_phases_small():
    rng = np.random.default_rng(0)
    ph = rng.random(4000)
    z = np.asarray(eventstats.z2m(ph, m=2))
    # uniform phases: each Z^2_k ~ chi2(2); sum of 2 ~ chi2(4), mean 4
    assert z[-1] < 20.0


def test_hm_detects_pulsation():
    rng = np.random.default_rng(1)
    # strongly peaked phases
    ph = (0.1 * rng.standard_normal(2000)) % 1.0
    h = float(eventstats.hm(ph))
    assert h > 100.0
    assert eventstats.sf_hm(h) < 1e-17
    assert eventstats.h2sig(h) > 5.0


def test_hmw_weights_reduce_to_hm():
    rng = np.random.default_rng(2)
    ph = rng.random(500)
    h1 = float(eventstats.hm(ph, m=5))
    h2 = float(eventstats.hmw(ph, np.ones(500), m=5))
    assert h1 == pytest.approx(h2, rel=1e-10)


def test_sf_z2m_matches_chi2():
    assert eventstats.sf_z2m(9.49, m=2) == pytest.approx(0.05, rel=0.01)


def test_sig2sigma():
    assert eventstats.sig2sigma(2.866e-7) == pytest.approx(5.0, rel=1e-3)


# ---------------- grid search ----------------


PAR = """
PSR GRIDTEST
RAJ 12:00:00.0
DECJ 10:00:00.0
F0 100.0 1
F1 -1e-14 1
PEPOCH 55000
DM 15.0 1
"""


@pytest.fixture(scope="module")
def grid_fitter():
    model = get_model(PAR)
    mjds = np.linspace(54500, 55500, 30)
    freqs = np.where(np.arange(30) % 2, 1400.0, 800.0)
    toas = make_fake_toas_fromMJDs(mjds, model, error_us=1.0, freq_mhz=freqs,
                                   obs="gbt", add_noise=True, seed=7)
    f = WLSFitter(toas, model)
    f.fit_toas()
    return f


def test_grid_chisq_minimum_at_fit(grid_fitter):
    f0_fit = grid_fitter.model.F0.value
    df = 5e-11
    vals = np.array([f0_fit - 40 * df, f0_fit - df, f0_fit,
                     f0_fit + df, f0_fit + 40 * df])
    chi2 = grid_chisq(grid_fitter, ["F0"], [vals])
    assert chi2.shape == (5,)
    # minimum at (or adjacent to) the fitted value; edges clearly worse
    assert np.argmin(chi2) in (1, 2, 3)
    assert chi2[0] > chi2[2] + 1.0
    assert chi2[4] > chi2[2] + 1.0


def test_grid_chisq_2d_shape(grid_fitter):
    f0 = grid_fitter.model.F0.value
    f1 = grid_fitter.model.F1.value
    chi2 = grid_chisq(grid_fitter, ["F0", "F1"],
                      [f0 + np.array([-1e-10, 0.0, 1e-10]),
                       f1 + np.array([-1e-16, 0.0, 1e-16])])
    assert chi2.shape == (3, 3)
    assert np.isfinite(chi2).all()
    # center should be the best (or tied)
    assert chi2[1, 1] <= chi2.max()


def test_grid_chisq_frozen_param(grid_fitter):
    # gridding over a frozen parameter must work (temporary unfreeze)
    import copy

    f = copy.deepcopy(grid_fitter)
    f.model.DM.frozen = True
    dm0 = f.model.DM.value
    chi2 = grid_chisq(f, ["DM"], [dm0 + np.array([-0.001, 0.0, 0.001])])
    assert chi2.shape == (3,)
    assert np.isfinite(chi2).all()
    assert f.model.DM.frozen  # restored


def test_h2sig_no_saturation():
    # beyond the f64 underflow floor the sigma must keep growing
    assert eventstats.h2sig(4000) > eventstats.h2sig(2000) > 38.0


def test_grid_chisq_derived(grid_fitter):
    # grid over period P, mapping to F0 = 1/P
    f0 = grid_fitter.model.F0.value
    p0 = 1.0 / f0
    ps = p0 + np.array([-1e-14, 0.0, 1e-14])
    chi2 = grid_chisq_derived(grid_fitter, ["F0"], [lambda p: 1.0 / p],
                              ["P"], [ps])
    assert chi2.shape == (3,)
    assert np.isfinite(chi2).all()


def test_correlated_noise_simulation():
    """add_correlated_noise realizes the modeled covariance: ECORR
    epoch blocks move together, red noise is time-correlated; fitted
    residual scatter grows beyond the white level (reference:
    simulation.py add_correlated_noise)."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TCN\nRAJ 03:00:00\nDECJ 20:00:00\nF0 250.0 1\nPEPOCH 55500\n"
           "DM 10.0\nECORR 50.0\n")  # bare mask: every TOA
    m = get_model(par)
    rng = np.random.default_rng(0)
    days = np.sort(rng.uniform(55000, 56000, 30))
    mjds = np.concatenate([days + k * 0.5 / 86400 for k in range(4)])
    mjds = np.sort(mjds)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True,
                                add_correlated_noise=True, seed=3)
    r = np.asarray(Residuals(t, m, subtract_mean=False,
                             track_mode="nearest").calc_time_resids()) * 1e6
    # per-epoch means dominated by the 50us ECORR draws, not the 1us white
    means = [r[4 * k:4 * k + 4].mean() for k in range(30)]
    assert np.std(means) > 10.0  # ECORR-scale epoch offsets present
    # within-epoch scatter stays white-noise sized
    within = np.concatenate([r[4 * k:4 * k + 4] - means[k] for k in range(30)])
    assert np.std(within) < 5.0


def test_get_derived_params():
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TDQ\nRAJ 04:00:00\nDECJ 30:00:00\nF0 200.0 1\nF1 -1e-15 1\n"
           "PEPOCH 55500\nDM 10.0\nPMRA 3.0\nPMDEC -4.0\n"
           "BINARY ELL1\nPB 2.0 1\nA1 3.0\nTASC 55500\nEPS1 0\nEPS2 0\n"
           "M2 0.3\nSINI 0.9\n")
    m = get_model(par)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 56000, 40), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=1)
    f = WLSFitter(t, m)
    f.fit_toas(maxiter=2)
    d = f.get_derived_params()
    assert d["P0"][0] == pytest.approx(1 / f.model.F0.value, rel=1e-12)
    assert d["P0"][1] is not None and d["P0"][1] > 0
    assert d["P1"][0] == pytest.approx(1e-15 / 200.0**2, rel=1e-2)
    assert d["PMTOT_masyr"][0] == pytest.approx(5.0, rel=1e-6)
    assert d["AGE_yr"][0] > 0 and d["BSURF_G"][0] > 0
    from pint_tpu.derived_quantities import mass_function

    assert d["MASSFN_Msun"][0] == pytest.approx(float(mass_function(2.0, 3.0)))
    assert d["MC_MIN_Msun"][0] < d["MC_MED_Msun"][0]
    assert 0.5 < d["MP_Msun"][0] < 3.0


def test_d_phase_d_toa_spin_frequency():
    """d_phase_d_toa: F0 + F1*dt exactly at the barycenter; Doppler-
    modulated at a ground site (reference: TimingModel.d_phase_d_toa)."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TDPDT\nRAJ 6:00:00\nDECJ 10:00:00\nF0 300.0 1\n"
           "F1 -1e-13 1\nPEPOCH 55000\nDM 0\n")
    m = get_model(par)
    mjds = np.linspace(54800, 55200, 12)
    t_bary = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                     obs="@", add_noise=False, iterations=0)
    f_bary = m.d_phase_d_toa(t_bary)
    dt = (np.asarray(t_bary.tdb.day) - 55000) * 86400.0 \
        + np.asarray(t_bary.tdb.sec)
    expect = 300.0 - 1e-13 * dt
    np.testing.assert_allclose(f_bary, expect, rtol=1e-10)

    t_gbt = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                    obs="gbt", add_noise=False, iterations=0)
    f_gbt = m.d_phase_d_toa(t_gbt)
    frac = f_gbt / expect - 1.0
    # Earth orbital Doppler: |v/c| <= ~1.1e-4, and it must actually vary
    assert np.abs(frac).max() < 1.2e-4
    assert np.abs(frac).max() > 1e-6
    assert frac.std() > 1e-6


def test_total_dm_sums_dispersion_components():
    """total_dm = DM Taylor series + DMX window + solar wind
    (reference: TimingModel.total_dm)."""
    import numpy as np

    from pint_tpu.constants import DMconst
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TTDM\nRAJ 6:00:00\nDECJ 10:00:00\nF0 300.0 1\n"
           "PEPOCH 55000\nDM 15.0 1\nDM1 0.002\nDMEPOCH 55000\n"
           "DMX_0001 0.01\nDMXR1_0001 55100\nDMXR2_0001 55200\n"
           "DMWXEPOCH 55000\nDMWXFREQ_0001 0.005\n"
           "DMWXSIN_0001 0.003\nDMWXCOS_0001 -0.001\n"
           "NE_SW 8.0\n")
    m = get_model(par)
    mjds = np.linspace(54900, 55300, 40)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False, iterations=0)
    dm = m.total_dm(t)
    # DM series by hand (Julian years since DMEPOCH)
    dt_yr = ((np.asarray(t.tdb.day) - 55000) * 86400.0
             + np.asarray(t.tdb.sec)) / (365.25 * 86400.0)
    expect = 15.0 + 0.002 * dt_yr
    win = (t.get_mjds() >= 55100) & (t.get_mjds() <= 55200)
    expect = expect + 0.01 * win
    # DMWaveX Fourier term (dt in days from DMWXEPOCH)
    dt_day = (np.asarray(t.tdb.day) - 55000) + np.asarray(t.tdb.sec) / 86400.0
    arg = 2 * np.pi * 0.005 * dt_day
    expect = expect + 0.003 * np.sin(arg) - 0.001 * np.cos(arg)
    # solar wind adds a small positive DM; subtract the no-SW model
    m0 = get_model(par.replace("NE_SW 8.0\n", ""))
    dm0 = m0.total_dm(t)
    np.testing.assert_allclose(dm0, expect, rtol=0, atol=1e-12)
    sw = dm - dm0
    assert (sw > 0).all() and sw.max() < 1.0  # ne_sw=8: small DM, varies
    assert sw.std() > 0
    # and the solar-wind DM matches the component's delay * f^2/DMconst
    # (delta of two full delay chains would add ~1e-13 s f64 noise from
    # the ~500 s Roemer term, so compare against the component directly)
    import jax.numpy as jnp

    pp = m.prepare(t)
    comp = m.components["SolarWindDispersion"]
    d_sw = np.asarray(comp.delay(pp.params0, pp.batch, pp.prep,
                                 jnp.zeros(len(t))))
    np.testing.assert_allclose(sw, d_sw * 1400.0**2 / DMconst, rtol=1e-9)


def test_total_dm_without_taylor_dm_line():
    """A par with solar wind / DMX but no DM line still reports its
    dispersion (review finding: the Taylor base is optional)."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TNODM\nRAJ 6:00:00\nDECJ 10:00:00\nF0 300.0 1\n"
           "PEPOCH 55000\nNE_SW 8.0\n")
    m = get_model(par)
    assert "DispersionDM" not in m.components
    t = make_fake_toas_fromMJDs(np.linspace(54900, 55100, 10), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=False, iterations=0)
    dm = m.total_dm(t)
    assert (dm > 0).all() and dm.max() < 1.0  # pure solar-wind DM


def test_d_phase_d_toa_doppler_matches_observatory_velocity():
    """The apparent-frequency modulation equals +(v_obs . n_hat)/c
    from the packed observatory velocities (delay = -r.n, so
    f/F0 - 1 = -d(delay)/dt = +v.n_hat) — a quantitative anchor for
    the full time-derivative chain, not just 'it varies'."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TDOPP\nRAJ 6:00:00\nDECJ 10:00:00\nF0 300.0 1\n"
           "PEPOCH 55000\nDM 0\n")
    m = get_model(par)
    mjds = np.linspace(54800, 55200, 16)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False, iterations=0)
    f = m.d_phase_d_toa(t)
    pp = m.prepare(t)
    astrom = m.components["AstrometryEquatorial"]
    n_hat = np.asarray(astrom.ssb_to_psb_xyz(pp.params0, pp.prep))
    v = np.asarray(pp.batch.obs_vel_ls)  # [ls/s] = fraction of c
    beta = np.sum(v * n_hat, axis=-1)
    frac = f / 300.0 - 1.0
    # pulse rate scales as d(t_emission)/d(t_arrival) = 1 - d(delay)/dt
    # = 1 + v.n/c (delay = -r.n) at this precision (no binary, no
    # dispersion drift)
    np.testing.assert_allclose(frac, beta, rtol=0, atol=2e-9)
    assert np.abs(beta).max() > 3e-5  # the anchor has real signal
