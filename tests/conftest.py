"""Test harness: force JAX onto CPU with 8 virtual devices.

Mirrors the reference's CI strategy of running everything on plain
hosts (reference: no GPU in CI); multi-chip sharding tests run on the
virtual CPU mesh exactly as the driver's dryrun does.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running integration tests excluded from the "
        "tier-1 gate (-m 'not slow')")

# The axon sitecustomize registers the TPU plugin at interpreter start and
# overrides JAX_PLATFORMS, so the env var alone is not enough: force CPU via
# config. Tests must run on CPU — the axon TPU's emulated f64 is ~47-bit and
# not correctly rounded, while tests validate exact-IEEE numerics.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


@pytest.fixture
def pallas_interpret():
    """Interpret-mode selector for Pallas kernel tests. On hosts
    without a real TPU (tier-1 CI) this is True: the kernel bodies run
    through the Pallas interpreter on CPU, so the exact kernel logic —
    whitening, MXU Gram accumulation, block padding — is exercised
    against the jnp references on every run, not just on hardware. On
    a real TPU it is False and the same tests compile the kernels for
    the chip."""
    return jax.devices()[0].platform != "tpu"


@pytest.fixture
def device_mesh():
    """N>=4 virtual-device CPU mesh for distributed-failure-domain
    tests. The XLA_FLAGS above normally guarantee 8 virtual devices,
    but a backend that ignores the flag (a real accelerator plugin
    that won the platform race, or a host pinned to 1 device) must
    skip rather than fail — device-loss tests are meaningless with
    nothing to steal onto."""
    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip(f"need >=4 devices for fleet failure-domain tests, "
                    f"have {len(devices)} ({devices[0].platform})")
    return devices
