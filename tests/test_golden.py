"""Golden-tensor regression tests.

(reference test pattern: SURVEY.md section 4 pattern 1 — the reference
pins physics against precomputed TEMPO/Tempo2 outputs. No external
golden files can exist offline, so these tensors are this framework's
own frozen outputs on the shipped NGC6440E example; they pin the FULL
pipeline (tim parse -> clock -> TDB -> ephemeris -> delays -> phase ->
residuals) against accidental physics drift across refactors. Any
intentional physics change must regenerate them (see the module
docstring of the generator block in git history) and justify the delta
in the commit message.
"""

import os
import warnings

import numpy as np

warnings.simplefilter("ignore")

HERE = os.path.dirname(os.path.abspath(__file__))
PAR = os.path.join(HERE, "..", "pint_tpu", "data", "examples", "NGC6440E.par")
TIM = os.path.join(HERE, "..", "pint_tpu", "data", "examples", "NGC6440E.tim")


def test_ngc6440e_prefit_residuals_frozen():
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.toa import get_TOAs

    golden = np.load(os.path.join(HERE, "golden",
                                  "ngc6440e_prefit_resids_us.npy"))
    m = get_model(PAR)
    t = get_TOAs(TIM, usepickle=False)
    r = Residuals(t, m)
    resid_us = np.asarray(r.calc_time_resids()) * 1e6
    assert resid_us.shape == golden.shape
    # 1 ns bar: any real physics change shows up orders of magnitude
    # above this; pure refactors must stay below it
    np.testing.assert_allclose(resid_us, golden, rtol=0, atol=1e-3)
    assert abs(r.rms_weighted() * 1e6 - 24.266879) < 1e-3


def test_ngc6440e_delays_frozen():
    from pint_tpu.models import get_model
    from pint_tpu.toa import get_TOAs

    golden = np.load(os.path.join(HERE, "golden", "ngc6440e_delays_s.npy"))
    m = get_model(PAR)
    t = get_TOAs(TIM, usepickle=False)
    d = np.asarray(m.delay(t))
    # delays are ~500 s (Roemer); 1 ns absolute agreement
    np.testing.assert_allclose(d, golden, rtol=0, atol=1e-9)


def test_b1855sim_binary_noise_frozen():
    """Golden pack #2: B1855-like ELL1H + DMX + EFAC/EQUAD/ECORR/red
    noise, simulated once and committed as par/tim — pins the binary +
    noise + multi-frequency pipeline (reference golden pattern:
    B1855+09 NANOGrav 9yv1 GLS files, SURVEY.md section 4 pattern 1)."""
    from pint_tpu.fitter import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.toa import get_TOAs

    par = os.path.join(HERE, "golden", "b1855sim.par")
    tim = os.path.join(HERE, "golden", "b1855sim.tim")
    golden = np.load(os.path.join(HERE, "golden",
                                  "b1855sim_prefit_resids_us.npy"))
    m = get_model(par)
    t = get_TOAs(tim, usepickle=False)
    assert len(t) == 300
    r = Residuals(t, m)
    resid_us = np.asarray(r.calc_time_resids()) * 1e6
    np.testing.assert_allclose(resid_us, golden, rtol=0, atol=1e-3)  # 1 ns
    assert abs(r.rms_weighted() * 1e6 - 1.044006) < 1e-4
    # GLS refit reproduces the frozen whitened chi2 (the full Woodbury
    # noise path: ECORR quantization + red-noise Fourier basis)
    f = GLSFitter(t, m)
    f.fit_toas(maxiter=2)
    assert abs(f.chi2_whitened - 207.511488) < 0.01
