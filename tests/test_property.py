"""Property-based round-trip tests (hypothesis).

(reference pattern: SURVEY.md section 4 item 9 — upstream uses
hypothesis for format/time-conversion round-trips.)
"""

import warnings

import numpy as np
from hypothesis import given, settings, strategies as st

warnings.simplefilter("ignore")

from pint_tpu.mjd import Epochs, format_mjd, parse_mjd_string
from pint_tpu import timescales as ts


@settings(max_examples=200, deadline=None)
@given(day=st.integers(41000, 69000),
       sec=st.floats(0.0, 86399.999, allow_nan=False))
def test_mjd_string_roundtrip(day, sec):
    s = format_mjd(day, sec, ndigits=16)
    d2, s2 = parse_mjd_string(s)
    err_s = abs((d2 - day) * 86400.0 + (s2 - sec))
    assert err_s < 1e-9  # < 1 ns through the string form


@settings(max_examples=100, deadline=None)
@given(day=st.integers(50000, 62000),
       sec=st.floats(0.0, 86399.0, allow_nan=False))
def test_utc_tai_roundtrip(day, sec):
    e = Epochs(np.array([day]), np.array([sec]), "utc")
    back = ts.tai_to_utc(ts.utc_to_tai(e))
    err = abs((back.day[0] - day) * 86400.0 + (back.sec[0] - sec))
    assert err < 1e-12


@settings(max_examples=100, deadline=None)
@given(day=st.integers(50000, 62000),
       sec=st.floats(0.0, 86399.0, allow_nan=False))
def test_tt_tdb_roundtrip(day, sec):
    e = Epochs(np.array([day]), np.array([sec]), "tt")
    back = ts.tdb_to_tt(ts.tt_to_tdb(e))
    err = abs((back.day[0] - day) * 86400.0 + (back.sec[0] - sec))
    assert err < 1e-10


@settings(max_examples=50, deadline=None)
@given(f0=st.floats(0.1, 1000.0, allow_nan=False),
       dm=st.floats(0.0, 500.0, allow_nan=False),
       f1=st.floats(-1e-12, 0.0, allow_nan=False))
def test_parfile_roundtrip_values(f0, dm, f1):
    from pint_tpu.models import get_model

    par = (f"PSR PROP\nRAJ 06:00:00.0\nDECJ 10:00:00.0\nF0 {f0!r} 1\n"
           f"F1 {f1!r} 1\nPEPOCH 55000\nDM {dm!r} 1\n")
    m = get_model(par)
    m2 = get_model(m.as_parfile())
    assert m2.F0.value == m.F0.value
    assert m2.F1.value == m.F1.value
    assert m2.DM.value == m.DM.value


# ---- native vs python tim parser agreement (property) ----

_flag_key = st.text(alphabet="abcdefghijklmnopqrstuvwxyz_", min_size=1,
                    max_size=8)
_flag_val = st.text(
    alphabet=st.characters(codec="utf-8", exclude_characters=" \t\r\n#",
                           min_codepoint=33, max_codepoint=383),
    min_size=1, max_size=10)


@settings(max_examples=60, deadline=None)
@given(st.lists(
    st.tuples(
        st.integers(40000, 69000),                     # day
        st.integers(0, 10**19 - 1),                    # frac digits value
        st.integers(1, 19),                            # n frac digits
        st.floats(100.0, 5000.0, allow_nan=False),     # freq
        st.floats(0.01, 100.0, allow_nan=False),       # err
        st.sampled_from(["gbt", "AO", "parkes", "@", "meerkat"]),
        st.dictionaries(_flag_key, _flag_val, max_size=3),
    ),
    min_size=1, max_size=12))
def test_native_parser_agrees_with_python(tmp_path_factory, rows):
    """For arbitrary FORMAT-1 content the C++ and Python parsers must
    produce identical columns, MJD splits, and flag dicts."""
    import pytest

    from pint_tpu import native
    from pint_tpu.toa import TOAs, _read_tim_native, read_tim_file

    if native.get_lib() is None:
        pytest.skip("native library unavailable: nothing to compare")
    lines = ["FORMAT 1"]
    for day, fracval, nd, freq, err, obs, flags in rows:
        frac = str(fracval % 10**nd).rjust(nd, "0")
        flagstr = " ".join(f"-{k} {v}" for k, v in flags.items())
        lines.append(f"t{day} {freq!r} {day}.{frac} {err!r} {obs} {flagstr}")
    p = tmp_path_factory.mktemp("prop") / "prop.tim"
    p.write_text("\n".join(lines) + "\n", encoding="utf-8")
    tn = _read_tim_native(str(p))
    toalist, _ = read_tim_file(str(p))
    tp = TOAs(toalist)
    if tn is None:
        # fallback is legitimate only for content the C++ parser
        # cannot mirror bit-for-bit (non-ASCII bytes: unicode
        # whitespace/digit semantics live in python)
        data = p.read_bytes()
        assert any(b >= 0x80 for b in data), \
            "native parser refused plain-ASCII content"
        return
    assert len(tn) == len(tp)
    assert np.array_equal(tn.day, tp.day)
    assert np.array_equal(tn.sec, tp.sec)
    assert np.array_equal(tn.freq_mhz, tp.freq_mhz)
    assert np.array_equal(tn.error_us, tp.error_us)
    assert list(tn.obs.astype(str)) == list(tp.obs.astype(str))
    assert tn.flags == tp.flags
