"""Property-based round-trip tests (hypothesis).

(reference pattern: SURVEY.md section 4 item 9 — upstream uses
hypothesis for format/time-conversion round-trips.)
"""

import warnings

import numpy as np
from hypothesis import given, settings, strategies as st

warnings.simplefilter("ignore")

from pint_tpu.mjd import Epochs, format_mjd, parse_mjd_string
from pint_tpu import timescales as ts


@settings(max_examples=200, deadline=None)
@given(day=st.integers(41000, 69000),
       sec=st.floats(0.0, 86399.999, allow_nan=False))
def test_mjd_string_roundtrip(day, sec):
    s = format_mjd(day, sec, ndigits=16)
    d2, s2 = parse_mjd_string(s)
    err_s = abs((d2 - day) * 86400.0 + (s2 - sec))
    assert err_s < 1e-9  # < 1 ns through the string form


@settings(max_examples=100, deadline=None)
@given(day=st.integers(50000, 62000),
       sec=st.floats(0.0, 86399.0, allow_nan=False))
def test_utc_tai_roundtrip(day, sec):
    e = Epochs(np.array([day]), np.array([sec]), "utc")
    back = ts.tai_to_utc(ts.utc_to_tai(e))
    err = abs((back.day[0] - day) * 86400.0 + (back.sec[0] - sec))
    assert err < 1e-12


@settings(max_examples=100, deadline=None)
@given(day=st.integers(50000, 62000),
       sec=st.floats(0.0, 86399.0, allow_nan=False))
def test_tt_tdb_roundtrip(day, sec):
    e = Epochs(np.array([day]), np.array([sec]), "tt")
    back = ts.tdb_to_tt(ts.tt_to_tdb(e))
    err = abs((back.day[0] - day) * 86400.0 + (back.sec[0] - sec))
    assert err < 1e-10


@settings(max_examples=50, deadline=None)
@given(f0=st.floats(0.1, 1000.0, allow_nan=False),
       dm=st.floats(0.0, 500.0, allow_nan=False),
       f1=st.floats(-1e-12, 0.0, allow_nan=False))
def test_parfile_roundtrip_values(f0, dm, f1):
    from pint_tpu.models import get_model

    par = (f"PSR PROP\nRAJ 06:00:00.0\nDECJ 10:00:00.0\nF0 {f0!r} 1\n"
           f"F1 {f1!r} 1\nPEPOCH 55000\nDM {dm!r} 1\n")
    m = get_model(par)
    m2 = get_model(m.as_parfile())
    assert m2.F0.value == m.F0.value
    assert m2.F1.value == m.F1.value
    assert m2.DM.value == m.DM.value
