"""Property-based round-trip tests (hypothesis).

(reference pattern: SURVEY.md section 4 item 9 — upstream uses
hypothesis for format/time-conversion round-trips.)
"""

import warnings

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package")
from hypothesis import given, settings, strategies as st

warnings.simplefilter("ignore")

from pint_tpu.mjd import Epochs, format_mjd, parse_mjd_string
from pint_tpu import timescales as ts


@settings(max_examples=200, deadline=None)
@given(day=st.integers(41000, 69000),
       sec=st.floats(0.0, 86399.999, allow_nan=False))
def test_mjd_string_roundtrip(day, sec):
    s = format_mjd(day, sec, ndigits=16)
    d2, s2 = parse_mjd_string(s)
    err_s = abs((d2 - day) * 86400.0 + (s2 - sec))
    assert err_s < 1e-9  # < 1 ns through the string form


@settings(max_examples=500, deadline=None)
@given(day=st.integers(50000, 62000),
       sec=st.floats(0.0, 86399.0, allow_nan=False))
def test_utc_tai_roundtrip(day, sec):
    """Bit-exact: the Epochs.lo compensation makes the +/-37 s shift
    exactly invertible. Before the compensation existed this failed at
    ~1 ulp for sec crossing the 2^16 binade (e.g. sec=65507.32: moving
    to sec+37 > 65536 halves the representable resolution — provably
    unfixable with a single-f64 seconds field)."""
    e = Epochs(np.array([day]), np.array([sec]), "utc")
    back = ts.tai_to_utc(ts.utc_to_tai(e))
    assert back.day[0] == day
    assert back.sec[0] + back.lo[0] == sec


@settings(max_examples=100, deadline=None)
@given(day=st.integers(50000, 62000),
       sec=st.floats(0.0, 86399.0, allow_nan=False))
def test_tt_tdb_roundtrip(day, sec):
    e = Epochs(np.array([day]), np.array([sec]), "tt")
    back = ts.tdb_to_tt(ts.tt_to_tdb(e))
    err = abs((back.day[0] - day) * 86400.0 + (back.sec[0] - sec))
    assert err < 1e-10


@settings(max_examples=50, deadline=None)
@given(f0=st.floats(0.1, 1000.0, allow_nan=False),
       dm=st.floats(0.0, 500.0, allow_nan=False),
       f1=st.floats(-1e-12, 0.0, allow_nan=False))
def test_parfile_roundtrip_values(f0, dm, f1):
    from pint_tpu.models import get_model

    par = (f"PSR PROP\nRAJ 06:00:00.0\nDECJ 10:00:00.0\nF0 {f0!r} 1\n"
           f"F1 {f1!r} 1\nPEPOCH 55000\nDM {dm!r} 1\n")
    m = get_model(par)
    m2 = get_model(m.as_parfile())
    assert m2.F0.value == m.F0.value
    assert m2.F1.value == m.F1.value
    assert m2.DM.value == m.DM.value


# ---- native vs python tim parser agreement (property) ----

_flag_key = st.text(alphabet="abcdefghijklmnopqrstuvwxyz_", min_size=1,
                    max_size=8)
_flag_val = st.text(
    alphabet=st.characters(codec="utf-8", exclude_characters=" \t\r\n#",
                           min_codepoint=33, max_codepoint=383),
    min_size=1, max_size=10)


@settings(max_examples=60, deadline=None)
@given(st.lists(
    st.tuples(
        st.integers(40000, 69000),                     # day
        st.integers(0, 10**19 - 1),                    # frac digits value
        st.integers(1, 19),                            # n frac digits
        st.floats(100.0, 5000.0, allow_nan=False),     # freq
        st.floats(0.01, 100.0, allow_nan=False),       # err
        st.sampled_from(["gbt", "AO", "parkes", "@", "meerkat"]),
        st.dictionaries(_flag_key, _flag_val, max_size=3),
    ),
    min_size=1, max_size=12))
def test_native_parser_agrees_with_python(tmp_path_factory, rows):
    """For arbitrary FORMAT-1 content the C++ and Python parsers must
    produce identical columns, MJD splits, and flag dicts."""
    import pytest

    from pint_tpu import native
    from pint_tpu.toa import TOAs, _read_tim_native, read_tim_file

    if native.get_lib() is None:
        pytest.skip("native library unavailable: nothing to compare")
    lines = ["FORMAT 1"]
    for day, fracval, nd, freq, err, obs, flags in rows:
        frac = str(fracval % 10**nd).rjust(nd, "0")
        flagstr = " ".join(f"-{k} {v}" for k, v in flags.items())
        lines.append(f"t{day} {freq!r} {day}.{frac} {err!r} {obs} {flagstr}")
    p = tmp_path_factory.mktemp("prop") / "prop.tim"
    p.write_text("\n".join(lines) + "\n", encoding="utf-8")
    tn = _read_tim_native(str(p))
    toalist, _ = read_tim_file(str(p))
    tp = TOAs(toalist)
    if tn is None:
        # fallback is legitimate only for content the C++ parser
        # cannot mirror bit-for-bit (non-ASCII bytes: unicode
        # whitespace/digit semantics live in python)
        data = p.read_bytes()
        assert any(b >= 0x80 for b in data), \
            "native parser refused plain-ASCII content"
        return
    assert len(tn) == len(tp)
    assert np.array_equal(tn.day, tp.day)
    assert np.array_equal(tn.sec, tp.sec)
    assert np.array_equal(tn.freq_mhz, tp.freq_mhz)
    assert np.array_equal(tn.error_us, tp.error_us)
    assert list(tn.obs.astype(str)) == list(tp.obs.astype(str))
    assert tn.flags == tp.flags


@given(
    st.floats(min_value=-1e8, max_value=1e8, allow_nan=False),
    st.floats(min_value=-1e-6, max_value=1e-6, allow_nan=False),
    st.floats(min_value=-1e8, max_value=1e8, allow_nan=False),
    st.floats(min_value=-1e-6, max_value=1e-6, allow_nan=False),
)
@settings(max_examples=200, deadline=None)
@pytest.mark.skipif(np.finfo(np.longdouble).machep == -52,
                    reason="np.longdouble is plain float64 here; no "
                           "extended-precision reference available")
def test_dd_add_mul_vs_longdouble(ah, al, bh, bl):
    """Double-double add/mul track x86 80-bit longdouble to well below
    f64 ulp of the result (the dd pair carries ~32 digits; longdouble
    ~19 — longdouble is the weaker link, so agreement to ~1e-17
    relative pins both)."""
    import numpy as np

    from pint_tpu import dd

    x = dd.from_2sum(ah, al)
    y = dd.from_2sum(bh, bl)
    xl = np.longdouble(ah) + np.longdouble(al)
    yl = np.longdouble(bh) + np.longdouble(bl)

    # bound relative to the INPUT magnitude: under catastrophic
    # cancellation longdouble's own representation error of the inputs
    # (its 64-bit mantissa) dominates, and dd is the more accurate side
    scale = max(abs(xl), abs(yl), np.longdouble(1e-30))

    s = dd.add(x, y)
    sl = xl + yl
    err = abs((np.longdouble(s.hi) + np.longdouble(s.lo)) - sl)
    assert err <= scale * np.longdouble(4e-17) + np.longdouble(1e-30)

    p = dd.mul(x, y)
    pl = xl * yl
    err = abs((np.longdouble(p.hi) + np.longdouble(p.lo)) - pl)
    assert err <= (abs(pl) + scale**2 * np.longdouble(1e-16)) \
        * np.longdouble(4e-17) + np.longdouble(1e-30)


@given(
    st.lists(
        st.tuples(
            st.floats(min_value=50001.0, max_value=59999.0,
                      allow_nan=False),
            st.floats(min_value=0.01, max_value=100.0, allow_nan=False),
            st.floats(min_value=100.0, max_value=5000.0, allow_nan=False),
            st.sampled_from(["gbt", "arecibo", "parkes", "@"]),
            st.sampled_from([{}, {"be": "GUPPI"}, {"f": "L-wide", "pta": "NG"}]),
        ),
        min_size=1, max_size=12),
)
@settings(max_examples=40, deadline=None)
def test_tim_write_read_roundtrip_random(tmp_path_factory, rows):
    """TOAs -> write_TOA_file -> get_TOAs preserves times (to ns),
    errors, frequencies, observatories, and flags."""
    import numpy as np

    from pint_tpu.toa import TOA, TOAs, get_TOAs

    toalist = [TOA(int(m), (m - int(m)) * 86400.0, error_us=e,
                   freq_mhz=f, obs=o, flags=dict(fl))
               for m, e, f, o, fl in rows]
    t = TOAs(toalist)
    d = tmp_path_factory.mktemp("timrt")
    path = str(d / "rt.tim")
    t.write_TOA_file(path)
    t2 = get_TOAs(path, usepickle=False)
    assert len(t2) == len(t)
    order = np.argsort(t.day * 86400.0 + t.sec)
    order2 = np.argsort(t2.day * 86400.0 + t2.sec)
    for i, j in zip(order, order2):
        dt = (t.day[i] - t2.day[j]) * 86400.0 + (t.sec[i] - t2.sec[j])
        assert abs(dt) < 1e-9
        assert abs(t.error_us[i] - t2.error_us[j]) < 1e-6
        assert abs(t.freq_mhz[i] - t2.freq_mhz[j]) < 1e-6
        assert t.obs[i] == t2.obs[j]
        for k, v in t.flags[i].items():
            if k == "simulated":
                continue
            assert t2.flags[j].get(k) == v, (k, v, t2.flags[j])


def test_random_model_configurations_fuzz():
    """Seeded fuzz over component combinations: every random par file
    must load, simulate, fit, and round-trip without crashing — the
    cross-product coverage no hand-written test enumerates."""
    import copy
    import itertools

    import numpy as np

    from pint_tpu.fitter import DownhillWLSFitter, GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    rng = np.random.default_rng(42)
    binaries = [None,
                "BINARY ELL1\nPB 5.7\nA1 3.36\nTASC 55301\n"
                "EPS1 1e-5 1\nEPS2 -8e-6\n",
                "BINARY DD\nPB 12.3\nA1 9.2\nT0 55300\nECC 0.17 1\nOM 70\n",
                "BINARY BT\nPB 44.1\nA1 31.0\nT0 55290\nECC 0.33\nOM 201\n",
                "BINARY DDS\nPB 0.41\nA1 2.1\nT0 55300.1\nECC 0.09\n"
                "OM 81\nM2 1.1\nSHAPMAX 2.0 1\n",
                "BINARY ELL1H\nPB 3.2\nA1 2.8\nTASC 55300.5\n"
                "EPS1 5e-6\nEPS2 2e-6\nH3 2e-7 1\n"]
    extras = ["", "GLEP_1 55350\nGLF0_1 1e-8 1\n",
              "DMX_0001 0.001 1\nDMXR1_0001 55200\nDMXR2_0001 55400\n",
              "FD1 1e-5 1\nCORRECT_TROPOSPHERE Y\n",
              "NE_SW 6.0 1\nWAVE_OM 0.01\nWAVE1 1e-4 -5e-5\n",
              "JUMP -f L-wide 1e-5 1\nSIFUNC 2\nIFUNC1 55100 0.0\n"
              "IFUNC2 55300 1e-6\nIFUNC3 55500 0.0\n",
              "SWM 0\nNE_SW 4.0\nSWX_0001 5.0 1\nSWXR1_0001 55000\n"
              "SWXR2_0001 55600\n",
              "SWM 1\nNE_SW 5.0 1\nSWP 2.4 1\n",
              "CM 0.02 1\nTNCHROMIDX 4\nPHOFF 0.01 1\n"]
    noises = ["", "EFAC -f L-wide 1.2\nEQUAD -f L-wide 0.4\n",
              "ECORR -f L-wide 0.6\nTNREDAMP -13.5\nTNREDGAM 3.5\nTNREDC 8\n"]
    configs = list(itertools.product(binaries, extras, noises))
    rng.shuffle(configs)
    for k, (binary, extra, noise) in enumerate(configs[:26]):
        par = (f"PSR FZ{k}\nRAJ {k % 23}:30:00\nDECJ {(k * 7) % 50 - 20}:10:00\n"
               f"F0 {120 + 13 * k}.25 1\nF1 -{1 + k % 5}e-15 1\nPEPOCH 55300\n"
               f"DM {4 + k}.5 1\n")
        par += (binary or "") + extra + noise
        m = get_model(par)
        # every line the fuzz generates must BIND — a warning in a
        # green test is a bug report (VERDICT r3: the SWX family fell
        # through to `unrecognized` for a round while the suite passed)
        assert not m.unrecognized, (par, m.unrecognized)
        m2 = get_model(m.as_parfile())  # round-trip
        assert not m2.unrecognized, (m.as_parfile(), m2.unrecognized)
        assert sorted(m2.params) == sorted(m.params), par
        days = np.sort(rng.uniform(55000, 55600, 24))
        mjds = np.sort(np.concatenate([days, days + 1.5 / 86400.0]))
        freqs = np.where(np.arange(len(mjds)) % 2, 1400.0, 800.0)
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                    obs="gbt", add_noise=True, seed=k,
                                    iterations=1)
        for f in t.flags:
            f["f"] = "L-wide"
        cls = GLSFitter if "ECORR" in noise else DownhillWLSFitter
        fit = cls(t, copy.deepcopy(m))
        fit.fit_toas(maxiter=3)
        assert np.isfinite(fit.resids.chi2), par
        for p in fit.model.free_params:
            v = getattr(fit.model, p).value
            assert v is not None and np.isfinite(v), (par, p)
