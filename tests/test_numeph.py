"""Numerically-integrated ephemeris artifact tests (numeph_v1.bsp).

Pins: the shipped kernel parses through the real SPK path, serves as
the default provider tier inside coverage, stays dynamically and
numerically consistent (velocity = d(position)/dt, record-boundary
continuity, EMB mass-ratio point), agrees with the analytic tier at
the analytic tier's own truncation scale, and carries build metadata
whose restoration experiment proves the fit-recovers-dropped-dynamics
mechanism. (reference role: the reference's jplephem+DE tests trust
JPL's product; shipping our own integrated kernel means proving the
equivalent properties here. See ephemeris/numeph.py.)
"""

import json
import os

import numpy as np
import pytest

from pint_tpu.mjd import Epochs

HERE = os.path.dirname(os.path.abspath(__file__))
BSP = os.path.join(HERE, "..", "pint_tpu", "data", "numeph_v1.bsp")
META = os.path.join(HERE, "..", "pint_tpu", "data", "numeph_v1.json")

pytestmark = pytest.mark.skipif(
    not os.path.exists(BSP), reason="numeph artifact not built")


def _epochs(mjds):
    mjds = np.asarray(mjds, dtype=np.float64)
    day = np.floor(mjds).astype(np.int64)
    return Epochs(day, (mjds - day) * 86400.0, "tdb")


def test_artifact_metadata_and_fit_quality():
    with open(META) as fh:
        meta = json.load(fh)
    # fit residual vs target ~ the target's own truncation error:
    # a tiny value would mean overfitting the truncated series, a huge
    # one a broken fit
    earth_rms = meta["fit"]["final_rms_m"]["earth"]
    assert 3e4 < earth_rms < 1.5e6
    # the injection experiment is the evidence the mechanism works: a
    # known synthetic SHORT-PERIOD target error (the regime of the
    # production series' dropped tail) must be overwhelmingly rejected
    # by the IC fit, while the LONG-PERIOD lane documents the aliasing
    # limitation the error budget carries explicitly
    inj = meta["injection"]
    assert inj["short_period"]["leakage_fraction"] < 0.25
    assert inj["short_period"]["injected_rms_m"] > 2e5
    assert inj["long_period"]["leakage_fraction"] > 0.3  # honest: aliases
    # Chebyshev compression must sit far below the fit floor
    for body, v in meta["cheb_validation"].items():
        assert v["max_pos_err_m"] < 50.0, body
        assert v["max_vel_err_m_s"] < 1e-3, body


def test_numeph_is_default_provider_in_coverage():
    import pint_tpu.ephemeris as eph

    t = _epochs([52000.0, 55000.25, 58000.5])
    assert eph.ephemeris_provider("de440s", t) == "numeph"
    # out-of-coverage epochs fall back to the analytic tier
    t_out = _epochs([30000.0, 55000.0])
    assert eph.ephemeris_provider("de440s", t_out) == "analytic"
    pv_out = eph.objPosVel_wrt_SSB("earth", t_out)
    from pint_tpu.ephemeris import analytic

    p_ref, _ = analytic.body_posvel_ssb("earth", np.array([30000.0, 55000.0]))
    np.testing.assert_allclose(pv_out.pos, p_ref, atol=1e-3)


def test_numeph_disable_env(monkeypatch):
    import pint_tpu.ephemeris as eph

    monkeypatch.setenv("PINT_TPU_DISABLE_NUMEPH", "1")
    t = _epochs([55000.0])
    assert eph.ephemeris_provider("de440s", t) == "analytic"


def test_numeph_vs_analytic_at_truncation_scale():
    """numeph and the analytic tier must agree at the analytic tier's
    documented truncation scale — close enough to prove they describe
    the same solar system, far enough apart to prove numeph is not
    just replaying the series."""
    import pint_tpu.ephemeris as eph
    from pint_tpu.ephemeris import analytic

    mjds = np.linspace(41000.0, 63000.0, 200)
    t = _epochs(mjds)
    pv = eph.objPosVel_wrt_SSB("earth", t)
    p_ana, _ = analytic.body_posvel_ssb("earth", mjds)
    d = np.linalg.norm(pv.pos - p_ana, axis=1)
    assert d.max() < 3e6      # < 3000 km: same solar system
    assert d.max() > 3e3      # > 3 km: genuinely different provider


def test_numeph_velocity_is_position_derivative():
    import pint_tpu.ephemeris as eph

    mjds = np.array([46321.7, 52000.2, 57777.9, 61003.4])
    dt = 64.0  # s
    pv = eph.objPosVel_wrt_SSB("earth", _epochs(mjds))
    pp = eph.objPosVel_wrt_SSB("earth", Epochs(
        _epochs(mjds).day, _epochs(mjds).sec + dt, "tdb"))
    pm = eph.objPosVel_wrt_SSB("earth", Epochs(
        _epochs(mjds).day, _epochs(mjds).sec - dt, "tdb"))
    v_num = (pp.pos - pm.pos) / (2 * dt)
    np.testing.assert_allclose(pv.vel, v_num, rtol=0, atol=1e-5)


def test_numeph_record_boundary_continuity_all_segments():
    """EVERY pair of adjacent Chebyshev records in EVERY segment must
    agree AT THE SAME INSTANT on its shared boundary (position < 1 m,
    velocity < 1e-4 m/s), evaluated from the raw record polynomials at
    s=+1 / s=-1. Probing via two nearby epochs instead would just
    measure the body's real ~30 km/s motion across the probe gap.
    Sweeping ALL boundaries (vectorized) is what catches a corrupted
    record anywhere — e.g. the pre-fix build whose last Uranus/Neptune
    records were silent scipy extrapolations past the integration end,
    1e8 m off."""
    from pint_tpu.io.spk import SPKKernel

    kern = SPKKernel(BSP)
    for seg_summary in kern.segments:
        seg = kern.segment_for(seg_summary.target, seg_summary.center)
        rsize = seg.rsize
        ncoef = (rsize - 2) // 3
        rec = kern._words(seg.start_word,
                          seg.n_records * rsize).reshape(seg.n_records,
                                                         rsize)
        coef = rec[:, 2:].reshape(seg.n_records, 3, ncoef)
        k = np.arange(ncoef)
        at_hi = np.ones(ncoef)                   # T_k(+1) = 1
        at_lo = (-1.0) ** k                      # T_k(-1) = (-1)^k
        dT_hi = k * k                            # T_k'(+1) = k^2
        dT_lo = (-1.0) ** (k + 1) * k * k        # T_k'(-1)
        p_hi = coef @ at_hi                      # (n_rec, 3) at s=+1
        p_lo = coef @ at_lo                      # (n_rec, 3) at s=-1
        v_hi = (coef @ dT_hi) / rec[:, 1:2]
        v_lo = (coef @ dT_lo) / rec[:, 1:2]
        p_jump = np.abs(p_lo[1:] - p_hi[:-1]).max() * 1e3    # m
        v_jump = np.abs(v_lo[1:] - v_hi[:-1]).max() * 1e3    # m/s
        key = (seg_summary.target, seg_summary.center)
        assert p_jump < 1.0, (key, p_jump)
        assert v_jump < 1e-4, (key, v_jump)


def test_numeph_emb_on_earth_moon_line():
    import pint_tpu.ephemeris as eph
    from pint_tpu.ephemeris.analytic import _EARTH_MOON_MASS_RATIO

    t = _epochs([50123.4, 56789.0])
    e = eph.objPosVel_wrt_SSB("earth", t).pos
    m = eph.objPosVel_wrt_SSB("moon", t).pos
    b = eph.objPosVel_wrt_SSB("emb", t).pos
    np.testing.assert_allclose(
        b, e + (m - e) / (1.0 + _EARTH_MOON_MASS_RATIO), atol=5.0)


def test_toas_record_numeph_provider(tmp_path):
    from pint_tpu.toa import get_TOAs, merge_TOAs

    tim = tmp_path / "prov.tim"
    tim.write_text("FORMAT 1\n"
                   "f1 1400.0 55000.0 1.0 gbt\n"
                   "f2 1400.0 55100.0 1.0 gbt\n")
    t = get_TOAs(str(tim), usepickle=False)
    t.compute_posvels()
    assert t.ephem_provider == "numeph"
    # the tag travels with the posvels it describes
    sub = t.mask(np.array([True, False]))
    assert sub.ephem_provider == "numeph"
    assert merge_TOAs([t, t]).ephem_provider == "numeph"
    t.select(np.array([True, False]))
    assert t.ephem_provider == "numeph"
    t.unselect()
    assert t.ephem_provider == "numeph"
