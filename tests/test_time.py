"""Time-scale and MJD precision tests.

(reference: tests/test_pulsar_mjd.py, tests/test_precision.py patterns —
round-trips, leap-second days, known scale offsets.)
"""

import numpy as np
import pytest

from pint_tpu.mjd import (
    Epochs,
    caldate_to_mjd,
    format_mjd,
    mjd_to_caldate,
    parse_mjd_string,
)
from pint_tpu import timescales as ts


def test_parse_format_roundtrip():
    s = "55555.123456789012345"
    day, sec = parse_mjd_string(s)
    assert day == 55555
    out = format_mjd(day, sec, ndigits=15)
    assert out == s


def test_parse_precision_ns():
    # 1 ns = 1.157e-14 day; 16 fractional digits resolves it
    day, sec = parse_mjd_string("58000.0000000000000116")
    assert sec == pytest.approx(1.16e-14 * 86400.0, rel=1e-6)


def test_caldate():
    assert caldate_to_mjd(2000, 1, 1) == 51544
    assert mjd_to_caldate(51544) == (2000, 1, 1)
    assert caldate_to_mjd(2017, 1, 1) == 57754
    for mjd in [40000, 50000, 58849, 60000]:
        y, m, d = mjd_to_caldate(mjd)
        assert caldate_to_mjd(y, m, d) == mjd


def test_leap_seconds():
    # TAI-UTC was 32 s during 1999-2005, 37 s from 2017
    assert ts.tai_minus_utc(51544)[0] == 32.0
    assert ts.tai_minus_utc(58000)[0] == 37.0
    # boundary: 2016-12-31 (57753) -> 36; 2017-01-01 (57754) -> 37
    assert ts.tai_minus_utc(57753)[0] == 36.0
    assert ts.tai_minus_utc(57754)[0] == 37.0


def test_utc_tt_roundtrip():
    t = Epochs([58000, 51000], [12345.6789, 86399.5], "utc")
    tt = ts.utc_to_tt(t)
    back = ts.tai_to_utc(ts.tt_to_tai(tt))
    np.testing.assert_array_equal(back.day, t.day)
    np.testing.assert_allclose(back.sec, t.sec, atol=1e-9)


def test_tt_scale_value():
    # TT - UTC = 32.184 + 37 = 69.184 s in 2018
    t = Epochs([58119], [0.0], "utc")
    tt = ts.utc_to_tt(t)
    dt = (tt.day[0] - t.day[0]) * 86400.0 + (tt.sec[0] - t.sec[0])
    assert dt == pytest.approx(69.184, abs=1e-9)


def test_tdb_tt_magnitude():
    # TDB-TT is bounded by ~1.7 ms and annual-periodic
    days = np.arange(50000, 51000, 7)
    tt = Epochs(days, np.zeros_like(days, dtype=float), "tt")
    d = ts.tdb_minus_tt(tt)
    assert np.max(np.abs(d)) < 2e-3
    assert np.max(np.abs(d)) > 1e-3  # annual term should show up over a year


def test_tdb_roundtrip():
    t = Epochs([55000], [43200.0], "tt")
    tdb = ts.tt_to_tdb(t)
    back = ts.tdb_to_tt(tdb)
    assert back.day[0] == t.day[0]
    assert back.sec[0] == pytest.approx(t.sec[0], abs=1e-12)


def test_diff_seconds_dd():
    a = Epochs([58000], [0.125], "tdb")
    b = Epochs([51000], [86399.875], "tdb")
    hi, lo = a.diff_seconds_dd(b)
    expected = np.longdouble(7000 * 86400) - np.longdouble(86399.75)
    got = np.longdouble(hi[0]) + np.longdouble(lo[0])
    assert float(got - expected) == 0.0


def test_normalized_carry():
    t = Epochs([58000], [86400.0 + 1.5], "utc").normalized()
    assert t.day[0] == 58001
    assert t.sec[0] == pytest.approx(1.5)


def test_phase_split():
    import jax.numpy as jnp

    from pint_tpu import dd, phase

    x = dd.from_2sum(jnp.float64(1e11), jnp.float64(0.25))
    p = phase.from_dd(x)
    assert float(p.int_) == 1e11
    assert float(p.frac) == 0.25
    q = p + phase.from_f64(jnp.float64(0.5))
    assert float(q.frac) == -0.25
    assert float(q.int_) == 1e11 + 1


def test_tdb_series_secular_clamped_out_of_coverage():
    """Outside the extension fit window (MJD 40000..64000) the
    fit-derived secular factors (quadratic + T-modulated tail) freeze
    at the window edge: they are regression coefficients, not physics,
    and unclamped they added ~5 us/cy^2 of spurious drift (ADVICE r4).
    The series must stay within the published-FB + harmonic-tail
    envelope arbitrarily far out."""
    for day in (15000, 20000, 80000, 90000):
        tt = Epochs([day], [43200.0], "tt")
        series = ts.tdb_minus_tt_series(tt)
        fb10 = ts._tdb_fb10(tt)
        # harmonic tail total amplitude is ~13 us; clamped secular adds
        # a bounded ~5 us. Pre-fix, MJD 15000 (T ~ -1 cy) differed from
        # fb10 by the unclamped quadratic alone (~5 us) PLUS linearly
        # growing T-terms (~4 us/cy) on top of that envelope.
        assert abs(float(series[0] - fb10[0])) < 2.5e-5
    # continuity at the window edges: clamping must not introduce a jump
    for edge in (40000.0, 64000.0):
        lo = ts.tdb_minus_tt_series(Epochs([int(edge) - 1], [86000.0], "tt"))
        hi = ts.tdb_minus_tt_series(Epochs([int(edge)], [500.0], "tt"))
        assert abs(float(hi[0] - lo[0])) < 1e-6
