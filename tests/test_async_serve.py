"""Async continuous-batching front door (pint_tpu.serve.frontdoor)
and SLO-aware admission control (pint_tpu.serve.admission): digest
parity with the sync engine, concurrent-submit lock discipline
(tests/lockcheck runtime instrumentation), watchdog recovery from a
stalled flusher, exactly-once journaling of the intake_overflow
fault, tenant quota isolation, clean shutdown, and a smoke pass of
the multi-threaded saturation sweep."""

import threading
import time
import types
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.obs.reqlife import TERMINAL_STATES, LifecycleLedger
from pint_tpu.resilience.faultinject import FaultPoint, inject
from pint_tpu.serve import (PRIORITY_BATCH, PRIORITY_HIGH,
                            PRIORITY_NORMAL, AdmissionController,
                            AsyncServeEngine, FitRequest, ServeEngine,
                            result_digest)
from pint_tpu.simulation import make_fake_toas_fromMJDs

from lockcheck import assert_no_violations, instrument

PAR = """
PSR ASYN{i}
RAJ 12:0{i}:00.0
DECJ 10:00:00.0
F0 3{i}1.25 1
F1 -4e-16 1
PEPOCH 55500
DM 12.{i} 1
"""


def _pulsar(i=0, n_toa=24, seed=0):
    m = get_model(PAR.format(i=i))
    rng = np.random.default_rng(seed + i)
    mjds = np.sort(rng.uniform(54500, 56500, n_toa))
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=seed + i,
                                iterations=0)
    return m, t


@pytest.fixture(scope="module")
def two_pulsars():
    return [_pulsar(0, 24), _pulsar(1, 24)]


def _reqs(two_pulsars, n, **kw):
    return [FitRequest(*two_pulsars[i % 2], maxiter=2, **kw)
            for i in range(n)]


# -- digest parity with the sync engine ------------------------------


def test_async_results_bitwise_identical_to_sync(two_pulsars):
    """The continuous-batching front door must deliver byte-identical
    results to the inline-flush sync engine on the same request
    stream: lanes are independent under vmap and every flush pads to
    max_batch, so batch composition cannot leak into the numbers."""
    sync = ServeEngine(max_batch=4, max_latency_s=1e9, bucket_floor=32)
    ref = sync.run_stream(_reqs(two_pulsars, 6))
    assert all(r.status == "ok" for r in ref)

    eng = AsyncServeEngine(max_batch=4, max_latency_s=1e9,
                           bucket_floor=32)
    try:
        handles = [eng.submit(r) for r in _reqs(two_pulsars, 6)]
        eng.drain()
        assert all(h.status == "ok" for h in handles)
        for r, h in zip(ref, handles):
            assert result_digest(r.value) == result_digest(h.value)
    finally:
        eng.close()


# -- concurrent-submit stress under lock instrumentation -------------


def test_concurrent_stress_lock_discipline(two_pulsars):
    """N producer threads x mixed tenants hammer submit() while the
    flusher drains; every shared structure the threads touch is
    runtime-instrumented — zero cross-thread unlocked writes, every
    request reaches exactly one terminal lifecycle state."""
    from pint_tpu.serve.batcher import MicroBatcher
    from pint_tpu.serve.frontdoor import IntakeQueue
    from pint_tpu.serve.metrics import ServeTelemetry

    ledger = LifecycleLedger()
    eng = AsyncServeEngine(max_batch=4, max_latency_s=1e9,
                           bucket_floor=32, max_queue=64,
                           reqlife=ledger)
    eng.prewarm(_reqs(two_pulsars, 2))

    n_producers, per_producer = 4, 8
    tenants = ("alice", "bob", "carol", "dave")
    handles = [[None] * per_producer for _ in range(n_producers)]

    def producer(pid):
        for k in range(per_producer):
            req = FitRequest(*two_pulsars[(pid + k) % 2], maxiter=2,
                             tenant=tenants[pid],
                             priority=(k % 3))
            handles[pid][k] = eng.submit(req)

    violations = []
    try:
        with instrument(ServeTelemetry, violations,
                        dict_attrs=("counters",),
                        instances=(eng.telemetry,)), \
             instrument(MicroBatcher, violations,
                        dict_attrs=("_slots",),
                        instances=(eng.batcher,)), \
             instrument(IntakeQueue, violations,
                        instances=(eng.intake,)), \
             instrument(AdmissionController, violations,
                        dict_attrs=("_buckets", "_burning",
                                    "_throttled"),
                        instances=(eng.admission,)):
            threads = [threading.Thread(target=producer, args=(pid,))
                       for pid in range(n_producers)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            eng.drain()
    finally:
        eng.close()

    assert_no_violations(violations)
    flat = [h for row in handles for h in row]
    assert all(h.done for h in flat)
    assert all(h.status in ("ok", "shed") for h in flat)
    assert len(ledger.nonterminal_ids()) == 0
    # exactly one terminal state per request, cross-thread or not
    for h in flat:
        rec = ledger.record(h.request.request_id)
        terms = [s for s in rec["states"]
                 if s["state"] in TERMINAL_STATES]
        assert len(terms) == 1
    # mixed tenants all accounted
    rows = eng.telemetry.tenant_rows()
    assert set(tenants) <= set(rows)


# -- runtime lock-order vs static DAG --------------------------------


def test_runtime_lock_order_consistent_with_static_dag(two_pulsars):
    """Record real lock acquisition order while producer threads hammer
    submit() and the flusher drains, then check the observed edges
    against the static lock-order DAG from pintlint's whole-program
    pass: the union of runtime and derived acquired-while-held edges
    must stay acyclic. A cycle here means the running system took locks
    in an order the static analysis forbids — a latent deadlock this
    particular run merely survived."""
    import os

    import pint_tpu
    from pint_tpu.analysis.core import run_project
    from pint_tpu.analysis.rules_lockorder import LockOrderRule

    from lockcheck import assert_order_consistent, record_order

    pkg = os.path.dirname(pint_tpu.__file__)
    findings, project = run_project([pkg], rules=[LockOrderRule()])
    assert not [f for f in findings if not f.suppressed], \
        "static lock-order cycles present; fix those first"
    static_edges = set(project.lock_graph.edges)
    assert static_edges, "static pass found no acquired-while-held edges"

    eng = AsyncServeEngine(max_batch=4, max_latency_s=1e9,
                           bucket_floor=32, max_queue=64)
    eng.prewarm(_reqs(two_pulsars, 2))
    specs = [
        (eng, "AsyncServeEngine._work_mutex", "_work_mutex"),
        (eng.intake, "IntakeQueue._lock"),
        (eng.admission, "AdmissionController._lock"),
        (eng.batcher, "MicroBatcher._lock"),
        (eng.telemetry, "ServeTelemetry._lock"),
        (eng.cache, "ExecutableCache._lock"),
        (eng.health, "HealthMonitor._lock"),
        (eng.breaker, "CircuitBreaker._lock"),
    ]
    n_producers, per_producer = 4, 6
    handles = [[None] * per_producer for _ in range(n_producers)]

    def producer(pid):
        for k in range(per_producer):
            req = FitRequest(*two_pulsars[(pid + k) % 2], maxiter=2,
                             priority=(k % 3))
            handles[pid][k] = eng.submit(req)

    try:
        with record_order(*specs) as rec:
            threads = [threading.Thread(target=producer, args=(pid,))
                       for pid in range(n_producers)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            eng.drain()
    finally:
        eng.close()

    flat = [h for row in handles for h in row]
    assert all(h.done for h in flat)
    runtime = rec.edge_set()
    assert runtime, "no acquisition-order edges observed at runtime"
    # the flusher's work-mutex-held phase must have been exercised
    assert any(held == "AsyncServeEngine._work_mutex"
               for held, _ in runtime)
    assert_order_consistent(runtime, static_edges)


# -- flusher stall -> watchdog restart -------------------------------


def test_flusher_stall_watchdog_restarts(two_pulsars):
    """An injected flusher_stall wedges the worker without killing
    it; the watchdog must supersede the stale generation, start a
    fresh flusher, and no request may lose its terminal state."""
    ledger = LifecycleLedger()
    eng = AsyncServeEngine(max_batch=4, max_latency_s=1e9,
                           bucket_floor=32, reqlife=ledger,
                           stall_timeout_s=0.1, watchdog_poll_s=0.02)
    eng.prewarm(_reqs(two_pulsars, 2))
    try:
        with inject(FaultPoint("flusher_stall", count=1,
                               payload={"hang_s": 1.0})):
            time.sleep(0.01)  # let the stall fire at the loop top
            handles = [eng.submit(r) for r in _reqs(two_pulsars, 4)]
            deadline = time.monotonic() + 10.0
            while (eng.telemetry.counters.get("flusher_restarts", 0)
                   < 1):
                assert time.monotonic() < deadline, \
                    "watchdog never restarted the stalled flusher"
                time.sleep(0.01)
            eng.drain()
        assert all(h.status == "ok" for h in handles)
        assert len(ledger.nonterminal_ids()) == 0
        assert eng.telemetry.counters["flusher_restarts"] >= 1
        snap = eng.snapshot()
        assert snap["intake"]["generation"] >= 1
        assert snap["intake"]["flusher_alive"]
    finally:
        eng.close()


def test_flusher_death_watchdog_restarts(two_pulsars):
    """A flusher that dies outright (not just stalls) is detected by
    liveness, not heartbeat, and replaced."""
    eng = AsyncServeEngine(max_batch=4, max_latency_s=1e9,
                           bucket_floor=32,
                           stall_timeout_s=30.0, watchdog_poll_s=0.02)
    eng.prewarm(_reqs(two_pulsars, 2))
    try:
        eng.intake.supersede()  # current flusher exits at loop top
        eng._flusher.join(timeout=5.0)
        assert not eng._flusher.is_alive()
        deadline = time.monotonic() + 10.0
        while not eng._flusher.is_alive():
            assert time.monotonic() < deadline, \
                "watchdog never replaced the dead flusher"
            time.sleep(0.01)
        handles = [eng.submit(r) for r in _reqs(two_pulsars, 4)]
        eng.drain()
        assert all(h.status == "ok" for h in handles)
        assert eng.telemetry.counters["flusher_restarts"] >= 1
    finally:
        eng.close()


# -- intake_overflow fault: shed is journaled exactly-once -----------


def test_intake_overflow_shed_is_committed(two_pulsars, tmp_path):
    """The intake_overflow fault fires AFTER the WAL intake, so the
    shed must be committed — replay sees a terminal record, not a
    pending request to re-run."""
    eng = AsyncServeEngine(max_batch=4, max_latency_s=1e9,
                           bucket_floor=32,
                           durable_dir=str(tmp_path / "wal"))
    try:
        with inject(FaultPoint("intake_overflow", count=1)):
            h = eng.submit(FitRequest(*two_pulsars[0], maxiter=2))
        assert h.status == "shed"
        assert h.reason == "intake_overflow"
        assert eng.telemetry.counters["shed_intake_overflow"] == 1
        eng.journal.sync()
        jrep = eng.journal.replay()
        rid = h.request.request_id
        assert rid in jrep.committed
        assert jrep.committed[rid].get("status") == "shed"
        assert all(p["rid"] != rid for p in jrep.pending)
    finally:
        eng.close()
        eng.journal.close()


# -- recovery: admission sheds of replays are committed --------------


def test_replayed_admission_shed_is_committed(two_pulsars, tmp_path):
    """recover() pre-marks replayed intakes (journal.note_intake)
    before re-submitting them through submit(); a replay shed at
    admission must still write a commit record, or the next recover()
    replays — and may execute — it again."""
    from pint_tpu.serve.journal import RequestJournal

    wal = str(tmp_path / "wal")
    req = FitRequest(*two_pulsars[0], maxiter=2, tenant="hot")
    # a crashed process's journal: intake on disk, no commit
    j = RequestJournal(wal)
    j.record_intake(req)
    j.sync()
    j.close()

    adm = AdmissionController()
    adm.observe_slo(
        [{"name": "tenant_hot_availability", "alerting": True}])
    eng = AsyncServeEngine(max_batch=4, max_latency_s=1e9,
                           bucket_floor=32, admission=adm,
                           durable_dir=wal)
    try:
        rep = eng.recover()
        assert rep["n_replayed"] == 1
        h = rep["replayed"][req.request_id]
        assert h.status == "shed"
        assert h.reason == "slo_throttle"
        jrep = eng.journal.replay()
        assert req.request_id in jrep.committed
        assert jrep.committed[req.request_id].get("status") == "shed"
        assert all(p["rid"] != req.request_id for p in jrep.pending)
        # idempotent: a second recover finds the commit and replays
        # nothing — the shed request can never execute
        rep2 = eng.recover()
        assert rep2["n_replayed"] == 0
    finally:
        eng.close()
        eng.journal.close()


# -- flusher _handle crash: no stranded pending request --------------


def test_flusher_handle_crash_completes_request(two_pulsars, tmp_path):
    """An unexpected exception escaping _handle on the flusher thread
    must complete the dequeued request as an error (terminal
    lifecycle state + journal commit) instead of stranding it pending
    forever — and must not kill the flusher."""
    ledger = LifecycleLedger()
    eng = AsyncServeEngine(max_batch=4, max_latency_s=1e9,
                           bucket_floor=32, reqlife=ledger,
                           durable_dir=str(tmp_path / "wal"))
    try:
        eng._screen = lambda *a, **kw: (_ for _ in ()).throw(
            RuntimeError("boom"))
        h = eng.submit(FitRequest(*two_pulsars[0], maxiter=2))
        deadline = time.monotonic() + 10.0
        while not h.done:
            assert time.monotonic() < deadline, \
                "crashed _handle stranded the request as pending"
            time.sleep(0.01)
        assert h.status == "error"
        assert "boom" in h.reason
        assert eng.telemetry.counters["flusher_handle_errors"] == 1
        assert len(ledger.nonterminal_ids()) == 0
        eng.journal.sync()
        jrep = eng.journal.replay()
        rid = h.request.request_id
        assert rid in jrep.committed
        assert jrep.committed[rid].get("status") == "error"
        # the flusher survived the escape and still serves
        del eng._screen
        h2 = eng.submit(FitRequest(*two_pulsars[1], maxiter=2))
        eng.drain()
        assert h2.status == "ok"
    finally:
        eng.close()
        eng.journal.close()


# -- admission controller unit semantics -----------------------------


def _fake_req(tenant="anon", priority=PRIORITY_NORMAL):
    return types.SimpleNamespace(tenant=tenant, priority=priority)


def test_admission_quota_bucket():
    t = [0.0]
    adm = AdmissionController(quotas={"hot": 2.0}, burst_s=1.0,
                              clock=lambda: t[0])
    for _ in range(2):
        assert adm.decide(_fake_req("hot"), depth=0, capacity=64).admit
    d = adm.decide(_fake_req("hot"), depth=0, capacity=64)
    assert not d.admit and d.reason == "tenant_quota"
    assert d.detail["tenant"] == "hot"
    # unquota'd tenants ride free; tokens refill with the clock
    assert adm.decide(_fake_req("cold"), depth=0, capacity=64).admit
    t[0] += 1.0
    assert adm.decide(_fake_req("hot"), depth=0, capacity=64).admit


def test_admission_backpressure_priority_ladder():
    adm = AdmissionController(soft_watermark=0.5)
    depth, cap = 40, 64  # above the soft watermark, below capacity
    assert adm.decide(_fake_req(priority=PRIORITY_HIGH),
                      depth=depth, capacity=cap).admit
    assert adm.decide(_fake_req(priority=PRIORITY_NORMAL),
                      depth=depth, capacity=cap).admit
    d = adm.decide(_fake_req(priority=PRIORITY_BATCH),
                   depth=depth, capacity=cap)
    assert not d.admit and d.reason == "backpressure"


def test_admission_slo_throttle():
    adm = AdmissionController()
    throttled = adm.observe_slo(
        [{"name": "tenant_hot_availability", "alerting": True},
         {"name": "tenant_good_latency_p99", "alerting": False}])
    assert throttled == {"hot"}
    d = adm.decide(_fake_req("hot"), depth=0, capacity=64)
    assert not d.admit and d.reason == "slo_throttle"
    # high-priority traffic from the burning tenant still lands
    assert adm.decide(_fake_req("hot", priority=PRIORITY_HIGH),
                      depth=0, capacity=64).admit
    # recovery clears the throttle
    adm.observe_slo(
        [{"name": "tenant_hot_availability", "alerting": False}])
    assert adm.decide(_fake_req("hot"), depth=0, capacity=64).admit


def test_shed_rungs_do_not_debit_quota():
    """A request shed by a later rung (slo_throttle / backpressure)
    must not consume a quota token — a throttled or backpressured
    tenant is not additionally pushed into tenant_quota sheds by
    traffic that never entered the queue."""
    t = [0.0]
    adm = AdmissionController(quotas={"hot": 2.0}, burst_s=1.0,
                              clock=lambda: t[0])
    adm.observe_slo(
        [{"name": "tenant_hot_availability", "alerting": True}])
    for _ in range(5):
        d = adm.decide(_fake_req("hot"), depth=0, capacity=64)
        assert not d.admit and d.reason == "slo_throttle"
    adm.observe_slo(
        [{"name": "tenant_hot_availability", "alerting": False}])
    # the throttled sheds consumed nothing: the full burst is intact
    for _ in range(2):
        assert adm.decide(_fake_req("hot"), depth=0, capacity=64).admit
    d = adm.decide(_fake_req("hot"), depth=0, capacity=64)
    assert not d.admit and d.reason == "tenant_quota"
    # backpressure sheds don't debit either
    t[0] += 1.0  # refill the burst
    for _ in range(3):
        d = adm.decide(_fake_req("hot", priority=PRIORITY_BATCH),
                       depth=60, capacity=64)
        assert not d.admit and d.reason == "backpressure"
    for _ in range(2):
        assert adm.decide(_fake_req("hot"), depth=0, capacity=64).admit


# -- intake stop: shutdown race is draining, not queue_full ----------


def test_offer_reports_stopped_vs_full():
    from pint_tpu.serve.frontdoor import IntakeQueue

    q = IntakeQueue(1)
    assert q.offer("a") is None
    assert q.offer("b") == "full"
    q.stop()
    assert q.offer("c") == "stopped"


def test_stop_between_screen_and_offer_rejects_draining(two_pulsars,
                                                        tmp_path):
    """intake.stop() landing between submit's is_running() screen and
    the offer must surface as the synchronous draining rejection (and
    a journal commit), not masquerade as queue saturation in the
    shed_queue_full counter."""
    eng = AsyncServeEngine(max_batch=4, max_latency_s=1e9,
                           bucket_floor=32,
                           durable_dir=str(tmp_path / "wal"))
    try:
        eng.intake.stop()
        # shadow is_running so submit's screen sees the pre-stop
        # world — the exact race the offer must disambiguate
        eng.intake.is_running = lambda: True
        try:
            h = eng.submit(FitRequest(*two_pulsars[0], maxiter=2))
        finally:
            del eng.intake.is_running
        assert h.status == "rejected"
        assert h.reason == "draining"
        assert eng.telemetry.counters.get("shed_queue_full", 0) == 0
        eng.journal.sync()
        jrep = eng.journal.replay()
        rid = h.request.request_id
        assert rid in jrep.committed
        assert jrep.committed[rid].get("status") == "rejected"
    finally:
        eng.close(drain=False)
        eng.journal.close()


# -- tenant isolation ------------------------------------------------


def test_hot_tenant_quota_does_not_starve_good_tenant(two_pulsars):
    """A hot tenant at ~3x its quota gets shed (and attributed in
    tenant_rows); a well-behaved tenant keeps 100% availability and a
    sane p99."""
    adm = AdmissionController(quotas={"hot": 4.0}, burst_s=1.0)
    ledger = LifecycleLedger()
    eng = AsyncServeEngine(max_batch=4, max_latency_s=1e9,
                           bucket_floor=32, max_queue=64,
                           admission=adm, reqlife=ledger)
    eng.prewarm(_reqs(two_pulsars, 2))
    hot_h, good_h = [], []

    def hot():
        # ~3x the 4 rps quota for ~1s
        for k in range(12):
            hot_h.append(eng.submit(
                FitRequest(*two_pulsars[k % 2], maxiter=2,
                           tenant="hot")))
            time.sleep(1.0 / 12.0)

    def good():
        for k in range(6):
            good_h.append(eng.submit(
                FitRequest(*two_pulsars[k % 2], maxiter=2,
                           tenant="good")))
            time.sleep(0.18)

    try:
        th, tg = threading.Thread(target=hot), \
            threading.Thread(target=good)
        th.start(); tg.start()
        th.join(); tg.join()
        eng.drain()
    finally:
        eng.close()

    rows = eng.telemetry.tenant_rows()
    # the hot tenant's overage was shed and attributed to it
    assert rows["hot"]["shed"] >= 1
    assert sum(1 for h in hot_h if h.status == "shed") \
        == rows["hot"]["shed"]
    assert all(h.reason == "tenant_quota" for h in hot_h
               if h.status == "shed")
    # the good tenant is untouched: full availability, no sheds
    assert rows["good"]["shed"] == 0
    assert rows["good"]["rejected"] == 0
    assert rows["good"]["ok"] == rows["good"]["requests"] == 6
    assert all(h.status == "ok" for h in good_h)
    assert rows["good"]["p99_s"] is not None
    assert rows["good"]["p99_s"] < 5.0
    assert len(ledger.nonterminal_ids()) == 0


# -- shutdown / draining ---------------------------------------------


def test_close_drains_then_rejects(two_pulsars):
    eng = AsyncServeEngine(max_batch=4, max_latency_s=1e9,
                           bucket_floor=32)
    handles = [eng.submit(r) for r in _reqs(two_pulsars, 4)]
    eng.close()
    assert all(h.status == "ok" for h in handles)
    assert not eng.intake.is_running()
    assert eng._flusher is None or not eng._flusher.is_alive()
    late = eng.submit(FitRequest(*two_pulsars[0], maxiter=2))
    assert late.status == "rejected"
    assert late.reason == "draining"


# -- saturation sweep smoke ------------------------------------------


@pytest.mark.slow
def test_arrival_sweep_async_smoke():
    from pint_tpu.scripts.pint_serve_bench import run_arrival_sweep

    rep = run_arrival_sweep(n_per_rate=8, fracs=(0.5, 1.0),
                            max_batch=4, sizes=(48,), maxiter=2,
                            producers=2, seed=0)
    assert rep["engine"] == "async"
    assert rep["producers"] == 2
    assert rep["monotone_offered"]
    assert rep["reqlife_nonterminal"] == 0
    assert len(rep["rows"]) == 2
    for row in rep["rows"]:
        assert row["delivered"] + row["shed"] + row["errors"] == 8
        assert row["errors"] == 0
    assert "queue_bounded_by_inline_flush" \
        not in rep["null_reasons"].values()
    assert len(rep["schedule_sha256"]) == 64
