"""Edge-case battery II (VERDICT r3 item 7, continued): par-file
pathologies, astrometry sign traps, pulse-number tracking across gaps,
mask-parameter range semantics, wideband flags, selection state.
Each test names its upstream analog.
"""

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = ("PSR EC2\nRAJ 05:00:00\nDECJ 10:00:00\nF0 100.0 1\n"
       "F1 -1e-15 1\nPEPOCH 55000\nDM 10.0 1\n")


def _toas(m, n=24, span=(55000, 55300), seed=0, **kw):
    rng = np.random.default_rng(seed)
    mjds = np.sort(rng.uniform(*span, n))
    return make_fake_toas_fromMJDs(mjds, m, error_us=1.0, obs="gbt",
                                   add_noise=True, seed=seed,
                                   iterations=1, **kw)


# ---------------------------------------------------------------------------
# par-file pathologies (reference: models/parameter.py parse;
# upstream tests/test_parfile.py / test_model.py)
# ---------------------------------------------------------------------------

class TestParPathologies:
    def test_fortran_d_exponent(self):
        # tempo par files carry FORTRAN 'D' exponents
        m = get_model(PAR.replace("F1 -1e-15 1", "F1 -1.5D-15 1"))
        assert m.F1.value == -1.5e-15

    def test_fit_flag_two_means_free(self):
        # tempo uses fit flag 2 for some parameters; any nonzero = free
        m = get_model(PAR.replace("F0 100.0 1", "F0 100.0 2"))
        assert not m.F0.frozen

    def test_value_flag_uncertainty_columns(self):
        m = get_model(PAR.replace("F0 100.0 1", "F0 100.0 1 3e-12"))
        assert not m.F0.frozen and m.F0.uncertainty == 3e-12

    def test_negative_zero_degrees_decj(self):
        # THE classic sign trap: -00:30:00 must be -0.5 deg, not +0.5
        # (upstream fixed this in angle parsing years ago)
        m = get_model(PAR.replace("DECJ 10:00:00", "DECJ -00:30:00"))
        assert np.degrees(m.DECJ.value) == pytest.approx(-0.5, abs=1e-12)

    def test_raj_uncertainty_in_seconds_of_time(self):
        # RAJ uncertainty column is seconds of RA: 0.001 s = 2pi/86400e3
        m = get_model(PAR.replace("RAJ 05:00:00", "RAJ 05:00:00 1 0.001"))
        assert m.RAJ.uncertainty == pytest.approx(2 * np.pi / 86400e3,
                                                  rel=1e-9)

    def test_duplicate_parameter_last_wins_or_warns(self):
        # a par with F0 twice must not silently produce a third value
        import warnings as w

        with w.catch_warnings():
            w.simplefilter("ignore")
            m = get_model(PAR + "F0 123.0 1\n")
        assert m.F0.value in (100.0, 123.0)

    def test_whitespace_and_tab_separated(self):
        m = get_model(PAR.replace("F0 100.0 1", "F0\t100.0\t1"))
        assert m.F0.value == 100.0 and not m.F0.frozen

    def test_unknown_lines_reported_not_fatal(self):
        import warnings as w

        with w.catch_warnings():
            w.simplefilter("ignore")
            m = get_model(PAR + "NOTAPARAM 1.23\n")
        assert "NOTAPARAM" in m.unrecognized


# ---------------------------------------------------------------------------
# astrometry traps (reference: models/astrometry.py; upstream
# tests/test_astrometry.py)
# ---------------------------------------------------------------------------

class TestAstrometryTraps:
    def test_proper_motion_moves_position(self):
        # 100 mas/yr over ~2.7 yr from POSEPOCH ~ 274 mas of motion:
        # the Roemer delay must shift measurably vs the no-PM model
        m0 = get_model(PAR)
        mpm = get_model(PAR + "PMRA 100.0\nPMDEC -50.0\nPOSEPOCH 54000\n")
        t = _toas(m0)
        d0 = m0.delay_breakdown(t)["AstrometryEquatorial"]
        d1 = mpm.delay_breakdown(t)["AstrometryEquatorial"]
        # annual-parallax-scale signature: > 100 ns somewhere
        assert np.abs(np.asarray(d1) - np.asarray(d0)).max() > 1e-7

    def test_negative_parallax_rejected_or_flagged(self):
        # PX < 0 is unphysical; model must either raise at validate or
        # carry it without NaN (upstream warns and carries)
        m = get_model(PAR + "PX -1.0\n")
        t = _toas(m)
        r = Residuals(t, m)
        assert np.isfinite(np.asarray(r.time_resids)).all()

    def test_ecliptic_equatorial_same_sky_position(self):
        # the SAME sky point expressed in both frames gives matching
        # Roemer delays to sub-us (frame conversion correctness)
        m_eq = get_model(PAR)
        from pint_tpu.modelutils import model_equatorial_to_ecliptic

        m_ecl = model_equatorial_to_ecliptic(m_eq)
        t = _toas(m_eq)
        r_eq = np.asarray(Residuals(t, m_eq).time_resids)
        r_ecl = np.asarray(Residuals(t, m_ecl).time_resids)
        assert np.abs(r_eq - r_ecl).max() < 1e-6

    def test_posepoch_defaults_to_pepoch(self):
        m = get_model(PAR + "PMRA 10.0\nPMDEC 0.0\n")
        t = _toas(m)
        assert np.isfinite(np.asarray(Residuals(t, m).time_resids)).all()


# ---------------------------------------------------------------------------
# pulse-number tracking (reference: toa.py::compute_pulse_numbers +
# residuals track_mode; upstream tests/test_pulse_number.py)
# ---------------------------------------------------------------------------

class TestPulseNumberTracking:
    def test_tracking_honors_manual_phase_wrap(self):
        # pintk's wrap tool edits -pn flags: adding +1 to the second
        # cluster must shift TRACKED residuals by exactly one turn
        # (1/F0 = 10 ms) there, while nearest-pulse residuals ignore
        # pn entirely — the deterministic TRACK -2 semantics
        m = get_model("PSR TRK1\nRAJ 5:0:0\nDECJ 10:0:0\nF0 100.0 1\n"
                      "PEPOCH 55000\nDM 10.0\n")
        mjds = np.concatenate([np.linspace(55000, 55050, 10),
                               np.linspace(55500, 55550, 10)])
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, obs="gbt",
                                    iterations=2)
        t.compute_pulse_numbers(m)
        assert len(t.get_pulse_numbers()) == 20
        r0 = np.asarray(Residuals(t, m, track_mode="use_pulse_numbers",
                                  subtract_mean=False).time_resids)
        r_near0 = np.asarray(Residuals(t, m, track_mode="nearest",
                                       subtract_mean=False).time_resids)
        for f in t.flags[10:]:
            f["pn"] = f"{float(f['pn']) + 1:.0f}"
        r1 = np.asarray(Residuals(t, m, track_mode="use_pulse_numbers",
                                  subtract_mean=False).time_resids)
        r_near1 = np.asarray(Residuals(t, m, track_mode="nearest",
                                       subtract_mean=False).time_resids)
        d = r1 - r0
        np.testing.assert_allclose(d[:10], 0.0, atol=1e-12)
        np.testing.assert_allclose(np.abs(d[10:]), 1.0 / 100.0,
                                   rtol=1e-9)
        np.testing.assert_allclose(r_near1, r_near0, atol=1e-15)

    def test_pn_flags_roundtrip_through_residuals(self):
        m = get_model(PAR)
        t = _toas(m)
        t.compute_pulse_numbers(m)
        r = Residuals(t, m, track_mode="use_pulse_numbers")
        assert np.isfinite(np.asarray(r.time_resids)).all()


# ---------------------------------------------------------------------------
# mask parameter range semantics (reference: parameter.maskParameter;
# upstream tests/test_jump.py / test_select.py)
# ---------------------------------------------------------------------------

class TestMaskSemantics:
    def test_jump_mjd_range_hits_only_range(self):
        # PhaseJump acts in phase (not the delay chain): observe it as
        # the un-mean-subtracted residual difference vs the jump-free
        # model — exactly -value inside the window, zero outside
        m0 = get_model(PAR)
        mj = get_model(PAR + "JUMP MJD 55100 55200 5e-4 1\n")
        t = _toas(m0, n=40)
        mjd = t.day + t.sec / 86400.0
        in_range = (mjd >= 55100) & (mjd <= 55200)
        assert in_range.any() and (~in_range).any()
        r0 = np.asarray(Residuals(t, m0, subtract_mean=False).time_resids)
        r1 = np.asarray(Residuals(t, mj, subtract_mean=False).time_resids)
        d = r1 - r0
        np.testing.assert_allclose(d[~in_range], 0.0, atol=1e-12)
        np.testing.assert_allclose(d[in_range], -5e-4, rtol=1e-6)

    def test_jump_freq_range(self):
        m0 = get_model(PAR)
        mj = get_model(PAR + "JUMP FREQ 1200 1500 3e-4 1\n")
        rng = np.random.default_rng(0)
        mjds = np.sort(rng.uniform(55000, 55300, 30))
        t = make_fake_toas_fromMJDs(
            mjds, m0, error_us=1.0, obs="gbt", add_noise=True, seed=0,
            iterations=1,
            freq_mhz=np.where(np.arange(30) % 2, 1400.0, 800.0))
        hi = (t.freq_mhz >= 1200) & (t.freq_mhz <= 1500)
        assert hi.any() and (~hi).any()
        r0 = np.asarray(Residuals(t, m0, subtract_mean=False).time_resids)
        r1 = np.asarray(Residuals(t, mj, subtract_mean=False).time_resids)
        d = r1 - r0
        np.testing.assert_allclose(d[~hi], 0.0, atol=1e-12)
        np.testing.assert_allclose(d[hi], -3e-4, rtol=1e-6)

    def test_efac_tel_mask(self):
        # no dash: 'tel' selects on the observatory column
        # (dashed keys select on tim FLAGS — simulated TOAs carry none)
        m = get_model(PAR + "EFAC tel gbt 2.0\n")
        t = _toas(m)
        r = Residuals(t, m)
        sig = np.asarray(r.prepared.scaled_sigma_us())
        np.testing.assert_allclose(sig, 2.0, rtol=1e-12)

    def test_overlapping_masks_compose(self):
        # EFAC then EQUAD on the same TOAs: sigma = sqrt((e*f)^2+q^2)
        m = get_model(PAR + "EFAC tel gbt 2.0\nEQUAD tel gbt 3.0\n")
        t = _toas(m)
        sig = np.asarray(Residuals(t, m).prepared.scaled_sigma_us())
        want = np.sqrt((2.0 * 1.0) ** 2 + (2.0 * 3.0) ** 2)
        # EQUAD convention: added in quadrature scaled by EFAC
        # (tempo2/PINT 'EFAC scales EQUAD' convention; equality with
        # either convention is accepted but must be one of them)
        alt = np.sqrt((2.0 * 1.0) ** 2 + 3.0 ** 2)
        ok = (np.allclose(sig, want, rtol=1e-9)
              or np.allclose(sig, alt, rtol=1e-9))
        assert ok, sig[:3]


# ---------------------------------------------------------------------------
# selection state (reference: toa.py select/unselect; upstream
# tests/test_toa_selection.py)
# ---------------------------------------------------------------------------

class TestSelectionState:
    def test_select_unselect_stack(self):
        m = get_model(PAR)
        t = _toas(m, n=30)
        n0 = len(t)
        mjd = t.day + t.sec / 86400.0
        t.select(mjd > 55100)
        n1 = len(t)
        assert n1 < n0
        t.select(t.freq_mhz > 1000)
        assert len(t) <= n1
        t.unselect()
        assert len(t) == n1
        t.unselect()
        assert len(t) == n0

    def test_mask_returns_independent_copy(self):
        m = get_model(PAR)
        t = _toas(m, n=10)
        sub = t.mask(np.arange(10) < 4)
        assert len(sub) == 4 and len(t) == 10
        sub.sec[0] += 1.0
        assert t.sec[0] != sub.sec[0]

    def test_adjust_times_invalidates_derived(self):
        m = get_model(PAR)
        t = _toas(m, n=8)
        pos0 = t.ssb_obs.pos.copy()
        t.adjust_times(3600.0)  # +1 hour
        # contract: derived columns are INVALIDATED (not silently kept)
        assert t.ssb_obs is None
        t.compute_posvels()
        assert np.abs(t.ssb_obs.pos - pos0).max() > 1e4  # Earth moved


# ---------------------------------------------------------------------------
# wideband flags (reference: simulation wideband + residuals;
# upstream tests/test_wideband_dm_data.py)
# ---------------------------------------------------------------------------

class TestWidebandFlags:
    def test_wideband_simulation_sets_pp_flags(self):
        m = get_model(PAR)
        t = _toas(m, wideband=True)
        for f in t.flags:
            assert "pp_dm" in f and "pp_dme" in f
            assert np.isfinite(float(f["pp_dm"]))

    def test_wideband_fit_uses_dm_channel(self):
        from pint_tpu.fitter import WidebandTOAFitter

        m = get_model(PAR)
        t = _toas(m, n=30, wideband=True)
        f = WidebandTOAFitter(t, m)
        f.fit_toas()
        assert np.isfinite(float(f.resids.chi2))
        # DM is constrained by the DM channel even with 2 params
        assert f.model.DM.uncertainty is not None


# ---------------------------------------------------------------------------
# polycos boundary behavior (reference: polycos.py; upstream
# tests/test_polycos.py)
# ---------------------------------------------------------------------------

class TestPolycosBoundary:
    def test_eval_at_segment_edges_continuous(self):
        from pint_tpu.polycos import Polycos

        m = get_model(PAR)
        p = Polycos.generate_polycos(m, 55000, 55002, "gbt", 60, 8,
                                     1400.0)
        # evaluate just inside both sides of an internal boundary
        eps = 1e-7
        t_edge = 55001.0
        # eval_abs_phase returns (int turns, frac turns)
        i_lo, f_lo = p.eval_abs_phase(np.array([t_edge - eps]))
        i_hi, f_hi = p.eval_abs_phase(np.array([t_edge + eps]))
        dphi = float((np.asarray(i_hi)[0] - np.asarray(i_lo)[0])
                     + (np.asarray(f_hi)[0] - np.asarray(f_lo)[0]))
        f0 = 100.0
        # continuity: phase difference ~ f0 * 2*eps*86400, not a jump
        assert dphi == pytest.approx(f0 * 2 * eps * 86400.0, rel=0.05)

    def test_eval_outside_span_raises(self):
        from pint_tpu.polycos import Polycos

        m = get_model(PAR)
        p = Polycos.generate_polycos(m, 55000, 55001, "gbt", 60, 8,
                                     1400.0)
        with pytest.raises((ValueError, IndexError)):
            p.eval_abs_phase(np.array([56000.0]))


# ---------------------------------------------------------------------------
# merge/simulate/auto-fitter helpers (reference: toa.merge_TOAs,
# simulation.make_fake_toas_fromtim, fitter auto-selection; upstream
# tests/test_toa_merge.py / test_fake_toas.py)
# ---------------------------------------------------------------------------

class TestMergeAndHelpers:
    def test_merge_toas_multi_observatory_fit(self):
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.toa import merge_TOAs

        m = get_model(PAR)
        a = make_fake_toas_fromMJDs(np.linspace(55000, 55100, 12), m,
                                    obs="gbt", add_noise=True, seed=1,
                                    flags={"be": "GUPPI"})
        b = make_fake_toas_fromMJDs(np.linspace(55050, 55250, 12), m,
                                    obs="parkes", add_noise=True, seed=2,
                                    flags={"be": "CASPSR"})
        mg = merge_TOAs([a, b])
        assert len(mg) == 24
        assert sorted(set(mg.obs.astype(str))) == ["gbt", "parkes"]
        # per-TOA identity (flags) survives the merge
        assert sum(f.get("be") == "GUPPI" for f in mg.flags) == 12
        # the merged multi-telescope set fits end to end (posvels per
        # observatory, ECORR-quantization-safe ordering handled inside)
        f = WLSFitter(mg, m)
        f.fit_toas()
        assert np.isfinite(float(f.resids.chi2))

    def test_make_fake_toas_fromtim_preserves_layout(self, tmp_path):
        from pint_tpu.simulation import make_fake_toas_fromtim

        m = get_model(PAR)
        t0 = make_fake_toas_fromMJDs(np.linspace(55000, 55100, 9), m,
                                     obs="gbt", error_us=2.5,
                                     flags={"f": "L-wide"})
        p = tmp_path / "layout.tim"
        t0.write_TOA_file(str(p))
        t1 = make_fake_toas_fromtim(str(p), m)
        assert len(t1) == 9
        np.testing.assert_allclose(t1.error_us, 2.5, rtol=1e-9)
        assert all(f.get("f") == "L-wide" for f in t1.flags)
        # zero-residual property: simulated arrival phases land on
        # integer pulses under the generating model
        from pint_tpu.residuals import Residuals

        r = np.asarray(Residuals(t1, m, subtract_mean=False).time_resids)
        assert np.abs(r).max() < 5e-8

    def test_auto_fitter_selection_matrix(self):
        from pint_tpu.fitter import auto_fitter

        m_white = get_model(PAR)
        m_corr = get_model(PAR + "ECORR -f L-wide 0.5\n"
                           "RNAMP 1e-14\nRNIDX -3.0\n")
        t = _toas(m_white, n=12)
        tw = _toas(m_white, n=12, wideband=True)
        assert "WLS" in type(auto_fitter(t, m_white)).__name__
        assert "GLS" in type(auto_fitter(t, m_corr)).__name__
        assert "Wideband" in type(auto_fitter(tw, m_white)).__name__
