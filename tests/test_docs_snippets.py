"""Execute the python code blocks of every docs/*.md tutorial: the
documentation must never drift from the actual API (upstream pins this
with executed example notebooks in CI)."""

import os
import re

import pytest

DOCS = os.path.join(os.path.dirname(__file__), "..", "docs")

# docs whose python blocks are fully self-contained (no user files):
# these EXECUTE; all other docs' blocks are still compile-checked so
# the syntax can't rot
_EXECUTABLE = {"tutorial_wideband.md", "tutorial_noise.md",
               "tutorial_polycos.md", "tutorial_templates.md",
               "tutorial_distributed.md"}


def _blocks(name):
    text = open(os.path.join(DOCS, name)).read()
    return re.findall(r"```python\n(.*?)```", text, re.S)


@pytest.mark.parametrize("doc", sorted(
    f for f in os.listdir(DOCS) if f.endswith(".md")))
def test_doc_python_blocks_execute(doc):
    blocks = _blocks(doc)
    if not blocks:
        pytest.skip("no python blocks")
    ns = {}
    for i, src in enumerate(blocks):
        try:
            code = compile(src, f"{doc}[block {i}]", "exec")
        except SyntaxError:
            pytest.fail(f"{doc} block {i} does not parse")
        if doc in _EXECUTABLE:
            exec(code, ns)  # shared namespace: blocks build on earlier
