"""Request-lifecycle observatory (pint_tpu.obs.reqlife + the serve
engine wiring): bounded-ledger memory/loss accounting, the exactly-
one-terminal-state invariant, deterministic open-loop arrival
schedules, tail-exemplar -> lifecycle joins (`python -m pint_tpu.obs
tail`), per-tenant cardinality folds, and the bitwise on-vs-off
contract (instrumented serving must not change results)."""

import json
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.obs.metricsreg import Registry
from pint_tpu.obs.reqlife import (TERMINAL_STATES, LifecycleLedger,
                                  phase_split, resolve_tail,
                                  tail_artifact)
from pint_tpu.serve import (FitRequest, RequestJournal,
                            ResidualRequest, ServeEngine)
from pint_tpu.serve.metrics import ServeTelemetry
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR RQLF{i}
RAJ 11:0{i}:00.0
DECJ 9:00:00.0
F0 2{i}7.5 1
F1 -3e-16 1
PEPOCH 55500
DM 11.{i} 1
"""


def _pulsar(i=0, n_toa=24, seed=3):
    m = get_model(PAR.format(i=i))
    rng = np.random.default_rng(seed + i)
    mjds = np.sort(rng.uniform(54500, 56500, n_toa))
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=seed + i,
                                iterations=0)
    return m, t


@pytest.fixture(scope="module")
def pulsar():
    return _pulsar(0, 24)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- deterministic open-loop arrivals --------------------------------


def test_arrival_schedule_bit_identical_and_monotone():
    from pint_tpu.scripts.pint_serve_bench import arrival_schedule

    a = arrival_schedule(5.0, 64, seed=1, rate_index=2)
    b = arrival_schedule(5.0, 64, seed=1, rate_index=2)
    assert np.array_equal(a, b)  # seeded: bit-identical across calls
    assert a.shape == (64,)
    assert np.all(np.diff(a) > 0)  # cumsum of positive gaps
    # the rate index is part of the seed sequence: each rung of the
    # sweep ladder gets its own independent-but-reproducible stream
    c = arrival_schedule(5.0, 64, seed=1, rate_index=3)
    assert not np.array_equal(a, c)
    # mean gap tracks 1/rate (loose: 64 samples of an exponential)
    assert 0.5 / 5.0 < np.mean(np.diff(a)) < 2.0 / 5.0


# -- bounded ledger memory and loss accounting -----------------------


def test_ledger_bounded_under_10k_terminal_requests():
    led = LifecycleLedger(capacity=512, clock=lambda: 0.0)
    for i in range(10_000):
        rid = f"r{i}"
        led.submitted(rid, tenant=f"t{i % 3}")
        led.transition(rid, "delivered")
    assert len(led) <= 512
    snap = led.snapshot()
    assert snap["records"] == 10_000
    assert snap["resident"] <= 512
    # evicting a record that already reached a terminal state is
    # routine bookkeeping, not data loss
    assert snap["lost_records"] == 0
    assert snap["non_terminal"] == 0


def test_ledger_counts_nonterminal_evictions_as_lost():
    led = LifecycleLedger(capacity=8, clock=lambda: 0.0)
    for i in range(20):
        led.submitted(f"r{i}")  # never reaches a terminal state
    assert len(led) == 8
    assert led.snapshot()["lost_records"] == 12


def test_double_terminal_refused_and_counted():
    led = LifecycleLedger(capacity=8, clock=lambda: 0.0)
    led.submitted("r0")
    led.transition("r0", "delivered")
    led.transition("r0", "shed", reason="deadline")  # refused
    rec = led.record("r0")
    assert rec["state"] == "delivered"
    assert [s["state"] for s in rec["states"]] == ["submitted",
                                                   "delivered"]
    assert led.snapshot()["double_terminal"] == 1


def test_unknown_request_counted_not_raised():
    led = LifecycleLedger(capacity=8, clock=lambda: 0.0)
    assert led.transition("ghost", "delivered") is None
    assert led.snapshot()["unknown_request"] == 1


def test_resubmit_reanchors_and_keeps_trace():
    # recovery re-submits a journaled id through submit(): the record
    # re-opens (non-terminal) but keeps its original trace id
    led = LifecycleLedger(capacity=8, clock=lambda: 0.0)
    tr = led.submitted("r0")
    led.transition("r0", "re_executed")
    assert "re_executed" not in TERMINAL_STATES
    assert led.submitted("r0") == tr
    rec = led.record("r0")
    assert rec["terminal"] is False
    assert rec["states"][-1]["state"] == "submitted"
    assert led.by_trace(tr)["request_id"] == "r0"


def test_snapshot_folds_tenant_tail_into_other():
    led = LifecycleLedger(capacity=64, clock=lambda: 0.0)
    for i in range(10):
        for k in range(10 - i):  # tenant t0 largest, t9 smallest
            rid = f"r{i}-{k}"
            led.submitted(rid, tenant=f"t{i}")
            led.transition(rid, "delivered")
    snap = led.snapshot(tenant_cap=3)
    tenants = snap["by_tenant"]
    assert set(tenants) == {"t0", "t1", "t2", "other"}
    assert tenants["t0"] == 10
    assert tenants["other"] == sum(range(1, 8))  # t3..t9 folded
    assert sum(tenants.values()) == snap["resident"]


# -- phase decomposition and the tail join ---------------------------


def test_phase_split_queue_wait_vs_execute():
    rec = {"states": [{"state": "submitted", "t": 1.0},
                      {"state": "queued", "t": 1.0},
                      {"state": "packed", "t": 1.4},
                      {"state": "executing", "t": 1.5},
                      {"state": "delivered", "t": 2.25}]}
    split = phase_split(rec)
    assert split["queue_wait_s"] == pytest.approx(0.5)
    assert split["execute_s"] == pytest.approx(0.75)
    assert split["per_state_s"]["queued"] == pytest.approx(0.4)


def _synthetic_artifact():
    led = LifecycleLedger(capacity=16, clock=lambda: 0.0)
    traces = {}
    for i, total in enumerate([0.01, 0.02, 0.50]):
        rid = f"r{i}"
        traces[rid] = led.submitted(rid, tenant="alice" if i < 2
                                    else "bob")
        led.transition(rid, "queued", t=0.0)
        led.transition(rid, "executing", t=total * 0.4)
        led.transition(rid, "delivered", t=total,
                       flush_trace="t000099")
    tele = {"total_s": {"p99": 0.45},
            "exemplars": [
                {"value": 0.02, "trace": traces["r1"],
                 "request_id": "r1", "tenant": "alice"},
                {"value": 0.50, "trace": traces["r2"],
                 "request_id": "r2", "tenant": "bob"}],
            "tenants": {"alice": {"requests": 2}, "bob": {"requests": 1}}}
    return tail_artifact(tele, led)


def test_resolve_tail_joins_p99_exemplar_to_lifecycle():
    art = _synthetic_artifact()
    out = resolve_tail(art)
    assert out["resolved"] is True
    # nearest exemplar at-or-above the p99, not just the max
    assert out["request_id"] == "r2"
    assert out["tenant"] == "bob"
    assert out["states"] == ["submitted", "queued", "executing",
                             "delivered"]
    assert out["queue_wait_s"] == pytest.approx(0.2)
    assert out["execute_s"] == pytest.approx(0.3)
    assert out["flush_trace"] == "t000099"


def test_resolve_tail_reason_codes_empty_artifact():
    out = resolve_tail({"p99_s": None, "exemplars": [],
                        "lifecycle": []})
    assert out["resolved"] is False
    assert out["reason"] == "no_exemplars"


def test_obs_tail_cli_resolves_artifact(tmp_path, capsys):
    from pint_tpu.obs.__main__ import main

    art = _synthetic_artifact()
    p = tmp_path / "tail.json"
    p.write_text(json.dumps(art))
    assert main(["tail", str(p)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["resolved"] is True and out["tenant"] == "bob"
    # --trace resolves a specific request instead of the p99 pick
    tr = art["lifecycle"][0]["trace"]
    assert main(["tail", str(p), "--trace", tr]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["request_id"] == "r0"


# -- cardinality caps ------------------------------------------------


def test_registry_label_cap_folds_to_other(monkeypatch):
    monkeypatch.setenv("PINT_TPU_LABEL_CAP", "2")
    reg = Registry()
    for t in ("a", "b", "c", "d"):
        reg.counter("serve.tenant.requests", labels={"tenant": t}).inc()
    snap = reg.snapshot()
    counters = snap["counters"]
    assert counters['serve.tenant.requests{tenant="a"}'] == 1
    assert counters['serve.tenant.requests{tenant="b"}'] == 1
    # c and d fold into one "other" series; each fold ticks the meter
    assert counters['serve.tenant.requests{tenant="other"}'] == 2
    assert counters["metrics.label_overflow"] == 2
    assert 'serve.tenant.requests{tenant="c"}' not in counters


def test_tenant_rows_fold_preserves_totals():
    tele = ServeTelemetry()
    for i in range(6):
        for k in range(6 - i):
            tele.record(request_id=f"r{i}-{k}", tenant=f"t{i}",
                        status="ok", total_s=0.01 * (i + 1))
    rows = tele.tenant_rows(cap=2)
    assert set(rows) == {"t0", "t1", "other"}
    assert rows["t0"]["requests"] == 6
    assert rows["other"]["requests"] == sum(range(1, 5))
    assert sum(r["requests"] for r in rows.values()) == 21
    assert rows["other"]["p99_s"] is not None


# -- engine wiring ---------------------------------------------------


def test_engine_happy_path_lifecycle(pulsar):
    m, t = pulsar
    led = LifecycleLedger(capacity=64)
    eng = ServeEngine(max_batch=1, max_latency_s=1e9, bucket_floor=32,
                      reqlife=led)
    res = eng.submit(ResidualRequest(m, t, tenant="alice"))
    assert res.status == "ok"
    rec = led.record(res.request.request_id)
    assert rec["tenant"] == "alice"
    assert rec["terminal"] is True
    assert [s["state"] for s in rec["states"]] == [
        "submitted", "queued", "packed", "executing", "delivered"]
    # the delivery joins the request plane to the flush span
    assert rec["attrs"].get("flush_trace")
    assert led.nonterminal_ids() == []
    # result telemetry carries the trace id the ledger minted
    assert res.telemetry.get("trace") == rec["trace"]


def test_engine_shed_is_terminal_with_reason(pulsar):
    m, t = pulsar
    clock = FakeClock()
    led = LifecycleLedger(capacity=64, clock=clock)
    eng = ServeEngine(max_batch=8, max_latency_s=0.2, bucket_floor=32,
                      clock=clock, reqlife=led)
    res = eng.submit(ResidualRequest(m, t, deadline_s=0.1))
    clock.advance(0.3)
    eng.poll()
    assert res.status == "shed"
    rec = led.record(res.request.request_id)
    assert rec["state"] == "shed" and rec["terminal"] is True
    shed = [s for s in rec["states"] if s["state"] == "shed"]
    assert shed and shed[0]["reason"] == "deadline"
    assert led.nonterminal_ids() == []


def test_recover_ledgers_replayed_and_re_executed(pulsar, tmp_path):
    m, t = pulsar

    def req(rid):
        return FitRequest(m, t, method="wls", maxiter=2,
                          request_id=rid, tenant="carol")

    # a dead process's journal: r0 committed, r1 accepted but pending
    ddir = tmp_path / "durable"
    j = RequestJournal(ddir)
    j.record_intake(req("r0"))
    j.record_commit("r0", "ok", value={"marker": 1.0},
                    telemetry={"tenant": "carol"})
    j.record_intake(req("r1"))
    j.close()

    led = LifecycleLedger(capacity=64)
    eng = ServeEngine(max_batch=1, max_latency_s=1e9, bucket_floor=32,
                      durable_dir=ddir, reqlife=led)
    rep = eng.recover()
    assert rep["n_committed"] == 1 and rep["n_replayed"] == 1
    # committed: terminal straight from the journal, no serve path
    r0 = led.record("r0")
    assert r0["state"] == "replayed_committed" and r0["terminal"]
    assert r0["tenant"] == "carol"
    # pending: re_executed marker, then the live machine ran it to a
    # real terminal state — recover() drains before returning
    r1 = led.record("r1")
    states = [s["state"] for s in r1["states"]]
    assert "re_executed" in states
    assert r1["terminal"] and r1["state"] == "delivered"
    assert led.nonterminal_ids() == []
    eng.journal.close()


# -- the acceptance capstone: serve bench invariants -----------------


def test_serve_stream_exactly_one_terminal_and_bitwise():
    """Every request in a served stream reaches exactly one terminal
    state, the ledger-on run is bitwise identical to ledger-off, the
    ledger tax stays under the 1% budget, and the emitted tail
    artifact resolves a real p99 exemplar end-to-end."""
    from pint_tpu.scripts.pint_serve_bench import run_serve_stream

    rep = run_serve_stream(n_requests=12, sizes=(32,), per_combo=1,
                           maxiter=2, bucket_floor=32,
                           compare_offline=False,
                           tenants=("alice", "bob"))
    assert rep["reqlife_exactly_one_terminal"] is True
    assert rep["reqlife_nonterminal"] == 0
    assert rep["reqlife_lost_records"] == 0
    assert rep["reqlife_double_terminal"] == 0
    assert rep["reqlife_bitwise_on_off"] is True
    assert set(rep["tenants"]) == {"alice", "bob"}
    out = resolve_tail(rep["tail_artifact"])
    assert out["resolved"] is True
    assert out["tenant"] in ("alice", "bob")
    assert out["queue_wait_s"] is not None
    assert out["execute_s"] is not None
