"""Double-double arithmetic precision tests.

Equivalent of the reference's longdouble precision tests
(reference: tests/test_precision.py) — the DD layer must beat x86
longdouble (64-bit mantissa) so golden comparisons hold at <1 ns.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pint_tpu import dd

LD = np.longdouble


def dd_to_ld(x: dd.DD):
    return LD(np.asarray(x.hi)) + LD(np.asarray(x.lo))


def test_two_sum_exact():
    a = jnp.float64(1e16)
    b = jnp.float64(1.0)
    s = dd.two_sum(a, b)
    assert float(s.hi) == 1e16 + 1.0 or float(s.lo) != 0.0
    assert dd_to_ld(s) == LD(1e16) + LD(1.0)


def test_two_prod_exact():
    a = jnp.float64(1.1)
    b = jnp.float64(1e9 + 1 / 3)
    p = dd.two_prod(a, b)
    # exact product of the two representable doubles
    expected = LD(float(a)) * LD(float(b))
    assert abs(float(dd_to_ld(p) - expected)) < 1e-25 * abs(float(expected))


def test_add_mul_precision():
    rng = np.random.default_rng(0)
    a = rng.uniform(1e8, 1e9, 100)
    b = rng.uniform(-1e-9, 1e-9, 100)
    x = dd.from_2sum(jnp.array(a), jnp.array(b))
    y = dd.mul(x, x)
    expected = (LD(a) + LD(b)) ** 2
    got = dd_to_ld(y)
    rel = np.abs((got - expected) / expected).astype(float)
    # comparison is limited by the longdouble reference itself (~5e-20)
    assert rel.max() < 5e-19


def test_div():
    x = dd.from_f64(jnp.float64(1.0))
    y = dd.from_f64(jnp.float64(3.0))
    q = dd.div(x, y)
    expected = LD(1) / LD(3)
    assert abs(float(dd_to_ld(q) - expected)) < 1e-31


def test_horner_spindown_scale():
    """Phase over 20 years at F0=339 Hz must keep frac-phase to <1e-9 cycles."""
    F0 = 339.31568729824
    F1 = -1.6e-15
    dt = dd.from_2sum(jnp.float64(20 * 365.25 * 86400.0), jnp.float64(0.123456789))
    ph = dd.horner(dt, [0.0, F0, F1])
    dt_ld = LD(20 * 365.25 * 86400.0) + LD(0.123456789)
    expected = LD(F0) * dt_ld + LD(F1) * dt_ld**2 / 2
    got = dd_to_ld(ph)
    # ~2e11 cycles total; fractional agreement to <1e-9 cycles
    assert abs(float(got - expected)) < 1e-9


def test_floor_round():
    x = dd.from_2sum(jnp.float64(2.5), jnp.float64(-1e-20))
    f = dd.floor(x)
    assert float(dd.to_f64(f)) == 2.0
    r = dd.round_half(dd.from_2sum(jnp.float64(2.5), jnp.float64(1e-20)))
    assert float(dd.to_f64(r)) == 3.0


def test_jit_and_vmap():
    @jax.jit
    def f(hi, lo):
        x = dd.DD(hi, lo)
        return dd.to_f64(dd.mul(x, x))

    hi = jnp.arange(1.0, 5.0)
    lo = jnp.zeros(4)
    np.testing.assert_allclose(np.asarray(f(hi, lo)), np.arange(1.0, 5.0) ** 2)


def test_horner_deriv():
    dt = dd.from_f64(jnp.float64(100.0))
    coeffs = [0.0, 2.0, 3.0, 4.0]
    d1 = dd.horner_deriv(dt, coeffs, 1)
    # d/dt [2t + 3t^2/2 + 4t^3/6] = 2 + 3t + 2t^2
    assert float(dd.to_f64(d1)) == pytest.approx(2 + 3 * 100 + 2 * 100**2, rel=1e-12)
