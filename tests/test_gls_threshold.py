"""GLS eigenvalue-threshold defense (VERDICT round-1 weak item 7).

The GLS normal-equation solver drops eigenvalue directions below
cut = max(threshold^2, 3e-14) relative to the largest. These tests pin
both sides of that floor: (a) an exactly-degenerate direction (duplicate
design column) must be dropped — its eigenvalue appears at the eigh
noise floor ~n*eps; (b) a genuinely small but real direction several
decades above the floor must be retained and fitted.
"""

import copy
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.fitter import GLSFitter, WLSFitter
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR TESTTH
RAJ 04:37:00.0
DECJ -47:15:00.0
F0 173.7 1
F1 -1.7e-15 1
PEPOCH 55500
DM 2.64 1
"""


def _toas(m, n=60, seed=6):
    mjds = np.linspace(55000, 56000, n)
    freqs = np.where(np.arange(n) % 2, 1400.0, 800.0)
    return make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                   obs="gbt", add_noise=True, seed=seed)


def test_exact_degeneracy_dropped():
    """Two JUMPs covering the SAME TOA subset are exactly degenerate
    (duplicate design columns): the fit must return finite parameters
    with zero update along the degenerate difference direction instead
    of exploding on a noise eigenvalue."""
    par = PAR + ("JUMP -f L-wide 0.0 1\nJUMP -f L-wide 0.0 1\n")
    m = get_model(par)
    t = _toas(m)
    for i, f in enumerate(t.flags):
        f["f"] = "L-wide" if i % 2 else "S-wide"
    f = GLSFitter(t, copy.deepcopy(m))
    chi2 = f.fit_toas(maxiter=2)
    assert np.isfinite(chi2)
    j1 = f.model.JUMP1.value
    j2 = f.model.JUMP2.value
    assert np.isfinite(j1) and np.isfinite(j2)
    # the degenerate direction (j1 - j2) received no update; the
    # physical sum stays bounded by the per-TOA error scale
    assert abs(j1 - j2) < 1e-9
    assert abs(j1 + j2) < 5e-5


def test_small_but_real_direction_retained():
    """F1's normalized eigenvalue sits decades below the leading ones
    but far above the 3e-14 floor: it must be fitted, not dropped."""
    m = get_model(PAR)
    t = _toas(m, n=80)
    mp = copy.deepcopy(m)
    mp.F1.value = m.F1.value - 3e-18  # small injected F1 offset
    f = GLSFitter(t, mp)
    f.fit_toas(maxiter=2)
    # recovered back to truth within uncertainty (if the F1 direction
    # were dropped, the offset would persist exactly)
    assert abs(f.model.F1.value - m.F1.value) < max(
        3 * (f.model.F1.uncertainty or 0), 1e-18)


def test_gls_matches_wls_without_noise():
    """With no correlated noise, GLS and WLS must agree (the threshold
    machinery must not perturb a well-conditioned fit)."""
    m = get_model(PAR)
    t = _toas(m, n=70, seed=8)
    fg = GLSFitter(t, copy.deepcopy(m))
    fg.fit_toas(maxiter=2)
    fw = WLSFitter(t, copy.deepcopy(m))
    fw.fit_toas(maxiter=2)
    for p in m.free_params:
        a = getattr(fg.model, p)
        b = getattr(fw.model, p)
        assert abs(a.value - b.value) <= 1e-3 * max(
            b.uncertainty or 1e-12, 1e-15), p


def test_extreme_prior_spread_does_not_zero_params():
    """Regression: a steep red-noise spectrum gives phi_inv spanning
    ~30 decades; before the prior-folded normalization the relative
    eigenvalue cut zeroed EVERY parameter update (dx ~ 1e-47), so the
    fit silently returned the input model."""
    import copy

    import numpy as np

    from pint_tpu.fitter import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TPRI\nRAJ 01:00:00\nDECJ 05:00:00\nF0 300.0 1\nF1 -1e-15 1\n"
           "PEPOCH 55500\nDM 12.0 1\n"
           # RNIDX -6 over 30 harmonics: weight ratio ~ 30^6 ~ 7e8, and
           # the tiny absolute RNAMP pushes 1/w to ~1e40 s^-2
           "RNAMP 1e-16\nRNIDX -6.0\nTNREDC 30\nECORR 0.5\n")
    m = get_model(par)
    rng = np.random.default_rng(4)
    days = np.sort(rng.uniform(55000, 56000, 30))
    mjds = np.sort(np.concatenate([days, days + 30.0 / 86400]))
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=4)
    m2 = copy.deepcopy(m)
    df0 = 4e-10
    m2.F0.value += df0
    f = GLSFitter(t, m2)
    f.fit_toas(maxiter=3)
    # the fitter must actually MOVE F0 back (not silently no-op)
    assert abs(f.model.F0.value - m.F0.value) < 0.2 * df0
    assert f.model.F0.uncertainty is not None and f.model.F0.uncertainty > 0


# ---- mixed-precision GLS (f32 Gram + f64 iterative refinement) ----
# (reference: SURVEY section 7.1 precision strategy — f64 only where
# needed; the Gram product is MXU-native f32 on TPU, refined back)


def test_mixed_refine_unit_behavior():
    """gls_eigh_refine on a well-conditioned f32 Gram converges to the
    f64 solution; on a pathologically conditioned one it REPORTS
    non-convergence via rel_resid instead of returning garbage."""
    import jax.numpy as jnp

    from pint_tpu.fitter import gls_eigh_refine, gls_eigh_solve, gls_gram

    rng = np.random.default_rng(3)
    n, k = 400, 30
    Mn = jnp.asarray(rng.standard_normal((n, k)))
    Mn = Mn / jnp.linalg.norm(Mn, axis=0)
    q = jnp.zeros(k)
    b = Mn.T @ jnp.asarray(rng.standard_normal(n))
    A32 = gls_gram(Mn, q, "mixed")
    assert float(jnp.max(jnp.abs(A32 - (Mn.T @ Mn)))) > 0  # f32 really active
    dx64, _ = gls_eigh_solve(Mn.T @ Mn, b)
    dxn, _, relres = gls_eigh_refine(A32, b, lambda v: Mn.T @ (Mn @ v))
    assert float(relres) < 1e-10
    np.testing.assert_allclose(np.asarray(dxn), np.asarray(dx64),
                               rtol=1e-9, atol=1e-12)
    # pathological: nearly collinear columns, kept spectrum ~1e10 wide
    base = rng.standard_normal((n, 4))
    Mbad = jnp.asarray(base @ rng.standard_normal((4, k))
                       + 1e-6 * rng.standard_normal((n, k)))
    Mbad = Mbad / jnp.linalg.norm(Mbad, axis=0)
    bb = Mbad.T @ jnp.asarray(rng.standard_normal(n))
    _, _, relres_bad = gls_eigh_refine(
        gls_gram(Mbad, q, "mixed"), bb, lambda v: Mbad.T @ (Mbad @ v))
    assert float(relres_bad) > 1e-8  # diagnostic fires -> caller falls back


def test_mixed_precision_matches_f64_single_pulsar():
    """GLSFitter(precision='mixed') reproduces the f64 fit to <= 1e-9
    relative in every parameter and uncertainty to ~1e-5 (VERDICT r4
    item 3 acceptance)."""
    m = get_model(PAR + "RNAMP 1e-14\nRNIDX -3.0\nTNREDC 10\nECORR 0.6\n")
    t = _toas(m, n=80, seed=9)
    f64 = GLSFitter(t, m)
    chi64 = f64.fit_toas(maxiter=2)
    m2 = get_model(PAR + "RNAMP 1e-14\nRNIDX -3.0\nTNREDC 10\nECORR 0.6\n")
    fmx = GLSFitter(t, m2)
    chimx = fmx.fit_toas(maxiter=2, precision="mixed")
    assert chimx == pytest.approx(chi64, rel=1e-9)
    for p in f64.model.free_params:
        v64 = getattr(f64.model, p).value
        vmx = getattr(fmx.model, p).value
        assert vmx == pytest.approx(v64, rel=1e-9, abs=1e-300), p
        u64 = getattr(f64.model, p).uncertainty
        umx = getattr(fmx.model, p).uncertainty
        assert umx == pytest.approx(u64, rel=1e-4), p


def test_mixed_precision_matches_f64_pta_batch():
    """PTABatch.gls_fit(precision='mixed'): parameters <= 1e-9 relative
    vs f64 on BOTH ECORR solve modes (marginalized + dense)."""
    from pint_tpu.parallel import PTABatch

    rng = np.random.default_rng(0)
    models, toas_list = [], []
    for i in range(3):
        par = (f"PSR TM{i}\nRAJ {10+i}:00:00.0\nDECJ {5+i}:30:00.0\n"
               f"F0 {200+7*i}.5 1\nF1 -{2+i}e-16 1\nPEPOCH 55500\n"
               f"DM {10+i}.5 1\n"
               "EFAC -f L-wide 1.1\nEQUAD -f L-wide 0.4\n"
               "ECORR -f L-wide 0.6\n"
               "RNAMP 1e-14\nRNIDX -3.0\nTNREDC 8\n")
        m = get_model(par)
        n = 40
        days = np.sort(rng.uniform(55000, 55800, n // 2))
        mjds = np.sort(np.concatenate([days, days + 30.0 / 86400]))
        freqs = np.where(np.arange(n) % 2, 1400.0, 800.0)
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                    obs="gbt", add_noise=True, seed=i,
                                    iterations=1)
        for fl in t.flags:
            fl["f"] = "L-wide"
        models.append(m)
        toas_list.append(t)
    pta = PTABatch(models, toas_list)
    for mode in ("auto", "dense"):
        x64, chi64, cov64 = pta.gls_fit(maxiter=2, ecorr_mode=mode)
        xmx, chimx, covmx = pta.gls_fit(maxiter=2, ecorr_mode=mode,
                                        precision="mixed")
        np.testing.assert_allclose(np.asarray(xmx), np.asarray(x64),
                                   rtol=1e-9, atol=1e-30)
        np.testing.assert_allclose(np.asarray(chimx), np.asarray(chi64),
                                   rtol=1e-9)
        d64 = np.sqrt(np.einsum("pii->pi", np.asarray(cov64)))
        dmx = np.sqrt(np.einsum("pii->pi", np.asarray(covmx)))
        np.testing.assert_allclose(dmx, d64, rtol=1e-4)


def test_mixed_precision_fallback_warns():
    """A kept spectrum too wide for the f32 preconditioner triggers the
    automatic f64 refit (with a warning) instead of silently returning
    an unconverged solution."""
    from pint_tpu import fitter as fit_mod
    from pint_tpu.fitter import gls_solve, stack_noise_bases
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    n, k = 300, 20
    base = rng.standard_normal((n, 3))
    M = jnp.asarray(base @ rng.standard_normal((3, k))
                    + 1e-6 * rng.standard_normal((n, k)))
    r = jnp.asarray(rng.standard_normal(n))
    sigma = jnp.ones(n)
    sqrt_phi_inv = jnp.zeros(k)
    dx64, _, chi64 = gls_solve(M, r, sigma, sqrt_phi_inv)
    dxmx, _, chimx = gls_solve(M, r, sigma, sqrt_phi_inv,
                               precision="mixed")
    # the fallback makes mixed == f64 even on this hostile spectrum
    np.testing.assert_allclose(np.asarray(dxmx), np.asarray(dx64),
                               rtol=1e-9, atol=1e-12)
    assert chimx == pytest.approx(chi64, rel=1e-9)


def test_mixed_precision_via_downhill_and_wideband():
    """precision='mixed' flows through DownhillGLSFitter and
    WidebandTOAFitter identically to f64 (the passthrough plumbing)."""
    from pint_tpu.fitter import DownhillGLSFitter, WidebandTOAFitter

    par = PAR + "RNAMP 1e-14\nRNIDX -3.0\nTNREDC 6\n"
    m = get_model(par)
    t = _toas(m, n=40, seed=2)
    for fl in t.flags:
        fl["pp_dm"] = "12.0"
        fl["pp_dme"] = "1e-4"
    c1 = DownhillGLSFitter(t, get_model(par)).fit_toas(maxiter=4)
    c2 = DownhillGLSFitter(t, get_model(par)).fit_toas(
        maxiter=4, precision="mixed")
    assert c2 == pytest.approx(c1, rel=1e-9)
    w1 = WidebandTOAFitter(t, get_model(par)).fit_toas(maxiter=2)
    w2 = WidebandTOAFitter(t, get_model(par)).fit_toas(
        maxiter=2, precision="mixed")
    assert w2 == pytest.approx(w1, rel=1e-9)
    from pint_tpu.fitter import WidebandDownhillFitter, WidebandLMFitter

    d1 = WidebandDownhillFitter(t, get_model(par)).fit_toas(maxiter=6)
    d2 = WidebandDownhillFitter(t, get_model(par)).fit_toas(
        maxiter=6, precision="mixed")
    assert d2 == pytest.approx(d1, rel=1e-8)
    l1 = WidebandLMFitter(t, get_model(par)).fit_toas(maxiter=8)
    l2 = WidebandLMFitter(t, get_model(par)).fit_toas(
        maxiter=8, precision="mixed")
    assert l2 == pytest.approx(l1, rel=1e-8)
    with pytest.raises(ValueError, match="precision"):
        WidebandTOAFitter(t, get_model(par)).fit_toas(precision="bf16")


def test_wideband_lm_mixed_noncontracting_preconditioner(monkeypatch):
    """Regression for the WidebandLMFitter mixed path: when the f32
    Gram fails to precondition a damped step (refinement relres above
    tolerance), the fitter must warn and redo THAT step with the f64
    Gram instead of silently keeping the unconverged update. Forced
    here by patching gls_gram to return a non-contracting mixed Gram
    (diagonal inflated by 10x the matrix scale, so refinement against
    the true damped operator stalls at O(1) relative residual)."""
    import jax.numpy as jnp

    from pint_tpu import fitter as fit_mod
    from pint_tpu.fitter import WidebandLMFitter

    par = PAR + "RNAMP 1e-14\nRNIDX -3.0\nTNREDC 6\n"
    m = get_model(par)
    t = _toas(m, n=40, seed=2)
    for fl in t.flags:
        fl["pp_dm"] = "12.0"
        fl["pp_dme"] = "1e-4"

    c_64 = WidebandLMFitter(t, get_model(par)).fit_toas(maxiter=8)

    real_gram = fit_mod.gls_gram
    mixed_calls = {"n": 0}

    def noncontracting_gram(Mn, q, precision="f64"):
        A = real_gram(Mn, q, "f64")
        if precision == "mixed":
            mixed_calls["n"] += 1
            return A + 10.0 * jnp.max(jnp.abs(A)) * jnp.eye(A.shape[0])
        return A

    monkeypatch.setattr(fit_mod, "gls_gram", noncontracting_gram)
    with pytest.warns(UserWarning,
                      match="mixed-precision LM refinement"):
        c_mx = WidebandLMFitter(t, get_model(par)).fit_toas(
            maxiter=8, precision="mixed")
    assert mixed_calls["n"] >= 1  # the sabotaged path actually ran
    # every sabotaged step fell back to the f64 Gram, so the fit
    # matches the pure-f64 trajectory instead of quietly degrading
    assert c_mx == pytest.approx(c_64, rel=1e-8)
