"""GLS eigenvalue-threshold defense (VERDICT round-1 weak item 7).

The GLS normal-equation solver drops eigenvalue directions below
cut = max(threshold^2, 3e-14) relative to the largest. These tests pin
both sides of that floor: (a) an exactly-degenerate direction (duplicate
design column) must be dropped — its eigenvalue appears at the eigh
noise floor ~n*eps; (b) a genuinely small but real direction several
decades above the floor must be retained and fitted.
"""

import copy
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.fitter import GLSFitter, WLSFitter
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR TESTTH
RAJ 04:37:00.0
DECJ -47:15:00.0
F0 173.7 1
F1 -1.7e-15 1
PEPOCH 55500
DM 2.64 1
"""


def _toas(m, n=60, seed=6):
    mjds = np.linspace(55000, 56000, n)
    freqs = np.where(np.arange(n) % 2, 1400.0, 800.0)
    return make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                   obs="gbt", add_noise=True, seed=seed)


def test_exact_degeneracy_dropped():
    """Two JUMPs covering the SAME TOA subset are exactly degenerate
    (duplicate design columns): the fit must return finite parameters
    with zero update along the degenerate difference direction instead
    of exploding on a noise eigenvalue."""
    par = PAR + ("JUMP -f L-wide 0.0 1\nJUMP -f L-wide 0.0 1\n")
    m = get_model(par)
    t = _toas(m)
    for i, f in enumerate(t.flags):
        f["f"] = "L-wide" if i % 2 else "S-wide"
    f = GLSFitter(t, copy.deepcopy(m))
    chi2 = f.fit_toas(maxiter=2)
    assert np.isfinite(chi2)
    j1 = f.model.JUMP1.value
    j2 = f.model.JUMP2.value
    assert np.isfinite(j1) and np.isfinite(j2)
    # the degenerate direction (j1 - j2) received no update; the
    # physical sum stays bounded by the per-TOA error scale
    assert abs(j1 - j2) < 1e-9
    assert abs(j1 + j2) < 5e-5


def test_small_but_real_direction_retained():
    """F1's normalized eigenvalue sits decades below the leading ones
    but far above the 3e-14 floor: it must be fitted, not dropped."""
    m = get_model(PAR)
    t = _toas(m, n=80)
    mp = copy.deepcopy(m)
    mp.F1.value = m.F1.value - 3e-18  # small injected F1 offset
    f = GLSFitter(t, mp)
    f.fit_toas(maxiter=2)
    # recovered back to truth within uncertainty (if the F1 direction
    # were dropped, the offset would persist exactly)
    assert abs(f.model.F1.value - m.F1.value) < max(
        3 * (f.model.F1.uncertainty or 0), 1e-18)


def test_gls_matches_wls_without_noise():
    """With no correlated noise, GLS and WLS must agree (the threshold
    machinery must not perturb a well-conditioned fit)."""
    m = get_model(PAR)
    t = _toas(m, n=70, seed=8)
    fg = GLSFitter(t, copy.deepcopy(m))
    fg.fit_toas(maxiter=2)
    fw = WLSFitter(t, copy.deepcopy(m))
    fw.fit_toas(maxiter=2)
    for p in m.free_params:
        a = getattr(fg.model, p)
        b = getattr(fw.model, p)
        assert abs(a.value - b.value) <= 1e-3 * max(
            b.uncertainty or 1e-12, 1e-15), p


def test_extreme_prior_spread_does_not_zero_params():
    """Regression: a steep red-noise spectrum gives phi_inv spanning
    ~30 decades; before the prior-folded normalization the relative
    eigenvalue cut zeroed EVERY parameter update (dx ~ 1e-47), so the
    fit silently returned the input model."""
    import copy

    import numpy as np

    from pint_tpu.fitter import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TPRI\nRAJ 01:00:00\nDECJ 05:00:00\nF0 300.0 1\nF1 -1e-15 1\n"
           "PEPOCH 55500\nDM 12.0 1\n"
           # RNIDX -6 over 30 harmonics: weight ratio ~ 30^6 ~ 7e8, and
           # the tiny absolute RNAMP pushes 1/w to ~1e40 s^-2
           "RNAMP 1e-16\nRNIDX -6.0\nTNREDC 30\nECORR 0.5\n")
    m = get_model(par)
    rng = np.random.default_rng(4)
    days = np.sort(rng.uniform(55000, 56000, 30))
    mjds = np.sort(np.concatenate([days, days + 30.0 / 86400]))
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=4)
    m2 = copy.deepcopy(m)
    df0 = 4e-10
    m2.F0.value += df0
    f = GLSFitter(t, m2)
    f.fit_toas(maxiter=3)
    # the fitter must actually MOVE F0 back (not silently no-op)
    assert abs(f.model.F0.value - m.F0.value) < 0.2 * df0
    assert f.model.F0.uncertainty is not None and f.model.F0.uncertainty > 0
