"""Quantitative tests for the exotic binary variants.

(reference pattern: tests/test_dd.py, tests/test_ddk.py,
tests/test_ell1h.py golden Tempo2 comparisons; here each variant is
pinned against INDEPENDENT formulas — derived_quantities GR relations,
hand Kopeikin expressions — so dropping a physics term fails the test.)
"""

import copy
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_fromMJDs

BASE = """
PSR TESTBV
RAJ 06:30:00.0
DECJ -05:00:00.0
F0 315.4 1
F1 -6e-16 1
PEPOCH 55500
DM 12.4 1
"""


def _toas(m, n=120, span=(55000, 56000), **kw):
    mjds = np.linspace(*span, n)
    return make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                   obs="gbt", add_noise=False, **kw)


def test_ddgr_pk_params_match_derived_quantities():
    """DDGR's internally derived PK parameters must equal the
    independent GR formulas in derived_quantities.py."""
    from pint_tpu.derived_quantities import gamma, omdot, pbdot

    mtot, m2, pb_days, ecc, a1 = 2.8, 1.3, 0.3229, 0.617, 2.342
    par = BASE + (f"BINARY DDGR\nPB {pb_days} 1\nA1 {a1} 1\nT0 55100.0 1\n"
                  f"ECC {ecc} 1\nOM 226.0 1\nMTOT {mtot}\nM2 {m2}\n")
    m = get_model(par)
    comp = m.components["BinaryDDGR"]
    t = _toas(m, n=10)
    prepared = m.prepare(t)
    params = {k: np.asarray(v) for k, v in prepared.params0.items()}
    gr = comp._gr_params(params, prepared.prep)
    mp = mtot - m2
    # OMDOT: k [rad advance per orbit radian] * n -> deg/yr
    omdot_expect = omdot(mp, m2, pb_days, ecc)  # deg/yr
    n_orb = 2 * np.pi / (pb_days * 86400.0)
    omdot_got = float(gr["k"] * n_orb) * (365.25 * 86400.0) / np.deg2rad(1.0)
    assert omdot_got == pytest.approx(omdot_expect, rel=1e-9)
    # GAMMA (Einstein delay amplitude, s)
    assert float(gr["GAMMA"]) == pytest.approx(gamma(mp, m2, pb_days, ecc),
                                               rel=1e-9)
    # PBDOT (GW decay, dimensionless)
    assert float(gr["PBDOT"]) == pytest.approx(pbdot(mp, m2, pb_days, ecc),
                                               rel=1e-9)
    # SINI from the mass function geometry: sini = a1 * n^(2/3) M^(2/3)
    # / (Tsun^(1/3) m2)  (Damour & Deruelle 1986)
    from pint_tpu.constants import TSUN_S

    sini_expect = (a1 * n_orb ** (2 / 3) * mtot ** (2 / 3)
                   / (TSUN_S ** (1 / 3) * m2))
    assert float(gr["SINI"]) == pytest.approx(sini_expect, rel=1e-12)


def test_ddgr_equals_dd_with_explicit_pk():
    """DDGR delays == plain DD with the PK params set to the GR values
    (would fail if any derived term were dropped or mis-applied)."""
    par_gr = BASE + ("BINARY DDGR\nPB 0.4 1\nA1 2.0 1\nT0 55100.0 1\n"
                     "ECC 0.3 1\nOM 100.0 1\nMTOT 2.6\nM2 1.2\n")
    mgr = get_model(par_gr)
    comp = mgr.components["BinaryDDGR"]
    t = _toas(mgr, n=200, span=(55000, 55400))
    prepared = mgr.prepare(t)
    params = {k: np.asarray(v) for k, v in prepared.params0.items()}
    gr = comp._gr_params(params, prepared.prep)
    n_orb = 2 * np.pi / (0.4 * 86400.0)
    omdot_degyr = float(gr["k"] * n_orb) * (365.25 * 86400.0) / np.deg2rad(1.0)
    par_dd = BASE + (
        "BINARY DD\nPB 0.4 1\nA1 2.0 1\nT0 55100.0 1\n"
        "ECC 0.3 1\nOM 100.0 1\nM2 1.2\n"
        f"SINI {float(gr['SINI']):.15g}\nGAMMA {float(gr['GAMMA']):.15g}\n"
        f"OMDOT {omdot_degyr:.15g}\nPBDOT {float(gr['PBDOT']):.15g}\n"
        f"DR {float(gr['DR']):.15g}\nDTH {float(gr['DTH']):.15g}\n")
    mdd = get_model(par_dd)
    d_gr = np.asarray(mgr.delay(t))
    d_dd = np.asarray(mdd.delay(t))
    # few-ulp differences on ~400 s absolute delays (5e-14 rel)
    np.testing.assert_allclose(d_gr, d_dd, rtol=0, atol=1e-10)


def test_dds_equals_dd_at_high_inclination():
    """DDS(SHAPMAX) delay == DD(SINI) delay with SINI = 1-exp(-SHAPMAX),
    at high inclination where the reparameterization matters."""
    sini = 0.9995
    shapmax = -np.log(1 - sini)
    common = ("PB 1.2 1\nA1 8.0 1\nT0 55100.0 1\nECC 0.05 1\nOM 30.0 1\n"
              "M2 0.4\n")
    mdd = get_model(BASE + "BINARY DD\n" + common + f"SINI {sini}\n")
    mdds = get_model(BASE + "BINARY DDS\n" + common
                     + f"SHAPMAX {shapmax:.15g}\n")
    t = _toas(mdd, n=150, span=(55090, 55110))
    np.testing.assert_allclose(np.asarray(mdds.delay(t)),
                               np.asarray(mdd.delay(t)), rtol=0, atol=1e-12)
    # and the Shapiro term is actually large here (sanity: drop M2)
    mdd0 = copy.deepcopy(mdd)
    mdd0.M2.value = 0.0
    assert np.abs(np.asarray(mdd.delay(t))
                  - np.asarray(mdd0.delay(t))).max() > 1e-6


def test_ddk_proper_motion_secular_terms():
    """DDK K96 secular terms: with KOM=0 and pure north proper motion,
    x(t) = x + x*cot(i)*mu_n*dt -> the binary-delay difference envelope
    vs plain DD grows as |dx(t)| (Kopeikin 1996 eq. 10)."""
    kin = 60.0
    mu_n_masyr = 30.0
    common = (f"PB 10.0 1\nA1 20.0 1\nT0 55500.0 1\nECC 0.01 1\nOM 45.0 1\n"
              f"M2 0.2\n")
    par_ddk = BASE.replace("DECJ -05:00:00.0 ",
                           "DECJ -05:00:00.0 ") + (
        f"PMDEC {mu_n_masyr}\nPX 0\n"
        "BINARY DDK\n" + common + f"KIN {kin}\nKOM 0.0\nK96 1\n")
    par_dd = BASE + (f"PMDEC {mu_n_masyr}\nPX 0\nBINARY DD\n" + common
                     + f"SINI {np.sin(np.deg2rad(kin)):.15g}\n")
    mk = get_model(par_ddk)
    md = get_model(par_dd)
    t = _toas(md, n=2000, span=(55500, 56500))
    dk = np.asarray(mk.delay(t))
    dd = np.asarray(md.delay(t))
    diff = dk - dd
    from pint_tpu.constants import MASYR_TO_RADS

    dt_end = (56500 - 55500) * 86400.0
    dx_end = 20.0 / np.tan(np.deg2rad(kin)) * mu_n_masyr * MASYR_TO_RADS * dt_end
    # envelope near the end of the span reaches ~|dx_end| (the orbit
    # phase sweeps many cycles over the last ~10% of the span)
    tail = diff[int(0.95 * len(diff)):]  # ~5 orbits, 20 samples/orbit
    assert np.abs(tail).max() == pytest.approx(abs(dx_end), rel=0.2)
    # and the effect is absent with zero proper motion
    mk0 = get_model(par_ddk.replace(f"PMDEC {mu_n_masyr}", "PMDEC 0"))
    md0 = get_model(par_dd.replace(f"PMDEC {mu_n_masyr}", "PMDEC 0"))
    diff0 = np.asarray(mk0.delay(t)) - np.asarray(md0.delay(t))
    assert np.abs(diff0).max() < 0.05 * abs(dx_end)


def test_ddk_annual_orbital_parallax_scale():
    """DDK annual terms scale as 1/distance: halving PX halves the
    DDK-vs-DD delay difference (Kopeikin 1995)."""
    common = ("PB 10.0 1\nA1 20.0 1\nT0 55500.0 1\nECC 0.01 1\nOM 45.0 1\n"
              "M2 0.2\nKIN 60.0\nKOM 30.0\nK96 0\n")
    diffs = {}
    for px in (2.0, 1.0):
        mk = get_model(BASE + f"PMDEC 0\nPX {px}\nBINARY DDK\n" + common)
        md = get_model(BASE + "PMDEC 0\nPX 0\nBINARY DD\n"
                       + common.replace("KIN 60.0\nKOM 30.0\nK96 0\n",
                                        f"SINI {np.sin(np.deg2rad(60.0)):.15g}\n"))
        t = _toas(md, n=200, span=(55500, 55865))
        # disable K96 drift terms: PM zero, so only annual terms remain
        diffs[px] = np.asarray(mk.delay(t)) - np.asarray(md.delay(t))
    r = np.abs(diffs[2.0]).max() / np.abs(diffs[1.0]).max()
    assert r == pytest.approx(2.0, rel=1e-6)
    assert np.abs(diffs[1.0]).max() > 1e-10  # annual terms present


def test_ell1h_h3_h4_vs_m2_sini():
    """ELL1H with exact orthometric (H3, H4) from (M2, SINI) matches
    ELL1's Shapiro delay through the harmonic expansion
    (Freire & Wex 2010)."""
    from pint_tpu.constants import TSUN_S

    m2, sini = 0.3, 0.95
    cosi = np.sqrt(1 - sini**2)
    st = sini / (1 + cosi)
    h3 = TSUN_S * m2 * st**3
    h4 = h3 * st
    common = ("PB 0.8 1\nA1 1.9 1\nTASC 55100.0 1\nEPS1 1e-6 1\n"
              "EPS2 2e-6 1\n")
    mell = get_model(BASE + "BINARY ELL1\n" + common
                     + f"M2 {m2}\nSINI {sini}\n")
    mh = get_model(BASE + "BINARY ELL1H\n" + common
                   + f"H3 {h3:.15e}\nH4 {h4:.15e}\n")
    t = _toas(mell, n=300, span=(55095, 55105))
    d_e = np.asarray(mell.delay(t))
    d_h = np.asarray(mh.delay(t))
    # harmonic truncation error O(stigma^5 * 2r) ~ sub-ns at sini=0.95
    np.testing.assert_allclose(d_h, d_e, rtol=0, atol=2e-8)
    # dropping H4 (pure H3 mode) must change the delay measurably
    mh3 = get_model(BASE + "BINARY ELL1H\n" + common + f"H3 {h3:.15e}\n")
    assert np.abs(np.asarray(mh3.delay(t)) - d_e).max() > 1e-9


def test_mixed_structure_pta_fleet():
    """PTAFleet buckets a mixed batch (isolated + ELL1 binaries) and
    matches per-pulsar fits."""
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.parallel import PTAFleet

    rng = np.random.default_rng(11)
    models, toas_list = [], []
    for i in range(4):
        par = (f"PSR MX{i}\nRAJ {8 + i}:00:00.0\nDECJ {2 * i}:00:00.0\n"
               f"F0 {280 + 3 * i}.5 1\nF1 -{2 + i}e-16 1\nPEPOCH 55500\n"
               f"DM {9 + i}.1 1\n")
        if i % 2:
            par += (f"BINARY ELL1\nPB {1.5 + i} 1\nA1 {2 + i} 1\n"
                    f"TASC 55101.0 1\nEPS1 1e-6 1\nEPS2 -1e-6 1\n")
        m = get_model(par)
        mjds = np.sort(rng.uniform(55000, 56000, 40 + 5 * i))
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                    obs="gbt", add_noise=True, seed=40 + i)
        models.append(m)
        toas_list.append(t)
    fleet = PTAFleet([copy.deepcopy(m) for m in models], toas_list)
    assert len(fleet.batches) == 2  # isolated bucket + binary bucket
    xs, chi2s, covs = fleet.fit(maxiter=3)
    assert fleet.diverged == []
    fmaps = fleet.free_maps()
    for i in range(4):
        f = WLSFitter(toas_list[i], copy.deepcopy(models[i]))
        f.fit_toas(maxiter=3)
        for j, (pname, _, _) in enumerate(fmaps[i]):
            par = getattr(f.model, pname)
            tol = max(1e-2 * (par.uncertainty or 1e-12), 1e-15)
            assert abs(xs[i][j] - par.value) <= tol, (i, pname)


def test_ddh_matches_dd_at_equivalent_shapiro():
    """DDH (orthometric H3/STIGMA) must reproduce DD's delays when the
    parameters map through Freire & Wex 2010: STIGMA = SINI/(1+cos i),
    H3 = Tsun*M2*STIGMA^3 (reference: DDH_model.py)."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    m2, sini = 0.35, 0.92
    cosi = np.sqrt(1 - sini**2)
    stigma = sini / (1 + cosi)
    h3 = 4.925490947e-6 * m2 * stigma**3
    base = ("PSR TDDH\nRAJ 10:00:00\nDECJ 20:00:00\nF0 150.0 1\n"
            "PEPOCH 55300\nDM 5.0\n")
    orb = "PB 8.0\nA1 12.0\nT0 55300\nECC 0.12\nOM 45.0\nGAMMA 1e-4\n"
    m_dd = get_model(base + "BINARY DD\n" + orb +
                     f"M2 {m2}\nSINI {sini}\n")
    m_ddh = get_model(base + "BINARY DDH\n" + orb +
                      f"H3 {h3}\nSTIGMA {stigma}\n")
    mjds = np.linspace(55300, 55316, 400)
    t = make_fake_toas_fromMJDs(mjds, m_dd, error_us=1.0, freq_mhz=1400.0,
                                obs="@", add_noise=False, iterations=0)
    d_dd = np.asarray(m_dd.prepare(t).delay())
    d_ddh = np.asarray(m_ddh.prepare(t).delay())
    # identical to sub-ns (same physics, different parameterization)
    assert np.abs(d_dd - d_ddh).max() < 1e-10


def test_convert_binary_dd_to_ddh_roundtrip():
    from pint_tpu.binaryconvert import convert_binary
    from pint_tpu.models import get_model

    par = ("PSR TCONV\nRAJ 10:00:00\nDECJ 20:00:00\nF0 150.0 1\n"
           "PEPOCH 55300\nDM 5.0\nBINARY DD\nPB 8.0\nA1 12.0\nT0 55300\n"
           "ECC 0.12\nOM 45.0\nM2 0.35 1\nSINI 0.92 1\n")
    m = get_model(par)
    m_ddh = convert_binary(m, "DDH")
    assert "BinaryDDH" in m_ddh.components
    import numpy as np

    sini, m2 = 0.92, 0.35
    cosi = np.sqrt(1 - sini**2)
    st = sini / (1 + cosi)
    assert m_ddh.STIGMA.value == pytest.approx(st, rel=1e-12)
    assert m_ddh.H3.value == pytest.approx(4.925490947e-6 * m2 * st**3,
                                           rel=1e-12)
    back = convert_binary(m_ddh, "DD")
    assert back.SINI.value == pytest.approx(sini, rel=1e-10)
    assert back.M2.value == pytest.approx(m2, rel=1e-10)


def test_ddh_h4_fallback_and_validation():
    """DDH accepts H3+H4 (sigma = H4/H3) like ELL1H, rejects H3 alone,
    and drops the no-op M2/SINI params (review findings)."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.models.timing_model import MissingParameter

    base = ("PSR TDDH2\nRAJ 10:00:00\nDECJ 20:00:00\nF0 150.0 1\n"
            "PEPOCH 55300\nDM 5.0\nBINARY DDH\nPB 8.0\nA1 12.0\nT0 55300\n"
            "ECC 0.12\nOM 45.0\n")
    stigma = 0.55
    h3 = 4.925490947e-6 * 0.35 * stigma**3
    m_h4 = get_model(base + f"H3 {h3}\nH4 {h3 * stigma}\n")
    m_st = get_model(base + f"H3 {h3}\nSTIGMA {stigma}\n")
    assert "M2" not in m_h4.params and "SINI" not in m_h4.params
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    mjds = np.linspace(55300, 55316, 100)
    t = make_fake_toas_fromMJDs(mjds, m_st, error_us=1.0, freq_mhz=1400.0,
                                obs="@", add_noise=False, iterations=0)
    d1 = np.asarray(m_h4.prepare(t).delay())
    d2 = np.asarray(m_st.prepare(t).delay())
    assert np.abs(d1 - d2).max() < 1e-12  # H4/H3 route == STIGMA route
    with pytest.raises(MissingParameter):
        get_model(base + f"H3 {h3}\n")  # H3 alone: loud, not wrong


def test_convert_ddh_to_dds_keeps_companion_mass():
    """DDH -> DDS must derive M2 (review finding: the Shapiro range was
    silently zero)."""
    from pint_tpu.binaryconvert import convert_binary
    from pint_tpu.models import get_model

    import numpy as np

    sini, m2 = 0.92, 0.35
    st = sini / (1 + np.sqrt(1 - sini**2))
    par = ("PSR TC2\nRAJ 10:00:00\nDECJ 20:00:00\nF0 150.0 1\n"
           "PEPOCH 55300\nDM 5.0\nBINARY DDH\nPB 8.0\nA1 12.0\nT0 55300\n"
           f"ECC 0.12\nOM 45.0\nH3 {4.925490947e-6 * m2 * st**3} 1\n"
           f"STIGMA {st} 1\n")
    m = get_model(par)
    dds = convert_binary(m, "DDS")
    assert dds.M2.value == pytest.approx(m2, rel=1e-10)
    assert dds.SHAPMAX.value == pytest.approx(-np.log(1 - sini), rel=1e-10)


def test_kepler_high_eccentricity_convergence():
    """The fixed-iteration Kepler solve must stay at machine precision
    even at e=0.9 (BT/DD family): E - e sin E = M residual < 1e-13 for
    every mean anomaly."""
    import jax.numpy as jnp
    import numpy as np

    from pint_tpu.models.binary.base import kepler_solve

    e = 0.9
    M = jnp.asarray(np.linspace(-20, 20, 4001))
    E = kepler_solve(M, e)
    resid = np.asarray(E - e * jnp.sin(E) - M)
    assert np.abs(resid).max() < 1e-12


def test_dd_high_eccentricity_fit_recovery():
    """A DD binary at e=0.6 (Hulse-Taylor-like) round-trips through
    simulate -> perturb -> fit."""
    import numpy as np

    from pint_tpu.fitter import DownhillWLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR THTLIKE\nRAJ 19:15:28\nDECJ 16:06:27\nF0 16.94 1\n"
           "F1 -2.5e-15 1\nPEPOCH 55300\nDM 168.77 1\nBINARY DD\n"
           "PB 0.322997 1\nA1 2.3418 1\nECC 0.6171 1\nOM 292.54 1\n"
           "T0 55301.0 1\nM2 1.39\nSINI 0.73\nGAMMA 0.0043\n")
    true = get_model(par)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55600, 250), true,
                                error_us=5.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=8)
    start = get_model(par)
    start.ECC.value += 1e-5
    start.OM.value += 0.01
    f = DownhillWLSFitter(t, start)
    f.fit_toas(maxiter=15)
    assert abs(f.model.ECC.value - 0.6171) < 5 * (f.model.ECC.uncertainty or 1)
    assert abs(f.model.OM.value - 292.54) < 5 * (f.model.OM.uncertainty or 1)


def test_bt_piecewise_matches_bt_per_segment():
    """BT_piecewise TOAs inside a window use T0X/A1X, outside the
    globals (reference: binary_piecewise.py::BinaryBTPiecewise)."""
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    base = ("PSR TBTPW\nRAJ 10:00:00\nDECJ 20:00:00\nF0 150.0 1\n"
            "PEPOCH 55300\nDM 5.0\n")
    orb = "PB 8.0\nA1 12.0\nT0 55300\nECC 0.12\nOM 45.0\n"
    orbx = "PB 8.0\nA1 12.05\nT0 55300.0002\nECC 0.12\nOM 45.0\n"
    m_pw = get_model(base + "BINARY BT_piecewise\n" + orb +
                     "T0X_0001 55300.0002\nA1X_0001 12.05\n"
                     "XR1_0001 55350\nXR2_0001 55400\n")
    m_bt = get_model(base + "BINARY BT\n" + orb)
    m_in = get_model(base + "BINARY BT\n" + orbx)
    mjds = np.linspace(55300, 55450, 500)
    t = make_fake_toas_fromMJDs(mjds, m_bt, error_us=1.0, freq_mhz=1400.0,
                                obs="@", add_noise=False, iterations=0)
    d_pw = np.asarray(m_pw.prepare(t).delay())
    d_bt = np.asarray(m_bt.prepare(t).delay())
    d_in = np.asarray(m_in.prepare(t).delay())
    win = (t.get_mjds() >= 55350) & (t.get_mjds() <= 55400)
    assert win.sum() > 50 and (~win).sum() > 50
    np.testing.assert_allclose(d_pw[~win], d_bt[~win], atol=1e-12, rtol=0)
    # in-window goes through the pack-time epoch-delta path: agrees with
    # an exactly-repacked BT to ~20 ps (f64 delta rounding), far under
    # the ~1 ns physics bar
    np.testing.assert_allclose(d_pw[win], d_in[win], atol=1e-10, rtol=0)


def test_bt_piecewise_fit_recovers_piece_params():
    """A perturbed T0X/A1X piece is recovered by the fitter (the piece
    vectors are live design-matrix columns, not frozen pack constants)."""
    import copy

    from pint_tpu.fitter import DownhillWLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TBTPW2\nRAJ 10:00:00\nDECJ 20:00:00\nF0 150.0 1\n"
           "PEPOCH 55300\nDM 5.0\nBINARY BT_piecewise\n"
           "PB 8.0\nA1 12.0 1\nT0 55300 1\nECC 0.12\nOM 45.0\n"
           "T0X_0001 55300.00004 1\nA1X_0001 12.001 1\n"
           "XR1_0001 55350\nXR2_0001 55450\n")
    true = get_model(par)
    mjds = np.linspace(55300, 55500, 300)
    t = make_fake_toas_fromMJDs(mjds, true, error_us=2.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=11)
    start = copy.deepcopy(true)
    start.T0X_0001.value += 3e-5
    start.A1X_0001.value += 5e-4
    f = DownhillWLSFitter(t, start)
    f.fit_toas(maxiter=15)
    assert f.resids.reduced_chi2 < 2.0
    for p in ("T0X_0001", "A1X_0001"):
        diff = getattr(f.model, p).value - getattr(true, p).value
        unc = getattr(f.model, p).uncertainty
        assert unc and abs(diff) < 5 * unc, f"{p}: off by {diff}"


def test_bt_piecewise_parfile_roundtrip_and_validation():
    import pytest

    from pint_tpu.models import get_model

    par = ("PSR TBTPW3\nRAJ 10:00:00\nDECJ 20:00:00\nF0 150.0 1\n"
           "PEPOCH 55300\nDM 5.0\nBINARY BT_piecewise\n"
           "PB 8.0\nA1 12.0\nT0 55300\nECC 0.12\nOM 45.0\n"
           "T0X_0001 55300.0002\nA1X_0001 12.05\n"
           "XR1_0001 55350\nXR2_0001 55400\n")
    m = get_model(par)
    m2 = get_model(m.as_parfile())
    assert "BinaryBTPiecewise" in m2.components
    assert m2.T0X_0001.value == m.T0X_0001.value
    assert m2.A1X_0001.value == m.A1X_0001.value
    assert m2.XR1_0001.value == m.XR1_0001.value
    # overlapping windows are rejected loudly
    with pytest.raises(ValueError, match="overlap"):
        get_model(par + "T0X_0002 55300.0003\n"
                  "XR1_0002 55390\nXR2_0002 55420\n")


def test_ell1k_rotating_eccentricity_vector():
    """ELL1k equals plain ELL1 with the analytically rotated/scaled
    eccentricity vector at each epoch: eps' = (1 + LNEDOT dt) R(w) eps,
    w = OMDOT dt (reference: ELL1k_model.py convention)."""
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    base = ("PSR TE1K\nRAJ 10:00:00\nDECJ 20:00:00\nF0 150.0 1\n"
            "PEPOCH 55300\nDM 5.0\n")
    orb = "PB 1.2\nA1 4.0\nTASC 55300\n"
    e1, e2 = 3e-4, -1.5e-4
    omdot_deg_yr, lnedot = 40.0, 3e-10  # rapid advance, e growth
    m_k = get_model(base + "BINARY ELL1k\n" + orb +
                    f"EPS1 {e1}\nEPS2 {e2}\n"
                    f"OMDOT {omdot_deg_yr}\nLNEDOT {lnedot}\n")
    mjds = np.linspace(55300, 55800, 7)
    t = make_fake_toas_fromMJDs(mjds, m_k, error_us=1.0, freq_mhz=1400.0,
                                obs="@", add_noise=False, iterations=0)
    d_k = np.asarray(m_k.prepare(t).delay())
    yr_s = 365.25 * 86400.0
    for i, mjd in enumerate(t.get_mjds()):
        dt = (mjd - 55300.0) * 86400.0
        w = np.deg2rad(omdot_deg_yr) / yr_s * dt
        s = 1.0 + lnedot * dt
        e1p = s * (e1 * np.cos(w) + e2 * np.sin(w))
        e2p = s * (e2 * np.cos(w) - e1 * np.sin(w))
        m_i = get_model(base + "BINARY ELL1\n" + orb +
                        f"EPS1 {float(e1p):.17g}\nEPS2 {float(e2p):.17g}\n")
        d_i = np.asarray(m_i.prepare(t).delay())[i]
        # same closed form; residual difference = the analytic dt here
        # uses UTC MJDs while the model rotates eps in TDB seconds
        # (~69 s offset -> e * omdot * 69 s * x / 2 ~ 1e-9)
        assert abs(d_i - d_k[i]) < 5e-9, (i, d_i - d_k[i])
    # and the rotation really matters at this OMDOT (not a trivial pass)
    m_0 = get_model(base + "BINARY ELL1\n" + orb +
                    f"EPS1 {e1}\nEPS2 {e2}\n")
    d_0 = np.asarray(m_0.prepare(t).delay())
    assert np.abs(d_0 - d_k).max() > 1e-5


def test_binary_t2_container_auto_selects():
    """BINARY T2 (tempo2's universal container) auto-selects the
    concrete model from the parameters present — same rules as
    scripts/t2binary2pint.py — warns, loads, fits, and round-trips as
    the chosen model (reference: upstream points users at the
    conversion script; selecting on load is the conversion applied
    in-memory)."""
    import numpy as np
    import pytest

    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    base = ("PSR T2SEL\nRAJ 05:00:00\nDECJ 01:00:00\nF0 100.0 1\n"
            "PEPOCH 55000\nDM 10.0\nBINARY T2\nPB 10.0\nA1 5.0\n")
    cases = (
        ("KOM 90\nKIN 60\nECC 0.01\nOM 30\nT0 55000\nM2 0.3\nPX 1.0\n",
         "BinaryDDK"),
        ("EPS1 1e-5 1\nEPS2 2e-5\nTASC 55000\n", "BinaryELL1"),
        ("ECC 0.01 1\nOM 30\nT0 55000\nM2 0.3\nSINI 0.9\n", "BinaryDD"),
        ("ECC 0.01 1\nOM 30\nT0 55000\nM2 1.1\nSHAPMAX 2.0\n",
         "BinaryDDS"),
        ("ECC 0.01 1\nOM 30\nT0 55000\n", "BinaryBT"),
    )
    for extra, want in cases:
        with pytest.warns(UserWarning, match="T2"):
            m = get_model(base + extra)
        assert want in m.components, (want, list(m.components))
        # round-trips as the CONCRETE model (conversion persisted)
        m2 = get_model(m.as_parfile())
        assert want in m2.components
        t = make_fake_toas_fromMJDs(np.linspace(55000, 55200, 30), m,
                                    error_us=1.0, obs="gbt",
                                    add_noise=True, seed=1, iterations=1)
        f = WLSFitter(t, m2)
        f.fit_toas(maxiter=2)
        assert np.isfinite(float(f.resids.chi2))
