"""Binary model tests.

(reference test patterns: tests/test_dd.py, tests/test_ell1h.py —
there golden vs Tempo2; here self-consistent simulate->fit recovery +
internal identities: Kepler solver exactness, ELL1 vs DD agreement in
the low-eccentricity limit.)
"""

import copy
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.fitter import DownhillWLSFitter
from pint_tpu.simulation import make_fake_toas_fromMJDs

BASE = """
PSR TESTB
RAJ 10:22:57.9
DECJ 10:01:52.8
F0 100.5 1
F1 -5e-16 1
PEPOCH 55000
DM 20.0
"""


def _fit_roundtrip(par, perturb, ntoa=80, seed=3, span=(54500, 55500)):
    m = get_model(par)
    mjds = np.linspace(*span, ntoa)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=seed)
    m2 = copy.deepcopy(m)
    for k, v in perturb.items():
        getattr(m2, k).value += v
    f = DownhillWLSFitter(t, m2)
    f.fit_toas()
    assert f.resids.reduced_chi2 < 2.0
    for p in perturb:
        diff = getattr(f.model, p).value - getattr(m, p).value
        unc = getattr(f.model, p).uncertainty
        assert abs(diff) < 5 * unc, f"{p}: {diff/unc:.1f} sigma"
    return f


def test_kepler_solver():
    import jax.numpy as jnp

    from pint_tpu.models.binary.base import kepler_solve

    M = jnp.linspace(-np.pi, np.pi, 101)
    for e in (0.01, 0.3, 0.7):
        E = kepler_solve(M, e)
        np.testing.assert_allclose(np.asarray(E - e * jnp.sin(E)), np.asarray(M),
                                   atol=1e-13)


def test_ell1_fit_recovery():
    par = BASE + ("BINARY ELL1\nPB 1.53 1\nA1 1.898 1\nTASC 55001.0 1\n"
                  "EPS1 2e-8 1\nEPS2 -8e-8 1\nM2 0.21\nSINI 0.998\n")
    _fit_roundtrip(par, {"PB": 3e-9, "A1": 1e-7, "TASC": 1e-8, "EPS1": 5e-8})


def test_bt_fit_recovery():
    par = BASE + ("BINARY BT\nPB 10.5 1\nA1 12.3 1\nT0 55005.5 1\n"
                  "ECC 0.21 1\nOM 75.3 1\nGAMMA 0.002\n")
    _fit_roundtrip(par, {"PB": 1e-7, "A1": 1e-6, "ECC": 1e-7, "OM": 1e-5})


def test_dd_fit_recovery():
    par = BASE + ("BINARY DD\nPB 0.40 1\nA1 2.34 1\nT0 55005.5 1\nECC 0.17 1\n"
                  "OM 120.0 1\nOMDOT 4.22 1\nGAMMA 0.004\nM2 1.3\nSINI 0.95\n")
    _fit_roundtrip(par, {"PB": 1e-8, "A1": 1e-6, "ECC": 1e-7, "OM": 1e-4,
                         "OMDOT": 1e-3}, ntoa=120)


def test_ell1_matches_dd_at_low_ecc():
    """ELL1 and DD must agree to O(e^2 x) for tiny eccentricity."""
    e = 1e-6
    om_deg = 40.0
    eps1 = e * np.sin(np.deg2rad(om_deg))
    eps2 = e * np.cos(np.deg2rad(om_deg))
    # T0 (periastron) and TASC differ by om/n: TASC = T0 - (om/2pi)*PB
    pb = 2.0
    t0 = 55005.0
    tasc = t0 - (np.deg2rad(om_deg) / (2 * np.pi)) * pb
    par_dd = BASE + (f"BINARY DD\nPB {pb} 1\nA1 5.0 1\nT0 {t0}\n"
                     f"ECC {e}\nOM {om_deg}\n")
    par_ell1 = BASE + (f"BINARY ELL1\nPB {pb} 1\nA1 5.0 1\nTASC {tasc:.12f}\n"
                       f"EPS1 {eps1:.3e}\nEPS2 {eps2:.3e}\n")
    m_dd = get_model(par_dd)
    m_ell1 = get_model(par_ell1)
    mjds = np.linspace(55000, 55100, 50)
    t = make_fake_toas_fromMJDs(mjds, m_dd, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False)
    # the models differ by the unobservable constant -(3/2) eps1 x
    # (absorbed by the phase offset), so compare mean-subtracted
    r = np.asarray(Residuals(t, m_ell1, subtract_mean=False).calc_time_resids())
    assert np.abs(r - r.mean()).max() < 1e-9


def test_ell1h_shapiro_mapping():
    """ELL1H with (H3, STIGMA) equals ELL1 with the mapped (M2, SINI)."""
    from pint_tpu.constants import TSUN_S

    sini = 0.9
    cosi = np.sqrt(1 - sini**2)
    stigma = sini / (1.0 + cosi)
    m2 = 0.3
    h3 = TSUN_S * m2 * stigma**3
    par_a = BASE + ("BINARY ELL1\nPB 1.5 1\nA1 2.0 1\nTASC 55001.0\n"
                    f"EPS1 1e-7\nEPS2 2e-7\nM2 {m2}\nSINI {sini}\n")
    par_b = BASE + ("BINARY ELL1H\nPB 1.5 1\nA1 2.0 1\nTASC 55001.0\n"
                    f"EPS1 1e-7\nEPS2 2e-7\nH3 {h3:.6e}\nSTIGMA {stigma:.8f}\n")
    m_a = get_model(par_a)
    m_b = get_model(par_b)
    mjds = np.linspace(55000, 55030, 40)
    t = make_fake_toas_fromMJDs(mjds, m_a, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False)
    r = Residuals(t, m_b, subtract_mean=False)
    assert np.abs(np.asarray(r.calc_time_resids())).max() < 2e-9


def test_dds_shapmax():
    par = BASE + ("BINARY DDS\nPB 0.4 1\nA1 2.34 1\nT0 55005.5 1\nECC 0.01 1\n"
                  "OM 120.0 1\nM2 1.3\nSHAPMAX 3.0\n")
    m = get_model(par)
    mjds = np.linspace(55000, 55060, 60)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False)
    r = Residuals(t, m)
    assert r.rms_weighted() < 1e-9  # self-consistency through SHAPMAX path


def test_fb_orbit_mode():
    """FB0 parameterization instead of PB."""
    fb0 = 1.0 / (1.53 * 86400.0)
    par = BASE + (f"BINARY ELL1\nFB0 {fb0:.12e} 1\nA1 1.898 1\nTASC 55001.0 1\n"
                  "EPS1 2e-8\nEPS2 -8e-8\n")
    m = get_model(par)
    assert "FB0" in m.params
    mjds = np.linspace(55000, 55100, 40)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False)
    r = Residuals(t, m)
    assert r.rms_weighted() < 1e-9


def test_ddgr_matches_dd_with_explicit_pk():
    """DDGR's mass-derived PK params must equal a DD model with the
    same values written explicitly (GR relations, DD86/TW89)."""
    from pint_tpu.constants import TSUN_S, SECS_PER_JULIAN_YEAR

    pb, a1, ecc, om, t0 = 0.323, 2.34, 0.617, 226.0, 55005.0
    mtot, m2 = 2.83, 1.39
    m1 = mtot - m2
    n = 2 * np.pi / (pb * 86400.0)
    u2 = (TSUN_S * mtot * n) ** (2.0 / 3.0)
    k = 3.0 * u2 / (1.0 - ecc**2)
    omdot = k * n * SECS_PER_JULIAN_YEAR / np.deg2rad(1.0)
    gamma = (ecc * TSUN_S ** (2 / 3) * n ** (-1 / 3) * m2 * (m1 + 2 * m2)
             * mtot ** (-4 / 3))
    pbdot = (-(192 * np.pi / 5) * (TSUN_S * n) ** (5 / 3) * m1 * m2
             * mtot ** (-1 / 3)
             * (1 + (73 / 24) * ecc**2 + (37 / 96) * ecc**4)
             * (1 - ecc**2) ** -3.5)
    sini = a1 * n ** (2 / 3) * mtot ** (2 / 3) / (TSUN_S ** (1 / 3) * m2)
    dr = (3 * m1**2 + 6 * m1 * m2 + 2 * m2**2) / mtot**2 * u2
    dth = (3.5 * m1**2 + 6 * m1 * m2 + 2 * m2**2) / mtot**2 * u2
    par_gr = BASE + (f"BINARY DDGR\nPB {pb} 1\nA1 {a1} 1\nT0 {t0}\n"
                     f"ECC {ecc} 1\nOM {om}\nMTOT {mtot}\nM2 {m2}\n")
    par_dd = BASE + (f"BINARY DD\nPB {pb} 1\nA1 {a1} 1\nT0 {t0}\n"
                     f"ECC {ecc} 1\nOM {om}\nOMDOT {omdot:.10f}\n"
                     f"GAMMA {gamma:.8e}\nPBDOT {pbdot:.8e}\nM2 {m2}\n"
                     f"SINI {sini:.10f}\nDR {dr:.8e}\nDTH {dth:.8e}\n")
    m_gr = get_model(par_gr)
    m_dd = get_model(par_dd)
    mjds = np.linspace(55000, 55100, 80)
    t = make_fake_toas_fromMJDs(mjds, m_gr, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False)
    r = np.asarray(Residuals(t, m_dd, subtract_mean=False).calc_time_resids())
    assert np.abs(r).max() < 5e-9


def test_ddgr_fit_recovers_mtot():
    par = BASE + ("BINARY DDGR\nPB 0.323 1\nA1 2.34 1\nT0 55005.0 1\n"
                  "ECC 0.617 1\nOM 226.0 1\nMTOT 2.83 1\nM2 1.39\n")
    _fit_roundtrip(par, {"MTOT": 1e-4}, ntoa=120)


def test_ell1k_matches_ell1_for_small_rotation():
    """ELL1k's rigid eccentricity-vector rotation linearizes to
    EPS1DOT/EPS2DOT for small OMDOT*dt."""
    omdot = 1.0  # deg/yr
    wdot = np.deg2rad(omdot) / (365.25 * 86400.0)  # rad/s
    eps1, eps2 = 1e-7, 2e-7
    par_k = BASE + ("BINARY ELL1K\nPB 1.5 1\nA1 2.0 1\nTASC 55001.0\n"
                    f"EPS1 {eps1}\nEPS2 {eps2}\nOMDOT {omdot}\n")
    par_l = BASE + ("BINARY ELL1\nPB 1.5 1\nA1 2.0 1\nTASC 55001.0\n"
                    f"EPS1 {eps1}\nEPS2 {eps2}\n"
                    f"EPS1DOT {eps2 * wdot:.10e}\nEPS2DOT {-eps1 * wdot:.10e}\n")
    m_k = get_model(par_k)
    m_l = get_model(par_l)
    mjds = np.linspace(55000, 55100, 50)
    t = make_fake_toas_fromMJDs(mjds, m_k, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False)
    r = np.asarray(Residuals(t, m_l, subtract_mean=False).calc_time_resids())
    assert np.abs(r).max() < 1e-10


def test_btx_fb_harmonics():
    """BTX (FBn orbit) equals BT with the equivalent PB."""
    fb0 = 1.0 / (10.5 * 86400.0)
    par_x = BASE + (f"BINARY BTX\nFB0 {fb0:.15e} 1\nA1 12.3 1\nT0 55005.5\n"
                    "ECC 0.21\nOM 75.3\nGAMMA 0.002\n")
    par_b = BASE + ("BINARY BT\nPB 10.5 1\nA1 12.3 1\nT0 55005.5\n"
                    "ECC 0.21\nOM 75.3\nGAMMA 0.002\n")
    m_x = get_model(par_x)
    m_b = get_model(par_b)
    assert type(m_x.components["BinaryBTX"]).__name__ == "BinaryBTX"
    mjds = np.linspace(55000, 55200, 60)
    t = make_fake_toas_fromMJDs(mjds, m_x, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False)
    r = np.asarray(Residuals(t, m_b, subtract_mean=False).calc_time_resids())
    assert np.abs(r).max() < 2e-9


def test_get_barycentric_toas():
    """Barycentric TOAs strip delays up to the binary: for an isolated
    pulsar they equal TDB minus ALL delays; for a binary, the residual
    difference is exactly the orbital delay (A1-scale, PB-periodic)
    (reference: TimingModel.get_barycentric_toas)."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    iso_par = ("PSR TBARY\nRAJ 6:00:00\nDECJ 10:00:00\nF0 100.0 1\n"
               "PEPOCH 55500\nDM 10.0\n")
    m_iso = get_model(iso_par)
    t = make_fake_toas_uniform(55000, 55400, 50, m_iso, error_us=1.0,
                               freq_mhz=800.0)
    prep = m_iso.prepare(t)
    bary = m_iso.get_barycentric_toas(t)
    expect = (np.asarray(prep.batch.tdb_day)
              + (np.asarray(prep.batch.tdb_sec)
                 - np.asarray(prep.delay())) / 86400.0)
    np.testing.assert_allclose(bary, expect, rtol=0, atol=1e-12)

    bin_par = iso_par + ("BINARY ELL1\nPB 2.5\nA1 4.0\nTASC 55001.0\n"
                         "EPS1 1e-6\nEPS2 -2e-6\n")
    m_bin = get_model(bin_par)
    t2 = make_fake_toas_uniform(55000, 55400, 200, m_bin, error_us=1.0,
                                freq_mhz=800.0)
    prep2 = m_bin.prepare(t2)
    bary2 = m_bin.get_barycentric_toas(t2)
    full2 = (np.asarray(prep2.batch.tdb_day)
             + (np.asarray(prep2.batch.tdb_sec)
                - np.asarray(prep2.delay())) / 86400.0)
    orb_s = (bary2 - full2) * 86400.0  # the stripped binary delay
    # near-circular orbit: Roemer amplitude ~ A1 = 4 ls
    assert 3.5 < np.max(np.abs(orb_s)) < 4.5
    # PB-periodic: fold at PB and check smoothness (max gap-jump small
    # compared to amplitude when sorted by orbital phase)
    phase = np.modf((bary2 - 55001.0) / 2.5)[0] % 1.0
    order = np.argsort(phase)
    jumps = np.abs(np.diff(orb_s[order]))
    assert np.max(jumps) < 1.0  # smooth sinusoid, no phase scatter
    # explicit cutoff by component name matches the default
    np.testing.assert_allclose(
        bary2, m_bin.get_barycentric_toas(t2, cutoff_component="BinaryELL1"),
        atol=0)
    # non-delay component names are rejected, not silently all-stripped
    import pytest
    with pytest.raises(KeyError):
        m_bin.get_barycentric_toas(t2, cutoff_component="Spindown")


def test_model_orbital_phase():
    """Model-level orbital phase: zero at the epoch (TASC), half a
    cycle at TASC + PB/2, monotonic fold (reference:
    TimingModel.orbital_phase)."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TORB\nRAJ 6:00:00\nDECJ 10:00:00\nF0 100.0 1\n"
           "PEPOCH 55500\nDM 10.0\nBINARY ELL1\nPB 2.0\nA1 3.0\n"
           "TASC 55200.0\nEPS1 1e-6\nEPS2 -2e-6\n")
    m = get_model(par)
    # TOAs at exact multiples/half-multiples of PB from TASC (pick the
    # barycentric epoch; topocentric offsets shift phase by < Roemer/PB)
    mjds = np.array([55200.0, 55202.0, 55201.0, 55203.0, 55200.5])
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                iterations=1)
    ph = m.orbital_phase(t)
    # Roemer/clock offsets move the fold point by up to ~500 s / PB ~ 3e-3
    tol = 5e-3
    assert abs(ph[0] - round(ph[0])) % 1.0 < tol or abs(ph[0] - 1) < tol
    for k, expect in ((1, 0.0), (2, 0.5), (3, 0.5), (4, 0.25)):
        d = min(abs(ph[k] - expect), abs(ph[k] - expect - 1),
                abs(ph[k] - expect + 1))
        assert d < tol, (k, ph[k], expect)
    ph_rad = m.orbital_phase(t, radians=True)
    np.testing.assert_allclose(ph_rad, ph * 2 * np.pi, atol=1e-12)
    # isolated model refuses
    import pytest
    with pytest.raises(AttributeError):
        get_model(par.split("BINARY")[0]).orbital_phase(t)
