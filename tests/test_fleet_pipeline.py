"""Pipelined fleet executor: bitwise equivalence vs the sequential
path (including under injected solver divergence), optimal_split_bounds
properties, concurrent AOT compilation feeding the serve
ExecutableCache, pow2 bucket unification, precision="auto", and the
bench fleet-pipeline metric contract."""

import copy
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.parallel import PTABatch, PTAFleet, fleet_pipeline_metrics
from pint_tpu.resilience import FaultPoint, inject
from pint_tpu.simulation import make_fake_toas_fromMJDs


def _spin_pulsars(n_psr=2, base_toas=24):
    """Spin-only pulsars (WLS route under method="auto"), ragged."""
    rng = np.random.default_rng(0)
    models, toas_list = [], []
    for i in range(n_psr):
        par = (f"PSR FP{i}\nRAJ 1{i % 10}:00:00.0\nDECJ {5 + i}:30:00.0\n"
               f"F0 {200 + 10 * i}.5 1\nF1 -{3 + i}e-16 1\nPEPOCH 55500\n"
               f"DM {10 + i}.5 1\n")
        m = get_model(par)
        n = base_toas + 4 * i
        mjds = np.sort(rng.uniform(55000, 56000, n))
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                    obs="gbt", add_noise=True, seed=i)
        models.append(m)
        toas_list.append(t)
    return models, toas_list


def _noise_pulsars(n_psr=2, n_epochs=10, per_epoch=3):
    """EFAC/EQUAD/ECORR pulsars (GLS route under method="auto")."""
    models, toas_list = [], []
    for i in range(n_psr):
        par = (f"PSR NP{i}\nRAJ 0{(2 * i) % 10}:30:00.0\n"
               f"DECJ {8 + i}:00:00.0\n"
               f"F0 {310 + 4 * i}.25 1\nF1 -{2 + i}e-16 1\nPEPOCH 55500\n"
               f"DM {12 + i}.3 1\n"
               "EFAC -f L-wide 1.2\nEQUAD -f L-wide 0.5\n"
               "ECORR -f L-wide 0.9\n")
        m = get_model(par)
        ne = n_epochs + 2 * i
        epoch_days = np.linspace(55000, 56000, ne)
        mjds = np.concatenate(
            [d + np.arange(per_epoch) * 0.5 / 86400.0 for d in epoch_days])
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0,
                                    freq_mhz=np.full(len(mjds), 1400.0),
                                    obs="gbt", add_noise=True, seed=100 + i)
        for f in t.flags:
            f["f"] = "L-wide"
        models.append(m)
        toas_list.append(t)
    return models, toas_list


def _mixed_fleet(**fleet_kw):
    """wls-structure + gls-structure pulsars in one fleet (2 buckets)."""
    ms, ts = _spin_pulsars(2)
    mn, tn = _noise_pulsars(2)
    return PTAFleet(ms + mn, ts + tn, **fleet_kw)


def _fit_arrays(fleet, **kw):
    x, chi2, cov = fleet.fit(**kw)
    return (np.asarray(x), np.asarray(chi2), np.asarray(cov),
            sorted(int(i) for i in fleet.diverged))


# -- bitwise equivalence ---------------------------------------------


def test_pipelined_fit_bitwise_matches_sequential():
    """Pipelined execution (async dispatch + concurrent AOT compile +
    overlapped host prep) must be a pure scheduling change: bitwise
    identical x/chi2/cov on a mixed wls+gls fleet."""
    fleet = _mixed_fleet(pipeline=True)
    xs, c2s, covs, div_s = _fit_arrays(fleet, method="auto", maxiter=3,
                                       pipeline=False)
    xp, c2p, covp, div_p = _fit_arrays(fleet, method="auto", maxiter=3,
                                       pipeline=True)
    assert np.array_equal(xs, xp)
    assert np.array_equal(c2s, c2p)
    assert np.array_equal(covs, covp)
    assert div_s == div_p == []
    assert sorted(fleet.fit_metrics) == sorted(fleet.group_indices)


def test_pipelined_fit_bitwise_under_injected_divergence():
    """The solver_diverge fault point fires on a deterministic
    eligibility-check schedule; because the pipelined path finalizes
    buckets in the same order the sequential path fits them, the SAME
    bucket/lane diverges and the isolated results stay bitwise equal
    (including the NaN chi2 on the poisoned lane)."""
    fleet = _mixed_fleet()

    def run(pipeline):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            # fresh FaultPoint per run: identical (seed, check
            # sequence) => identical fire schedule iff the two paths
            # hit the injection site in the same bucket order
            with inject(FaultPoint("solver_diverge", count=1,
                                   payload={"lanes": [1]})):
                return _fit_arrays(fleet, method="auto", maxiter=3,
                                   pipeline=pipeline)

    xs, c2s, covs, div_s = run(False)
    xp, c2p, covp, div_p = run(True)
    assert div_s == div_p and len(div_s) == 1
    assert np.array_equal(xs, xp)
    assert np.array_equal(c2s, c2p, equal_nan=True)
    assert np.array_equal(covs, covp, equal_nan=True)


def test_pipelined_rejects_bad_kwargs_like_sequential():
    """kwarg validation must not be deferred past dispatch: a wls
    bucket given a gls-only kwarg raises the same TypeError either
    way."""
    ms, ts = _spin_pulsars(2)
    fleet = PTAFleet(ms, ts)
    with pytest.raises(TypeError, match="unexpected keyword"):
        fleet.fit(method="wls", maxiter=3, pipeline=True,
                  ecorr_mode="auto")
    with pytest.raises(TypeError, match="unexpected keyword"):
        fleet.fit(method="wls", maxiter=3, pipeline=False,
                  ecorr_mode="auto")


# -- optimal_split_bounds properties ---------------------------------


def test_optimal_split_bounds_k_at_least_n():
    counts = [30, 100, 550, 12]
    bounds = PTAFleet.optimal_split_bounds(counts, k=10)
    # k >= n: zero padding is achievable, so every distinct count is
    # its own (or a duplicate-shared) bound
    assert bounds == sorted(set(counts))
    assert sum(min(x for x in bounds if x >= c)
               for c in counts) == sum(counts)


def test_optimal_split_bounds_duplicates_and_single():
    # all-equal counts: one segment is already optimal
    assert PTAFleet.optimal_split_bounds([40, 40, 40], k=3) == [40]
    # single pulsar: its own count, regardless of k
    assert PTAFleet.optimal_split_bounds([77], k=5) == [77]
    assert PTAFleet.optimal_split_bounds([], k=3) == []


def test_optimal_split_bounds_general_properties():
    rng = np.random.default_rng(11)
    counts = rng.integers(20, 3000, size=12).tolist()
    for k in (1, 2, 3):
        bounds = PTAFleet.optimal_split_bounds(counts, k)
        assert bounds == sorted(bounds)
        assert 1 <= len(bounds) <= k
        assert bounds[-1] == max(counts)  # largest pulsar must fit
        # padded area never exceeds the one-bucket baseline
        area = sum(min(b for b in bounds if b >= c) for c in counts)
        assert area <= len(counts) * max(counts)


# -- pow2 bucket unification with serve/batcher ----------------------


def test_fleet_pow2_buckets_use_serve_convention():
    from pint_tpu.serve.batcher import pow2_bucket

    ms, ts = _spin_pulsars(3, base_toas=30)  # counts 30, 34, 38
    fleet = PTAFleet(ms, ts, toa_bucket="pow2", bucket_floor=16)
    got = sorted(key[1] for key in fleet.group_indices)
    want = sorted({pow2_bucket(len(t), 16) for t in ts})
    assert got == want == [32, 64]
    # floor dominates when counts sit below it (the serve slot floor)
    fleet256 = PTAFleet(ms, ts, toa_bucket="pow2", bucket_floor=256)
    assert [key[1] for key in fleet256.group_indices] == [256]


# -- precision="auto" -------------------------------------------------


def test_precision_auto_picks_and_matches_explicit():
    mn, tn = _noise_pulsars(2)
    pta = PTABatch([copy.deepcopy(m) for m in mn], tn)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        x_a, chi2_a, cov_a = pta.gls_fit(maxiter=2, precision="auto")
    verdict = pta.precision_auto
    assert verdict["choice"] in ("f64", "mixed")
    assert verdict["f64_s"] > 0 and verdict["mixed_s"] > 0
    # auto must equal the explicitly-requested winner bitwise
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        x_e, chi2_e, cov_e = pta.gls_fit(maxiter=2,
                                         precision=verdict["choice"])
    assert np.array_equal(np.asarray(x_a), np.asarray(x_e))
    assert np.array_equal(np.asarray(chi2_a), np.asarray(chi2_e))
    # the per-structure verdict is cached process-wide: a second auto
    # fit must reuse it, not re-probe
    from pint_tpu.parallel.pta import _PRECISION_AUTO_CACHE

    n_cached = len(_PRECISION_AUTO_CACHE)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        pta.gls_fit(maxiter=2, precision="auto")
    assert len(_PRECISION_AUTO_CACHE) == n_cached


def test_precision_rejects_unknown():
    mn, tn = _noise_pulsars(2)
    pta = PTABatch(mn, tn)
    with pytest.raises(ValueError, match="precision"):
        pta.gls_fit(maxiter=2, precision="f16")


# -- concurrent AOT compile and the serve ExecutableCache ------------


def test_prewarm_concurrent_matches_lazy_cache_keys():
    """Concurrent prewarm must stage exactly the executables the lazy
    jit path would compile: same ExecutableCache keys, then a zero-miss
    stream."""
    from pint_tpu.serve import FitRequest, ServeEngine

    (m0, t0), (m1, t1) = zip(*_spin_pulsars(2))
    reqs = [FitRequest(copy.deepcopy(m0), t0, maxiter=3),
            FitRequest(copy.deepcopy(m1), t1, maxiter=3)]

    lazy = ServeEngine(max_batch=2, max_latency_s=1e9, bucket_floor=32)
    for r in reqs:
        lazy.submit(FitRequest(copy.deepcopy(r.model), r.toas, maxiter=3))
    lazy.drain()

    warm = ServeEngine(max_batch=2, max_latency_s=1e9, bucket_floor=32)
    n = warm.prewarm_concurrent(reqs)
    assert n >= 1
    assert sorted(map(repr, warm.cache.keys())) == \
        sorted(map(repr, lazy.cache.keys()))
    assert warm.cache.counters()["prefilled"] == len(warm.cache)

    results = [warm.submit(FitRequest(copy.deepcopy(r.model), r.toas,
                                      maxiter=3)) for r in reqs]
    warm.drain()
    assert all(r.status == "ok" for r in results)
    assert warm.cache.misses == 0 and warm.cache.hits >= 1


def test_fleet_precompile_populates_program_tables():
    """fleet.precompile (concurrent AOT) must install exactly the
    program keys the lazy path would, and the subsequent pipelined fit
    stays bitwise equal to a never-precompiled sequential fit."""
    fleet = _mixed_fleet()
    infos, wall_s = fleet.precompile(method="auto", maxiter=3)
    assert len(infos) == len(fleet.group_indices) and wall_s > 0
    for info in infos:
        assert info["trace_s"] >= 0 and info["backend_compile_s"] >= 0
    for key in fleet.group_indices:
        batch = fleet.batches[key]
        method = "gls" if fleet._use_gls(batch, "auto") else "wls"
        assert batch.program_key(method=method, maxiter=3) in batch._fns
    xp, c2p, covp, _ = _fit_arrays(fleet, method="auto", maxiter=3,
                                   pipeline=True)
    ref = _mixed_fleet()
    xs, c2s, covs, _ = _fit_arrays(ref, method="auto", maxiter=3,
                                   pipeline=False)
    assert np.array_equal(xp, xs)
    assert np.array_equal(c2p, c2s)
    assert np.array_equal(covp, covs)


# -- bench metric contract (tier-1-safe smoke) -----------------------


def test_fleet_pipeline_metrics_keys_finite():
    """The bench/profile fleet_pipeline stage contract: all new keys
    present and finite, bitwise flag true."""
    ms, ts = _spin_pulsars(3, base_toas=30)  # buckets 32 and 64
    fleet = PTAFleet(ms, ts, toa_bucket="pow2", bucket_floor=16)
    rep = fleet_pipeline_metrics(fleet, method="wls", maxiter=3,
                                 repeats=1)
    for key in ("fleet_compile_serial_s", "fleet_compile_concurrent_s",
                "fleet_fit_sequential_s", "fleet_fit_pipelined_s",
                "fleet_pipeline_overlap_pct"):
        assert rep[key] is not None and np.isfinite(rep[key]), (key, rep)
    assert rep["fleet_pipeline_bitwise"] is True
    assert rep["fleet_buckets"] == 2


# -- runtime lock discipline (lockcheck instrumented proxies) --------


def test_lockcheck_detects_deliberate_violation():
    """The instrumentation itself must fire: an attribute rebind and a
    dict mutation from a foreign thread without the lock are both
    recorded, and the same writes under the lock are not."""
    import threading

    from lockcheck import GuardedDict, instrument

    class Shared:
        def __init__(self):
            self._lock = threading.RLock()
            self.count = 0
            self._slots = {}

    s = Shared()
    violations = []
    with instrument(Shared, violations, dict_attrs=("_slots",),
                    instances=[s]):
        assert isinstance(s._slots, GuardedDict)

        def unlocked():
            s.count += 1
            s._slots["k"] = 1

        def locked():
            with s._lock:
                s.count += 1
                s._slots["k2"] = 2

        for fn in (unlocked, locked):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
    kinds = sorted((v.attr, v.op) for v in violations)
    assert kinds == [("_slots", "__setitem__"), ("count", "setattr")]
    # original dict restored with contents intact
    assert not isinstance(s._slots, GuardedDict)
    assert s._slots == {"k": 1, "k2": 2}


def test_pipelined_fit_holds_fleet_lock():
    """The pipelined executor's worker threads resolve deferred packs
    and land them in fleet.batches; every such cross-thread write must
    hold fleet._lock (PTAFleet._resolve's contract)."""
    from lockcheck import assert_no_violations, instrument

    from pint_tpu.parallel.pta import PTAFleet

    violations = []
    with instrument(PTAFleet, violations,
                    dict_attrs=("batches", "_batch_futures")):
        fleet = _mixed_fleet(pipeline=True)
        _fit_arrays(fleet, method="auto", maxiter=3, pipeline=True)
    assert_no_violations(violations)


def test_prewarm_holds_cache_and_batcher_locks():
    """Concurrent prewarm inserts into the ExecutableCache from worker
    threads while submits queue through the MicroBatcher; every
    cross-thread mutation of either must hold the owning _lock."""
    import copy

    from lockcheck import assert_no_violations, instrument

    from pint_tpu.serve import FitRequest, ServeEngine
    from pint_tpu.serve.batcher import MicroBatcher
    from pint_tpu.serve.excache import ExecutableCache

    (m0, t0), (m1, t1) = zip(*_spin_pulsars(2))
    reqs = [FitRequest(copy.deepcopy(m0), t0, maxiter=3),
            FitRequest(copy.deepcopy(m1), t1, maxiter=3)]
    cache_violations = []
    batcher_violations = []
    with instrument(ExecutableCache, cache_violations,
                    dict_attrs=("_entries",)), \
            instrument(MicroBatcher, batcher_violations,
                       dict_attrs=("_slots",)):
        eng = ServeEngine(max_batch=2, max_latency_s=1e9,
                          bucket_floor=32)
        assert eng.prewarm_concurrent(reqs) >= 1
        for r in reqs:
            eng.submit(FitRequest(copy.deepcopy(r.model), r.toas,
                                  maxiter=3))
        eng.drain()
    assert_no_violations(cache_violations)
    assert_no_violations(batcher_violations)


# -- distributed failure domains (fleetmesh, ISSUE 6) ----------------


from pint_tpu.parallel import (CollectiveTimeout, DeviceLost,  # noqa: E402
                               FleetMesh)


@pytest.fixture(scope="module")
def fleet_psrs():
    """wls-structure + gls-structure pulsars (2 buckets, 4 pulsars)."""
    ms, ts = _spin_pulsars(2)
    mn, tn = _noise_pulsars(2)
    return ms + mn, ts + tn


@pytest.fixture(scope="module")
def fleet_ref(fleet_psrs):
    """Healthy FleetMesh fit every chaos test compares against."""
    models, toas_list = fleet_psrs
    fm = FleetMesh(models, toas_list, collective_timeout_s=None)
    xs, chi2s, covs = fm.fit(method="auto", maxiter=2)
    assert fm.snapshot()["lost_lanes"] == []
    return ([np.asarray(x) for x in xs], np.asarray(chi2s),
            [np.asarray(c) for c in covs])


def _assert_matches_ref(ref, got, rel_tol=0.0):
    rx, rc, rcov = ref
    gx, gc, gcov = got
    np.testing.assert_array_equal(rc, np.asarray(gc))
    for i in range(len(rx)):
        if rel_tol == 0.0:
            np.testing.assert_array_equal(rx[i], np.asarray(gx[i]))
            np.testing.assert_array_equal(rcov[i], np.asarray(gcov[i]))
        else:
            denom = np.maximum(np.abs(rx[i]), 1e-30)
            rel = float(np.max(np.abs(np.asarray(gx[i]) - rx[i]) / denom))
            assert rel <= rel_tol, rel


def test_fleetmesh_healthy_bitwise_matches_ptafleet(fleet_psrs,
                                                    fleet_ref,
                                                    device_mesh):
    """Sharding the fleet over per-device lanes is pure scheduling:
    same buckets, same programs, bitwise-identical results to the
    single-placement PTAFleet path."""
    models, toas_list = fleet_psrs
    fleet = PTAFleet(models, toas_list)
    xf, cf, covf = fleet.fit(method="auto", maxiter=2)
    _assert_matches_ref(fleet_ref,
                        ([np.asarray(x) for x in np.asarray(xf)],
                         np.asarray(cf),
                         [np.asarray(c) for c in np.asarray(covf)]))


def test_fleetmesh_device_loss_completes_on_survivors(fleet_psrs,
                                                      fleet_ref,
                                                      device_mesh):
    """The acceptance criterion: an N>=4-lane fleet with one lane
    killed mid-fit completes on the survivors, parameters within
    1e-15 relative of the healthy run (bitwise on CPU: the stolen
    bucket re-runs the identical program on another device)."""
    models, toas_list = fleet_psrs
    assert len(device_mesh) >= 4
    fm = FleetMesh(models, toas_list, collective_timeout_s=None)
    with inject(FaultPoint("device_loss", rate=1.0,
                           payload={"lane": 0})):
        got = fm.fit(method="auto", maxiter=2)
    _assert_matches_ref(fleet_ref, got, rel_tol=1e-15)
    _assert_matches_ref(fleet_ref, got)  # and in fact bitwise
    snap = fm.snapshot()
    assert snap["lost_lanes"] == [0]
    assert snap["alive_lanes"] == len(device_mesh) - 1
    assert snap["stolen_buckets"] >= 1
    assert snap["quarantined_pulsars"] == []


def test_fleetmesh_work_steal_deterministic(fleet_psrs, device_mesh):
    """Reassignment after a lane loss is a pure function of the
    (bucket set, survivor set): two identical chaos runs produce the
    same reassignment ledger and bitwise-equal results."""
    models, toas_list = fleet_psrs

    def chaos_run():
        fm = FleetMesh(models, toas_list, collective_timeout_s=None)
        got = fm.fit(method="auto", maxiter=2)
        return got, fm.snapshot()

    with inject(FaultPoint("device_loss", rate=1.0,
                           payload={"lane": 0})):
        got1, snap1 = chaos_run()
    with inject(FaultPoint("device_loss", rate=1.0,
                           payload={"lane": 0})):
        got2, snap2 = chaos_run()
    assert snap1["reassignments"] == snap2["reassignments"]
    assert snap1["lost_lanes"] == snap2["lost_lanes"]
    _assert_matches_ref((got1[0], got1[1], got1[2]), got2)


def test_fleetmesh_collective_timeout_trips_breaker(fleet_psrs,
                                                    fleet_ref,
                                                    device_mesh):
    """A collective that hangs past the watchdog raises a catchable
    CollectiveTimeout, strikes the lane's breaker, and after
    breaker_threshold strikes the lane is quarantined and its buckets
    stolen — the fit still completes, matching the healthy run. The
    hang is simulated through the injected sleep: no real waiting."""
    models, toas_list = fleet_psrs
    slept = []
    fm = FleetMesh(models, toas_list, collective_timeout_s=30.0,
                   sleep=slept.append, breaker_threshold=2)
    with inject(FaultPoint("collective_timeout", rate=1.0, count=2,
                           payload={"lane": 0, "hang_s": 60.0})):
        got = fm.fit(method="auto", maxiter=2)
    _assert_matches_ref(fleet_ref, got)
    snap = fm.snapshot()
    assert snap["lost_lanes"] == [0]
    assert snap["stolen_buckets"] >= 1
    # the watchdog waited its full bound (simulated), twice
    assert slept.count(30.0) == 2


def test_fleetmesh_late_collective_is_absorbed(fleet_psrs, fleet_ref,
                                               device_mesh):
    """A hang SHORTER than the watchdog bound is a slow-but-ok
    collective: no timeout, no strike, no lane loss."""
    models, toas_list = fleet_psrs
    slept = []
    fm = FleetMesh(models, toas_list, collective_timeout_s=30.0,
                   sleep=slept.append)
    with inject(FaultPoint("collective_timeout", rate=1.0, count=1,
                           payload={"lane": 0, "hang_s": 5.0})):
        got = fm.fit(method="auto", maxiter=2)
    _assert_matches_ref(fleet_ref, got)
    assert fm.snapshot()["lost_lanes"] == []
    assert 5.0 in slept


def test_fleetmesh_straggler_slows_without_failing(fleet_psrs,
                                                   fleet_ref,
                                                   device_mesh):
    """straggler_delay stalls one lane's bucket dispatch (recorded in
    its health flush window) but nothing fails and nothing is
    stolen."""
    models, toas_list = fleet_psrs
    slept = []
    fm = FleetMesh(models, toas_list, collective_timeout_s=None,
                   sleep=slept.append)
    with inject(FaultPoint("straggler_delay", rate=1.0, count=1,
                           payload={"lane": 0, "delay_s": 7.5})):
        got = fm.fit(method="auto", maxiter=2)
    _assert_matches_ref(fleet_ref, got)
    snap = fm.snapshot()
    assert snap["lost_lanes"] == [] and snap["stolen_buckets"] == 0
    assert 7.5 in slept


def test_pipelined_straggler_stays_bitwise():
    """The pipelined executor's straggler site delays one bucket's
    dispatch; finalize order is unchanged, so results stay bitwise
    equal to the sequential path."""
    fleet = _mixed_fleet(pipeline=True)
    xs, c2s, covs, div_s = _fit_arrays(fleet, method="auto", maxiter=2,
                                       pipeline=False)
    fp = FaultPoint("straggler_delay", rate=1.0, count=1,
                    payload={"delay_s": 0.0})
    with inject(fp):
        xp, c2p, covp, div_p = _fit_arrays(fleet, method="auto",
                                           maxiter=2, pipeline=True)
    assert fp.fires == 1  # the chaos actually landed
    assert np.array_equal(xs, xp)
    assert np.array_equal(c2s, c2p)
    assert np.array_equal(covs, covp)
    assert div_s == div_p == []


def test_fleetmesh_resume_after_device_loss_bitwise(tmp_path,
                                                    fleet_psrs,
                                                    fleet_ref,
                                                    device_mesh):
    """Kill the whole fleet mid-fit (every lane dies when touched,
    after the first bucket checkpointed), restart from the
    checkpoint: the restored + re-fit parameters are bitwise equal to
    an uninterrupted run's."""
    models, toas_list = fleet_psrs
    fm1 = FleetMesh(models, toas_list, collective_timeout_s=None)
    with inject(FaultPoint("device_loss", rate=1.0, after=1)):
        with pytest.raises(DeviceLost):
            fm1.fit(method="auto", maxiter=2,
                    checkpoint_dir=str(tmp_path))
    from pint_tpu.checkpoint import FitCheckpointer

    saved = FitCheckpointer(tmp_path).restore("fleetmesh")
    assert saved is not None and len(saved["done"]) == 1  # mid-fleet

    fm2 = FleetMesh(models, toas_list, collective_timeout_s=None)
    got = fm2.fit(method="auto", maxiter=2,
                  checkpoint_dir=str(tmp_path))
    _assert_matches_ref(fleet_ref, got)
    assert fm2.snapshot()["lost_lanes"] == []


def test_fleetmesh_foreign_checkpoint_warns_and_restarts(tmp_path,
                                                         fleet_psrs,
                                                         device_mesh):
    """A checkpoint taken for a different fit configuration must not
    be silently half-applied: warn and restart from scratch."""
    models, toas_list = fleet_psrs
    fm1 = FleetMesh(models, toas_list, collective_timeout_s=None)
    fm1.fit(method="auto", maxiter=2, checkpoint_dir=str(tmp_path))
    fm2 = FleetMesh(models, toas_list, collective_timeout_s=None)
    with pytest.warns(UserWarning,
                      match="different fleet/fit configuration"):
        got = fm2.fit(method="auto", maxiter=1,
                      checkpoint_dir=str(tmp_path))
    assert all(np.isfinite(np.asarray(c)).all() for c in got[1:2])
