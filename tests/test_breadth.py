"""Breadth-parity tests: AbsPhase/TZR, modelutils frame conversion,
binaryconvert, dmxparse, plot_utils, logging, config.

(reference patterns: tests/test_absphase.py, tests/test_modelutils.py,
tests/test_binaryconvert.py, tests/test_dmxparse.py.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_fromMJDs

BASE = """
PSR TESTBR
RAJ 06:30:49.4
DECJ -28:34:42.7
F0 301.5 1
F1 -7e-16 1
PEPOCH 55100
DM 22.0 1
"""


def test_absphase_tzr():
    """With TZR*, the TZR TOA itself must land at integer phase."""
    par = BASE + "TZRMJD 55100.1234\nTZRSITE @\nTZRFRQ 1400\n"
    m = get_model(par)
    assert "AbsPhase" in m.components
    tzr_toas = m.components["AbsPhase"].get_TZR_toa(m)
    ph = m.phase(tzr_toas)
    frac = float(np.asarray(ph.frac)[0])
    assert abs(frac) < 1e-7, frac
    assert abs(float(np.asarray(ph.int_)[0])) < 1  # counts from TZR


def test_model_equatorial_to_ecliptic_roundtrip():
    from pint_tpu.modelutils import (model_ecliptic_to_equatorial,
                                     model_equatorial_to_ecliptic)

    m = get_model(BASE)
    m.RAJ.uncertainty = 1e-8
    m.DECJ.uncertainty = 2e-8
    m.PMRA.value, m.PMDEC.value = 3.2, -1.1
    me = model_equatorial_to_ecliptic(m)
    assert "AstrometryEcliptic" in me.components
    # residuals identical: same sky position
    mjds = np.linspace(55000, 55200, 30)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False)
    r = np.asarray(Residuals(t, me, subtract_mean=False).calc_time_resids())
    assert np.abs(r).max() < 2e-9
    # round-trip restores angles and PM
    mq = model_ecliptic_to_equatorial(me)
    assert mq.RAJ.value == pytest.approx(m.RAJ.value, abs=1e-12)
    assert mq.DECJ.value == pytest.approx(m.DECJ.value, abs=1e-12)
    assert mq.PMRA.value == pytest.approx(3.2, abs=1e-6)
    assert mq.PMDEC.value == pytest.approx(-1.1, abs=1e-6)
    # diagonal-only propagation drops the RAJ/DECJ cross-covariance, so
    # the round-tripped uncertainty inflates slightly (a few %)
    assert mq.RAJ.uncertainty == pytest.approx(1e-8, rel=0.1)


def test_convert_binary_ell1_dd_roundtrip():
    from pint_tpu.binaryconvert import convert_binary

    e, om_deg = 1e-5, 37.0
    om = np.deg2rad(om_deg)
    par = BASE + (f"BINARY ELL1\nPB 2.5 1\nA1 4.2 1\nTASC 55101.0 1\n"
                  f"EPS1 {e*np.sin(om):.15e} 1\nEPS2 {e*np.cos(om):.15e} 1\n")
    m = get_model(par)
    m.EPS1.uncertainty = 1e-8
    m.EPS2.uncertainty = 1e-8
    md = convert_binary(m, "DD")
    assert "BinaryDD" in md.components
    assert md.ECC.value == pytest.approx(e, rel=1e-10)
    assert md.OM.value == pytest.approx(om_deg, rel=1e-8)
    assert md.ECC.uncertainty is not None
    # T0 = TASC + OM/2pi*PB
    assert md.T0.value == pytest.approx(55101.0 + om / (2 * np.pi) * 2.5,
                                        abs=1e-9)
    # residual agreement between parameterizations
    mjds = np.linspace(55050, 55150, 40)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False)
    r = np.asarray(Residuals(t, md, subtract_mean=True).calc_time_resids())
    assert np.abs(r).max() < 5e-8  # O(x e^2) = 4e-10 s + expansion terms
    # back to ELL1
    me = convert_binary(md, "ELL1")
    assert me.EPS1.value == pytest.approx(e * np.sin(om), rel=1e-8)
    assert me.TASC.value == pytest.approx(55101.0, abs=1e-9)


def test_convert_binary_dds_derives_shapmax():
    """DD -> DDS must DERIVE SHAPMAX = -ln(1-SINI) (not silently drop
    the Shapiro delay), and back-convert SINI = 1 - exp(-SHAPMAX)."""
    from pint_tpu.binaryconvert import convert_binary

    sini = 0.95
    par = BASE + ("BINARY DD\nPB 3.1 1\nA1 6.0 1\nT0 55100.0 1\n"
                  "ECC 1e-4 1\nOM 45.0 1\nM2 0.3\nSINI 0.95\n")
    m = get_model(par)
    m.SINI.uncertainty = 0.01
    mdds = convert_binary(m, "DDS")
    assert "BinaryDDS" in mdds.components
    assert mdds.SHAPMAX.value == pytest.approx(-np.log(1 - sini), rel=1e-12)
    assert mdds.SHAPMAX.uncertainty == pytest.approx(0.01 / (1 - sini), rel=1e-9)
    # residual equivalence: the Shapiro delay survives the conversion
    mjds = np.linspace(55050, 55150, 60)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False)
    r = np.asarray(Residuals(t, mdds, subtract_mean=False).calc_time_resids())
    assert np.abs(r).max() < 1e-10
    # and back
    mdd = convert_binary(mdds, "DD")
    assert mdd.SINI.value == pytest.approx(sini, rel=1e-12)


def test_convert_binary_ell1h_orthometric():
    """ELL1 -> ELL1H derives (H3, STIGMA) from (M2, SINI); inverse
    recovers them (Freire & Wex 2010)."""
    from pint_tpu.binaryconvert import convert_binary

    m2, sini = 0.25, 0.9
    par = BASE + ("BINARY ELL1\nPB 1.8 1\nA1 3.0 1\nTASC 55101.0 1\n"
                  "EPS1 1e-6 1\nEPS2 -2e-6 1\nM2 0.25\nSINI 0.9\n")
    m = get_model(par)
    mh = convert_binary(m, "ELL1H")
    assert "BinaryELL1H" in mh.components
    cosi = np.sqrt(1 - sini**2)
    st = sini / (1 + cosi)
    tsun = 4.925490947e-6
    assert mh.STIGMA.value == pytest.approx(st, rel=1e-12)
    assert mh.H3.value == pytest.approx(tsun * m2 * st**3, rel=1e-12)
    # residual equivalence through the orthometric expansion
    mjds = np.linspace(55050, 55150, 60)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False)
    r = np.asarray(Residuals(t, mh, subtract_mean=True).calc_time_resids())
    # exact-harmonics vs m2/sini ln-form difference only
    assert np.abs(r).max() < 5e-8
    # inverse: ELL1H -> ELL1 recovers M2/SINI
    me = convert_binary(mh, "ELL1")
    assert me.SINI.value == pytest.approx(sini, rel=1e-10)
    assert me.M2.value == pytest.approx(m2, rel=1e-10)


def test_dmxparse_and_ranges():
    from pint_tpu.utils import dmx_ranges, dmxparse
    from pint_tpu.fitter import WLSFitter

    par = BASE + ("DMX 6.5\nDMX_0001 1e-4 1\nDMXR1_0001 55000\nDMXR2_0001 55100\n"
                  "DMX_0002 -2e-4 1\nDMXR1_0002 55100\nDMXR2_0002 55200\n")
    m = get_model(par)
    mjds = np.linspace(55001, 55199, 60)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=2)
    f = WLSFitter(t, m)
    f.fit_toas()
    out = dmxparse(f)
    assert len(out["dmxs"]) == 2
    assert np.isfinite(out["dmx_verrs"]).all()
    assert out["dmxeps"][0] == pytest.approx(55050.0)
    ranges = dmx_ranges(t, binwidth_days=10.0)
    assert ranges[0][0] <= mjds[0] and ranges[-1][1] >= mjds[-1]


def test_plot_utils(tmp_path):
    from pint_tpu.plot_utils import phaseogram, phaseogram_binned

    rng = np.random.default_rng(0)
    mjds = rng.uniform(55000, 55010, 500)
    ph = rng.vonmises(np.pi, 3.0, 500) / (2 * np.pi) % 1.0
    f1 = tmp_path / "pg.png"
    phaseogram(mjds, ph, plotfile=str(f1), title="t")
    f2 = tmp_path / "pgb.png"
    phaseogram_binned(mjds, ph, plotfile=str(f2))
    assert f1.exists() and f2.exists()


def test_logging_dedup(capsys):
    import io

    from pint_tpu.logging_setup import setup, get_logger

    buf = io.StringIO()
    setup(level="INFO", stream=buf)
    log = get_logger("test")
    for _ in range(5):
        log.warning("repeated message")
    log.info("info passes")
    text = buf.getvalue()
    assert text.count("repeated message") == 1
    assert "info passes" in text


def test_config_accessors():
    from pint_tpu import config

    par = config.examplefile("NGC6440E.par")
    m = get_model(par)
    assert m.F0.value is not None
    assert config.runtimefile("observatories.json").endswith("observatories.json")
    with pytest.raises(FileNotFoundError):
        config.examplefile("nope.par")


def test_toa_pickle_cache(tmp_path):
    from pint_tpu.scripts import zima
    from pint_tpu.toa import get_TOAs, load_pickle

    par = tmp_path / "pk.par"
    par.write_text(BASE)
    tim = str(tmp_path / "pk.tim")
    zima.main([str(par), tim, "--ntoa", "15", "--startMJD", "55000",
               "--duration", "100"])
    t1 = get_TOAs(tim, usepickle=True)
    import os
    assert os.path.exists(tim + ".pickle.gz")
    t2 = get_TOAs(tim, usepickle=True)  # served from cache
    np.testing.assert_array_equal(t1.day, t2.day)
    np.testing.assert_allclose(np.asarray(t1.ssb_obs.pos),
                               np.asarray(t2.ssb_obs.pos))
    # different settings -> cache miss
    assert load_pickle(tim, planets=True) is None
    # editing the tim busts the cache
    with open(tim, "a") as f:
        f.write("# touched\n")
    assert load_pickle(tim) is None


def test_fit_checkpointing(tmp_path):
    import copy

    from pint_tpu.checkpoint import FitCheckpointer, checkpointed_fit
    from pint_tpu.fitter import WLSFitter

    ck = FitCheckpointer(tmp_path / "ck")
    ck.save("t", {"x": np.arange(3.0), "iter": 4, "chi2": 12.5})
    state = ck.restore("t")
    np.testing.assert_allclose(state["x"], [0, 1, 2])
    assert ck.latest_iteration("t") == 4
    assert ck.restore("missing") is None
    # end-to-end resume: fit, checkpoint, perturb, restore-by-rerun
    m = get_model(BASE)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55400, 40), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=4)
    m2 = copy.deepcopy(m)
    m2.F0.value += 1e-9
    f = WLSFitter(t, m2)
    chi2 = checkpointed_fit(f, tmp_path / "fit_ck", maxiter=3)
    assert np.isfinite(chi2)
    # a fresh fitter resumes from the snapshot
    m3 = copy.deepcopy(m)
    m3.F0.value += 5e-9
    f2 = WLSFitter(t, m3)
    chi2b = checkpointed_fit(f2, tmp_path / "fit_ck", maxiter=4)
    assert abs(f2.model.F0.value - f.model.F0.value) < 1e-11


def test_checkpoint_cross_format_restore(tmp_path):
    """A snapshot written by the npz backend (orbax unavailable at save
    time) must restore once orbax IS importable — save() chose the
    format at write time."""
    from pint_tpu.checkpoint import FitCheckpointer

    ck_npz = FitCheckpointer(tmp_path / "x")
    ck_npz._ocp = None  # simulate "orbax absent" at save time
    ck_npz.save("t", {"x": np.arange(4.0), "iter": 2, "chi2": 3.5})
    ck_orbax = FitCheckpointer(tmp_path / "x")  # orbax importable now
    state = ck_orbax.restore("t")
    assert state is not None
    np.testing.assert_allclose(state["x"], np.arange(4.0))
    assert ck_orbax.latest_iteration("t") == 2


def test_fitter_plot_smoke(tmp_path):
    """Fitter.plot writes a residual plot (reference: Fitter.plot)."""
    import matplotlib

    matplotlib.use("Agg")
    import numpy as np

    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    m = get_model("PSR TPLOT\nRAJ 6:00:00\nDECJ 10:00:00\nF0 200.0 1\n"
                  "PEPOCH 55000\nDM 10.0\n")
    t = make_fake_toas_fromMJDs(np.linspace(54900, 55100, 25), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=2)
    f = WLSFitter(t, m)
    f.fit_toas()
    out = tmp_path / "resid.png"
    f.plot(plotfile=str(out))
    assert out.exists() and out.stat().st_size > 1000


def test_delay_breakdown_sums_to_total():
    """delay_breakdown pieces sum to the full delay chain and carry
    the expected per-component scales."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TDBRK\nRAJ 6:00:00\nDECJ 10:00:00\nF0 200.0 1\n"
           "PEPOCH 55000\nDM 15.0 1\nBINARY ELL1\nPB 5.7\nA1 3.36\n"
           "TASC 55001\nEPS1 1e-5\nEPS2 -8e-6\nM2 0.2\nSINI 0.9\n")
    m = get_model(par)
    t = make_fake_toas_fromMJDs(np.linspace(54800, 55200, 30), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=False, iterations=0)
    parts = m.delay_breakdown(t)
    total = np.asarray(m.delay(t))
    # 1 ns bound: the eager per-op sum and the fused jitted chain may
    # round differently at the ~500 s Roemer scale (and TPU-emulated
    # f64 has a higher error floor than exact-IEEE CPU)
    np.testing.assert_allclose(sum(parts.values()), total, rtol=0,
                               atol=1e-9)
    assert np.abs(parts["AstrometryEquatorial"]).max() > 100.0  # Roemer
    assert 1e-3 < np.abs(parts["DispersionDM"]).max() < 1.0
    assert np.abs(parts["BinaryELL1"]).max() > 1.0  # x = 3.36 ls
    assert np.abs(parts["SolarSystemShapiro"]).max() < 1e-3
