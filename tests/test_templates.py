"""Template / profile tests (reference test patterns:
tests/test_templates.py, tests/test_fftfit.py — normalization,
likelihood fit recovery, fftfit shift recovery vs known rotations).
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.templates import (LCFitter, LCGaussian, LCLorentzian,
                                LCSkewGaussian, LCTemplate, LCTopHat,
                                LCVonMises)
from pint_tpu.profile import fftfit_basic, fftfit_full


def test_gaussian_primitive_normalized():
    g = LCGaussian([0.03, 0.4])
    assert float(g.integrate()) == pytest.approx(1.0, abs=1e-6)


def test_vonmises_primitive_normalized():
    v = LCVonMises([0.05, 0.7])
    assert float(v.integrate()) == pytest.approx(1.0, abs=1e-4)


def test_template_normalized_and_peak():
    t = LCTemplate([LCGaussian([0.02, 0.25]), LCGaussian([0.05, 0.6])],
                   [0.5, 0.3])
    assert float(t.integrate()) == pytest.approx(1.0, abs=1e-6)
    assert t.max_location() == pytest.approx(0.25, abs=0.01)


def test_template_dc_floor():
    t = LCTemplate([LCGaussian([0.02, 0.5])], [0.6])
    # far from the peak the density is the DC level 1 - 0.6
    assert float(t(np.array([0.0]))[0]) == pytest.approx(0.4, abs=1e-6)


def _draw_phases(rng, n, loc, sigma, frac):
    pulsed = rng.random(n) < frac
    ph = np.where(pulsed, (loc + sigma * rng.standard_normal(n)) % 1.0,
                  rng.random(n))
    return ph


def test_lcfitter_recovers_location():
    rng = np.random.default_rng(5)
    ph = _draw_phases(rng, 20000, loc=0.37, sigma=0.025, frac=0.55)
    t = LCTemplate([LCGaussian([0.04, 0.30])], [0.4])
    f = LCFitter(t, ph)
    ll0 = float(f.loglikelihood())
    f.fit(steps=500)
    assert f.ll > ll0
    assert t.primitives[0].loc == pytest.approx(0.37, abs=0.005)
    assert t.norms[0] == pytest.approx(0.55, abs=0.05)
    assert t.primitives[0].p[0] == pytest.approx(0.025, abs=0.008)


def test_lcfitter_weighted():
    rng = np.random.default_rng(6)
    ph = _draw_phases(rng, 8000, loc=0.5, sigma=0.03, frac=0.5)
    w = np.full(8000, 0.8)
    t = LCTemplate([LCGaussian([0.04, 0.45])], [0.5])
    f = LCFitter(t, ph, weights=w)
    f.fit(steps=300)
    assert t.primitives[0].loc == pytest.approx(0.5, abs=0.01)


def test_phase_shift_uncertainty_scales():
    rng = np.random.default_rng(7)
    t = LCTemplate([LCGaussian([0.03, 0.5])], [0.7])
    ph_small = _draw_phases(rng, 1000, 0.5, 0.03, 0.7)
    ph_big = _draw_phases(rng, 16000, 0.5, 0.03, 0.7)
    s_small = LCFitter(t, ph_small).phase_shift_uncertainty()
    s_big = LCFitter(t, ph_big).phase_shift_uncertainty()
    assert s_big < s_small
    assert s_small == pytest.approx(4.0 * s_big, rel=0.3)  # ~1/sqrt(N)


# ---------------- fftfit ----------------


def _profile(n, loc, width, amp=1000.0, dc=100.0):
    x = np.arange(n) / n
    d = np.minimum(np.abs(x - loc), 1 - np.abs(x - loc))
    return dc + amp * np.exp(-0.5 * (d / width) ** 2)


def test_fftfit_exact_shift():
    tmpl = _profile(256, 0.3, 0.02, dc=0.0)
    for true in (0.0, 0.123, -0.2, 0.43):
        prof = _profile(256, (0.3 + true) % 1.0, 0.02, dc=0.0)
        got = fftfit_basic(tmpl, prof)
        err = (got - true + 0.5) % 1.0 - 0.5
        assert abs(err) < 1e-6


def test_fftfit_scale_offset():
    tmpl = _profile(128, 0.5, 0.03, amp=1.0, dc=0.0)
    prof = 7.5 + 3.0 * np.roll(tmpl, 10)
    r = fftfit_full(tmpl, prof)
    assert r.scale == pytest.approx(3.0, rel=1e-6)
    assert r.offset == pytest.approx(7.5, rel=1e-6)
    assert r.shift == pytest.approx(10 / 128, abs=1e-8)


def test_fftfit_noisy_shift_and_uncertainty():
    rng = np.random.default_rng(8)
    tmpl = _profile(512, 0.4, 0.015, amp=500.0, dc=0.0)
    errs, sigs = [], []
    for i in range(20):
        prof = np.roll(tmpl, 37) + rng.standard_normal(512) * 20.0
        r = fftfit_full(tmpl, prof)
        errs.append(r.shift - 37 / 512)
        sigs.append(r.uncertainty)
    errs = np.array(errs)
    # reported uncertainty consistent with scatter (within x3)
    assert np.std(errs) < 3 * np.mean(sigs)
    assert np.mean(sigs) < 3e-4
    assert np.abs(np.mean(errs)) < 3 * np.mean(sigs)


def test_lorentzian_skewgaussian_normalized():
    from pint_tpu.templates import LCLorentzian, LCSkewGaussian

    lo = LCLorentzian([0.03, 0.4])
    assert float(lo.integrate()) == pytest.approx(1.0, abs=1e-6)
    sk = LCSkewGaussian([0.02, 0.05, 0.6])
    assert float(sk.integrate()) == pytest.approx(1.0, abs=1e-4)
    # skew: rises faster than it falls (sigma1 < sigma2)
    import numpy as _np

    d_lead = float(sk(_np.array([0.6 - 0.02]))[0])
    d_trail = float(sk(_np.array([0.6 + 0.02]))[0])
    assert d_lead == pytest.approx(d_trail * _np.exp(-0.5 + 0.5 * (0.02/0.05)**2),
                                   rel=1e-6)


def test_norm_angles_roundtrip():
    from pint_tpu.templates import NormAngles, angles_from_norms, norms_from_angles

    for norms in ([0.55], [0.3, 0.2], [0.5, 0.1, 0.25], [0.0, 0.4]):
        a = angles_from_norms(norms)
        back = np.asarray(norms_from_angles(a))
        np.testing.assert_allclose(back, norms, atol=1e-12)
        assert back.sum() <= 1.0 + 1e-12
    na = NormAngles([0.3, 0.4])
    np.testing.assert_allclose(na(), [0.3, 0.4], atol=1e-12)
    with pytest.raises(ValueError):
        angles_from_norms([0.7, 0.5])  # sum > 1


def test_two_component_photon_template_end_to_end():
    """Simulate photons from a two-peak template; LCFitter recovers
    both peak locations and norms; Hessian uncertainties bracket the
    errors (the VERDICT 'two-component end-to-end' requirement)."""
    from pint_tpu.templates import LCGaussian, LCFitter, LCTemplate

    rng = np.random.default_rng(17)
    true = LCTemplate([LCGaussian([0.03, 0.30]), LCGaussian([0.06, 0.75])],
                      [0.35, 0.25])
    # rejection-sample photon phases from the density
    n = 20000
    ph = []
    fmax = 6.5
    while len(ph) < n:
        x = rng.uniform(0, 1, 4 * n)
        y = rng.uniform(0, fmax, 4 * n)
        acc = x[y < np.asarray(true(x))]
        ph.extend(acc.tolist())
    ph = np.array(ph[:n])
    start = LCTemplate([LCGaussian([0.05, 0.27]), LCGaussian([0.05, 0.8])],
                       [0.3, 0.3])
    f = LCFitter(start, ph)
    ll = f.fit(steps=500)
    assert np.isfinite(ll)
    locs = sorted(pr.loc for pr in start.primitives)
    assert abs(locs[0] - 0.30) < 0.01
    assert abs(locs[1] - 0.75) < 0.02
    assert abs(start.norms.sum() - 0.60) < 0.05
    sig = f.param_uncertainties()
    assert sig.shape == (2 + 2 + 2,)
    assert (sig[:2] < 0.05).all() and (sig[:2] > 0).all()


def test_fftfit_backend_shims():
    from pint_tpu.profile import (fftfit_basic_aarchiba, fftfit_cprof,
                                  fftfit_full_nustar, fftfit_full_presto)
    from pint_tpu.templates import LCGaussian, LCTemplate

    n = 256
    x = np.arange(n) / n
    tmpl = np.asarray(LCTemplate([LCGaussian([0.04, 0.5])], [0.8])(x))
    shift_true = 0.1337
    prof = np.asarray(LCTemplate([LCGaussian([0.04, 0.5 + shift_true])],
                                 [0.8])(x)) * 2.5 + 1.0
    assert fftfit_basic_aarchiba(tmpl, prof) == pytest.approx(shift_true,
                                                              abs=1e-6)
    s, es, snr, esnr = fftfit_full_nustar(tmpl, prof)
    assert s == pytest.approx(shift_true, abs=1e-6) and snr > 100
    sb, esb = fftfit_full_presto(tmpl, prof)
    assert sb == pytest.approx(shift_true * n, abs=1e-3)
    c, amp, phase = fftfit_cprof(prof)
    assert c == pytest.approx(prof.sum())
    assert len(amp) == n // 2


def test_new_primitives_normalized():
    from pint_tpu.templates import LCHarmonic, LCTopHat

    for prim in (LCTopHat([0.2, 0.3]), LCTopHat([0.05, 0.9]),
                 LCHarmonic([1, 0.4]), LCHarmonic([3, 0.1])):
        assert float(prim.integrate()) == pytest.approx(1.0, abs=2e-3)


def test_harmonic_order_structural():
    """LCHarmonic's order never drifts in a fit; its loc does."""
    import jax.numpy as jnp

    from pint_tpu.templates import LCHarmonic

    pr = LCHarmonic([2, 0.35])
    q = pr.project_params(jnp.asarray([2.4, 1.2]))
    assert float(q[0]) == 2.0
    assert float(q[1]) == pytest.approx(0.2)


def test_energy_dependent_gaussian():
    """LCEGaussian: at the 1 GeV pivot it equals its base; away from it
    the peak moves by slope * dlogE (reference: lceprimitives.py)."""
    from pint_tpu.templates import LCEGaussian, LCGaussian

    base = LCGaussian([0.03, 0.50])
    e = LCEGaussian([0.03, 0.50], slopes=[0.0, 0.05])
    x = np.linspace(0, 1, 512, endpoint=False)
    # pivot energy: identical densities
    d_pivot = np.asarray(e(x, log10_ens=np.full(512, 3.0)))
    assert np.allclose(d_pivot, np.asarray(base(x)), atol=1e-12)
    # at 10 GeV (log10 E = 4): peak shifted by 0.05
    d_hi = np.asarray(e(x, log10_ens=np.full(512, 4.0)))
    assert abs(x[np.argmax(d_hi)] - 0.55) < 2.0 / 512


def test_energy_dependent_template_fit_recovers_slope():
    """Unbinned ML fit of an energy-dependent template recovers an
    injected location-vs-energy drift."""
    from pint_tpu.templates import LCEGaussian, LCFitter, LCTemplate

    rng = np.random.default_rng(11)
    n = 6000
    log10_e = rng.uniform(2.0, 4.5, n)  # 100 MeV .. 30 GeV
    slope_true = 0.04
    locs = 0.50 + slope_true * (log10_e - 3.0)
    pulsed = rng.random(n) < 0.7
    phases = np.where(pulsed,
                      (rng.normal(locs, 0.03)) % 1.0,
                      rng.random(n))
    tmpl = LCTemplate([LCEGaussian([0.05, 0.45], slopes=[0.0, 0.0])], [0.5])
    f = LCFitter(tmpl, phases, log10_ens=log10_e)
    f.fit(steps=600, lr=5e-3)
    fitted = tmpl.primitives[0]
    assert abs(float(fitted.p[1]) - 0.50) < 0.01       # pivot loc
    assert abs(float(fitted.p[3]) - slope_true) < 0.01  # loc slope


def test_gauss_template_file_roundtrip(tmp_path):
    from pint_tpu.templates import (LCGaussian, LCTemplate,
                                    gauss_template_from_file,
                                    write_gauss_template)

    t = LCTemplate([LCGaussian([0.03, 0.25]), LCGaussian([0.08, 0.70])],
                   [0.45, 0.20])
    p = tmp_path / "tmpl.gauss"
    write_gauss_template(t, p)
    t2 = gauss_template_from_file(p)
    assert len(t2.primitives) == 2
    assert np.allclose(t2.norms, t.norms, atol=1e-5)
    for a, b in zip(t.primitives, t2.primitives):
        assert np.allclose(a.p, b.p, atol=1e-5)
    x = np.linspace(0, 1, 256, endpoint=False)
    assert np.allclose(np.asarray(t(x)), np.asarray(t2(x)), atol=1e-4)


def test_gauss_template_from_pygaussfit_style(tmp_path):
    text = """# gauss fit from pygaussfit.py
const  = 0.400
phas1  =     0.100000 +/- 0.0010
fwhm1  =     0.070640 +/- 0.0020
ampl1  =     0.500000 +/- 0.0100
"""
    p = tmp_path / "presto.gauss"
    p.write_text(text)
    from pint_tpu.templates import gauss_template_from_file

    t = gauss_template_from_file(p)
    assert len(t.primitives) == 1
    # fwhm -> sigma conversion
    assert float(t.primitives[0].p[0]) == pytest.approx(0.03, abs=1e-4)
    assert float(t.primitives[0].loc) == pytest.approx(0.1)
    # ampl 0.5 exceeds 1-const=0.6? no: fits, kept as-is
    assert float(t.norms[0]) == pytest.approx(0.5)


def test_empirical_fourier_template():
    from pint_tpu.templates import LCEmpiricalFourier, LCGaussian, LCTemplate

    x = np.linspace(0, 1, 512, endpoint=False)
    truth = LCTemplate([LCGaussian([0.05, 0.37])], [0.6])
    prof = np.asarray(truth(x))
    emp = LCEmpiricalFourier(profile=prof, nharm=16)
    d = np.asarray(emp(x))
    assert np.allclose(d, prof, atol=0.02)  # nonparametric reconstruction
    assert abs(emp.max_location() - 0.37) < 0.01
    # photon-sample constructor: harmonics from unbinned phases
    rng = np.random.default_rng(5)
    ph = np.concatenate([(rng.normal(0.37, 0.05, 40000)) % 1.0,
                         rng.random(30000)])
    emp2 = LCEmpiricalFourier(phases=ph, nharm=8)
    assert abs(emp2.max_location() - 0.37) < 0.02


def test_fftfit_cc_backend_agrees():
    """The cross-correlation backend and the Taylor backend agree on
    clean and noisy shifted profiles (mutual validation, reference:
    multiple fftfit backends)."""
    from pint_tpu.profile import fftfit_cc, fftfit_full
    from pint_tpu.templates import LCGaussian, LCTemplate

    n = 256
    x = np.arange(n) / n
    t = LCTemplate([LCGaussian([0.04, 0.5])], [0.8])
    tmpl = np.asarray(t(x))
    rng = np.random.default_rng(2)
    for shift_true in (-0.31, 0.0, 0.0731, 0.49):
        prof_t = LCTemplate([LCGaussian([0.04, (0.5 + shift_true) % 1.0])],
                            [0.8])
        prof = np.asarray(prof_t(x)) * 1.7 + 0.3
        s_cc = fftfit_cc(tmpl, prof)
        s_taylor = fftfit_full(tmpl, prof).shift
        d = (s_cc - shift_true + 0.5) % 1.0 - 0.5
        assert abs(d) < 1e-4, (shift_true, s_cc)
        d2 = (s_cc - s_taylor + 0.5) % 1.0 - 0.5
        assert abs(d2) < 1e-4
        noisy = prof + rng.normal(0, 0.05, n)
        d3 = (fftfit_cc(tmpl, noisy) - fftfit_full(tmpl, noisy).shift
              + 0.5) % 1.0 - 0.5
        assert abs(d3) < 5e-3


# ---------------------------------------------------------------------------
# r4 quantitative depth (VERDICT r3 item 8): calibration, asymmetry,
# published anchors, per-family parameter recovery
# ---------------------------------------------------------------------------

def _sample_from_template(rng, t, n, fmax=None):
    """Rejection-sample photon phases from an LCTemplate density."""
    grid = np.linspace(0, 1, 2048, endpoint=False)
    dens = np.asarray(t(grid))
    fmax = fmax or dens.max() * 1.05
    out = []
    while sum(len(o) for o in out) < n:
        ph = rng.uniform(0, 1, 4 * n)
        keep = rng.uniform(0, fmax, 4 * n) < np.asarray(t(ph))
        out.append(ph[keep])
    return np.concatenate(out)[:n]


def test_fftfit_uncertainty_statistically_calibrated():
    """The reported shift uncertainty must MATCH the Monte-Carlo
    scatter (ratio within [0.6, 1.6]), not merely bound it — the
    wideband TOA pipeline consumes this number as a real sigma
    (reference: fftfit Taylor 1992 appendix; upstream
    tests/test_fftfit.py checks the same calibration)."""
    rng = np.random.default_rng(42)
    tmpl = _profile(512, 0.4, 0.02, amp=800.0, dc=0.0)
    errs, sigs = [], []
    for i in range(60):
        prof = np.roll(tmpl, 23) + rng.standard_normal(512) * 30.0
        r = fftfit_full(tmpl, prof)
        errs.append(r.shift - 23 / 512)
        sigs.append(r.uncertainty)
    ratio = np.std(errs) / np.mean(sigs)
    assert 0.6 < ratio < 1.6, ratio


def test_fftfit_cc_vs_taylor_on_asymmetric_profile():
    """Asymmetric (skew) pulse: the Taylor fftfit and the independent
    cross-correlation backend must agree within their combined
    uncertainty, and neither may show a bias beyond 3 sigma — the
    asymmetry is exactly where a centroid-style estimator would
    diverge from the template-matched one."""
    from pint_tpu.profile.fftfit import fftfit_cc

    rng = np.random.default_rng(7)
    x = np.arange(512) / 512.0
    d = (x - 0.35 + 0.5) % 1.0 - 0.5
    sig = np.where(d < 0, 0.015, 0.06)  # sharp rise, slow decay
    tmpl = 600.0 * np.exp(-0.5 * (d / sig) ** 2)
    true = 41 / 512.0
    biases_t, biases_c, sigs = [], [], []
    for i in range(25):
        prof = np.roll(tmpl, 41) + rng.standard_normal(512) * 25.0
        rt = fftfit_full(tmpl, prof)
        cc = fftfit_cc(tmpl, prof)
        biases_t.append(rt.shift - true)
        biases_c.append(((cc - true) + 0.5) % 1.0 - 0.5)
        sigs.append(rt.uncertainty)
    mt, mc, s = (np.mean(biases_t), np.mean(biases_c),
                 np.mean(sigs) / np.sqrt(len(biases_t)))
    assert abs(mt) < 3 * s, (mt, s)          # Taylor unbiased
    assert abs(mt - mc) < 5 * s, (mt, mc, s)  # backends agree


def test_sf_hm_published_anchor():
    """H-test significance against the PUBLISHED de Jager & Busching
    (2010, A&A 517, L9) calibration P = exp(-0.4 H), and the
    documented sig2sigma example (2.866e-7 -> 5.0 sigma)."""
    from pint_tpu.eventstats import sf_hm, sig2sigma

    assert sf_hm(23.0) == pytest.approx(np.exp(-9.2), rel=1e-12)
    assert sf_hm(50.0) == pytest.approx(2.0611536e-9, rel=1e-6)
    assert sig2sigma(2.866515719235352e-07) == pytest.approx(5.0, abs=1e-6)


def test_htest_false_alarm_rate_calibrated():
    """Monte-Carlo false-alarm calibration of OUR hm implementation
    against the published survival function: for uniform (no-signal)
    phases, P(H > h) must track exp(-0.4 h) (within Poisson error x a
    factor ~2 calibration band, as in the original paper's fig. 1)."""
    from pint_tpu.eventstats import hm

    rng = np.random.default_rng(11)
    n_trials, n_ph = 800, 120
    phases = rng.uniform(0, 1, (n_trials, n_ph))
    hs = np.array([float(hm(phases[i])) for i in range(n_trials)])
    for h0 in (5.0, 8.0):
        emp = float(np.mean(hs > h0))
        pred = np.exp(-0.4 * h0)
        # Poisson band on the empirical rate, doubled for the
        # calibration-formula tolerance
        band = 2.0 * (np.sqrt(pred * n_trials) / n_trials + 2.0 / n_trials)
        assert abs(emp - pred) < band, (h0, emp, pred, band)


@pytest.mark.parametrize("prim,true_p,tol_loc,tol_w", [
    (LCGaussian([0.03, 0.40]), [0.03, 0.40], 0.006, 0.010),
    (LCLorentzian([0.02, 0.55]), [0.02, 0.55], 0.008, 0.012),
    (LCVonMises([0.04, 0.30]), [0.04, 0.30], 0.008, 0.020),
    (LCSkewGaussian([0.02, 0.05, 0.60]), [0.02, 0.05, 0.60], 0.012, 0.020),
    (LCTopHat([0.20, 0.45]), [0.20, 0.45], 0.015, 0.030),
])
def test_primitive_family_parameter_recovery(prim, true_p, tol_loc, tol_w):
    """Per-family QUANTITATIVE recovery (not smoke): photons drawn
    from each primitive's own density, refit from a perturbed start,
    parameters recovered within stated tolerances (reference:
    upstream tests/test_lcprimitives.py per-class batteries)."""
    import zlib

    from pint_tpu.templates import LCTemplate

    # deterministic per-family seed (hash() is salted per-process)
    rng = np.random.default_rng(zlib.crc32(type(prim).__name__.encode()))
    t_true = LCTemplate([type(prim)(list(true_p))], [0.65])
    ph = _sample_from_template(rng, t_true, 25000)
    start = list(true_p)
    start[0] *= 1.4          # misstate the width
    start[-1] = (start[-1] + 0.04) % 1.0  # and the location
    t_fit = LCTemplate([type(prim)(start)], [0.5])
    f = LCFitter(t_fit, ph)
    f.fit(steps=600)
    got = t_fit.primitives[0].p
    assert got[-1] == pytest.approx(true_p[-1], abs=tol_loc)
    assert got[0] == pytest.approx(true_p[0], abs=tol_w)
    assert t_fit.norms[0] == pytest.approx(0.65, abs=0.06)


def test_template_fit_error_propagation_at_scale():
    """Error propagation through template fits at photon scale:
    reported parameter uncertainties follow 1/sqrt(N) between N=5k
    and N=20k, and the reported phase-shift uncertainty (the location
    sigma the wideband/event pipelines consume) matches the
    Monte-Carlo scatter of independent refits within a calibration
    band (reference: lcfitters hessian errors; upstream
    tests/test_lcfitters.py)."""
    from pint_tpu.templates import LCTemplate

    def fit_once(n, seed):
        r = np.random.default_rng(seed)
        t_true = LCTemplate([LCGaussian([0.03, 0.42])], [0.6])
        ph = _sample_from_template(r, t_true, n)
        t = LCTemplate([LCGaussian([0.035, 0.40])], [0.5])
        f = LCFitter(t, ph)
        f.fit(steps=500)
        unc = f.param_uncertainties()
        return t.primitives[0].loc, unc, f.phase_shift_uncertainty()

    loc5, unc5, sig5 = fit_once(5000, 100)
    loc20, unc20, sig20 = fit_once(20000, 101)
    unc5 = np.asarray(unc5)
    unc20 = np.asarray(unc20)
    assert np.all(np.isfinite(unc5)) and np.all(unc5 > 0)
    # 1/sqrt(N): factor 2 between 5k and 20k photons (30% slack)
    np.testing.assert_allclose(unc5, 2.0 * unc20, rtol=0.35)
    assert sig5 == pytest.approx(2.0 * sig20, rel=0.35)
    # MC calibration: the scatter of independently refit locations
    # must match the REPORTED location sigma within a factor 2.5 —
    # a ~3x mis-scaled sigma fails this band
    locs = [fit_once(5000, 200 + i)[0] for i in range(10)]
    scatter = np.std(locs)
    assert sig5 / 2.5 < scatter < sig5 * 2.5, (scatter, sig5)


def test_kernel_density_template_recovers_shift():
    """LCKernelDensity (reference: lcprimitives.py::LCKernelDensity):
    a KDE template bootstrapped from one photon sample, unit-
    normalized, reproducing the sample's peak; fitting it to a SHIFTED
    second sample recovers the shift quantitatively — upstream's
    template-from-the-data workflow end-to-end."""
    from pint_tpu.templates import LCKernelDensity

    rng = np.random.default_rng(21)
    n = 24000
    base = np.concatenate([
        (0.30 + 0.025 * rng.standard_normal(n // 2)) % 1.0,
        rng.uniform(0, 1, n // 2)])
    kde = LCKernelDensity(base)
    # unit density + peak location from the data
    grid = np.linspace(0, 1, 2048, endpoint=False)
    d = np.asarray(kde(grid))
    assert d.mean() == pytest.approx(1.0, abs=1e-6)
    assert grid[np.argmax(d)] == pytest.approx(0.30, abs=0.01)
    assert 0.005 < kde.bandwidth < 0.2  # circular Silverman sanity
    # bin-center interpolation: the KDE of a symmetric peak must be
    # UNBIASED well below the half-bin scale (0.5/512 ~ 1 milliphase
    # was the bias of left-edge interpolation, caught in r4 review)
    win = (grid > 0.2) & (grid < 0.4)
    centroid = np.sum(grid[win] * (d[win] - d[win].min())) \
        / np.sum(d[win] - d[win].min())
    assert centroid == pytest.approx(0.30, abs=3e-4), centroid

    # fit the frozen shape's phase shift to a rotated second sample
    true_shift = 0.137
    sample2 = np.concatenate([
        (0.30 + true_shift + 0.025 * rng.standard_normal(n // 2)) % 1.0,
        rng.uniform(0, 1, n // 2)])
    t = LCTemplate([LCKernelDensity(base)], [0.9])
    f = LCFitter(t, sample2)
    f.fit(steps=400)
    got = t.primitives[0].loc
    err = (got - true_shift + 0.5) % 1.0 - 0.5
    assert abs(err) < 0.005, (got, true_shift)


def test_binned_fit_matches_unbinned():
    """LCFitter.fit(unbinned=False): the Poisson-histogram objective
    (reference: lcfitters.py binned mode) recovers the same location
    and width as the exact unbinned likelihood to well within the
    statistical uncertainty, and reports a comparable unbinned logL."""
    rng = np.random.default_rng(31)
    ph = _draw_phases(rng, 30000, loc=0.62, sigma=0.03, frac=0.6)
    t_u = LCTemplate([LCGaussian([0.05, 0.58])], [0.5])
    f_u = LCFitter(t_u, ph)
    ll_u = f_u.fit(steps=500)
    t_b = LCTemplate([LCGaussian([0.05, 0.58])], [0.5])
    f_b = LCFitter(t_b, ph)
    ll_b = f_b.fit(steps=500, unbinned=False, nbins=256)
    assert t_b.primitives[0].loc == pytest.approx(t_u.primitives[0].loc,
                                                  abs=0.002)
    assert t_b.primitives[0].p[0] == pytest.approx(t_u.primitives[0].p[0],
                                                   rel=0.1)
    assert t_b.norms[0] == pytest.approx(t_u.norms[0], abs=0.03)
    # comparable unbinned logL (binned optimum is near the MLE)
    assert ll_b == pytest.approx(ll_u, abs=5.0)
    # energy-dependent templates and weighted photons are unbinned-only
    with pytest.raises(ValueError, match="binned"):
        LCFitter(t_b, ph, log10_ens=np.full(len(ph), 3.0)).fit(
            steps=1, unbinned=False)
    with pytest.raises(ValueError, match="weights"):
        LCFitter(t_b, ph, weights=np.full(len(ph), 0.7)).fit(
            steps=1, unbinned=False)


def test_binned_fit_wraps_out_of_range_phases():
    """Binned mode histograms phases mod 1 (ADVICE r4: out-of-[0,1)
    phases — accepted by the unbinned path, which wraps inside the
    primitives — were silently dropped from the histogram, biasing the
    Poisson objective). Shifting every photon by an integer number of
    turns must leave the binned fit unchanged."""
    rng = np.random.default_rng(77)
    ph = _draw_phases(rng, 8000, loc=0.4, sigma=0.04, frac=0.7)
    shifted = ph + np.where(np.arange(len(ph)) % 3 == 0, 1.0,
                            np.where(np.arange(len(ph)) % 3 == 1, -2.0, 0.0))
    t_a = LCTemplate([LCGaussian([0.06, 0.35])], [0.5])
    ll_a = LCFitter(t_a, ph).fit(steps=300, unbinned=False, nbins=128)
    t_b = LCTemplate([LCGaussian([0.06, 0.35])], [0.5])
    ll_b = LCFitter(t_b, shifted).fit(steps=300, unbinned=False, nbins=128)
    assert t_b.primitives[0].loc == pytest.approx(t_a.primitives[0].loc,
                                                  abs=1e-9)
    assert t_b.primitives[0].p[0] == pytest.approx(t_a.primitives[0].p[0],
                                                   rel=1e-9)
    assert ll_b == pytest.approx(ll_a, abs=1e-6)


# ---- two-sided (asymmetric) primitives: LCGaussian2 / LCLorentzian2
# (reference: lcprimitives.py::LCGaussian2/LCLorentzian2 — VERDICT r4
# item 5) ----


def _draw_two_sided(rng, n, loc, w1, w2, frac, kind="gauss"):
    """Photon sample: frac from a two-sided peak (leading width w1,
    trailing w2) + (1-frac) uniform background."""
    n_sig = int(n * frac)
    lead = rng.random(n_sig) < w1 / (w1 + w2)
    if kind == "gauss":
        mag = np.abs(rng.standard_normal(n_sig))
    else:  # half-Cauchy
        mag = np.abs(np.tan(np.pi * (rng.random(n_sig) - 0.5)))
        mag = np.minimum(mag, 50.0)  # truncate monster tails
    d = np.where(lead, -mag * w1, mag * w2)
    sig = (loc + d) % 1.0
    return np.concatenate([sig, rng.random(n - n_sig)])


def test_lclorentzian2_density_normalized_and_asymmetric():
    from pint_tpu.templates import LCLorentzian2

    import jax.numpy as jnp

    prim = LCLorentzian2([0.01, 0.04, 0.3])
    x = jnp.linspace(0.0, 1.0, 20001)
    dens = prim(x)
    # exact truncated-kernel normalization: unit mass on [0,1)
    assert float(jnp.trapezoid(dens, x)) == pytest.approx(1.0, abs=2e-4)
    assert float(dens.min()) >= 0.0
    # peak at loc; leading side falls off 4x faster than trailing
    assert abs(float(x[int(jnp.argmax(dens))]) - 0.3) < 1e-3
    lead = prim(jnp.asarray([0.3 - 0.02]))[0]
    trail = prim(jnp.asarray([0.3 + 0.02]))[0]
    assert float(trail) > 2.0 * float(lead)
    # HWHM semantics per side: density at loc -/+ gamma_i is half peak
    peak = float(prim(jnp.asarray([0.3]))[0])
    assert float(prim(jnp.asarray([0.3 - 0.01]))[0]) == pytest.approx(
        peak / 2, rel=0.02)
    assert float(prim(jnp.asarray([0.3 + 0.04]))[0]) == pytest.approx(
        peak / 2, rel=0.02)


def test_lcgaussian2_alias_is_skew_gaussian():
    from pint_tpu.templates import LCGaussian2, LCSkewGaussian

    assert LCGaussian2 is LCSkewGaussian


def test_two_sided_gaussian_fit_recovers_asymmetry():
    """Unbinned AND binned fits of an asymmetric peak recover distinct
    leading/trailing widths (the upstream LCGaussian2 use case)."""
    from pint_tpu.templates import LCGaussian2, LCTemplate
    from pint_tpu.templates.lcfitters import LCFitter

    rng = np.random.default_rng(42)
    ph = _draw_two_sided(rng, 40000, loc=0.55, w1=0.015, w2=0.06,
                         frac=0.65, kind="gauss")
    for unbinned in (True, False):
        t = LCTemplate([LCGaussian2([0.03, 0.03, 0.5])], [0.6])
        f = LCFitter(t, ph)
        f.fit(steps=600, unbinned=unbinned, nbins=256)
        s1, s2, loc = (float(v) for v in t.primitives[0].p)
        assert loc == pytest.approx(0.55, abs=0.005), unbinned
        assert s1 == pytest.approx(0.015, rel=0.25), unbinned
        assert s2 == pytest.approx(0.06, rel=0.25), unbinned
        assert s2 > 2.5 * s1  # the asymmetry itself is detected


def test_two_sided_lorentzian_fit_recovers_asymmetry():
    from pint_tpu.templates import LCLorentzian2, LCTemplate
    from pint_tpu.templates.lcfitters import LCFitter

    rng = np.random.default_rng(7)
    ph = _draw_two_sided(rng, 40000, loc=0.4, w1=0.01, w2=0.035,
                         frac=0.7, kind="lorentz")
    t = LCTemplate([LCLorentzian2([0.02, 0.02, 0.45])], [0.6])
    f = LCFitter(t, ph)
    f.fit(steps=600)
    g1, g2, loc = (float(v) for v in t.primitives[0].p)
    assert loc == pytest.approx(0.4, abs=0.005)
    assert g1 == pytest.approx(0.01, rel=0.35)
    assert g2 == pytest.approx(0.035, rel=0.35)
    assert g2 > 1.8 * g1


def test_gauss_template_file_edge_cases(tmp_path):
    """Gauss-file ecosystem edge cases (VERDICT r4 item on the .gauss
    ecosystem): index gaps, over-unity amplitude renormalization
    against const, no-component errors, and explicit rejection (not
    silent flattening) of primitives the format cannot hold."""
    from pint_tpu.templates import (LCGaussian, LCLorentzian2, LCTemplate,
                                    gauss_template_from_file,
                                    write_gauss_template)

    # component indices with a gap (1 and 3): both read, order kept
    p = tmp_path / "gap.gauss"
    p.write_text("const = 0.2\n"
                 "phas1 = 0.10\nfwhm1 = 0.0706\nampl1 = 0.30\n"
                 "phas3 = 0.60\nfwhm3 = 0.1413\nampl3 = 0.25\n")
    t = gauss_template_from_file(p)
    assert len(t.primitives) == 2
    assert float(t.primitives[1].loc) == pytest.approx(0.60)
    # amplitudes above 1-const are renormalized to the pulsed cap
    p2 = tmp_path / "over.gauss"
    p2.write_text("const = 0.5\n"
                  "phas1 = 0.2\nfwhm1 = 0.07\nampl1 = 0.4\n"
                  "phas2 = 0.7\nfwhm2 = 0.07\nampl2 = 0.4\n")
    t2 = gauss_template_from_file(p2)
    assert float(np.sum(t2.norms)) == pytest.approx(0.5, abs=1e-9)
    # a file with no components errors instead of returning an empty
    # template
    p3 = tmp_path / "empty.gauss"
    p3.write_text("# nothing here\nconst = 1.0\n")
    with pytest.raises(ValueError, match="no gaussian"):
        gauss_template_from_file(p3)
    # two-sided primitives have no representation in the symmetric
    # presto format: writing must REJECT, not silently symmetrize
    t_asym = LCTemplate([LCLorentzian2([0.01, 0.04, 0.3])], [0.6])
    with pytest.raises(ValueError, match="LCGaussian"):
        write_gauss_template(t_asym, tmp_path / "bad.gauss")
