"""Template / profile tests (reference test patterns:
tests/test_templates.py, tests/test_fftfit.py — normalization,
likelihood fit recovery, fftfit shift recovery vs known rotations).
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.templates import LCFitter, LCGaussian, LCTemplate, LCVonMises
from pint_tpu.profile import fftfit_basic, fftfit_full


def test_gaussian_primitive_normalized():
    g = LCGaussian([0.03, 0.4])
    assert float(g.integrate()) == pytest.approx(1.0, abs=1e-6)


def test_vonmises_primitive_normalized():
    v = LCVonMises([0.05, 0.7])
    assert float(v.integrate()) == pytest.approx(1.0, abs=1e-4)


def test_template_normalized_and_peak():
    t = LCTemplate([LCGaussian([0.02, 0.25]), LCGaussian([0.05, 0.6])],
                   [0.5, 0.3])
    assert float(t.integrate()) == pytest.approx(1.0, abs=1e-6)
    assert t.max_location() == pytest.approx(0.25, abs=0.01)


def test_template_dc_floor():
    t = LCTemplate([LCGaussian([0.02, 0.5])], [0.6])
    # far from the peak the density is the DC level 1 - 0.6
    assert float(t(np.array([0.0]))[0]) == pytest.approx(0.4, abs=1e-6)


def _draw_phases(rng, n, loc, sigma, frac):
    pulsed = rng.random(n) < frac
    ph = np.where(pulsed, (loc + sigma * rng.standard_normal(n)) % 1.0,
                  rng.random(n))
    return ph


def test_lcfitter_recovers_location():
    rng = np.random.default_rng(5)
    ph = _draw_phases(rng, 20000, loc=0.37, sigma=0.025, frac=0.55)
    t = LCTemplate([LCGaussian([0.04, 0.30])], [0.4])
    f = LCFitter(t, ph)
    ll0 = float(f.loglikelihood())
    f.fit(steps=500)
    assert f.ll > ll0
    assert t.primitives[0].loc == pytest.approx(0.37, abs=0.005)
    assert t.norms[0] == pytest.approx(0.55, abs=0.05)
    assert t.primitives[0].p[0] == pytest.approx(0.025, abs=0.008)


def test_lcfitter_weighted():
    rng = np.random.default_rng(6)
    ph = _draw_phases(rng, 8000, loc=0.5, sigma=0.03, frac=0.5)
    w = np.full(8000, 0.8)
    t = LCTemplate([LCGaussian([0.04, 0.45])], [0.5])
    f = LCFitter(t, ph, weights=w)
    f.fit(steps=300)
    assert t.primitives[0].loc == pytest.approx(0.5, abs=0.01)


def test_phase_shift_uncertainty_scales():
    rng = np.random.default_rng(7)
    t = LCTemplate([LCGaussian([0.03, 0.5])], [0.7])
    ph_small = _draw_phases(rng, 1000, 0.5, 0.03, 0.7)
    ph_big = _draw_phases(rng, 16000, 0.5, 0.03, 0.7)
    s_small = LCFitter(t, ph_small).phase_shift_uncertainty()
    s_big = LCFitter(t, ph_big).phase_shift_uncertainty()
    assert s_big < s_small
    assert s_small == pytest.approx(4.0 * s_big, rel=0.3)  # ~1/sqrt(N)


# ---------------- fftfit ----------------


def _profile(n, loc, width, amp=1000.0, dc=100.0):
    x = np.arange(n) / n
    d = np.minimum(np.abs(x - loc), 1 - np.abs(x - loc))
    return dc + amp * np.exp(-0.5 * (d / width) ** 2)


def test_fftfit_exact_shift():
    tmpl = _profile(256, 0.3, 0.02, dc=0.0)
    for true in (0.0, 0.123, -0.2, 0.43):
        prof = _profile(256, (0.3 + true) % 1.0, 0.02, dc=0.0)
        got = fftfit_basic(tmpl, prof)
        err = (got - true + 0.5) % 1.0 - 0.5
        assert abs(err) < 1e-6


def test_fftfit_scale_offset():
    tmpl = _profile(128, 0.5, 0.03, amp=1.0, dc=0.0)
    prof = 7.5 + 3.0 * np.roll(tmpl, 10)
    r = fftfit_full(tmpl, prof)
    assert r.scale == pytest.approx(3.0, rel=1e-6)
    assert r.offset == pytest.approx(7.5, rel=1e-6)
    assert r.shift == pytest.approx(10 / 128, abs=1e-8)


def test_fftfit_noisy_shift_and_uncertainty():
    rng = np.random.default_rng(8)
    tmpl = _profile(512, 0.4, 0.015, amp=500.0, dc=0.0)
    errs, sigs = [], []
    for i in range(20):
        prof = np.roll(tmpl, 37) + rng.standard_normal(512) * 20.0
        r = fftfit_full(tmpl, prof)
        errs.append(r.shift - 37 / 512)
        sigs.append(r.uncertainty)
    errs = np.array(errs)
    # reported uncertainty consistent with scatter (within x3)
    assert np.std(errs) < 3 * np.mean(sigs)
    assert np.mean(sigs) < 3e-4
    assert np.abs(np.mean(errs)) < 3 * np.mean(sigs)
