"""Template / profile tests (reference test patterns:
tests/test_templates.py, tests/test_fftfit.py — normalization,
likelihood fit recovery, fftfit shift recovery vs known rotations).
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.templates import LCFitter, LCGaussian, LCTemplate, LCVonMises
from pint_tpu.profile import fftfit_basic, fftfit_full


def test_gaussian_primitive_normalized():
    g = LCGaussian([0.03, 0.4])
    assert float(g.integrate()) == pytest.approx(1.0, abs=1e-6)


def test_vonmises_primitive_normalized():
    v = LCVonMises([0.05, 0.7])
    assert float(v.integrate()) == pytest.approx(1.0, abs=1e-4)


def test_template_normalized_and_peak():
    t = LCTemplate([LCGaussian([0.02, 0.25]), LCGaussian([0.05, 0.6])],
                   [0.5, 0.3])
    assert float(t.integrate()) == pytest.approx(1.0, abs=1e-6)
    assert t.max_location() == pytest.approx(0.25, abs=0.01)


def test_template_dc_floor():
    t = LCTemplate([LCGaussian([0.02, 0.5])], [0.6])
    # far from the peak the density is the DC level 1 - 0.6
    assert float(t(np.array([0.0]))[0]) == pytest.approx(0.4, abs=1e-6)


def _draw_phases(rng, n, loc, sigma, frac):
    pulsed = rng.random(n) < frac
    ph = np.where(pulsed, (loc + sigma * rng.standard_normal(n)) % 1.0,
                  rng.random(n))
    return ph


def test_lcfitter_recovers_location():
    rng = np.random.default_rng(5)
    ph = _draw_phases(rng, 20000, loc=0.37, sigma=0.025, frac=0.55)
    t = LCTemplate([LCGaussian([0.04, 0.30])], [0.4])
    f = LCFitter(t, ph)
    ll0 = float(f.loglikelihood())
    f.fit(steps=500)
    assert f.ll > ll0
    assert t.primitives[0].loc == pytest.approx(0.37, abs=0.005)
    assert t.norms[0] == pytest.approx(0.55, abs=0.05)
    assert t.primitives[0].p[0] == pytest.approx(0.025, abs=0.008)


def test_lcfitter_weighted():
    rng = np.random.default_rng(6)
    ph = _draw_phases(rng, 8000, loc=0.5, sigma=0.03, frac=0.5)
    w = np.full(8000, 0.8)
    t = LCTemplate([LCGaussian([0.04, 0.45])], [0.5])
    f = LCFitter(t, ph, weights=w)
    f.fit(steps=300)
    assert t.primitives[0].loc == pytest.approx(0.5, abs=0.01)


def test_phase_shift_uncertainty_scales():
    rng = np.random.default_rng(7)
    t = LCTemplate([LCGaussian([0.03, 0.5])], [0.7])
    ph_small = _draw_phases(rng, 1000, 0.5, 0.03, 0.7)
    ph_big = _draw_phases(rng, 16000, 0.5, 0.03, 0.7)
    s_small = LCFitter(t, ph_small).phase_shift_uncertainty()
    s_big = LCFitter(t, ph_big).phase_shift_uncertainty()
    assert s_big < s_small
    assert s_small == pytest.approx(4.0 * s_big, rel=0.3)  # ~1/sqrt(N)


# ---------------- fftfit ----------------


def _profile(n, loc, width, amp=1000.0, dc=100.0):
    x = np.arange(n) / n
    d = np.minimum(np.abs(x - loc), 1 - np.abs(x - loc))
    return dc + amp * np.exp(-0.5 * (d / width) ** 2)


def test_fftfit_exact_shift():
    tmpl = _profile(256, 0.3, 0.02, dc=0.0)
    for true in (0.0, 0.123, -0.2, 0.43):
        prof = _profile(256, (0.3 + true) % 1.0, 0.02, dc=0.0)
        got = fftfit_basic(tmpl, prof)
        err = (got - true + 0.5) % 1.0 - 0.5
        assert abs(err) < 1e-6


def test_fftfit_scale_offset():
    tmpl = _profile(128, 0.5, 0.03, amp=1.0, dc=0.0)
    prof = 7.5 + 3.0 * np.roll(tmpl, 10)
    r = fftfit_full(tmpl, prof)
    assert r.scale == pytest.approx(3.0, rel=1e-6)
    assert r.offset == pytest.approx(7.5, rel=1e-6)
    assert r.shift == pytest.approx(10 / 128, abs=1e-8)


def test_fftfit_noisy_shift_and_uncertainty():
    rng = np.random.default_rng(8)
    tmpl = _profile(512, 0.4, 0.015, amp=500.0, dc=0.0)
    errs, sigs = [], []
    for i in range(20):
        prof = np.roll(tmpl, 37) + rng.standard_normal(512) * 20.0
        r = fftfit_full(tmpl, prof)
        errs.append(r.shift - 37 / 512)
        sigs.append(r.uncertainty)
    errs = np.array(errs)
    # reported uncertainty consistent with scatter (within x3)
    assert np.std(errs) < 3 * np.mean(sigs)
    assert np.mean(sigs) < 3e-4
    assert np.abs(np.mean(errs)) < 3 * np.mean(sigs)


def test_lorentzian_skewgaussian_normalized():
    from pint_tpu.templates import LCLorentzian, LCSkewGaussian

    lo = LCLorentzian([0.03, 0.4])
    assert float(lo.integrate()) == pytest.approx(1.0, abs=1e-6)
    sk = LCSkewGaussian([0.02, 0.05, 0.6])
    assert float(sk.integrate()) == pytest.approx(1.0, abs=1e-4)
    # skew: rises faster than it falls (sigma1 < sigma2)
    import numpy as _np

    d_lead = float(sk(_np.array([0.6 - 0.02]))[0])
    d_trail = float(sk(_np.array([0.6 + 0.02]))[0])
    assert d_lead == pytest.approx(d_trail * _np.exp(-0.5 + 0.5 * (0.02/0.05)**2),
                                   rel=1e-6)


def test_norm_angles_roundtrip():
    from pint_tpu.templates import NormAngles, angles_from_norms, norms_from_angles

    for norms in ([0.55], [0.3, 0.2], [0.5, 0.1, 0.25], [0.0, 0.4]):
        a = angles_from_norms(norms)
        back = np.asarray(norms_from_angles(a))
        np.testing.assert_allclose(back, norms, atol=1e-12)
        assert back.sum() <= 1.0 + 1e-12
    na = NormAngles([0.3, 0.4])
    np.testing.assert_allclose(na(), [0.3, 0.4], atol=1e-12)
    with pytest.raises(ValueError):
        angles_from_norms([0.7, 0.5])  # sum > 1


def test_two_component_photon_template_end_to_end():
    """Simulate photons from a two-peak template; LCFitter recovers
    both peak locations and norms; Hessian uncertainties bracket the
    errors (the VERDICT 'two-component end-to-end' requirement)."""
    from pint_tpu.templates import LCGaussian, LCFitter, LCTemplate

    rng = np.random.default_rng(17)
    true = LCTemplate([LCGaussian([0.03, 0.30]), LCGaussian([0.06, 0.75])],
                      [0.35, 0.25])
    # rejection-sample photon phases from the density
    n = 20000
    ph = []
    fmax = 6.5
    while len(ph) < n:
        x = rng.uniform(0, 1, 4 * n)
        y = rng.uniform(0, fmax, 4 * n)
        acc = x[y < np.asarray(true(x))]
        ph.extend(acc.tolist())
    ph = np.array(ph[:n])
    start = LCTemplate([LCGaussian([0.05, 0.27]), LCGaussian([0.05, 0.8])],
                       [0.3, 0.3])
    f = LCFitter(start, ph)
    ll = f.fit(steps=500)
    assert np.isfinite(ll)
    locs = sorted(pr.loc for pr in start.primitives)
    assert abs(locs[0] - 0.30) < 0.01
    assert abs(locs[1] - 0.75) < 0.02
    assert abs(start.norms.sum() - 0.60) < 0.05
    sig = f.param_uncertainties()
    assert sig.shape == (2 + 2 + 2,)
    assert (sig[:2] < 0.05).all() and (sig[:2] > 0).all()


def test_fftfit_backend_shims():
    from pint_tpu.profile import (fftfit_basic_aarchiba, fftfit_cprof,
                                  fftfit_full_nustar, fftfit_full_presto)
    from pint_tpu.templates import LCGaussian, LCTemplate

    n = 256
    x = np.arange(n) / n
    tmpl = np.asarray(LCTemplate([LCGaussian([0.04, 0.5])], [0.8])(x))
    shift_true = 0.1337
    prof = np.asarray(LCTemplate([LCGaussian([0.04, 0.5 + shift_true])],
                                 [0.8])(x)) * 2.5 + 1.0
    assert fftfit_basic_aarchiba(tmpl, prof) == pytest.approx(shift_true,
                                                              abs=1e-6)
    s, es, snr, esnr = fftfit_full_nustar(tmpl, prof)
    assert s == pytest.approx(shift_true, abs=1e-6) and snr > 100
    sb, esb = fftfit_full_presto(tmpl, prof)
    assert sb == pytest.approx(shift_true * n, abs=1e-3)
    c, amp, phase = fftfit_cprof(prof)
    assert c == pytest.approx(prof.sum())
    assert len(amp) == n // 2
