"""Template / profile tests (reference test patterns:
tests/test_templates.py, tests/test_fftfit.py — normalization,
likelihood fit recovery, fftfit shift recovery vs known rotations).
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.templates import LCFitter, LCGaussian, LCTemplate, LCVonMises
from pint_tpu.profile import fftfit_basic, fftfit_full


def test_gaussian_primitive_normalized():
    g = LCGaussian([0.03, 0.4])
    assert float(g.integrate()) == pytest.approx(1.0, abs=1e-6)


def test_vonmises_primitive_normalized():
    v = LCVonMises([0.05, 0.7])
    assert float(v.integrate()) == pytest.approx(1.0, abs=1e-4)


def test_template_normalized_and_peak():
    t = LCTemplate([LCGaussian([0.02, 0.25]), LCGaussian([0.05, 0.6])],
                   [0.5, 0.3])
    assert float(t.integrate()) == pytest.approx(1.0, abs=1e-6)
    assert t.max_location() == pytest.approx(0.25, abs=0.01)


def test_template_dc_floor():
    t = LCTemplate([LCGaussian([0.02, 0.5])], [0.6])
    # far from the peak the density is the DC level 1 - 0.6
    assert float(t(np.array([0.0]))[0]) == pytest.approx(0.4, abs=1e-6)


def _draw_phases(rng, n, loc, sigma, frac):
    pulsed = rng.random(n) < frac
    ph = np.where(pulsed, (loc + sigma * rng.standard_normal(n)) % 1.0,
                  rng.random(n))
    return ph


def test_lcfitter_recovers_location():
    rng = np.random.default_rng(5)
    ph = _draw_phases(rng, 20000, loc=0.37, sigma=0.025, frac=0.55)
    t = LCTemplate([LCGaussian([0.04, 0.30])], [0.4])
    f = LCFitter(t, ph)
    ll0 = float(f.loglikelihood())
    f.fit(steps=500)
    assert f.ll > ll0
    assert t.primitives[0].loc == pytest.approx(0.37, abs=0.005)
    assert t.norms[0] == pytest.approx(0.55, abs=0.05)
    assert t.primitives[0].p[0] == pytest.approx(0.025, abs=0.008)


def test_lcfitter_weighted():
    rng = np.random.default_rng(6)
    ph = _draw_phases(rng, 8000, loc=0.5, sigma=0.03, frac=0.5)
    w = np.full(8000, 0.8)
    t = LCTemplate([LCGaussian([0.04, 0.45])], [0.5])
    f = LCFitter(t, ph, weights=w)
    f.fit(steps=300)
    assert t.primitives[0].loc == pytest.approx(0.5, abs=0.01)


def test_phase_shift_uncertainty_scales():
    rng = np.random.default_rng(7)
    t = LCTemplate([LCGaussian([0.03, 0.5])], [0.7])
    ph_small = _draw_phases(rng, 1000, 0.5, 0.03, 0.7)
    ph_big = _draw_phases(rng, 16000, 0.5, 0.03, 0.7)
    s_small = LCFitter(t, ph_small).phase_shift_uncertainty()
    s_big = LCFitter(t, ph_big).phase_shift_uncertainty()
    assert s_big < s_small
    assert s_small == pytest.approx(4.0 * s_big, rel=0.3)  # ~1/sqrt(N)


# ---------------- fftfit ----------------


def _profile(n, loc, width, amp=1000.0, dc=100.0):
    x = np.arange(n) / n
    d = np.minimum(np.abs(x - loc), 1 - np.abs(x - loc))
    return dc + amp * np.exp(-0.5 * (d / width) ** 2)


def test_fftfit_exact_shift():
    tmpl = _profile(256, 0.3, 0.02, dc=0.0)
    for true in (0.0, 0.123, -0.2, 0.43):
        prof = _profile(256, (0.3 + true) % 1.0, 0.02, dc=0.0)
        got = fftfit_basic(tmpl, prof)
        err = (got - true + 0.5) % 1.0 - 0.5
        assert abs(err) < 1e-6


def test_fftfit_scale_offset():
    tmpl = _profile(128, 0.5, 0.03, amp=1.0, dc=0.0)
    prof = 7.5 + 3.0 * np.roll(tmpl, 10)
    r = fftfit_full(tmpl, prof)
    assert r.scale == pytest.approx(3.0, rel=1e-6)
    assert r.offset == pytest.approx(7.5, rel=1e-6)
    assert r.shift == pytest.approx(10 / 128, abs=1e-8)


def test_fftfit_noisy_shift_and_uncertainty():
    rng = np.random.default_rng(8)
    tmpl = _profile(512, 0.4, 0.015, amp=500.0, dc=0.0)
    errs, sigs = [], []
    for i in range(20):
        prof = np.roll(tmpl, 37) + rng.standard_normal(512) * 20.0
        r = fftfit_full(tmpl, prof)
        errs.append(r.shift - 37 / 512)
        sigs.append(r.uncertainty)
    errs = np.array(errs)
    # reported uncertainty consistent with scatter (within x3)
    assert np.std(errs) < 3 * np.mean(sigs)
    assert np.mean(sigs) < 3e-4
    assert np.abs(np.mean(errs)) < 3 * np.mean(sigs)


def test_lorentzian_skewgaussian_normalized():
    from pint_tpu.templates import LCLorentzian, LCSkewGaussian

    lo = LCLorentzian([0.03, 0.4])
    assert float(lo.integrate()) == pytest.approx(1.0, abs=1e-6)
    sk = LCSkewGaussian([0.02, 0.05, 0.6])
    assert float(sk.integrate()) == pytest.approx(1.0, abs=1e-4)
    # skew: rises faster than it falls (sigma1 < sigma2)
    import numpy as _np

    d_lead = float(sk(_np.array([0.6 - 0.02]))[0])
    d_trail = float(sk(_np.array([0.6 + 0.02]))[0])
    assert d_lead == pytest.approx(d_trail * _np.exp(-0.5 + 0.5 * (0.02/0.05)**2),
                                   rel=1e-6)


def test_norm_angles_roundtrip():
    from pint_tpu.templates import NormAngles, angles_from_norms, norms_from_angles

    for norms in ([0.55], [0.3, 0.2], [0.5, 0.1, 0.25], [0.0, 0.4]):
        a = angles_from_norms(norms)
        back = np.asarray(norms_from_angles(a))
        np.testing.assert_allclose(back, norms, atol=1e-12)
        assert back.sum() <= 1.0 + 1e-12
    na = NormAngles([0.3, 0.4])
    np.testing.assert_allclose(na(), [0.3, 0.4], atol=1e-12)
    with pytest.raises(ValueError):
        angles_from_norms([0.7, 0.5])  # sum > 1


def test_two_component_photon_template_end_to_end():
    """Simulate photons from a two-peak template; LCFitter recovers
    both peak locations and norms; Hessian uncertainties bracket the
    errors (the VERDICT 'two-component end-to-end' requirement)."""
    from pint_tpu.templates import LCGaussian, LCFitter, LCTemplate

    rng = np.random.default_rng(17)
    true = LCTemplate([LCGaussian([0.03, 0.30]), LCGaussian([0.06, 0.75])],
                      [0.35, 0.25])
    # rejection-sample photon phases from the density
    n = 20000
    ph = []
    fmax = 6.5
    while len(ph) < n:
        x = rng.uniform(0, 1, 4 * n)
        y = rng.uniform(0, fmax, 4 * n)
        acc = x[y < np.asarray(true(x))]
        ph.extend(acc.tolist())
    ph = np.array(ph[:n])
    start = LCTemplate([LCGaussian([0.05, 0.27]), LCGaussian([0.05, 0.8])],
                       [0.3, 0.3])
    f = LCFitter(start, ph)
    ll = f.fit(steps=500)
    assert np.isfinite(ll)
    locs = sorted(pr.loc for pr in start.primitives)
    assert abs(locs[0] - 0.30) < 0.01
    assert abs(locs[1] - 0.75) < 0.02
    assert abs(start.norms.sum() - 0.60) < 0.05
    sig = f.param_uncertainties()
    assert sig.shape == (2 + 2 + 2,)
    assert (sig[:2] < 0.05).all() and (sig[:2] > 0).all()


def test_fftfit_backend_shims():
    from pint_tpu.profile import (fftfit_basic_aarchiba, fftfit_cprof,
                                  fftfit_full_nustar, fftfit_full_presto)
    from pint_tpu.templates import LCGaussian, LCTemplate

    n = 256
    x = np.arange(n) / n
    tmpl = np.asarray(LCTemplate([LCGaussian([0.04, 0.5])], [0.8])(x))
    shift_true = 0.1337
    prof = np.asarray(LCTemplate([LCGaussian([0.04, 0.5 + shift_true])],
                                 [0.8])(x)) * 2.5 + 1.0
    assert fftfit_basic_aarchiba(tmpl, prof) == pytest.approx(shift_true,
                                                              abs=1e-6)
    s, es, snr, esnr = fftfit_full_nustar(tmpl, prof)
    assert s == pytest.approx(shift_true, abs=1e-6) and snr > 100
    sb, esb = fftfit_full_presto(tmpl, prof)
    assert sb == pytest.approx(shift_true * n, abs=1e-3)
    c, amp, phase = fftfit_cprof(prof)
    assert c == pytest.approx(prof.sum())
    assert len(amp) == n // 2


def test_new_primitives_normalized():
    from pint_tpu.templates import LCHarmonic, LCTopHat

    for prim in (LCTopHat([0.2, 0.3]), LCTopHat([0.05, 0.9]),
                 LCHarmonic([1, 0.4]), LCHarmonic([3, 0.1])):
        assert float(prim.integrate()) == pytest.approx(1.0, abs=2e-3)


def test_harmonic_order_structural():
    """LCHarmonic's order never drifts in a fit; its loc does."""
    import jax.numpy as jnp

    from pint_tpu.templates import LCHarmonic

    pr = LCHarmonic([2, 0.35])
    q = pr.project_params(jnp.asarray([2.4, 1.2]))
    assert float(q[0]) == 2.0
    assert float(q[1]) == pytest.approx(0.2)


def test_energy_dependent_gaussian():
    """LCEGaussian: at the 1 GeV pivot it equals its base; away from it
    the peak moves by slope * dlogE (reference: lceprimitives.py)."""
    from pint_tpu.templates import LCEGaussian, LCGaussian

    base = LCGaussian([0.03, 0.50])
    e = LCEGaussian([0.03, 0.50], slopes=[0.0, 0.05])
    x = np.linspace(0, 1, 512, endpoint=False)
    # pivot energy: identical densities
    d_pivot = np.asarray(e(x, log10_ens=np.full(512, 3.0)))
    assert np.allclose(d_pivot, np.asarray(base(x)), atol=1e-12)
    # at 10 GeV (log10 E = 4): peak shifted by 0.05
    d_hi = np.asarray(e(x, log10_ens=np.full(512, 4.0)))
    assert abs(x[np.argmax(d_hi)] - 0.55) < 2.0 / 512


def test_energy_dependent_template_fit_recovers_slope():
    """Unbinned ML fit of an energy-dependent template recovers an
    injected location-vs-energy drift."""
    from pint_tpu.templates import LCEGaussian, LCFitter, LCTemplate

    rng = np.random.default_rng(11)
    n = 6000
    log10_e = rng.uniform(2.0, 4.5, n)  # 100 MeV .. 30 GeV
    slope_true = 0.04
    locs = 0.50 + slope_true * (log10_e - 3.0)
    pulsed = rng.random(n) < 0.7
    phases = np.where(pulsed,
                      (rng.normal(locs, 0.03)) % 1.0,
                      rng.random(n))
    tmpl = LCTemplate([LCEGaussian([0.05, 0.45], slopes=[0.0, 0.0])], [0.5])
    f = LCFitter(tmpl, phases, log10_ens=log10_e)
    f.fit(steps=600, lr=5e-3)
    fitted = tmpl.primitives[0]
    assert abs(float(fitted.p[1]) - 0.50) < 0.01       # pivot loc
    assert abs(float(fitted.p[3]) - slope_true) < 0.01  # loc slope


def test_gauss_template_file_roundtrip(tmp_path):
    from pint_tpu.templates import (LCGaussian, LCTemplate,
                                    gauss_template_from_file,
                                    write_gauss_template)

    t = LCTemplate([LCGaussian([0.03, 0.25]), LCGaussian([0.08, 0.70])],
                   [0.45, 0.20])
    p = tmp_path / "tmpl.gauss"
    write_gauss_template(t, p)
    t2 = gauss_template_from_file(p)
    assert len(t2.primitives) == 2
    assert np.allclose(t2.norms, t.norms, atol=1e-5)
    for a, b in zip(t.primitives, t2.primitives):
        assert np.allclose(a.p, b.p, atol=1e-5)
    x = np.linspace(0, 1, 256, endpoint=False)
    assert np.allclose(np.asarray(t(x)), np.asarray(t2(x)), atol=1e-4)


def test_gauss_template_from_pygaussfit_style(tmp_path):
    text = """# gauss fit from pygaussfit.py
const  = 0.400
phas1  =     0.100000 +/- 0.0010
fwhm1  =     0.070640 +/- 0.0020
ampl1  =     0.500000 +/- 0.0100
"""
    p = tmp_path / "presto.gauss"
    p.write_text(text)
    from pint_tpu.templates import gauss_template_from_file

    t = gauss_template_from_file(p)
    assert len(t.primitives) == 1
    # fwhm -> sigma conversion
    assert float(t.primitives[0].p[0]) == pytest.approx(0.03, abs=1e-4)
    assert float(t.primitives[0].loc) == pytest.approx(0.1)
    # ampl 0.5 exceeds 1-const=0.6? no: fits, kept as-is
    assert float(t.norms[0]) == pytest.approx(0.5)


def test_empirical_fourier_template():
    from pint_tpu.templates import LCEmpiricalFourier, LCGaussian, LCTemplate

    x = np.linspace(0, 1, 512, endpoint=False)
    truth = LCTemplate([LCGaussian([0.05, 0.37])], [0.6])
    prof = np.asarray(truth(x))
    emp = LCEmpiricalFourier(profile=prof, nharm=16)
    d = np.asarray(emp(x))
    assert np.allclose(d, prof, atol=0.02)  # nonparametric reconstruction
    assert abs(emp.max_location() - 0.37) < 0.01
    # photon-sample constructor: harmonics from unbinned phases
    rng = np.random.default_rng(5)
    ph = np.concatenate([(rng.normal(0.37, 0.05, 40000)) % 1.0,
                         rng.random(30000)])
    emp2 = LCEmpiricalFourier(phases=ph, nharm=8)
    assert abs(emp2.max_location() - 0.37) < 0.02


def test_fftfit_cc_backend_agrees():
    """The cross-correlation backend and the Taylor backend agree on
    clean and noisy shifted profiles (mutual validation, reference:
    multiple fftfit backends)."""
    from pint_tpu.profile import fftfit_cc, fftfit_full
    from pint_tpu.templates import LCGaussian, LCTemplate

    n = 256
    x = np.arange(n) / n
    t = LCTemplate([LCGaussian([0.04, 0.5])], [0.8])
    tmpl = np.asarray(t(x))
    rng = np.random.default_rng(2)
    for shift_true in (-0.31, 0.0, 0.0731, 0.49):
        prof_t = LCTemplate([LCGaussian([0.04, (0.5 + shift_true) % 1.0])],
                            [0.8])
        prof = np.asarray(prof_t(x)) * 1.7 + 0.3
        s_cc = fftfit_cc(tmpl, prof)
        s_taylor = fftfit_full(tmpl, prof).shift
        d = (s_cc - shift_true + 0.5) % 1.0 - 0.5
        assert abs(d) < 1e-4, (shift_true, s_cc)
        d2 = (s_cc - s_taylor + 0.5) % 1.0 - 0.5
        assert abs(d2) < 1e-4
        noisy = prof + rng.normal(0, 0.05, n)
        d3 = (fftfit_cc(tmpl, noisy) - fftfit_full(tmpl, noisy).shift
              + 0.5) % 1.0 - 0.5
        assert abs(d3) < 5e-3
