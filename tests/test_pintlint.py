"""pintlint (pint_tpu/analysis): per-rule bad/good fixtures, the
suppression grammar, the CLI contract, and the CI gate that keeps the
whole tree at zero unsuppressed findings.

The nan-guard bad fixtures are the three real bugs ADVICE.md round 5
found in this codebase (np.max(relres) > tol at pta.py, float(rel) >
tol at fitter.py, max(worst, float(rel)) at pint_serve_bench.py) —
each rule is seeded from a failure that actually shipped.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from pint_tpu.analysis import (LintConfig, json_report, run,
                               text_report, unsuppressed)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "pint_tpu")


def lint(tmp_path, sources, config):
    """Write {relpath: source} under tmp_path and lint the files."""
    paths = []
    for rel, src in sources.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        paths.append(str(p))
    return run(paths, config=config)


def live(findings, rule):
    return [f for f in unsuppressed(findings) if f.rule == rule]


# -- nan-guard (seed fixtures: the three shipped bugs) ---------------


NAN_CFG = LintConfig()


def test_nan_guard_flags_gt_on_diagnostic(tmp_path):
    bad = """
        import numpy as np

        def finalize(relres):
            if np.max(relres) > 1e-8:  # the pta.py:937 bug
                return "fallback"
            return "ok"
    """
    fs = lint(tmp_path, {"m.py": bad}, NAN_CFG)
    assert len(live(fs, "nan-guard")) == 1


def test_nan_guard_flags_float_cast_gt(tmp_path):
    bad = """
        def check(rel_resid):
            return float(rel_resid) > 1e-8  # the fitter.py bug
    """
    fs = lint(tmp_path, {"m.py": bad}, NAN_CFG)
    assert len(live(fs, "nan-guard")) == 1


def test_nan_guard_flags_builtin_max_fold(tmp_path):
    bad = """
        def worst_of(rels):
            worst = 0.0
            for rel in rels:
                worst = max(worst, float(rel.relres))
            return worst
    """
    fs = lint(tmp_path, {"m.py": bad}, NAN_CFG)
    assert len(live(fs, "nan-guard")) == 1


def test_nan_guard_quiet_on_sanctioned_forms(tmp_path):
    good = """
        import numpy as np

        from pint_tpu.fitter import relres_failed

        def finalize(relres):
            if relres_failed(relres, tol=1e-8):
                return "fallback"
            return "ok"

        def check(rel_resid):
            return not np.all(rel_resid <= 1e-8)

        def worst_of(rels):
            worst = 0.0
            for rel in rels:
                worst = float(np.maximum(worst, rel.relres))
            return worst
    """
    fs = lint(tmp_path, {"m.py": good}, NAN_CFG)
    assert live(fs, "nan-guard") == []


# -- f32-in-f64 ------------------------------------------------------


F32_CFG = LintConfig(f64_critical={"crit.py": {"gls_whiten"}})


def test_f32_in_f64_flags_astype(tmp_path):
    bad = """
        import jax.numpy as jnp

        def gls_whiten(M, sigma):
            Mw = (M / sigma[:, None]).astype(jnp.float32)
            return Mw
    """
    fs = lint(tmp_path, {"crit.py": bad}, F32_CFG)
    assert len(live(fs, "f32-in-f64")) == 1


def test_f32_in_f64_quiet_outside_registry(tmp_path):
    good = """
        import jax.numpy as jnp

        def gls_whiten(M, sigma):
            return M / sigma[:, None]

        def photon_kernel(x):
            # not registered: deliberately-f32 kernels stay legal
            return x.astype(jnp.float32)
    """
    fs = lint(tmp_path, {"crit.py": good}, F32_CFG)
    assert live(fs, "f32-in-f64") == []


# -- host-sync-in-jit ------------------------------------------------


def test_host_sync_in_jit_flags_float(tmp_path):
    bad = """
        import jax

        def fit_one(x):
            return float(x) + 1.0

        fit = jax.jit(fit_one)
    """
    fs = lint(tmp_path, {"m.py": bad}, LintConfig())
    assert len(live(fs, "host-sync-in-jit")) == 1


def test_host_sync_scoped_same_name_not_confused(tmp_path):
    """A host-side closure sharing its name with a jitted function in
    another scope must NOT be flagged (fitter.py has three distinct
    chi2_of; only one is traced)."""
    good = """
        import jax

        def device_side():
            @jax.jit
            def chi2_of(x):
                return x * x
            return chi2_of

        def host_side(vals):
            def chi2_of(x):
                return float(x) * 2.0
            return [chi2_of(v) for v in vals]
    """
    fs = lint(tmp_path, {"m.py": good}, LintConfig())
    assert live(fs, "host-sync-in-jit") == []


# -- static-unhashable -----------------------------------------------


def test_static_unhashable_flags_list_literal(tmp_path):
    bad = """
        import jax

        def solve(x, opts):
            return x

        solve = jax.jit(solve, static_argnames=("opts",))
        y = solve(1.0, opts=["a", "b"])
    """
    fs = lint(tmp_path, {"m.py": bad}, LintConfig())
    assert len(live(fs, "static-unhashable")) == 1


def test_static_unhashable_quiet_on_tuple(tmp_path):
    good = """
        import jax

        def solve(x, opts):
            return x

        solve = jax.jit(solve, static_argnames=("opts",))
        y = solve(1.0, opts=("a", "b"))
    """
    fs = lint(tmp_path, {"m.py": good}, LintConfig())
    assert live(fs, "static-unhashable") == []


# -- serve-unpadded-batch --------------------------------------------


SERVE_CFG = LintConfig(serve_pad_modules=("serve/",))


def test_serve_unpadded_batch_flags_missing_pad(tmp_path):
    bad = """
        def flush(models, toas_list, bucket):
            pta = PTABatch(models, toas_list)
            return pta
    """
    fs = lint(tmp_path, {"serve/eng.py": bad}, SERVE_CFG)
    assert len(live(fs, "serve-unpadded-batch")) == 1


def test_serve_unpadded_batch_quiet_with_pad(tmp_path):
    good = """
        def flush(models, toas_list, bucket):
            pta = PTABatch(models, toas_list, pad_toas=bucket)
            return pta
    """
    fs = lint(tmp_path, {"serve/eng.py": good}, SERVE_CFG)
    assert live(fs, "serve-unpadded-batch") == []


# -- bucket-hardcoded ------------------------------------------------


BUCKET_CFG = LintConfig(
    bucket_allowed_modules=("parallel/shapeplan.py", "serve/batcher.py"))


def test_bucket_hardcoded_flags_direct_call(tmp_path):
    bad = """
        from pint_tpu.serve.batcher import pow2_bucket

        def group(toas_list, floor):
            return {pow2_bucket(len(t), floor) for t in toas_list}
    """
    fs = lint(tmp_path, {"parallel/grouping.py": bad}, BUCKET_CFG)
    assert len(live(fs, "bucket-hardcoded")) == 1


def test_bucket_hardcoded_flags_attribute_call(tmp_path):
    bad = """
        from pint_tpu.serve import batcher

        def width(n):
            return batcher.pow2_bucket(n)
    """
    fs = lint(tmp_path, {"serve/eng.py": bad}, BUCKET_CFG)
    assert len(live(fs, "bucket-hardcoded")) == 1


def test_bucket_hardcoded_quiet_in_allowed_modules(tmp_path):
    impl = """
        def pow2_bucket(n, floor=256):
            b = int(floor)
            while b < n:
                b *= 2
            return b

        def slot(n):
            return pow2_bucket(n)
    """
    wrapper = """
        def pow2_width(n, floor=256):
            from ..serve.batcher import pow2_bucket

            return pow2_bucket(n, floor)
    """
    fs = lint(tmp_path, {"serve/batcher.py": impl,
                         "parallel/shapeplan.py": wrapper}, BUCKET_CFG)
    assert live(fs, "bucket-hardcoded") == []


def test_bucket_hardcoded_quiet_on_planner_api(tmp_path):
    good = """
        from pint_tpu.parallel.shapeplan import ladder_width, pow2_width

        def width(n, plan):
            if plan is not None:
                return ladder_width(n, plan.widths)
            return pow2_width(n)
    """
    fs = lint(tmp_path, {"serve/eng.py": good}, BUCKET_CFG)
    assert live(fs, "bucket-hardcoded") == []


# -- lock-discipline -------------------------------------------------


LOCK_CFG = LintConfig(
    locked_classes={"Cache": {"lock": "_lock", "attrs": None}},
    locked_globals={"CACHE": "CACHE_LOCK"})


def test_lock_discipline_flags_unlocked_mutations(tmp_path):
    bad = """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.RLock()
                self.hits = 0
                self._d = {}

            def bump(self):
                self.hits += 1

            def put(self, key, value):
                self._d[key] = value

            def drop(self, key):
                self._d.pop(key, None)
    """
    fs = lint(tmp_path, {"m.py": bad}, LOCK_CFG)
    assert len(live(fs, "lock-discipline")) == 3


def test_lock_discipline_quiet_under_lock(tmp_path):
    good = """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.RLock()
                self.hits = 0
                self._d = {}

            def bump(self):
                with self._lock:
                    self.hits += 1

            def put(self, key, value):
                with self._lock:
                    self._d[key] = value
    """
    fs = lint(tmp_path, {"m.py": good}, LOCK_CFG)
    assert live(fs, "lock-discipline") == []


def test_lock_discipline_module_global(tmp_path):
    bad = """
        import threading

        CACHE = {}
        CACHE_LOCK = threading.RLock()

        def put(key, value):
            CACHE[key] = value
    """
    good = """
        import threading

        CACHE = {}
        CACHE_LOCK = threading.RLock()

        def put(key, value):
            with CACHE_LOCK:
                CACHE[key] = value
    """
    assert len(live(lint(tmp_path, {"a/m.py": bad}, LOCK_CFG),
                    "lock-discipline")) == 1
    assert live(lint(tmp_path, {"b/m.py": good}, LOCK_CFG),
                "lock-discipline") == []


# -- locked-helper-call ----------------------------------------------


def test_locked_helper_call_requires_lock(tmp_path):
    bad = """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.RLock()
                self._d = {}

            def _entry_locked(self, key):
                return self._d.setdefault(key, {"n": 0})

            def bump(self, key):
                e = self._entry_locked(key)
                e["n"] += 1
    """
    good = """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.RLock()
                self._d = {}

            def _entry_locked(self, key):
                return self._d.setdefault(key, {"n": 0})

            def bump(self, key):
                with self._lock:
                    e = self._entry_locked(key)
                    e["n"] += 1
    """
    assert len(live(lint(tmp_path, {"a/m.py": bad}, LOCK_CFG),
                    "locked-helper-call")) == 1
    assert live(lint(tmp_path, {"b/m.py": good}, LOCK_CFG),
                "locked-helper-call") == []


# -- fault-point coverage (both directions) --------------------------


FAULT_REGISTRY = """
    POINTS = ("toa_nan", "compile_fail")

    def fire(point):
        return point in POINTS
"""


def _fault_cfg():
    return LintConfig(fault_registry_suffix="faultreg.py")


def test_fault_point_unknown_flags_typo(tmp_path):
    user = """
        from faultreg import fire

        def go():
            fire("toa_nan")
            fire("compile_fial")  # typo'd point: never fires
            fire("compile_fail")
    """
    fs = lint(tmp_path, {"faultreg.py": FAULT_REGISTRY,
                         "user.py": user}, _fault_cfg())
    unknown = live(fs, "fault-point-unknown")
    assert len(unknown) == 1 and "compile_fial" in unknown[0].message
    assert live(fs, "fault-point-unfired") == []


def test_fault_point_unfired_flags_dead_registry_entry(tmp_path):
    user = """
        from faultreg import fire

        def go():
            fire("toa_nan")
    """
    fs = lint(tmp_path, {"faultreg.py": FAULT_REGISTRY,
                         "user.py": user}, _fault_cfg())
    unfired = live(fs, "fault-point-unfired")
    assert len(unfired) == 1 and "compile_fail" in unfired[0].message
    assert live(fs, "fault-point-unknown") == []


DEVICE_FAULT_REGISTRY = """
    POINTS = ("toa_nan", "device_loss", "straggler_delay")
    DEVICE_POINTS = ("device_loss", "straggler_delay")

    def fire(point):
        return point in POINTS
"""

DEVICE_FAULT_USER = """
    from faultreg import fire

    def go():
        fire("toa_nan")
        fire("device_loss")
        fire("straggler_delay")
"""


def test_fault_point_untested_flags_unarmed_device_point(tmp_path):
    test = """
        from faultreg import FaultPoint, inject

        def test_device_loss():
            with inject(FaultPoint("device_loss", rate=1.0)):
                pass
    """
    fs = lint(tmp_path, {"faultreg.py": DEVICE_FAULT_REGISTRY,
                         "user.py": DEVICE_FAULT_USER,
                         "tests/test_chaos.py": test}, _fault_cfg())
    untested = live(fs, "fault-point-untested")
    # straggler_delay is fired by the package but never armed by the
    # test; device_loss is armed (both FaultPoint() and inject() count)
    assert len(untested) == 1, untested
    assert "straggler_delay" in untested[0].message


def test_fault_point_untested_quiet_when_all_armed(tmp_path):
    test = """
        from faultreg import FaultPoint, inject

        def test_chaos():
            with inject(FaultPoint("device_loss", rate=1.0),
                        FaultPoint("straggler_delay", rate=1.0)):
                pass
    """
    fs = lint(tmp_path, {"faultreg.py": DEVICE_FAULT_REGISTRY,
                         "user.py": DEVICE_FAULT_USER,
                         "tests/test_chaos.py": test}, _fault_cfg())
    assert live(fs, "fault-point-untested") == []


def test_fault_point_untested_quiet_without_tests_in_scope(tmp_path):
    # package-only scan: the rule cannot tell armed from unarmed, so
    # it must stay silent instead of flagging every device point
    fs = lint(tmp_path, {"faultreg.py": DEVICE_FAULT_REGISTRY,
                         "user.py": DEVICE_FAULT_USER}, _fault_cfg())
    assert live(fs, "fault-point-untested") == []


# -- timing-no-block -------------------------------------------------


def test_timing_no_block_flags_async_window(tmp_path):
    bad = """
        import time

        import jax

        def bench():
            def step(x):
                return x * 2.0

            g = jax.jit(step)
            t0 = time.perf_counter()
            out = g(1.0)  # async enqueue; nothing waits for the device
            dt = time.perf_counter() - t0
            return out, dt
    """
    fs = lint(tmp_path, {"m.py": bad}, LintConfig())
    assert len(live(fs, "timing-no-block")) == 1


def test_timing_no_block_quiet_with_block(tmp_path):
    good = """
        import time

        import jax

        def bench():
            def step(x):
                return x * 2.0

            g = jax.jit(step)
            t0 = time.perf_counter()
            out = jax.block_until_ready(g(1.0))
            dt = time.perf_counter() - t0
            return out, dt
    """
    fs = lint(tmp_path, {"m.py": good}, LintConfig())
    assert live(fs, "timing-no-block") == []


def test_timing_no_block_sees_obs_clock_windows(tmp_path):
    # the sanctioned obs clock opens timing windows too: migrating a
    # bench from time.perf_counter to obs_clock.now must not blind
    # the async-dispatch check
    bad = """
        import jax

        from pint_tpu.obs import clock as obs_clock

        def bench():
            def step(x):
                return x * 2.0

            g = jax.jit(step)
            t0 = obs_clock.now()
            out = g(1.0)  # async enqueue, nothing waits
            dt = obs_clock.now() - t0
            return out, dt
    """
    fs = lint(tmp_path, {"m.py": bad}, LintConfig())
    assert len(live(fs, "timing-no-block")) == 1


# -- timing-untraced -------------------------------------------------


OBS_CFG = LintConfig(obs_instrumented_modules=("/engine.py",))


def test_timing_untraced_flags_raw_reads(tmp_path):
    bad = """
        import time

        def flush():
            t0 = time.perf_counter()
            wall = time.time()
            return time.perf_counter() - t0, wall
    """
    fs = lint(tmp_path, {"engine.py": bad}, OBS_CFG)
    assert len(live(fs, "timing-untraced")) == 3


def test_timing_untraced_quiet_on_obs_clock_and_sleep(tmp_path):
    good = """
        import time

        from pint_tpu.obs import clock as obs_clock

        def flush(clock=time.monotonic):  # reference, not a call
            t0 = obs_clock.now()
            time.sleep(0.0)  # a delay, not a measurement
            return obs_clock.now() - t0
    """
    fs = lint(tmp_path, {"engine.py": good}, OBS_CFG)
    assert live(fs, "timing-untraced") == []


def test_timing_untraced_quiet_outside_instrumented_modules(tmp_path):
    src = """
        import time

        def helper():
            return time.perf_counter()
    """
    fs = lint(tmp_path, {"other.py": src}, OBS_CFG)
    assert live(fs, "timing-untraced") == []


def test_timing_untraced_allows_obs_package_and_tests(tmp_path):
    src = """
        import time

        def probe():
            return time.perf_counter()
    """
    cfg = LintConfig(obs_instrumented_modules=("/clock.py",
                                               "/test_engine.py"))
    fs = lint(tmp_path, {"obs/clock.py": src,
                         "tests/test_engine.py": src}, cfg)
    assert live(fs, "timing-untraced") == []


# -- quality-signal-dropped ------------------------------------------


QUALITY_CFG = LintConfig(quality_signal_modules=("/fitter.py",))


def test_quality_signal_dropped_flags_unrecorded_verdict(tmp_path):
    bad = """
        def gls_solve(relres):
            if relres_failed(relres):
                return "f64"
            return "mixed"
    """
    fs = lint(tmp_path, {"fitter.py": bad}, QUALITY_CFG)
    assert len(live(fs, "quality-signal-dropped")) == 1


def test_quality_signal_dropped_flags_unrecorded_chi2(tmp_path):
    bad = """
        class F:
            def fit_toas(self, chi2):
                self.chi2_whitened = chi2
                return chi2
    """
    fs = lint(tmp_path, {"fitter.py": bad}, QUALITY_CFG)
    assert len(live(fs, "quality-signal-dropped")) == 1


def test_quality_signal_dropped_quiet_when_recorded(tmp_path):
    good = """
        from pint_tpu.obs import fitquality as obs_fitq

        def gls_solve(relres):
            if relres_failed(relres):
                obs_fitq.FITQ.note_fallback(["gls_solve"])
                return "f64"
            return "mixed"

        class F:
            def fit_toas(self, chi2):
                self.chi2_whitened = chi2
                self._record_fit_quality(chi2)
                return chi2
    """
    fs = lint(tmp_path, {"fitter.py": good}, QUALITY_CFG)
    assert live(fs, "quality-signal-dropped") == []


def test_quality_signal_dropped_ignores_guard_def_and_reads(tmp_path):
    good = """
        def relres_failed(rel, tol=1e-8):
            return not (rel <= tol)

        def report(fitter):
            return getattr(fitter, "chi2_whitened", None)
    """
    fs = lint(tmp_path, {"fitter.py": good}, QUALITY_CFG)
    assert live(fs, "quality-signal-dropped") == []


def test_quality_signal_dropped_scoped_and_suppressible(tmp_path):
    bad = """
        def gls_solve(relres):
            return relres_failed(relres)
    """
    # outside the registered modules: quiet
    fs = lint(tmp_path, {"other.py": bad}, QUALITY_CFG)
    assert live(fs, "quality-signal-dropped") == []
    suppressed = """
        def gls_solve(relres):
            # probe diagnostic, recorded by the caller
            # pintlint: disable=quality-signal-dropped
            return relres_failed(relres)
    """
    fs = lint(tmp_path, {"fitter.py": suppressed}, QUALITY_CFG)
    assert live(fs, "quality-signal-dropped") == []
    assert any(f.rule == "quality-signal-dropped" and f.suppressed
               for f in fs)


# -- request-state-leak ----------------------------------------------


RSL_CFG = LintConfig(serve_state_modules=("/engine.py",))


def test_request_state_leak_flags_unrecorded_status(tmp_path):
    bad = """
        def shed(res):
            res.status = "shed"
            res.reason = "queue_full"
            return res
    """
    fs = lint(tmp_path, {"engine.py": bad}, RSL_CFG)
    assert len(live(fs, "request-state-leak")) == 1


def test_request_state_leak_quiet_when_recorded(tmp_path):
    good = """
        class Engine:
            def shed_one(self, req, res):
                res.status = "shed"
                self.telemetry.incr("shed_queue_full")
                return res

            def error_one(self, req, res):
                res.status = "error"
                self._lc(req, "error")
                return res
    """
    fs = lint(tmp_path, {"engine.py": good}, RSL_CFG)
    assert live(fs, "request-state-leak") == []


def test_request_state_leak_ignores_self_and_scope(tmp_path):
    quiet = """
        class Engine:
            def note(self):
                self.status = "healthy"
    """
    # self.* is engine state, not a request outcome
    fs = lint(tmp_path, {"engine.py": quiet}, RSL_CFG)
    assert live(fs, "request-state-leak") == []
    bad = """
        def shed(res):
            res.status = "shed"
    """
    # outside the registered modules: quiet
    fs = lint(tmp_path, {"other.py": bad}, RSL_CFG)
    assert live(fs, "request-state-leak") == []


def test_request_state_leak_suppressible(tmp_path):
    suppressed = """
        def touch(res):
            # outcome recorded by the caller
            # pintlint: disable=request-state-leak
            res.reason = None
    """
    fs = lint(tmp_path, {"engine.py": suppressed}, RSL_CFG)
    assert live(fs, "request-state-leak") == []
    assert any(f.rule == "request-state-leak" and f.suppressed
               for f in fs)


# -- durable-write-unatomic ------------------------------------------


DURABLE_CFG = LintConfig(
    durable_artifact_modules=("/checkpoint.py", "/serve/journal.py"))


def test_durable_write_flags_truncating_open(tmp_path):
    bad = """
        import json

        def save_meta(path, meta):
            with open(path, "w") as fh:   # tears on a crash mid-write
                json.dump(meta, fh)
    """
    fs = lint(tmp_path, {"checkpoint.py": bad}, DURABLE_CFG)
    assert len(live(fs, "durable-write-unatomic")) == 1


def test_durable_write_flags_mode_kwarg_and_exclusive(tmp_path):
    bad = """
        def a(path):
            return open(path, mode="wb")

        def b(path):
            return open(path, "x")
    """
    # anchor.py keeps the lint root at tmp_path so the registered
    # "/serve/journal.py" suffix sees its directory
    fs = lint(tmp_path, {"serve/journal.py": bad, "anchor.py": "x = 1\n"},
              DURABLE_CFG)
    assert len(live(fs, "durable-write-unatomic")) == 2


def test_durable_write_quiet_on_reads_appends_and_patches(tmp_path):
    good = """
        def scan(path):
            with open(path, "rb") as fh:
                return fh.read()

        def append_frame(path, frame):
            # append-only log: the CRC framing is its torn-write
            # protocol, so "ab" is the legal durable mode
            with open(path, "ab") as fh:
                fh.write(frame)

        def damage(path):
            # the fault injectors' in-place byte-flipper
            with open(path, "r+b") as fh:
                fh.write(b"x")
    """
    fs = lint(tmp_path, {"serve/journal.py": good, "anchor.py": "x = 1\n"},
              DURABLE_CFG)
    assert live(fs, "durable-write-unatomic") == []


def test_durable_write_flags_pack_store(tmp_path):
    # the packed-TOA store is registered in the REAL durable registry
    # (not just a fixture one): a truncating write there would tear a
    # multi-hundred-MB column file on crash
    from pint_tpu.analysis.config import DURABLE_ARTIFACT_MODULES

    assert "/store/packstore.py" in DURABLE_ARTIFACT_MODULES
    bad = """
        def save_entry(path, blob):
            with open(path, "wb") as fh:   # tears on a crash mid-write
                fh.write(blob)
    """
    fs = lint(tmp_path, {"store/packstore.py": bad,
                         "anchor.py": "x = 1\n"},
              LintConfig(
                  durable_artifact_modules=DURABLE_ARTIFACT_MODULES))
    assert len(live(fs, "durable-write-unatomic")) == 1


def test_durable_write_scoped_to_registered_modules(tmp_path):
    src = """
        def export(path, text):
            with open(path, "w") as fh:
                fh.write(text)
    """
    # the same truncating open outside the durable registry is legal
    fs = lint(tmp_path, {"report.py": src}, DURABLE_CFG)
    assert live(fs, "durable-write-unatomic") == []


def test_durable_write_suppressible(tmp_path):
    src = """
        def debug_dump(path, text):
            # throwaway debug artifact, loss is fine
            # pintlint: disable=durable-write-unatomic
            with open(path, "w") as fh:
                fh.write(text)
    """
    fs = lint(tmp_path, {"checkpoint.py": src}, DURABLE_CFG)
    assert live(fs, "durable-write-unatomic") == []
    assert any(f.rule == "durable-write-unatomic" and f.suppressed
               for f in fs)


# -- kernel-silent-fallback ------------------------------------------


KERNEL_CFG = LintConfig(kernel_dispatch_modules=("/kernels/",))


def test_kernel_silent_fallback_flags_bare_pass(tmp_path):
    # the seed fixture: the except/pass that shipped in
    # kernels/seggram.py's dispatcher — one mosaic quirk away from an
    # invisible fleet-wide jnp slowdown
    bad = """
        def segment_gram(x, seg, n_seg, block, precision="f64"):
            if precision == "mixed":
                try:
                    return segment_gram_pallas(x, seg, n_seg, block)
                except Exception:
                    pass
            return segment_gram_jnp(x, seg, n_seg, block)
    """
    fs = lint(tmp_path, {"kernels/seggram.py": bad,
                         "anchor.py": "x = 1\n"}, KERNEL_CFG)
    assert len(live(fs, "kernel-silent-fallback")) == 1


def test_kernel_silent_fallback_flags_silent_return(tmp_path):
    # swallowing into a direct fallback return is just as invisible
    # as pass
    bad = """
        def harmonic_sums(ph, m):
            try:
                return harmonic_sums_pallas(ph, m)
            except Exception:
                return harmonic_sums_jnp(ph, m)
    """
    fs = lint(tmp_path, {"kernels/harmonics.py": bad,
                         "anchor.py": "x = 1\n"}, KERNEL_CFG)
    assert len(live(fs, "kernel-silent-fallback")) == 1


def test_kernel_silent_fallback_quiet_on_noted_or_reraised(tmp_path):
    good = """
        from .fallback import note_pallas_fallback

        def segment_gram(x, seg, n_seg, block, precision="f64"):
            if precision == "mixed":
                try:
                    return segment_gram_pallas(x, seg, n_seg, block)
                except Exception as exc:
                    note_pallas_fallback("seggram.segment_gram", exc)
            return segment_gram_jnp(x, seg, n_seg, block)

        def strict(x, seg, n_seg, block):
            try:
                return segment_gram_pallas(x, seg, n_seg, block)
            except Exception:
                raise
    """
    fs = lint(tmp_path, {"kernels/seggram.py": good,
                         "anchor.py": "x = 1\n"}, KERNEL_CFG)
    assert live(fs, "kernel-silent-fallback") == []


def test_kernel_silent_fallback_scoped_to_kernel_modules(tmp_path):
    # non-Pallas try bodies in kernels/ (the _tpu_backend device
    # probe) and Pallas swallows OUTSIDE kernels/ are both legal
    src_probe = """
        def _tpu_backend():
            import jax
            try:
                return jax.devices()[0].platform == "tpu"
            except Exception:
                return False
    """
    src_outside = """
        def helper(x):
            try:
                return run_pallas(x)
            except Exception:
                pass
    """
    fs = lint(tmp_path, {"kernels/seggram.py": src_probe,
                         "other.py": src_outside}, KERNEL_CFG)
    assert live(fs, "kernel-silent-fallback") == []


def test_kernel_silent_fallback_suppressible(tmp_path):
    src = """
        def probe(x):
            try:
                return run_pallas_probe(x)
            # a capability probe: failure IS the answer, not a
            # degradation worth counting
            # pintlint: disable=kernel-silent-fallback
            except Exception:
                return None
    """
    fs = lint(tmp_path, {"kernels/probe.py": src,
                         "anchor.py": "x = 1\n"}, KERNEL_CFG)
    assert live(fs, "kernel-silent-fallback") == []
    assert any(f.rule == "kernel-silent-fallback" and f.suppressed
               for f in fs)


# -- lock-order-cycle (whole-program) --------------------------------


def test_lock_order_cycle_two_lock_inversion(tmp_path):
    src = """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def ab():
            with A:
                with B:
                    pass

        def ba():
            with B:
                with A:
                    pass
    """
    fs = lint(tmp_path, {"m.py": src}, LintConfig())
    hits = live(fs, "lock-order-cycle")
    assert len(hits) == 1
    assert "m.A" in hits[0].message and "m.B" in hits[0].message


def test_lock_order_cycle_three_locks_call_mediated(tmp_path):
    # the cycle spans two modules and only exists through the call
    # graph: no single function acquires locks in a bad order
    one = """
        import threading

        from two import mid

        A = threading.Lock()

        def start():
            with A:
                mid()

        def use_a():
            with A:
                pass
    """
    two = """
        import threading

        from one import use_a

        B = threading.Lock()
        C = threading.Lock()

        def mid():
            with B:
                tail()

        def tail():
            with C:
                use_a()
    """
    fs = lint(tmp_path, {"one.py": one, "two.py": two}, LintConfig())
    hits = live(fs, "lock-order-cycle")
    assert len(hits) >= 1
    assert "one.A" in hits[0].message


def test_lock_order_cycle_locked_helper_mediated(tmp_path):
    # B.sync_locked holds B._lock by convention (no with-block at all)
    # and calls into A, which calls back into B: a deadlock only the
    # *_locked implicit-hold modeling can see
    src = """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.buddy = B()

            def poke(self):
                with self._lock:
                    self.buddy.grab()

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self.peer = A()

            def grab(self):
                with self._lock:
                    pass

            def sync_locked(self):
                self.peer.poke()
    """
    fs = lint(tmp_path, {"pair.py": src}, LintConfig())
    hits = live(fs, "lock-order-cycle")
    assert len(hits) == 1
    assert "A._lock" in hits[0].message
    assert "B._lock" in hits[0].message
    assert "*_locked convention" in hits[0].message


def test_lock_order_quiet_and_dag_artifact(tmp_path):
    from pint_tpu.analysis.rules_lockorder import lock_order_graph

    src = """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def f1():
            with A:
                with B:
                    pass

        def f2():
            with A, B:
                pass
    """
    p = tmp_path / "m.py"
    p.write_text(textwrap.dedent(src))
    fs = run([str(p)], config=LintConfig())
    assert live(fs, "lock-order-cycle") == []
    dag = lock_order_graph([str(p)], config=LintConfig())
    assert set(dag["nodes"]) == {"m.A", "m.B"}
    edges = {(e["held"], e["acquired"]) for e in dag["edges"]}
    assert edges == {("m.A", "m.B")}
    witness = dag["edges"][0]["witness"]
    assert any("m.py" in step for step in witness)


# -- precision-flow (whole-program) ----------------------------------


FLOW_CFG = LintConfig(f64_critical={"crit.py": {"gls_whiten"}},
                      f32_source_patterns=(r"_pallas$",))

FLOW_KERN = """
    def whiten_pallas(x):
        return x * 2
"""

FLOW_CRIT = """
    def gls_whiten(r, w):
        return r - w
"""


def test_precision_flow_cross_module_chain(tmp_path):
    mid = """
        from kern import whiten_pallas

        def prep(x):
            y = whiten_pallas(x)
            return y
    """
    drive = """
        from crit import gls_whiten
        from mid import prep

        def solve(r):
            w = prep(r)
            return gls_whiten(r, w)
    """
    fs = lint(tmp_path, {"kern.py": FLOW_KERN, "mid.py": mid,
                         "crit.py": FLOW_CRIT, "drive.py": drive},
              FLOW_CFG)
    hits = live(fs, "precision-flow")
    assert len(hits) == 1
    assert hits[0].path == "drive.py"
    # the finding names the full source -> sink chain
    assert "whiten_pallas" in hits[0].message
    assert "gls_whiten" in hits[0].message
    assert "mid.py" in hits[0].message


def test_precision_flow_quiet_when_sanitized_midway(tmp_path):
    mid = """
        import jax.numpy as jnp

        from kern import whiten_pallas

        def prep(x):
            y = whiten_pallas(x)
            return (y * 2).astype(jnp.float64)
    """
    drive = """
        from crit import gls_whiten
        from mid import prep

        def solve(r):
            w = prep(r)
            return gls_whiten(r, w)
    """
    fs = lint(tmp_path, {"kern.py": FLOW_KERN, "mid.py": mid,
                         "crit.py": FLOW_CRIT, "drive.py": drive},
              FLOW_CFG)
    assert live(fs, "precision-flow") == []


def test_precision_flow_astype_f32_source_and_suppression(tmp_path):
    drive = """
        import jax.numpy as jnp

        from crit import gls_whiten

        def solve(r):
            w = r.astype(jnp.float32)
            return gls_whiten(r, w)  # pintlint: disable=precision-flow
    """
    fs = lint(tmp_path, {"crit.py": FLOW_CRIT, "drive.py": drive},
              FLOW_CFG)
    assert live(fs, "precision-flow") == []
    assert any(f.rule == "precision-flow" and f.suppressed for f in fs)


# -- signature-incomplete (whole-program) ----------------------------


SIG_CFG = LintConfig(signature_classes={
    "Batch": {"signature": "shape_signature", "exempt": set()}})


def test_signature_incomplete_flags_uncovered_traced_read(tmp_path):
    src = """
        import jax

        class Batch:
            def __init__(self, x, scale):
                self.x = x
                self.scale = scale
                self.extra = scale
                self._fns = {}

            def shape_signature(self):
                return (self.x.shape,)

            def compile(self, key):
                def run(v):
                    return v * self.scale + self.x
                self._fns[key] = jax.jit(run)

            def dispatch(self, key, v):
                return self._fns[key](v, self.extra)
    """
    fs = lint(tmp_path, {"m.py": src}, SIG_CFG)
    hits = live(fs, "signature-incomplete")
    msgs = " | ".join(h.message for h in hits)
    assert len(hits) == 2
    assert "self.scale" in msgs          # traced closure read
    assert "self.extra" in msgs          # runtime dispatch argument
    assert "self.x" not in msgs          # folded into the signature


def test_signature_incomplete_quiet_when_covered_or_exempt(tmp_path):
    src = """
        import jax

        class Batch:
            def __init__(self, x, scale, label):
                self.x = x
                self.scale = scale
                self.label = label
                self._fns = {}

            def shape_signature(self):
                return (self.x.shape, self.scale)

            def compile(self, key):
                def run(v):
                    return v * self.scale + self.x
                self._fns[key] = jax.jit(run)
    """
    fs = lint(tmp_path, {"m.py": src}, SIG_CFG)
    assert live(fs, "signature-incomplete") == []

    # host-only metadata goes in the exempt set, not the signature
    src_label = src.replace("return v * self.scale + self.x",
                            "return v * self.scale + len(self.label)")
    cfg = LintConfig(signature_classes={
        "Batch": {"signature": "shape_signature",
                  "exempt": {"label"}}})
    fs = lint(tmp_path, {"n.py": src_label}, cfg)
    assert live(fs, "signature-incomplete") == []


def test_signature_incomplete_missing_signature_method(tmp_path):
    src = """
        class Batch:
            def __init__(self):
                self._fns = {}
    """
    fs = lint(tmp_path, {"m.py": src}, SIG_CFG)
    hits = live(fs, "signature-incomplete")
    assert len(hits) == 1
    assert "does not define" in hits[0].message


# -- registry-drift (whole-program) ----------------------------------


def test_registry_drift_flags_unregistered_lock_owner(tmp_path):
    cfg = LintConfig(locked_classes={
        "Known": {"lock": "_lock", "attrs": None}})
    src = """
        import threading

        class Known:
            def __init__(self):
                self._lock = threading.Lock()

        class Rogue:
            def __init__(self):
                self._lock = threading.RLock()
                self.hits = 0
    """
    fs = lint(tmp_path, {"m.py": src}, cfg)
    hits = live(fs, "registry-drift")
    assert len(hits) == 1
    assert "Rogue" in hits[0].message
    assert "LOCKED_CLASSES" in hits[0].message


def test_registry_drift_inert_on_empty_registry(tmp_path):
    # fixture configs with no LOCKED_CLASSES must not fire: an empty
    # registry means "not using the lock rules", not "nothing is
    # registered yet"
    src = """
        import threading

        class Rogue:
            def __init__(self):
                self._lock = threading.Lock()
    """
    fs = lint(tmp_path, {"m.py": src}, LintConfig())
    assert live(fs, "registry-drift") == []


def test_registry_drift_flags_stale_entries(tmp_path):
    cfg = LintConfig(
        locked_classes={"Ghost": {"lock": "_lock", "attrs": None}},
        serve_state_modules=("serve/engine.py",),
        registry_anchor_suffix="reg.py")
    fs = lint(tmp_path, {"reg.py": "X = 1\n",
                         "m.py": "class NotGhost:\n    pass\n"}, cfg)
    hits = live(fs, "registry-drift")
    msgs = " | ".join(h.message for h in hits)
    assert len(hits) == 2
    assert "serve/engine.py" in msgs
    assert "Ghost" in msgs
    assert all(h.path == "reg.py" and h.line == 1 for h in hits)


def test_registry_drift_stale_check_needs_anchor_in_scan(tmp_path):
    # linting one file must not claim the whole registry is stale
    cfg = LintConfig(
        serve_state_modules=("serve/engine.py",),
        registry_anchor_suffix="reg.py")
    fs = lint(tmp_path, {"m.py": "class C:\n    pass\n"}, cfg)
    assert live(fs, "registry-drift") == []


# -- suppression grammar ---------------------------------------------


def test_suppression_inline_next_line_and_file(tmp_path):
    src = """
        def a(relres):
            return relres > 1e-8  # pintlint: disable=nan-guard

        def b(relres):
            # non-finite handled by the caller's isfinite gate
            # pintlint: disable=nan-guard
            return relres > 1e-8

        def c(relres):
            return relres > 1e-8
    """
    fs = lint(tmp_path, {"m.py": src}, NAN_CFG)
    assert len(fs) == 3  # all three still REPORTED...
    assert len(live(fs, "nan-guard")) == 1  # ...but only c counts
    assert [f.suppressed for f in fs] == [True, True, False]

    filewide = "# pintlint: disable-file=nan-guard\n" + textwrap.dedent(src)
    p = tmp_path / "fw.py"
    p.write_text(filewide)
    fs2 = run([str(p)], config=NAN_CFG)
    assert len(fs2) == 3 and unsuppressed(fs2) == []


def test_suppression_all_wildcard(tmp_path):
    src = """
        def a(relres):
            return relres > 1e-8  # pintlint: disable=all
    """
    fs = lint(tmp_path, {"m.py": src}, NAN_CFG)
    assert len(fs) == 1 and unsuppressed(fs) == []


# -- reporters + CLI -------------------------------------------------


def test_reports_text_and_json(tmp_path):
    src = """
        def a(relres):
            return relres > 1e-8
    """
    fs = lint(tmp_path, {"m.py": src}, NAN_CFG)
    txt = text_report(fs)
    assert "[nan-guard]" in txt and "1 finding(s)" in txt
    payload = json.loads(json_report(fs))
    assert payload["unsuppressed"] == 1
    assert payload["counts_by_rule"] == {"nan-guard": 1}
    assert payload["findings"][0]["rule"] == "nan-guard"


def test_cli_exit_codes_and_list_rules(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(relres):\n    return relres > 1e-8\n")
    good = tmp_path / "good.py"
    good.write_text("def f(x):\n    return x\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    r = subprocess.run(
        [sys.executable, "-m", "pint_tpu.analysis", str(bad),
         "--format", "json"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 1, r.stderr
    assert json.loads(r.stdout)["unsuppressed"] == 1

    r = subprocess.run(
        [sys.executable, "-m", "pint_tpu.analysis", str(good)],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr

    r = subprocess.run(
        [sys.executable, "-m", "pint_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0
    for rule_id in ("nan-guard", "lock-discipline", "timing-no-block",
                    "fault-point-unknown", "serve-unpadded-batch"):
        assert rule_id in r.stdout


def test_cli_changed_mode(tmp_path):
    """--changed lints only the git diff (staged set with --cached),
    skips the whole-program pass, and exits 0 on a clean tree."""
    repo = tmp_path / "wt"
    repo.mkdir()
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")

    def git(*argv):
        r = subprocess.run(["git"] + list(argv), cwd=repo, env=env,
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        return r

    git("init", "-q")
    (repo / "ok.py").write_text("def f(x):\n    return x\n")
    git("add", "ok.py")
    git("commit", "-q", "-m", "seed")

    # clean tree: nothing to lint, exit 0
    r = subprocess.run(
        [sys.executable, "-m", "pint_tpu.analysis", "--changed"],
        capture_output=True, text=True, cwd=repo, env=env)
    assert r.returncode == 0, r.stderr
    assert "no changed python files" in r.stdout

    # a staged file with a finding: --changed --cached flags it
    (repo / "bad.py").write_text(
        "def f(relres):\n    return relres > 1e-8\n")
    git("add", "bad.py")
    r = subprocess.run(
        [sys.executable, "-m", "pint_tpu.analysis", "--changed",
         "--cached"],
        capture_output=True, text=True, cwd=repo, env=env)
    assert r.returncode == 1, r.stderr
    assert "nan-guard" in r.stdout

    # explicit paths and --changed are exclusive
    r = subprocess.run(
        [sys.executable, "-m", "pint_tpu.analysis", "--changed",
         str(repo / "bad.py")],
        capture_output=True, text=True, cwd=repo, env=env)
    assert r.returncode == 2


def test_cli_lock_dag_artifact(tmp_path):
    # a tiny two-lock fixture keeps this a plumbing test (flag -> JSON
    # artifact); the real tree's DAG is gated by
    # test_tree_lock_dag_acyclic_with_expected_edges off the shared scan
    src = tmp_path / "src"
    src.mkdir()
    (src / "m.py").write_text(textwrap.dedent("""\
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def f():
            with A:
                with B:
                    pass
    """))
    out = tmp_path / "dag.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "pint_tpu.analysis", str(src),
         "--lock-dag", str(out)],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    dag = json.loads(out.read_text())
    assert dag["nodes"] and dag["edges"]
    assert all(set(e) == {"held", "acquired", "witness"}
               for e in dag["edges"])
    assert {"m.A", "m.B"} <= set(dag["nodes"])


# -- the CI gate -----------------------------------------------------

# The whole-program pass over the real package costs ~15-25 s; the
# tree gates below all interrogate the SAME scan (findings, index,
# lock graph), so it runs once per pytest session, not once per gate.
_TREE_SCAN = None


def tree_scan():
    global _TREE_SCAN
    if _TREE_SCAN is None:
        from pint_tpu.analysis.core import run_project
        _TREE_SCAN = run_project([PKG], config=LintConfig.default())
    return _TREE_SCAN


def test_tree_has_zero_unsuppressed_findings():
    """The acceptance criterion: pintlint over the whole package is
    clean. Any new finding must be fixed or carry a justified
    suppression comment — this test is the enforcement point."""
    findings, _ = tree_scan()
    bad = unsuppressed(findings)
    assert bad == [], text_report(findings)


def test_tree_device_faults_are_armed_by_tests():
    """Every device-level fault point in the live registry must be
    armed by at least one test: the quarantine / work-steal / resume
    recovery ladder only exists if CI can actually trigger it. Scans
    package + tests filtered to the one rule — the broader tests tree
    is not held to the package's zero-findings bar."""
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    # fault-point coverage is a per-file rule: skip the whole-program
    # pass, which would re-index package + tests for nothing
    findings = run([PKG, tests_dir], config=LintConfig.default(),
                   whole_program=False)
    bad = [f for f in unsuppressed(findings)
           if f.rule == "fault-point-untested"]
    assert bad == [], text_report(findings)


def test_tree_suppressions_stay_bounded():
    """Suppressions are reviewed exceptions, not an escape hatch: the
    per-rule counts are pinned exactly so silently suppressing a new
    finding fails here and forces a review of this test."""
    findings, _ = tree_scan()
    by_rule = {}
    for f in findings:
        if f.suppressed:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    # 1 serve-unpadded-batch (canonical pad-compute site) + 2 seeded
    # quality-signal-dropped (precision-auto probe diagnostic, sharded
    # single-pulsar path) — each carries its justification in place
    assert by_rule == {"quality-signal-dropped": 2,
                       "serve-unpadded-batch": 1}, \
        text_report(findings, show_suppressed=True)


def test_tree_index_builds_cleanly():
    """The whole-program pass must index every module in the package:
    a parse failure or an unindexed file silently shrinks whole-program
    coverage."""
    from pint_tpu.analysis.core import iter_py_files

    findings, project = tree_scan()
    idx = project.index
    assert idx is not None
    n_files = len(list(iter_py_files([PKG])))
    assert len(idx.modules) == n_files
    assert not project.extra_findings, project.extra_findings
    # every module contributed functions or classes to the symbol table
    # unless genuinely empty
    assert idx.functions and idx.classes


def test_tree_lock_dag_acyclic_with_expected_edges():
    """The static acquired-while-held graph over the real tree: a DAG
    (no deadlock), containing the edges the serve path is known to
    take. Losing an expected edge means the analyzer's call/type
    resolution regressed — the graph silently thinned out."""
    from lockcheck import find_cycle

    _, project = tree_scan()
    dag = project.lock_graph.as_dict()
    edges = {(e["held"], e["acquired"]) for e in dag["edges"]}
    assert find_cycle(edges) is None
    expected = {
        # flusher work under the work mutex takes component locks
        ("AsyncServeEngine._work_mutex", "IntakeQueue._lock"),
        ("AsyncServeEngine._work_mutex", "ServeTelemetry._lock"),
        # telemetry record() updates per-phase histograms under its lock
        ("ServeTelemetry._lock", "Histogram._lock"),
        # the memory tier consults the persistent tier while held
        ("ExecutableCache._lock", "PersistentExecutableCache._lock"),
    }
    assert expected <= edges, sorted(edges)
