"""Event/photon pipeline tests: FITS I/O, event TOA loading, satellite
observatories, H-test detection of an injected pulsation.

(reference test patterns: tests/test_event_toas.py, tests/test_fermi.py,
tests/test_satobs.py — there against small bundled mission FITS files;
here against synthetic files written by pint_tpu.io.fits itself.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.io.fits import write_fits_table, read_fits, get_table
from pint_tpu.event_toas import (load_event_TOAs, load_NICER_TOAs,
                                 load_Fermi_TOAs, get_event_weights,
                                 met_to_day_sec, MISSION_MJDREF)
from pint_tpu.models import get_model

PAR = """
PSR TESTEV
RAJ 10:00:00.0
DECJ 15:00:00.0
F0 29.946923 1
F1 -3.77e-10 1
PEPOCH 56700
DM 0.0
"""


def test_fits_table_roundtrip(tmp_path):
    path = tmp_path / "t.fits"
    cols = {"TIME": np.linspace(0.0, 1e5, 50),
            "PHA": np.arange(50, dtype=np.int32),
            "POSITION": np.arange(150, dtype=float).reshape(50, 3)}
    write_fits_table(path, cols, {"MJDREFI": 56658, "MJDREFF": 7.77e-4,
                                  "TIMESYS": "TT"}, extname="EVENTS")
    header, data = get_table(path, "EVENTS")
    assert header["MJDREFI"] == 56658
    assert header["TIMESYS"] == "TT"
    np.testing.assert_allclose(data["TIME"], cols["TIME"])
    np.testing.assert_array_equal(data["PHA"], cols["PHA"])
    np.testing.assert_allclose(data["POSITION"], cols["POSITION"])
    # multiple HDUs parse
    hdus = read_fits(path)
    assert hdus[0]["data"] is None and hdus[1]["name"] == "EVENTS"


def test_met_to_day_sec_precision():
    mjdref = MISSION_MJDREF["nicer"]
    met = np.array([1e8 + 0.123456789])
    day, sec = met_to_day_sec(met, mjdref)
    total = np.longdouble(day[0]) + np.longdouble(sec[0]) / 86400
    expected = np.longdouble(mjdref) + np.longdouble(met[0]) / 86400
    assert abs(float((total - expected) * 86400)) < 1e-6  # < 1 us


def _write_events(path, mjds_tdb, timesys="TDB", mission_ref=56658.000777592593,
                  weights=None):
    met = (np.asarray(mjds_tdb, np.longdouble) - mission_ref) * 86400.0
    cols = {"TIME": np.asarray(met, np.float64)}
    if weights is not None:
        cols["PSRPROB"] = np.asarray(weights, float)
    write_fits_table(path, cols,
                     {"MJDREFI": int(mission_ref),
                      "MJDREFF": mission_ref - int(mission_ref),
                      "TIMESYS": timesys, "TELESCOP": "NICER"},
                     extname="EVENTS")


def test_htest_detects_injected_pulsation(tmp_path):
    """Photon phases folded with the true model must give a huge
    H-test; scrambled photons must not (the photonphase workflow,
    reference: scripts/photonphase.py + eventstats)."""
    from pint_tpu.eventstats import hm, sf_hm

    m = get_model(PAR)
    f0 = m.F0.value
    rng = np.random.default_rng(5)
    n_ph = 3000
    pulse_n = rng.integers(0, int(0.5 * 86400 * f0), n_ph)
    phases = (rng.vonmises(2 * np.pi * 0.3, 8.0, n_ph) / (2 * np.pi)) % 1.0
    # invert the (F0, F1) Taylor phase to TDB times (barycentered events)
    dt = (pulse_n + phases) / f0
    f1 = m.F1.value
    dt = dt - 0.5 * f1 * dt**2 / f0  # first-order F1 correction
    mjds = 56700.0 + np.asarray(dt, np.longdouble) / 86400.0
    path = tmp_path / "evt.fits"
    _write_events(path, mjds, timesys="TDB")
    toas = load_event_TOAs(path, "nicer")
    assert len(toas) == n_ph
    assert set(toas.obs.astype(str)) == {"barycenter"}
    ph = np.asarray(m.phase(toas).frac) % 1.0
    h = float(hm(ph))
    h_scrambled = float(hm(rng.uniform(0, 1, n_ph)))
    assert h > 500.0, h
    assert h_scrambled < 50.0
    assert sf_hm(h, logprob=True) < -100
    # weighted loader path
    _write_events(tmp_path / "evtw.fits", mjds, timesys="TDB",
                  weights=np.full(n_ph, 0.7))
    tw = load_Fermi_TOAs(tmp_path / "evtw.fits", weightcolumn="PSRPROB")
    w = get_event_weights(tw)
    assert w is not None and np.allclose(w, 0.7)


def test_satellite_observatory(tmp_path):
    """Orbit-file observatory: interpolated posvel must track the
    analytic orbit, and TT-native TOAs must convert to TDB."""
    from pint_tpu.observatory.satellite_obs import get_satellite_observatory
    from pint_tpu.ephemeris import objPosVel_wrt_SSB
    from pint_tpu.mjd import Epochs
    from pint_tpu.timescales import tt_to_tdb

    mjdref = MISSION_MJDREF["nicer"]
    r_orb, period = 6.98e6, 5700.0
    met_grid = np.arange(0.5 * 86400, 1.5 * 86400, 30.0) + (56700 - mjdref) * 86400
    wt = 2 * np.pi / period

    def orbit(met):
        ang = wt * met
        pos = np.stack([r_orb * np.cos(ang), r_orb * np.sin(ang),
                        np.zeros_like(ang)], axis=-1)
        vel = np.stack([-r_orb * wt * np.sin(ang), r_orb * wt * np.cos(ang),
                        np.zeros_like(ang)], axis=-1)
        return pos, vel

    pos, vel = orbit(met_grid)
    orb_path = tmp_path / "orb.fits"
    write_fits_table(orb_path, {"TIME": met_grid, "POSITION": pos,
                                "VELOCITY": vel},
                     {"MJDREFI": int(mjdref), "MJDREFF": mjdref - int(mjdref),
                      "TIMESYS": "TT"}, extname="ORBIT")
    ob = get_satellite_observatory("nicer", orb_path)
    assert ob.timescale == "tt"
    # off-grid sample points, compare to analytic orbit
    met_q = met_grid[0] + np.array([100.3, 1234.56, 40000.77])
    tt_day = np.full(3, 56700, np.int64)
    tt_sec = met_q - (56700 - mjdref) * 86400
    tt = Epochs(tt_day, tt_sec, "tt").normalized()
    tdb = tt_to_tdb(tt)
    pv = ob.posvel_ssb(tdb, None, "de440s")
    earth = objPosVel_wrt_SSB("earth", tdb, "de440s")
    p_ana, v_ana = orbit(met_q)
    assert np.abs(pv.pos - earth.pos - p_ana).max() < 1.0  # < 1 m
    assert np.abs(pv.vel - earth.vel - v_ana).max() < 1e-2  # < 1 cm/s
    # event TOAs tagged with the satellite obs flow through TDB+posvel
    mjds_tt = mjdref + met_q / 86400.0
    evt = tmp_path / "evt_tt.fits"
    _write_events(evt, mjds_tt, timesys="TT", mission_ref=mjdref)
    toas = load_event_TOAs(evt, "nicer")
    toas.apply_clock_corrections()
    toas.compute_TDBs()
    toas.compute_posvels()
    assert np.abs(np.asarray(toas.ssb_obs.pos) - pv.pos).max() < 1.0


def test_fermi_calc_weights(tmp_path):
    """weightcolumn='CALC': heuristic PSF weights computed from the
    FT1 RA/DEC/ENERGY columns and the target position (reference
    convention: fermi_toas.py::calc_lat_weights). On-source high-energy
    photons weigh ~1, off-source or soft photons are suppressed."""
    from pint_tpu.event_toas import calc_lat_weights, load_Fermi_TOAs

    path = tmp_path / "ft1.fits"
    n = 40
    rng = np.random.default_rng(5)
    met = np.sort(rng.uniform(0, 1e5, n))
    ra0, dec0 = 150.0, 15.0
    # half the photons on-source, half offset by 0.5-3 deg
    off = np.where(np.arange(n) % 2, 0.0, rng.uniform(0.5, 3.0, n))
    ra = ra0 + off / np.cos(np.radians(dec0))
    dec = np.full(n, dec0)
    energy = np.where(np.arange(n) % 4 < 2, 10000.0, 150.0)  # MeV
    write_fits_table(path, {"TIME": met, "RA": ra, "DEC": dec,
                            "ENERGY": energy},
                     {"MJDREFI": 51910, "MJDREFF": 7.428703703703703e-4,
                      "TIMESYS": "TT", "TELESCOP": "GLAST"},
                     extname="EVENTS")
    t = load_Fermi_TOAs(str(path), weightcolumn="CALC",
                        targetcoord=(ra0, dec0))
    w = np.asarray(t.weights)
    assert w.shape == (n,)
    assert np.all((w >= 0) & (w <= 1))
    on_hard = w[(off == 0) & (energy > 1000)]
    off_soft = w[(off > 0) & (energy < 1000)]
    assert on_hard.min() > 0.5
    assert off_soft.max() < 0.1
    assert on_hard.min() > 10 * off_soft.max()
    # matches the exported formula directly
    from pint_tpu.event_toas import _angsep_deg

    w_direct = calc_lat_weights(energy, _angsep_deg(ra0, dec0, ra, dec))
    np.testing.assert_allclose(w, w_direct, rtol=1e-12)
    # CALC without a target position is a clear error
    with pytest.raises(ValueError, match="targetcoord"):
        load_Fermi_TOAs(str(path), weightcolumn="CALC")


# ---------------------------------------------------------------------------
# r4 edge cases (upstream analogs: tests/test_event_toas.py pathologies,
# tests/test_satobs.py span/unit handling, io robustness)
# ---------------------------------------------------------------------------

def test_event_minmjd_maxmjd_window(tmp_path):
    mjds = 56700.0 + np.linspace(0, 10, 50)
    p = tmp_path / "evt.fits"
    _write_events(p, mjds, timesys="TDB")
    t = load_event_TOAs(p, "nicer", minmjd=56702.0, maxmjd=56705.0)
    f = t.day + t.sec / 86400.0
    assert len(t) and (f >= 56702.0 - 1e-9).all() and (f <= 56705.0 + 1e-9).all()


def test_event_weightcolumn_flags(tmp_path):
    mjds = 56700.0 + np.linspace(0, 1, 20)
    w = np.linspace(0.1, 0.9, 20)
    p = tmp_path / "evtw.fits"
    _write_events(p, mjds, timesys="TDB", weights=w)
    t = load_event_TOAs(p, "nicer", weightcolumn="PSRPROB")
    got = get_event_weights(t)
    np.testing.assert_allclose(got, w, rtol=1e-7)


def test_event_tdb_native_goes_barycentric(tmp_path):
    # TIMESYS TDB photons are barycentric: obs must be barycenter and
    # the posvel chain must produce ZERO observatory offset
    mjds = 56700.0 + np.linspace(0, 1, 5)
    p = tmp_path / "evtb.fits"
    _write_events(p, mjds, timesys="TDB")
    t = load_event_TOAs(p, "nicer")
    assert set(t.obs.astype(str)) == {"barycenter"}
    t.apply_clock_corrections()
    t.compute_TDBs()
    t.compute_posvels()
    assert np.abs(np.asarray(t.ssb_obs.pos)).max() == 0.0


def test_fits_reader_rejects_non_fits(tmp_path):
    p = tmp_path / "not.fits"
    p.write_bytes(b"definitely not a FITS file" * 100)
    with pytest.raises((ValueError, KeyError, OSError)):
        read_fits(str(p))


def test_fits_reader_truncated_file(tmp_path):
    # write a valid file then truncate mid-data: must raise, not hang
    # or return garbage silently
    mjds = 56700.0 + np.linspace(0, 1, 100)
    p = tmp_path / "trunc.fits"
    _write_events(p, mjds, timesys="TDB")
    data = p.read_bytes()
    p.write_bytes(data[:len(data) // 2])
    with pytest.raises((ValueError, KeyError, OSError, EOFError)):
        get_table(str(p), "EVENTS")


def test_get_table_missing_extension(tmp_path):
    mjds = 56700.0 + np.linspace(0, 1, 5)
    p = tmp_path / "evt.fits"
    _write_events(p, mjds, timesys="TDB")
    with pytest.raises(KeyError):
        get_table(str(p), "NOPE")


def test_satellite_km_unit_orbit_normalized(tmp_path):
    # FPorbit-style tables in km must be converted to m (radius check)
    from pint_tpu.io.fits import write_fits_table
    from pint_tpu.observatory.satellite_obs import SatelliteObs

    mjdref = MISSION_MJDREF["nicer"]
    met = np.arange(0, 86400, 60.0)
    r_km = 6980.0
    ang = 2 * np.pi * met / 5700.0
    pos_km = np.stack([r_km * np.cos(ang), r_km * np.sin(ang),
                       np.zeros_like(ang)], axis=-1)
    p = tmp_path / "orb_km.fits"
    write_fits_table(p, {"TIME": met, "POSITION": pos_km},
                     {"MJDREFI": int(mjdref),
                      "MJDREFF": mjdref - int(mjdref)}, extname="ORBIT")
    ob = SatelliteObs.from_orbit_file("nicer", p)
    r = np.linalg.norm(ob.pos_m[0])
    assert r == pytest.approx(r_km * 1e3, rel=1e-12)


def test_satellite_unsorted_orbit_sorted(tmp_path):
    from pint_tpu.observatory.satellite_obs import SatelliteObs

    met = np.array([300.0, 100.0, 200.0, 0.0])
    pos = np.stack([met * 10, met * 0, met * 0], axis=-1) + 7e6
    ob = SatelliteObs("nicer", met, pos)
    assert (np.diff(ob.met_s) > 0).all()
    assert ob.pos_m[0, 0] == pytest.approx(7e6)  # met=0 row first


def test_satellite_out_of_span_raises():
    from pint_tpu.observatory.satellite_obs import SatelliteObs
    from pint_tpu.mjd import Epochs
    from pint_tpu.timescales import tt_to_tdb

    mjdref = MISSION_MJDREF["nicer"]
    met = np.arange(0, 3600, 30.0)
    ang = 2 * np.pi * met / 5700.0
    pos = np.stack([6.98e6 * np.cos(ang), 6.98e6 * np.sin(ang),
                    np.zeros_like(ang)], axis=-1)
    ob = SatelliteObs("nicer", met, pos, mjdref=mjdref)
    # an epoch ~1 day past the orbit span
    day = int(mjdref) + 1
    tt = Epochs(np.array([day], np.int64), np.array([40000.0]), "tt")
    with pytest.raises(ValueError, match="orbit"):
        ob.posvel_ssb(tt_to_tdb(tt), None, "de440s")
