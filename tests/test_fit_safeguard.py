"""Best-iterate safeguard in the plain fitters.

A plain Gauss-Newton step can increase chi2 — through strong
nonlinearity or (observed on the axon TPU backend, whose emulated f64
carries a ~47-bit significand) a corrupted normal-equation projection
along a near-degenerate direction. The plain WLS/GLS/wideband fitters
must never hand back an iterate worse than one they already evaluated.
The poisoned-step tests simulate the corruption deterministically by
monkeypatching the solver to return a huge bogus step.
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu import fitter as F
from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR TESTSAFE
RAJ 12:10:00.0
DECJ 09:00:00.0
F0 218.8 1
F1 -4e-16 1
PEPOCH 55300
DM 15.0 1
"""


def _toas(m, n=80, **kw):
    mjds = np.linspace(55000, 55600, n)
    f = np.where(np.arange(n) % 2, 800.0, 1400.0)
    return make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=f,
                                   obs="gbt", add_noise=True, seed=5, **kw)


def test_wls_poisoned_step_reverts(monkeypatch):
    m = get_model(PAR)
    t = _toas(m)
    # clean fit for the expected answer
    clean = F.WLSFitter(t, get_model(PAR))
    clean_chi2 = clean.fit_toas(maxiter=2)

    # the WLS iteration is one fused device program now
    # (_wls_fused_fns), so the corruption is injected at its host
    # boundary: poison the second step's iterate, then re-evaluate its
    # chi2 honestly — exactly what a corrupted normal-equation
    # projection looks like to the safeguard
    real_fns = F._wls_fused_fns
    calls = {"n": 0}

    def patched(prepared, **kw):
        eval_fn, step_fn, noff = real_fns(prepared, **kw)

        def poisoned_step(x, rw, s):
            x2, rw2, s2, chi2, covn, norm = step_fn(x, rw, s)
            calls["n"] += 1
            if calls["n"] == 2:  # second iteration steps off a cliff
                x2 = x2 + 1e-6
                rw2, s2, chi2 = eval_fn(x2)
            return x2, rw2, s2, chi2, covn, norm

        return eval_fn, poisoned_step, noff

    monkeypatch.setattr(F, "_wls_fused_fns", patched)
    f = F.WLSFitter(t, get_model(PAR))
    with pytest.warns(UserWarning, match="increased chi2"):
        chi2 = f.fit_toas(maxiter=2)
    # the good first step was kept, the poisoned second discarded
    assert chi2 < clean_chi2 * 1.01
    assert abs(f.model.F0.value - clean.model.F0.value) < 1e-9


def test_gls_poisoned_step_reverts(monkeypatch):
    par = PAR + "EFAC -f L-wide 1.1\nRNAMP 1e-14\nRNIDX -3\nTNREDC 5\n"
    m = get_model(par)
    t = _toas(m)
    for fl in t.flags:
        fl["f"] = "L-wide"
    clean = F.GLSFitter(t, get_model(par))
    clean_chi2 = clean.fit_toas(maxiter=2)

    real_solve = F.gls_solve
    calls = {"n": 0}

    def poisoned(Mfull, r, sigma, sqrt_phi_inv, threshold=1e-12, **kw):
        dx, cov, chi2 = real_solve(Mfull, r, sigma, sqrt_phi_inv, threshold,
                                   **kw)
        calls["n"] += 1
        if calls["n"] == 2:
            dx = dx + 1e-5
        return dx, cov, chi2

    monkeypatch.setattr(F, "gls_solve", poisoned)
    f = F.GLSFitter(t, get_model(par))
    with pytest.warns(UserWarning, match="increased chi2"):
        chi2 = f.fit_toas(maxiter=2)
    assert chi2 < clean_chi2 * 1.01


def test_gls_clean_fit_unchanged():
    """The safeguard must not disturb a well-behaved fit: same fitted
    values as before, chi2 monotone path accepted."""
    par = PAR + "EFAC -f L-wide 1.1\nRNAMP 1e-14\nRNIDX -3\nTNREDC 5\n"
    m = get_model(par)
    t = _toas(m)
    for fl in t.flags:
        fl["f"] = "L-wide"
    start = get_model(par)
    start.F0.value += 1e-9
    f = F.GLSFitter(t, start)
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        # no "increased chi2" warning on a clean fit (clock warnings
        # are already emitted once by earlier tests in this process)
        chi2 = f.fit_toas(maxiter=3)
    assert np.isfinite(chi2)
    assert abs(f.model.F0.value - 218.8) < 5e-11


def test_marginalized_chi2_matches_plain_when_no_bases():
    import jax.numpy as jnp

    r = jnp.asarray(np.linspace(-1e-6, 1e-6, 10))
    sig = jnp.full(10, 1e-6)
    assert F.marginalized_chi2(r, sig, (None, None)) == pytest.approx(
        float(jnp.sum(jnp.square(r / sig))))


def test_marginalized_chi2_reduces_with_basis():
    """Marginalizing a basis that spans the residual lowers chi2."""
    import jax.numpy as jnp

    n = 40
    t = np.linspace(0, 1, n)
    sig = jnp.full(n, 1.0)
    shape = np.sin(2 * np.pi * t)
    r = jnp.asarray(3.0 * shape)
    B = jnp.asarray(shape[:, None])
    w = jnp.asarray([1e16])  # loose prior (10^16 us^2 = 10^4 s^2)
    chi2_plain = float(jnp.sum(jnp.square(r)))
    chi2_marg = F.marginalized_chi2(r, sig, (B, w))
    assert chi2_marg < 0.01 * chi2_plain


def test_degraded_probe_runs():
    """The probe returns a bool and is cached; on the CPU test backend
    f64 is IEEE so it must be False."""
    assert F.degraded_f64() in (True, False)
    import jax

    if jax.default_backend() == "cpu":
        assert F.degraded_f64() is False
