"""N-body integrator invariants (ephemeris/nbody.py).

Anchors are closed-form / published physics, independent of any
ephemeris data: Kepler closure with the known 1PN drift, Mercury's GR
perihelion precession (42.98 arcsec/century, the classic test), and
conservation laws. (reference role: the reference trusts JPL's
integrator implicitly by reading DE kernels; building our own means
proving the dynamics here.)
"""

import numpy as np
import pytest

from pint_tpu.constants import AU_M, GMSUN_M3_S2
from pint_tpu.ephemeris import analytic, nbody


def test_two_body_period_closure_with_1pn_drift():
    """One period of an Earth-like circular orbit returns to the start
    up to the analytically known 1PN offset: per orbit, perihelion
    advance 6*pi*GM/(c^2 a) plus an equal along-track shift from the
    1PN mean-motion change — ~55.6 km total at 1 AU. Matching this at
    1% tests BOTH the integrator accuracy and the 1PN term's
    normalization."""
    gm = np.array([GMSUN_M3_S2, GMSUN_M3_S2 / 332946.0])
    r = AU_M
    v = np.sqrt(gm.sum() / r)
    pos0 = np.array([[0.0, 0, 0], [r, 0, 0]])
    vel0 = np.array([[0.0, 0, 0], [0, v, 0]])
    pos0, vel0 = nbody.to_barycentric(pos0, vel0, gm)
    P = 2 * np.pi * np.sqrt(r**3 / gm.sum())
    y = nbody.integrate(pos0, vel0, 0.0, P, gm).sol(P)
    err = np.linalg.norm(y[3:6] - pos0[1])
    c2 = nbody.C_M_S**2 if hasattr(nbody, "C_M_S") else 299792458.0**2
    # precession 6*pi*GM/(c^2 a) + along-track 3*GM/(c^2 a) * 2*pi
    expected = (6 * np.pi + 6 * np.pi) * GMSUN_M3_S2 / (c2 * r) * r
    assert err == pytest.approx(expected, rel=0.02)


def test_mercury_gr_perihelion_precession():
    """Sun+Mercury only, 10 years: Laplace-Runge-Lenz vector rotation
    = 42.98 arcsec/century (GR). Newtonian-only would give ~0."""
    gm = np.array([GMSUN_M3_S2, GMSUN_M3_S2 / 6.0236e6])
    a_m, e_m = 0.38709893 * AU_M, 0.20563069
    rp = a_m * (1 - e_m)
    vp = np.sqrt(gm.sum() * (2 / rp - 1 / a_m))
    pos0 = np.array([[0.0, 0, 0], [rp, 0, 0]])
    vel0 = np.array([[0.0, 0, 0], [0, vp, 0]])
    pos0, vel0 = nbody.to_barycentric(pos0, vel0, gm)
    yrs = 10.0
    T = yrs * 365.25 * 86400

    sol = nbody.integrate(pos0, vel0, 0.0, T, gm).sol

    def lrl_angle(y):
        r = y[3:6] - y[0:3]
        v = y[9:12] - y[6:9]
        h = np.cross(r, v)
        ev = np.cross(v, h) / gm.sum() - r / np.linalg.norm(r)
        return np.arctan2(ev[1], ev[0])

    d = lrl_angle(sol(T)) - lrl_angle(sol(0.0))
    d = (d + np.pi) % (2 * np.pi) - np.pi
    arcsec_cy = np.degrees(d) * 3600 * (100 / yrs)
    assert arcsec_cy == pytest.approx(42.98, rel=0.05)


def test_full_system_conservation_2yr():
    """Energy/momentum/angular momentum of the full 10-body system over
    2 years from analytic initial conditions. (The 1PN term makes the
    Newtonian energy oscillate at the 1e-8 level; drift beyond 1e-7
    would mean an integrator or force bug.)"""
    pos0 = np.zeros((10, 3))
    vel0 = np.zeros((10, 3))
    for i, b in enumerate(nbody.BODIES):
        p, v = analytic.body_posvel_ssb(b, np.array([52000.0]))
        pos0[i], vel0[i] = p[0], v[0]
    pos0, vel0 = nbody.to_barycentric(pos0, vel0)
    E0, M0, L0 = nbody.energy_momentum(pos0, vel0)
    T = 2 * 365.25 * 86400
    y = nbody.integrate(pos0, vel0, 0.0, T).sol(T)
    pos1, vel1 = y[:30].reshape(10, 3), y[30:].reshape(10, 3)
    E1, M1, L1 = nbody.energy_momentum(pos1, vel1)
    assert abs((E1 - E0) / E0) < 1e-7
    assert np.linalg.norm(L1 - L0) / np.linalg.norm(L0) < 1e-8
    # momentum stays ~0 (barycentric start, momentum-conserving forces)
    v_scale = np.sum(nbody.GM * np.linalg.norm(vel0, axis=1))
    assert np.linalg.norm(M1) / v_scale < 1e-10


def test_batched_integration_matches_single():
    """integrate_batch on [unperturbed, perturbed] copies: lane 0 must
    match a plain integrate() run; the perturbed lane must differ."""
    pos0 = np.zeros((10, 3))
    vel0 = np.zeros((10, 3))
    for i, b in enumerate(nbody.BODIES):
        p, v = analytic.body_posvel_ssb(b, np.array([52000.0]))
        pos0[i], vel0[i] = p[0], v[0]
    pos0, vel0 = nbody.to_barycentric(pos0, vel0)
    T = 30 * 86400.0
    t_eval = np.array([-T, -0.3 * T, 0.0, 0.4 * T, T])

    pb = np.stack([pos0, pos0])
    vb = np.stack([vel0, vel0])
    pb[1, 3] += np.array([1e5, 0, 0])  # nudge Earth 100 km
    out = nbody.integrate_batch(pb, vb, 0.0, t_eval, rtol=1e-12)

    fwd = nbody.integrate(pos0, vel0, 0.0, T).sol
    bck = nbody.integrate(pos0, vel0, 0.0, -T).sol
    for k, t in enumerate(t_eval):
        ref = (bck if t < 0 else fwd)(t)
        got = np.concatenate([out[0, 0, :, :, k].ravel(),
                              out[0, 1, :, :, k].ravel()])
        assert np.allclose(got, ref, rtol=0, atol=5.0), f"t={t}"
    # perturbed Earth lane differs by ~the perturbation
    d = np.linalg.norm(out[1, 0, 3, :, -1] - out[0, 0, 3, :, -1])
    assert 1e4 < d < 1e7
