"""Perf observatory (pint_tpu.obs costmodel / baseline / slo):
roofline attribution math and the null-MFU fix, executable cost
capture on the AOT spans, per-program MFU on fleet execute spans, the
bench-trajectory regression gate (real history passes, an injected
20% slowdown fails loudly), SLO dual-window burn-rate alerts with
flight-dump plumbing, flight-recorder dump rotation, histogram
reservoir semantics, Prometheus exposition conformance, and the
pintlint meta-key-unbudgeted rule."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pint_tpu import obs
from pint_tpu.obs import baseline, costmodel
from pint_tpu.obs import recorder as obs_recorder
from pint_tpu.obs import slo as obs_slo
from pint_tpu.obs.metricsreg import (Histogram, Registry, percentile,
                                     prom_name, prometheus_text)
from pint_tpu.obs.recorder import FlightRecorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_obs():
    """Tracing off, empty rings, no dump dir around every test
    (module-global tracer/recorder state)."""
    obs.disable()
    obs.reset()
    obs_recorder.RECORDER.reset()
    obs_recorder.RECORDER.dump_dir = None
    yield
    obs.disable()
    obs.reset()
    obs_recorder.RECORDER.reset()
    obs_recorder.RECORDER.dump_dir = None


# -- cost model / roofline math --------------------------------------


def test_peak_table_never_null(monkeypatch):
    """The BENCH_r05 null-MFU failure mode: an unrecorded platform
    must fall back to the nominal spec, not null every consumer."""
    monkeypatch.delenv("PINT_TPU_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("PINT_TPU_PEAK_BYTES_PER_S", raising=False)
    for platform in ("cpu", "tpu", "gpu", "some_future_backend"):
        spec = costmodel.device_spec(platform)
        assert spec["peak_flops"] > 0
        assert spec["peak_bytes_per_s"] > 0
        assert costmodel.mfu_pct(1e9, 1.0, platform) is not None
    assert costmodel.device_spec("some_future_backend").get("nominal")
    assert not costmodel.device_spec("cpu").get("nominal")


def test_env_override_pins_mfu(monkeypatch):
    """The pinned synthetic MFU figure: peak 1e12, 1e10 FLOPs in
    0.1 s -> exactly 10% MFU on every platform."""
    monkeypatch.setenv("PINT_TPU_PEAK_FLOPS", "1e12")
    assert costmodel.mfu_pct(1e10, 0.1, "cpu") == 10.0
    assert costmodel.mfu_pct(1e10, 0.1, "unknown") == 10.0
    # unknown flops/wall are the ONLY null cases
    assert costmodel.mfu_pct(None, 0.1, "cpu") is None
    assert costmodel.mfu_pct(1e10, None, "cpu") is None


def test_bench_mfu_delegation_non_null(monkeypatch):
    """bench.py's MFU helpers delegate to the costmodel table, so a
    CPU round reports real numbers instead of the r05 nulls."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    monkeypatch.delenv("PINT_TPU_PEAK_FLOPS", raising=False)
    assert bench._peak_flops("cpu") == costmodel.peak_flops("cpu")
    assert bench._mfu(1e10, 0.5, "cpu") is not None
    monkeypatch.setenv("PINT_TPU_PEAK_FLOPS", "1e12")
    assert bench._mfu(1e10, 0.1, "cpu") == 10.0


def test_attribute_roofline_math(monkeypatch):
    monkeypatch.setenv("PINT_TPU_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("PINT_TPU_PEAK_BYTES_PER_S", "1e11")
    # knee = 10 FLOP/byte. intensity 2 -> memory-bound, ceiling 2e11
    a = costmodel.attribute(2e9, 1e9, wall_s=0.1)
    assert a["intensity_flops_per_byte"] == 2.0
    assert a["bound"] == "memory"
    assert a["roofline_ceiling_flops"] == pytest.approx(2e11)
    assert a["achieved_flops_per_s"] == pytest.approx(2e10)
    assert a["mfu_pct"] == pytest.approx(2.0)
    assert a["roofline_pct"] == pytest.approx(10.0)
    # intensity 20 -> compute-bound, ceiling = the flat peak
    b = costmodel.attribute(2e10, 1e9)
    assert b["bound"] == "compute"
    assert b["roofline_ceiling_flops"] == pytest.approx(1e12)
    assert b["mfu_pct"] is None  # no wall given
    # unknown bytes: no intensity/bound, ceiling degrades to the peak
    c = costmodel.attribute(2e9, None, wall_s=0.1)
    assert c["intensity_flops_per_byte"] is None
    assert c["bound"] is None
    assert c["roofline_ceiling_flops"] == pytest.approx(1e12)
    assert c["mfu_pct"] is not None


def test_program_ledger_roundtrip():
    led = costmodel.ProgramLedger()
    led.record("prog", {"flops": 1e9, "bytes_accessed": 1e9})
    attr = led.attribute("prog", wall_s=1.0, platform="cpu")
    assert attr["mfu_pct"] is not None
    assert led.attribute("never_compiled") is None
    assert "prog" in led.snapshot()
    led.reset()
    assert led.snapshot() == {}


# -- AOT compile split: cost capture on spans ------------------------


def test_aot_backend_compile_span_carries_cost_and_roofline():
    import jax.numpy as jnp

    from pint_tpu import fitter

    def f(x):
        return jnp.dot(x, x)

    low = fitter.aot_lower(f, jnp.arange(64, dtype=jnp.float64))
    obs.enable()
    info = fitter.aot_backend_compile(low["lowered"], label="test_prog")
    obs.disable()
    assert info["flops"] and info["flops"] > 0
    assert info["backend_compile_s"] >= 0
    (rec,) = [s for s in obs.spans()
              if s["name"] == "aot.backend_compile"]
    attrs = rec["attrs"]
    assert float(attrs["flops"]) > 0
    assert float(attrs["roofline_ceiling_flops"]) > 0
    assert attrs["program"] == "test_prog"
    # the ledger lets execute-time consumers attribute this program
    led = costmodel.LEDGER.attribute("test_prog", wall_s=1.0)
    assert led is not None and led["mfu_pct"] is not None


def _tiny_wls_fleet():
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    rng = np.random.default_rng(7)
    models, toas_list = [], []
    for i in range(2):
        par = (f"PSR OBS{i}\nRAJ 6:{10 + i}:00\nDECJ 12:00:00\n"
               f"F0 {310 + i}.9 1\nF1 -4e-16 1\nPEPOCH 55500\n"
               f"DM {11 + i}.3 1\n")
        m = get_model(par)
        mjds = np.sort(rng.uniform(55000, 56000, 40))
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0,
                                    freq_mhz=1400.0, obs="gbt",
                                    add_noise=True, seed=i,
                                    iterations=0)
        models.append(m)
        toas_list.append(t)
    return models, toas_list


def test_fleet_execute_span_mfu_non_null_on_cpu():
    """The acceptance criterion: a traced fleet fit's execute spans
    carry non-null mfu_pct with an attributed roofline ceiling, on
    CPU, in both the pipelined and the precompiled sequential path."""
    from pint_tpu.parallel import PTAFleet

    models, toas_list = _tiny_wls_fleet()
    obs.enable()
    try:
        fleet = PTAFleet(models, toas_list, toa_bucket="pow2",
                         bucket_floor=16, pipeline=True)
        fleet.fit(method="wls", maxiter=2)
        execs = [s for s in obs.spans() if s["name"] == "fleet.execute"]
        assert execs, "no fleet.execute spans recorded"
        for s in execs:
            assert s["attrs"]["mfu_pct"] is not None
            assert float(s["attrs"]["mfu_pct"]) > 0
            assert float(s["attrs"]["roofline_ceiling_flops"]) > 0
            assert s["attrs"]["bound"] in ("compute", "memory")
        obs.reset()
        # sequential path: AOT-precompile installs the cost records,
        # then the plain fit loop attributes against them
        seq = PTAFleet(models, toas_list, toa_bucket="pow2",
                       bucket_floor=16, pipeline=False)
        seq.precompile(method="wls", maxiter=2)
        seq.fit(method="wls", maxiter=2)
        execs = [s for s in obs.spans() if s["name"] == "fleet.execute"]
        assert execs
        assert all(s["attrs"]["mfu_pct"] is not None for s in execs)
    finally:
        obs.disable()


# -- bench-trajectory store + regression gate ------------------------


def test_regress_passes_on_real_history():
    report = baseline.run_regress(root=REPO)
    assert report["n_rounds"] >= 3
    assert report["ok"], (report["budget_violations"],
                          report["regressions"])
    assert report["checked"], "regression gate checked zero keys"


def _write_rounds(tmp_path, walls, key="wls_refit_wall_s",
                  extra_latest=None):
    """Synthetic BENCH_r0*.json trajectory with one detail key."""
    for i, wall in enumerate(walls, start=1):
        detail = {key: wall}
        if extra_latest and i == len(walls):
            detail.update(extra_latest)
        doc = {"parsed": {"metric": "pta_gls_refit_toas_per_sec",
                          "value": 1e5, "detail": detail}}
        (tmp_path / ("BENCH_r%02d.json" % i)).write_text(
            json.dumps(doc))
    return str(tmp_path)


def test_regress_fails_on_injected_20pct_slowdown(tmp_path):
    """A stable 5-round history then a 20% slower latest round: the
    10% relative floor dominates the MAD tolerance, so the gate must
    fail loudly and name the key."""
    root = _write_rounds(tmp_path,
                         [1.00, 1.002, 0.998, 1.001, 0.999, 1.20])
    report = baseline.run_regress(root=root)
    assert not report["ok"]
    keys = [r["key"] for r in report["regressions"]]
    assert "wls_refit_wall_s" in keys
    (viol,) = [r for r in report["regressions"]
               if r["key"] == "wls_refit_wall_s"]
    assert viol["ratio"] > 1.15
    assert "regressed" in viol["detail"]


def test_regress_direction_aware(tmp_path):
    # a FASTER wall is an improvement, never a regression
    root = _write_rounds(tmp_path,
                         [1.00, 1.002, 0.998, 1.001, 0.999, 0.50])
    report = baseline.run_regress(root=root)
    assert report["ok"], report["regressions"]


def test_regress_min_prior_gate(tmp_path):
    # 2 prior rounds < min_prior 3: skipped, not guessed at
    root = _write_rounds(tmp_path, [1.0, 1.0, 5.0])
    report = baseline.run_regress(root=root)
    assert report["ok"]
    assert "insufficient_history" in \
        report["skipped"]["wls_refit_wall_s"]


def test_regress_budget_violation_binds_when_present(tmp_path):
    root = _write_rounds(
        tmp_path, [1.0, 1.0, 1.0, 1.0],
        extra_latest={"measured_670k_plan_padding_ratio": 1.50})
    report = baseline.run_regress(root=root)
    assert not report["ok"]
    (viol,) = report["budget_violations"]
    assert viol["key"] == "measured_670k_plan_padding_ratio"
    assert "exceeds budget max" in viol["detail"]


def test_robust_tolerance_mad_beats_outlier():
    # one historic outlier must not inflate the tolerance the way a
    # stddev would: MAD of [1,1,1,1,10] is 0
    tol, med = baseline.robust_tolerance([1.0, 1.0, 1.0, 1.0, 10.0],
                                         rel_floor=0.10, k_mad=4.0)
    assert med == 1.0
    assert tol == 0.10  # the floor, not an outlier-inflated band


def test_registered_keys_cover_all_sections():
    keys = baseline.registered_keys()
    assert "measured_670k_mfu_pct" in keys        # regressions
    assert "measured_670k_padding_ratio" in keys  # budgets
    assert "serve_cache_hit_rate" in keys         # tracked


def test_regress_cli_exit_codes(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    (tmp_path / "good").mkdir()
    good = _write_rounds(tmp_path / "good",
                         [1.0, 1.001, 0.999, 1.0, 1.0])
    proc = subprocess.run(
        [sys.executable, "-m", "pint_tpu.obs", "regress",
         "--root", good, "--json"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout)["ok"] is True
    (tmp_path / "bad").mkdir()
    bad = _write_rounds(tmp_path / "bad",
                        [1.0, 1.001, 0.999, 1.0, 1.3])
    proc = subprocess.run(
        [sys.executable, "-m", "pint_tpu.obs", "regress",
         "--root", bad],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert proc.returncode == 1
    assert "wls_refit_wall_s" in proc.stderr + proc.stdout


# -- SLO burn-rate monitor -------------------------------------------


def _snap(requests, ok, shed=0, breaker=0, p99=0.01, lost=()):
    return {
        "requests": requests,
        "requests_ok": ok,
        "counters": {"shed_queue_full": shed,
                     "rejected_circuit_open": breaker, "errors": 0},
        "total_s": {"p50": p99 / 2, "p99": p99, "max": p99},
        "devices": {"n_lanes": 4,
                    "alive_lanes": 4 - len(lost),
                    "lost_lanes": list(lost)},
    }


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_slo_spec_validation():
    with pytest.raises(ValueError):
        obs_slo.SLOSpec("x", budget=0.0, bad="counters.errors")
    with pytest.raises(ValueError):
        obs_slo.SLOSpec("x", budget=0.01)  # neither bad nor value
    with pytest.raises(ValueError):
        obs_slo.SLOSpec("x", budget=0.01, bad="a", value="b")


def test_serve_slos_alerts_are_reachable():
    """Max possible burn is 1/budget (every sample bad); each default
    budget must leave that above the fast factor or the alert can
    never fire."""
    for spec in obs_slo.serve_slos():
        assert 1.0 / spec.budget > spec.fast_burn, spec.name


def test_slo_dual_window_alert_flight_dump_and_gauges(tmp_path):
    clock = FakeClock()
    rec = FlightRecorder(dump_dir=str(tmp_path))
    reg = Registry()
    mon = obs_slo.BurnRateMonitor(
        specs=obs_slo.serve_slos(fast_window_s=300.0,
                                 slow_window_s=3600.0),
        clock=clock, registry=reg, recorder=rec)
    # healthy hour of traffic: nothing alerts
    n = 0
    for _ in range(30):
        clock.t += 120.0
        n += 100
        mon.ingest(_snap(requests=n, ok=n))
    assert mon.alerting() == []
    # then a hard availability cliff: every new request fails
    ok = n
    for _ in range(31):
        clock.t += 120.0
        n += 100
        states = mon.ingest(_snap(requests=n, ok=ok))
    assert "availability" in mon.alerting()
    assert mon.alerts_fired >= 1
    avail = [s for s in states if s["name"] == "availability"][0]
    assert avail["burn_fast"] >= 14.4
    assert avail["burn_slow"] >= 6.0
    # the alert dumped flight context and exported gauges
    assert any("slo_burn_availability" in p for p in rec.dumps)
    assert reg.gauge("slo.availability.alerting").value == 1
    assert reg.gauge("slo.availability.burn_fast").value >= 14.4
    assert reg.counter("slo.alerts_fired").value == mon.alerts_fired
    events = [e for e in rec.events() if e.get("what") == "slo_alert"]
    assert any(e.get("slo") == "availability" for e in events)
    # recovery: new requests all succeed (the bad count freezes), so
    # both windows drain and the alert clears with a resolved event
    bad_total = n - ok
    for _ in range(40):
        clock.t += 120.0
        n += 100
        mon.ingest(_snap(requests=n, ok=n - bad_total))
    assert "availability" not in mon.alerting()
    assert any(e.get("what") == "slo_resolved" for e in rec.events())


def test_slo_fast_only_spike_stays_quiet(tmp_path):
    """A short cliff lights the fast window but not the slow one:
    no page — the multi-window rule exists to absorb transients."""
    clock = FakeClock()
    rec = FlightRecorder(dump_dir=str(tmp_path))
    mon = obs_slo.BurnRateMonitor(
        specs=obs_slo.serve_slos(), clock=clock,
        registry=Registry(), recorder=rec)
    n = 0
    for _ in range(60):  # two hours of clean traffic
        clock.t += 120.0
        n += 100
        mon.ingest(_snap(requests=n, ok=n))
    # one 2-minute total outage: the fast window burns hot, but
    # 100 bad out of ~3000 slow-window requests stays under 6x
    ok = n
    clock.t += 120.0
    n += 100
    states = {s["name"]: s
              for s in mon.ingest(_snap(requests=n, ok=ok))}
    assert states["availability"]["burn_fast"] >= 14.4
    assert states["availability"]["burn_slow"] < 6.0
    assert mon.alerting() == []
    assert rec.dumps == []


def test_slo_threshold_mode_latency_and_lanes(tmp_path):
    clock = FakeClock()
    mon = obs_slo.BurnRateMonitor(
        specs=obs_slo.serve_slos(latency_limit_s=0.25),
        clock=clock, registry=Registry(),
        recorder=FlightRecorder(dump_dir=str(tmp_path)))
    n = 0
    for _ in range(40):  # every check violates p99 AND has a lost lane
        clock.t += 120.0
        n += 100
        mon.ingest(_snap(requests=n, ok=n, p99=0.9, lost=[2]))
    alerting = mon.alerting()
    assert "latency_p99" in alerting
    assert "lane_loss" in alerting
    assert "availability" not in alerting


def test_slo_snapshot_shape():
    mon = obs_slo.BurnRateMonitor(specs=obs_slo.serve_slos(),
                                  clock=FakeClock(),
                                  registry=Registry(),
                                  recorder=FlightRecorder())
    snap = mon.snapshot()  # before any ingest: all-quiet zeros
    assert set(snap) == {"availability", "shed", "breaker",
                         "latency_p99", "lane_loss"}
    for st in snap.values():
        assert st == {"burn_fast": 0.0, "burn_slow": 0.0,
                      "alerting": False, "budget": st["budget"]}
    json.dumps(snap)  # JSON-safe by contract


def test_serve_standing_counters_present_from_first_snapshot():
    """The SLO monitor and Prometheus read shed/breaker counters by
    name: they must exist (as 0) before the first increment."""
    from pint_tpu.serve.metrics import ServeTelemetry

    snap = ServeTelemetry().snapshot()
    for name in ("shed_queue_full", "rejected_circuit_open", "errors"):
        assert snap["counters"][name] == 0


# -- flight recorder rotation ----------------------------------------


def test_flight_dump_rotation_caps_on_disk_dumps(tmp_path):
    rec = FlightRecorder(dump_dir=str(tmp_path), max_dumps=3)
    for i in range(5):
        rec.note("event", i=i)
        rec.dump("reason%d" % i)
    files = sorted(os.listdir(tmp_path))
    assert len(files) == 3
    # the newest three survive, lexical order == dump order
    assert files == ["flight_003_reason2.json",
                     "flight_004_reason3.json",
                     "flight_005_reason4.json"]
    # the in-process dump list is pruned with the files
    assert [os.path.basename(p) for p in rec.dumps] == files
    # surviving dumps still parse
    with open(tmp_path / files[-1]) as fh:
        assert json.load(fh)["reason"] == "reason4"


def test_flight_max_env_override_and_disable(tmp_path, monkeypatch):
    monkeypatch.setenv("PINT_TPU_FLIGHT_MAX", "2")
    rec = FlightRecorder(dump_dir=str(tmp_path / "a"))
    assert rec.max_dumps == 2
    for i in range(4):
        rec.dump("r%d" % i)
    assert len(os.listdir(tmp_path / "a")) == 2
    # unparseable env value falls back to the default, never raises
    monkeypatch.setenv("PINT_TPU_FLIGHT_MAX", "lots")
    assert FlightRecorder().max_dumps == 32
    # <= 0 disables rotation entirely
    monkeypatch.delenv("PINT_TPU_FLIGHT_MAX")
    rec0 = FlightRecorder(dump_dir=str(tmp_path / "b"), max_dumps=0)
    for i in range(5):
        rec0.dump("r%d" % i)
    assert len(os.listdir(tmp_path / "b")) == 5


def test_configure_sets_max_dumps(tmp_path):
    before = obs_recorder.RECORDER.max_dumps
    try:
        rec = obs_recorder.configure(dump_dir=str(tmp_path),
                                     max_dumps=7)
        assert rec.max_dumps == 7
    finally:
        obs_recorder.configure(max_dumps=before)


# -- histogram reservoir semantics -----------------------------------


def test_histogram_exact_below_capacity():
    """Below capacity the quantiles must be byte-compatible with the
    unbounded nearest-rank implementation."""
    h = Histogram(capacity=100)
    vals = [float(v) for v in np.random.default_rng(3).uniform(
        0, 10, 80)]
    for v in vals:
        h.record(v)
    for q in (50, 90, 99):
        assert h.percentile(q) == percentile(vals, q)
    summ = h.summary()
    assert summ["count"] == 80
    assert summ["observed"] == 80
    assert summ["sum"] == pytest.approx(sum(vals))


def test_histogram_reservoir_past_capacity():
    h = Histogram(capacity=100, seed=0)
    rng = np.random.default_rng(5)
    stream = rng.normal(50.0, 5.0, 10_000)
    for v in stream:
        h.record(v)
    assert len(h.values()) == 100          # bounded memory
    assert h.observed == 10_000            # full-stream count
    assert h.sum == pytest.approx(float(stream.sum()))
    # an unbiased uniform sample: p50 lands near the true median,
    # which a keep-the-last-window buffer would not guarantee for a
    # drifting stream
    assert abs(h.percentile(50) - float(np.median(stream))) < 2.5
    # every buffered value came from the stream
    stream_set = set(float(v) for v in stream)
    assert all(v in stream_set for v in h.values())


def test_histogram_reservoir_deterministic():
    a, b = Histogram(capacity=10), Histogram(capacity=10)
    for i in range(1000):
        a.record(i)
        b.record(i)
    assert a.values() == b.values()


def test_histogram_empty_and_singleton():
    h = Histogram(capacity=4)
    assert h.percentile(50) is None
    summ = h.summary()
    assert summ["count"] == 0 and summ["observed"] == 0
    assert summ["sum"] == 0.0
    h.record(3.5)
    assert h.percentile(50) == 3.5
    assert h.percentile(99) == 3.5
    assert h.summary()["observed"] == 1


# -- Prometheus exposition conformance -------------------------------


class TestPrometheusConformance:
    def _reg(self):
        reg = Registry()
        reg.counter("serve.requests").inc(7)
        reg.gauge("fleet.overlap_pct").set(61.5)
        h = reg.histogram("serve.total_s", capacity=8)
        for v in (0.1, 0.2, 0.3, 0.4):
            h.record(v)
        return reg

    def test_type_line_per_metric_and_valid_names(self):
        text = prometheus_text(registry=self._reg())
        lines = text.strip().split("\n")
        types = [ln for ln in lines if ln.startswith("# TYPE ")]
        assert "# TYPE pint_tpu_serve_requests counter" in types
        assert "# TYPE pint_tpu_fleet_overlap_pct gauge" in types
        assert "# TYPE pint_tpu_serve_total_s summary" in types
        name_re = __import__("re").compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
        for ln in lines:
            if ln.startswith("#"):
                continue
            metric = ln.split("{")[0].split(" ")[0]
            assert name_re.match(metric), metric

    def test_histogram_count_sum_cover_full_stream(self):
        reg = Registry()
        h = reg.histogram("lat", capacity=4)
        for v in range(100):
            h.record(float(v))
        text = prometheus_text(registry=reg)
        assert "pint_tpu_lat_count 100" in text
        assert "pint_tpu_lat_sum 4950.0" in text
        assert 'pint_tpu_lat{quantile="0.50"}' in text

    def test_nan_and_inf_value_formats(self):
        reg = Registry()
        reg.gauge("g.none").set(None)
        reg.gauge("g.nan").set(float("nan"))
        reg.gauge("g.pinf").set(float("inf"))
        reg.gauge("g.ninf").set(float("-inf"))
        text = prometheus_text(registry=reg)
        assert "pint_tpu_g_none NaN" in text
        assert "pint_tpu_g_nan NaN" in text
        assert "pint_tpu_g_pinf +Inf" in text
        assert "pint_tpu_g_ninf -Inf" in text
        # every exposed VALUE is a float literal or NaN/+Inf/-Inf —
        # never Python's "inf"/"Infinity" spellings
        for ln in text.strip().split("\n"):
            if ln.startswith("#"):
                continue
            val = ln.rsplit(" ", 1)[1]
            assert val in ("NaN", "+Inf", "-Inf") or \
                float(val) == float(val)

    def test_colliding_sanitized_names_share_one_type_line(self):
        reg = Registry()
        reg.gauge("a.b").set(1.0)
        reg.gauge("a/b").set(2.0)  # sanitizes to the same name
        assert prom_name("a.b") == prom_name("a/b")
        text = prometheus_text(registry=reg)
        assert text.count("# TYPE pint_tpu_a_b gauge") == 1
        assert text.count("pint_tpu_a_b ") >= 2

    def test_slo_gauges_flow_into_exposition(self, tmp_path):
        reg = Registry()
        mon = obs_slo.BurnRateMonitor(
            specs=[obs_slo.SLOSpec("avail", 0.01,
                                   bad="bad", total="total")],
            clock=FakeClock(), registry=reg,
            recorder=FlightRecorder())
        mon.ingest({"bad": 0, "total": 100})
        text = prometheus_text(registry=reg)
        assert "pint_tpu_slo_avail_burn_fast" in text
        assert "pint_tpu_slo_avail_alerting 0" in text
        assert "pint_tpu_slo_alerts_fired 0" in text


# -- pintlint meta-key-unbudgeted rule -------------------------------


def _lint(tmp_path, rel, src, cfg):
    import textwrap

    from pint_tpu.analysis import run, unsuppressed

    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return [f for f in unsuppressed(run([str(p)], config=cfg))
            if f.rule == "meta-key-unbudgeted"]


def test_meta_key_rule_flags_unregistered_key(tmp_path):
    from pint_tpu.analysis import LintConfig

    cfg = LintConfig(budget_meta_modules=("/bench.py",),
                     budgeted_meta_keys=frozenset({"serve_known"}))
    bad = """
        meta.update({"measured_670k_shiny_new_s": 1.0,
                     "serve_known": 2.0,
                     "not_a_meta_key": 3.0})
    """
    (finding,) = _lint(tmp_path, "bench.py", bad, cfg)
    assert "measured_670k_shiny_new_s" in finding.message
    assert "budgets.json" in finding.message


def test_meta_key_rule_ignores_reads_and_other_modules(tmp_path):
    from pint_tpu.analysis import LintConfig

    cfg = LintConfig(budget_meta_modules=("/bench.py",),
                     budgeted_meta_keys=frozenset())
    # a subscript READ of another report dict is not a definition
    ok = 'x = report["serve_p99_latency_s"]\n'
    assert _lint(tmp_path, "bench.py", ok, cfg) == []
    # an unregistered key outside the governed modules is not flagged
    bad = 'meta = {"measured_rogue": 1}\n'
    assert _lint(tmp_path, "other.py", bad, cfg) == []


def test_meta_key_rule_inert_without_budget_file(tmp_path):
    from pint_tpu.analysis import LintConfig

    cfg = LintConfig(budget_meta_modules=("/bench.py",),
                     budgeted_meta_keys=None)
    bad = 'meta = {"measured_rogue": 1}\n'
    assert _lint(tmp_path, "bench.py", bad, cfg) == []


def test_default_config_binds_real_budget_registry():
    from pint_tpu.analysis import LintConfig

    cfg = LintConfig.default()
    assert "/bench.py" in cfg.budget_meta_modules
    assert cfg.budgeted_meta_keys is not None
    assert "measured_670k_mfu_pct" in cfg.budgeted_meta_keys


def test_real_bench_meta_keys_all_registered(tmp_path):
    """The shipped bench.py must lint clean under the rule — every
    measured_*/serve_* key it emits is in budgets.json."""
    from pint_tpu.analysis import (LintConfig, run, unsuppressed)

    findings = run([os.path.join(REPO, "bench.py")],
                   config=LintConfig.default())
    bad = [f for f in unsuppressed(findings)
           if f.rule == "meta-key-unbudgeted"]
    assert bad == [], [f.message for f in bad]


# -- SLO CLI ----------------------------------------------------------


def test_slo_cli_exit_codes(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    quiet = tmp_path / "quiet.json"
    quiet.write_text(json.dumps(_snap(requests=100, ok=100)))
    proc = subprocess.run(
        [sys.executable, "-m", "pint_tpu.obs", "slo", str(quiet)],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["alerting"] == []
    # a sustained total outage across fast+slow windows pages
    paths = []
    n = 0
    for i in range(40):
        n += 100
        p = tmp_path / ("s%02d.json" % i)
        p.write_text(json.dumps(_snap(requests=n, ok=0)))
        paths.append(str(p))
    proc = subprocess.run(
        [sys.executable, "-m", "pint_tpu.obs", "slo",
         "--step", "120", *paths],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "availability" in json.loads(proc.stdout)["alerting"]
