"""Regenerate the b1855sim golden pack (par/tim/prefit-resid tensor).

Run after an INTENTIONAL physics change, then update the frozen wrms /
whitened-chi2 constants in tests/test_golden.py from the printed
values and justify the delta in the commit message:

    python tests/golden/generate_b1855sim.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import warnings

import numpy as np

warnings.simplefilter("ignore")

HERE = os.path.dirname(os.path.abspath(__file__))



def main():
    from pint_tpu.fitter import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_fromMJDs
    from pint_tpu.toa import get_TOAs

    # the committed par file is the single source of truth
    parfile = os.path.join(HERE, "b1855sim.par")
    timfile = os.path.join(HERE, "b1855sim.tim")
    m = get_model(parfile)
    rng = np.random.default_rng(1855)
    days = np.sort(rng.uniform(53300, 55300, 100))
    mjds = np.sort(np.concatenate([days + k * 0.4 / 86400
                                   for k in range(3)]))
    freqs = np.tile([430.0, 1410.0, 2380.0], 100)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                obs="arecibo", add_noise=True,
                                add_correlated_noise=True, seed=1855)
    for f in t.flags:
        f["f"] = "L-wide"
    t.write_TOA_file(timfile)
    t2 = get_TOAs(timfile, usepickle=False)
    r = Residuals(t2, m)
    resid_us = np.asarray(r.calc_time_resids()) * 1e6
    np.save(os.path.join(HERE, "b1855sim_prefit_resids_us.npy"), resid_us)
    f = GLSFitter(t2, m)
    f.fit_toas(maxiter=2)
    print("update tests/test_golden.py constants:")
    print("  n=%d prefit wrms=%.6f us postfit whitened chi2=%.6f" % (
        len(t2), r.rms_weighted() * 1e6, f.chi2_whitened))


if __name__ == "__main__":
    main()
