"""Regenerate the NGC6440E golden tensors (prefit resids + delay chain).

Run after an INTENTIONAL physics change (e.g. a new default ephemeris
provider tier), then update the frozen wrms constant in
tests/test_golden.py from the printed value and justify the delta in
the commit message:

    python tests/golden/generate_ngc6440e.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import warnings

import numpy as np

warnings.simplefilter("ignore")

HERE = os.path.dirname(os.path.abspath(__file__))
PAR = os.path.join(HERE, "..", "..", "pint_tpu", "data", "examples",
                   "NGC6440E.par")
TIM = os.path.join(HERE, "..", "..", "pint_tpu", "data", "examples",
                   "NGC6440E.tim")


def main():
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.toa import get_TOAs

    m = get_model(PAR)
    t = get_TOAs(TIM, usepickle=False)
    t.compute_posvels()
    r = Residuals(t, m)
    resid_us = np.asarray(r.calc_time_resids()) * 1e6
    delays = np.asarray(m.delay(t))
    np.save(os.path.join(HERE, "ngc6440e_prefit_resids_us.npy"), resid_us)
    np.save(os.path.join(HERE, "ngc6440e_delays_s.npy"), delays)
    print(f"ephem provider: {t.ephem_provider}")
    print(f"wrms_us = {r.rms_weighted() * 1e6:.6f}  "
          f"(update the frozen constant in test_golden.py)")


if __name__ == "__main__":
    main()
