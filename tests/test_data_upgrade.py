"""The data-upgrade path, exercised with synthetic real-format files.

The three offline-degraded tiers (solar-system ephemeris, Earth
orientation, observatory clock chains) each claim "drop the real data
file in and the provider upgrades transparently" (ERRORBUDGET.md).
These tests prove it: generate a minimal-but-valid file in each real
format — a DAF/SPK .bsp with known Chebyshev coefficients, an IERS
finals2000A snippet, tempo- and tempo2-format clock files — load it
through the real reader, and assert the provider switches and the
corrections match the synthetic truth.

(reference: jplephem's DAF/SPK reading, astropy.utils.iers's
finals2000A parsing, and src/pint/observatory/clock_file.py — each is
exercised constantly upstream; these are our equivalents.)
"""

import os
import struct

import numpy as np
import numpy.polynomial.chebyshev as cheb
import pytest

from pint_tpu.io.spk import SPKKernel
from pint_tpu.mjd import Epochs


# ---------------------------------------------------------------------------
# synthetic DAF/SPK writer
# ---------------------------------------------------------------------------

_ND, _NI = 2, 6
_SS_WORDS = _ND + (_NI + 1) // 2  # 5 words per summary


def _write_spk(path, segments):
    """Write a little-endian DAF/SPK with type 2/3 Chebyshev segments.

    segments: list of dicts
      target, center, data_type, init, intlen, records (n_rec, rsize)
    Layout: record 1 file record, record 2 summaries, record 3 names,
    data from record 4 (word 385). Word addresses are 1-indexed 8-byte
    words, as io/spk.py reads them.
    """
    data_words = []
    summaries = []
    next_word = 3 * 128 + 1  # first data word (record 4)
    for seg in segments:
        rec = np.asarray(seg["records"], dtype="<f8")
        n_rec, rsize = rec.shape
        start_word = next_word
        flat = list(rec.ravel()) + [
            float(seg["init"]), float(seg["intlen"]),
            float(rsize), float(n_rec),
        ]
        end_word = start_word + len(flat) - 1
        data_words.extend(flat)
        summaries.append((
            float(seg["init"]),
            float(seg["init"]) + n_rec * float(seg["intlen"]),
            seg["target"], seg["center"], 1, seg["data_type"],
            start_word, end_word,
        ))
        next_word = end_word + 1

    n_data_bytes = len(data_words) * 8
    total = 3 * 1024 + ((n_data_bytes + 1023) // 1024) * 1024
    buf = bytearray(total)

    # file record
    buf[0:8] = b"DAF/SPK "
    struct.pack_into("<ii", buf, 8, _ND, _NI)
    buf[16:76] = b"synthetic test kernel".ljust(60)
    struct.pack_into("<iii", buf, 76, 2, 2, next_word)  # fward, bward, free
    buf[88:96] = b"LTL-IEEE"

    # summary record (record 2)
    base = 1024
    struct.pack_into("<ddd", buf, base, 0.0, 0.0, float(len(summaries)))
    for i, (et0, et1, tgt, ctr, frame, dtype_, w0, w1) in enumerate(summaries):
        off = base + 24 + i * _SS_WORDS * 8
        struct.pack_into("<dd", buf, off, et0, et1)
        struct.pack_into("<6i", buf, off + 16, tgt, ctr, frame, dtype_, w0, w1)

    # name record (record 3) left blank; data from record 4
    buf[3 * 1024:3 * 1024 + n_data_bytes] = np.asarray(
        data_words, dtype="<f8").tobytes()
    with open(path, "wb") as f:
        f.write(bytes(buf))


def _type2_records(coeffs_xyz, init, intlen, n_rec):
    """Records (n_rec, 2+3*ncoef) from per-record coeff arrays
    coeffs_xyz[r] = (3, ncoef)."""
    ncoef = np.asarray(coeffs_xyz[0]).shape[1]
    out = np.zeros((n_rec, 2 + 3 * ncoef))
    for r in range(n_rec):
        mid = init + (r + 0.5) * intlen
        out[r, 0], out[r, 1] = mid, intlen / 2.0
        out[r, 2:] = np.asarray(coeffs_xyz[r]).ravel()
    return out


def _truth_type2(records, init, intlen, et):
    """Direct numpy Chebyshev evaluation of the synthetic segment."""
    et = np.atleast_1d(et)
    ncoef = (records.shape[1] - 2) // 3
    idx = np.clip(((et - init) / intlen).astype(int), 0, len(records) - 1)
    pos = np.empty((len(et), 3))
    vel = np.empty((len(et), 3))
    for i, (t, r) in enumerate(zip(et, idx)):
        mid, radius = records[r, 0], records[r, 1]
        s = (t - mid) / radius
        for ax in range(3):
            c = records[r, 2 + ax * ncoef: 2 + (ax + 1) * ncoef]
            pos[i, ax] = cheb.chebval(s, c)
            vel[i, ax] = cheb.chebval(s, cheb.chebder(c)) / radius
    return pos, vel


def test_spk_type2_roundtrip(tmp_path):
    rng = np.random.default_rng(42)
    ncoef, n_rec = 6, 4
    init, intlen = 1000.0, 864000.0  # 10-day records
    coeffs = [rng.normal(scale=1e5, size=(3, ncoef)) for _ in range(n_rec)]
    records = _type2_records(coeffs, init, intlen, n_rec)
    path = tmp_path / "t2.bsp"
    _write_spk(path, [dict(target=10, center=0, data_type=2,
                           init=init, intlen=intlen, records=records)])

    kern = SPKKernel(str(path))
    # epochs spread across all records, including ones near boundaries
    et = init + np.array([0.1, 0.9, 1.5, 2.2, 3.0, 3.97]) * intlen
    pos, vel = kern.posvel(10, 0, et)
    tp, tv = _truth_type2(records, init, intlen, et)
    np.testing.assert_allclose(pos, tp, rtol=1e-12)
    np.testing.assert_allclose(vel, tv, rtol=1e-12)


def test_spk_type3_roundtrip(tmp_path):
    """Type 3 carries explicit velocity coefficients."""
    rng = np.random.default_rng(3)
    ncoef, n_rec = 5, 2
    init, intlen = -500.0, 432000.0
    records = np.zeros((n_rec, 2 + 6 * ncoef))
    pos_c = rng.normal(scale=1e4, size=(n_rec, 3, ncoef))
    vel_c = rng.normal(scale=1.0, size=(n_rec, 3, ncoef))
    for r in range(n_rec):
        records[r, 0] = init + (r + 0.5) * intlen
        records[r, 1] = intlen / 2.0
        records[r, 2:2 + 3 * ncoef] = pos_c[r].ravel()
        records[r, 2 + 3 * ncoef:] = vel_c[r].ravel()
    path = tmp_path / "t3.bsp"
    _write_spk(path, [dict(target=301, center=3, data_type=3,
                           init=init, intlen=intlen, records=records)])

    kern = SPKKernel(str(path))
    et = init + np.array([0.25, 0.75, 1.4, 1.9]) * intlen
    pos, vel = kern.posvel(301, 3, et)
    idx = ((et - init) / intlen).astype(int)
    for i, (t, r) in enumerate(zip(et, idx)):
        s = (t - records[r, 0]) / records[r, 1]
        for ax in range(3):
            assert pos[i, ax] == pytest.approx(
                cheb.chebval(s, pos_c[r, ax]), rel=1e-12)
            assert vel[i, ax] == pytest.approx(
                cheb.chebval(s, vel_c[r, ax]), rel=1e-12)


def test_spk_rejects_non_spk_file(tmp_path):
    path = tmp_path / "junk.bsp"
    path.write_bytes(b"NOT A DAF" + b"\0" * 2000)
    with pytest.raises(ValueError, match="not an SPK"):
        SPKKernel(str(path))


def test_spk_missing_segment_raises(tmp_path):
    records = _type2_records([np.ones((3, 3))], 0.0, 86400.0, 1)
    path = tmp_path / "one.bsp"
    _write_spk(path, [dict(target=10, center=0, data_type=2,
                           init=0.0, intlen=86400.0, records=records)])
    kern = SPKKernel(str(path))
    with pytest.raises(KeyError, match="no SPK segment"):
        kern.segment_for(5, 0)


def test_ephemeris_provider_switches_with_kernel(tmp_path, monkeypatch):
    """Drop a .bsp in $PINT_TPU_EPHEM_DIR -> provider flips
    analytic->spk and Earth posvel comes from the kernel chain."""
    import pint_tpu.ephemeris as eph

    # earth wrt SSB = (EMB wrt SSB) + (earth wrt EMB): two segments
    init, intlen = 0.0, 86400.0 * 32
    n_rec = 3
    rng = np.random.default_rng(7)
    emb_c = [rng.normal(scale=1e7, size=(3, 4)) for _ in range(n_rec)]
    geo_c = [rng.normal(scale=1e3, size=(3, 4)) for _ in range(n_rec)]
    emb_rec = _type2_records(emb_c, init, intlen, n_rec)
    geo_rec = _type2_records(geo_c, init, intlen, n_rec)
    _write_spk(tmp_path / "detest.bsp", [
        dict(target=3, center=0, data_type=2, init=init, intlen=intlen,
             records=emb_rec),
        dict(target=399, center=3, data_type=2, init=init, intlen=intlen,
             records=geo_rec),
    ])
    monkeypatch.setenv("PINT_TPU_EPHEM_DIR", str(tmp_path))
    monkeypatch.setattr(eph, "_KERNELS", {})

    assert eph.ephemeris_provider("detest") == "spk"
    # with the shipped numeph kernel out of the way, a missing name
    # falls all the way back to the analytic tier
    monkeypatch.setenv("PINT_TPU_DISABLE_NUMEPH", "1")
    assert eph.ephemeris_provider("detest_missing") == "analytic"
    monkeypatch.delenv("PINT_TPU_DISABLE_NUMEPH")

    # TDB epochs inside the segment span (ET from J2000 epoch)
    day = np.array([51544, 51560], dtype=np.int64)
    sec = np.array([43200.0, 2000.0])
    t = Epochs(day, sec, "tdb")
    pv = eph.objPosVel_wrt_SSB("earth", t, "detest")

    from pint_tpu.io.spk import tdb_epochs_to_et

    et = tdb_epochs_to_et(t.day, t.sec)
    p1, v1 = _truth_type2(emb_rec, init, intlen, et)
    p2, v2 = _truth_type2(geo_rec, init, intlen, et)
    np.testing.assert_allclose(pv.pos, (p1 + p2) * 1e3, rtol=1e-12)
    np.testing.assert_allclose(pv.vel, (v1 + v2) * 1e3, rtol=1e-12)


# ---------------------------------------------------------------------------
# IERS finals2000A
# ---------------------------------------------------------------------------

def _finals_line(mjd, x_arcsec, y_arcsec, dut1_s):
    """One Bulletin-A-format line with the columns eop.py reads:
    [7:15] MJD, [18:27] x, [37:46] y, [58:68] UT1-UTC."""
    line = [" "] * 80
    line[7:15] = f"{mjd:8.2f}"
    line[16] = "I"
    line[18:27] = f"{x_arcsec:9.6f}"
    line[27:36] = f"{0.000009:9.6f}"
    line[37:46] = f"{y_arcsec:9.6f}"
    line[46:55] = f"{0.000009:9.6f}"
    line[57] = "I"
    line[58:68] = f"{dut1_s:10.7f}"
    return "".join(line)


def test_eop_finals2000a_parse_and_interp(tmp_path):
    from pint_tpu.constants import ARCSEC_TO_RAD
    from pint_tpu.earth.eop import EOPTable

    mjds = np.arange(58000, 58010)
    dut = 0.1 + 0.01 * (mjds - 58000)          # linear ramp, seconds
    px = 0.05 + 0.002 * (mjds - 58000)         # arcsec
    py = -0.2 + 0.001 * (mjds - 58000)
    lines = [_finals_line(m, x, y, d)
             for m, x, y, d in zip(mjds, px, py, dut)]
    # header-ish junk and a short line must be skipped, not crash
    content = "garbage header\nshort\n" + "\n".join(lines) + "\n"
    path = tmp_path / "finals2000A.all"
    path.write_text(content)

    tab = EOPTable.from_finals2000a(str(path))
    assert len(tab.mjd) == 10

    # interpolation at a half-day point hits the linear-ramp truth
    t = Epochs(np.array([58004], dtype=np.int64), np.array([43200.0]), "utc")
    assert tab.ut1_minus_utc(t)[0] == pytest.approx(0.1 + 0.01 * 4.5, abs=1e-12)
    xp, yp = tab.polar_motion(t)
    assert xp[0] == pytest.approx((0.05 + 0.002 * 4.5) * ARCSEC_TO_RAD,
                                  rel=1e-12)
    assert yp[0] == pytest.approx((-0.2 + 0.001 * 4.5) * ARCSEC_TO_RAD,
                                  rel=1e-12)


def test_eop_empty_file_raises(tmp_path):
    from pint_tpu.earth.eop import EOPTable

    path = tmp_path / "empty.all"
    path.write_text("no data here\n")
    with pytest.raises(ValueError, match="no EOP rows"):
        EOPTable.from_finals2000a(str(path))


def test_eop_upgrade_reaches_rotation_chain(tmp_path, monkeypatch):
    """$PINT_TPU_EOP_FILE upgrades gcrs_posvel_from_itrf transparently:
    a 0.3 s UT1-UTC offset rotates the site by ~omega*dt."""
    from pint_tpu.earth import gcrs_posvel_from_itrf
    from pint_tpu.earth.erfa_lite import OMEGA_EARTH
    from pint_tpu.earth import eop as eop_mod

    dut1 = 0.3
    mjds = np.arange(58000, 58010)
    lines = [_finals_line(m, 0.0, 0.0, dut1) for m in mjds]
    path = tmp_path / "finals2000A.all"
    path.write_text("\n".join(lines) + "\n")

    xyz = np.array([882589.65, -4924872.32, 3943729.348])  # GBT ITRF
    t = Epochs(np.array([58004], dtype=np.int64), np.array([43200.0]), "utc")

    monkeypatch.delenv("PINT_TPU_EOP_FILE", raising=False)
    eop_mod.reset_eop_discovery()
    pos0, _ = gcrs_posvel_from_itrf(xyz, t)
    assert eop_mod.get_eop_table() is None  # fallback tier: no data found

    monkeypatch.setenv("PINT_TPU_EOP_FILE", str(path))
    eop_mod.reset_eop_discovery()
    try:
        assert eop_mod.get_eop_table() is not None  # tier upgraded
        pos1, _ = gcrs_posvel_from_itrf(xyz, t)
        shift = np.linalg.norm(pos1 - pos0)
        r_equatorial = np.linalg.norm(xyz[:2])
        expect = OMEGA_EARTH * dut1 * r_equatorial
        assert shift == pytest.approx(expect, rel=1e-3)

        # explicit disable sticks: no silent re-discovery
        eop_mod.set_eop_table(None)
        assert eop_mod.get_eop_table() is None
        pos2, _ = gcrs_posvel_from_itrf(xyz, t)
        np.testing.assert_allclose(pos2, pos0, rtol=0, atol=1e-9)
    finally:
        eop_mod.reset_eop_discovery()  # don't leak into other tests


# ---------------------------------------------------------------------------
# clock files
# ---------------------------------------------------------------------------

def test_clock_tempo2_roundtrip(tmp_path):
    from pint_tpu.observatory.clock_file import ClockFile

    path = tmp_path / "site2utc.clk"
    path.write_text(
        "# UTC(site) UTC\n"
        "# comment line\n"
        "50000.0 1.0e-6\n"
        "50010.0 3.0e-6\n"
        "50020.0 2.0e-6\n")
    cf = ClockFile.read_tempo2(str(path))
    assert len(cf.mjd) == 3
    t = Epochs(np.array([50005], dtype=np.int64), np.array([0.0]), "utc")
    assert cf.evaluate(t)[0] == pytest.approx(2.0e-6, rel=1e-12)


def test_clock_tempo_roundtrip(tmp_path):
    """TEMPO time.dat: offsets in microseconds, comment markers."""
    from pint_tpu.observatory.clock_file import ClockFile

    path = tmp_path / "time_xyz.dat"
    path.write_text(
        "# TEMPO-format site clock\n"
        "C  old-style comment\n"
        "  50000.00  50000.50   1.50  0.00  gbt\n"
        "  50010.00  50010.50   3.50  0.00  gbt\n")
    cf = ClockFile.read_tempo(str(path))
    assert len(cf.mjd) == 2
    t = Epochs(np.array([50005], dtype=np.int64), np.array([0.0]), "utc")
    # 1.5 us at 50000 -> 3.5 us at 50010, linear: 2.5 us at midpoint
    assert cf.evaluate(t)[0] == pytest.approx(2.5e-6, rel=1e-12)


def test_clock_out_of_range_policy(tmp_path):
    from pint_tpu.observatory.clock_file import ClockFile

    cf = ClockFile([50000.0, 50010.0], [1e-6, 2e-6], name="rangetest")
    t = Epochs(np.array([51000], dtype=np.int64), np.array([0.0]), "utc")
    with pytest.warns(UserWarning, match="outside range"):
        cf.evaluate(t, limits="warn")
    with pytest.raises(RuntimeError, match="outside range"):
        cf.evaluate(t, limits="error")


def test_clock_chain_upgrade_reaches_observatory(tmp_path, monkeypatch):
    """Drop site + GPS files into $PINT_TPU_CLOCK_DIR -> the
    observatory's clock chain switches from zero to the file values."""
    import pint_tpu.observatory as obs_mod
    from pint_tpu.observatory import get_observatory
    from pint_tpu.observatory import clock_file as cfmod

    (tmp_path / "time_gbt.dat").write_text(
        "  50000.00  50000.50   2.00  0.00  gbt\n"
        "  51000.00  51000.50   4.00  0.00  gbt\n")
    (tmp_path / "gps2utc.clk").write_text(
        "# GPS to UTC\n"
        "50000.0 1.0e-7\n"
        "51000.0 1.0e-7\n")
    monkeypatch.setenv("PINT_TPU_CLOCK_DIR", str(tmp_path))
    monkeypatch.setattr(cfmod, "_cache", {})

    gbt = get_observatory("gbt")
    monkeypatch.setattr(gbt, "_clock", None)  # drop any cached (empty) chain
    t = Epochs(np.array([50500], dtype=np.int64), np.array([43200.0]), "utc")
    corr = gbt.clock_corrections(t, include_bipm=False)
    # site: linear 2 us -> 4 us over MJD [50000, 51000] (col 0 is the
    # MJD the parser keys on): 3.001 us at 50500.5
    site_truth = (2.0 + 2.0 * (50500.5 - 50000.0) / 1000.0) * 1e-6
    assert corr[0] == pytest.approx(site_truth + 1.0e-7, rel=1e-6)

    # without the env dir (cache cleared) the chain degrades to GPS-less zero
    monkeypatch.delenv("PINT_TPU_CLOCK_DIR")
    monkeypatch.setattr(cfmod, "_cache", {})
    monkeypatch.setattr(gbt, "_clock", None)
    monkeypatch.setattr(gbt, "_warned", False)
    with pytest.warns(UserWarning, match="no clock files"):
        corr0 = gbt.clock_corrections(t, include_bipm=False)
    assert corr0[0] == 0.0


def test_spk_writer_reader_roundtrip(tmp_path):
    """io/spk_write.py::write_spk_type2 -> io/spk.py::SPKKernel: known
    Chebyshev coefficients come back bit-exactly through the real DAF
    container (summary chain, word addressing, trailer), for multiple
    segments in one file — the writer behind the shipped numeph kernel,
    proven directly."""
    from pint_tpu.io.spk_write import write_spk_type2

    rng = np.random.default_rng(11)
    init, intlen = -1000.0 * 86400.0, 16.0 * 86400.0
    segs = []
    truth = {}
    for (tgt, ctr, n_rec, ncoef) in ((3, 0, 5, 7), (399, 3, 8, 5),
                                     (10, 0, 3, 9)):
        coeffs = rng.normal(scale=1e6, size=(n_rec, 3, ncoef))
        segs.append({"target": tgt, "center": ctr, "init_et": init,
                     "intlen_s": intlen, "coeffs": coeffs})
        truth[(tgt, ctr)] = coeffs
    path = tmp_path / "w.bsp"
    write_spk_type2(str(path), segs)

    kern = SPKKernel(str(path))
    assert len(kern.segments) == 3
    for (tgt, ctr), coeffs in truth.items():
        seg = kern.segment_for(tgt, ctr)
        assert seg.data_type == 2
        assert seg.init == init and seg.intlen == intlen
        assert seg.n_records == coeffs.shape[0]
        # evaluate off-node epochs in several records; compare to a
        # direct Chebyshev evaluation of the source coefficients
        for r in (0, coeffs.shape[0] // 2, coeffs.shape[0] - 1):
            s = 0.37
            et = init + (r + (s + 1) / 2) * intlen
            pos, vel = kern.posvel(tgt, ctr, np.array([et]))
            for ax in range(3):
                want = cheb.chebval(s, coeffs[r, ax])
                assert pos[0, ax] == pytest.approx(want, rel=1e-13)
                dwant = cheb.chebval(s, cheb.chebder(coeffs[r, ax])) \
                    / (intlen / 2)
                assert vel[0, ax] == pytest.approx(dwant, rel=1e-12)
    # coverage bookkeeping: summary ET range matches the record grid
    for (tgt, ctr), coeffs in truth.items():
        seg = kern.segment_for(tgt, ctr)
        assert seg.start_et == init
        assert seg.end_et == init + coeffs.shape[0] * intlen


# ---------------------------------------------------------------------------
# ALL tiers at once: the upgrade story must COMPOSE (VERDICT r3 item 5)
# ---------------------------------------------------------------------------

def test_all_tiers_upgrade_end_to_end(tmp_path, monkeypatch):
    """Synthesize reference-grade data for EVERY offline-degraded tier
    at once — a DE-style SPK kernel with a known injected Earth-orbit
    perturbation (io/spk_write.py), an IERS finals2000A file with a
    known UT1-UTC, site+GPS+BIPM clock files with a known step — then
    run the full par+tim -> TOAs -> residuals -> fit pipeline and
    assert each injected signal is recovered END TO END, not just
    parsed. (reference: SURVEY section 4 patterns 1+6 — upstream pins
    this with real DE/IERS/clock data in tests/datafile/; offline, the
    synthetic-injection equivalent is the strongest available form.)
    """
    import pint_tpu.ephemeris as eph
    from pint_tpu.earth import eop as eop_mod
    from pint_tpu.io.spk_write import write_spk_type2
    from pint_tpu.models import get_model
    from pint_tpu.observatory import clock_file as cfmod
    from pint_tpu.observatory import get_observatory
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_fromMJDs
    from pint_tpu.toa import get_TOAs
    from pint_tpu.constants import SECS_PER_DAY

    C_KM_S = 299792.458
    par = ("PSR COMPOSE1\nRAJ 06:30:00.0\nDECJ 15:30:00.0\n"
           "F0 312.5 1\nF1 -2e-15 1\nPEPOCH 55050\nPOSEPOCH 55050\n"
           "DM 21.3 1\nEPHEM compose\n")
    m = get_model(par)

    # --- synthesize the tim file (baseline physics: no data tiers) ---
    rng = np.random.default_rng(3)
    mjds = np.sort(rng.uniform(55001.0, 55099.0, 60))
    t0 = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, obs="gbt",
                                 add_noise=True, seed=3, iterations=2)
    tim = tmp_path / "compose.tim"
    lines = ["FORMAT 1"]
    for i in range(len(t0)):
        frac = int(round(t0.sec[i] / SECS_PER_DAY * 1e13))
        lines.append(f" fake {t0.freq_mhz[i]:.6f} "
                     f"{t0.day[i]}.{frac:013d} 1.000 gbt")
    tim.write_text("\n".join(lines) + "\n")
    parfile = tmp_path / "compose.par"
    parfile.write_text(par)

    def load():
        return get_TOAs(str(tim), model=get_model(str(parfile)),
                        usepickle=False)

    def resids(t):
        return np.asarray(Residuals(t, get_model(str(parfile))).time_resids)

    # pulsar unit vector (RAJ 6:30 -> 97.5 deg, DECJ 15:30)
    ra, dec = np.radians(97.5), np.radians(15.5)
    nhat = np.array([np.cos(dec) * np.cos(ra), np.cos(dec) * np.sin(ra),
                     np.sin(dec)])

    # --- baseline: every tier in its no-data fallback state ---
    monkeypatch.delenv("PINT_TPU_EPHEM_DIR", raising=False)
    monkeypatch.delenv("PINT_TPU_EOP_FILE", raising=False)
    monkeypatch.delenv("PINT_TPU_CLOCK_DIR", raising=False)
    monkeypatch.setattr(eph, "_KERNELS", {})
    monkeypatch.setattr(cfmod, "_cache", {})
    eop_mod.reset_eop_discovery()
    gbt = get_observatory("gbt")
    monkeypatch.setattr(gbt, "_clock", None)
    monkeypatch.setattr(gbt, "_warned", True)
    t_base = load()
    r_base = resids(t_base)
    pos_base = t_base.ssb_obs.pos.copy()
    clk_base = t_base.clock_corr_s.copy()

    # --- tier 1: SPK kernel = package Earth/Sun + injected 30 km
    # periodic Earth-orbit perturbation at 2pi/20d ---
    span_lo, span_hi = 54995.0, 55105.0
    from pint_tpu.io.spk import tdb_epochs_to_et
    from pint_tpu.ephemeris import objPosVel_wrt_SSB

    intlen_d, ncoef = 5.0, 13
    n_rec = int(np.ceil((span_hi - span_lo) / intlen_d))
    init_et = tdb_epochs_to_et(np.array([int(span_lo)]),
                               np.array([(span_lo % 1) * SECS_PER_DAY]))[0]
    nodes = np.cos(np.pi * (np.arange(ncoef) + 0.5) / ncoef)  # cheb pts
    A_KM = 30.0
    w_rad_per_day = 2 * np.pi / 20.0

    def perturb(mjd_arr):
        ph = w_rad_per_day * (mjd_arr - 55000.0)
        return A_KM * np.stack([np.sin(ph), np.cos(ph),
                                np.zeros_like(ph)], axis=-1)

    segs = []
    for tgt, ctr, body, pert in ((3, 0, "earth", True), (399, 3, None, False),
                                 (10, 0, "sun", False)):
        coeffs = np.zeros((n_rec, 3, ncoef))
        for r in range(n_rec):
            lo = span_lo + r * intlen_d
            mjd_nodes = lo + (nodes + 1) / 2 * intlen_d
            ep = Epochs(mjd_nodes.astype(np.int64),
                        (mjd_nodes % 1.0) * SECS_PER_DAY, "tdb")
            if body is None:
                pos_km = np.zeros((ncoef, 3))
            else:
                pos_km = objPosVel_wrt_SSB(body, ep).pos / 1e3
                if pert:
                    pos_km = pos_km + perturb(mjd_nodes)
            # Chebyshev fit on the nodes (exact interpolation)
            V = np.polynomial.chebyshev.chebvander(nodes, ncoef - 1)
            coeffs[r] = np.linalg.solve(V, pos_km).T
        segs.append({"target": tgt, "center": ctr, "init_et": init_et,
                     "intlen_s": intlen_d * SECS_PER_DAY, "coeffs": coeffs})
    write_spk_type2(str(tmp_path / "compose.bsp"), segs)
    monkeypatch.setenv("PINT_TPU_EPHEM_DIR", str(tmp_path))
    monkeypatch.setattr(eph, "_KERNELS", {})

    t_spk = load()
    assert t_spk.ephem_provider == "spk"  # tier actually switched
    # injected orbit perturbation appears in ssb_obs verbatim
    dpos_km = (t_spk.ssb_obs.pos - pos_base) / 1e3
    mjd_f = t_spk.day + t_spk.sec / SECS_PER_DAY
    expect_km = perturb(mjd_f)
    assert np.abs(dpos_km - expect_km).max() < 1.0  # cheb fit + tier delta
    # ... and in the residuals as the predicted Roemer signature
    r_spk = resids(t_spk)
    dr = r_spk - r_base
    delay_s = (expect_km @ nhat) / C_KM_S
    w = 1.0 / np.asarray(t_spk.error_us) ** 2
    for sign in (+1.0, -1.0):
        pred = sign * (delay_s - np.sum(w * delay_s) / np.sum(w))
        if np.abs(dr - pred).max() < 0.15e-6:
            break
    else:
        raise AssertionError(
            f"ephemeris signature not recovered: max dev "
            f"{np.abs(dr - pred).max():.3g}s vs amplitude "
            f"{np.abs(delay_s).max():.3g}s")

    # --- tier 2: EOP (UT1-UTC = 0.4 s) on top of the kernel ---
    dut1 = 0.4
    eop_lines = [_finals_line(mjd, 0.0, 0.0, dut1)
                 for mjd in range(54995, 55106)]
    (tmp_path / "finals2000A.all").write_text("\n".join(eop_lines) + "\n")
    monkeypatch.setenv("PINT_TPU_EOP_FILE",
                       str(tmp_path / "finals2000A.all"))
    eop_mod.reset_eop_discovery()
    try:
        t_eop = load()
        # site rotated by ~omega * dut1 * r_equatorial through the FULL
        # pipeline (not just the unit-level chain test)
        from pint_tpu.earth.erfa_lite import OMEGA_EARTH

        shift = np.linalg.norm(t_eop.ssb_obs.pos - t_spk.ssb_obs.pos, axis=1)
        r_eq = np.linalg.norm(
            np.array([882589.65, -4924872.32, 3943729.348])[:2])
        expect_shift = OMEGA_EARTH * dut1 * r_eq
        np.testing.assert_allclose(shift, expect_shift, rtol=2e-3)
        # residual change follows the predicted per-TOA Roemer delta
        r_eop = resids(t_eop)
        delay2 = ((t_eop.ssb_obs.pos - t_spk.ssb_obs.pos) @ nhat) / 299792458.0
        for sign in (+1.0, -1.0):
            pred2 = sign * (delay2 - np.sum(w * delay2) / np.sum(w))
            if np.abs((r_eop - r_spk) - pred2).max() < 30e-9:
                break
        else:
            raise AssertionError("EOP residual signature not recovered")

        # --- tier 3: clock chain (site step + GPS + BIPM) on top ---
        (tmp_path / "time_gbt.dat").write_text(
            "  54995.00  54995.50   0.00  0.00  gbt\n"
            "  55050.00  55050.50   0.00  0.00  gbt\n"
            "  55050.01  55050.51  10.00  0.00  gbt\n"
            "  55106.00  55106.50  10.00  0.00  gbt\n")
        (tmp_path / "gps2utc.clk").write_text(
            "# GPS to UTC\n54995.0 2.0e-7\n55106.0 2.0e-7\n")
        (tmp_path / "tai2tt_bipm2019.clk").write_text(
            "# TAI to TT(BIPM2019)\n54995.0 32.1840276\n55106.0 32.1840276\n")
        monkeypatch.setenv("PINT_TPU_CLOCK_DIR", str(tmp_path))
        monkeypatch.setattr(cfmod, "_cache", {})
        monkeypatch.setattr(gbt, "_clock", None)
        t_clk = load()
        dclk = t_clk.clock_corr_s - clk_base
        late = mjd_f > 55050.5
        # site step (10 us) + GPS (0.2 us) + BIPM (27.6 us) compose
        np.testing.assert_allclose(dclk[~late], 27.6e-6 + 0.2e-6, atol=2e-8)
        np.testing.assert_allclose(dclk[late], 37.6e-6 + 0.2e-6, atol=2e-8)
        # the step (minus weighted mean) is what residuals can see:
        # late-minus-early group offset recovers the 10 us injection
        r_clk = resids(t_clk)
        dr_clk = r_clk - r_eop
        step = dr_clk[late].mean() - dr_clk[~late].mean()
        assert abs(abs(step) - 10e-6) < 0.3e-6, step

        # --- every tier on: the full pipeline still fits green ---
        from pint_tpu.fitter import WLSFitter

        f = WLSFitter(t_clk, get_model(str(parfile)))
        f.fit_toas()
        assert np.isfinite(float(f.resids.chi2))
        for p in f.model.free_params:
            assert np.isfinite(getattr(f.model, p).value)
            assert np.isfinite(getattr(f.model, p).uncertainty or 1.0)
    finally:
        eop_mod.reset_eop_discovery()
