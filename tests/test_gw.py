"""GW detection subsystem (pint_tpu/gw/): Hellings–Downs optimal
statistic over fleet fit outputs.

Pinned contracts:
- batched pair-block sweep == sequential per-pair reference <= 1e-12
  on the 68-pulsar injected fixture (the f64 jnp path);
- the Pallas pair kernel (interpret mode on CPU) matches the jnp
  reference to f32 accuracy;
- the injected-GWB optimal statistic recovers the injected amplitude
  and the HD template beats the monopole/dipole alternatives;
- seeded scramble nulls are bit-reproducible ([seed, draw] rng idiom);
- PTAFleet.gw_stage runs end to end on regular AND packed-plan
  layouts and the two agree;
- the pair-coherence census reaches the FitQualityLedger and the
  gw_coherence SLO;
- BayesianTiming.lnposterior is finite and vmaps over a walker batch
  (the dormant-module wake-up smoke).
"""

import sys

import numpy as np
import pytest

sys.path.insert(0, "/root/repo")

from pint_tpu import gw
from pint_tpu.gw.hd import isotropic_positions
from pint_tpu.kernels import (pair_products, pair_products_jnp,
                              pair_products_pallas)


def _injected(P=68, M=64, amp=0.5, seed=0):
    pos = isotropic_positions(P, seed=seed)
    return gw.inject_gwb(pos, M, amp, seed=seed)


# -- HD curve ----------------------------------------------------------


def test_hd_curve_known_values():
    # coincident distinct pulsars -> 1/2 (the x -> 0 limit)
    assert gw.hd_curve(1.0) == pytest.approx(0.5)
    # 90 degrees: 0.75*ln(1/2) + 0.375
    assert gw.hd_curve(0.0) == pytest.approx(
        0.75 * np.log(0.5) + 0.375)
    # antipodal: x = 1 -> -1/4 + 1/2
    assert gw.hd_curve(-1.0) == pytest.approx(0.25)
    # vectorized + finite everywhere including the endpoint
    c = np.linspace(-1, 1, 101)
    assert np.all(np.isfinite(gw.hd_curve(c)))


# -- pair kernel + sweep -----------------------------------------------


def test_pair_products_pallas_matches_jnp(pallas_interpret):
    rng = np.random.default_rng(11)
    ua, wa = rng.standard_normal((13, 37)), rng.uniform(0.5, 2, (13, 37))
    ub, wb = rng.standard_normal((21, 37)), rng.uniform(0.5, 2, (21, 37))
    n_ref, d_ref = pair_products_jnp(ua, wa, ub, wb)
    n_pl, d_pl = pair_products_pallas(ua, wa, ub, wb, tile=8,
                                      interpret=pallas_interpret)
    np.testing.assert_allclose(np.asarray(n_pl), np.asarray(n_ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(d_pl), np.asarray(d_ref),
                               rtol=2e-5, atol=2e-5)
    # the dispatcher routes precision="mixed" through the kernel here
    n_mx, _ = pair_products(ua, wa, ub, wb, precision="mixed",
                            interpret=pallas_interpret)
    np.testing.assert_array_equal(np.asarray(n_mx), np.asarray(n_pl))


def test_batched_sweep_matches_sequential_reference_1e12():
    # 68-pulsar fixture; block=13 forces off-diagonal tiles, partial
    # edge tiles, and diagonal-tile triangle masking all at once
    lat = _injected()
    num, den, stats = gw.correlation_matrix(lat.z, lat.w, block=13)
    assert stats["n_pairs"] == 68 * 67 // 2
    u = lat.w * lat.z
    for a in range(lat.n_pulsars):
        for b in range(lat.n_pulsars):
            if a < b:
                np.testing.assert_allclose(
                    num[a, b], float(u[a] @ u[b]),
                    rtol=1e-12, atol=1e-12)
                np.testing.assert_allclose(
                    den[a, b], float(lat.w[a] @ lat.w[b]),
                    rtol=1e-12, atol=1e-12)
            else:
                assert num[a, b] == 0.0 and den[a, b] == 0.0


def test_sweep_block_size_invariance():
    lat = _injected(P=17, M=32)
    ref = gw.correlation_matrix(lat.z, lat.w, block=1000)[0]
    for block in (3, 8, 17):
        got = gw.correlation_matrix(lat.z, lat.w, block=block)[0]
        np.testing.assert_allclose(got, ref, rtol=1e-13, atol=1e-13)


# -- optimal statistic on the injected fixture -------------------------


def test_injected_os_recovers_amplitude_and_hd_wins():
    amp = 0.5
    lat = _injected(amp=amp)
    os_hd = gw.optimal_statistic(lat, orf="hd")
    assert os_hd["amp2"] is not None and os_hd["amp2"] > 0
    # seed-pinned recovery: sqrt(amp2) within a factor 2 of injected
    assert 0.5 * amp < np.sqrt(os_hd["amp2"]) < 2.0 * amp
    assert os_hd["snr"] > 5.0
    # the HD template must beat the boring explanations on HD data
    os_mono = gw.optimal_statistic(lat, orf="monopole")
    os_dip = gw.optimal_statistic(lat, orf="dipole")
    assert os_hd["snr"] > abs(os_mono["snr"])
    assert os_hd["snr"] > abs(os_dip["snr"])
    assert os_hd["n_pairs"] == 68 * 67 // 2


def test_zero_injection_null_scrambles_bit_reproducible():
    lat = _injected(amp=0.0, seed=4)
    a = gw.scramble_null(lat, n_draws=12, seed=9, mode="sky")
    b = gw.scramble_null(lat, n_draws=12, seed=9, mode="sky")
    np.testing.assert_array_equal(a["snr_null"], b["snr_null"])
    assert a["p_value"] == b["p_value"]
    # a different seed must give a different null draw set
    c = gw.scramble_null(lat, n_draws=12, seed=10, mode="sky")
    assert not np.array_equal(a["snr_null"], c["snr_null"])
    # zero injection: the observed S/N should be unremarkable
    assert a["p_value"] > 0.05


def test_phase_scramble_mode_reproducible():
    lat = _injected(P=12, M=48, amp=0.0, seed=2)
    a = gw.scramble_null(lat, n_draws=5, seed=1, mode="phase")
    b = gw.scramble_null(lat, n_draws=5, seed=1, mode="phase")
    np.testing.assert_array_equal(a["snr_null"], b["snr_null"])
    assert a["n_draws"] == 5 and a["mode"] == "phase"


def test_sky_scramble_draw_never_regenerates_true_sky():
    # isotropic_positions and scramble draw d share `seed` but use
    # distinct rng sub-streams; a collision would plant the observed
    # statistic inside its own null (seen live before the key split)
    seed, P = 0, 31
    pos = isotropic_positions(P, seed=seed)
    for d in range(8):
        rng = np.random.default_rng([seed, d])
        v = rng.standard_normal((P, 3))
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        assert not np.allclose(v, pos)


# -- fleet integration -------------------------------------------------


def test_fleet_gw_stage_regular_and_packed_agree():
    from bench import build_batch
    from pint_tpu.parallel.pta import PTAFleet

    models, toas = build_batch(8, 40, noise=True, seed=0)
    fleet = PTAFleet(models, toas)
    out = fleet.gw_stage(maxiter=2, lattice_days=60.0, n_scrambles=4,
                         seed=3)
    assert out["n_pulsars"] == 8
    # the sweep visits every unordered pair; the OS keeps those with
    # lattice overlap (den > 0) — a strict subset on sparse cadences
    assert out["sweep"]["n_pairs"] == 8 * 7 // 2
    assert 0 < out["n_pairs"] <= 8 * 7 // 2
    assert out["amp2"] is not None and np.isfinite(out["amp2"])
    assert out["null"]["n_draws"] == 4
    assert 0.0 < out["null"]["p_value"] <= 1.0
    # the packed-plan layout must reproduce the same statistic
    packed = PTAFleet(models, toas, toa_bucket="plan",
                      plan_quantum=16)
    out_p = packed.gw_stage(maxiter=2, lattice_days=60.0)
    assert any(getattr(b, "_pack", None)
               for b in (packed._resolve(k)
                         for k in packed.group_indices))
    np.testing.assert_allclose(out_p["amp2"], out["amp2"], rtol=1e-9)
    np.testing.assert_allclose(out_p["snr"], out["snr"], rtol=1e-9)


def test_gw_arrays_matches_time_residuals_at_start_vector():
    from bench import build_batch
    from pint_tpu.parallel.pta import PTAFleet

    models, toas = build_batch(3, 24, noise=False, seed=1)
    fleet = PTAFleet(models, toas)
    (key,) = fleet.group_indices
    batch = fleet._resolve(key)
    import jax

    x0 = np.asarray(jax.device_get(batch._x0()))
    arrays = batch.gw_arrays(x0)
    r_ref, mask = batch.time_residuals()
    r_ref = np.asarray(jax.device_get(r_ref))
    np.testing.assert_allclose(arrays["resid"][arrays["mask"]],
                               r_ref[np.asarray(mask)],
                               rtol=0, atol=1e-15)
    # TOAs are MJD-ordered and within the simulated span
    for i in range(3):
        t = arrays["mjd"][i][arrays["mask"][i]]
        assert np.all(np.diff(t) >= 0)
        assert t.min() > 53000 and t.max() < 58000


def test_sky_positions_equatorial_unit_vectors():
    from pint_tpu.models import get_model

    m = get_model("PSR T1\nRAJ 06:00:00.0\nDECJ 30:00:00.0\n"
                  "F0 100.0 1\nPEPOCH 55500\nDM 10.0\n")
    (v,) = gw.sky_positions([m])
    assert np.linalg.norm(v) == pytest.approx(1.0)
    # RA 6h = 90 deg, DEC +30 deg
    np.testing.assert_allclose(
        v, [0.0, np.cos(np.pi / 6), 0.5], atol=1e-12)


# -- coherence ledger / SLO (satellite) --------------------------------


def test_pair_coherence_reaches_ledger_and_slo():
    from pint_tpu.obs import fitquality as obs_fitq
    from pint_tpu.obs.fitquality import fit_quality_slos

    lat = _injected(P=10, M=32, amp=3.0, seed=7)
    obs_fitq.reset()
    obs_fitq.enable()
    try:
        # a tiny z-limit makes the strongly-injected pairs incoherent
        gw.optimal_statistic(lat, z_limit=0.5)
        snap = obs_fitq.FITQ.snapshot()
    finally:
        obs_fitq.disable()
        obs_fitq.reset()
    assert snap["counters"]["pairs_probed"] == 10 * 9 // 2
    assert snap["counters"]["pairs_incoherent"] > 0
    assert snap["max_pair_snr"] > 0.5
    spec = {s.name: s for s in fit_quality_slos()}["gw_coherence"]
    assert spec.bad(snap) == snap["counters"]["pairs_incoherent"]
    assert spec.total(snap) == snap["counters"]["pairs_probed"]


def test_ledger_state_roundtrip_with_pair_fields():
    from pint_tpu.obs.fitquality import FitQualityLedger

    led = FitQualityLedger()
    led.note_pair_coherence(100, 3, 5.5)
    fresh = FitQualityLedger()
    fresh.load_state_dict(led.state_dict())
    assert fresh.pairs_probed == 100
    assert fresh.pairs_incoherent == 3
    assert fresh.max_pair_snr == 5.5
    # legacy (pre-gw) v1 state still loads: fields default to zero
    legacy = led.state_dict()
    legacy["counters"] = {"fits": 2}
    legacy.pop("max_pair_snr")
    fresh2 = FitQualityLedger()
    fresh2.load_state_dict(legacy)
    assert fresh2.pairs_probed == 0 and fresh2.max_pair_snr is None


# -- CLI ---------------------------------------------------------------


def test_gw_cli_json(capsys):
    import json

    from pint_tpu.gw.__main__ import main

    assert main(["--pulsars", "16", "--cells", "32",
                 "--amplitude", "0.7", "--scrambles", "4"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["hd"]["amp2"] is not None
    assert out["null"]["n_draws"] == 4
    assert out["recovered_amplitude"] is not None


# -- bayesian wake-up (satellite) --------------------------------------


def test_bayesian_lnposterior_finite_and_vmaps():
    import jax
    import jax.numpy as jnp

    from pint_tpu.bayesian import BayesianTiming
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    m = get_model("PSR BAY1\nRAJ 05:00:00.0\nDECJ -10:30:00.0\n"
                  "F0 250.318 1\nF1 -3e-16 1\nPEPOCH 55500\n"
                  "DM 12.4 1\n")
    mjds = np.sort(np.random.default_rng([0, 0, 3]).uniform(
        54500, 56500, 40))
    toas = make_fake_toas_fromMJDs(mjds, m, error_us=1.0,
                                   freq_mhz=1400.0, obs="gbt",
                                   add_noise=True, seed=5,
                                   iterations=0)
    prior_info = {"F0": {"min": 250.3, "max": 250.34},
                  "F1": {"min": -1e-15, "max": 0.0},
                  "DM": {"min": 11.0, "max": 14.0}}
    bt = BayesianTiming(m, toas, prior_info=prior_info)
    assert bt.nparams == 3
    x0 = bt.initial_position()
    lp0 = float(bt.lnposterior(jnp.asarray(x0)))
    assert np.isfinite(lp0)
    # walker batch: seeded ball around x0 with per-parameter scales
    # small against each prior box, vmapped in one call
    rng = np.random.default_rng([0, 0, 4])
    scales = np.array([1e-6, 1e-17, 1e-3])  # F0 (Hz), F1 (s^-2), DM
    walkers = x0 + scales * rng.standard_normal((6, 3))
    lps = np.asarray(jax.vmap(bt.lnposterior)(jnp.asarray(walkers)))
    assert lps.shape == (6,)
    assert np.all(np.isfinite(lps))
    # outside the prior box the posterior is exactly -inf, vmap-safely
    bad = x0.copy()
    bad[2] = 99.0
    both = np.stack([x0, bad])
    lp_both = np.asarray(jax.vmap(bt.lnposterior)(jnp.asarray(both)))
    assert np.isfinite(lp_both[0]) and lp_both[1] == -np.inf
