"""Noise model + GLS fitter tests.

(reference test patterns: tests/test_gls_fitter.py, tests/test_ecorr*,
tests/test_wls_wb_fitters* — golden NANOGrav comparisons there; here
self-consistent injections: EFAC/EQUAD scaling formulas, ECORR
quantization structure, GLS vs WLS behavior with correlated noise.)
"""

import copy
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.fitter import WLSFitter, GLSFitter, DownhillGLSFitter
from pint_tpu.simulation import make_fake_toas_fromMJDs
from pint_tpu.mjd import Epochs

PAR = """
PSR TESTN
RAJ 12:00:00.0
DECJ 15:00:00.0
F0 218.8 1
F1 -4e-16 1
PEPOCH 55500
DM 15.99 1
EFAC -f L-wide 1.5
EQUAD -f L-wide 1.2
EFAC -f S-wide 0.9
"""


def _clustered_toas(model, n_epochs=25, per_epoch=4, seed=1):
    rng = np.random.default_rng(seed)
    epoch_days = np.linspace(55000, 56000, n_epochs)
    mjds = []
    for d in epoch_days:
        # TOAs 0.5 s apart within an epoch (DM delays shift same-epoch
        # TOAs by ~20 ms; keep gaps well inside the 2 s quantization)
        mjds.extend(d + np.arange(per_epoch) * 0.5 / 86400.0)
    mjds = np.array(mjds)
    freqs = np.where(np.arange(len(mjds)) % 2, 1400.0, 2300.0)
    t = make_fake_toas_fromMJDs(mjds, model, error_us=1.0, freq_mhz=freqs,
                                obs="gbt", add_noise=False)
    for i, f in enumerate(t.flags):
        f["f"] = "L-wide" if freqs[i] < 2000 else "S-wide"
    return t


def test_efac_equad_scaling():
    m = get_model(PAR)
    t = _clustered_toas(m)
    sigma = np.asarray(m.scaled_toa_uncertainty(t))
    lmask = np.array([f["f"] == "L-wide" for f in t.flags])
    expected_l = np.sqrt((1.5 * 1.0) ** 2 + 1.2**2)
    np.testing.assert_allclose(sigma[lmask], expected_l, rtol=1e-10)
    np.testing.assert_allclose(sigma[~lmask], 0.9, rtol=1e-10)


def test_ecorr_quantization():
    m = get_model(PAR + "ECORR -f L-wide 0.8\n")
    t = _clustered_toas(m)
    prep = m.prepare(t)
    U = np.asarray(prep.prep["ecorr_U"])
    # every L-wide epoch (25 epochs, 2 L-wide TOAs each) becomes a column
    assert U.shape[1] == 25
    assert set(U.sum(axis=0)) == {2.0}
    # columns are disjoint
    assert (U.sum(axis=1) <= 1).all()


def test_gls_with_ecorr_downweights_epochs():
    m = get_model(PAR + "ECORR -f L-wide 5.0\n")
    t = _clustered_toas(m)
    rng = np.random.default_rng(5)
    # inject: white per-TOA + strong common offset per L-wide epoch
    lmask = np.array([f["f"] == "L-wide" for f in t.flags])
    epoch_id = np.repeat(np.arange(25), 4)
    epoch_noise = rng.standard_normal(25) * 5e-6
    white = rng.standard_normal(len(t)) * 1e-6
    t.sec = t.sec + white + np.where(lmask, epoch_noise[epoch_id], 0.0)
    t.tdb = None; t.ssb_obs = None; t._clock_applied = False
    t.apply_clock_corrections(); t.compute_TDBs(); t.compute_posvels()

    m_wls = copy.deepcopy(m)
    m_wls.remove_component("EcorrNoise")
    f_wls = WLSFitter(t, m_wls); f_wls.fit_toas()
    f_gls = GLSFitter(t, copy.deepcopy(m)); chi2_gls = f_gls.fit_toas()
    # whitened chi2 must be ~dof once ECORR absorbs the epoch noise,
    # while the unmodeled WLS fit shows the inflation
    dof = len(t) - len(m.free_params) - 1
    assert chi2_gls / dof < 2.0
    assert f_wls.resids.reduced_chi2 > 2.5
    # modeling epoch correlations cannot shrink the uncertainty
    assert f_gls.model.F0.uncertainty > f_wls.model.F0.uncertainty


def test_plrednoise_basis():
    m = get_model(PAR + "TNREDAMP -13.5\nTNREDGAM 3.5\nTNREDC 15\n")
    t = _clustered_toas(m)
    prep = m.prepare(t)
    comp = m.components["PLRedNoise"]
    F, phi = comp.basis_weight(prep.params0, prep.prep)
    assert F.shape == (len(t), 30)
    phi = np.asarray(phi)
    assert (phi > 0).all()
    # power-law: lowest harmonic carries the most variance
    assert phi[0] > phi[-2]
    # sin/cos pairs share weights
    np.testing.assert_allclose(phi[0::2], phi[1::2])


def test_gls_red_noise_whitening():
    par = PAR + "TNREDAMP -12.3\nTNREDGAM 4.0\nTNREDC 20\n"
    m = get_model(par)
    t = _clustered_toas(m, n_epochs=40, per_epoch=2)
    rng = np.random.default_rng(11)
    # inject a smooth wandering signal (red-ish) + white noise
    mjds = t.get_mjds()
    span = mjds.max() - mjds.min()
    red = sum(
        (5e-6 / (k ** 2)) * np.sin(2 * np.pi * k * (mjds - mjds.min()) / span
                                   + rng.uniform(0, 2 * np.pi))
        for k in range(1, 6))
    t.sec = t.sec + red + rng.standard_normal(len(t)) * 1e-6
    t.tdb = None; t.ssb_obs = None; t._clock_applied = False
    t.apply_clock_corrections(); t.compute_TDBs(); t.compute_posvels()

    f = DownhillGLSFitter(t, copy.deepcopy(m))
    chi2 = f.fit_toas()
    dof = len(t) - len(m.free_params) - 1
    # red signal absorbed by Fourier basis -> whitened chi2 near dof
    assert chi2 / dof < 2.5
    # and the noise amplitudes are actually nonzero
    assert f.noise_ampls is not None
    assert np.abs(f.noise_ampls).max() > 0
