"""Noise model + GLS fitter tests.

(reference test patterns: tests/test_gls_fitter.py, tests/test_ecorr*,
tests/test_wls_wb_fitters* — golden NANOGrav comparisons there; here
self-consistent injections: EFAC/EQUAD scaling formulas, ECORR
quantization structure, GLS vs WLS behavior with correlated noise.)
"""

import copy
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.fitter import WLSFitter, GLSFitter, DownhillGLSFitter
from pint_tpu.simulation import make_fake_toas_fromMJDs
from pint_tpu.mjd import Epochs

PAR = """
PSR TESTN
RAJ 12:00:00.0
DECJ 15:00:00.0
F0 218.8 1
F1 -4e-16 1
PEPOCH 55500
DM 15.99 1
EFAC -f L-wide 1.5
EQUAD -f L-wide 1.2
EFAC -f S-wide 0.9
"""


def _clustered_toas(model, n_epochs=25, per_epoch=4, seed=1):
    rng = np.random.default_rng(seed)
    epoch_days = np.linspace(55000, 56000, n_epochs)
    mjds = []
    for d in epoch_days:
        # TOAs 0.5 s apart within an epoch (DM delays shift same-epoch
        # TOAs by ~20 ms; keep gaps well inside the 2 s quantization)
        mjds.extend(d + np.arange(per_epoch) * 0.5 / 86400.0)
    mjds = np.array(mjds)
    freqs = np.where(np.arange(len(mjds)) % 2, 1400.0, 2300.0)
    t = make_fake_toas_fromMJDs(mjds, model, error_us=1.0, freq_mhz=freqs,
                                obs="gbt", add_noise=False)
    for i, f in enumerate(t.flags):
        f["f"] = "L-wide" if freqs[i] < 2000 else "S-wide"
    return t


def test_efac_equad_scaling():
    m = get_model(PAR)
    t = _clustered_toas(m)
    sigma = np.asarray(m.scaled_toa_uncertainty(t))
    lmask = np.array([f["f"] == "L-wide" for f in t.flags])
    expected_l = np.sqrt((1.5 * 1.0) ** 2 + 1.2**2)
    np.testing.assert_allclose(sigma[lmask], expected_l, rtol=1e-10)
    np.testing.assert_allclose(sigma[~lmask], 0.9, rtol=1e-10)


def test_ecorr_quantization():
    from pint_tpu.models.noise import EcorrNoise

    m = get_model(PAR + "ECORR -f L-wide 0.8\n")
    t = _clustered_toas(m)
    prep = m.prepare(t)
    # disjoint epochs pack the sparse O(n) epoch index, not a dense U
    assert "ecorr_U" not in prep.prep and "ecorr_eidx" in prep.prep
    U = np.asarray(EcorrNoise.dense_U(prep.prep))
    # every L-wide epoch (25 epochs, 2 L-wide TOAs each) becomes a column
    assert U.shape[1] == 25
    assert set(U.sum(axis=0)) == {2.0}
    # columns are disjoint
    assert (U.sum(axis=1) <= 1).all()


def test_gls_with_ecorr_downweights_epochs():
    m = get_model(PAR + "ECORR -f L-wide 5.0\n")
    t = _clustered_toas(m)
    rng = np.random.default_rng(5)
    # inject: white per-TOA + strong common offset per L-wide epoch
    lmask = np.array([f["f"] == "L-wide" for f in t.flags])
    epoch_id = np.repeat(np.arange(25), 4)
    epoch_noise = rng.standard_normal(25) * 5e-6
    white = rng.standard_normal(len(t)) * 1e-6
    t.sec = t.sec + white + np.where(lmask, epoch_noise[epoch_id], 0.0)
    t.tdb = None; t.ssb_obs = None; t._clock_applied = False
    t.apply_clock_corrections(); t.compute_TDBs(); t.compute_posvels()

    m_wls = copy.deepcopy(m)
    m_wls.remove_component("EcorrNoise")
    f_wls = WLSFitter(t, m_wls); f_wls.fit_toas()
    f_gls = GLSFitter(t, copy.deepcopy(m)); chi2_gls = f_gls.fit_toas()
    # whitened chi2 must be ~dof once ECORR absorbs the epoch noise,
    # while the unmodeled WLS fit shows the inflation
    dof = len(t) - len(m.free_params) - 1
    assert chi2_gls / dof < 2.0
    assert f_wls.resids.reduced_chi2 > 2.5
    # modeling epoch correlations cannot shrink the uncertainty
    assert f_gls.model.F0.uncertainty > f_wls.model.F0.uncertainty


def test_plrednoise_basis():
    m = get_model(PAR + "TNREDAMP -13.5\nTNREDGAM 3.5\nTNREDC 15\n")
    t = _clustered_toas(m)
    prep = m.prepare(t)
    comp = m.components["PLRedNoise"]
    F, phi = comp.basis_weight(prep.params0, prep.prep)
    assert F.shape == (len(t), 30)
    phi = np.asarray(phi)
    assert (phi > 0).all()
    # power-law: lowest harmonic carries the most variance
    assert phi[0] > phi[-2]
    # sin/cos pairs share weights
    np.testing.assert_allclose(phi[0::2], phi[1::2])


def test_gls_red_noise_whitening():
    par = PAR + "TNREDAMP -12.3\nTNREDGAM 4.0\nTNREDC 20\n"
    m = get_model(par)
    t = _clustered_toas(m, n_epochs=40, per_epoch=2)
    rng = np.random.default_rng(11)
    # inject a smooth wandering signal (red-ish) + white noise
    mjds = t.get_mjds()
    span = mjds.max() - mjds.min()
    red = sum(
        (5e-6 / (k ** 2)) * np.sin(2 * np.pi * k * (mjds - mjds.min()) / span
                                   + rng.uniform(0, 2 * np.pi))
        for k in range(1, 6))
    t.sec = t.sec + red + rng.standard_normal(len(t)) * 1e-6
    t.tdb = None; t.ssb_obs = None; t._clock_applied = False
    t.apply_clock_corrections(); t.compute_TDBs(); t.compute_posvels()

    f = DownhillGLSFitter(t, copy.deepcopy(m))
    chi2 = f.fit_toas()
    dof = len(t) - len(m.free_params) - 1
    # red signal absorbed by Fourier basis -> whitened chi2 near dof
    assert chi2 / dof < 2.5
    # and the noise amplitudes are actually nonzero
    assert f.noise_ampls is not None
    assert np.abs(f.noise_ampls).max() > 0


def test_ecorr_average():
    """Epoch-averaged residuals: grouping follows the ECORR
    quantization, weighted means are exact, errors shrink ~1/sqrt(n)
    and include the ECORR term (reference: Residuals.ecorr_average)."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TECA\nRAJ 05:00:00\nDECJ 10:00:00\nF0 300.0 1\nPEPOCH 55100\n"
           "DM 12.0\nEFAC -f X 2.0\nECORR -f X 0.5\n")
    m = get_model(par)
    rng = np.random.default_rng(0)
    epochs = np.sort(rng.uniform(55000, 55200, 25))
    mjds = np.concatenate([e + np.arange(4) * 0.5 / 86400 for e in epochs])
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=1)
    for f in t.flags:
        f["f"] = "X"
    r = Residuals(t, m)
    avg = r.ecorr_average()
    assert len(avg["mjds"]) == 25  # one group per 4-TOA epoch
    assert np.all(np.diff(avg["mjds"]) > 0)
    # exact weighted mean for the first group
    g = avg["indices"][0]
    sig = np.asarray(r.prepared.scaled_sigma_us())[g]
    w = 1 / sig**2
    expect = np.sum(np.asarray(r.time_resids)[g] * w) / w.sum()
    assert avg["time_resids"][0] == pytest.approx(expect, rel=1e-12)
    # error: EFAC=2 scales sigma to 2us -> 2/sqrt(4)=1, plus ECORR=0.5
    assert avg["errors"][0] == pytest.approx(np.sqrt(1.0 + 0.25), rel=1e-6)
    # without the noise model: RAW errors (no EFAC), no ECORR term
    avg0 = r.ecorr_average(use_noise_model=False)
    assert avg0["errors"][0] == pytest.approx(0.5, rel=1e-6)
    # singleton handling: no-ECORR model -> every TOA its own group
    m2 = get_model("PSR T2\nRAJ 05:00:00\nDECJ 10:00:00\nF0 300.0 1\n"
                   "PEPOCH 55100\nDM 12.0\n")
    r2 = Residuals(t, m2)
    avg2 = r2.ecorr_average()
    assert len(avg2["mjds"]) == len(t)


SW_PAR = """
PSR TESTSW
RAJ 12:00:00.0
DECJ 2:00:00.0
F0 218.8 1
F1 -4e-16 1
PEPOCH 55500
DM 15.99 1
NE_SW 8.0
"""


def test_plswnoise_basis_is_solar_wind_signature():
    """PLSWNoise basis rows equal the plain Fourier basis scaled by
    the per-TOA delay of a unit NE_SW change (reference:
    noise_model.py::PLSWNoise — solar-wind GP rides the line-of-sight
    geometry and 1/nu^2)."""
    from pint_tpu.models.noise import fourier_basis

    par = SW_PAR + "TNSWAMP 0.0\nTNSWGAM 2.0\nTNSWC 10\n"
    m = get_model(par)
    assert "PLSWNoise" in m.components
    rng = np.random.default_rng(5)
    mjds = np.sort(rng.uniform(55000, 55700, 60))
    freqs = np.where(np.arange(60) % 2, 800.0, 400.0)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=0.5, freq_mhz=freqs,
                                obs="gbt", add_noise=False, iterations=1)
    prep = m.prepare(t)
    comp = m.components["PLSWNoise"]
    F, phi = comp.basis_weight(prep.params0, prep.prep)
    assert F.shape == (60, 20)
    phi = np.asarray(phi)
    assert (phi > 0).all() and phi[0] > phi[-2]
    # unit-NE_SW delay from the SolarWindDispersion component itself
    d1 = np.asarray(get_model(par.replace("NE_SW 8.0", "NE_SW 1.0")).prepare(t).delay())
    d0 = np.asarray(get_model(par.replace("NE_SW 8.0", "NE_SW 0.0")).prepare(t).delay())
    scale_us = 1e6 * (d1 - d0)
    F0, _, _ = fourier_basis(t, 10)
    # rtol limited by cancellation in d1-d0 (full-pipeline delays)
    np.testing.assert_allclose(np.asarray(F), F0 * scale_us[:, None],
                               rtol=1e-6, atol=1e-12)


def test_plswnoise_gls_whitening_roundtrip():
    """Simulated PLSWNoise realizations are absorbed by the GLS basis:
    whitened chi2 near dof, and the par round-trips the TNSW params."""
    par = SW_PAR + "TNSWAMP -5.5\nTNSWGAM 2.0\nTNSWC 8\n"
    m = get_model(par)
    rng = np.random.default_rng(9)
    mjds = np.sort(rng.uniform(55000, 55700, 80))
    freqs = np.where(np.arange(80) % 2, 800.0, 400.0)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=0.3, freq_mhz=freqs,
                                obs="gbt", add_noise=True,
                                add_correlated_noise=True, seed=9,
                                iterations=2)
    # the SW draw must actually perturb the TOAs beyond white noise
    r = np.asarray(Residuals(t, m, subtract_mean=False).time_resids)
    assert r.std() > 1.0e-6  # white floor is 0.3 us
    f = DownhillGLSFitter(t, copy.deepcopy(m))
    chi2 = f.fit_toas()
    dof = len(t) - len(m.free_params) - 1
    assert chi2 / dof < 2.5
    assert f.noise_ampls is not None and np.abs(f.noise_ampls).max() > 0
    # round-trip
    m2 = get_model(f.model.as_parfile())
    assert "PLSWNoise" in m2.components
    assert m2.TNSWAMP.value == pytest.approx(-5.5)
    assert m2.TNSWGAM.value == pytest.approx(2.0)


def test_temponest_noise_spellings():
    """TNEF/TNEQ/TNECORR/TNGlobalEF/TNGlobalEQ parse to the canonical
    EFAC/EQUAD/ECORR params; TNEQ-family values are log10-seconds
    (reference: noise_model.py temponest aliases)."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    base = ("PSR TTNN\nRAJ 10:00:00\nDECJ 20:00:00\nF0 150.0 1\n"
            "PEPOCH 55300\nDM 5.0\n")
    tn = (base + "TNEF -f L-wide 1.3\nTNEQ -f L-wide -5.69897000433602\n"
          "TNECORR -f L-wide 0.8\nTNGlobalEF 1.1\nTNGlobalEQ -6.0\n")
    canon = (base + "EFAC -f L-wide 1.3\nEQUAD -f L-wide 2.0\n"
             "ECORR -f L-wide 0.8\nEFAC 1.1\nEQUAD 1.0\n")
    m_tn = get_model(tn)
    m_c = get_model(canon)
    assert not m_tn.unrecognized
    # 10**-5.699 s = 2.0 us; 10**-6 s = 1.0 us
    np.testing.assert_allclose(m_tn.EQUAD1.value, 2.0, rtol=1e-12)
    np.testing.assert_allclose(m_tn.EQUAD2.value, 1.0, rtol=1e-12)
    assert m_tn.ECORR1.value == 0.8 and m_tn.EFAC2.value == 1.1
    mjds = np.sort(55300 + np.repeat(np.arange(20), 2) * 5
                   + np.tile([0.0, 1e-5], 20))
    t = make_fake_toas_fromMJDs(mjds, m_c, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=False, iterations=0)
    for f in t.flags:
        f["f"] = "L-wide"
    s_tn = np.asarray(m_tn.scaled_toa_uncertainty(t))
    s_c = np.asarray(m_c.scaled_toa_uncertainty(t))
    np.testing.assert_allclose(s_tn, s_c, rtol=1e-12)


def test_get_noise_resids_whitens():
    """The fitted red-noise realization explains the injected
    correlated power: subtracting it returns the residual RMS to the
    white level (reference: GLSFitter populating noise_resids)."""
    par = ("PSR TNRZ\nRAJ 6:00:00\nDECJ 10:00:00\nF0 200.0 1\nF1 -1e-14 1\n"
           "PEPOCH 55500\nDM 10.0\nTNREDAMP -13\nTNREDGAM 3.0\nTNREDC 15\n")
    m = get_model(par)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 56000, 150), m,
                                error_us=0.5, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, add_correlated_noise=True,
                                seed=9)
    f = GLSFitter(t, m)
    f.fit_toas(maxiter=3)
    nr = f.get_noise_resids()
    assert set(nr) == {"PLRedNoise"}
    r = np.asarray(f.resids.calc_time_resids())
    r_white = r - nr["PLRedNoise"]
    assert r.std() > 1.5 * r_white.std()  # realization carries real power
    assert r_white.std() < 0.7e-6  # back to ~0.5 us white level
    # unfitted model refuses
    f2 = GLSFitter(t, get_model(par))
    with pytest.raises(ValueError, match="amplitudes"):
        f2.get_noise_resids()


def test_whitened_resids_subtract_noise_realization():
    """Post-GLS-fit residuals whiten against the FULL noise model:
    calc_whitened_resids subtracts the attached realizations
    (reference: Residuals.calc_whitened_resids with noise_resids)."""
    par = ("PSR TWHN\nRAJ 6:00:00\nDECJ 10:00:00\nF0 200.0 1\nF1 -1e-14 1\n"
           "PEPOCH 55500\nDM 10.0\nTNREDAMP -13\nTNREDGAM 3.0\nTNREDC 15\n")
    m = get_model(par)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 56000, 150), m,
                                error_us=0.5, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, add_correlated_noise=True,
                                seed=9)
    f = GLSFitter(t, m)
    f.fit_toas(maxiter=3)
    assert set(f.resids.noise_resids) == {"PLRedNoise"}
    w = np.asarray(f.resids.calc_whitened_resids())
    # whitened scatter back at the unit level; raw r/sigma inflated
    r_over_sig = (np.asarray(f.resids.calc_time_resids())
                  / (np.asarray(f.resids.prepared.scaled_sigma_us()) * 1e-6))
    assert w.std() < 1.4
    assert r_over_sig.std() > 1.5 * w.std()
    # a fresh (unfitted) Residuals has no realization: unchanged path
    r2 = Residuals(t, m)
    assert not getattr(r2, "noise_resids", None)


def test_chi2_stays_marginal_not_realization_conditioned():
    """calc_chi2/lnlikelihood do NOT subtract the realization (no
    amplitude-prior term available there); only calc_whitened_resids
    does. The identity -2 lnL = chi2 + sum log(2 pi sigma^2) holds."""
    par = ("PSR TWHC\nRAJ 6:00:00\nDECJ 10:00:00\nF0 200.0 1\nF1 -1e-14 1\n"
           "PEPOCH 55500\nDM 10.0\nTNREDAMP -13\nTNREDGAM 3.0\nTNREDC 15\n")
    m = get_model(par)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 56000, 150), m,
                                error_us=0.5, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, add_correlated_noise=True,
                                seed=9)
    f = GLSFitter(t, m)
    f.fit_toas(maxiter=3)
    r = f.resids
    raw = np.asarray(r.calc_time_resids())
    sig = np.asarray(r.prepared.scaled_sigma_us()) * 1e-6
    assert abs(r.chi2 - float(np.sum((raw / sig) ** 2))) < 1e-6
    lhs = -2.0 * r.lnlikelihood() - float(np.sum(np.log(2 * np.pi * sig**2)))
    assert abs(lhs - r.chi2) < 1e-6
    # whitened view is realization-subtracted, so strictly smaller
    assert float(np.sum(np.asarray(r.calc_whitened_resids())**2)) < r.chi2


def test_plswnoise_row_scale_follows_swx_window_p():
    """Under SolarWindDispersionX the GP basis row scale must use each
    window's SWXP index for TOAs inside that window (ADVICE r4: the
    basis previously fell back to p=2 under SWX even when
    SWXP_#### != 2, mis-weighting conjunction epochs)."""
    swx_extra = ("SWXDM_0001 2.0 1\nSWXR1_0001 55000\nSWXR2_0001 55300\n"
                 "SWXP_0001 4.0\nTNSWAMP 0.0\nTNSWGAM 2.0\nTNSWC 6\n")
    par4 = SW_PAR + swx_extra
    par2 = SW_PAR + swx_extra.replace("SWXP_0001 4.0", "SWXP_0001 2.0")
    m4 = get_model(par4)
    assert "SolarWindDispersionX" in m4.components
    rng = np.random.default_rng(11)
    mjds = np.sort(rng.uniform(54900, 55600, 50))
    freqs = np.full(50, 800.0)
    t = make_fake_toas_fromMJDs(mjds, m4, error_us=0.5, freq_mhz=freqs,
                                obs="gbt", add_noise=False, iterations=1)
    in_win = (mjds >= 55000) & (mjds < 55300)
    assert in_win.any() and (~in_win).any()
    prep4 = m4.prepare(t)
    comp4 = m4.components["PLSWNoise"]
    s4 = comp4._row_scale(m4, t, prep4.prep, prep4.params0)
    m2 = get_model(par2)
    prep2 = m2.prepare(t)
    s2 = m2.components["PLSWNoise"]._row_scale(m2, t, prep2.prep,
                                               prep2.params0)
    # outside the window both models agree (base p=2 wind)
    np.testing.assert_allclose(s4[~in_win], s2[~in_win], rtol=1e-12)
    # inside the p=4 window the geometry differs measurably from p=2
    assert np.all(np.abs(s4[in_win] / s2[in_win] - 1.0) > 1e-3)
    # and matches the SWM 1 base-wind geometry at the same p
    from pint_tpu.models.solar_wind import solar_wind_geometry_p
    from pint_tpu.models.noise import DMconst
    n_hat = np.asarray(m4.components["AstrometryEquatorial"].ssb_to_psb_xyz(
        prep4.params0, prep4.prep))
    geom4 = np.asarray(solar_wind_geometry_p(
        t.obs_sun.pos / 299792458.0, n_hat, 4.0))
    expected_in = 1e6 * DMconst * geom4[in_win] / freqs[in_win] ** 2
    np.testing.assert_allclose(s4[in_win], expected_in, rtol=1e-9)


def test_gls_hoist_guard_falls_back_with_free_noise_param():
    """The Gauss-Newton hoist (constant noise-basis blocks) is only
    valid with frozen noise parameters; a free EFAC must disable it
    and the fit must still run (and agree with the dense cross-check
    path). The free EFAC's design column is identically zero, so the
    threshold drops it — the point here is the guard, not the EFAC."""
    from pint_tpu.parallel import PTABatch

    par_free = ("PSR TH0\nRAJ 10:00:00\nDECJ 05:00:00\nF0 200.5 1\n"
                "F1 -2e-16 1\nPEPOCH 55500\nDM 10.5 1\n"
                "EFAC -f L 1.1 1\nECORR -f L 0.6\n"
                "RNAMP 1e-14\nRNIDX -3\nTNREDC 4\n")
    m = get_model(par_free)
    assert "EFAC1" in m.free_params
    rng = np.random.default_rng(2)
    days = np.sort(rng.uniform(55000, 55800, 15))
    # 1 s pairs: inside the 2 s ECORR quantization window, so real
    # epochs exist and the marginalized (hoistable) path is reachable
    mjds = np.sort(np.concatenate([days, days + 1.0 / 86400]))
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=2,
                                iterations=1)
    for fl in t.flags:
        fl["f"] = "L"
    pta = PTABatch([m], [t])
    key, _ = pta._build_gls()
    assert key[-1] is False  # hoist disabled by the free EFAC
    x_a, chi2_a, _ = pta.gls_fit(maxiter=2)
    x_d, chi2_d, _ = pta.gls_fit(maxiter=2, ecorr_mode="dense")
    assert np.isfinite(np.asarray(chi2_a)).all()
    np.testing.assert_allclose(np.asarray(x_a), np.asarray(x_d),
                               rtol=1e-8, atol=1e-20)
    # frozen-noise control: same structure, EFAC frozen -> hoisted
    m2 = get_model(par_free.replace("EFAC -f L 1.1 1", "EFAC -f L 1.1"))
    pta2 = PTABatch([m2], [t])
    key2, _ = pta2._build_gls()
    assert key2[-1] is True
