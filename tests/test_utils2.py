"""Utility-surface parity: p_to_f/pferrs, ELL1_check, wavex_setup
family, Wave<->WaveX translation, P0/P1 par conversion.

(reference patterns: tests/test_utils.py, tests/test_wavex.py
upstream.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu import utils as U
from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_fromMJDs

BASE = """
PSR TESTU2
RAJ 12:10:00.0
DECJ 09:00:00.0
F0 218.8 1
F1 -4e-16 1
PEPOCH 55300
DM 15.0 1
"""


def test_p_to_f_roundtrip():
    p0, p1 = 0.005, 1e-20
    f0, f1 = U.p_to_f(p0, p1)
    assert f0 == pytest.approx(200.0)
    p0b, p1b = U.p_to_f(f0, f1)
    assert p0b == pytest.approx(p0)
    assert p1b == pytest.approx(p1)
    f0c, f1c, f2c = U.p_to_f(p0, p1, 0.0)
    assert f2c == pytest.approx(2 * p1**2 / p0**3)


def test_pferrs():
    f, ferr = U.pferrs(0.005, 1e-12)
    assert f == pytest.approx(200.0)
    assert ferr == pytest.approx(1e-12 / 0.005**2)
    f, ferr, fd, fderr = U.pferrs(0.005, 1e-12, 1e-20, 1e-22)
    assert fd == pytest.approx(-1e-20 / 0.005**2)
    assert fderr > 0


def test_p0_parfile_conversion():
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        m = get_model("PSR T\nP0 0.005 1 1e-12\nP1 1e-20 1\nPEPOCH 55000\nDM 10\n")
    assert any("P0/P1" in str(x.message) for x in w)
    assert m.F0.value == pytest.approx(200.0)
    assert m.F1.value == pytest.approx(-1e-20 / 0.005**2)
    assert not m.F0.frozen and not m.F1.frozen
    assert m.F0.uncertainty == pytest.approx(1e-12 / 0.005**2)


def test_ell1_check():
    assert U.ELL1_check(1.9, 1e-7, 0.5, 1000, outstring=False)
    assert not U.ELL1_check(10.0, 0.01, 0.1, 100, outstring=False)
    s = U.ELL1_check(1.9, 1e-7, 0.5, 1000)
    assert "ok" in s


def test_wavex_setup_and_translation_equivalence():
    """A Wave model and its WaveX translation produce identical
    residuals."""
    par = BASE + ("WAVEEPOCH 55300\nWAVE_OM 0.02\n"
                  "WAVE1 0.0002 -0.0001\nWAVE2 -5e-5 8e-5\n")
    m_wave = get_model(par)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55600, 80),
                                get_model(BASE), error_us=1.0,
                                freq_mhz=1400.0, obs="gbt", add_noise=False)
    r_wave = np.asarray(Residuals(t, m_wave).time_resids)
    m_wx = U.translate_wave_to_wavex(get_model(par))
    assert "Wave" not in m_wx.components and "WaveX" in m_wx.components
    r_wx = np.asarray(Residuals(t, m_wx).time_resids)
    # not exact: Wave evaluates its harmonics at the delay-corrected
    # time, WaveX at barycentric dt — difference O(amp * om * delay)
    # ~ 2e-4 s * 0.02/day * 500 s ~ 3e-8 s (same approximation as the
    # reference's translate_wave_to_wavex)
    np.testing.assert_allclose(r_wave, r_wx, atol=1e-7)
    # round-trip back is EXACT in parameters
    m_back = U.translate_wavex_to_wave(m_wx)
    assert "Wave" in m_back.components
    assert m_back.WAVE_OM.value == pytest.approx(0.02)
    assert getattr(m_back, "WAVE1").value[0] == pytest.approx(0.0002)
    assert getattr(m_back, "WAVE2").value[1] == pytest.approx(8e-5)
    np.testing.assert_allclose(
        np.asarray(Residuals(t, m_back).time_resids), r_wave, atol=1e-12)


def test_wavex_setup_creates_harmonics():
    m = get_model(BASE)
    freqs = U.wavex_setup(m, T_span_days=500.0, n_freqs=4)
    np.testing.assert_allclose(freqs, np.arange(1, 5) / 500.0)
    assert "WXSIN_0003" in m.params
    # explicit frequencies extend the family
    more = U.wavex_setup(m, T_span_days=500.0, freqs=[0.05])
    assert more[-1] == pytest.approx(0.05)
    with pytest.raises(ValueError):
        U.wavex_setup(m, 500.0)


def test_dmwavex_cmwavex_setup():
    m = get_model(BASE)
    U.dmwavex_setup(m, 400.0, n_freqs=2)
    assert "DMWXSIN_0002" in m.params
    m2 = get_model(BASE)
    U.cmwavex_setup(m2, 400.0, n_freqs=2)
    assert "CMWXSIN_0002" in m2.params
    assert "ChromaticCM" in m2.components  # TNCHROMIDX home rides along
    s, c = U.get_wavex_amps(m2, "CMWXSIN", "CMWXCOS")
    assert len(s) == 2 and np.all(s == 0)
    assert U.get_wavex_freqs(m2, "CMWXFREQ") == pytest.approx(
        [1 / 400.0, 2 / 400.0])


def test_p2_parfile_conversion():
    m = get_model("PSR T\nP0 0.005\nP1 1e-20\nP2 1e-30 1\nPEPOCH 55000\nDM 10\n")
    assert m.F2.value == pytest.approx(
        2 * 1e-20**2 / 0.005**3 - 1e-30 / 0.005**2)
    assert not m.F2.frozen
    # P2 without P1 still produces F1=0 so the F-family is contiguous
    m2 = get_model("PSR T\nP0 0.005\nP2 1e-30\nPEPOCH 55000\nDM 10\n")
    assert m2.F1.value == 0.0 and m2.F2.value is not None


def test_wavex_setup_noncontiguous_ids():
    """Extending a WaveX family whose par ids don't start at 1 must not
    collide with existing parameters."""
    par = BASE + ("WXFREQ_0002 0.004\nWXSIN_0002 1e-5\nWXCOS_0002 0.0\n"
                  "WXFREQ_0003 0.008\nWXSIN_0003 0.0\nWXCOS_0003 0.0\n")
    m = get_model(par)
    wx = m.components["WaveX"]
    assert wx.wx_ids == [2, 3]
    U.wavex_setup(m, 500.0, n_freqs=1)
    assert wx.wx_ids == [2, 3, 4]
    assert m.WXSIN_0002.value == pytest.approx(1e-5)  # untouched
    assert m.WXFREQ_0004.value == pytest.approx(1 / 500.0)


def test_plrednoise_wavex_round_trip_structure():
    par = BASE + "TNREDAMP -13.0\nTNREDGAM 3.5\nTNREDC 12\n"
    m = get_model(par)
    U.plrednoise_to_wavex(m, t_span_days=600.0)
    assert "PLRedNoise" not in m.components
    wx = m.components["WaveX"]
    assert len(wx.wx_ids) == 12
    assert not m.WXSIN_0001.frozen
    np.testing.assert_allclose(m.WXFREQ_0001.value, 1 / 600.0)


def test_wavex_to_plrednoise_recovers_powerlaw():
    """WaveX amplitudes drawn exactly on a power law convert back to
    the generating (log10 A, gamma)."""
    m = get_model(BASE)
    tspan = 500.0
    n_harm = 15
    U.wavex_setup(m, tspan, n_freqs=n_harm)
    log10_A, gamma = -13.2, 3.4
    A = 10.0**log10_A
    fyr = 1.0 / (365.25 * 86400.0)
    tspan_s = tspan * 86400.0
    for k, i in enumerate(m.components["WaveX"].wx_ids, start=1):
        f = k / tspan_s
        phi = A**2 / (12 * np.pi**2) * (f / fyr) ** (-gamma) / fyr**3 / tspan_s
        amp = np.sqrt(phi)  # put all power in sin, none in cos
        getattr(m, f"WXSIN_{i:04d}").value = amp * np.sqrt(2)
        getattr(m, f"WXCOS_{i:04d}").value = 0.0
    U.wavex_to_plrednoise(m, t_span_days=tspan)
    assert "WaveX" not in m.components and "PLRedNoise" in m.components
    assert m.TNREDGAM.value == pytest.approx(gamma, abs=1e-6)
    assert m.TNREDAMP.value == pytest.approx(log10_A, abs=1e-6)
    assert m.TNREDC.value == n_harm


def test_wavex_to_plrednoise_estimation_from_fit():
    """End-to-end: simulate red noise, fit WaveX amplitudes, recover a
    plausible spectral index."""
    from pint_tpu.fitter import WLSFitter

    true = get_model(BASE + "TNREDAMP -12.3\nTNREDGAM 3.0\nTNREDC 8\n")
    mjds = np.linspace(55000, 55600, 300)
    t = make_fake_toas_fromMJDs(mjds, true, error_us=0.5, freq_mhz=1400.0,
                                obs="gbt", add_noise=True,
                                add_correlated_noise=True, seed=12)
    fitm = get_model(BASE)
    U.wavex_setup(fitm, 601.0, n_freqs=8)
    for i in fitm.components["WaveX"].wx_ids:
        getattr(fitm, f"WXSIN_{i:04d}").frozen = False
        getattr(fitm, f"WXCOS_{i:04d}").frozen = False
    f = WLSFitter(t, fitm)
    f.fit_toas(maxiter=3)
    out = U.wavex_to_plrednoise(f.model)
    # one realization of 8 harmonics: loose bounds only
    assert 0.5 < out.TNREDGAM.value < 6.5
    assert -15.0 < out.TNREDAMP.value < -10.0


def test_information_criteria_prefer_true_model():
    """AIC/BIC penalize an overparameterized model on white-noise data
    (reference: utils.py::akaike_information_criterion)."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.utils import (akaike_information_criterion,
                                bayesian_information_criterion)

    par = ("PSR TAIC\nRAJ 6:00:00\nDECJ 10:00:00\nF0 200.0 1\nF1 -1e-14 1\n"
           "PEPOCH 55000\nDM 10.0 1\n")
    true = get_model(par)
    t = make_fake_toas_fromMJDs(np.linspace(54700, 55300, 150), true,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=6)
    f_true = WLSFitter(t, true)
    f_true.fit_toas(maxiter=3)
    # overparameterized: 6 extra glitch params the data doesn't need
    over = get_model(par + "GLEP_1 55000\nGLPH_1 0 1\nGLF0_1 0 1\n"
                     "GLF1_1 0 1\n")
    f_over = WLSFitter(t, over)
    f_over.fit_toas(maxiter=3)
    aic_t = akaike_information_criterion(f_true.model, t)
    aic_o = akaike_information_criterion(f_over.model, t)
    bic_t = bayesian_information_criterion(f_true.model, t)
    bic_o = bayesian_information_criterion(f_over.model, t)
    assert np.isfinite([aic_t, aic_o, bic_t, bic_o]).all()
    assert aic_t < aic_o and bic_t < bic_o
    # BIC penalizes extra params harder than AIC at n=150
    assert (bic_o - bic_t) > (aic_o - aic_t)


def test_information_criteria_reject_correlated_noise():
    import numpy as np
    import pytest

    from pint_tpu.fitter import CorrelatedErrors
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs
    from pint_tpu.utils import akaike_information_criterion

    m = get_model("PSR TAICC\nRAJ 6:00:00\nDECJ 10:00:00\nF0 200.0 1\n"
                  "PEPOCH 55000\nDM 10.0\nECORR 0.5\n")
    t = make_fake_toas_fromMJDs(np.linspace(54900, 55100, 20), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=1)
    with pytest.raises(CorrelatedErrors):
        akaike_information_criterion(m, t)


def test_list_parameters_catalog():
    from pint_tpu.utils import list_parameters

    rows = list_parameters()
    by_name = {}
    for r in rows:
        by_name.setdefault(r["name"], []).append(r)
    # spot checks across layers
    assert any(r["component"] == "Spindown" for r in by_name["F0"])
    assert any(r["component"].startswith("Binary") for r in by_name["PB"])
    assert "XDOT" in by_name["A1DOT"][0]["aliases"]
    assert by_name["DM"][0]["units"] in ("pc cm^-3", "pc/cm^3")
    # par-line-created families appear via exemplar members
    for fam in ("GLEP_1", "JUMP1", "EFAC1", "ECORR1", "DMX_0001",
                "WXFREQ_0001", "T0X_0001"):
        assert fam in by_name, fam
    assert len(rows) > 100  # the full surface, not a stub


def test_dmxparse_save_file(tmp_path):
    """dmxparse(save=) writes the NANOGrav dmxparse.out convention
    (mean-subtracted values, epoch/r1/r2/bin columns)."""
    import numpy as np

    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs
    from pint_tpu.utils import dmxparse

    par = ("PSR TDMXP\nRAJ 6:00:00\nDECJ 10:00:00\nF0 200.0 1\n"
           "PEPOCH 55100\nDM 12.0 1\n"
           "DMX_0001 0.001 1\nDMXR1_0001 55000\nDMXR2_0001 55100\n"
           "DMX_0002 -0.002 1\nDMXR1_0002 55100\nDMXR2_0002 55200\n")
    m = get_model(par)
    mjds = np.linspace(55000, 55200, 60)
    freqs = np.where(np.arange(60) % 2, 1400.0, 800.0)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                obs="gbt", add_noise=True, seed=3)
    f = WLSFitter(t, m)
    f.fit_toas(maxiter=3)
    out = tmp_path / "dmxparse.out"
    d = dmxparse(f, save=str(out))
    text = out.read_text()
    assert "Mean DMX value" in text
    lines = [l for l in text.splitlines() if not l.startswith("#")]
    assert len(lines) == 2
    ep, val, err, r1, r2, label = lines[0].split()
    assert label == "DMX_0001" and float(r1) == 55000.0
    # file stores mean-subtracted values
    np.testing.assert_allclose(float(val), d["dmxs"][0] - d["mean_dmx"],
                               atol=2e-7)
