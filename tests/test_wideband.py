"""Wideband (time+DM) fitting tests.

(reference test pattern: tests/test_wideband_fitters.py — joint
residual vector and combined design matrix.)
"""

import copy
import warnings

import numpy as np

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.fitter import WidebandTOAFitter
from pint_tpu.residuals import WidebandTOAResiduals
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR TESTW
RAJ 12:00:00.0
DECJ 15:00:00.0
F0 218.8 1
F1 -4e-16 1
PEPOCH 55500
DM 15.99 1
"""


def _wb_toas(model, dm_true=15.99, seed=2):
    rng = np.random.default_rng(seed)
    mjds = np.linspace(55000, 56000, 50)
    t = make_fake_toas_fromMJDs(mjds, model, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=seed)
    for f in t.flags:
        f["pp_dm"] = f"{dm_true + rng.standard_normal() * 1e-4:.8f}"
        f["pp_dme"] = "1e-4"
    return t


def test_wideband_residuals():
    m = get_model(PAR)
    t = _wb_toas(m)
    wb = WidebandTOAResiduals(t, m)
    assert wb.dm.valid.all()
    assert abs(np.mean(wb.dm.resids)) < 5e-5
    assert wb.chi2 > 0


def test_wideband_fit_constrains_dm():
    """With single-frequency TOAs, only the DM measurements pin DM."""
    m = get_model(PAR)
    t = _wb_toas(m, dm_true=15.9905)  # DM measurements offset by 5e-4
    m2 = copy.deepcopy(m)
    f = WidebandTOAFitter(t, m2)
    f.fit_toas(maxiter=3)
    # fitted DM pulled to the measured value despite time-domain degeneracy
    assert abs(f.model.DM.value - 15.9905) < 1e-4
    assert f.model.DM.uncertainty < 1e-4
