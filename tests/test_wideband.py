"""Wideband (time+DM) fitting tests.

(reference test pattern: tests/test_wideband_fitters.py — joint
residual vector and combined design matrix.)
"""

import copy
import warnings

import numpy as np

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.fitter import WidebandTOAFitter
from pint_tpu.residuals import WidebandTOAResiduals
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR TESTW
RAJ 12:00:00.0
DECJ 15:00:00.0
F0 218.8 1
F1 -4e-16 1
PEPOCH 55500
DM 15.99 1
"""


def _wb_toas(model, dm_true=15.99, seed=2):
    rng = np.random.default_rng(seed)
    mjds = np.linspace(55000, 56000, 50)
    t = make_fake_toas_fromMJDs(mjds, model, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=seed)
    for f in t.flags:
        f["pp_dm"] = f"{dm_true + rng.standard_normal() * 1e-4:.8f}"
        f["pp_dme"] = "1e-4"
    return t


def test_wideband_residuals():
    m = get_model(PAR)
    t = _wb_toas(m)
    wb = WidebandTOAResiduals(t, m)
    assert wb.dm.valid.all()
    assert abs(np.mean(wb.dm.resids)) < 5e-5
    assert wb.chi2 > 0


def test_wideband_fit_constrains_dm():
    """With single-frequency TOAs, only the DM measurements pin DM."""
    m = get_model(PAR)
    t = _wb_toas(m, dm_true=15.9905)  # DM measurements offset by 5e-4
    m2 = copy.deepcopy(m)
    f = WidebandTOAFitter(t, m2)
    f.fit_toas(maxiter=3)
    # fitted DM pulled to the measured value despite time-domain degeneracy
    assert abs(f.model.DM.value - 15.9905) < 1e-4
    assert f.model.DM.uncertainty < 1e-4


def test_wideband_downhill_and_lm_fitters():
    """Downhill and LM wideband variants converge to (at least) the
    single-step fitter's chi2 from a perturbed start."""
    from pint_tpu.fitter import WidebandDownhillFitter, WidebandLMFitter

    m = get_model(PAR)
    t = _wb_toas(m, dm_true=15.9905)
    ref = WidebandTOAFitter(t, copy.deepcopy(m))
    chi2_ref = ref.fit_toas(maxiter=3)

    m_d = copy.deepcopy(m)
    m_d.F0.value += 2e-9
    m_d.DM.value += 5e-3
    fd = WidebandDownhillFitter(t, m_d)
    chi2_d = fd.fit_toas()
    assert chi2_d <= chi2_ref * 1.01
    assert abs(fd.model.DM.value - 15.9905) < 1e-4

    m_l = copy.deepcopy(m)
    m_l.F0.value += 2e-9
    m_l.DM.value += 5e-3
    fl = WidebandLMFitter(t, m_l)
    chi2_l = fl.fit_toas()
    assert chi2_l <= chi2_ref * 1.01
    assert abs(fl.model.DM.value - 15.9905) < 1e-4
    assert fl.model.DM.uncertainty is not None


def test_typed_fit_exceptions():
    """CorrelatedErrors from WLS on a correlated-noise model;
    MaxiterReached from an exhausted downhill loop."""
    import pytest

    from pint_tpu.fitter import (CorrelatedErrors, DownhillWLSFitter,
                                 MaxiterReached, WLSFitter)

    m = get_model(PAR + "ECORR -f L-wide 0.8\n")
    t = _wb_toas(m)
    for f in t.flags:
        f["f"] = "L-wide"
    with pytest.raises(CorrelatedErrors) as ei:
        WLSFitter(t, copy.deepcopy(m)).fit_toas()
    assert "EcorrNoise" in str(ei.value)

    m2 = get_model(PAR)
    t2 = _wb_toas(m2)
    m2p = copy.deepcopy(m2)
    m2p.F0.value += 5e-10  # recoverable (no phase wrap) but needs >1 iter
    fd = DownhillWLSFitter(t2, m2p)
    with pytest.raises(MaxiterReached):
        fd.fit_toas(maxiter=1, raise_maxiter=True)
    # the one improving step was still written back (fitter's own copy)
    assert abs(fd.model.F0.value - 218.8) < 1e-10


def test_powell_fitter():
    from pint_tpu.fitter import PowellFitter, WLSFitter

    m = get_model(PAR)
    # two frequencies: single-frequency data leaves DM degenerate with
    # the mean (Powell would walk DM to absurd values instead of F0)
    mjds = np.linspace(55000, 56000, 50)
    freqs = np.where(np.arange(50) % 2, 1400.0, 800.0)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                obs="gbt", add_noise=True, seed=3)
    mp = copy.deepcopy(m)
    mp.F0.value += 3e-10
    f = PowellFitter(t, mp)
    chi2 = f.fit_toas()
    ref = WLSFitter(t, copy.deepcopy(m))
    chi2_ref = ref.fit_toas()
    assert chi2 <= chi2_ref * 1.05
    assert abs(f.model.F0.value - ref.model.F0.value) < 3 * (
        ref.model.F0.uncertainty or 1e-9)


def test_dmjump_recovers_receiver_offset():
    """DMJUMP: a receiver-dependent offset in the measured DMs is
    absorbed by the masked DMJUMP parameter, not by global DM
    (reference: dispersion_model.py::DispersionJump; convention
    resid = dm_obs - (dm_model + DMJUMP), i.e. DMJUMP subtracts from
    the measurement)."""
    rng = np.random.default_rng(5)
    par = PAR + "DMJUMP -fe Rcvr_800 0.0 1\n"
    m = get_model(par)
    assert "DispersionJump" in m.components
    mjds = np.linspace(55000, 56000, 60)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=5)
    offset = 3e-3
    for i, f in enumerate(t.flags):
        f["fe"] = "Rcvr_800" if i % 2 else "Rcvr1_2"
        dm = 15.99 + (offset if i % 2 else 0.0)
        f["pp_dm"] = f"{dm + rng.standard_normal() * 1e-4:.8f}"
        f["pp_dme"] = "1e-4"
    fit = WidebandTOAFitter(t, copy.deepcopy(m))
    fit.fit_toas(maxiter=3)
    # upstream sign: the jump enters the model DM negated, so a +offset
    # measurement bias fits as DMJUMP = -offset
    assert abs(fit.model.DMJUMP1.value - (-offset)) < 5e-5
    assert abs(fit.model.DM.value - 15.99) < 1e-4
    # par round trip keeps the jump (mask spec + fitted value)
    m2 = get_model(fit.model.as_parfile())
    assert abs(m2.DMJUMP1.value - fit.model.DMJUMP1.value) < 1e-12
    assert m2.DMJUMP1.key == "-fe" and m2.DMJUMP1.key_value == ["Rcvr_800"]


def test_free_dmjump_rejected_by_narrowband_fitters():
    """A free DMJUMP has a zero time-domain design column; WLS/GLS must
    refuse rather than report a zero-uncertainty no-op (review finding)."""
    import pytest

    from pint_tpu.fitter import DownhillWLSFitter, WLSFitter

    par = PAR + "DMJUMP -fe Rcvr_800 1e-3 1\n"
    m = get_model(par)
    t = make_fake_toas_fromMJDs(np.linspace(55000, 55500, 20), m,
                                error_us=1.0, freq_mhz=1400.0, obs="gbt",
                                add_noise=True, seed=1)
    for f in t.flags:
        f["fe"] = "Rcvr_800"
    with pytest.raises(ValueError, match="DMJUMP"):
        WLSFitter(t, m).fit_toas()
    with pytest.raises(ValueError, match="DMJUMP"):
        DownhillWLSFitter(t, m).fit_toas()
    # frozen DMJUMP is fine narrowband
    m.DMJUMP1.frozen = True
    WLSFitter(t, m).fit_toas(maxiter=1)


def test_wideband_gls_with_red_noise_and_ecorr():
    """Wideband fitters stack TOA-noise bases (red noise + ECORR) like
    the narrowband GLS (reference: WidebandTOAFitter is a GLS fitter);
    parameter recovery must survive injected correlated noise, and the
    basis amplitudes must absorb it."""
    from pint_tpu.fitter import (WidebandDownhillFitter, WidebandLMFitter)
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = PAR + ("ECORR 0.8\nRNAMP 2e-14\nRNIDX -3.5\nTNREDC 15\n")
    m = get_model(par)
    rng = np.random.default_rng(9)
    days = np.sort(rng.uniform(55000, 56000, 40))
    mjds = np.sort(np.concatenate([days + k * 0.3 / 86400 for k in range(3)]))
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True,
                                add_correlated_noise=True, seed=9)
    for f in t.flags:
        f["pp_dm"] = f"{15.99 + rng.standard_normal() * 1e-4:.8f}"
        f["pp_dme"] = "1e-4"
    m2 = copy.deepcopy(m)
    m2.F0.value += 3e-10
    fit = WidebandTOAFitter(t, m2)
    chi2 = fit.fit_toas(maxiter=3)
    assert fit.noise_ampls is not None and len(fit.noise_ampls) > 0
    # per-component realizations over the TOA rows, eager-captured
    # against the fit's own prepare
    nr = fit.get_noise_resids()
    assert set(nr) == {"EcorrNoise", "PLRedNoise"}
    assert all(v.shape == (len(t),) for v in nr.values())
    assert fit._noise_basis_segments is not None
    # F0 recovered despite injected red+ECORR noise
    assert abs(fit.model.F0.value - m.F0.value) < 5e-11
    assert abs(fit.model.DM.value - 15.99) < 1e-3
    assert np.isfinite(chi2)

    # downhill + LM variants run the same noise-aware system
    m3 = copy.deepcopy(m)
    m3.F0.value += 3e-10
    fd = WidebandDownhillFitter(t, m3)
    fd.fit_toas(maxiter=6)
    assert abs(fd.model.F0.value - m.F0.value) < 5e-11
    m4 = copy.deepcopy(m)
    m4.F0.value += 3e-10
    fl = WidebandLMFitter(t, m4)
    fl.fit_toas(maxiter=10)
    assert abs(fl.model.F0.value - m.F0.value) < 1e-10


def test_simulated_wideband_roundtrip(tmp_path):
    """zima --wideband writes -pp_dm/-pp_dme flags at the model DM;
    a WidebandTOAFitter on the written tim recovers a perturbed DM
    (reference: zima --wideband + simulation.py wideband TOAs)."""
    from pint_tpu.scripts import zima
    from pint_tpu.toa import get_TOAs

    par = tmp_path / "wb.par"
    par.write_text("PSR TWB\nRAJ 2:00:00\nDECJ 3:00:00\nF0 250.0 1\n"
                   "F1 -4e-16 1\nPEPOCH 55500\nDM 31.5 1\n")
    tim = tmp_path / "wb.tim"
    assert zima.main([str(par), str(tim), "--ntoa", "60", "--startMJD",
                      "55000", "--duration", "800", "--addnoise",
                      "--wideband", "--dmerror", "2e-4",
                      "--seed", "4"]) == 0
    t = get_TOAs(str(tim))
    dms = [f.get("pp_dm") for f in t.flags]
    assert all(d is not None for d in dms)
    assert abs(np.mean([float(d) for d in dms]) - 31.5) < 1e-3
    m = get_model(str(par))
    m.DM.value += 3e-3  # perturb; DM data must pull it back
    f = WidebandTOAFitter(t, m)
    f.fit_toas()
    assert abs(f.model.DM.value - 31.5) < 5 * f.model.DM.uncertainty
    assert f.model.DM.uncertainty < 1e-4
