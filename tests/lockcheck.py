"""Runtime lock-discipline instrumentation — the dynamic counterpart
of pintlint's static lock-discipline rule (pint_tpu/analysis/
rules_locks.py).

The static rule sees direct ``self.attr`` mutations; it cannot see a
mutation through a local alias (``e = self._keys[k]; e["n"] += 1``) or
prove that two threads actually interleave. This helper closes that
gap at test time: instrument a shared class while a genuinely
multi-threaded scenario runs (the fleet's pipelined fit, the serve
engine's concurrent prewarm) and record every attribute rebind or
dict mutation performed by a non-owner thread that does not hold the
instance's RLock.

Two mechanisms, composed by :func:`instrument`:

- class-level ``__setattr__`` patching catches attribute REBINDS
  (``self.hits += 1``, ``self._prep_pool = None``);
- :class:`GuardedDict` / :class:`GuardedOrderedDict` wrap dict-valued
  shared attributes so in-place mutations (``setdefault``, ``pop``,
  ``move_to_end``) are checked too — these never go through
  ``__setattr__``.

A write is a violation only when BOTH hold: the writing thread is not
the thread that constructed the instance (single-threaded setup code
is fine unlocked), and the writer does not hold the lock (checked via
RLock._is_owned()).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from contextlib import contextmanager


class Violation:
    __slots__ = ("cls", "attr", "op", "thread")

    def __init__(self, cls, attr, op, thread):
        self.cls = cls
        self.attr = attr
        self.op = op
        self.thread = thread

    def __repr__(self):
        return (f"Violation({self.cls}.{self.attr} {self.op} "
                f"from {self.thread!r})")


def _lock_held(lock):
    """True when the CURRENT thread holds ``lock`` (RLock only —
    _is_owned is how threading.Condition itself checks)."""
    is_owned = getattr(lock, "_is_owned", None)
    return bool(is_owned()) if is_owned is not None else False


def _unsynchronized(lock, owner_ident):
    return (threading.get_ident() != owner_ident
            and (lock is None or not _lock_held(lock)))


class _GuardMixin:
    """Mutator-checking mixin for dict types; reads stay unchecked
    (the CPython dict read path is atomic enough for the monitored
    structures, and checking reads would double the noise)."""

    _MUTATORS = ("__setitem__", "__delitem__", "pop", "popitem",
                 "setdefault", "update", "clear", "move_to_end")

    def _bind_guard(self, label, lock, owner_ident, violations):
        self._guard = (label, lock, owner_ident, violations)
        return self

    def _check(self, op):
        guard = getattr(self, "_guard", None)
        if guard is None:
            return
        label, lock, owner_ident, violations = guard
        if _unsynchronized(lock, owner_ident):
            cls, attr = label
            violations.append(
                Violation(cls, attr, op,
                          threading.current_thread().name))


def _checked(name):
    def method(self, *args, **kwargs):
        self._check(name)
        return getattr(super(type(self), self), name)(*args, **kwargs)
    method.__name__ = name
    return method


class GuardedDict(_GuardMixin, dict):
    pass


class GuardedOrderedDict(_GuardMixin, OrderedDict):
    pass


for _cls in (GuardedDict, GuardedOrderedDict):
    for _m in _GuardMixin._MUTATORS:
        if hasattr(_cls, _m):
            setattr(_cls, _m, _checked(_m))


@contextmanager
def instrument(cls, violations, lock_attr="_lock", dict_attrs=(),
               exempt=("clock", "_sleep"), instances=()):
    """Patch ``cls`` so unsynchronized cross-thread writes are
    recorded in ``violations`` (a list the caller owns).

    ``dict_attrs`` names dict-valued shared attributes to wrap with
    checked dicts on the given ``instances`` (and on any instance
    constructed while the patch is active). ``exempt`` attributes are
    never flagged. Restores the class on exit.
    """
    orig_setattr = cls.__setattr__
    orig_init = cls.__init__
    guard_key = "_lockcheck_owner"
    wrapped = []

    def _wrap_dicts(obj):
        lock = obj.__dict__.get(lock_attr)
        owner = obj.__dict__.get(guard_key, threading.get_ident())
        for attr in dict_attrs:
            cur = obj.__dict__.get(attr)
            if cur is None or isinstance(cur, _GuardMixin):
                continue
            gcls = (GuardedOrderedDict if isinstance(cur, OrderedDict)
                    else GuardedDict)
            g = gcls(cur)._bind_guard((cls.__name__, attr), lock,
                                      owner, violations)
            obj.__dict__[attr] = g
            wrapped.append((obj, attr, cur))

    def patched_setattr(self, name, value):
        d = self.__dict__
        if guard_key not in d:
            d[guard_key] = threading.get_ident()
        lock = d.get(lock_attr)
        if (name != lock_attr and name != guard_key
                and name not in exempt
                and lock_attr in d  # construction still in flight
                and _unsynchronized(lock, d[guard_key])):
            violations.append(
                Violation(cls.__name__, name, "setattr",
                          threading.current_thread().name))
        orig_setattr(self, name, value)

    def patched_init(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        _wrap_dicts(self)

    cls.__setattr__ = patched_setattr
    cls.__init__ = patched_init
    for obj in instances:
        obj.__dict__.setdefault(guard_key, threading.get_ident())
        _wrap_dicts(obj)
    try:
        yield violations
    finally:
        cls.__setattr__ = orig_setattr
        cls.__init__ = orig_init
        for obj, attr, cur in wrapped:
            cur.clear()
            cur.update(obj.__dict__[attr])
            obj.__dict__[attr] = cur


def assert_no_violations(violations):
    assert not violations, (
        "unsynchronized cross-thread writes detected:\n  "
        + "\n  ".join(repr(v) for v in violations))


# -- runtime lock-ORDER recording -------------------------------------
#
# The dynamic counterpart of pintlint's whole-program lock-order-cycle
# rule (pint_tpu/analysis/rules_lockorder.py). The static analysis
# derives "acquire B while holding A" edges from with-blocks, resolved
# calls, and the *_locked convention; this recorder observes the edges
# a real multi-threaded scenario actually takes, so a test can assert
# the union of both edge sets is still acyclic — runtime behaviour must
# be a linear extension of the static DAG, never a contradiction of it.


class LockOrderRecorder:
    """Collects (held, acquired) label pairs across all RecordingLocks
    sharing this recorder. Per-thread held stacks; first witness thread
    name kept per edge."""

    def __init__(self):
        self._mu = threading.Lock()
        self.edges = {}            # (held, acquired) -> witness thread
        self._local = threading.local()

    def _held(self):
        held = getattr(self._local, "held", None)
        if held is None:
            held = self._local.held = []
        return held

    def note_acquire(self, label):
        held = self._held()
        if held:
            tname = threading.current_thread().name
            with self._mu:
                for h in held:
                    self.edges.setdefault((h, label), tname)
        held.append(label)

    def note_release(self, label):
        held = self._held()
        if held and held[-1] == label:
            held.pop()
        elif label in held:        # out-of-order release; stay sane
            held.remove(label)

    def edge_set(self):
        with self._mu:
            return set(self.edges)


class RecordingLock:
    """Transparent proxy around a real Lock/RLock that reports
    acquisition order to a :class:`LockOrderRecorder`. Reentrant
    acquires (RLock) are depth-counted per thread so only the OUTERMOST
    acquire/release records — nested re-entry is not an ordering edge.

    Installed into ``obj.__dict__`` so a ``threading.Condition`` built
    from the original lock at construction time keeps working: the
    Condition holds the real lock directly and bypasses the proxy
    (those acquisitions simply go unrecorded), while ``with
    self._lock:`` sites route through it."""

    def __init__(self, inner, label, recorder):
        self._inner = inner
        self._label = label
        self._recorder = recorder
        self._depth = threading.local()

    def _bump(self, delta):
        n = getattr(self._depth, "n", 0) + delta
        self._depth.n = n
        return n

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got and self._bump(+1) == 1:
            self._recorder.note_acquire(self._label)
        return got

    def release(self):
        if self._bump(-1) == 0:
            self._recorder.note_release(self._label)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):   # _is_owned, locked, ...
        return getattr(self._inner, name)


@contextmanager
def record_order(*specs, recorder=None):
    """Wrap each instance's lock with a RecordingLock for the duration.

    ``specs`` are ``(obj, label)`` or ``(obj, label, lock_attr)``
    tuples; ``label`` should match the static analyzer's node naming
    ("ClassName.attr") so edge sets compare directly. Yields the
    recorder; restores the original locks on exit."""
    rec = recorder if recorder is not None else LockOrderRecorder()
    saved = []
    for spec in specs:
        obj, label = spec[0], spec[1]
        lock_attr = spec[2] if len(spec) > 2 else "_lock"
        inner = obj.__dict__[lock_attr]
        obj.__dict__[lock_attr] = RecordingLock(inner, label, rec)
        saved.append((obj, lock_attr, inner))
    try:
        yield rec
    finally:
        for obj, lock_attr, inner in saved:
            obj.__dict__[lock_attr] = inner


def find_cycle(edges):
    """First directed cycle in an edge iterable, as a node path
    ``[a, ..., a]``, or None when the graph is acyclic."""
    succ = {}
    for a, b in edges:
        succ.setdefault(a, set()).add(b)
    color = {}                     # missing=white, 1=on stack, 2=done
    parent = {}
    for start in sorted(succ):
        if color.get(start):
            continue
        color[start] = 1
        stack = [(start, iter(sorted(succ.get(start, ()))))]
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                c = color.get(nxt)
                if c == 1:         # back edge: cycle nxt -> ... -> node
                    path = [node]
                    while path[-1] != nxt:
                        path.append(parent[path[-1]])
                    path.reverse()
                    path.append(nxt)
                    return path
                if c is None:
                    parent[nxt] = node
                    color[nxt] = 1
                    stack.append(
                        (nxt, iter(sorted(succ.get(nxt, ())))))
                    advanced = True
                    break
            if not advanced:
                color[node] = 2
                stack.pop()
    return None


def assert_order_consistent(runtime_edges, static_edges):
    """Assert the union of runtime-observed and static lock-order
    edges is acyclic. Returns the combined edge set. A cycle here means
    the running system took locks in an order the static DAG forbids —
    a latent deadlock the single test run happened to survive."""
    combined = set(runtime_edges) | set(static_edges)
    cycle = find_cycle(combined)
    assert cycle is None, (
        "runtime lock acquisition order contradicts the static "
        "lock-order DAG; combined cycle: " + " -> ".join(cycle))
    return combined
