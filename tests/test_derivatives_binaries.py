"""Design-matrix cross-checks per binary family: jacfwd columns vs
central finite differences of the phase for every free parameter, for
each of the seven binary models (DD, DDS, DDK, DDGR, BT, ELL1H, ELL1k)
including their post-Keplerian and Shapiro parameterizations.

(reference pattern: SURVEY.md section 4 pattern 2 — upstream carries a
per-family derivative test file (test_dd.py, test_ell1h.py, ...)
checking analytic derivatives against d_delay_d_param_num; here jacfwd
is the analytic side and central differences the independent check.
tests/test_derivatives.py covers ELL1 + the non-binary components with
the same machinery.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

import jax

from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_fromMJDs

_COMMON = ("PSR TDBIN\nRAJ 07:51:09.2\nDECJ 18:07:38.5 1\n"
           "F0 287.457853 1\nF1 -3.44e-15 1\nPEPOCH 55400\nDM 19.6 1\n")

# (case id, par tail, {param: (rel_step, abs_floor[, tol])}).
# Default tol 1e-4; looser per-param tolerances are the measured
# central-difference noise/curvature floor: the phase is ~1e10
# cycles so FD cancellation bottoms out at ~2e-6/h cycles, and
# near-edge-on Shapiro (SINI~0.99) has O(h^2) curvature ~1e-2 —
# the test targets sign/units/factor bugs, not that floor.
_DEFAULT_STEP = (1e-6, 0.0)
CASES = [
    ("DD_full", _COMMON + (
        "BINARY DD\nPB 0.3229 1\nA1 1.8599 1\nT0 55400.15 1\n"
        "ECC 0.0878 1\nOM 73.8 1\nOMDOT 4.22 1\nPBDOT -2.4e-12 1\n"
        "GAMMA 0.0044 1\nM2 1.25 1\nSINI 0.9874 1\nA1DOT 1e-14 1\n"
        "EDOT 1e-15 1\n"),
     {"PB": (1e-9, 0), "A1": (1e-8, 0), "T0": (3e-10, 0),
      "ECC": (1e-6, 0), "OM": (1e-7, 0), "OMDOT": (1e-4, 0),
      "PBDOT": (1e-3, 0), "GAMMA": (1e-3, 0), "M2": (0, 0.02, 1e-3),
      "SINI": (0, 1e-3, 2e-2), "A1DOT": (1e-3, 0, 1e-3),
      "EDOT": (1e-3, 0, 2e-3)}),
    ("DDS_shapmax", _COMMON + (
        "BINARY DDS\nPB 0.3229 1\nA1 1.8599 1\nT0 55400.15 1\n"
        "ECC 0.0878 1\nOM 73.8 1\nM2 1.25 1\nSHAPMAX 2.25 1\n"),
     {"PB": (1e-9, 0), "A1": (1e-8, 0), "T0": (3e-10, 0),
      "ECC": (1e-6, 0), "OM": (1e-7, 0), "M2": (0, 0.02),
      "SHAPMAX": (1e-4, 0)}),
    ("DDK_kopeikin", _COMMON + (
        "PMRA -2.66 1\nPMDEC -25.5 1\nPX 1.0 1\nPOSEPOCH 55400\n"
        "BINARY DDK\nPB 0.3229 1\nA1 1.8599 1\nT0 55400.15 1\n"
        "ECC 0.0878 1\nOM 73.8 1\nM2 1.25 1\nKIN 80.6 1\nKOM 45.0 1\n"),
     {"PB": (1e-9, 0), "A1": (1e-8, 0), "T0": (3e-10, 0),
      "ECC": (1e-6, 0), "OM": (1e-7, 0), "M2": (0, 0.02),
      "KIN": (1e-6, 0, 1e-3), "KOM": (0, 0.05, 5e-3), "PX": (0, 0.1),
      "PMRA": (1e-4, 0, 1e-3), "PMDEC": (1e-4, 0, 1e-3)}),
    ("DDGR_masses", _COMMON + (
        "BINARY DDGR\nPB 0.10225 1\nA1 1.4150 1\nT0 55400.05 1\n"
        "ECC 0.0877775 1\nOM 87.03 1\nMTOT 2.58708 1\nM2 1.2489 1\n"),
     {"PB": (1e-9, 0), "A1": (1e-8, 0), "T0": (3e-10, 0),
      "ECC": (1e-6, 0), "OM": (1e-7, 0), "MTOT": (1e-6, 0),
      "M2": (1e-4, 0, 5e-3)}),
    ("BT_basic", _COMMON + (
        "BINARY BT\nPB 117.349 1\nA1 64.809 1\nT0 55402.0 1\n"
        "ECC 0.6584 1\nOM 226.9 1\nGAMMA 0.005 1\n"),
     {"PB": (1e-9, 0), "A1": (1e-8, 0), "T0": (1e-9, 0),
      "ECC": (1e-6, 0), "OM": (1e-7, 0), "GAMMA": (1e-3, 0)}),
    ("ELL1H_ortho", _COMMON + (
        "BINARY ELL1H\nPB 5.7410 1\nA1 3.3667 1\nTASC 55401.0 1\n"
        "EPS1 1.9e-5 1\nEPS2 -8e-6 1\nH3 2.7e-7 1\nH4 2.0e-7 1\n"),
     {"PB": (1e-9, 0), "A1": (1e-8, 0), "TASC": (1e-9, 0),
      "EPS1": (1e-3, 0), "EPS2": (1e-3, 0), "H3": (1e-3, 0),
      "H4": (1e-3, 0)}),
    ("ELL1k_precessing", _COMMON + (
        "BINARY ELL1k\nPB 0.0907 1\nA1 0.0362 1\nTASC 55400.02 1\n"
        "EPS1 2e-5 1\nEPS2 -1e-5 1\nOMDOT 10.0 1\nLNEDOT 1e-10 1\n"),
     {"PB": (1e-9, 0), "A1": (1e-8, 0, 5e-4), "TASC": (1e-10, 0),
      "EPS1": (0, 2e-7, 5e-4), "EPS2": (0, 2e-7, 5e-4),
      "OMDOT": (0, 0.1, 1e-3), "LNEDOT": (0, 1e-11, 2e-3)}),
]


@pytest.mark.parametrize("case_id,par,steps", CASES,
                         ids=[c[0] for c in CASES])
def test_binary_design_columns_match_fd(case_id, par, steps):
    m = get_model(par)
    n = 90
    mjds = np.linspace(55300, 55700, n)
    freqs = np.tile([800.0, 1400.0, 2100.0], n // 3)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=freqs,
                                obs="gbt", add_noise=False)
    prepared = m.prepare(t)
    dm_fn, labels = prepared.designmatrix_fn()
    off = 1 if labels[0] == "Offset" else 0
    x0 = np.asarray(prepared.vector_from_params())
    M = np.asarray(dm_fn(prepared.vector_from_params()))
    phase_fn = jax.jit(
        lambda x: prepared._phase_continuous(prepared.params_with_vector(x)))
    names = [nm for nm, _, _ in prepared.free_param_map()]

    # every binary parameter in the case must actually be free
    for p in steps:
        assert p in names, f"{case_id}: {p} not free in the packed model"

    failures = []
    for j, name in enumerate(names):
        spec = steps.get(name, _DEFAULT_STEP)
        rel, floor = spec[0], spec[1]
        tol = spec[2] if len(spec) > 2 else 1e-4
        h = max(abs(x0[j]) * rel if x0[j] != 0 else rel, floor)
        if h == 0:
            continue
        xp, xm = x0.copy(), x0.copy()
        xp[j] += h
        xm[j] -= h
        dnum = (np.asarray(phase_fn(xp)) - np.asarray(phase_fn(xm))) / (2 * h)
        dana = M[:, off + j]
        scale = max(np.abs(dnum).max(), np.abs(dana).max())
        if scale == 0:
            failures.append((name, "both zero"))
            continue
        err = np.abs(dana - dnum).max() / scale
        if err > tol:
            failures.append((name, float(err), tol))
    assert not failures, f"{case_id} jacfwd vs numeric: {failures}"
