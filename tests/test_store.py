"""Packed-TOA columnar store (pint_tpu/store): keying, CRC framing,
invalidation, and crash behavior.

The contract under test (ISSUE 13): a store entry can cost TIME,
never CORRECTNESS. Every failure mode — bitrot, truncation, a stale
jax/pack-geometry identity, a mismatched content signature — must
warn, delete the entry, and rebuild from live prep to bit-identical
fit parameters; a SIGKILL at the ``store_write`` fault point must
leave no torn artifact on disk.
"""

import copy
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.parallel import PTAFleet
from pint_tpu.simulation import make_fake_toas_fromMJDs
from pint_tpu.store import (PackStore, content_signature,
                            store_identity)


def _fleet_inputs(n_psr=3, base_toas=30):
    rng = np.random.default_rng(0)
    models, toas_list = [], []
    for i in range(n_psr):
        par = (f"PSR ST{i}\nRAJ 1{i % 10}:00:00.0\n"
               f"DECJ {5 + i}:30:00.0\nF0 {200 + 10 * i}.5 1\n"
               f"F1 -{3 + i}e-16 1\nPEPOCH 55500\nDM {10 + i}.5 1\n")
        m = get_model(par)
        n = base_toas + 5 * i
        mjds = np.sort(rng.uniform(55000, 56000, n))
        freqs = np.where(np.arange(n) % 2, 1400.0, 800.0)
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0,
                                    freq_mhz=freqs, obs="gbt",
                                    add_noise=True, seed=i)
        models.append(m)
        toas_list.append(t)
    return models, toas_list


def _fit(models, toas_list, store=None):
    fleet = PTAFleet([copy.deepcopy(m) for m in models], toas_list,
                     store=store)
    x, chi2, cov = fleet.fit(method="wls", maxiter=2)
    return np.asarray(x), np.asarray(chi2)


_SYNTH = None


def _synthetic_state():
    # pack_state-shaped tree: dict/list/tuple nodes, numeric numpy
    # leaves, and non-array scalars/strings in the meta region
    return {
        "params": np.linspace(0.0, 1.0, 7),
        "batch": {"day": np.arange(10, dtype=np.int64),
                  "freq": np.full((2, 5), 1400.0, np.float32)},
        "free_map": [("F0", 0), ("DM", 2)],
        "n_toas": np.array([10, 10]),
        "static": {"ephem": "de440", "planets": True},
    }


def test_synthetic_roundtrip_and_mmap_views(tmp_path):
    store = PackStore(tmp_path)
    state = _synthetic_state()
    sig = "pack-" + "a" * 40
    nbytes = store.put(sig, (0, 256), state)
    assert nbytes > 0
    out = store.load(sig, (0, 256))
    assert out is not None
    np.testing.assert_array_equal(out["params"], state["params"])
    np.testing.assert_array_equal(out["batch"]["day"],
                                  state["batch"]["day"])
    assert out["batch"]["freq"].dtype == np.float32
    assert out["batch"]["freq"].shape == (2, 5)
    # container types and non-array leaves survive the meta pickle
    assert out["free_map"] == [("F0", 0), ("DM", 2)]
    assert isinstance(out["free_map"][0], tuple)
    assert out["static"] == {"ephem": "de440", "planets": True}
    # array leaves are read-only views over the pinned mmap
    assert not out["params"].flags.writeable
    c = store.counters()
    assert c["puts"] == 1 and c["hits"] == 1 and c["misses"] == 0
    assert c["bytes_written"] == nbytes and c["bytes_mapped"] > 0


def test_cold_miss_counts_rebuild(tmp_path):
    store = PackStore(tmp_path)
    assert store.load("pack-" + "b" * 40, (0, 256)) is None
    c = store.counters()
    assert c["misses"] == 1 and c["rebuilds"] == 1 and c["hits"] == 0


def test_content_signature_discriminates():
    models, toas_list = _fleet_inputs(2)
    sig = content_signature(models, toas_list, bucket_floor=256)
    # deterministic over equal inputs (deepcopies)
    assert content_signature([copy.deepcopy(m) for m in models],
                             toas_list, bucket_floor=256) == sig
    # a par-file edit must change the key
    m2 = copy.deepcopy(models[0])
    m2.F0.value += 1e-6
    assert content_signature([m2, models[1]], toas_list,
                             bucket_floor=256) != sig
    # fewer TOA tables / different bucketing options must change it
    assert content_signature(models, toas_list[:1],
                             bucket_floor=256) != sig
    assert content_signature(models, toas_list,
                             bucket_floor=512) != sig
    # the environment identity is deliberately NOT hashed into the
    # signature (it is checked at load; see the geometry-bump test)
    assert sig.startswith("pack-")
    assert set(store_identity()) == {"format", "jax_version",
                                     "pack_geometry"}


def test_fleet_store_hit_is_bit_identical(tmp_path):
    models, toas_list = _fleet_inputs()
    x_live, chi2_live = _fit(models, toas_list, store=None)

    cold = PackStore(tmp_path)
    x_cold, chi2_cold = _fit(models, toas_list, store=cold)
    cc = cold.counters()
    assert cc["misses"] >= 1 and cc["puts"] >= 1 and cc["hits"] == 0

    warm = PackStore(tmp_path)  # fresh process-equivalent
    x_warm, chi2_warm = _fit(models, toas_list, store=warm)
    wc = warm.counters()
    assert wc["hits"] >= 1 and wc["misses"] == 0 and wc["puts"] == 0

    np.testing.assert_array_equal(x_cold, x_live)
    np.testing.assert_array_equal(x_warm, x_live)
    np.testing.assert_array_equal(chi2_warm, chi2_live)


def test_byte_flip_warns_deletes_rebuilds(tmp_path):
    models, toas_list = _fleet_inputs()
    x_live, _ = _fit(models, toas_list, store=None)
    cold = PackStore(tmp_path)
    _fit(models, toas_list, store=cold)
    (key,) = [k for k in os.listdir(tmp_path) if k.endswith(".ptpk")]
    path = os.path.join(tmp_path, key)
    # flip the file's LAST byte: the file ends exactly at the final
    # column's payload, so this is real column data and some CRC
    # check must catch it
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.seek(size - 1)
        b = fh.read(1)
        fh.seek(size - 1)
        fh.write(bytes([b[0] ^ 0xFF]))

    hurt = PackStore(tmp_path)
    with pytest.warns(UserWarning, match="unusable"):
        x2, _ = _fit(models, toas_list, store=hurt)
    hc = hurt.counters()
    assert hc["corrupt"] >= 1 and hc["rebuilds"] >= 1 \
        and hc["puts"] >= 1
    np.testing.assert_array_equal(x2, x_live)
    # the rebuild re-published a verifying entry
    assert hurt.scan() == {"entries": 1, "valid": 1,
                           "corrupt_or_stale": 0,
                           "bytes": os.path.getsize(
                               os.path.join(tmp_path, key))}


def test_truncation_warns_and_rebuilds(tmp_path):
    store = PackStore(tmp_path)
    sig = "pack-" + "c" * 40
    store.put(sig, (0, 256), _synthetic_state())
    (name,) = os.listdir(tmp_path)
    path = os.path.join(tmp_path, name)
    os.truncate(path, os.path.getsize(path) // 2)
    with pytest.warns(UserWarning, match="unusable"):
        assert store.load(sig, (0, 256)) is None
    assert not os.path.exists(path)  # deleted, not left to re-fail
    c = store.counters()
    assert c["corrupt"] == 1 and c["rebuilds"] == 1


def test_signature_mismatch_is_stale_not_corrupt(tmp_path):
    # defense in depth: a file whose EMBEDDED signature disagrees
    # with the requested one (hash collision, manual copy) is stale
    store = PackStore(tmp_path)
    sig_a = "pack-" + "d" * 40
    sig_b = "pack-" + "e" * 40
    store.put(sig_a, (0, 256), _synthetic_state())
    os.rename(store._path(sig_a, (0, 256)),
              store._path(sig_b, (0, 256)))
    with pytest.warns(UserWarning, match="stale"):
        assert store.load(sig_b, (0, 256)) is None
    c = store.counters()
    assert c["stale"] == 1 and c["corrupt"] == 0


def test_geometry_bump_invalidates_visibly(tmp_path, monkeypatch):
    """A PACK_GEOMETRY_VERSION bump (a ShapePlan whose key is stable
    but whose layout moved — the PR 11 hazard) must find the OLD
    entry at the SAME path and invalidate it with warn + delete +
    rebuild, never serve stale columns and never silently orphan."""
    from pint_tpu.parallel import shapeplan

    models, toas_list = _fleet_inputs()
    x_live, _ = _fit(models, toas_list, store=None)
    _fit(models, toas_list, store=PackStore(tmp_path))
    assert len(os.listdir(tmp_path)) == 1

    monkeypatch.setattr(shapeplan, "PACK_GEOMETRY_VERSION",
                        shapeplan.PACK_GEOMETRY_VERSION + 1)
    bumped = PackStore(tmp_path)
    with pytest.warns(UserWarning, match="stale"):
        x2, _ = _fit(models, toas_list, store=bumped)
    bc = bumped.counters()
    assert bc["stale"] >= 1 and bc["rebuilds"] >= 1 and bc["puts"] >= 1
    np.testing.assert_array_equal(x2, x_live)
    # exactly one entry remains (rewritten under the new identity,
    # same content signature -> same path; no orphan accumulation)
    assert len(os.listdir(tmp_path)) == 1
    assert bumped.scan()["valid"] == 1


def test_prewarm_stages_and_load_consumes(tmp_path):
    sig = "pack-" + "f" * 40
    PackStore(tmp_path).put(sig, (0, 256), _synthetic_state())

    store = PackStore(tmp_path)
    t = store.prewarm(background=True)
    assert t is not None
    out = store.load(sig, (0, 256))  # joins the worker internally
    assert out is not None
    c = store.counters()
    assert c["prewarm_hits"] == 1 and c["hits"] == 1

    # inline prewarm (background=False) stages synchronously
    store2 = PackStore(tmp_path)
    assert store2.prewarm(background=False) is None
    assert store2.load(sig, (0, 256)) is not None
    assert store2.counters()["prewarm_hits"] == 1

    # empty directory: nothing to do, no thread
    assert PackStore(tmp_path / "empty").prewarm() is None


def test_scan_is_a_pure_probe(tmp_path):
    store = PackStore(tmp_path)
    siga = "pack-" + "1" * 40
    sigb = "pack-" + "2" * 40
    store.put(siga, (0, 256), _synthetic_state())
    store.put(sigb, (1, 512), _synthetic_state())
    rep = store.scan()
    assert rep["entries"] == 2 and rep["valid"] == 2
    assert rep["corrupt_or_stale"] == 0 and rep["bytes"] > 0
    store._damage(sigb, (1, 512), offset=3)
    with pytest.warns(UserWarning):
        rep2 = store.scan()
    assert rep2["corrupt_or_stale"] == 1 and rep2["valid"] == 1
    # a scan is telemetry-neutral: the corruption counters only move
    # for real traffic (the damaged entry was deleted by the probe)
    c = store.counters()
    assert c["corrupt"] == 0 and c["stale"] == 0


_KILL_CHILD = """
import os, warnings
warnings.simplefilter("ignore")
import numpy as np
from pint_tpu.store import PackStore
store = PackStore({d!r})
store.put("pack-" + "9" * 40, (0, 256),
          {{"a": np.arange(64.0), "s": {{"k": 1}}}})
print("SURVIVED", len(os.listdir({d!r})))
"""


def test_sigkill_at_store_write_leaves_no_torn_artifact(tmp_path):
    """The ``store_write`` process-kill fault fires immediately
    before the atomic publish: the killed writer must leave an empty
    directory (no entry, no temp file), and an unfaulted retry must
    publish a verifying entry. The serving-scale version of this —
    kill during bring-up, restart, clean-miss rebuild — runs in
    tests/test_crash_recovery.py's SIGKILL matrix."""
    d = str(tmp_path / "store")
    code = _KILL_CHILD.format(d=d)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PINT_TPU_FAULTS="process_kill:at=store_write,after=0")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == -9, proc.stderr[-1000:]
    assert "SURVIVED" not in proc.stdout
    leftovers = os.listdir(d) if os.path.isdir(d) else []
    assert leftovers == [], leftovers  # nothing torn, nothing temp

    env.pop("PINT_TPU_FAULTS")
    proc2 = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=300)
    assert proc2.returncode == 0, proc2.stderr[-1000:]
    assert "SURVIVED 1" in proc2.stdout
    rep = PackStore(d).scan()
    assert rep == {"entries": 1, "valid": 1, "corrupt_or_stale": 0,
                   "bytes": rep["bytes"]}
    assert rep["bytes"] > 0
