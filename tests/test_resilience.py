"""Chaos suite for pint_tpu.resilience: deterministic fault
injection, retry/backoff, circuit breaking, lane quarantine, health
state, checkpoint integrity, and the coordinator timeout — every
injection point exercised end-to-end on CPU with a fake clock
(tier-1-safe: no real sleeps, no accelerator, tiny batches)."""

import copy
import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu import checkpoint as ckpt_mod
from pint_tpu import fitter
from pint_tpu.checkpoint import FitCheckpointer, checkpointed_pta_fit
from pint_tpu.models import get_model
from pint_tpu.parallel import PTABatch
from pint_tpu.resilience import (BackoffPolicy, CircuitBreaker,
                                 FaultInjected, FaultPoint,
                                 HealthMonitor, arm_from_env, armed,
                                 disarm, inject, parse_spec,
                                 with_retries)
from pint_tpu.serve import FitRequest, ResidualRequest, ServeEngine
from pint_tpu.serve import policy as serve_policy
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR RSLT{i}
RAJ 11:0{i}:00.0
DECJ 9:00:00.0
F0 2{i}9.125 1
F1 -3e-16 1
PEPOCH 55500
DM 11.{i} 1
"""


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _pulsar(i=0, n_toa=24, seed=0):
    m = get_model(PAR.format(i=i))
    rng = np.random.default_rng(seed + i)
    mjds = np.sort(rng.uniform(54500, 56500, n_toa))
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=seed + i,
                                iterations=0)
    return m, t


@pytest.fixture(scope="module")
def two_pulsars():
    return [_pulsar(0, 24), _pulsar(1, 24)]


@pytest.fixture(autouse=True)
def _disarm_everything():
    yield
    disarm()


def _fake_engine(clock=None, **kw):
    clock = clock or FakeClock()
    kw.setdefault("max_latency_s", 1e9)
    kw.setdefault("bucket_floor", 32)
    eng = ServeEngine(clock=clock, sleep=clock.advance, **kw)
    return eng, clock


# -- fault injection registry ----------------------------------------


def test_fault_point_determinism():
    a = FaultPoint("toa_nan", rate=0.3, seed=7)
    b = FaultPoint("toa_nan", rate=0.3, seed=7)
    pa = [a.should_fire() for _ in range(200)]
    pb = [b.should_fire() for _ in range(200)]
    assert pa == pb  # pure function of the seed
    assert 20 < sum(pa) < 100  # rate is roughly honored
    # count caps total fires; after skips leading checks
    c = FaultPoint("toa_nan", count=2, after=3)
    fires = [c.should_fire() for _ in range(8)]
    assert fires == [False, False, False, True, True, False, False,
                     False]


def test_fire_requires_arming(two_pulsars):
    from pint_tpu.resilience import fire

    assert fire("toa_nan") is None  # disarmed: no-op
    with inject("toa_nan"):
        out = fire("toa_nan", request_id="r1")
        assert out["point"] == "toa_nan" and out["fire"] == 1
        assert out["request_id"] == "r1"
        assert "toa_nan" in armed()
    assert armed() == {} and fire("toa_nan") is None


def test_parse_spec_and_env(monkeypatch):
    pts = parse_spec("toa_nan:rate=0.05,seed=7;"
                     "compile_fail:count=1,retryable=false;"
                     "solver_diverge:lanes=0+2;"
                     "dispatch_slow:delay_s=0.5")
    by = {p.name: p for p in pts}
    assert by["toa_nan"].rate == 0.05 and by["toa_nan"].seed == 7
    assert by["compile_fail"].count == 1
    assert by["compile_fail"].payload == {"retryable": False}
    assert by["solver_diverge"].payload == {"lanes": [0, 2]}
    assert by["dispatch_slow"].payload == {"delay_s": 0.5}
    with pytest.raises(ValueError, match="unknown fault point"):
        parse_spec("not_a_point")
    monkeypatch.setenv("PINT_TPU_FAULTS", "toa_nan:rate=0.5")
    armed_pts = arm_from_env()
    assert [p.name for p in armed_pts] == ["toa_nan"]
    assert armed()["toa_nan"].rate == 0.5


# -- retry / backoff / breaker ---------------------------------------


def test_backoff_deterministic_and_bounded():
    d1 = BackoffPolicy(max_attempts=6, base_s=0.05, max_s=0.4,
                       seed=3).delays(5)
    d2 = BackoffPolicy(max_attempts=6, base_s=0.05, max_s=0.4,
                       seed=3).delays(5)
    assert d1 == d2  # deterministic under the seed
    for i, d in enumerate(d1):
        raw = min(0.4, 0.05 * 2.0 ** i)
        assert 0.5 * raw <= d <= 1.5 * raw  # jitter_frac=0.5 envelope
    nojit = BackoffPolicy(jitter_frac=0.0, base_s=0.1, max_s=0.3)
    assert nojit.delays(4) == [0.1, 0.2, 0.3, 0.3]


def test_with_retries_transient_then_success():
    calls, slept = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise FaultInjected("compile_fail", retryable=True)
        return "done"

    out = with_retries(flaky, BackoffPolicy(max_attempts=4, seed=0),
                       sleep=slept.append)
    assert out == "done" and len(calls) == 3 and len(slept) == 2


def test_with_retries_fails_fast_on_nonretryable():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("bad request")

    with pytest.raises(ValueError):
        with_retries(broken, BackoffPolicy(max_attempts=5),
                     sleep=lambda s: None)
    assert len(calls) == 1  # no retries burned on a permanent failure


def test_circuit_breaker_lifecycle():
    clock = FakeClock()
    br = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=clock)
    assert br.allow("slot")
    assert br.record_failure("slot") is False
    assert br.record_failure("slot") is True  # trips on the 2nd
    assert br.state("slot") == "open" and not br.allow("slot")
    assert br.retry_after_s("slot") == pytest.approx(10.0)
    clock.advance(10.1)
    assert br.state("slot") == "half_open"
    assert br.allow("slot")        # the single half-open trial
    assert not br.allow("slot")    # everyone else stays rejected
    br.record_failure("slot")      # trial failed: re-open, new cooldown
    assert br.state("slot") == "open"
    clock.advance(10.1)
    assert br.allow("slot")
    br.record_success("slot")      # trial succeeded: closed again
    assert br.state("slot") == "closed" and br.allow("slot")
    assert br.snapshot()["trips"] == 1


# -- engine intake validation (satellite fix) ------------------------


def test_intake_rejects_nonfinite(two_pulsars):
    (m0, t0), (m1, t1) = two_pulsars
    bad = copy.deepcopy(t0)
    bad.sec = np.array(bad.sec)
    bad.sec[3] = np.nan
    eng, _ = _fake_engine(max_batch=2)
    rb = eng.submit(FitRequest(m0, bad, maxiter=2))
    assert rb.status == "rejected" and rb.reason == "nonfinite_input"
    assert rb.telemetry["rejected"] is True
    assert rb.telemetry["detail"]["nonfinite_values"] == 1
    rg = eng.submit(FitRequest(m1, t1, maxiter=2))
    eng.drain()
    assert rg.status == "ok"  # the neighbor never saw the poison
    assert eng.telemetry.counters["rejected_nonfinite_input"] == 1
    # client-fault rejections must not degrade the engine's health
    assert eng.snapshot()["health"]["state"] == "healthy"


def test_intake_rejects_inf_errors_not_inf_freq(two_pulsars):
    (m0, t0), _ = two_pulsars
    inf_err = copy.deepcopy(t0)
    inf_err.error_us = np.array(inf_err.error_us)
    inf_err.error_us[0] = np.inf
    eng, _ = _fake_engine(max_batch=1)
    res = eng.submit(ResidualRequest(m0, inf_err))
    assert res.status == "rejected"
    assert res.telemetry["detail"]["nonfinite_errors"] == 1
    # infinite FREQUENCY is legitimate (barycentered TOAs) and must
    # pass intake
    bary = copy.deepcopy(t0)
    bary.freq_mhz = np.full_like(np.array(bary.freq_mhz), np.inf)
    assert ServeEngine._nonfinite_counts(
        ResidualRequest(m0, bary)) == (0, 0)


def test_injected_toa_nan_never_mutates_caller(two_pulsars):
    (m0, t0), _ = two_pulsars
    sec_before = np.array(t0.sec, copy=True)
    eng, _ = _fake_engine(max_batch=1)
    with inject(FaultPoint("toa_nan")):
        req = FitRequest(m0, t0, maxiter=2)
        res = eng.submit(req)
    assert res.status == "rejected" and res.reason == "nonfinite_input"
    assert res.telemetry["detail"]["injected_point"] == "toa_nan"
    np.testing.assert_array_equal(np.array(t0.sec), sec_before)
    assert req.toas is t0  # the caller's request object is untouched


def test_injected_toa_inf_error(two_pulsars):
    (m0, t0), _ = two_pulsars
    eng, _ = _fake_engine(max_batch=1)
    with inject(FaultPoint("toa_inf_error")):
        res = eng.submit(ResidualRequest(m0, t0))
    assert res.status == "rejected"
    assert res.telemetry["detail"]["injected_point"] == "toa_inf_error"
    assert res.telemetry["detail"]["nonfinite_errors"] == 1
    assert np.all(np.isfinite(np.array(t0.error_us)))


# -- lane quarantine -------------------------------------------------


def test_quarantine_isolates_poisoned_lane(two_pulsars):
    """solver_diverge poisons lane 0 of a 2-lane flush: lane 0 must be
    rejected with a structured reason and lane 1 completed from the
    warm re-run with results identical to the offline path."""
    (m0, t0), (m1, t1) = two_pulsars
    eng, _ = _fake_engine(max_batch=2)
    with inject(FaultPoint("solver_diverge", count=1,
                           payload={"lanes": [0]})):
        r0 = eng.submit(FitRequest(m0, t0, maxiter=3))
        r1 = eng.submit(FitRequest(m1, t1, maxiter=3))
    assert r0.status == "rejected" and r0.reason == "solver_diverged"
    assert r0.telemetry["detail"]["quarantined"] is True
    assert r1.status == "ok"
    assert eng.telemetry.counters["quarantined"] == 1
    off = PTABatch([m1], [t1])
    x_off, _, _ = off.wls_fit(maxiter=3)
    rel = np.max(np.abs(r1.value["x"] - np.asarray(x_off)[0])
                 / np.maximum(np.abs(np.asarray(x_off)[0]), 1e-30))
    assert rel <= 1e-12


def test_compile_fail_transient_is_retried(two_pulsars):
    (m0, t0), _ = two_pulsars
    eng, clock = _fake_engine(max_batch=1)
    with inject(FaultPoint("compile_fail", count=1)):
        res = eng.submit(ResidualRequest(m0, t0))
    assert res.status == "ok"  # retry compiled and served it
    assert eng.telemetry.counters["retries"] == 1
    assert clock.t > 0  # the backoff slept on the fake clock


def test_bisect_completes_healthy_requests(two_pulsars):
    """A non-retryable whole-flush failure is bisected: with the fault
    exhausted after one fire, both halves succeed — no healthy request
    fails, and the bisect is counted."""
    (m0, t0), (m1, t1) = two_pulsars
    eng, _ = _fake_engine(max_batch=2)
    with inject(FaultPoint("compile_fail", count=1,
                           payload={"retryable": False})):
        r0 = eng.submit(ResidualRequest(m0, t0))
        r1 = eng.submit(ResidualRequest(m1, t1))
    assert r0.status == "ok" and r1.status == "ok"
    assert eng.telemetry.counters["flush_bisects"] == 1
    assert eng.telemetry.counters.get("retries") is None


def test_dispatch_slow_trips_watchdog(two_pulsars):
    (m0, t0), _ = two_pulsars
    clock = FakeClock()
    health = HealthMonitor(clock=clock, flush_watchdog_s=5.0,
                           recovery_s=30.0)
    eng, _ = _fake_engine(clock=clock, max_batch=1, health=health)
    with inject(FaultPoint("dispatch_slow", count=1,
                           payload={"delay_s": 9.0})):
        res = eng.submit(ResidualRequest(m0, t0))
    assert res.status == "ok"  # slow, not wrong
    snap = eng.snapshot()["health"]
    assert snap["state"] == "degraded"
    assert "flush_watchdog" in snap["reasons"]
    assert snap["watchdog_breaches"] == 1
    # quiet recovery: watchdog memory expires after recovery_s
    clock.advance(61.0)
    eng.submit(ResidualRequest(m0, t0))
    eng.drain()
    assert eng.snapshot()["health"]["state"] == "healthy"


# -- circuit breaker through the engine ------------------------------


def test_breaker_trips_and_recovers_through_engine(two_pulsars):
    (m0, t0), _ = two_pulsars
    clock = FakeClock()
    breaker = CircuitBreaker(threshold=2, cooldown_s=30.0, clock=clock)
    eng, _ = _fake_engine(clock=clock, max_batch=1, breaker=breaker)
    with inject(FaultPoint("compile_fail",
                           payload={"retryable": False})):
        r1 = eng.submit(ResidualRequest(m0, t0))
        r2 = eng.submit(ResidualRequest(m0, t0))
        assert r1.status == "error" and r2.status == "error"
        # breaker now open: traffic is rejected BEFORE flushing
        r3 = eng.submit(ResidualRequest(m0, t0))
    assert r3.status == "rejected" and r3.reason == "circuit_open"
    assert r3.telemetry["detail"]["retry_after_s"] > 0
    assert eng.snapshot()["health"]["state"] != "healthy"
    # cooldown elapses, fault is gone: half-open trial closes it
    clock.advance(30.1)
    r4 = eng.submit(ResidualRequest(m0, t0))
    assert r4.status == "ok"
    assert breaker.state(next(iter(breaker._keys))) == "closed"


def test_unexpected_recompile_trips_breaker(two_pulsars):
    (m0, t0), _ = two_pulsars
    clock = FakeClock()
    breaker = CircuitBreaker(threshold=1, cooldown_s=30.0, clock=clock)
    eng, _ = _fake_engine(clock=clock, max_batch=1, breaker=breaker)
    req = ResidualRequest(m0, t0)
    key = eng.batcher.slot_key(req, serve_policy.resolve(req))
    # a prior executable for this slot that the next compile won't
    # match = the shape-contract violation the counter exists for
    eng._slot_exec_keys[key] = {"sentinel-executable"}
    res = eng.submit(ResidualRequest(m0, t0))
    assert res.status == "ok"  # the request itself still completes
    assert eng.telemetry.counters["unexpected_recompiles"] == 1
    assert breaker.open_count() == 1
    follow = eng.submit(ResidualRequest(m0, t0))
    assert follow.status == "rejected"
    assert follow.reason == "circuit_open"


# -- health state machine --------------------------------------------


def test_health_shed_rate_transitions():
    clock = FakeClock()
    h = HealthMonitor(clock=clock, window=8, min_events=4,
                      degraded_shed_rate=0.25, draining_shed_rate=0.75,
                      recovery_s=10.0)
    assert h.state == "healthy"
    for _ in range(3):
        h.note_request("ok")
    h.note_request("shed")
    assert h.state == "degraded" and "shed_rate" in h.reasons
    for _ in range(6):
        h.note_request("shed")
    assert h.state == "draining"
    assert "shed_rate_critical" in h.reasons
    # draining rejections don't feed the window: recovery is possible
    for _ in range(8):
        h.note_request("rejected", "draining")
        h.note_request("ok")
    clock.advance(10.1)
    h.note_request("ok")
    clock.advance(10.1)
    h.note_request("ok")
    assert h.state == "healthy"


def test_health_ignores_client_fault_rejections():
    h = HealthMonitor(clock=FakeClock(), window=8, min_events=4)
    for _ in range(20):
        h.note_request("rejected", "nonfinite_input")
    assert h.state == "healthy" and h.shed_rate() == 0.0


def test_draining_engine_rejects_submits(two_pulsars):
    (m0, t0), _ = two_pulsars
    clock = FakeClock()
    health = HealthMonitor(clock=clock, min_events=2,
                           draining_shed_rate=0.5)
    health.note_request("shed")
    health.note_request("shed")
    assert health.state == "draining"
    eng, _ = _fake_engine(clock=clock, max_batch=1, health=health)
    res = eng.submit(ResidualRequest(m0, t0))
    assert res.status == "rejected" and res.reason == "draining"
    assert eng.telemetry.counters["rejected_draining"] == 1


def test_snapshot_exports_health_and_breaker(two_pulsars):
    (m0, t0), _ = two_pulsars
    eng, _ = _fake_engine(max_batch=1)
    eng.submit(ResidualRequest(m0, t0))
    snap = eng.snapshot()
    assert snap["health"]["state"] == "healthy"
    assert set(snap["breaker"]) == {"trips", "open", "tracked_keys"}
    import json

    json.dumps(snap)  # JSON-safe end to end


# -- checkpoint integrity (satellite fix) ----------------------------


def _state(i):
    return {"x": np.linspace(0, 1, 8) + i, "iter": i,
            "chi2": np.array([4.0 + i]),
            "param_names": np.array(["F0", "F1"])}


def test_checkpoint_crc_roundtrip(tmp_path):
    ckpt = FitCheckpointer(tmp_path)
    ckpt.save("fit", _state(1))
    out = ckpt.restore("fit")
    assert int(out["iter"]) == 1
    np.testing.assert_array_equal(out["x"], _state(1)["x"])
    assert [str(n) for n in out["param_names"]] == ["F0", "F1"]
    assert ckpt_mod.INTEGRITY_KEY not in out


def test_checkpoint_corruption_falls_back_to_prev(tmp_path):
    ckpt = FitCheckpointer(tmp_path)
    ckpt.save("fit", _state(1))
    ckpt.save("fit", _state(2))  # rotates iter=1 to fit.prev
    ckpt._corrupt_snapshot("fit")
    with pytest.warns(UserWarning,
                      match="unreadable or corrupt|integrity"):
        out = ckpt.restore("fit")
    assert out is not None and int(out["iter"]) == 1  # the .prev copy
    # corrupt the fallback too: nothing valid survives
    ckpt._corrupt_snapshot("fit.prev")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert ckpt.restore("fit") is None


def test_checkpoint_corrupt_injection_point(tmp_path):
    ckpt = FitCheckpointer(tmp_path)
    ckpt.save("fit", _state(1))
    with inject(FaultPoint("checkpoint_corrupt")):
        ckpt.save("fit", _state(2))  # snapshot damaged on disk
    with pytest.warns(UserWarning):
        out = ckpt.restore("fit")
    assert out is not None and int(out["iter"]) == 1


def test_checkpointed_pta_fit_restarts_cleanly(tmp_path):
    m, t = _pulsar(2, 20)
    pta = PTABatch([m], [t])
    x, chi2, _ = checkpointed_pta_fit(pta, tmp_path, tag="w", every=1,
                                      maxiter=1, method="wls")
    assert np.all(np.isfinite(np.asarray(chi2)))
    ckpt = FitCheckpointer(tmp_path)
    ckpt._corrupt_snapshot("w")  # only one snapshot exists: no .prev
    pta2 = PTABatch([m], [t])
    with pytest.warns(UserWarning, match="no valid snapshot survives"):
        x2, chi2b, _ = checkpointed_pta_fit(pta2, tmp_path, tag="w",
                                            every=1, maxiter=1,
                                            method="wls")
    # restarted from scratch and refit to the same answer
    np.testing.assert_allclose(np.asarray(x2), np.asarray(x),
                               rtol=1e-9)


def test_legacy_snapshot_without_crc_restores(tmp_path):
    # pre-embed era layout: a plain data .npz next to a .meta.json
    # sidecar with no integrity record — built by hand, since save()
    # now embeds meta+CRC inside a single snapshot artifact
    import json
    import os

    state = _state(3)
    numeric = {k: np.asarray(v) for k, v in state.items()
               if np.asarray(v).dtype.kind not in "US"}
    meta = {k: np.asarray(v).tolist() for k, v in state.items()
            if np.asarray(v).dtype.kind in "US"}
    np.savez(os.path.join(str(tmp_path), "fit.npz"), **numeric)
    with open(os.path.join(str(tmp_path), "fit.meta.json"), "w") as fh:
        json.dump(meta, fh)
    ckpt = FitCheckpointer(tmp_path)
    out = ckpt.restore("fit")
    assert out is not None and int(out["iter"]) == 3
    assert [str(n) for n in out["param_names"]] == ["F0", "F1"]


# -- solver_diverge at the fitter/pta entries ------------------------


def test_fitter_solver_diverge_raises():
    m, t = _pulsar(3, 20)
    f = fitter.WLSFitter(t, m)
    with inject(FaultPoint("solver_diverge", count=1)):
        with pytest.raises(fitter.ConvergenceFailure,
                           match="injected solver divergence"):
            f.fit_toas(maxiter=1)
        # count=1 exhausted: the hook goes quiet again
        fitter._maybe_inject_solver_diverge("wls")
    fitter._maybe_inject_solver_diverge("wls")  # disarmed: no-op


def test_pta_solver_diverge_isolates_lane(two_pulsars):
    (m0, t0), (m1, t1) = two_pulsars
    pta = PTABatch([m0, m1], [t0, t1])
    x_clean, _, _ = pta.wls_fit(maxiter=2)
    pta2 = PTABatch([m0, m1], [t0, t1])
    with inject(FaultPoint("solver_diverge", count=1,
                           payload={"lanes": [1]})):
        with pytest.warns(UserWarning, match="diverged"):
            x, chi2, _ = pta2.wls_fit(maxiter=2)
    assert list(pta2.diverged) == [1]
    assert not np.isfinite(chi2[1])
    # lane 0 is untouched; lane 1 got its start vector back
    np.testing.assert_allclose(x[0], np.asarray(x_clean)[0], rtol=1e-12)
    np.testing.assert_array_equal(x[1], np.asarray(pta2._x0())[1])


# -- distributed coordinator timeout (satellite fix) -----------------


def test_initialize_distributed_timeout_message():
    """Unreachable coordinator must surface a TimeoutError naming the
    address, process id, and elapsed time within the configured bound
    (subprocess: the abandoned native handshake thread must not leak
    into the test session)."""
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "from pint_tpu.parallel.distributed import "
        "initialize_distributed\n"
        "try:\n"
        "    initialize_distributed("
        "coordinator_address='127.0.0.1:1', num_processes=2, "
        "process_id=0, timeout_s=2.0)\n"
        "    print('NO-ERROR')\n"
        "except TimeoutError as e:\n"
        "    print('TIMEOUT-OK:', e)\n"
        "import os as _os\n"
        "_os._exit(0)\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120,
                         cwd="/root/repo")
    assert "TIMEOUT-OK:" in out.stdout
    assert "127.0.0.1:1" in out.stdout
    assert "process_id=0" in out.stdout
    assert "did not complete within 2.0s" in out.stdout


def test_initialize_distributed_env_timeout(monkeypatch):
    """JAX_COORDINATOR_TIMEOUT_S is honored without the kwarg."""
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "os.environ['JAX_COORDINATOR_TIMEOUT_S'] = '2'\n"
        "from pint_tpu.parallel.distributed import "
        "initialize_distributed\n"
        "try:\n"
        "    initialize_distributed("
        "coordinator_address='127.0.0.1:1', num_processes=2, "
        "process_id=0)\n"
        "except TimeoutError:\n"
        "    print('ENV-TIMEOUT-OK')\n"
        "import os as _os\n"
        "_os._exit(0)\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120,
                         cwd="/root/repo")
    assert "ENV-TIMEOUT-OK" in out.stdout


# -- end-to-end chaos acceptance (miniature) -------------------------


def test_chaos_stream_contract():
    """Miniature of the bench chaos acceptance run: 20% toa_nan into a
    small mixed stream — every injected request structurally rejected,
    every healthy request identical to the fault-free run, engine ends
    healthy with zero unexpected recompiles."""
    from pint_tpu.scripts.pint_serve_bench import run_chaos_stream

    report = run_chaos_stream(n_requests=24, fault_rate=0.2,
                              max_batch=4, bucket_floor=32,
                              sizes=(24,), per_combo=1, maxiter=2,
                              seed=1)
    assert report["ok"], report
    assert report["injected"] >= 1  # the schedule actually fired
    assert report["healthy_failures"] == 0
    assert report["max_rel_diff_vs_clean"] == 0.0
    assert report["health_state"] == "healthy"
    assert report["unexpected_recompiles"] == 0


# -- breaker/health checkpoint serialization (ISSUE 6 satellite) -----


def test_breaker_state_roundtrip_reanchors_cooldown():
    """An open key's cooldown serializes as REMAINING seconds and
    re-anchors on the restoring clock: a restarted process (fresh
    monotonic epoch) keeps the breaker open for exactly the time the
    crashed process had left."""
    clock = FakeClock()
    br = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=clock)
    br.record_failure(("lane", 0))
    br.record_failure(("lane", 0))  # trips
    clock.advance(4.0)  # 6 s of cooldown left at snapshot time
    state = br.state_dict()

    clock2 = FakeClock()
    clock2.advance(12345.0)  # unrelated monotonic epoch
    br2 = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=clock2)
    assert br2.load_state_dict(state)
    assert br2.state(("lane", 0)) == "open"
    assert br2.retry_after_s(("lane", 0)) == pytest.approx(6.0)
    assert br2.snapshot()["trips"] == 1
    clock2.advance(6.1)
    assert br2.state(("lane", 0)) == "half_open"


def test_breaker_state_version_mismatch_warns_and_resets():
    br = CircuitBreaker(clock=FakeClock())
    with pytest.warns(UserWarning, match="version/kind mismatch"):
        assert not br.load_state_dict(
            {"version": 99, "kind": "circuit_breaker"})
    assert br.state("anything") == "closed"  # left reset, not guessed


def test_health_state_roundtrip_preserves_standing():
    clock = FakeClock()
    h = HealthMonitor(clock=clock, window=8, min_events=4,
                      degraded_shed_rate=0.25, recovery_s=10.0)
    for _ in range(3):
        h.note_request("ok")
    h.note_request("shed")
    assert h.state == "degraded"
    state = h.state_dict()

    clock2 = FakeClock()
    clock2.advance(777.0)
    h2 = HealthMonitor(clock=clock2, window=8, min_events=4,
                       degraded_shed_rate=0.25, recovery_s=10.0)
    assert h2.load_state_dict(state)
    assert h2.state == "degraded" and "shed_rate" in h2.reasons
    # hysteresis survives the restart: recovery still needs the
    # configured quiet period on the NEW clock
    for _ in range(8):
        h2.note_request("ok")
    clock2.advance(10.1)
    h2.note_request("ok")
    clock2.advance(10.1)
    h2.note_request("ok")
    assert h2.state == "healthy"


def test_health_state_version_mismatch_warns_and_resets():
    h = HealthMonitor(clock=FakeClock())
    with pytest.warns(UserWarning, match="version/kind mismatch"):
        assert not h.load_state_dict(
            {"version": 0, "kind": "health_monitor", "state": "healthy"})
    assert h.state == "healthy"


def test_resilience_state_checkpoint_roundtrip(tmp_path):
    clock = FakeClock()
    br = CircuitBreaker(threshold=1, cooldown_s=30.0, clock=clock)
    br.record_failure(("lane", 2))  # threshold=1: trips immediately
    h = HealthMonitor(clock=clock, window=8, min_events=2,
                      degraded_shed_rate=0.25)
    h.note_request("shed")
    h.note_request("shed")
    assert h.state != "healthy"
    ckpt_mod.save_resilience_state(tmp_path, breaker=br, health=h)

    br2 = CircuitBreaker(threshold=1, cooldown_s=30.0,
                         clock=FakeClock())
    h2 = HealthMonitor(clock=FakeClock(), window=8, min_events=2,
                       degraded_shed_rate=0.25)
    restored = ckpt_mod.restore_resilience_state(
        tmp_path, breaker=br2, health=h2)
    assert restored == {"breaker", "health"}
    assert br2.state(("lane", 2)) == "open"
    assert h2.state == h.state


def test_resilience_state_rotation_falls_back_to_prev(tmp_path):
    """Breaker state rides FitCheckpointer's CRC + <tag>.prev
    machinery: a torn write of the current snapshot falls back to the
    previous one instead of silently resetting every breaker."""
    clock = FakeClock()
    br = CircuitBreaker(threshold=1, cooldown_s=30.0, clock=clock)
    br.record_failure(("lane", 1))
    ckpt = ckpt_mod.save_resilience_state(tmp_path, breaker=br)
    br.record_failure(("lane", 3))
    ckpt_mod.save_resilience_state(ckpt, breaker=br)  # rotates .prev
    ckpt._corrupt_snapshot("resilience")
    br2 = CircuitBreaker(threshold=1, cooldown_s=30.0,
                         clock=FakeClock())
    with pytest.warns(UserWarning,
                      match="unreadable or corrupt|integrity"):
        restored = ckpt_mod.restore_resilience_state(
            tmp_path, breaker=br2)
    assert restored == {"breaker"}
    # the .prev snapshot predates lane 3's trip
    assert br2.state(("lane", 1)) == "open"
    assert br2.state(("lane", 3)) == "closed"


def test_resilience_state_layout_version_mismatch(tmp_path, monkeypatch):
    br = CircuitBreaker(threshold=1, clock=FakeClock())
    br.record_failure(("lane", 0))
    ckpt_mod.save_resilience_state(tmp_path, breaker=br)
    monkeypatch.setattr(ckpt_mod, "RESILIENCE_STATE_VERSION", 2)
    br2 = CircuitBreaker(threshold=1, clock=FakeClock())
    with pytest.warns(UserWarning, match="layout version"):
        restored = ckpt_mod.restore_resilience_state(
            tmp_path, breaker=br2)
    assert restored == set()
    assert br2.state(("lane", 0)) == "closed"  # reset, not guessed


# -- device-level fault points (ISSUE 6) -----------------------------


def test_device_points_registered_and_classified():
    from pint_tpu.parallel import CollectiveTimeout, DeviceLost
    from pint_tpu.resilience import DEVICE_POINTS
    from pint_tpu.resilience.retry import is_retryable

    assert set(DEVICE_POINTS) == {"device_loss", "collective_timeout",
                                  "straggler_delay"}
    for p in DEVICE_POINTS:
        FaultPoint(p)  # every device point is a registered point
    # a hung collective is transient (the retry loop may try another
    # lane); a lost device is not — it must quarantine, not retry
    assert is_retryable(CollectiveTimeout("psum hung after 60 s"))
    assert not is_retryable(DeviceLost("lane 3 lost"))


def test_serve_device_loss_quarantines_and_reroutes(two_pulsars,
                                                    device_mesh):
    """A device_loss during a flush quarantines that DeviceLane and
    re-routes the slot to the next alive lane inline: the requests on
    the dead chip still complete, and results match a fault-free
    engine bitwise."""
    import jax

    (m0, t0), (m1, t1) = two_pulsars
    eng_ok, _ = _fake_engine(max_batch=1)
    clean = [eng_ok.submit(FitRequest(copy.deepcopy(m), t, maxiter=2))
             for m, t in [(m0, t0), (m1, t1)]]

    eng, _ = _fake_engine(max_batch=1, devices=jax.devices())
    with inject(FaultPoint("device_loss", rate=1.0, count=1)):
        chaos = [eng.submit(FitRequest(copy.deepcopy(m), t, maxiter=2))
                 for m, t in [(m0, t0), (m1, t1)]]
    assert all(r.status == "ok" for r in chaos)
    for rc, rl in zip(clean, chaos):
        np.testing.assert_array_equal(np.asarray(rc.value["x"]),
                                      np.asarray(rl.value["x"]))
    assert eng.telemetry.counters.get("device_lost", 0) == 1
    snap = eng.snapshot()
    assert len(snap["devices"]["lost_lanes"]) == 1
    assert snap["devices"]["alive_lanes"] == len(jax.devices()) - 1
