"""Online serving engine (pint_tpu.serve): micro-batcher flush
semantics, executable-cache accounting, degradation policy
(mixed->f64, oversize spill, queue/deadline shedding), and
equivalence of served results with the offline PTABatch path — plus
regression tests for the NaN-aware mixed-precision guards the serve
degradation path relies on."""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu import fitter
from pint_tpu.models import get_model
from pint_tpu.parallel import PTABatch
from pint_tpu.serve import (ExecutableCache, FitRequest,
                            PhasePredictRequest, ResidualRequest,
                            ServeEngine)
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR SRVT{i}
RAJ 12:0{i}:00.0
DECJ 10:00:00.0
F0 3{i}1.25 1
F1 -4e-16 1
PEPOCH 55500
DM 12.{i} 1
"""

NOISE = "RNAMP 1e-14\nRNIDX -3.2\nTNREDC 4\n"


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _pulsar(i=0, n_toa=24, noise=False, seed=0):
    m = get_model(PAR.format(i=i) + (NOISE if noise else ""))
    rng = np.random.default_rng(seed + i)
    mjds = np.sort(rng.uniform(54500, 56500, n_toa))
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=seed + i,
                                iterations=0)
    return m, t


@pytest.fixture(scope="module")
def two_pulsars():
    return [_pulsar(0, 24), _pulsar(1, 24)]


# -- batcher flush semantics -----------------------------------------


def test_flush_on_full(two_pulsars):
    (m0, t0), (m1, t1) = two_pulsars
    eng = ServeEngine(max_batch=2, max_latency_s=1e9, bucket_floor=32)
    r0 = eng.submit(ResidualRequest(m0, t0))
    assert not r0.done  # slot not full, timer never fires
    r1 = eng.submit(ResidualRequest(m1, t1))
    assert r0.done and r1.done  # second submit filled + flushed
    assert r0.status == "ok" and r1.status == "ok"
    assert eng.telemetry.counters["flushes"] == 1


def test_flush_on_timer(two_pulsars):
    (m0, t0), _ = two_pulsars
    clock = FakeClock()
    eng = ServeEngine(max_batch=8, max_latency_s=0.05,
                      bucket_floor=32, clock=clock)
    res = eng.submit(ResidualRequest(m0, t0))
    assert eng.poll() == [] and not res.done  # younger than the timer
    clock.advance(0.051)
    assert len(eng.poll()) == 1
    assert res.status == "ok"
    assert res.telemetry["queue_wait_s"] == pytest.approx(0.051)


# -- executable cache ------------------------------------------------


def test_cache_hit_miss_evict_counters(two_pulsars):
    (m0, t0), _ = two_pulsars
    (m1, t1) = _pulsar(1, 40)  # pads to bucket 64 != 32: second shape
    eng = ServeEngine(max_batch=1, max_latency_s=1e9, bucket_floor=32,
                      cache_capacity=1)
    eng.submit(ResidualRequest(m0, t0))  # miss, insert A
    assert (eng.cache.misses, eng.cache.hits) == (1, 0)
    eng.submit(ResidualRequest(m0, t0))  # hit A
    assert (eng.cache.misses, eng.cache.hits) == (1, 1)
    eng.submit(ResidualRequest(m1, t1))  # miss, evicts A
    assert eng.cache.evictions == 1
    eng.submit(ResidualRequest(m0, t0))  # miss again: A was evicted
    assert eng.cache.misses == 3
    assert len(eng.cache) == 1
    counters = eng.cache.counters()
    assert counters["hit_rate"] == pytest.approx(0.25)
    # warm flushes reuse the cached program table: only cold flushes
    # compiled (one per miss)
    assert eng.executables_compiled == 3


def test_cache_prefill():
    cache = ExecutableCache(capacity=4)
    cache.prefill([(("k", i), {"fns": i}) for i in range(3)])
    assert len(cache) == 3 and cache.misses == 0
    assert cache.lookup(("k", 1)) == {"fns": 1}
    assert cache.hits == 1


# -- served results match the offline path ---------------------------


def test_fit_resid_phase_match_offline(two_pulsars):
    (m0, t0), (m1, t1) = two_pulsars
    eng = ServeEngine(max_batch=2, max_latency_s=1e9, bucket_floor=32)
    fit0 = eng.submit(FitRequest(m0, t0, maxiter=3))
    fit1 = eng.submit(FitRequest(m1, t1, maxiter=3))
    rr = eng.submit(ResidualRequest(m0, t0))
    pp = eng.submit(PhasePredictRequest(m0, t0))
    eng.drain()
    assert all(r.status == "ok" for r in (fit0, fit1, rr, pp))

    off = PTABatch([m0, m1], [t0, t1])
    x_off, chi2_off, _ = off.wls_fit(maxiter=3)
    for lane, res in enumerate((fit0, fit1)):
        rel = np.max(np.abs(res.value["x"] - np.asarray(x_off)[lane])
                     / np.maximum(np.abs(np.asarray(x_off)[lane]), 1e-30))
        assert rel <= 1e-12
        assert res.value["chi2"] == pytest.approx(
            float(np.asarray(chi2_off)[lane]), rel=1e-9)
        assert res.value["free_names"] == [n for n, _, _ in off.free_map()]
    r_off, mask = off.time_residuals()
    np.testing.assert_allclose(rr.value["resid_s"],
                               np.asarray(r_off)[0][mask[0]],
                               rtol=0, atol=1e-12)
    ph_off, _ = off.phases()
    np.testing.assert_allclose(pp.value["phase"],
                               np.asarray(ph_off)[0][mask[0]],
                               rtol=0, atol=1e-9)


# -- degradation policy ----------------------------------------------


def test_mixed_degrades_to_f64(monkeypatch):
    """A mixed-precision GLS whose refinement reports failure (here: a
    NaN rel_resid, the shape of the original NaN-swallowing bug) must
    fall back to f64 inside PTABatch and be counted as degraded by the
    engine — with a correct result."""
    m, t = _pulsar(3, 20, noise=True)
    real_refine = fitter.gls_eigh_refine

    def nan_refine(A, b, matvec, threshold=1e-12, iters=2):
        import jax.numpy as jnp

        dxn, covn, rel = real_refine(A, b, matvec, threshold, iters)
        return dxn, covn, jnp.full_like(rel, jnp.nan)

    monkeypatch.setattr(fitter, "gls_eigh_refine", nan_refine)
    eng = ServeEngine(max_batch=1, max_latency_s=1e9, bucket_floor=32)
    res = eng.submit(FitRequest(m, t, method="gls", maxiter=2,
                                precision="mixed"))
    assert res.status == "ok"
    assert res.telemetry["degraded"] is True
    assert eng.telemetry.counters["degraded_mixed"] == 1

    monkeypatch.setattr(fitter, "gls_eigh_refine", real_refine)
    off = PTABatch([m], [t])
    x_off, _, _ = off.gls_fit(maxiter=2, precision="f64")
    np.testing.assert_allclose(res.value["x"], np.asarray(x_off)[0],
                               rtol=1e-10, atol=0)


def test_oversize_spill(two_pulsars):
    (m0, t0), _ = two_pulsars
    eng = ServeEngine(max_batch=4, max_latency_s=1e9, bucket_floor=32,
                      oversize_toas=len(t0) - 1)
    res = eng.submit(ResidualRequest(m0, t0))
    assert res.done and res.status == "ok"  # executed solo, immediately
    assert res.telemetry["spilled"] is True
    assert eng.telemetry.counters["spilled_oversize"] == 1
    assert eng.batcher.depth() == 0
    off = PTABatch([m0], [t0])
    r_off, mask = off.time_residuals()
    np.testing.assert_allclose(res.value["resid_s"],
                               np.asarray(r_off)[0][mask[0]],
                               rtol=0, atol=1e-12)


def test_deadline_shed(two_pulsars):
    (m0, t0), _ = two_pulsars
    clock = FakeClock()
    eng = ServeEngine(max_batch=8, max_latency_s=0.2, bucket_floor=32,
                      clock=clock)
    res = eng.submit(ResidualRequest(m0, t0, deadline_s=0.1))
    clock.advance(0.3)  # past the deadline by the time the timer fires
    eng.poll()
    assert res.status == "shed"
    assert res.reason == "deadline"
    assert res.telemetry["rejected"] is True
    assert res.telemetry["detail"]["deadline_s"] == 0.1
    assert eng.telemetry.counters["shed_deadline"] == 1
    # nothing was executed for an all-shed flush
    assert eng.executables_compiled == 0


def test_queue_full_shed(two_pulsars):
    (m0, t0), (m1, t1) = two_pulsars
    eng = ServeEngine(max_batch=8, max_latency_s=1e9, bucket_floor=32,
                      max_queue=1)
    first = eng.submit(ResidualRequest(m0, t0))
    assert not first.done  # queued
    second = eng.submit(ResidualRequest(m1, t1))
    assert second.status == "shed"
    assert second.reason == "queue_full"
    assert second.telemetry["detail"]["max_queue"] == 1
    assert eng.telemetry.counters["shed_queue_full"] == 1
    eng.drain()
    assert first.status == "ok"  # queued work unaffected by the shed


# -- NaN-relres regression (satellite guard fixes) -------------------


def test_relres_failed_is_nan_aware():
    nan = float("nan")
    assert fitter.relres_failed(nan)
    assert fitter.relres_failed([0.0, nan])
    assert fitter.relres_failed(np.array([1e-12, nan]))
    assert fitter.relres_failed(1.0)
    assert not fitter.relres_failed(1e-9)
    assert not fitter.relres_failed(np.array([1e-12, 1e-9]))
    # the two bugs the helper replaces: comparison and Python max()
    # both silently swallow NaN
    assert not (nan > 1e-8)
    assert max(0.0, nan) == 0.0


def test_gls_solve_falls_back_on_nan_relres(monkeypatch):
    """gls_solve(precision='mixed') must warn + redo in f64 when the
    refinement residual is NaN (it previously compared nan > 1e-8 =
    False and returned the unverified mixed solution)."""
    import jax.numpy as jnp

    real_refine = fitter.gls_eigh_refine

    def nan_refine(A, b, matvec, threshold=1e-12, iters=2):
        dxn, covn, rel = real_refine(A, b, matvec, threshold, iters)
        return dxn, covn, jnp.full_like(rel, jnp.nan)

    monkeypatch.setattr(fitter, "gls_eigh_refine", nan_refine)
    rng = np.random.default_rng(0)
    M = jnp.asarray(rng.standard_normal((12, 3)))
    r = jnp.asarray(rng.standard_normal(12))
    sigma = jnp.ones(12)
    with pytest.warns(UserWarning, match="refitting in f64"):
        dx, _, chi2 = fitter.gls_solve(M, r, sigma, jnp.zeros(3),
                                       precision="mixed")
    assert np.all(np.isfinite(np.asarray(dx))) and np.isfinite(chi2)
    monkeypatch.setattr(fitter, "gls_eigh_refine", real_refine)
    dx_f64, _, chi2_f64 = fitter.gls_solve(M, r, sigma, jnp.zeros(3),
                                           precision="f64")
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_f64),
                               rtol=1e-12)


# -- wideband DMEFAC/DMEQUAD rejection (satellite) -------------------


def _wb_pulsar():
    m = get_model(PAR.format(i=5) + "DMEFAC -all 1 2.0 1\n")
    mjds = np.linspace(55000, 55600, 30)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=5)
    for f in t.flags:
        f["pp_dm"] = "12.5001"
        f["pp_dme"] = "1e-4"
    return m, t


@pytest.mark.parametrize("cls", [fitter.WidebandTOAFitter,
                                 fitter.WidebandDownhillFitter,
                                 fitter.WidebandLMFitter])
def test_wideband_rejects_free_dmefac(cls):
    m, t = _wb_pulsar()
    f = cls(t, m)
    with pytest.raises(ValueError, match="DMEFAC"):
        f.fit_toas(maxiter=2)


@pytest.mark.parametrize("cls", [fitter.WidebandTOAFitter,
                                 fitter.WidebandDownhillFitter,
                                 fitter.WidebandLMFitter])
def test_wideband_rejects_free_dmequad(cls):
    """Freeing DMEQUAD must be rejected at every wideband entry point,
    exactly like DMEFAC: the scaling is applied once at start-of-fit
    values, so a "fitted" DMEQUAD would silently report its input."""
    m = get_model(PAR.format(i=6) + "DMEQUAD -all 1 0.5 1\n")
    mjds = np.linspace(55000, 55600, 30)
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=6)
    for fl in t.flags:
        fl["pp_dm"] = "12.5001"
        fl["pp_dme"] = "1e-4"
    f = cls(t, m)
    with pytest.raises(ValueError, match="DMEQUAD"):
        f.fit_toas(maxiter=2)
