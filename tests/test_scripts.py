"""CLI scripts layer tests — each entry point invoked in-process.

(reference test pattern: tests/test_zima.py, photonphase/fermiphase
smoke tests via console entry points.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

PAR = """
PSR J1744-1134
RAJ 17:44:29.4
DECJ -11:34:54.7
F0 245.4261196 1
F1 -5.38e-16 1
PEPOCH 55000
POSEPOCH 55000
DM 3.139 1
"""


@pytest.fixture(scope="module")
def parfile(tmp_path_factory):
    p = tmp_path_factory.mktemp("scripts") / "test.par"
    p.write_text(PAR)
    return str(p)


_EVT_MJDREF = 56658.000777592593  # NICER-style TDB reference epoch


def _write_event_fits(path, phases, f0, span_days=5, epoch=56000.0,
                      rng=None):
    """Synthetic event FITS shared by the event_optimize tests: turn a
    phase sample into photon METs at spin frequency f0 and write a
    NICER-convention TIME table."""
    from pint_tpu.io.fits import write_fits_table

    rng = np.random.default_rng(0) if rng is None else rng
    n = len(phases)
    pulse_n = np.sort(rng.integers(0, int(span_days * 86400 * f0), n))
    mjds = epoch + ((pulse_n + phases) / f0) / 86400.0
    met = (np.asarray(mjds, np.longdouble) - _EVT_MJDREF) * 86400.0
    write_fits_table(str(path), {"TIME": np.asarray(met, float)},
                     {"MJDREFI": int(_EVT_MJDREF),
                      "MJDREFF": _EVT_MJDREF - int(_EVT_MJDREF),
                      "TIMESYS": "TDB", "TELESCOP": "NICER"})
    return str(path)


def test_zima_then_pintempo(parfile, tmp_path, capsys):
    from pint_tpu.scripts import zima, pintempo

    tim = str(tmp_path / "fake.tim")
    assert zima.main([parfile, tim, "--ntoa", "25", "--startMJD", "54800",
                      "--duration", "400", "--addnoise", "--seed", "42"]) == 0
    out_par = str(tmp_path / "post.par")
    plot = str(tmp_path / "r.png")
    assert pintempo.main([parfile, tim, "--fitter", "downhill_wls",
                          "--outfile", out_par, "--plot",
                          "--plotfile", plot]) == 0
    cap = capsys.readouterr().out
    assert "Read 25 TOAs" in cap and "chi2" in cap.lower()
    import os
    assert os.path.exists(out_par) and os.path.exists(plot)
    # post-fit par loads back
    from pint_tpu.models import get_model

    m = get_model(out_par)
    assert abs(m.F0.value - 245.4261196) < 1e-6


def test_photonphase_and_fermiphase(parfile, tmp_path, capsys):
    from pint_tpu.io.fits import write_fits_table, get_table
    from pint_tpu.models import get_model
    from pint_tpu.scripts import photonphase

    m = get_model(PAR)
    f0 = m.F0.value
    rng = np.random.default_rng(1)
    n = 1500
    phases = (rng.vonmises(0.0, 6.0, n) / (2 * np.pi)) % 1.0
    pulse_n = rng.integers(0, int(2000 * f0), n)
    mjds = 55000.0 + ((pulse_n + phases) / f0) / 86400.0
    mjdref = 56658.000777592593
    met = (np.asarray(mjds, np.longdouble) - mjdref) * 86400.0
    evt = str(tmp_path / "evt.fits")
    write_fits_table(evt, {"TIME": np.asarray(met, float)},
                     {"MJDREFI": 56658, "MJDREFF": mjdref - 56658,
                      "TIMESYS": "TDB", "TELESCOP": "NICER"})
    out = str(tmp_path / "phased.fits")
    assert photonphase.main([evt, parfile, "--outfile", out]) == 0
    cap = capsys.readouterr().out
    assert "Htest" in cap
    h = float(cap.split("Htest :")[1].split()[0])
    assert h > 200.0
    _, cols = get_table(out, "EVENTS")
    assert "PULSE_PHASE" in cols and len(cols["PULSE_PHASE"]) == n


def test_pintbary(capsys):
    from pint_tpu.scripts import pintbary

    assert pintbary.main(["56000.0", "--ra", "10:00:00", "--dec", "15:00:00",
                          "--obs", "geocenter"]) == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    mjd = float(line)
    # Roemer delay to SSB is at most ~500 s
    assert abs(mjd - 56000.0) < 600.0 / 86400.0


def test_tcb2tdb(parfile, tmp_path, capsys):
    from pint_tpu.models import get_model
    from pint_tpu.models.tcb_conversion import convert_tcb_tdb, IFTE_K

    # a TCB par file is refused by default, converted with allow_tcb=True,
    # and kept raw with allow_tcb="raw" (reference: get_model allow_tcb)
    with pytest.raises(ValueError, match="TCB"):
        get_model(PAR + "UNITS TCB\n")
    m_auto = get_model(PAR + "UNITS TCB\n", allow_tcb=True)
    assert m_auto.UNITS.value == "TDB"
    m = get_model(PAR + "UNITS TCB\n", allow_tcb="raw")
    f0_tcb = m.F0.value
    pepoch_tcb = m.PEPOCH.value
    assert m_auto.F0.value == pytest.approx(f0_tcb * IFTE_K, rel=1e-15)
    convert_tcb_tdb(m)
    assert m.F0.value == pytest.approx(f0_tcb * IFTE_K, rel=1e-15)
    assert m.PEPOCH.value < pepoch_tcb  # pulled toward IFTE_MJD0
    assert abs(m.PEPOCH.value - pepoch_tcb) < 1e-3
    # round-trip back
    convert_tcb_tdb(m, backwards=True)
    assert m.F0.value == pytest.approx(f0_tcb, rel=1e-14)
    assert m.PEPOCH.value == pytest.approx(pepoch_tcb, abs=1e-9)
    # script end-to-end
    from pint_tpu.scripts import tcb2tdb

    src = tmp_path / "tcb.par"
    src.write_text(PAR + "UNITS TCB\n")
    dst = tmp_path / "tdb.par"
    assert tcb2tdb.main([str(src), str(dst)]) == 0
    m2 = get_model(str(dst))
    assert m2.F0.value == pytest.approx(f0_tcb * IFTE_K, rel=1e-14)


def test_compare_parfiles_and_pintpublish(parfile, tmp_path, capsys):
    from pint_tpu.scripts import compare_parfiles, pintpublish

    par2 = tmp_path / "b.par"
    par2.write_text(PAR.replace("245.4261196", "245.4261197"))
    assert compare_parfiles.main([parfile, str(par2)]) == 0
    assert "F0" in capsys.readouterr().out
    # --sigma filters sub-threshold rows; identical F1 disappears but
    # the changed F0 (no uncertainties in these pars) stays
    assert compare_parfiles.main([parfile, str(par2), "--sigma", "3"]) == 0
    out = capsys.readouterr().out
    assert "F0" in out and "\nF1 " not in out
    tex = tmp_path / "t.tex"
    assert pintpublish.main([parfile, "--outfile", str(tex)]) == 0
    text = tex.read_text()
    assert "\\begin{table}" in text and "F0" in text


def test_event_optimize_smoke(tmp_path, capsys):
    """event_optimize runs a short chain and improves the posterior."""
    from pint_tpu.models import get_model
    from pint_tpu.scripts import event_optimize

    par = "PSR TESTEO\nRAJ 05:00:00\nDECJ 20:00:00\nF0 10.0 1\nF1 0\nPEPOCH 56000\nDM 0\n"
    parfile = tmp_path / "eo.par"
    parfile.write_text(par)
    rng = np.random.default_rng(3)
    n = 800
    phases = (rng.vonmises(np.pi, 5.0, n) / (2 * np.pi)) % 1.0
    evt = _write_event_fits(tmp_path / "eo.fits", phases, f0=10.0,
                            span_days=10, rng=rng)
    out_par = str(tmp_path / "eo_post.par")
    assert event_optimize.main([evt, str(parfile), "--nsteps", "60",
                                "--outfile", out_par]) == 0
    cap = capsys.readouterr().out
    assert "max posterior" in cap
    import os
    assert os.path.exists(out_par)


def test_t2binary2pint(tmp_path):
    from pint_tpu.scripts.t2binary2pint import choose_model, main

    t2_par = """PSR J1012+5307
RAJ 10:12:33.43
DECJ 53:07:02.5
F0 190.2678376 1
F1 -6.2e-16
PEPOCH 55000
DM 9.02
BINARY T2
PB 0.60467 1
A1 0.58181 1
TASC 50700.08 1
EPS1 1.3e-7 1
EPS2 -4.0e-7 1
"""
    src = tmp_path / "t2.par"
    out = tmp_path / "pint.par"
    src.write_text(t2_par)
    assert main([str(src), str(out)]) == 0
    text = out.read_text()
    assert "ELL1" in text and "T2" not in text.split()
    from pint_tpu.models import get_model

    m = get_model(str(out))
    assert "BinaryELL1" in m.components
    assert m.PB.value == pytest.approx(0.60467)

    # model choice heuristics (reference: t2binary2pint mapping)
    assert choose_model({"KIN", "ECC"}) == "DDK"
    assert choose_model({"EPS1", "H3"}) == "ELL1H"
    assert choose_model({"ECC", "OM", "H3", "STIG"}) == "DDH"
    assert choose_model({"ECC", "OM", "M2", "SINI"}) == "DD"
    assert choose_model({"ECC", "OM"}) == "BT"


def test_zima_correlated_noise(tmp_path):
    """--addcorrnoise draws the model's red-noise realization: the
    written TOAs show excess low-frequency power over white noise."""
    from pint_tpu.scripts import zima

    par = tmp_path / "z.par"
    par.write_text("PSR TZC\nRAJ 1:00:00\nDECJ 2:00:00\nF0 150.0 1\n"
                   "F1 -1e-15 1\nPEPOCH 56100\nDM 12\n"
                   "TNREDAMP -11.0\nTNREDGAM 4.0\nTNREDC 6\n")
    out_w = tmp_path / "white.tim"
    out_c = tmp_path / "corr.tim"
    for out, extra in ((out_w, []), (out_c, ["--addcorrnoise"])):
        assert zima.main([str(par), str(out), "--ntoa", "80",
                          "--addnoise", "--seed", "5"] + extra) == 0
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.toa import get_TOAs

    m = get_model(str(par))
    rw = np.asarray(Residuals(get_TOAs(str(out_w)), m).time_resids)
    rc = np.asarray(Residuals(get_TOAs(str(out_c)), m).time_resids)
    assert rc.std() > 3 * rw.std()


def test_convert_parfile_formats(tmp_path, capsys):
    """as_parfile(format=) + convert_parfile script: tempo2 spellings
    out (T2EFAC/VARSIGMA/LAMBDA), values intact, file loads back
    (reference: scripts/convert_parfile.py)."""
    from pint_tpu.models import get_model
    from pint_tpu.scripts import convert_parfile

    par = ("PSR TCONVP\nELONG 93.0 1\nELAT 21.0 1\nF0 218.81 1\n"
           "PEPOCH 55000\nDM 15.99 1\nNE_SW 7.9\n"
           "BINARY ELL1H\nPB 66.0\nA1 32.3\nTASC 55001\n"
           "EPS1 1e-7\nEPS2 -2e-7\nH3 2.7e-7\nSTIGMA 0.72\n"
           "EFAC -f L-wide 1.1\nEQUAD -f L-wide 0.3\n")
    src = tmp_path / "in.par"
    src.write_text(par)
    m = get_model(str(src))

    t2 = m.as_parfile(format="tempo2")
    for spell in ("T2EFAC", "T2EQUAD", "VARSIGMA", "LAMBDA", "BETA",
                  "NE1AU", "UNITS           TDB"):
        assert spell in t2, spell
    assert "\nSTIGMA " not in t2 and "\nEFAC " not in t2
    m2 = get_model(t2)
    assert m2.STIGMA.value == m.STIGMA.value
    assert m2.ELONG.value == m.ELONG.value
    assert m2.EFAC1.value == m.EFAC1.value

    t1 = m.as_parfile(format="tempo")
    assert t1.startswith("MODE")
    assert "LAMBDA" in t1 and "SOLARN0" in t1
    assert get_model(t1).NE_SW.value == m.NE_SW.value

    with pytest.raises(ValueError, match="format"):
        m.as_parfile(format="tempo3")

    out = tmp_path / "out.par"
    assert convert_parfile.main([str(src), "-f", "tempo2",
                                 "-o", str(out)]) == 0
    assert "VARSIGMA" in out.read_text()
    # stdout mode
    assert convert_parfile.main([str(src)]) == 0
    assert "ELONG" in capsys.readouterr().out


def test_event_optimize_multiple_smoke(tmp_path, capsys):
    """event_optimize_multiple jointly samples two event lists
    (reference: scripts/event_optimize_multiple.py)."""
    from pint_tpu.io.fits import write_fits_table
    from pint_tpu.scripts import event_optimize_multiple

    par = ("PSR TESTEOM\nRAJ 05:00:00\nDECJ 20:00:00\nF0 10.0 1\nF1 0\n"
           "PEPOCH 56000\nDM 0\n")
    parfile = tmp_path / "eom.par"
    parfile.write_text(par)
    rng = np.random.default_rng(5)
    mjdref = 56658.000777592593
    evts = []
    for k in range(2):
        n = 400
        phases = (rng.vonmises(np.pi, 5.0, n) / (2 * np.pi)) % 1.0
        pulse_n = rng.integers(0, 10 * 86400 * 10, n)
        mjds = 56000.0 + ((pulse_n + phases) / 10.0) / 86400.0
        met = (np.asarray(mjds, np.longdouble) - mjdref) * 86400.0
        evt = str(tmp_path / f"eom{k}.fits")
        write_fits_table(evt, {"TIME": np.asarray(met, float)},
                         {"MJDREFI": 56658, "MJDREFF": mjdref - 56658,
                          "TIMESYS": "TDB", "TELESCOP": "NICER"})
        evts.append(evt)
    listing = tmp_path / "sets.txt"
    listing.write_text(f"# dataset list\n{evts[0]} nicer\n{evts[1]}\n")
    out_par = str(tmp_path / "eom_post.par")
    assert event_optimize_multiple.main(
        [str(listing), str(parfile), "--nsteps", "50",
         "--outfile", out_par]) == 0
    cap = capsys.readouterr().out
    assert cap.count("Read 400 photons") == 2
    assert "max posterior" in cap
    import os
    assert os.path.exists(out_par)


def test_photonphase_polycos_mode(parfile, tmp_path, capsys):
    """--polycos gives the same phases as the full pipeline to
    polyco-approximation accuracy (reference: photonphase --polycos)."""
    from pint_tpu.io.fits import write_fits_table, get_table
    from pint_tpu.models import get_model
    from pint_tpu.scripts import photonphase

    m = get_model(PAR)
    f0 = m.F0.value
    rng = np.random.default_rng(4)
    n = 600
    phases = (rng.vonmises(0.0, 6.0, n) / (2 * np.pi)) % 1.0
    pulse_n = rng.integers(0, int(2.0 * 86400 * f0), n)
    mjds = 55000.0 + ((pulse_n + phases) / f0) / 86400.0
    mjdref = 56658.000777592593
    met = (np.asarray(mjds, np.longdouble) - mjdref) * 86400.0
    evt = str(tmp_path / "pev.fits")
    write_fits_table(evt, {"TIME": np.asarray(met, float)},
                     {"MJDREFI": 56658, "MJDREFF": mjdref - 56658,
                      "TIMESYS": "TDB", "TELESCOP": "NICER"})
    out_full = str(tmp_path / "full.fits")
    assert photonphase.main([evt, parfile, "--absphase",
                             "--outfile", out_full]) == 0
    out_pc = str(tmp_path / "pc.fits")
    assert photonphase.main([evt, parfile, "--polycos", "--absphase",
                             "--outfile", out_pc]) == 0
    cap = capsys.readouterr().out
    assert "polyco segments" in cap
    _, c_full = get_table(out_full, "EVENTS")
    _, c_pc = get_table(out_pc, "EVENTS")
    d = np.abs(np.asarray(c_full["PULSE_PHASE"])
               - np.asarray(c_pc["PULSE_PHASE"]))
    d = np.minimum(d, 1.0 - d)  # cyclic distance
    # bound = polyco truncation + f32 PULSE_PHASE storage in FITS;
    # 1e-5 cycles at F0=245 Hz is ~40 ns, far below X-ray timing needs
    assert d.max() < 1e-5
    # absolute pulse numbers agree exactly (int_ + frac invariant —
    # review finding: the polyco path once dropped the borrowed cycle)
    pn_full = np.asarray(c_full["PULSE_NUMBER"], np.float64)
    pn_pc = np.asarray(c_pc["PULSE_NUMBER"], np.float64)
    tot_full = pn_full + np.asarray(c_full["PULSE_PHASE"], np.float64)
    tot_pc = pn_pc + np.asarray(c_pc["PULSE_PHASE"], np.float64)
    assert np.abs(tot_full - tot_pc).max() < 1e-4


def test_photonphase_fermi_calc_weights(parfile, tmp_path, capsys):
    """photonphase --weightcol CALC on a Fermi file: heuristic PSF
    weights from the par-file position reach the weighted H-test."""
    from pint_tpu.io.fits import write_fits_table, get_table
    from pint_tpu.models import get_model
    from pint_tpu.scripts import photonphase

    m = get_model(PAR)
    f0 = m.F0.value
    ra0 = np.degrees(m.RAJ.value)
    dec0 = np.degrees(m.DECJ.value)
    rng = np.random.default_rng(3)
    n = 900
    phases = (rng.vonmises(0.0, 6.0, n) / (2 * np.pi)) % 1.0
    pulse_n = rng.integers(0, int(1000 * f0), n)
    mjds = 55000.0 + ((pulse_n + phases) / f0) / 86400.0
    mjdref = 51910.0007428703703703
    met = (np.asarray(mjds, np.longdouble) - mjdref) * 86400.0
    evt = str(tmp_path / "ft1.fits")
    write_fits_table(
        evt, {"TIME": np.asarray(met, float),
              "RA": np.full(n, ra0) + rng.normal(0, 0.05, n),
              "DEC": np.full(n, dec0) + rng.normal(0, 0.05, n),
              "ENERGY": rng.uniform(500.0, 20000.0, n)},
        {"MJDREFI": 51910, "MJDREFF": mjdref - 51910,
         "TIMESYS": "TDB", "TELESCOP": "GLAST"})
    out = str(tmp_path / "phased.fits")
    assert photonphase.main([evt, parfile, "--mission", "fermi",
                             "--weightcol", "CALC",
                             "--outfile", out]) == 0
    cap = capsys.readouterr().out
    assert "Htest" in cap
    h = float(cap.split("Htest :")[1].split()[0])
    assert h > 100.0
    _, cols = get_table(out, "EVENTS")
    assert "PULSE_PHASE" in cols


def test_photonphase_calc_weights_ecliptic_par(parfile, tmp_path, capsys):
    """CALC weights from an ELONG/ELAT par: the target position is
    converted to ICRS instead of crashing on the missing RAJ."""
    from pint_tpu.io.fits import write_fits_table
    from pint_tpu.models import get_model
    from pint_tpu.modelutils import model_equatorial_to_ecliptic
    from pint_tpu.scripts import photonphase

    m = get_model(PAR)
    m_ecl = model_equatorial_to_ecliptic(m)
    par_ecl = str(tmp_path / "ecl.par")
    with open(par_ecl, "w") as fh:
        fh.write(m_ecl.as_parfile())
    f0 = m.F0.value
    ra0, dec0 = np.degrees(m.RAJ.value), np.degrees(m.DECJ.value)
    rng = np.random.default_rng(9)
    n = 300
    phases = (rng.vonmises(0.0, 6.0, n) / (2 * np.pi)) % 1.0
    pulse_n = rng.integers(0, int(500 * f0), n)
    mjds = 55000.0 + ((pulse_n + phases) / f0) / 86400.0
    mjdref = 51910.0007428703703703
    met = (np.asarray(mjds, np.longdouble) - mjdref) * 86400.0
    evt = str(tmp_path / "ft1e.fits")
    write_fits_table(
        evt, {"TIME": np.asarray(met, float),
              "RA": np.full(n, ra0), "DEC": np.full(n, dec0),
              "ENERGY": np.full(n, 5000.0)},
        {"MJDREFI": 51910, "MJDREFF": mjdref - 51910,
         "TIMESYS": "TDB", "TELESCOP": "GLAST"})
    assert photonphase.main([evt, par_ecl, "--weightcol", "CALC"]) == 0
    cap = capsys.readouterr().out
    assert "Htest" in cap
    # on-source hard photons: weights near 1, so weighted H is large
    assert float(cap.split("Htest :")[1].split()[0]) > 50.0


def test_event_optimize_at_scale_1M(tmp_path, capsys):
    """event_optimize end-to-end on 1e6 synthetic photons (VERDICT r4
    item 5: the at-scale photon-domain demonstration), with the H-test
    significance anchored to the published de Jager & Busching (2010)
    calibration sf = exp(-0.4 H):

    - the pulsed sample's H must be enormous (sf underflows; sigma
      equivalent > 25 via sig2sigma's asymptotic branch),
    - a same-size UNIFORM sample must calibrate: median H over uniform
      realizations is ln(2)/0.4 ~ 1.73, and H stays O(10) (we assert
      H_uniform < 50, i.e. sf > 2e-9 — no false detection at 1e6
      photons).
    """
    from pint_tpu.eventstats import hm, sf_hm, sig2sigma
    from pint_tpu.scripts import event_optimize

    rng = np.random.default_rng(11)
    n = 1_000_000
    f0 = 29.946923  # Crab-like spin frequency
    par = ("PSR TESTBIG\nRAJ 05:34:31.97\nDECJ 22:00:52.1\n"
           f"F0 {f0} 1\nF1 0\nPEPOCH 56000\nDM 0\n")
    parfile = tmp_path / "big.par"
    parfile.write_text(par)
    # 30% pulsed (von Mises peak), 70% unpulsed
    n_sig = int(0.3 * n)
    phases = np.concatenate([
        (rng.vonmises(np.pi, 8.0, n_sig) / (2 * np.pi)) % 1.0,
        rng.random(n - n_sig)])
    rng.shuffle(phases)
    evt = _write_event_fits(tmp_path / "big.fits", phases, f0=f0,
                            rng=rng)
    # H-test anchors (published calibration)
    h_puls = float(hm(phases))
    assert h_puls > 1e4  # 300k pulsed photons: overwhelming detection
    assert sig2sigma(sf_hm(h_puls, logprob=True), logprob=True) > 25.0
    h_unif = float(hm(rng.random(n)))
    assert h_unif < 50.0  # sf > 2e-9: no false detection at 1e6 photons
    assert sf_hm(1.7329) == pytest.approx(0.5, rel=1e-3)  # median anchor
    # end-to-end script run on the full 1e6-photon FITS
    out_par = str(tmp_path / "big_post.par")
    assert event_optimize.main([evt, str(parfile), "--nsteps", "12",
                                "--outfile", out_par]) == 0
    cap = capsys.readouterr().out
    assert "Read 1000000 photons" in cap
    assert "max posterior" in cap
    import os
    assert os.path.exists(out_par)
