"""Chromatic (variable-index) model family: ChromaticCM/CMX, CMWaveX,
PLChromNoise.

(reference patterns: tests/test_cm.py / tests/test_cmwavex.py upstream —
the alpha=2 limit must reduce exactly to the DM components, windows must
be local, and fits must recover injected values.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_fromMJDs

BASE = """
PSR TESTCHROM
RAJ 16:00:51.9
DECJ -30:53:49.3
F0 277.94 1
F1 -7.3e-16 1
PEPOCH 55300
DM 52.33 1
"""


def _toas(m, n=60, span=(55000, 55600), freqs=(800.0, 1400.0), **kw):
    mjds = np.linspace(*span, n)
    f = np.where(np.arange(n) % 2, freqs[0], freqs[1])
    return make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=f,
                                   obs="gbt", add_noise=False, **kw)


def test_cm_alpha2_equals_dm_delay():
    """At TNCHROMIDX=2, CM is exactly a DM: the chromatic delay of
    CM=x must equal the dispersion delay of an extra DM=x."""
    m_cm = get_model(BASE + "CM 0.5\nTNCHROMIDX 2\n")
    m_dm = get_model(BASE.replace("DM 52.33 1", "DM 52.83 1"))
    t = _toas(m_cm)
    r_cm = Residuals(t, m_cm).time_resids
    r_dm = Residuals(t, m_dm).time_resids
    np.testing.assert_allclose(np.asarray(r_cm), np.asarray(r_dm),
                               atol=1e-12)


def test_cm_taylor_term():
    """CM1 advances CM(t) linearly in Julian years from CMEPOCH."""
    m = get_model(BASE + "CM 0.1\nCM1 0.05\nCMEPOCH 55300\nTNCHROMIDX 4\n")
    t = _toas(m)
    prepared = m.prepare(t)
    cmc = m.components["ChromaticCM"]
    cm_t = np.asarray(cmc.cm_value(prepared.params0, prepared.prep))
    yrs = np.asarray(prepared.prep["cmepoch_dt"]) / (365.25 * 86400.0)
    np.testing.assert_allclose(cm_t, 0.1 + 0.05 * yrs, rtol=1e-12)


def test_cmx_window_locality_and_scaling():
    """CMX perturbs only in-window TOAs, scaled as nu^-alpha."""
    from pint_tpu.constants import DMconst

    par_on = BASE + ("CM 0.0\nTNCHROMIDX 4\nCMX_0001 0.02 1\n"
                     "CMXR1_0001 55100\nCMXR2_0001 55200\n")
    m_on = get_model(par_on)
    m_off = get_model(BASE)
    t = _toas(m_off)
    d_on = np.asarray(Residuals(t, m_on).time_resids)
    d_off = np.asarray(Residuals(t, m_off).time_resids)
    mjds = t.get_mjds()
    inside = (mjds >= 55100) & (mjds <= 55200)
    nu = np.asarray(t.freq_mhz)
    expect = DMconst * 0.02 / nu**4
    # the model's extra delay moves residuals by the per-TOA delay minus
    # the weighted-mean subtraction (a constant), so compare the
    # mean-removed in/out splits separately
    delta = d_off - d_on
    delta = delta - delta[~inside].mean()
    np.testing.assert_allclose(delta[~inside], 0.0, atol=1e-12)
    np.testing.assert_allclose(delta[inside] - delta[inside].mean(),
                               expect[inside] - expect[inside].mean(),
                               atol=1e-12)


def test_cm_fit_recovery():
    """A WLS fit with two widely spaced bands recovers an injected CM
    perturbation (alpha=4 is separable from DM's alpha=2)."""
    from pint_tpu.fitter import WLSFitter

    true = get_model(BASE + "CM 0.030\nTNCHROMIDX 4\n")
    t = make_fake_toas_fromMJDs(
        np.linspace(55000, 55600, 120), true, error_us=0.5,
        freq_mhz=np.tile([400.0, 800.0, 1400.0, 3000.0], 30),
        obs="gbt", add_noise=True, seed=7)
    wrong = get_model(BASE + "CM 0.0 1\nTNCHROMIDX 4\n")
    f = WLSFitter(t, wrong)
    f.fit_toas(maxiter=3)
    assert abs(f.model.CM.value - 0.030) < 5 * f.model.CM.uncertainty


def test_cmwavex_alpha2_equals_dmwavex():
    par_cm = BASE + ("CM 0.0\nTNCHROMIDX 2\nCMWXEPOCH 55300\n"
                     "CMWXFREQ_0001 0.004\nCMWXSIN_0001 0.01\n"
                     "CMWXCOS_0001 -0.006\n")
    par_dm = BASE + ("DMWXEPOCH 55300\nDMWXFREQ_0001 0.004\n"
                     "DMWXSIN_0001 0.01\nDMWXCOS_0001 -0.006\n")
    m_cm = get_model(par_cm)
    m_dm = get_model(par_dm)
    t = _toas(m_dm)
    r_cm = np.asarray(Residuals(t, m_cm).time_resids)
    r_dm = np.asarray(Residuals(t, m_dm).time_resids)
    np.testing.assert_allclose(r_cm, r_dm, atol=1e-12)


def test_cmwavex_fit_recovery():
    from pint_tpu.fitter import WLSFitter

    true = get_model(BASE + ("CM 0.0\nTNCHROMIDX 4\nCMWXEPOCH 55300\n"
                             "CMWXFREQ_0001 0.003\nCMWXSIN_0001 0.012\n"
                             "CMWXCOS_0001 -0.004\n"))
    t = make_fake_toas_fromMJDs(
        np.linspace(55000, 55600, 160), true, error_us=0.5,
        freq_mhz=np.tile([400.0, 800.0, 1400.0, 3000.0], 40),
        obs="gbt", add_noise=True, seed=11)
    guess = get_model(BASE + ("CM 0.0\nTNCHROMIDX 4\nCMWXEPOCH 55300\n"
                              "CMWXFREQ_0001 0.003\nCMWXSIN_0001 0.0 1\n"
                              "CMWXCOS_0001 0.0 1\n"))
    f = WLSFitter(t, guess)
    f.fit_toas(maxiter=3)
    assert abs(f.model.CMWXSIN_0001.value - 0.012) \
        < 5 * f.model.CMWXSIN_0001.uncertainty
    assert abs(f.model.CMWXCOS_0001.value - (-0.004)) \
        < 5 * f.model.CMWXCOS_0001.uncertainty


def test_plchrom_basis_and_gls():
    """PLChromNoise basis = Fourier basis row-scaled by (1400/nu)^alpha;
    GLS runs; at alpha=2 the basis equals PLDMNoise's."""
    from pint_tpu.fitter import GLSFitter

    par = BASE + ("CM 0.0\nTNCHROMIDX 4\n"
                  "TNCHROMAMP -13.2\nTNCHROMGAM 3.0\nTNCHROMC 8\n")
    m = get_model(par)
    assert "PLChromNoise" in m.components
    t = _toas(m)
    prepared = m.prepare(t)
    F = np.asarray(prepared.prep["chromrn_F"])
    assert F.shape == (60, 16)
    chrom = (1400.0 / np.asarray(t.freq_mhz)) ** 4
    # column-0 sin basis over the span, rescaled per TOA
    mjds = t.get_mjds()
    tspan_s = (mjds.max() - mjds.min() + 1.0) * 86400.0
    t_s = (mjds - mjds.min()) * 86400.0
    np.testing.assert_allclose(
        F[:, 0], np.sin(2 * np.pi * t_s / tspan_s) * chrom, atol=1e-10)
    f = GLSFitter(t, m)
    chi2 = f.fit_toas()
    assert np.isfinite(chi2)

    # alpha=2 degeneracy with PLDMNoise
    par2 = BASE + ("CM 0.0\nTNCHROMIDX 2\n"
                   "TNCHROMAMP -13.2\nTNCHROMGAM 3.0\nTNCHROMC 8\n")
    pardm = BASE + "TNDMAMP -13.2\nTNDMGAM 3.0\nTNDMC 8\n"
    p2 = get_model(par2).prepare(t)
    pdm = get_model(pardm).prepare(t)
    np.testing.assert_allclose(np.asarray(p2.prep["chromrn_F"]),
                               np.asarray(pdm.prep["dmrn_F"]), atol=1e-12)


def test_chromatic_parfile_round_trip():
    par = BASE + ("CM 0.02 1\nCM1 0.001\nCMEPOCH 55300\nTNCHROMIDX 4\n"
                  "CMX_0001 0.01 1\nCMXR1_0001 54900\nCMXR2_0001 55100\n"
                  "CMWXFREQ_0001 0.003\nCMWXSIN_0001 0.005 1\n"
                  "CMWXCOS_0001 -0.002 1\n"
                  "TNCHROMAMP -13.5\nTNCHROMGAM 3.0\nTNCHROMC 8\n")
    m = get_model(par)
    m2 = get_model(m.as_parfile())
    for pname in ("CM", "CM1", "TNCHROMIDX", "CMX_0001", "CMWXFREQ_0001",
                  "CMWXSIN_0001", "CMWXCOS_0001", "TNCHROMAMP",
                  "TNCHROMGAM", "TNCHROMC"):
        assert getattr(m2, pname).value == getattr(m, pname).value, pname
    assert not m2.unrecognized
    # free flags survive
    assert set(m2.free_params) == set(m.free_params)


def test_cmx_missing_window_raises():
    """CMX_#### without CMXR1/CMXR2 must not silently parse into an
    empty window (zero design column)."""
    from pint_tpu.models.timing_model import MissingParameter

    with pytest.raises(MissingParameter):
        get_model(BASE + "CM 0.0\nCMX_0001 0.02 1\n")


def test_dmx_missing_window_raises():
    from pint_tpu.models.timing_model import MissingParameter

    with pytest.raises(MissingParameter):
        get_model(BASE + "DMX_0001 0.001 1\n")
