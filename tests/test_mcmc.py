"""MCMC / Bayesian tests (reference test patterns:
tests/test_mcmc_fitter.py, tests/test_bayesian.py — posterior
recovers injected params, priors gate the posterior, sampler sanity).
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.bayesian import BayesianTiming
from pint_tpu.fitter import WLSFitter
from pint_tpu.mcmc_fitter import MCMCFitter
from pint_tpu.models import get_model
from pint_tpu.priors import (GaussianPrior, UniformBoundedPrior)
from pint_tpu.sampler import run_ensemble
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """
PSR MCMCTEST
RAJ 06:30:00.0
DECJ -28:00:00.0
F0 300.0 1
F1 -1e-15 1
PEPOCH 55100
DM 20.0
"""


def test_ensemble_sampler_gaussian_target():
    # sample a 3-d Gaussian, check mean/cov recovery
    import jax.numpy as jnp

    def logpost(x):
        return -0.5 * jnp.sum(x**2 / jnp.array([1.0, 4.0, 0.25]))

    rng = np.random.default_rng(0)
    x0 = rng.standard_normal((32, 3))
    chain, lp, acc = run_ensemble(logpost, x0, 1500, seed=1)
    assert 0.2 < acc < 0.9
    flat = chain[500:].reshape(-1, 3)
    assert np.abs(flat.mean(axis=0)).max() < 0.25
    assert flat[:, 1].std() == pytest.approx(2.0, rel=0.2)
    assert flat[:, 2].std() == pytest.approx(0.5, rel=0.2)


@pytest.fixture(scope="module")
def fitted():
    model = get_model(PAR)
    mjds = np.linspace(54800, 55400, 25)
    toas = make_fake_toas_fromMJDs(mjds, model, error_us=2.0, obs="gbt",
                                   add_noise=True, seed=11)
    f = WLSFitter(toas, model)
    f.fit_toas()
    return f


def test_bayesian_timing_posterior_peak(fitted):
    bt = BayesianTiming(fitted.model, fitted.toas)
    x0 = bt.initial_position()
    lp0 = float(bt.lnposterior(x0))
    # moving F0 by 20 sigma must lower the posterior
    dx = np.zeros_like(x0)
    dx[bt.param_labels.index("F0")] = 20 * fitted.model.F0.uncertainty
    assert float(bt.lnposterior(x0 + dx)) < lp0 - 3.0


def test_bayesian_prior_gates(fitted):
    bt = BayesianTiming(fitted.model, fitted.toas,
                        prior_info={"F0": {"min": 299.9, "max": 300.1}})
    x = bt.initial_position()
    x[bt.param_labels.index("F0")] = 300.2
    assert np.isneginf(float(bt.lnposterior(x)))


def test_prior_transform(fitted):
    bt = BayesianTiming(fitted.model, fitted.toas)
    lo = bt.prior_transform(np.zeros(bt.nparams))
    hi = bt.prior_transform(np.ones(bt.nparams))
    mid = bt.prior_transform(0.5 * np.ones(bt.nparams))
    assert np.all(lo < mid) and np.all(mid < hi)
    np.testing.assert_allclose(mid, bt.initial_position(), rtol=1e-10)


def test_mcmc_fitter_recovers(fitted):
    mf = MCMCFitter(fitted.toas, fitted.model, seed=3)
    mf.fit_toas(n_steps=300)
    # max-posterior within ~5 WLS sigma of the WLS solution
    for p in ("F0", "F1"):
        wls = getattr(fitted.model, p)
        got = getattr(mf.model, p).value
        assert abs(got - wls.value) < 5 * wls.uncertainty
    samples = mf.get_posterior_samples(burn=75)
    assert set(samples) == set(mf.bt.param_labels)
    # posterior std same order as WLS uncertainty
    s = samples["F0"].std()
    assert 0.2 * fitted.model.F0.uncertainty < s < 5 * fitted.model.F0.uncertainty


def test_gaussian_prior_logpdf():
    pr = GaussianPrior(1.0, 2.0)
    import math

    expected = -0.5 * 0.25 - math.log(2.0 * math.sqrt(2 * math.pi))
    assert float(pr.logpdf(2.0)) == pytest.approx(expected, rel=1e-12)


def test_uniform_prior_bounds():
    pr = UniformBoundedPrior(0.0, 2.0)
    assert np.isneginf(float(pr.logpdf(2.5)))
    assert float(pr.logpdf(1.0)) == pytest.approx(-np.log(2.0))
    assert pr.ppf(0.25) == 0.5


def test_composite_mcmc_fitter():
    """Two photon sets sharing one model: the composite likelihood is
    the sum of the per-set template likelihoods, and a short chain
    prefers the true F0 over a detuned start."""
    import numpy as np

    from pint_tpu.mcmc_fitter import CompositeMCMCFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR TCOMP\nRAJ 12:00:00\nDECJ 10:00:00\nF0 2.0 1\n"
           "F1 0.0\nPEPOCH 55000\nDM 0.0\n")
    m = get_model(par)
    rng = np.random.default_rng(0)
    sets, templates = [], []
    for k in range(2):
        mjds = np.sort(rng.uniform(55000, 55002, 400))
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=np.inf,
                                    obs="@", add_noise=False)
        sets.append(t)
        bins = 32
        tpl = 1.0 + 0.8 * np.cos(2 * np.pi * (np.arange(bins) + 0.5) / bins)
        templates.append(tpl)
    prior = {"F0": {"min": 1.9999, "max": 2.0001}}
    f = CompositeMCMCFitter(sets, m, templates, n_walkers=16, seed=1,
                            prior_info=prior)
    lnl_true = float(f.bt._lnlike_raw(
        np.asarray(f.bt.initial_position())))
    assert np.isfinite(lnl_true)
    # composite = sum of parts
    from pint_tpu.mcmc_fitter import MCMCFitterBinnedTemplate

    parts = 0.0
    for t, tpl in zip(sets, templates):
        fb = MCMCFitterBinnedTemplate(t, get_model(par), tpl, n_walkers=16,
                                      prior_info=prior)
        parts += float(fb.bt._lnlike_raw(np.asarray(fb.bt.initial_position())))
    assert lnl_true == pytest.approx(parts, rel=1e-9)
    f.fit_toas(n_steps=60)
    assert np.isfinite(f.maxpost)
