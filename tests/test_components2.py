"""Version-dependent component tests: PLDMNoise, DMWaveX, SWX,
PiecewiseSpindown.

(reference patterns: tests/test_dmwavex.py, tests/test_sw.py,
tests/test_piecewise.py.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_fromMJDs

BASE = """
PSR TESTC2
RAJ 12:10:00.0
DECJ 09:00:00.0
F0 218.8 1
F1 -4e-16 1
PEPOCH 55300
DM 15.0 1
"""


def _toas(m, n=60, span=(55000, 55600), freqs=(800.0, 1400.0), **kw):
    mjds = np.linspace(*span, n)
    f = np.where(np.arange(n) % 2, freqs[0], freqs[1])
    return make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=f,
                                   obs="gbt", add_noise=False, **kw)


def test_pldm_noise_basis_and_gls():
    from pint_tpu.fitter import GLSFitter

    par = BASE + "TNDMAMP -13.0\nTNDMGAM 3.0\nTNDMC 10\n"
    m = get_model(par)
    assert "PLDMNoise" in m.components
    t = _toas(m)
    prepared = m.prepare(t)
    F = np.asarray(prepared.prep["dmrn_F"])
    assert F.shape == (60, 20)
    # reconstruct: F = fourier_basis * (1400/nu)^2 row scaling
    mjds = t.get_mjds()
    tspan_s = (mjds.max() - mjds.min() + 1.0) * 86400.0
    t_s = (mjds - mjds.min()) * 86400.0
    freqs = np.arange(1, 11) / tspan_s
    arg = 2 * np.pi * np.outer(t_s, freqs)
    base = np.empty((60, 20))
    base[:, 0::2] = np.sin(arg)
    base[:, 1::2] = np.cos(arg)
    chrom = (1400.0 / np.asarray(t.freq_mhz)) ** 2
    np.testing.assert_allclose(F, base * chrom[:, None], atol=1e-10)
    f = GLSFitter(t, m)
    chi2 = f.fit_toas()
    assert np.isfinite(chi2)


def test_dmwavex_chromatic_delay():
    par = BASE + ("DMWXEPOCH 55300\nDMWXFREQ_0001 0.005\n"
                  "DMWXSIN_0001 1e-4 1\nDMWXCOS_0001 -5e-5 1\n")
    m = get_model(par)
    assert "DMWaveX" in m.components
    base = get_model(BASE)
    t = _toas(base)
    d_all = np.asarray(m.delay(t)) - np.asarray(base.delay(t))
    # perfect 1/nu^2 scaling between the two frequency groups
    from pint_tpu.constants import DMconst

    mjd = t.day + t.sec / 86400.0
    dt_day = mjd - 55300.0
    arg = 2 * np.pi * 0.005 * dt_day
    dm_expect = 1e-4 * np.sin(arg) - 5e-5 * np.cos(arg)
    expect = DMconst * dm_expect / np.asarray(t.freq_mhz) ** 2
    np.testing.assert_allclose(d_all, expect, atol=1e-10)


def test_swx_windows():
    """Upstream SWX convention: SWXDM is the window's MAXIMUM
    solar-wind DM [pc cm^-3], contribution SWXDM * g(t)/max_window(g)."""
    swxdm = 2.5e-4  # pc cm^-3 (max DM over the window)
    par_plain = BASE + "NE_SW 7.9\n"
    par_swx = BASE + (f"NE_SW 7.9\nSWXDM_0001 {swxdm} 1\n"
                      "SWXR1_0001 54990\nSWXR2_0001 55300\n")
    m_plain = get_model(par_plain)
    m_swx = get_model(par_swx)
    assert "SolarWindDispersionX" in m_swx.components
    assert "SolarWindDispersion" not in m_swx.components
    t = _toas(m_plain)
    d_plain = np.asarray(m_plain.delay(t))
    d_swx = np.asarray(m_swx.delay(t))
    mjd = t.get_mjds()  # the same clock the window masks use
    inside = (mjd >= 54990) & (mjd < 55300)
    base = np.asarray(get_model(BASE).delay(t))
    sw_plain = d_plain - base
    sw_swx = d_swx - base
    # outside windows: base NE_SW applies unchanged
    np.testing.assert_allclose(sw_swx[~inside], sw_plain[~inside], rtol=1e-5)
    # inside: per-TOA geometry g recovered from the plain model
    # (sw_plain = DMconst * 7.9 * g / f^2), normalized by its window max
    from pint_tpu.constants import DMconst

    f2 = np.asarray(t.freq_mhz) ** 2
    g = sw_plain * f2 / (DMconst * 7.9)
    expect = DMconst * swxdm * (g / g[inside].max()) / f2
    np.testing.assert_allclose(sw_swx[inside], expect[inside], rtol=1e-4)
    # the window's peak DM equals SWXDM by construction
    dm_inside = sw_swx[inside] * f2[inside] / DMconst
    assert abs(dm_inside.max() - swxdm) < 1e-3 * swxdm


def test_swx_power_index_quadrature():
    """The general-p quadrature geometry: exact reduction at p=2 and
    agreement with direct numerical integration at p=2.5."""
    import jax.numpy as jnp

    from pint_tpu.constants import AU_LS, ONE_AU_PC
    from pint_tpu.models.solar_wind import solar_wind_geometry_p

    rng = np.random.default_rng(3)
    n = 40
    sun = rng.normal(0, AU_LS, (n, 3)) + np.array([AU_LS, 0, 0])
    nh = rng.normal(0, 1, (n, 3))
    nh /= np.linalg.norm(nh, axis=1, keepdims=True)
    # p = 2: closed form (pi - theta)/(r sin theta)
    g2 = np.asarray(solar_wind_geometry_p(jnp.asarray(sun), jnp.asarray(nh), 2.0))
    r = np.linalg.norm(sun, axis=1)
    cos_t = np.clip(np.sum(sun * nh, axis=1) / r, -1, 1)
    theta = np.arccos(cos_t)
    expect2 = ONE_AU_PC * (np.pi - theta) / ((r / AU_LS) * np.sin(theta))
    np.testing.assert_allclose(g2, expect2, rtol=1e-12)
    # p = 2.5: brute-force line-of-sight integral
    p = 2.5
    gp = np.asarray(solar_wind_geometry_p(jnp.asarray(sun), jnp.asarray(nh), p))
    s = np.linspace(0, 2000 * AU_LS, 400001)
    for i in range(0, n, 13):
        d = np.sqrt(r[i] ** 2 + s**2 - 2 * r[i] * s * cos_t[i])
        integ = np.trapezoid((AU_LS / d) ** p, s) * (ONE_AU_PC / AU_LS)
        np.testing.assert_allclose(gp[i], integ, rtol=1e-3)


def test_piecewise_spindown():
    from pint_tpu.fitter import DownhillWLSFitter
    import copy

    par = BASE + ("PWEP_0001 55100\nPWSTART_0001 55000\nPWSTOP_0001 55200\n"
                  "PWPH_0001 0.0\nPWF0_0001 1e-8 1\nPWF1_0001 0\n")
    m = get_model(par)
    assert "PiecewiseSpindown" in m.components
    t = _toas(m)
    # self-consistency: simulated from the same model -> flat residuals
    r = Residuals(t, m)
    assert r.rms_weighted() < 1e-8
    # the segment F0 offset is visible against a model without it
    m0 = copy.deepcopy(m)
    m0.PWF0_0001.value = 0.0
    r0 = np.asarray(Residuals(t, m0, subtract_mean=False).calc_time_resids())
    mjd = t.day + t.sec / 86400.0
    inside = (mjd >= 55000) & (mjd < 55200)
    assert np.abs(r0[inside]).max() > 1e-5  # 1e-8 Hz over ~100 d
    # and the fitter recovers it
    m1 = copy.deepcopy(m)
    m1.PWF0_0001.value = 0.0
    f = DownhillWLSFitter(t, m1)
    f.fit_toas()
    assert f.model.PWF0_0001.value == pytest.approx(1e-8, rel=1e-3)


def test_piecewise_pwf2_and_validation():
    import copy

    # PWF2 quadratic frequency term contributes dt^3/6 cycles in-window
    par = BASE + ("PWEP_0001 55100\nPWSTART_0001 55000\nPWSTOP_0001 55200\n"
                  "PWPH_0001 0.0\nPWF0_0001 0\nPWF1_0001 0\n"
                  "PWF2_0001 1e-21\n")
    m = get_model(par)
    assert m.PWF2_0001.value == pytest.approx(1e-21)
    t = _toas(m)
    m0 = copy.deepcopy(m)
    m0.PWF2_0001.value = 0.0
    r = np.asarray(Residuals(t, m, subtract_mean=False).calc_time_resids())
    r0 = np.asarray(Residuals(t, m0, subtract_mean=False).calc_time_resids())
    # same clock as the component's window masks (TDB, not UTC):
    # a boundary TOA must not flip between the two
    mjd = t.tdb.day + t.tdb.sec / 86400.0
    inside = (mjd >= 55000) & (mjd < 55200)
    f0 = m.F0.value
    dt = (mjd - 55100) * 86400.0
    expect = 1e-21 * dt**3 / 6.0 / f0  # <=0.11 cycles: no phase wrap
    got = r - r0
    # rtol: the component evaluates dt at the delay-corrected emission
    # time (TDB - delays), the hand formula at the raw UTC grid
    np.testing.assert_allclose(got[inside], expect[inside],
                               rtol=2e-3, atol=1e-10)
    assert np.abs(got[~inside]).max() < 1e-12
    # missing window bounds -> typed MissingParameter at validate time
    from pint_tpu.models.timing_model import MissingParameter

    with pytest.raises(MissingParameter):
        get_model(BASE + "PWEP_0001 55100\nPWF0_0001 1e-8\n")
