"""Version-dependent component tests: PLDMNoise, DMWaveX, SWX,
PiecewiseSpindown.

(reference patterns: tests/test_dmwavex.py, tests/test_sw.py,
tests/test_piecewise.py.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_fromMJDs

BASE = """
PSR TESTC2
RAJ 12:10:00.0
DECJ 09:00:00.0
F0 218.8 1
F1 -4e-16 1
PEPOCH 55300
DM 15.0 1
"""


def _toas(m, n=60, span=(55000, 55600), freqs=(800.0, 1400.0), **kw):
    mjds = np.linspace(*span, n)
    f = np.where(np.arange(n) % 2, freqs[0], freqs[1])
    return make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=f,
                                   obs="gbt", add_noise=False, **kw)


def test_pldm_noise_basis_and_gls():
    from pint_tpu.fitter import GLSFitter

    par = BASE + "TNDMAMP -13.0\nTNDMGAM 3.0\nTNDMC 10\n"
    m = get_model(par)
    assert "PLDMNoise" in m.components
    t = _toas(m)
    prepared = m.prepare(t)
    F = np.asarray(prepared.prep["dmrn_F"])
    assert F.shape == (60, 20)
    # reconstruct: F = fourier_basis * (1400/nu)^2 row scaling
    mjds = t.get_mjds()
    tspan_s = (mjds.max() - mjds.min() + 1.0) * 86400.0
    t_s = (mjds - mjds.min()) * 86400.0
    freqs = np.arange(1, 11) / tspan_s
    arg = 2 * np.pi * np.outer(t_s, freqs)
    base = np.empty((60, 20))
    base[:, 0::2] = np.sin(arg)
    base[:, 1::2] = np.cos(arg)
    chrom = (1400.0 / np.asarray(t.freq_mhz)) ** 2
    np.testing.assert_allclose(F, base * chrom[:, None], atol=1e-10)
    f = GLSFitter(t, m)
    chi2 = f.fit_toas()
    assert np.isfinite(chi2)


def test_dmwavex_chromatic_delay():
    par = BASE + ("DMWXEPOCH 55300\nDMWXFREQ_0001 0.005\n"
                  "DMWXSIN_0001 1e-4 1\nDMWXCOS_0001 -5e-5 1\n")
    m = get_model(par)
    assert "DMWaveX" in m.components
    base = get_model(BASE)
    t = _toas(base)
    d_all = np.asarray(m.delay(t)) - np.asarray(base.delay(t))
    # perfect 1/nu^2 scaling between the two frequency groups
    from pint_tpu.constants import DMconst

    mjd = t.day + t.sec / 86400.0
    dt_day = mjd - 55300.0
    arg = 2 * np.pi * 0.005 * dt_day
    dm_expect = 1e-4 * np.sin(arg) - 5e-5 * np.cos(arg)
    expect = DMconst * dm_expect / np.asarray(t.freq_mhz) ** 2
    np.testing.assert_allclose(d_all, expect, atol=1e-10)


def test_swx_windows():
    """Upstream SWX convention: SWXDM is the window's MAXIMUM
    solar-wind DM [pc cm^-3], contribution SWXDM * g(t)/max_window(g)."""
    swxdm = 2.5e-4  # pc cm^-3 (max DM over the window)
    par_plain = BASE + "NE_SW 7.9\n"
    par_swx = BASE + (f"NE_SW 7.9\nSWXDM_0001 {swxdm} 1\n"
                      "SWXR1_0001 54990\nSWXR2_0001 55300\n")
    m_plain = get_model(par_plain)
    m_swx = get_model(par_swx)
    assert "SolarWindDispersionX" in m_swx.components
    assert "SolarWindDispersion" not in m_swx.components
    t = _toas(m_plain)
    d_plain = np.asarray(m_plain.delay(t))
    d_swx = np.asarray(m_swx.delay(t))
    mjd = t.get_mjds()  # the same clock the window masks use
    inside = (mjd >= 54990) & (mjd < 55300)
    base = np.asarray(get_model(BASE).delay(t))
    sw_plain = d_plain - base
    sw_swx = d_swx - base
    # outside windows: base NE_SW applies unchanged
    np.testing.assert_allclose(sw_swx[~inside], sw_plain[~inside], rtol=1e-5)
    # inside: per-TOA geometry g recovered from the plain model
    # (sw_plain = DMconst * 7.9 * g / f^2), normalized by its window max
    from pint_tpu.constants import DMconst

    f2 = np.asarray(t.freq_mhz) ** 2
    g = sw_plain * f2 / (DMconst * 7.9)
    expect = DMconst * swxdm * (g / g[inside].max()) / f2
    np.testing.assert_allclose(sw_swx[inside], expect[inside], rtol=1e-4)
    # the window's peak DM equals SWXDM by construction
    dm_inside = sw_swx[inside] * f2[inside] / DMconst
    assert abs(dm_inside.max() - swxdm) < 1e-3 * swxdm


def test_swx_power_index_quadrature():
    """The general-p quadrature geometry: exact reduction at p=2 and
    agreement with direct numerical integration at p=2.5."""
    import jax.numpy as jnp

    from pint_tpu.constants import AU_LS, ONE_AU_PC
    from pint_tpu.models.solar_wind import solar_wind_geometry_p

    rng = np.random.default_rng(3)
    n = 40
    sun = rng.normal(0, AU_LS, (n, 3)) + np.array([AU_LS, 0, 0])
    nh = rng.normal(0, 1, (n, 3))
    nh /= np.linalg.norm(nh, axis=1, keepdims=True)
    # p = 2: closed form (pi - theta)/(r sin theta)
    g2 = np.asarray(solar_wind_geometry_p(jnp.asarray(sun), jnp.asarray(nh), 2.0))
    r = np.linalg.norm(sun, axis=1)
    cos_t = np.clip(np.sum(sun * nh, axis=1) / r, -1, 1)
    theta = np.arccos(cos_t)
    expect2 = ONE_AU_PC * (np.pi - theta) / ((r / AU_LS) * np.sin(theta))
    np.testing.assert_allclose(g2, expect2, rtol=1e-12)
    # p = 2.5: brute-force line-of-sight integral
    p = 2.5
    gp = np.asarray(solar_wind_geometry_p(jnp.asarray(sun), jnp.asarray(nh), p))
    s = np.linspace(0, 2000 * AU_LS, 400001)
    for i in range(0, n, 13):
        d = np.sqrt(r[i] ** 2 + s**2 - 2 * r[i] * s * cos_t[i])
        integ = np.trapezoid((AU_LS / d) ** p, s) * (ONE_AU_PC / AU_LS)
        np.testing.assert_allclose(gp[i], integ, rtol=1e-3)


def test_piecewise_spindown():
    from pint_tpu.fitter import DownhillWLSFitter
    import copy

    par = BASE + ("PWEP_0001 55100\nPWSTART_0001 55000\nPWSTOP_0001 55200\n"
                  "PWPH_0001 0.0\nPWF0_0001 1e-8 1\nPWF1_0001 0\n")
    m = get_model(par)
    assert "PiecewiseSpindown" in m.components
    t = _toas(m)
    # self-consistency: simulated from the same model -> flat residuals
    r = Residuals(t, m)
    assert r.rms_weighted() < 1e-8
    # the segment F0 offset is visible against a model without it
    m0 = copy.deepcopy(m)
    m0.PWF0_0001.value = 0.0
    r0 = np.asarray(Residuals(t, m0, subtract_mean=False).calc_time_resids())
    mjd = t.day + t.sec / 86400.0
    inside = (mjd >= 55000) & (mjd < 55200)
    assert np.abs(r0[inside]).max() > 1e-5  # 1e-8 Hz over ~100 d
    # and the fitter recovers it
    m1 = copy.deepcopy(m)
    m1.PWF0_0001.value = 0.0
    f = DownhillWLSFitter(t, m1)
    f.fit_toas()
    assert f.model.PWF0_0001.value == pytest.approx(1e-8, rel=1e-3)


def test_piecewise_pwf2_and_validation():
    import copy

    # PWF2 quadratic frequency term contributes dt^3/6 cycles in-window
    par = BASE + ("PWEP_0001 55100\nPWSTART_0001 55000\nPWSTOP_0001 55200\n"
                  "PWPH_0001 0.0\nPWF0_0001 0\nPWF1_0001 0\n"
                  "PWF2_0001 1e-21\n")
    m = get_model(par)
    assert m.PWF2_0001.value == pytest.approx(1e-21)
    t = _toas(m)
    m0 = copy.deepcopy(m)
    m0.PWF2_0001.value = 0.0
    r = np.asarray(Residuals(t, m, subtract_mean=False).calc_time_resids())
    r0 = np.asarray(Residuals(t, m0, subtract_mean=False).calc_time_resids())
    # same clock as the component's window masks (TDB, not UTC):
    # a boundary TOA must not flip between the two
    mjd = t.tdb.day + t.tdb.sec / 86400.0
    inside = (mjd >= 55000) & (mjd < 55200)
    f0 = m.F0.value
    dt = (mjd - 55100) * 86400.0
    expect = 1e-21 * dt**3 / 6.0 / f0  # <=0.11 cycles: no phase wrap
    got = r - r0
    # rtol: the component evaluates dt at the delay-corrected emission
    # time (TDB - delays), the hand formula at the raw UTC grid
    np.testing.assert_allclose(got[inside], expect[inside],
                               rtol=2e-3, atol=1e-10)
    assert np.abs(got[~inside]).max() < 1e-12
    # missing window bounds -> typed MissingParameter at validate time
    from pint_tpu.models.timing_model import MissingParameter

    with pytest.raises(MissingParameter):
        get_model(BASE + "PWEP_0001 55100\nPWF0_0001 1e-8\n")


def test_swm1_power_law_wind():
    """SWM 1 (r^-SWP power-law wind, reference:
    solar_wind_dispersion.py SWM 1): SWP=2 reproduces the SWM 0
    spherical model exactly (the cos-power quadrature is exact for
    p=2); SWP>2 concentrates DM toward conjunction; an injected
    SWP is recovered by fitting it, proving differentiability through
    the Gauss-Legendre geometry kernel."""
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs
    from pint_tpu.fitter import DownhillWLSFitter

    base = ("PSR SWM1T\nRAJ 05:00:00\nDECJ 02:00:00\nF0 200.0 1\n"
            "PEPOCH 55300\nDM 10.0 1\n")
    m0 = get_model(base + "SWM 0\nNE_SW 8.0\n")
    m1 = get_model(base + "SWM 1\nNE_SW 8.0\nSWP 2.0\n")
    mjds = np.linspace(55000.0, 55365.0, 120)
    t = make_fake_toas_fromMJDs(mjds, m0, error_us=1.0, obs="gbt",
                                iterations=0)
    d0 = np.asarray(m0.total_dm(t))
    d1 = np.asarray(m1.total_dm(t))
    np.testing.assert_allclose(d1, d0, rtol=0, atol=1e-12)

    # p=2.5: bigger DM excess near conjunction, and par round-trip
    m25 = get_model(base + "SWM 1\nNE_SW 8.0\nSWP 2.5\n")
    d25 = np.asarray(m25.total_dm(t))
    # steeper profile -> MORE DM at the conjunction peak, not merely
    # different (a garbage SWM 1 path could still satisfy !=)
    assert (d25 - 10.0).max() > (d0 - 10.0).max()
    m25b = get_model(m25.as_parfile())
    assert m25b.SWM.value == 1.0 and m25b.SWP.value == 2.5

    # recover an injected SWP by fitting (NE_SW fixed, SWP free)
    m_true = get_model(base + "SWM 1\nNE_SW 20.0\nSWP 2.6\n")
    t_sim = make_fake_toas_fromMJDs(mjds, m_true, error_us=0.5, obs="gbt",
                                    add_noise=True, seed=4, iterations=2)
    m_fit = get_model(base.replace("DM 10.0 1", "DM 10.0")
                      + "SWM 1\nNE_SW 20.0\nSWP 2.2 1\n")
    f = DownhillWLSFitter(t_sim, m_fit)
    f.fit_toas(maxiter=12)
    assert f.model.SWP.value == pytest.approx(2.6, abs=0.15), \
        f.model.SWP.value

    # SWM 2 and divergent SWP rejected
    with pytest.raises(ValueError, match="SWM"):
        get_model(base + "SWM 2\nNE_SW 8.0\n")
    with pytest.raises(ValueError, match="SWP"):
        get_model(base + "SWM 1\nNE_SW 8.0\nSWP 0.9\n")


def test_cospow_integral_accuracy_all_regimes():
    """The solar-wind cos-power quadrature (tanh-sinh + closed-form
    half-range) vs dense reference integration: <= 1e-10 absolute
    across p in [1.2, 6] and the full elongation range (measured
    2.4e-12 worst), and a finite p-gradient everywhere (SWP
    fitting)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pint_tpu.models.solar_wind import _cospow_integral

    def ref(phi_hi, p, n=400_001):
        u = (np.arange(n) + 0.5) / n
        psi = phi_hi * u
        return phi_hi * np.mean(np.cos(psi) ** (p - 2.0))

    for p in (1.2, 1.5, 1.9, 2.0, 2.5, 3.7, 5.0):
        for phi in (-1.4, -0.7, 0.5, 1.2, 1.5):
            got = float(_cospow_integral(jnp.array([phi]),
                                         jnp.array([p]))[0])
            want = ref(phi, p)
            assert abs(got - want) < 1e-10, (p, phi, got, want)
        # anti-solar extreme (the sin_t=1e-6 clip's farthest reach):
        # degraded but bounded — ~3e-4 absolute (|F|~5, so ~6e-5
        # relative) at p=1.2; the midpoint reference is itself
        # endpoint-singular here, so the band covers both
        phi = -(np.pi / 2 - 1e-6)
        got = float(_cospow_integral(jnp.array([phi]), jnp.array([p]))[0])
        want = ref(phi, p, n=4_000_001)
        assert abs(got - want) < 1e-3, (p, got, want)
    for p0 in (1.5, 3.0):
        g = jax.grad(lambda pp: jnp.sum(_cospow_integral(
            jnp.array([0.7]), pp * jnp.ones(1))))(p0)
        assert np.isfinite(float(g))


def test_swp_free_under_swm0_rejected():
    """Freeing SWP with SWM 0 would put an identically-zero column in
    the design matrix; validate() must reject it (r4 review), and
    SWP 0.0 under SWM 1 must not slip through a falsy-zero fallback."""
    import pytest

    from pint_tpu.models import get_model

    base = ("PSR SWV2\nRAJ 05:00:00\nDECJ 02:00:00\nF0 200.0 1\n"
            "PEPOCH 55300\nDM 10.0\n")
    with pytest.raises(ValueError, match="SWP"):
        get_model(base + "NE_SW 8.0\nSWP 2.5 1\n")
    with pytest.raises(ValueError, match="SWP"):
        get_model(base + "SWM 1\nNE_SW 8.0\nSWP 0.0\n")


def test_swxp_window_divergence_guard():
    """The per-window SWXP_#### gets the same SWP > 1 divergence guard
    as the base parameter (r4 review: _cospow_half(1.0) is inf, so an
    unguarded window would silently produce inf delays)."""
    import pytest

    from pint_tpu.models import get_model

    base = ("PSR SWV3\nRAJ 05:00:00\nDECJ 02:00:00\nF0 200.0 1\n"
            "PEPOCH 55300\nDM 10.0\nSWM 0\nNE_SW 4.0\n"
            "SWX_0001 5.0 1\nSWXR1_0001 55000\nSWXR2_0001 55600\n")
    with pytest.raises(ValueError, match="SWXP"):
        get_model(base + "SWXP_0001 1.0\n")
    with pytest.raises(ValueError, match="SWXP"):
        get_model(base + "SWXP_0001 0.0\n")
    m = get_model(base + "SWXP_0001 2.3\n")  # valid index loads fine
    assert m.SWXP_0001.value == 2.3
