"""Version-dependent component tests: PLDMNoise, DMWaveX, SWX,
PiecewiseSpindown.

(reference patterns: tests/test_dmwavex.py, tests/test_sw.py,
tests/test_piecewise.py.)
"""

import warnings

import numpy as np
import pytest

warnings.simplefilter("ignore")

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_fromMJDs

BASE = """
PSR TESTC2
RAJ 12:10:00.0
DECJ 09:00:00.0
F0 218.8 1
F1 -4e-16 1
PEPOCH 55300
DM 15.0 1
"""


def _toas(m, n=60, span=(55000, 55600), freqs=(800.0, 1400.0), **kw):
    mjds = np.linspace(*span, n)
    f = np.where(np.arange(n) % 2, freqs[0], freqs[1])
    return make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=f,
                                   obs="gbt", add_noise=False, **kw)


def test_pldm_noise_basis_and_gls():
    from pint_tpu.fitter import GLSFitter

    par = BASE + "TNDMAMP -13.0\nTNDMGAM 3.0\nTNDMC 10\n"
    m = get_model(par)
    assert "PLDMNoise" in m.components
    t = _toas(m)
    prepared = m.prepare(t)
    F = np.asarray(prepared.prep["dmrn_F"])
    assert F.shape == (60, 20)
    # reconstruct: F = fourier_basis * (1400/nu)^2 row scaling
    mjds = t.get_mjds()
    tspan_s = (mjds.max() - mjds.min() + 1.0) * 86400.0
    t_s = (mjds - mjds.min()) * 86400.0
    freqs = np.arange(1, 11) / tspan_s
    arg = 2 * np.pi * np.outer(t_s, freqs)
    base = np.empty((60, 20))
    base[:, 0::2] = np.sin(arg)
    base[:, 1::2] = np.cos(arg)
    chrom = (1400.0 / np.asarray(t.freq_mhz)) ** 2
    np.testing.assert_allclose(F, base * chrom[:, None], atol=1e-10)
    f = GLSFitter(t, m)
    chi2 = f.fit_toas()
    assert np.isfinite(chi2)


def test_dmwavex_chromatic_delay():
    par = BASE + ("DMWXEPOCH 55300\nDMWXFREQ_0001 0.005\n"
                  "DMWXSIN_0001 1e-4 1\nDMWXCOS_0001 -5e-5 1\n")
    m = get_model(par)
    assert "DMWaveX" in m.components
    base = get_model(BASE)
    t = _toas(base)
    d_all = np.asarray(m.delay(t)) - np.asarray(base.delay(t))
    # perfect 1/nu^2 scaling between the two frequency groups
    from pint_tpu.constants import DMconst

    mjd = t.day + t.sec / 86400.0
    dt_day = mjd - 55300.0
    arg = 2 * np.pi * 0.005 * dt_day
    dm_expect = 1e-4 * np.sin(arg) - 5e-5 * np.cos(arg)
    expect = DMconst * dm_expect / np.asarray(t.freq_mhz) ** 2
    np.testing.assert_allclose(d_all, expect, atol=1e-10)


def test_swx_windows():
    par_plain = BASE + "NE_SW 7.9\n"
    par_swx = BASE + ("NE_SW 7.9\nSWXDM_0001 12.5 1\n"
                      "SWXR1_0001 54990\nSWXR2_0001 55300\n")
    m_plain = get_model(par_plain)
    m_swx = get_model(par_swx)
    assert "SolarWindDispersionX" in m_swx.components
    assert "SolarWindDispersion" not in m_swx.components
    t = _toas(m_plain)
    d_plain = np.asarray(m_plain.delay(t))
    d_swx = np.asarray(m_swx.delay(t))
    mjd = t.get_mjds()  # the same clock the window masks use
    inside = (mjd >= 54990) & (mjd < 55300)
    base = np.asarray(get_model(BASE).delay(t))
    sw_plain = d_plain - base
    sw_swx = d_swx - base
    # rtol reflects subtractive cancellation: the ~1 us solar-wind term
    # is recovered from ~100 s total delays
    np.testing.assert_allclose(sw_swx[~inside], sw_plain[~inside], rtol=1e-5)
    np.testing.assert_allclose(sw_swx[inside], sw_plain[inside] * 12.5 / 7.9,
                               rtol=1e-5)


def test_piecewise_spindown():
    from pint_tpu.fitter import DownhillWLSFitter
    import copy

    par = BASE + ("PWEP_0001 55100\nPWSTART_0001 55000\nPWSTOP_0001 55200\n"
                  "PWPH_0001 0.0\nPWF0_0001 1e-8 1\nPWF1_0001 0\n")
    m = get_model(par)
    assert "PiecewiseSpindown" in m.components
    t = _toas(m)
    # self-consistency: simulated from the same model -> flat residuals
    r = Residuals(t, m)
    assert r.rms_weighted() < 1e-8
    # the segment F0 offset is visible against a model without it
    m0 = copy.deepcopy(m)
    m0.PWF0_0001.value = 0.0
    r0 = np.asarray(Residuals(t, m0, subtract_mean=False).calc_time_resids())
    mjd = t.day + t.sec / 86400.0
    inside = (mjd >= 55000) & (mjd < 55200)
    assert np.abs(r0[inside]).max() > 1e-5  # 1e-8 Hz over ~100 d
    # and the fitter recovers it
    m1 = copy.deepcopy(m)
    m1.PWF0_0001.value = 0.0
    f = DownhillWLSFitter(t, m1)
    f.fit_toas()
    assert f.model.PWF0_0001.value == pytest.approx(1e-8, rel=1e-3)
