"""Error-budget tests: assert the documented accuracy of each fallback
tier (see ERRORBUDGET.md). These are ABSOLUTE anchors, not
self-consistency — each pins a claim against independent published
values or independent implementations.

(reference pattern: tests/test_precision.py, tests/test_pulsar_mjd.py —
the reference pins its chain against TEMPO/Tempo2 golden values; with
no reference tree or kernels on disk, these anchors are hand-derivable
published constants and cross-implementation checks.)
"""

import numpy as np
import pytest

from pint_tpu.mjd import Epochs
from pint_tpu import timescales as ts


def test_vsop87_earth_anchors():
    """VSOP87-truncation Earth against published orbital facts:
    J2000 heliocentric distance & true longitude, aphelion/perihelion
    range, and the ~1 arcsec-class claim vs an independent formula."""
    from pint_tpu.ephemeris.vsop87 import (earth_heliocentric_lbr,
                                           earth_heliocentric_icrs_m)
    from pint_tpu.constants import AU_M

    # J2000.0: R = 0.9833 AU (3 days before perihelion), true longitude
    # = mean longitude (100.466 deg) + equation of center at M~357.5 deg
    L, B, R = earth_heliocentric_lbr(np.array([0.0]))
    assert abs(R[0] - 0.983327) < 2e-5
    assert abs(np.degrees(L[0]) - 100.378) < 0.01
    assert abs(B[0]) < 1e-5  # Earth defines the ecliptic to ~arcsec

    # distance range over a decade = [perihelion, aphelion]
    tau = np.linspace(0, 0.001 * 3653, 20000) / 1000.0  # 10 yr of millennia
    _, _, R10 = earth_heliocentric_lbr(tau)
    assert 0.9832 < R10.min() < 0.9834
    assert 1.0166 < R10.max() < 1.0168

    # ICRS frame: Earth's z-amplitude = sin(obliquity) * R
    T = np.linspace(0, 0.25, 5000)
    r = earth_heliocentric_icrs_m(T)
    zmax = np.abs(r[:, 2]).max() / AU_M
    assert abs(zmax - np.sin(np.deg2rad(23.4365)) * 1.0167) < 1e-3


def test_analytic_earth_uses_vsop87():
    """The ephemeris fallback's Earth must be the VSOP87 path (the
    Keplerian-elements Earth measured 5-16 thousand km off)."""
    from pint_tpu.ephemeris import analytic
    from pint_tpu.ephemeris.vsop87 import earth_heliocentric_icrs_m

    mjds = np.array([52000.0, 55000.5, 58700.25])
    T = (mjds - 51544.5) / 36525.0
    e, _ = analytic.body_posvel_ssb("earth", mjds)
    s, _ = analytic.body_posvel_ssb("sun", mjds)
    np.testing.assert_allclose(e - s, earth_heliocentric_icrs_m(T),
                               rtol=0, atol=1.0)  # metres


def test_earth_moon_emb_consistency():
    """EMB must sit on the Earth-Moon line at the mass-ratio point."""
    from pint_tpu.ephemeris import analytic

    mjds = np.array([55000.0, 56000.0])
    e, _ = analytic.body_posvel_ssb("earth", mjds)
    m, _ = analytic.body_posvel_ssb("moon", mjds)
    b, _ = analytic.body_posvel_ssb("emb", mjds)
    ratio = analytic._EARTH_MOON_MASS_RATIO
    np.testing.assert_allclose(b, e + (m - e) / (1.0 + ratio), atol=1e-3)


def test_nutation_published_anchor():
    """Nutation vs the published worked example (Meeus ch.22, 1987
    April 10.0 TD: dpsi = -3.788", deps = +9.443", full IAU 1980
    series). With the full 77-term IAU2000B table (r4) the remaining
    offset is the 1980-vs-2000 MODEL difference (~7 mas in dpsi from
    the IAU2000 amplitude/precession-rate revisions, ~2.5 mas in
    deps), not truncation — so the bounds are cross-model bounds, an
    order of magnitude tighter than the r3 13-term-truncation ones."""
    from pint_tpu.earth.erfa_lite import nutation

    T = (2446895.5 - 2451545.0) / 36525.0
    dpsi, deps = nutation(np.array([T]))
    dpsi_as = np.degrees(dpsi[0]) * 3600
    deps_as = np.degrees(deps[0]) * 3600
    assert abs(dpsi_as - (-3.788)) < 0.010
    assert abs(deps_as - 9.443) < 0.005


def test_nutation_sofa_nut00b_anchor():
    """EXACT anchor: the published SOFA/ERFA t_sofa_c test values for
    iauNut00b(2400000.5, 53736.0). This pins every one of the 77
    luni-solar rows, all six coefficient columns, the linear-only
    fundamental-argument convention, AND the planetary-bias offsets:
    a single mistyped table entry of 1 unit (0.1 uas ~ 5e-13 rad)
    would blow the 1e-13 tolerance. Measured residual ~1e-19 rad."""
    from pint_tpu.earth.erfa_lite import nutation

    T = (53736.0 - 51544.5) / 36525.0
    dpsi, deps = nutation(T)
    assert abs(dpsi - (-0.9632552291148362783e-5)) < 1e-13
    assert abs(deps - 0.4063197106621159367e-4) < 1e-13


def test_moon_meeus_worked_example():
    """Lunar series vs the published full-theory worked example
    (Meeus, Astronomical Algorithms ch.47, 1992 April 12.0 TD:
    lambda 133.162655 deg, beta -3.229126 deg, Delta 368409.7 km).
    The truncation tail is ~3 arcsec / few km; a single mistyped
    major coefficient would blow these bounds by 10-100x."""
    from pint_tpu.ephemeris.analytic import _moon_geocentric_ecliptic

    T = np.array([(2448724.5 - 2451545.0) / 36525.0])
    xyz = _moon_geocentric_ecliptic(T)[0]
    r = np.linalg.norm(xyz)
    lon = np.degrees(np.arctan2(xyz[1], xyz[0])) % 360
    lat = np.degrees(np.arcsin(xyz[2] / r))
    assert abs(lon - 133.162655) * 3600 < 6.0   # arcsec
    assert abs(lat + 3.229126) * 3600 < 4.0
    assert abs(r / 1e3 - 368409.7) < 6.0        # km
    full = 368409.7e3 * np.array([
        np.cos(np.radians(-3.229126)) * np.cos(np.radians(133.162655)),
        np.cos(np.radians(-3.229126)) * np.sin(np.radians(133.162655)),
        np.sin(np.radians(-3.229126))])
    assert np.linalg.norm(xyz - full) < 15e3    # ~15 km truncation tier


def test_tdb_table_vs_series():
    """Integrated TDB-TT table vs the harmonic series. With the r4
    fit-derived extension (timescales._TDB_TERMS_EXT; VERDICT r3 item
    4: 'TDB fallback <= 100 ns vs the table') the series must stay
    within 100 ns of the table across the full coverage — two orders
    under the r3 10-term truncation bound of ~10 us — so the
    out-of-range fallback and the C++ mirror are interchangeable with
    the primary path at the 0.1 us level."""
    mjd = np.arange(40001.0, 63999.0, 1.0)
    tt = Epochs(mjd.astype(np.int64), (mjd % 1) * 86400.0, "tt")
    tab = ts.tdb_minus_tt(tt)
    ser = ts.tdb_minus_tt_series(tt)
    d = tab - ser
    assert np.abs(d).max() < 100e-9, np.abs(d).max()
    # same estimator applied to table and series: the shared annual
    # term must agree at the ~1 us level (convention calibration)
    T = (mjd - 51544.5) / 36525.0
    w = 628.3075850
    A = np.stack([np.sin(w * T), np.cos(w * T), T * np.sin(w * T),
                  T * np.cos(w * T), np.ones_like(T), T], 1)
    ct, *_ = np.linalg.lstsq(A, tab, rcond=None)
    cs, *_ = np.linalg.lstsq(A, ser, rcond=None)
    amp_t, amp_s = np.hypot(ct[0], ct[1]), np.hypot(cs[0], cs[1])
    assert abs(amp_t - amp_s) < 2e-6
    assert abs(amp_s - 0.001656675) < 5e-6  # estimator-level check
    # out-of-table-range epochs fall back to the series
    far = Epochs(np.array([30000], np.int64), np.array([0.0]), "tt")
    np.testing.assert_allclose(ts.tdb_minus_tt(far),
                               ts.tdb_minus_tt_series(far), atol=1e-12)


def test_tdb_series_forced_by_env(monkeypatch):
    monkeypatch.setenv("PINT_TPU_TDB_SERIES", "1")
    mjd = np.array([55000.0])
    tt = Epochs(mjd.astype(np.int64), np.array([0.0]), "tt")
    np.testing.assert_allclose(ts.tdb_minus_tt(tt),
                               ts.tdb_minus_tt_series(tt), atol=1e-15)


def test_leap_seconds_vendored_file_loaded():
    """The vendored leap-seconds.list must actually parse (not the
    hardcoded fallback): spot-check entries beyond the fallback's span
    and the standard 2017 value."""
    assert ts.tai_minus_utc(np.array([57755]))[0] == 37.0
    assert ts.tai_minus_utc(np.array([50000]))[0] == 29.0
    assert ts.tai_minus_utc(np.array([41317]))[0] == 10.0
    # fallback and file agree everywhere both are defined
    for mjd, val in ts._LEAP_TABLE_FALLBACK:
        assert ts.tai_minus_utc(np.array([mjd]))[0] == val


def test_topocentric_tdb_diurnal_term():
    """Topocentric TDB-TT (v_earth . r_obs / c^2): comparing a ground
    observatory against the geocenter at identical epochs isolates the
    term — amplitude ~|v_E| R_earth cos(lat)/c^2 (~1.9 us at GBT's
    latitude), period one sidereal day."""
    from pint_tpu.toa import TOA, TOAs

    mjds = 55000.0 + np.arange(0.0, 3.0, 1.0 / 24.0)  # hourly, 3 days
    def build(obs):
        lst = [TOA(int(m), (m - int(m)) * 86400.0, error_us=1.0,
                   freq_mhz=1400.0, obs=obs) for m in mjds]
        t = TOAs(lst)
        t.apply_clock_corrections()
        t.compute_TDBs()
        return t

    t_gbt = build("gbt")
    t_geo = build("geocenter")
    # clock chain is zero (no files shipped), so the TDB difference IS
    # the topocentric term
    d = ((t_gbt.tdb.day - t_geo.tdb.day) * 86400.0
         + (t_gbt.tdb.sec - t_geo.tdb.sec))
    amp = (d.max() - d.min()) / 2
    assert 1.0e-6 < amp < 2.3e-6, amp
    # diurnal: strong anticorrelation at half a day, correlation at 1 d
    x = d - d.mean()
    lag12 = np.corrcoef(x[:-12], x[12:])[0, 1]
    lag24 = np.corrcoef(x[:-24], x[24:])[0, 1]
    assert lag12 < -0.8, lag12
    assert lag24 > 0.8, lag24


def test_sofa_cookbook_celestial_pole_anchor():
    """Published worked example: the SOFA 'Tools for Earth Attitude'
    cookbook (2007 April 5, 12h UTC) gives the celestial pole
    coordinates X = +0.000712264729599, Y = +0.000044385250426 for
    IAU 2000A. The bottom row of our equinox-based NPB matrix IS
    (X, Y, ~1) — the pole position is decomposition-independent, so
    this anchors the full bias+precession+nutation chain against an
    external published number. Tolerance 1e-7 rad (~20 mas) covers the
    IAU1976+2000B-vs-2000A model difference (measured ~4e-8 rad =
    8 mas) with margin; a sign/order/units mistake anywhere in the
    chain is orders of magnitude larger."""
    import jax
    jax.config.update("jax_enable_x64", True)
    from pint_tpu.earth import erfa_lite as el
    from pint_tpu import timescales as ts
    from pint_tpu.mjd import Epochs

    tt = ts.utc_to_tt(Epochs([54195], [43200.0], "utc"))
    T = float((tt.day[0] - 51544) - 0.5 + tt.sec[0] / 86400.0) / 36525.0
    NPB = (el.nutation_matrix(np.array([T]))[0]
           @ el.precession_matrix(np.array([T]))[0]
           @ el._bias_matrix())
    assert abs(NPB[2, 0] - 0.000712264729599) < 1e-7
    assert abs(NPB[2, 1] - 0.000044385250426) < 1e-7
    # and the pole column consistency (matrix is a rotation)
    assert abs(np.linalg.det(NPB) - 1.0) < 1e-12


def test_sofa_era00_anchor():
    """EXACT anchor: published SOFA t_sofa_c test value
    iauEra00(2400000.5, 54388.0) = 0.4022837240028158102 rad."""
    from pint_tpu.earth.erfa_lite import era
    from pint_tpu.mjd import Epochs

    got = float(era(Epochs([54388], [0.0], "ut1"))[0])
    assert abs(got - 0.4022837240028158102) < 1e-12


def test_sofa_obl06_anchor():
    """EXACT anchor: published SOFA t_sofa_c test value
    iauObl06(2400000.5, 54388.0) = 0.4090749229387258204 rad pins the
    IAU2006 mean-obliquity polynomial."""
    from pint_tpu.earth.erfa_lite import mean_obliquity

    T = (54388.0 - 51544.5) / 36525.0
    got = float(mean_obliquity(np.array([T]))[0])
    assert abs(got - 0.4090749229387258204) < 1e-13
