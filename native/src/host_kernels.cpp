// pint_tpu native host kernels.
//
// C++ equivalents of the hot host-side precompute loops, matching the
// Python implementations bit-for-bit in structure (same truncated
// series, same constants) so either path may serve any call:
//
//  - tdb_minus_tt:   Fairhead–Bretagnon 1990 truncated series
//                    (python: pint_tpu/timescales.py::tdb_minus_tt)
//  - itrf_to_gcrs:   Earth-rotation chain bias/precession/nutation/
//                    ERA/GAST/polar-motion
//                    (python: pint_tpu/earth/erfa_lite.py)
//  - cheby_posvel:   SPK type 2/3 Chebyshev record evaluation
//                    (python: pint_tpu/io/spk.py::SPKKernel.posvel)
//
// The reference package leans on native code for exactly these jobs —
// ERFA (C) for earth rotation and time scales, jplephem+numpy (C) for
// ephemeris Chebyshev work (reference: src/pint/erfautils.py,
// src/pint/solar_system_ephemerides.py) — so the TPU build carries
// native host kernels too, per-TOA work being the host-side hot path
// feeding the device TOABatch.
//
// C ABI, called from Python via ctypes (no pybind11 in the build env).

#include <cmath>
#include <cstddef>
#include <cstdint>
#include <vector>

namespace {

constexpr double TWO_PI = 6.283185307179586476925287;
constexpr double ARCSEC_TO_RAD = TWO_PI / (360.0 * 3600.0);
constexpr double SECS_PER_DAY = 86400.0;
constexpr double OMEGA_EARTH = 7.292115855306589e-5;  // rad/s (IERS)

inline double jc_from_epoch(std::int64_t day, double sec) {
  // Julian centuries since J2000.0 (MJD 51544.5)
  return ((static_cast<double>(day - 51544) - 0.5) + sec / SECS_PER_DAY) /
         36525.0;
}

struct Mat3 {
  double m[3][3];
};

inline Mat3 matmul(const Mat3& a, const Mat3& b) {
  Mat3 r{};
  for (int i = 0; i < 3; ++i)
    for (int j = 0; j < 3; ++j) {
      double s = 0.0;
      for (int k = 0; k < 3; ++k) s += a.m[i][k] * b.m[k][j];
      r.m[i][j] = s;
    }
  return r;
}

inline Mat3 rx(double a) {
  double c = std::cos(a), s = std::sin(a);
  return Mat3{{{1, 0, 0}, {0, c, s}, {0, -s, c}}};
}
inline Mat3 ry(double a) {
  double c = std::cos(a), s = std::sin(a);
  return Mat3{{{c, 0, -s}, {0, 1, 0}, {s, 0, c}}};
}
inline Mat3 rz(double a) {
  double c = std::cos(a), s = std::sin(a);
  return Mat3{{{c, s, 0}, {-s, c, 0}, {0, 0, 1}}};
}

// IAU2000B nutation, table INJECTED from Python at library load
// (pt_set_nut_table below; pint_tpu/native/__init__.py::get_lib pushes
// erfa_lite._NUT_TERMS so the 77x11 table has exactly one source of
// truth). Row layout: l lp F D Om multipliers then ps pst pc ec ect es
// in 0.1 uas; dpsi = (ps+pst*T) sin + pc cos, deps = (ec+ect*T) cos +
// es sin, plus the fixed planetary-bias offsets [arcsec]. Built-in
// default: the dominant 13 terms (pc/es zero), so a bare dlopen
// without the setter still computes a ~20 mas-class nutation.
std::vector<double> g_nut_table = {
    0, 0, 0, 0, 1, -172064161.0, -174666.0, 0, 92052331.0, 9086.0, 0,
    0, 0, 2, -2, 2, -13170906.0, -1675.0, 0, 5730336.0, -3015.0, 0,
    0, 0, 2, 0, 2, -2276413.0, -234.0, 0, 978459.0, -485.0, 0,
    0, 0, 0, 0, 2, 2074554.0, 207.0, 0, -897492.0, 470.0, 0,
    0, 1, 0, 0, 0, 1475877.0, -3633.0, 0, 73871.0, -184.0, 0,
    0, 1, 2, -2, 2, -516821.0, 1226.0, 0, 224386.0, -677.0, 0,
    1, 0, 0, 0, 0, 711159.0, 73.0, 0, -6750.0, 0.0, 0,
    0, 0, 2, 0, 1, -387298.0, -367.0, 0, 200728.0, 18.0, 0,
    1, 0, 2, 0, 2, -301461.0, -36.0, 0, 129025.0, -63.0, 0,
    0, -1, 2, -2, 2, 215829.0, -494.0, 0, -95929.0, 299.0, 0,
    0, 0, 2, -2, 1, 128227.0, 137.0, 0, -68982.0, -9.0, 0,
    -1, 0, 2, 0, 2, 123457.0, 11.0, 0, -53311.0, 32.0, 0,
    -1, 0, 0, 2, 0, 156994.0, 10.0, 0, -1235.0, 0.0, 0,
};
double g_nut_bias_psi_as = 0.0;  // [arcsec]
double g_nut_bias_eps_as = 0.0;

void nutation(double T, double* dpsi, double* deps) {
  // LINEAR-only Delaunay arguments, as the IAU2000B model prescribes
  // (mirrors erfa_lite._fund_args_nut00b; quadratic terms would move
  // the series ~10 uas off the published model at |T|~0.1)
  const double l = (485868.249036 + 1717915923.2178 * T) * ARCSEC_TO_RAD;
  const double lp = (1287104.79305 + 129596581.0481 * T) * ARCSEC_TO_RAD;
  const double F = (335779.526232 + 1739527262.8478 * T) * ARCSEC_TO_RAD;
  const double D = (1072260.70369 + 1602961601.2090 * T) * ARCSEC_TO_RAD;
  const double Om = (450160.398036 - 6962890.5431 * T) * ARCSEC_TO_RAD;
  double dp = 0.0, de = 0.0;
  const std::size_t n = g_nut_table.size() / 11;
  for (std::size_t j = 0; j < n; ++j) {
    const double* row = g_nut_table.data() + 11 * j;
    const double arg =
        row[0] * l + row[1] * lp + row[2] * F + row[3] * D + row[4] * Om;
    const double s = std::sin(arg), c = std::cos(arg);
    dp += (row[5] + row[6] * T) * s + row[7] * c;
    de += (row[8] + row[9] * T) * c + row[10] * s;
  }
  const double scale = 1e-7 * ARCSEC_TO_RAD;
  *dpsi = dp * scale + g_nut_bias_psi_as * ARCSEC_TO_RAD;
  *deps = de * scale + g_nut_bias_eps_as * ARCSEC_TO_RAD;
}

inline double mean_obliquity(double T) {
  return (84381.406 - 46.836769 * T - 0.0001831 * T * T +
          0.00200340 * T * T * T) *
         ARCSEC_TO_RAD;
}

Mat3 bias_matrix() {
  const double dpsi_b = -0.041775 * ARCSEC_TO_RAD;
  const double deps_b = -0.0068192 * ARCSEC_TO_RAD;
  const double dra0 = -0.0146 * ARCSEC_TO_RAD;
  const double eps0 = 84381.406 * ARCSEC_TO_RAD;
  return matmul(matmul(rx(deps_b), ry(dpsi_b * std::sin(eps0))), rz(-dra0));
}

Mat3 precession_matrix(double T) {
  const double zeta =
      (2306.2181 * T + 0.30188 * T * T + 0.017998 * T * T * T) * ARCSEC_TO_RAD;
  const double z =
      (2306.2181 * T + 1.09468 * T * T + 0.018203 * T * T * T) * ARCSEC_TO_RAD;
  const double theta =
      (2004.3109 * T - 0.42665 * T * T - 0.041833 * T * T * T) * ARCSEC_TO_RAD;
  return matmul(matmul(rz(-z), ry(theta)), rz(-zeta));
}

Mat3 nutation_matrix(double T, double dpsi, double deps) {
  const double eps = mean_obliquity(T);
  return matmul(matmul(rx(-(eps + deps)), rz(-dpsi)), rx(eps));
}

inline double era(std::int64_t ut1_day, double ut1_sec) {
  const double du =
      (static_cast<double>(ut1_day - 51544) - 0.5) + ut1_sec / SECS_PER_DAY;
  // Tu mod 1 carrier: MJD-split epoch has JD fraction 0.5 + sec/day; the
  // +0.5 is required or ERA comes out wrong by exactly pi.
  const double frac = ut1_sec / SECS_PER_DAY + 0.5;
  const double theta =
      TWO_PI * (0.7790572732640 + 0.00273781191135448 * du + frac);
  return std::fmod(theta, TWO_PI);
}

}  // namespace

extern "C" {

// Replace the nutation table (rows of 11 doubles, see g_nut_table)
// and planetary-bias offsets [arcsec]. Called once by the ctypes
// loader with erfa_lite's full IAU2000B table.
void pt_set_nut_table(std::int64_t n_rows, const double* rows,
                      double bias_psi_as, double bias_eps_as) {
  g_nut_table.assign(rows, rows + 11 * n_rows);
  g_nut_bias_psi_as = bias_psi_as;
  g_nut_bias_eps_as = bias_eps_as;
}

// TDB-TT [s] (FB1990-form harmonic series; terms injected from
// timescales.py via pt_set_tdb_terms — single source of truth).
// Built-in default: the 10 leading FB1990 terms + the largest
// T-modulated term.
std::vector<double> g_tdb_terms = {
    0.001656675, 628.3075850, 6.2400580,
    0.000022418, 575.3384885, 4.2969771,
    0.000013840, 1256.6151700, 6.1968992,
    0.000004770, 52.9690965, 0.4444038,
    0.000004677, 606.9776754, 4.0211665,
    0.000002257, 21.3299095, 5.5431320,
    0.000001694, 0.3523118, 5.0251207,
    0.000001556, 1203.6460735, 4.1698465,
    0.000001276, 1414.3495242, 4.2781490,
    0.000001193, 1097.7078770, 6.1798441,
};
std::vector<double> g_tdb_t_terms = {0.0000102, 628.3075850, 4.2490};
double g_tdb_poly[3] = {0.0, 0.0, 0.0};
// first g_tdb_n_t_published T-terms are published physics (secular
// factor uses true T); the rest are fit-derived (secular factor clamps
// to the fit window, like the polynomial). Mirrors timescales.py
// _N_T_TERMS_PUBLISHED / _TDB_T_CLAMP_*.
std::int64_t g_tdb_n_t_published = 1;
double g_tdb_t_clamp_lo = -1e30;
double g_tdb_t_clamp_hi = 1e30;

void pt_set_tdb_terms(std::int64_t n, const double* terms,
                      std::int64_t n_t, const double* t_terms,
                      const double* poly3, std::int64_t n_t_published,
                      double t_clamp_lo, double t_clamp_hi) {
  g_tdb_terms.assign(terms, terms + 3 * n);
  g_tdb_t_terms.assign(t_terms, t_terms + 3 * n_t);
  g_tdb_poly[0] = poly3[0];
  g_tdb_poly[1] = poly3[1];
  g_tdb_poly[2] = poly3[2];
  g_tdb_n_t_published = n_t_published;
  g_tdb_t_clamp_lo = t_clamp_lo;
  g_tdb_t_clamp_hi = t_clamp_hi;
}

void pt_tdb_minus_tt(std::int64_t n, const std::int64_t* tt_day,
                     const double* tt_sec, double* out) {
  const std::size_t n0 = g_tdb_terms.size() / 3;
  const std::size_t n1 = g_tdb_t_terms.size() / 3;
  const std::size_t npub = static_cast<std::size_t>(g_tdb_n_t_published);
  for (std::int64_t i = 0; i < n; ++i) {
    const double T = jc_from_epoch(tt_day[i], tt_sec[i]);
    const double Tc =
        std::min(std::max(T, g_tdb_t_clamp_lo), g_tdb_t_clamp_hi);
    double s = g_tdb_poly[0] + g_tdb_poly[1] * Tc + g_tdb_poly[2] * Tc * Tc;
    for (std::size_t j = 0; j < n0; ++j) {
      const double* t = g_tdb_terms.data() + 3 * j;
      s += t[0] * std::sin(t[1] * T + t[2]);
    }
    for (std::size_t j = 0; j < n1; ++j) {
      const double* t = g_tdb_t_terms.data() + 3 * j;
      s += t[0] * (j < npub ? T : Tc) * std::sin(t[1] * T + t[2]);
    }
    out[i] = s;
  }
}

// Observatory ITRF -> GCRS position [m] and velocity [m/s].
// Epoch conversions (UTC->TT, UT1) and EOP lookups stay in Python so
// leap-second policy lives in exactly one place.
void pt_itrf_to_gcrs(std::int64_t n, const std::int64_t* tt_day,
                     const double* tt_sec, const std::int64_t* ut1_day,
                     const double* ut1_sec, const double* xp, const double* yp,
                     const double* itrf, double* out_pos, double* out_vel) {
  const Mat3 B = bias_matrix();
  for (std::int64_t i = 0; i < n; ++i) {
    const double T = jc_from_epoch(tt_day[i], tt_sec[i]);
    double dpsi, deps;
    nutation(T, &dpsi, &deps);
    // GAST = ERA + GMST-poly + equation of equinoxes (main term)
    const double poly = (0.014506 + 4612.156534 * T + 1.3915817 * T * T -
                         0.00000044 * T * T * T) *
                        ARCSEC_TO_RAD;
    const double ee = dpsi * std::cos(mean_obliquity(T));
    const double theta =
        std::fmod(era(ut1_day[i], ut1_sec[i]) + poly + ee, TWO_PI);
    const Mat3 W = matmul(ry(xp[i]), rx(yp[i]));
    Mat3 c2t = matmul(
        W, matmul(rz(theta), matmul(nutation_matrix(T, dpsi, deps),
                                    matmul(precession_matrix(T), B))));
    // transpose -> ITRF->GCRS; pos = M r
    double p[3];
    for (int r = 0; r < 3; ++r) {
      p[r] = c2t.m[0][r] * itrf[0] + c2t.m[1][r] * itrf[1] +
             c2t.m[2][r] * itrf[2];
    }
    out_pos[3 * i + 0] = p[0];
    out_pos[3 * i + 1] = p[1];
    out_pos[3 * i + 2] = p[2];
    // vel = omega x pos (PN-rate terms ~1e5 x smaller)
    out_vel[3 * i + 0] = -OMEGA_EARTH * p[1];
    out_vel[3 * i + 1] = OMEGA_EARTH * p[0];
    out_vel[3 * i + 2] = 0.0;
  }
}

// SPK type 2/3 Chebyshev evaluation over gathered records.
// rec: (n, rsize) rows [mid, radius, coeffs...]; matches spk.py::posvel.
void pt_cheby_posvel(std::int64_t n, std::int64_t ncoef,
                     std::int64_t data_type, std::int64_t rsize,
                     const double* et, const double* rec, double* out_pos,
                     double* out_vel) {
  // stack buffers: DE kernels use <= 18 coefficients
  double Tp[32], dTp[32];
  for (std::int64_t i = 0; i < n; ++i) {
    const double* r = rec + i * rsize;
    const double mid = r[0], radius = r[1];
    const double s = (et[i] - mid) / radius;
    Tp[0] = 1.0;
    dTp[0] = 0.0;
    if (ncoef > 1) {
      Tp[1] = s;
      dTp[1] = 1.0;
    }
    for (std::int64_t k = 2; k < ncoef; ++k) {
      Tp[k] = 2.0 * s * Tp[k - 1] - Tp[k - 2];
      dTp[k] = 2.0 * Tp[k - 1] + 2.0 * s * dTp[k - 1] - dTp[k - 2];
    }
    for (int axis = 0; axis < 3; ++axis) {
      const double* c = r + 2 + axis * ncoef;
      double pos = 0.0, vel = 0.0;
      for (std::int64_t k = 0; k < ncoef; ++k) {
        pos += c[k] * Tp[k];
        vel += c[k] * dTp[k];
      }
      out_pos[3 * i + axis] = pos;
      out_vel[3 * i + axis] = vel / radius;
    }
    if (data_type == 3) {
      for (int axis = 0; axis < 3; ++axis) {
        const double* c = r + 2 + (3 + axis) * ncoef;
        double vel = 0.0;
        for (std::int64_t k = 0; k < ncoef; ++k) vel += c[k] * Tp[k];
        out_vel[3 * i + axis] = vel;
      }
    }
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// FORMAT-1 (tempo2) tim-file parser — the native data loader.
//
// The reference's tim parsing is a pure-Python per-line loop flagged as
// a hot spot for large files (reference: src/pint/toa.py::read_toa_file;
// PINT mitigates it with a pickle cache). Here the fast path lives in
// C++: one pass over the raw buffer producing column arrays plus a
// packed flags blob, mirroring pint_tpu/toa.py::_parse_tempo2_line and
// pint_tpu/mjd.py::parse_mjd_string semantics exactly. Any construct
// that needs stateful Python handling (INCLUDE recursion, TIME/EFAC/
// EQUAD/EMIN/EMAX/SKIP/JUMP/PHASE, princeton/parkes lines before a
// FORMAT 1) returns -1 so the caller falls back to the Python parser.
// ---------------------------------------------------------------------------

extern "C" {

namespace {
struct TimTok {
  const char* p;
  int len;
};

// python str.split() whitespace, full ASCII set: space, \t, \v, \f,
// \x1c-\x1f (file/group/record/unit separators are isspace() in
// python). \r and \n are line terminators, never intra-line here.
inline bool tim_space(char c) {
  return c == ' ' || c == '\t' || c == '\v' || c == '\f' ||
         (c >= '\x1c' && c <= '\x1f');
}

inline bool tok_is_ci(const TimTok& t, const char* kw) {
  int i = 0;
  for (; kw[i]; ++i) {
    if (i >= t.len) return false;
    char c = t.p[i];
    if (c >= 'a' && c <= 'z') c -= 32;
    if (c != kw[i]) return false;
  }
  return i == t.len;
}

// full-token float parse mirroring python float() (toa.py::_is_number):
// underscores allowed only between digits (stripped), hex floats
// rejected; otherwise strtod semantics match python's float grammar.
inline bool tok_float(const TimTok& t, double* out) {
  char tmp[64];
  int m = 0;
  if (t.len <= 0 || t.len >= 64) return false;
  for (int i = 0; i < t.len; ++i) {
    char c = t.p[i];
    if (c == '_') {
      if (i == 0 || i == t.len - 1) return false;
      char a = t.p[i - 1], b = t.p[i + 1];
      if (a < '0' || a > '9' || b < '0' || b > '9') return false;
      continue;  // python float() strips digit-adjacent underscores
    }
    if (c == '(') return false;  // strtod's nan(seq); python rejects
    tmp[m++] = c;
  }
  tmp[m] = 0;
  int s = (m > 0 && (tmp[0] == '+' || tmp[0] == '-')) ? 1 : 0;
  if (s + 1 < m && tmp[s] == '0' && (tmp[s + 1] == 'x' || tmp[s + 1] == 'X'))
    return false;  // python float() has no hex literals
  char* end = nullptr;
  double v = strtod(tmp, &end);
  if (end != tmp + m) return false;
  *out = v;
  return true;
}

// exact decimal MJD -> (int day, f64 seconds-of-day); mirrors
// mjd.py::parse_mjd_string (long double = x86 80-bit, same as numpy
// longdouble, so results are bit-identical for <= 19 frac digits).
inline bool tok_mjd(const TimTok& t, std::int64_t* day, double* sec) {
  int i = 0;
  bool neg = false;
  if (i < t.len && (t.p[i] == '+' || t.p[i] == '-')) {
    neg = t.p[i] == '-';
    ++i;
  }
  if (i >= t.len || t.p[i] < '0' || t.p[i] > '9') return false;
  std::int64_t ipart = 0;
  for (; i < t.len && t.p[i] >= '0' && t.p[i] <= '9'; ++i)
    ipart = ipart * 10 + (t.p[i] - '0');
  long double fsec = 0.0L;
  if (i < t.len && t.p[i] == '.') {
    ++i;
    if (i >= t.len) return false;  // regex requires >=1 frac digit
    long double fi = 0.0L;
    int nd = 0;
    for (; i < t.len && t.p[i] >= '0' && t.p[i] <= '9'; ++i) {
      fi = fi * 10.0L + (t.p[i] - '0');
      ++nd;
    }
    long double p10 = 1.0L;
    for (int k = 0; k < nd; ++k) p10 *= 10.0L;
    fsec = fi * 86400.0L / p10;
  }
  if (i != t.len) return false;
  std::int64_t d = neg ? -ipart : ipart;
  double s = static_cast<double>(fsec);
  if (neg && s > 0.0) {  // "-1.5" -> (-2, 43200): frac counts away from 0
    d -= 1;
    s = 86400.0 - s;
  }
  *day = d;
  *sec = s;
  return true;
}
}  // namespace

// Returns n_toas (>=0) on success; -1 = caller must use the Python
// parser (stateful command / non-FORMAT-1 line); -2 = a capacity was
// exceeded (caller falls back).  flags blob layout per TOA:
// "key\x1Fvalue\x1Ekey\x1Fvalue..." with flag_off[i]..flag_off[i+1]
// delimiting TOA i (flag_off has n+1 entries).
std::int64_t pt_parse_tim_t2(
    const char* buf, std::int64_t nbytes, std::int64_t* day, double* sec,
    double* freq, double* err, std::int32_t* obs_id, char* obs_tab,
    std::int64_t obs_cap, std::int64_t* obs_tab_len, char* flags,
    std::int64_t flags_cap, std::int64_t* flag_off, std::int64_t* n_bad) {
  constexpr int MAXTOK = 96;
  TimTok tok[MAXTOK];
  // small obs string table (unique sites in one tim file are few)
  constexpr int MAXOBS = 128;
  int obs_start[MAXOBS], obs_len[MAXOBS];
  int n_obs = 0;
  std::int64_t obs_used = 0;
  std::int64_t n = 0, bad = 0, fpos = 0;
  bool format1 = false;
  const char* end = buf + nbytes;
  const char* line = buf;
  while (line < end) {
    // universal-newline line split, matching python text mode:
    // \n, \r\n, and bare \r all terminate a line
    const char* eol = line;
    bool high_byte = false;
    while (eol < end && *eol != '\n' && *eol != '\r') {
      if (static_cast<unsigned char>(*eol) >= 0x80) high_byte = true;
      ++eol;
    }
    const char* next_line = eol + 1;
    if (eol < end && *eol == '\r' && eol + 1 < end && eol[1] == '\n')
      next_line = eol + 2;
    // tokenize
    int ntok = 0;
    const char* p = line;
    while (p < eol && ntok < MAXTOK) {
      while (p < eol && tim_space(*p)) ++p;
      if (p >= eol) break;
      const char* q = p;
      while (q < eol && !tim_space(*q)) ++q;
      tok[ntok].p = p;
      tok[ntok].len = static_cast<int>(q - p);
      ++ntok;
      p = q;
    }
    if (p < eol && ntok >= MAXTOK) return -1;  // pathological line: python owns it
    line = next_line;
    if (ntok == 0) continue;
    // comments: '#', or 'C '/'c ' (needs a second token to mirror
    // python's startswith("C ") on the stripped line). Checked BEFORE
    // the non-ASCII bailout so a unicode comment doesn't forfeit the
    // fast path for the whole file.
    if (tok[0].p[0] == '#') continue;
    if (tok[0].len == 1 && (tok[0].p[0] == 'C' || tok[0].p[0] == 'c') &&
        ntok > 1)
      continue;
    // non-ASCII on a DATA line: python owns the file — str.split()
    // honors unicode whitespace and float() honors unicode digits,
    // neither of which this parser mirrors (detected during the
    // newline scan above, no extra pass)
    if (high_byte) return -1;
    // command dispatch (python: head in _COMMANDS)
    if (tok_is_ci(tok[0], "FORMAT")) {
      if (ntok > 1 && tok[1].len == 1 && tok[1].p[0] == '1') format1 = true;
      continue;
    }
    if (tok_is_ci(tok[0], "MODE") || tok_is_ci(tok[0], "INFO") ||
        tok_is_ci(tok[0], "TRACK"))
      continue;
    if (tok_is_ci(tok[0], "END")) break;
    if (tok_is_ci(tok[0], "INCLUDE") || tok_is_ci(tok[0], "TIME") ||
        tok_is_ci(tok[0], "EFAC") || tok_is_ci(tok[0], "EQUAD") ||
        tok_is_ci(tok[0], "EMIN") || tok_is_ci(tok[0], "EMAX") ||
        tok_is_ci(tok[0], "SKIP") || tok_is_ci(tok[0], "NOSKIP") ||
        tok_is_ci(tok[0], "JUMP") || tok_is_ci(tok[0], "PHASE"))
      return -1;  // stateful: python parser owns these
    // TOA line
    if (!format1) return -1;  // princeton/parkes territory
    if (ntok < 5) {
      ++bad;
      continue;
    }
    double f, e;
    std::int64_t d;
    double s;
    if (!tok_float(tok[1], &f) || !tok_mjd(tok[2], &d, &s) ||
        !tok_float(tok[3], &e)) {
      ++bad;
      continue;
    }
    day[n] = d;
    sec[n] = s;
    freq[n] = f;
    err[n] = e;
    // observatory: lowercase, uniquified into obs_tab
    char site[64];
    if (tok[4].len > 63) return -1;  // absurd site name: python owns it
    int slen = tok[4].len;
    for (int i = 0; i < slen; ++i) {
      char c = tok[4].p[i];
      if (c >= 'A' && c <= 'Z') c += 32;
      site[i] = c;
    }
    int oid = -1;
    for (int i = 0; i < n_obs; ++i) {
      if (obs_len[i] == slen) {
        bool eq = true;
        for (int k = 0; k < slen; ++k)
          if (obs_tab[obs_start[i] + k] != site[k]) {
            eq = false;
            break;
          }
        if (eq) {
          oid = i;
          break;
        }
      }
    }
    if (oid < 0) {
      if (n_obs >= MAXOBS || obs_used + slen + 1 > obs_cap) return -2;
      obs_start[n_obs] = static_cast<int>(obs_used);
      obs_len[n_obs] = slen;
      for (int k = 0; k < slen; ++k) obs_tab[obs_used + k] = site[k];
      obs_tab[obs_used + slen] = '\n';
      obs_used += slen + 1;
      oid = n_obs++;
    }
    obs_id[n] = oid;
    // flags (python: _parse_tempo2_line flag loop + setdefault("name"))
    flag_off[n] = fpos;
    bool have_name = false, first_pair = true;
    int i = 5;
    while (i < ntok) {
      double dummy;
      bool is_flag = tok[i].len > 1 && tok[i].p[0] == '-' &&
                     !tok_float(tok[i], &dummy);
      if (tok[i].len == 1 && tok[i].p[0] == '-') is_flag = true;
      if (!is_flag) {
        ++i;
        continue;
      }
      const char* key = tok[i].p + 1;
      int klen = tok[i].len - 1;
      const char* val = nullptr;
      int vlen = 0;
      if (i + 1 < ntok) {
        bool next_is_flag = tok[i + 1].len >= 1 && tok[i + 1].p[0] == '-' &&
                            !tok_float(tok[i + 1], &dummy);
        if (!next_is_flag) {
          val = tok[i + 1].p;
          vlen = tok[i + 1].len;
          i += 2;
        } else {
          ++i;
        }
      } else {
        ++i;
      }
      if (klen == 4 && key[0] == 'n' && key[1] == 'a' && key[2] == 'm' &&
          key[3] == 'e')
        have_name = true;
      if (fpos + klen + vlen + 2 > flags_cap) return -2;
      if (!first_pair) flags[fpos++] = '\x1e';
      first_pair = false;
      for (int k = 0; k < klen; ++k) flags[fpos++] = key[k];
      flags[fpos++] = '\x1f';
      for (int k = 0; k < vlen; ++k) flags[fpos++] = val[k];
    }
    if (!have_name) {  // python: flags.setdefault("name", parts[0])
      if (fpos + tok[0].len + 7 > flags_cap) return -2;
      if (!first_pair) flags[fpos++] = '\x1e';
      const char nm[] = "name";
      for (int k = 0; k < 4; ++k) flags[fpos++] = nm[k];
      flags[fpos++] = '\x1f';
      for (int k = 0; k < tok[0].len; ++k) flags[fpos++] = tok[0].p[k];
    }
    ++n;
  }
  flag_off[n] = fpos;
  *obs_tab_len = obs_used;
  *n_bad = bad;
  return n;
}

}  // extern "C"
