#!/bin/bash
# TPU relay watcher: probe the axon relay with a short-lived subprocess;
# the moment a window opens, run the full bench on the real chip and
# save a session artifact the driver's BENCH_r{N} run can corroborate.
#
# The relay is single-tenant and wedges for minutes-hours after a failed
# claim (BASELINE.md r2-r4 history), so: probe with timeout, never two
# concurrent claimants, and grab the first working window greedily.
#
# Usage: bash benchmarks/tpu_watcher.sh [out_prefix]   (default r05_session)
cd "$(dirname "$0")/.." || exit 1
PREFIX="${1:-r05_session}"
PROBE_INTERVAL="${PROBE_INTERVAL:-600}"
echo "[watcher] start $(date -u +%H:%M:%S) prefix=$PREFIX"
while true; do
  if [ -f "benchmarks/${PREFIX}_bench.json" ]; then
    echo "[watcher] artifact exists; exiting"; exit 0
  fi
  t0=$(date +%s)
  timeout 150 python -c "
import time, jax, jax.numpy as jnp
t0=time.time(); ds=jax.devices()
assert any(d.platform!='cpu' for d in ds), f'cpu only: {ds}'
x=jnp.ones((512,512), jnp.bfloat16)
(x@x).block_until_ready()
print('probe ok', ds[0].platform, round(time.time()-t0,1),'s', flush=True)
" >"/tmp/tpu_probe_last.log" 2>&1
  rc=$?
  if [ $rc -eq 0 ]; then
    echo "[watcher] $(date -u +%H:%M:%S) window OPEN — running bench"
    timeout 3000 python bench.py \
      > "benchmarks/${PREFIX}_bench.json" \
      2> "benchmarks/${PREFIX}_bench.log"
    brc=$?
    echo "[watcher] bench rc=$brc"
    if [ $brc -eq 0 ] && grep -q '"platform": "tpu"' "benchmarks/${PREFIX}_bench.json"; then
      echo "[watcher] TPU bench captured; exiting"; exit 0
    fi
    # failed mid-window OR fell back to a non-TPU backend (relay died
    # between probe and bench): keep the log, clear the json so the
    # existence check cannot mistake it for success, retry later
    mv -f "benchmarks/${PREFIX}_bench.json" \
      "benchmarks/${PREFIX}_bench.failed.$(date +%s).json" 2>/dev/null
  else
    echo "[watcher] $(date -u +%H:%M:%S) relay wedged (probe rc=$rc)"
  fi
  el=$(( $(date +%s) - t0 ))
  sleep_s=$(( PROBE_INTERVAL - el )); [ $sleep_s -lt 30 ] && sleep_s=30
  sleep $sleep_s
done
