"""Profiling/tracing harness (reference analog: SURVEY.md section 5 —
the reference has no built-in profiling; devs use cProfile. The TPU
build's equivalent is jax.profiler traces + block_until_ready timing).

Usage:
    python benchmarks/profile_harness.py --workload wls --n-toas 5000
    python benchmarks/profile_harness.py --workload pta --trace /tmp/tr

With --trace, a TensorBoard-loadable XLA trace is written for the
timed region. Reports compile time separately from steady-state step
time, and asserts no retracing between iterations (SURVEY.md section 5
"race detection" analog: jit cache-miss guard).
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, ".")

import numpy as np

from pint_tpu.obs import clock as obs_clock


def _wls_workload(n_toas):
    import warnings

    warnings.simplefilter("ignore")
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR PROF1\nRAJ 11:00:00\nDECJ 11:00:00\nF0 333.1 1\nF1 -5e-16 1\n"
           "PEPOCH 55500\nDM 17.0 1\n")
    m = get_model(par)
    rng = np.random.default_rng(0)
    mjds = np.sort(rng.uniform(54500, 56500, n_toas))
    t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0, freq_mhz=1400.0,
                                obs="gbt", add_noise=True, seed=1,
                                iterations=0)
    prepared = m.prepare(t)
    resid_fn = prepared.residual_vector_fn()
    x = prepared.vector_from_params()
    return lambda: resid_fn(x)


def _pta_workload(n_psr, n_toas):
    from bench import build_batch
    from pint_tpu.parallel import PTABatch

    models, toas_list = build_batch(n_psr, n_toas)
    pta = PTABatch(models, toas_list)
    return lambda: pta.wls_fit(maxiter=3)[1]


def _serve_workload(n_requests, hit_threshold):
    """Mixed-shape request stream through pint_tpu.serve: asserts the
    zero-retrace property (no executable compiles after warmup, cache
    hit rate >= threshold) that the serving layer exists to provide.
    Returns the report dict; raises AssertionError on a retrace."""
    import warnings

    warnings.simplefilter("ignore")
    from pint_tpu.scripts.pint_serve_bench import run_serve_stream

    report = run_serve_stream(n_requests=n_requests, max_batch=4,
                              bucket_floor=32, sizes=(24, 48, 90),
                              per_combo=2, compare_offline=False)
    assert report["recompiles_after_warmup"] == 0, \
        f"serve stream retraced: {report['recompiles_after_warmup']} " \
        f"executables compiled after warmup"
    hit_rate = report["cache"]["hit_rate"] or 0.0
    assert hit_rate >= hit_threshold, \
        f"cache hit rate {hit_rate:.3f} < threshold {hit_threshold}"
    return report


def _chaos_workload(n_requests, fault_rate):
    """Chaos slice of the serve stream: low-rate toa_nan injection vs
    a fault-free reference. Asserts the resilience contract — zero
    healthy-request failures, healthy end state, zero unexpected
    recompiles."""
    import warnings

    warnings.simplefilter("ignore")
    from pint_tpu.scripts.pint_serve_bench import run_chaos_stream

    report = run_chaos_stream(n_requests=n_requests,
                              fault_rate=fault_rate, max_batch=4,
                              bucket_floor=32, sizes=(24, 48, 90),
                              per_combo=2)
    assert report["ok"], \
        f"chaos contract violated: " \
        f"healthy_failures={report['healthy_failures']}, " \
        f"health={report['health_state']}, " \
        f"unexpected_recompiles={report['unexpected_recompiles']}"
    return report


def _fleet_pipeline_workload(n_psr, n_toas):
    """Pipelined fleet executor on a mixed-structure fleet (wls + gls
    buckets, two TOA widths): concurrent AOT compile vs the
    serial-equivalent sum, then pipelined fit vs sequential. Asserts
    the bitwise-equivalence contract the pipeline guarantees. Returns
    the fleet_pipeline_metrics dict."""
    import warnings

    warnings.simplefilter("ignore")
    from pint_tpu.parallel import PTAFleet, fleet_pipeline_metrics
    from pint_tpu.scripts.pint_serve_bench import build_serve_fleet

    models, toas_list = build_serve_fleet(
        sizes=(max(16, n_toas // 2), n_toas), per_combo=max(1, n_psr // 4),
        seed=3)
    fleet = PTAFleet(models, toas_list, toa_bucket="pow2",
                     bucket_floor=16, pipeline=True)
    report = fleet_pipeline_metrics(fleet, method="auto", maxiter=3)
    assert report["fleet_pipeline_bitwise"], \
        "pipelined fleet fit diverged bitwise from the sequential path"
    for key in ("fleet_compile_serial_s", "fleet_compile_concurrent_s",
                "fleet_fit_sequential_s", "fleet_fit_pipelined_s",
                "fleet_pipeline_overlap_pct"):
        v = report[key]
        assert v is not None and np.isfinite(v), \
            f"fleet pipeline metric {key} is not finite: {v!r}"
    return report


def _shapeplan_workload(n_psr, n_toas):
    """Planned (segment-packed) fleet vs the pow2 ladder on a ragged
    noise fleet: reports padding ratios, compiled-program counts, and
    warm GLS refit walls for both layouts, and asserts the packed
    params match the per-lane pow2 fit to <= 1e-13 relative."""
    import warnings

    warnings.simplefilter("ignore")
    from pint_tpu.models import get_model
    from pint_tpu.parallel import PTAFleet
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    rng = np.random.default_rng(11)
    counts = np.linspace(max(16, n_toas // 6), n_toas, n_psr).astype(int)
    models, toas_list = [], []
    for i, n in enumerate(counts):
        par = (f"PSR SP{i}\nRAJ 10:{i % 60:02d}:00.0\nDECJ 5:00:00.0\n"
               f"F0 {200 + i}.5 1\nF1 -3e-16 1\nPEPOCH 55500\n"
               f"DM {10 + i % 7}.2 1\n"
               "EFAC -f L-wide 1.1\nEQUAD -f L-wide 0.4\n"
               "ECORR -f L-wide 0.8\n"
               "RNAMP 1e-14\nRNIDX -3.1\nTNREDC 8\n")
        m = get_model(par)
        n_ep = max(1, int(n) // 4)
        days = np.sort(rng.uniform(54200, 56800, n_ep))
        mjds = np.concatenate(
            [d + np.arange(4) * 0.5 / 86400.0 for d in days])[:int(n)]
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0,
                                    freq_mhz=1400.0, obs="gbt",
                                    add_noise=False, iterations=0)
        for f in t.flags:
            f["f"] = "L-wide"
        models.append(m)
        toas_list.append(t)

    report = {}
    fits = {}
    for mode, kw in (("plan", {"toa_bucket": "plan", "plan_quantum": 32,
                               "plan_max_pack": 4,
                               "plan_compile_budget": 2,
                               "plan_min_width": 64}),
                     ("pow2", {"toa_bucket": "pow2",
                               "bucket_floor": 64})):
        fleet = PTAFleet(models, toas_list, **kw)
        t0 = obs_clock.now()
        xs, chi2, _ = fleet.fit(method="gls", maxiter=2)
        cold_s = obs_clock.now() - t0
        t0 = obs_clock.now()
        xs, chi2, _ = fleet.fit(method="gls", maxiter=2)
        refit_s = obs_clock.now() - t0
        fits[mode] = [np.asarray(x) for x in xs]
        report.update({
            f"{mode}_padding_ratio": round(fleet.padding_ratio, 4),
            f"{mode}_n_programs": len(fleet.batches),
            f"{mode}_cold_fit_s": round(cold_s, 3),
            f"{mode}_refit_s": round(refit_s, 4),
        })
    maxrel = max(
        float(np.max(np.abs(a - b) / np.maximum(np.abs(b), 1e-300)))
        for a, b in zip(fits["plan"], fits["pow2"]))
    report["max_param_rel_plan_vs_pow2"] = maxrel
    assert maxrel <= 1e-13, \
        f"packed fit diverged from the per-lane pow2 fit: {maxrel:.3e}"
    assert report["plan_padding_ratio"] <= report["pow2_padding_ratio"], \
        "the planner padded worse than the pow2 ladder it replaces"
    return report


def _fusedgls_workload(n_psr, n_toas, iters):
    """Fused packed-GLS pipeline (whiten -> Gram -> RHS in one
    streamed pass) vs the classic packed path on a plan-packed fleet:
    warm refit walls for both, fused-vs-classic speedup,
    executable-level MFU attribution for the fused path, and the
    parity contract — fused params match the classic packed path to
    <= 1e-15 relative per lane."""
    import warnings

    warnings.simplefilter("ignore")
    import jax

    from bench import build_batch
    from pint_tpu.obs import costmodel
    from pint_tpu.parallel import PTAFleet

    models, toas_list = build_batch(n_psr, n_toas)
    fleet = PTAFleet(models, toas_list, toa_bucket="plan",
                     plan_quantum=32, plan_max_pack=8,
                     plan_compile_budget=2, plan_min_width=128)
    infos = [b.aot_compile(method="gls", maxiter=3)
             for b in fleet.batches.values()]
    flops = sum(i.get("flops") or 0 for i in infos) or None

    def _timed(**kw):
        fleet.fit(method="gls", maxiter=3, **kw)  # compile + warm
        best, xs_best = float("inf"), None
        for _ in range(max(1, iters)):
            t0 = obs_clock.now()
            xs, _, _ = fleet.fit(method="gls", maxiter=3, **kw)
            dt = obs_clock.now() - t0
            if dt < best:
                best, xs_best = dt, xs
        return best, [np.asarray(x) for x in xs_best]

    fused_s, xs_fused = _timed()
    classic_s, xs_classic = _timed(fused=False)
    maxrel = max(
        float(np.max(np.abs(a - b) / np.maximum(np.abs(b), 1e-300)))
        for a, b in zip(xs_fused, xs_classic))
    assert maxrel <= 1e-15, \
        f"fused packed GLS diverged from the classic path: {maxrel:.3e}"
    report = {
        "fused_refit_s": round(fused_s, 4),
        "classic_refit_s": round(classic_s, 4),
        "fused_vs_classic_speedup": round(classic_s / fused_s, 3),
        "fused_padding_ratio": round(fleet.padding_ratio, 4),
        "n_programs": len(fleet.batches),
        "max_param_rel_fused_vs_classic": maxrel,
    }
    report.update(costmodel.attribute(flops, None, wall_s=fused_s,
                                      platform=jax.default_backend()))
    return report


def _fitq_workload(n_psr, n_toas, iters):
    """Numerics-observatory slice: a warm fleet refit with fit-quality
    probes off and on. Asserts the observatory contract — the probed
    refit is BITWISE identical to the unprobed one and the ledger's
    self-timed probe wall stays under 1% of the warm refit wall —
    and reports the ledger snapshot (chi2 z-scores, condition
    numbers, fallback/divergence counters)."""
    import warnings

    warnings.simplefilter("ignore")
    from pint_tpu.obs import fitquality
    from pint_tpu.parallel import PTAFleet
    from pint_tpu.scripts.pint_serve_bench import build_serve_fleet

    models, toas_list = build_serve_fleet(
        sizes=(max(16, n_toas),), per_combo=max(1, n_psr // 3), seed=5)
    fleet = PTAFleet(models, toas_list, toa_bucket="pow2",
                     bucket_floor=64, pipeline=True)
    fleet.fit(method="auto", maxiter=3)  # compile + warm
    off_s = float("inf")
    for _ in range(max(1, iters)):
        t0 = obs_clock.now()
        xs_off, _, _ = fleet.fit(method="auto", maxiter=3)
        off_s = min(off_s, obs_clock.now() - t0)
    fitquality.reset()
    fitquality.enable()
    try:
        on_s = float("inf")
        for _ in range(max(1, iters)):
            t0 = obs_clock.now()
            xs_on, _, _ = fleet.fit(method="auto", maxiter=3)
            on_s = min(on_s, obs_clock.now() - t0)
        snap = fitquality.FITQ.snapshot()
    finally:
        fitquality.disable()
    assert all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(xs_off, xs_on)), \
        "fit-quality probes changed the fit (bitwise contract broken)"
    # cumulative probe wall over `iters` probed refits vs `iters`
    # unprobed walls: the <1% contract on the warm path. Probe cost
    # scales with pulsar count while the fit wall scales with TOAs,
    # so the ratio only means anything on a non-toy refit — below
    # 50 ms of fit the percentage is measuring the fleet's smallness,
    # not the probes (the contract pin at realistic scale lives in
    # tests/test_fitquality.py)
    probe_pct = 100.0 * snap["probe_wall_s"] / (off_s * max(1, iters))
    if off_s >= 0.05:
        assert probe_pct < 1.0, \
            f"probe wall {probe_pct:.3f}% of warm refit exceeds " \
            "the 1% budget"
    counters = snap["counters"]
    return {
        "fitq_overhead_pct": round(100.0 * (on_s - off_s) / off_s, 2),
        "fitq_probe_wall_s": round(snap["probe_wall_s"], 5),
        "fitq_probe_pct_of_refit": round(probe_pct, 4),
        "fitq_fits": counters["fits"],
        "fitq_fallbacks": counters["fallbacks"],
        "fitq_diverged": counters["diverged"],
        "fitq_max_abs_chi2_z": snap["max_abs_chi2_z"],
        "fitq_max_condition": snap["max_condition"],
        "fitq_n_pulsars": snap["n_pulsars"],
    }


def _store_workload(n_psr, n_toas):
    """Packed-TOA columnar store (pint_tpu/store) on a ragged fleet:
    cold build (live prep + CRC-framed write-back) vs warm bring-up
    (mmap + verify + from_packed, no astropy), with fit parity
    asserted bit-identical and the store counters reported. The
    670k-scale version runs as bench.py's store sub-stage
    (measured_670k_store_* keys)."""
    import copy
    import tempfile
    import warnings

    warnings.simplefilter("ignore")
    from pint_tpu.parallel import PTAFleet
    from pint_tpu.scripts.pint_serve_bench import build_serve_fleet
    from pint_tpu.store import PackStore

    models, toas_list = build_serve_fleet(
        sizes=(max(16, n_toas // 2), n_toas),
        per_combo=max(1, n_psr // 4), seed=3)

    def _fit(store=None):
        t0 = obs_clock.now()
        fleet = PTAFleet([copy.deepcopy(m) for m in models], toas_list,
                         toa_bucket="pow2", bucket_floor=16,
                         store=store)
        build_s = obs_clock.now() - t0
        x, chi2, _ = fleet.fit(method="auto", maxiter=2)
        return build_s, [np.asarray(xi) for xi in x]

    sdir = tempfile.mkdtemp(prefix="pint_store_prof_")
    live_build_s, x_live = _fit(store=None)
    cold = PackStore(sdir)
    cold_build_s, x_cold = _fit(store=cold)
    warm = PackStore(sdir)
    warm.prewarm(background=False)
    warm_build_s, x_warm = _fit(store=warm)
    cc, wc = cold.counters(), warm.counters()
    assert wc["hits"] >= 1 and wc["misses"] == 0, \
        f"warm store run missed: {wc}"
    assert wc["corrupt"] == 0 and wc["stale"] == 0, \
        f"store flagged its own fresh entries: {wc}"
    parity = max(float(np.max(np.abs(a - b)))
                 for a, b in zip(x_warm, x_live))
    assert parity == 0.0, \
        f"store-hit fit diverged from live prep (max abs {parity})"
    return {
        "live_prep_pack_s": round(live_build_s, 4),
        "cold_store_prep_pack_s": round(cold_build_s, 4),
        "warm_store_prep_pack_s": round(warm_build_s, 4),
        "prep_speedup_warm_vs_live": round(
            live_build_s / max(warm_build_s, 1e-9), 3),
        "store_bytes": cc["bytes_written"],
        "cold_counters": cc,
        "warm_counters": wc,
        "parity_max_abs": parity,
    }


def _gw_workload(n_psr, n_toas, iters):
    """GW-detection slice (pint_tpu/gw): the Hellings–Downs optimal
    statistic on a seeded injected-GWB lattice plus a pair-sweep
    throughput probe. Asserts the detection contract — the recovered
    amplitude sits within a factor of two of the injection and the HD
    S/N beats both the monopole and dipole alternatives — and reports
    pair throughput with roofline attribution. n_psr is the lattice
    pulsar count; n_toas caps the epoch-cell count."""
    import warnings

    warnings.simplefilter("ignore")
    from pint_tpu import gw

    amp = 0.5
    n_cells = max(32, min(512, n_toas))
    pos = gw.hd.isotropic_positions(max(8, n_psr), seed=0)
    lat = gw.inject_gwb(pos, n_cells, amp, seed=0)
    os_hd = gw.optimal_statistic(lat)
    os_mono = gw.optimal_statistic(lat, orf="monopole")
    os_dip = gw.optimal_statistic(lat, orf="dipole")
    rec = float(np.sqrt(os_hd["amp2"])) if os_hd["amp2"] > 0 else 0.0
    assert 0.5 * amp < rec < 2.0 * amp, \
        f"OS recovered amplitude {rec:.3f} outside 2x of injected {amp}"
    assert os_hd["snr"] > abs(os_mono["snr"]) and \
        os_hd["snr"] > abs(os_dip["snr"]), \
        "HD correlation did not beat the monopole/dipole alternatives"
    sweep = None
    for _ in range(max(1, iters)):
        s = gw.correlation_sweep(lat.z, lat.w, lambda *a: None)
        if sweep is None or s["wall_s"] < sweep["wall_s"]:
            sweep = s
    return {
        "os_snr": round(os_hd["snr"], 3),
        "recovered_amplitude": round(rec, 4),
        "injected_amplitude": amp,
        "monopole_snr": round(os_mono["snr"], 3),
        "dipole_snr": round(os_dip["snr"], 3),
        "n_pairs": os_hd["n_pairs"],
        "n_cells": n_cells,
        "pairs_per_s": sweep["pairs_per_s"],
        "mfu_pct": sweep["mfu_pct"],
        "roofline_pct": sweep["roofline_pct"],
        "bound": sweep["bound"],
    }


def _incremental_workload(n_toas, iters):
    """Streaming-refit slice (kernels/incremental + serve append
    lanes) at profiling scale; the 670k-scale version runs as
    bench.py's incremental stage (incremental_* keys). Times a
    from-scratch Gram rebuild vs a rank-r append+solve on the same
    synthetic normal system, asserts the floored-relative parity
    budget, then drives a real served lane through the journaled
    append_toas path and reports its latency split."""
    import tempfile
    import warnings

    warnings.simplefilter("ignore")
    import jax

    from pint_tpu.kernels import incremental as inc
    from pint_tpu.models import get_model
    from pint_tpu.serve import AppendToasRequest, ServeEngine
    from pint_tpu.serve.metrics import percentile
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    rng = np.random.default_rng(42)
    n_base, n_app, k = max(1024, n_toas * 8), 64, 10
    Xb = rng.standard_normal((n_base, k))
    rb = rng.standard_normal(n_base) * 1e-6
    wb = rng.uniform(0.5, 2.0, n_base) * 1e6
    Xa = rng.standard_normal((n_app, k))
    ra = rng.standard_normal(n_app) * 1e-6
    wa = rng.uniform(0.5, 2.0, n_app) * 1e6
    q = np.full(k, 1e-6)
    chunks = [(Xb, rb, wb), (Xa, ra, wa)]
    base = inc.build_normal(Xb, rb, wb, q=q)  # warms the jits

    scratch_s = inc_s = None
    dx_sc = dx_in = None
    for _ in range(max(1, iters)):
        t0 = obs_clock.now()
        dx_sc, _c2, _st, _i = inc.scratch_refit(chunks, q=q)
        jax.block_until_ready(dx_sc)
        dt = obs_clock.now() - t0
        scratch_s = dt if scratch_s is None else min(scratch_s, dt)
        st = inc.IncrementalNormal(base.A0, base.b, base.rNr, q=base.q)
        t0 = obs_clock.now()
        st.append(Xa, ra, wa)
        dx_in, _c2, _i = st.solve()
        jax.block_until_ready(dx_in)
        dt = obs_clock.now() - t0
        inc_s = dt if inc_s is None else min(inc_s, dt)
    dx_sc, dx_in = np.asarray(dx_sc), np.asarray(dx_in)
    den = np.maximum(np.abs(dx_sc),
                     np.finfo(np.float64).eps
                     * max(float(np.max(np.abs(dx_sc))), 1e-300))
    parity = float(np.max(np.abs(dx_in - dx_sc) / den))
    assert parity <= 1e-12, \
        f"incremental append diverged from the scratch refit: " \
        f"{parity:.3e}"
    # the >=10x acceptance lives at 670k scale in bench.py; at
    # profiling scale the scratch rebuild is small enough that the
    # append only has to not LOSE to it
    assert inc_s < scratch_s, \
        f"append+solve ({inc_s:.4f}s) slower than the scratch " \
        f"rebuild ({scratch_s:.4f}s)"

    par = ("PSR PROFI0\nRAJ 12:00:00.0\nDECJ 10:00:00.0\n"
           "F0 311.25 1\nF1 -4e-16 1\nPEPOCH 55500\nDM 12.5 1\n")
    m = get_model(par)
    t = make_fake_toas_fromMJDs(
        np.sort(rng.uniform(54500, 56500, 64)), m, error_us=1.0,
        freq_mhz=1400.0, obs="gbt", add_noise=True, seed=7)
    lat = []
    with tempfile.TemporaryDirectory() as d:
        eng = ServeEngine(durable_dir=d)
        eng.register_append_lane(m, t)
        lo = 56500.0
        for i in range(16):
            mj = np.sort(rng.uniform(lo, lo + 5.0, 8))
            lo += 5.0
            ta = make_fake_toas_fromMJDs(
                mj, m, error_us=1.0, freq_mhz=1400.0, obs="gbt",
                add_noise=True, seed=100 + i)
            t0 = obs_clock.now()
            res = eng.submit(AppendToasRequest(m, ta))
            lat.append(obs_clock.now() - t0)
            assert res.status == "ok", \
                f"served append {i} failed: {res.status}/{res.reason}"
        counters = eng.streaming.counters()
        eng.journal.close()
    lat = lat[2:]  # drop the lane's cold appends
    return {
        "scratch_refit_s": round(scratch_s, 5),
        "append_solve_s": round(inc_s, 5),
        "append_vs_refit_speedup": round(scratch_s / inc_s, 2),
        "parity_max_rel": parity,
        "n_base_rows": n_base,
        "n_appended_rows": n_app,
        "serve_append_p50_s": round(percentile(lat, 50.0), 5),
        "serve_append_p99_s": round(percentile(lat, 99.0), 5),
        "streaming_counters": counters,
    }


def _roofline_workload(n_psr, n_toas, iters):
    """One GLS program through the instrumented jit().lower()/.compile()
    split, then a warm refit timed and attributed against the platform
    roofline: arithmetic intensity, attainable ceiling, roofline_pct,
    mfu_pct. Asserts the perf-observatory contract — whenever XLA
    reports a FLOP count, attribution is non-null (the peak table's
    nominal fallback guarantees a denominator on every platform)."""
    import warnings

    warnings.simplefilter("ignore")
    import jax

    from bench import build_batch
    from pint_tpu.obs import costmodel
    from pint_tpu.parallel import PTABatch

    models, toas_list = build_batch(n_psr, n_toas)
    pta = PTABatch(models, toas_list)
    aot = pta.aot_compile(method="gls", maxiter=3)
    walls = []
    for _ in range(max(1, iters)):
        t0 = obs_clock.now()
        jax.block_until_ready(pta.gls_fit(maxiter=3)[1])
        walls.append(obs_clock.now() - t0)
    wall = float(np.median(walls))
    platform = jax.default_backend()
    report = {
        "trace_s": round(aot["trace_s"], 4),
        "backend_compile_s": round(aot["backend_compile_s"], 4),
        "refit_median_s": round(wall, 6),
        "memory": aot.get("memory"),
        "device_memory": costmodel.device_memory_stats(),
    }
    report.update(costmodel.attribute(aot.get("flops"),
                                      aot.get("bytes_accessed"),
                                      wall_s=wall, platform=platform))
    if report["flops"] is not None:
        assert report["mfu_pct"] is not None, \
            "XLA reported FLOPs but roofline attribution nulled MFU"
        assert report["roofline_ceiling_flops"], \
            "roofline ceiling missing despite a resolved platform spec"
    return report


def main(argv=None):
    import jax

    p = argparse.ArgumentParser()
    p.add_argument("--workload", choices=("wls", "pta", "serve",
                                          "chaos", "fleet_pipeline",
                                          "shapeplan", "roofline",
                                          "fitq", "fusedgls", "store",
                                          "gw", "incremental"),
                   default="wls")
    p.add_argument("--n-toas", type=int, default=5000)
    p.add_argument("--n-psr", type=int, default=8)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--requests", type=int, default=120,
                   help="stream length for --workload serve")
    p.add_argument("--hit-threshold", type=float, default=0.9,
                   help="min post-warmup cache hit rate (serve)")
    p.add_argument("--fault-rate", type=float, default=0.05,
                   help="injection rate for --workload chaos")
    p.add_argument("--trace", help="jax.profiler trace output dir")
    args = p.parse_args(argv)

    if args.workload == "fusedgls":
        t0 = obs_clock.now()
        report = _fusedgls_workload(args.n_psr, args.n_toas, args.iters)
        report.update({"workload": "fusedgls",
                       "platform": jax.default_backend(),
                       "wall_s": round(obs_clock.now() - t0, 3)})
        print(json.dumps(report, default=float))
        return 0

    if args.workload == "fitq":
        t0 = obs_clock.now()
        report = _fitq_workload(args.n_psr, args.n_toas, args.iters)
        report.update({"workload": "fitq",
                       "platform": jax.default_backend(),
                       "wall_s": round(obs_clock.now() - t0, 3)})
        print(json.dumps(report, default=float))
        return 0

    if args.workload == "store":
        t0 = obs_clock.now()
        report = _store_workload(args.n_psr, args.n_toas)
        report.update({"workload": "store",
                       "platform": jax.default_backend(),
                       "wall_s": round(obs_clock.now() - t0, 3)})
        print(json.dumps(report, default=float))
        return 0

    if args.workload == "gw":
        t0 = obs_clock.now()
        report = _gw_workload(args.n_psr, args.n_toas, args.iters)
        report.update({"workload": "gw",
                       "platform": jax.default_backend(),
                       "wall_s": round(obs_clock.now() - t0, 3)})
        print(json.dumps(report, default=float))
        return 0

    if args.workload == "incremental":
        t0 = obs_clock.now()
        report = _incremental_workload(args.n_toas, args.iters)
        report.update({"workload": "incremental",
                       "platform": jax.default_backend(),
                       "wall_s": round(obs_clock.now() - t0, 3)})
        print(json.dumps(report, default=float))
        return 0

    if args.workload == "roofline":
        t0 = obs_clock.now()
        report = _roofline_workload(args.n_psr, args.n_toas, args.iters)
        report.update({"workload": "roofline",
                       "platform": jax.default_backend(),
                       "wall_s": round(obs_clock.now() - t0, 3)})
        print(json.dumps(report, default=float))
        return 0

    if args.workload == "shapeplan":
        t0 = obs_clock.now()
        report = _shapeplan_workload(args.n_psr, args.n_toas)
        report.update({"workload": "shapeplan",
                       "platform": jax.default_backend(),
                       "wall_s": round(obs_clock.now() - t0, 3)})
        print(json.dumps(report, default=float))
        return 0

    if args.workload == "fleet_pipeline":
        t0 = obs_clock.now()
        report = _fleet_pipeline_workload(args.n_psr, args.n_toas)
        report.update({"workload": "fleet_pipeline",
                       "platform": jax.default_backend(),
                       "wall_s": round(obs_clock.now() - t0, 3)})
        print(json.dumps(report, default=float))
        return 0

    if args.workload == "chaos":
        t0 = obs_clock.now()
        report = _chaos_workload(args.requests, args.fault_rate)
        report.update({"workload": "chaos",
                       "platform": jax.default_backend(),
                       "wall_s": round(obs_clock.now() - t0, 3)})
        print(json.dumps(report, default=float))
        return 0

    if args.workload == "serve":
        t0 = obs_clock.now()
        report = _serve_workload(args.requests, args.hit_threshold)
        report.update({"workload": "serve",
                       "platform": jax.default_backend(),
                       "wall_s": round(obs_clock.now() - t0, 3),
                       "hit_threshold": args.hit_threshold})
        print(json.dumps(report, default=float))
        return 0

    step = (_wls_workload(args.n_toas) if args.workload == "wls"
            else _pta_workload(args.n_psr, args.n_toas))

    t0 = obs_clock.now()
    jax.block_until_ready(step())
    compile_s = obs_clock.now() - t0

    if args.trace:
        jax.profiler.start_trace(args.trace)
    times = []
    for _ in range(args.iters):
        t0 = obs_clock.now()
        jax.block_until_ready(step())
        times.append(obs_clock.now() - t0)
    if args.trace:
        jax.profiler.stop_trace()

    report = {
        "workload": args.workload,
        "platform": jax.default_backend(),
        "compile_plus_first_s": round(compile_s, 4),
        "step_median_s": round(float(np.median(times)), 6),
        "step_min_s": round(float(np.min(times)), 6),
        "trace_dir": args.trace,
    }
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
