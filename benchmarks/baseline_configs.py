"""BASELINE.md configs 1-4 measurement harness.

Runs the four single-pulsar benchmark configurations from
BASELINE.json (the driver-set targets; the reference publishes no
numbers of its own) and prints one JSON line per config with compile
time and steady-state wall time reported separately:

  1. WLSFitter on the NGC6440E example (~62 TOAs)
  2. GLSFitter, J1909-3744-like MSP with EFAC/EQUAD/ECORR
  3. WidebandTOAFitter (time + DM residuals)
  4. DownhillGLSFitter + PLRedNoise at 10k TOAs (J1713-scale)

Usage: python -m benchmarks.baseline_configs  (any backend; the driver
chip gives the TPU numbers, CPU runs give a floor).
"""

import json
import os
import sys
import time
import warnings

warnings.simplefilter("ignore")

# runnable as `python benchmarks/baseline_configs.py` too
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

MSP_PAR = """
PSR J1909-BENCH
RAJ 19:09:47.43
DECJ -37:44:14.5
F0 339.31568729 1
F1 -1.615e-15 1
PEPOCH 55500
POSEPOCH 55500
DM 10.39 1
BINARY ELL1
PB 1.533449 1
A1 1.89799 1
TASC 55001.0 1
EPS1 2e-8 1
EPS2 -8e-8 1
M2 0.21
SINI 0.998
EFAC -f L-wide 1.1
EQUAD -f L-wide 0.3
ECORR -f L-wide 0.7
"""

J1713_PAR = """
PSR J1713-BENCH
RAJ 17:13:49.53
DECJ 07:47:37.5
F0 218.81184 1
F1 -4.08e-16 1
PEPOCH 55000
DM 15.99 1
RNAMP 3e-14
RNIDX -3.8
TNREDC 30
EFAC -f L-wide 1.05
EQUAD -f L-wide 0.2
"""


def _timed(fit_call):
    import jax

    t0 = time.time()
    chi2 = fit_call()
    jax.block_until_ready(chi2) if hasattr(chi2, "block_until_ready") else None
    compile_s = time.time() - t0
    runs = 3
    t0 = time.time()
    for _ in range(runs):
        chi2 = fit_call()
    steady_s = (time.time() - t0) / runs
    return compile_s, steady_s, float(chi2)


def _emit(name, n_toas, compile_s, steady_s, chi2, extra=None):
    import jax

    out = {"config": name, "n_toas": n_toas,
           "compile_s": round(compile_s, 2),
           "steady_fit_s": round(steady_s, 4),
           "toas_per_sec": round(n_toas / steady_s, 1),
           "chi2": round(chi2, 2),
           "platform": jax.devices()[0].platform}
    if extra:
        out.update(extra)
    print(json.dumps(out), flush=True)
    return out


def _clustered(model, n_toa, span=(53000, 57000), per_epoch=4, seed=0,
               flag=True):
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    rng = np.random.default_rng(seed)
    n_epochs = max(1, n_toa // per_epoch)
    days = np.sort(rng.uniform(*span, n_epochs))
    mjds = np.concatenate(
        [d + np.arange(per_epoch) * 0.5 / 86400.0 for d in days])[:n_toa]
    freqs = np.where(np.arange(len(mjds)) % 2, 1400.0, 800.0)
    t = make_fake_toas_fromMJDs(mjds, model, error_us=1.0, freq_mhz=freqs,
                                obs="gbt", add_noise=True, seed=seed,
                                iterations=1)
    if flag:
        for f in t.flags:
            f["f"] = "L-wide"
    return t


def config1_ngc6440e():
    from pint_tpu import config
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model_and_toas

    m, t = get_model_and_toas(config.examplefile("NGC6440E.par"),
                              config.examplefile("NGC6440E.tim"),
                              usepickle=False)
    c, s, chi2 = _timed(lambda: WLSFitter(t, m).fit_toas(maxiter=2))
    return _emit("1_NGC6440E_WLS", len(t), c, s, chi2)


def config2_gls_msp():
    from pint_tpu.fitter import GLSFitter
    from pint_tpu.models import get_model

    m = get_model(MSP_PAR)
    t = _clustered(m, 2000, seed=2)
    c, s, chi2 = _timed(lambda: GLSFitter(t, m).fit_toas(maxiter=2))
    return _emit("2_J1909_GLS_ecorr", len(t), c, s, chi2)


def config3_wideband():
    from pint_tpu.fitter import WidebandTOAFitter
    from pint_tpu.models import get_model

    m = get_model(MSP_PAR.replace("ECORR -f L-wide 0.7\n", ""))
    t = _clustered(m, 1000, seed=3)
    rng = np.random.default_rng(3)
    for f in t.flags:
        f["pp_dm"] = f"{10.39 + rng.standard_normal() * 1e-4:.8f}"
        f["pp_dme"] = "1e-4"
    c, s, chi2 = _timed(lambda: WidebandTOAFitter(t, m).fit_toas(maxiter=2))
    return _emit("3_wideband_time+DM", len(t), c, s, chi2)


def config4_downhill_gls_10k():
    from pint_tpu.fitter import DownhillGLSFitter
    from pint_tpu.models import get_model

    m = get_model(J1713_PAR)
    t = _clustered(m, 10000, seed=4)
    c, s, chi2 = _timed(
        lambda: DownhillGLSFitter(t, m).fit_toas(maxiter=4))
    return _emit("4_J1713_downhillGLS_rednoise_10k", len(t), c, s, chi2)


def main():
    # same wedged-relay guard as the headline bench: measure on CPU
    # rather than die when the tunneled device won't materialize
    from bench import _guard_wedged_device

    _guard_wedged_device()
    results = []
    for fn in (config1_ngc6440e, config2_gls_msp, config3_wideband,
               config4_downhill_gls_10k):
        results.append(fn())
    out = os.path.join(os.path.dirname(__file__), "baseline_results.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
