"""Pallas kernel vs jnp-path timings on the current backend.

Usage: python -m benchmarks.kernel_bench  (prints one JSON line per
kernel with both paths' steady-state times).
"""

import json
import time
import warnings

warnings.simplefilter("ignore")

import numpy as np


def _best(fn, k=5):
    import jax

    fn()  # compile
    ts = []
    for _ in range(k):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main():
    import jax

    from pint_tpu.kernels import harmonic_sums_jnp, harmonic_sums_pallas

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    n, m = 4_000_000, 20
    ph = rng.random(n)
    w = rng.random(n)

    t_jnp = _best(lambda: harmonic_sums_jnp(ph, m, w)[0])
    if platform == "tpu":
        t_pl = _best(lambda: harmonic_sums_pallas(ph, m, weights=w)[0])
    else:
        t_pl = None  # interpreter timing is meaningless
    print(json.dumps({
        "kernel": "harmonic_sums", "n_photons": n, "m": m,
        "platform": platform,
        "jnp_s": round(t_jnp, 4),
        "pallas_s": None if t_pl is None else round(t_pl, 4),
        "speedup": None if t_pl is None else round(t_jnp / t_pl, 2),
    }))


if __name__ == "__main__":
    main()
