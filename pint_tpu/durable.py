"""Atomic durable-write primitives shared by every on-disk artifact.

Crash safety everywhere in this codebase reduces to one idiom: write
the full payload to a temporary file in the destination directory,
fsync it, rename it over the destination, then fsync the directory so
the rename itself is durable. A reader can then never observe a
half-written artifact — it sees either the old file or the new one.

The checkpoint writer, the flight recorder, the serve request journal
and the persisted-executable cache all route through these helpers;
the pintlint rule ``durable-write-unatomic`` flags any truncating
``open(..., "w")`` in those modules that bypasses them.

Only append-mode writers (the journal's CRC-framed log) legitimately
write in place; they carry their own torn-tail recovery protocol.
"""

from __future__ import annotations

import json
import os
import tempfile

__all__ = [
    "atomic_write_bytes",
    "atomic_write_text",
    "atomic_write_json",
    "fsync_dir",
    "atomic_replace",
]


def fsync_dir(path):
    """fsync a directory so renames inside it survive power loss.

    Best-effort: some platforms/filesystems refuse O_RDONLY fsync on
    directories; a failure there degrades durability, not correctness.
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path, data):
    """Write ``data`` to ``path`` atomically (temp + fsync + rename).

    Returns ``path``. On any failure the destination is untouched and
    the temporary file is removed.
    """
    path = os.fspath(path)
    directory = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=".tmp", dir=directory)
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(directory)
    return path


def atomic_write_text(path, text, encoding="utf-8"):
    """Text-mode convenience wrapper over :func:`atomic_write_bytes`."""
    return atomic_write_bytes(path, text.encode(encoding))


def atomic_write_json(path, obj, **dumps_kwargs):
    """Serialize ``obj`` as JSON and write it atomically."""
    return atomic_write_text(path, json.dumps(obj, **dumps_kwargs))


def atomic_replace(src, dst):
    """Atomically move ``src`` over ``dst`` and fsync the directory.

    The single-syscall building block for snapshot rotation: a crash
    before the replace leaves ``dst`` intact, a crash after leaves the
    new generation — never a mixed pair.
    """
    os.replace(src, dst)
    fsync_dir(os.path.dirname(os.fspath(dst)) or ".")
