"""NormAngles: unconstrained angle parameterization of template norms.

(reference: src/pint/templates/lcnorm.py::NormAngles — the mixture
norms n_i (n_i >= 0, sum <= 1) are reparameterized through angles so
optimizers can move freely without simplex projection.)

Mapping (stick-breaking, differentiable everywhere):
    total  = sin^2(a_0)                     (overall pulsed fraction)
    g_i    = stick-breaking fractions from sin^2(a_1..a_{k-1})
    n_i    = total * g_i
The inverse recovers angles from any valid norm vector, so fits can be
seeded from explicit norms.
"""

from __future__ import annotations

import numpy as np


def norms_from_angles(angles):
    """angles (k,) -> norms (k,) with sum(norms) = sin^2(a0) <= 1."""
    import jax.numpy as jnp

    a = jnp.asarray(angles)
    total = jnp.sin(a[0]) ** 2
    k = a.shape[0]
    if k == 1:
        return total[None]
    s2 = jnp.sin(a[1:]) ** 2
    # stick breaking: g_i = s2_i * prod_{j<i}(1-s2_j); last takes rest
    rest = jnp.concatenate([jnp.ones(1), jnp.cumprod(1.0 - s2)])
    g = jnp.concatenate([s2, jnp.ones(1)]) * rest
    return total * g


def angles_from_norms(norms):
    """Inverse of norms_from_angles (numpy, host-side seeding)."""
    n = np.asarray(norms, float)
    total = n.sum()
    if total > 1.0 + 1e-9 or (n < -1e-12).any():
        raise ValueError("norms must be >= 0 with sum <= 1")
    k = len(n)
    a = np.zeros(k)
    a[0] = np.arcsin(np.sqrt(min(total, 1.0)))
    if k == 1:
        return a
    g = n / total if total > 0 else np.full(k, 1.0 / k)
    rest = 1.0
    for i in range(k - 1):
        frac = g[i] / rest if rest > 1e-300 else 0.0
        a[i + 1] = np.arcsin(np.sqrt(np.clip(frac, 0.0, 1.0)))
        rest -= g[i]
    return a


class NormAngles:
    """Object wrapper matching the reference's NormAngles surface."""

    def __init__(self, norms):
        self.p = angles_from_norms(norms)

    def __call__(self):
        return np.asarray(norms_from_angles(self.p))

    def set_total(self, total):
        self.p[0] = np.arcsin(np.sqrt(np.clip(total, 0.0, 1.0)))
