"""LCFitter: unbinned maximum-likelihood fit of a template to photon
phases.

(reference: src/pint/templates/lcfitters.py — LCFitter.fit with
unbinned loglikelihood sum(log f(phi_i)) [optionally weighted],
scipy minimize backend.)

TPU-native: the log-likelihood and its gradient are one jitted reduce
over the photon axis; optimization is a small fixed-iteration Adam
loop on host driving device grads (no scipy dependency in the hot
path, and 1e6+ photons batch cleanly).
"""

from __future__ import annotations

import numpy as np


class LCFitter:
    def __init__(self, template, phases, weights=None, log10_ens=None):
        self.template = template
        self.phases = np.asarray(phases, float) % 1.0
        self.weights = None if weights is None else np.asarray(weights, float)
        # per-photon log10(E/MeV) for energy-dependent templates
        # (reference: lcfitters.py LCFitter(..., log10_ens))
        self.log10_ens = (None if log10_ens is None
                          else np.asarray(log10_ens, float))

    def loglikelihood(self, vec=None):
        import jax.numpy as jnp

        from . import photon_loglike

        fn, vec0 = self.template.gradient_ready()
        v = jnp.asarray(vec0 if vec is None else vec)
        f = fn(v, jnp.asarray(self.phases), log10_ens=self.log10_ens)
        w = None if self.weights is None else jnp.asarray(self.weights)
        return photon_loglike(f, w)

    def fit(self, steps=400, lr=3e-3, unbinned=True, nbins=256):
        """Maximize the likelihood; returns final logL.

        ``unbinned=True`` (default): exact photon likelihood
        sum(log f(phi_i)). ``unbinned=False``: Poisson likelihood of
        the ``nbins``-bin histogram — the reference's binned mode
        (lcfitters.py LCFitter.fit(unbinned=False)), UNWEIGHTED
        photons only (the weighted convention is per-photon); the
        objective cost is O(nbins) per step instead of O(n_photons),
        the classic choice for very bright pulsars, and the reported
        ``self.ll`` stays the UNBINNED value so the two modes are
        comparable.

        Positivity/simplex constraints are enforced by projection after
        each step (norms in [0, 1], widths > 1e-4), matching the
        reference's bounded fit behavior.
        """
        import jax
        import jax.numpy as jnp

        fn, vec0 = self.template.gradient_ready()
        ens = None if self.log10_ens is None else jnp.asarray(self.log10_ens)
        n_norm = len(self.template.primitives)

        from . import photon_loglike

        if unbinned:
            ph = jnp.asarray(self.phases)
            w = None if self.weights is None else jnp.asarray(self.weights)

            def negll(v):
                return -photon_loglike(fn(v, ph, log10_ens=ens), w)
        else:
            if ens is not None:
                raise ValueError("binned fitting does not support "
                                 "energy-dependent templates (each "
                                 "photon has its own density); use "
                                 "unbinned=True")
            if self.weights is not None:
                raise ValueError(
                    "binned fitting does not support photon weights: "
                    "the weighted likelihood is per-photon "
                    "(w_i f + 1 - w_i) and cannot be expressed as a "
                    "histogram objective without changing the "
                    "convention; use unbinned=True")
            # wrap into [0, 1): the unbinned path accepts any real phase
            # (primitives wrap internally), so the binned mode must see
            # the identical photon set — an unwrapped histogram would
            # silently drop out-of-range phases from counts AND n_tot,
            # biasing the Poisson objective (ADVICE r4)
            counts, _ = np.histogram(
                np.asarray(self.phases) % 1.0, bins=nbins, range=(0.0, 1.0))
            c = jnp.asarray(counts, jnp.float64)
            n_tot = float(counts.sum())
            centers = jnp.asarray(
                (np.arange(nbins) + 0.5) / nbins)

            def negll(v):
                # expected counts mu_i = N * f(center_i)/nbins (density
                # normalized to 1 over the cycle); Poisson log-like up
                # to the v-independent log(c!) term
                mu = jnp.maximum(
                    n_tot * fn(v, centers, log10_ens=None) / nbins,
                    1e-300)
                return -jnp.sum(c * jnp.log(mu) - mu)

        grad = jax.jit(jax.grad(negll))
        val = jax.jit(negll)
        v = jnp.asarray(vec0)
        # Adam
        m = jnp.zeros_like(v)
        s = jnp.zeros_like(v)
        b1, b2, eps = 0.9, 0.999, 1e-8
        for t in range(1, steps + 1):
            g = grad(v)
            m = b1 * m + (1 - b1) * g
            s = b2 * s + (1 - b2) * g**2
            mhat = m / (1 - b1**t)
            shat = s / (1 - b2**t)
            v = v - lr * mhat / (jnp.sqrt(shat) + eps)
            # project: norms within [1e-5, 1-1e-5] (and simplex), widths positive
            norms = jnp.clip(v[:n_norm], 1e-5, 1.0 - 1e-5)
            total = jnp.sum(norms)
            norms = jnp.where(total > 1.0 - 1e-5,
                              norms * (1.0 - 1e-5) / total, norms)
            v = v.at[:n_norm].set(norms)
            i = n_norm
            for pr in self.template.primitives:
                # each primitive owns its constraint set (widths > 0,
                # wrapped locs, frozen structural params, free slopes)
                v = v.at[i:i + pr.n_params].set(
                    pr.project_params(v[i:i + pr.n_params]))
                i += pr.n_params
        self.template.set_parameters(np.asarray(v))
        if unbinned:
            self.ll = -float(val(v))
        else:
            # report the comparable UNBINNED logL at the binned optimum
            self.ll = float(self.loglikelihood(np.asarray(v)))
        return self.ll

    def param_uncertainties(self):
        """1-sigma uncertainties of the fitted template parameters from
        the inverse Hessian of the log-likelihood (reference:
        lcfitters.py hess_errors)."""
        import jax
        import jax.numpy as jnp

        from . import photon_loglike

        fn, vec0 = self.template.gradient_ready()
        ph = jnp.asarray(self.phases)
        w = None if self.weights is None else jnp.asarray(self.weights)
        ens = None if self.log10_ens is None else jnp.asarray(self.log10_ens)

        def negll(v):
            return -photon_loglike(fn(v, ph, log10_ens=ens), w)

        H = np.asarray(jax.hessian(negll)(jnp.asarray(vec0)))
        # pseudo-inverse: parameters at projection bounds can be flat
        cov = np.linalg.pinv(H)
        var = np.clip(np.diag(cov), 0.0, None)
        return np.sqrt(var)

    def phase_shift_uncertainty(self):
        """Cramer-Rao sigma of an overall phase shift, from the Fisher
        information of the fitted template (used for TOA errors)."""
        import jax
        import jax.numpy as jnp

        fn, vec0 = self.template.gradient_ready()
        ph = jnp.asarray(self.phases)

        from . import photon_loglike

        def ll_of_shift(dphi):
            f = fn(jnp.asarray(vec0), (ph + dphi) % 1.0,
                   log10_ens=None if self.log10_ens is None
                   else jnp.asarray(self.log10_ens))
            return photon_loglike(f, None if self.weights is None
                                  else jnp.asarray(self.weights))

        info = -jax.hessian(ll_of_shift)(0.0)
        return float(1.0 / jnp.sqrt(jnp.maximum(info, 1e-300)))
