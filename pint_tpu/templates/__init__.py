"""Pulse-profile template package.

(reference: src/pint/templates/ — lcprimitives, lctemplate, lcfitters,
lcnorm; used by photon-event fitting and TOA extraction.)
"""

from .lcprimitives import LCGaussian, LCVonMises  # noqa: F401
from .lctemplate import LCTemplate  # noqa: F401
from .lcfitters import LCFitter  # noqa: F401
