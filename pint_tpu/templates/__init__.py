"""Pulse-profile template package.

(reference: src/pint/templates/ — lcprimitives, lctemplate, lcfitters,
lcnorm; used by photon-event fitting and TOA extraction.)
"""

def photon_loglike(f, weights=None):
    """Unbinned photon log-likelihood sum(log f) — weighted form
    sum(log(w f + 1 - w)) per the reference's wtemp convention
    (reference: lcfitters.py::LCFitter.loglikelihood). Single home for
    the expression so the floor and weight convention can't diverge
    between template fitting and MCMC."""
    import jax.numpy as jnp

    if weights is None:
        return jnp.sum(jnp.log(jnp.maximum(f, 1e-300)))
    return jnp.sum(jnp.log(jnp.maximum(weights * f + (1.0 - weights), 1e-300)))


from .lcprimitives import (LCGaussian2, LCLorentzian2,  # noqa: E402,F401
                           )
from .lcprimitives import (LCGaussian, LCLorentzian, LCSkewGaussian,  # noqa: E402,F401
                           LCVonMises)
from .lcnorm import NormAngles, angles_from_norms, norms_from_angles  # noqa: E402,F401
from .lctemplate import (LCTemplate, LCEmpiricalFourier,  # noqa: E402,F401
                         gauss_template_from_file, write_gauss_template)
from .lcprimitives import (LCHarmonic, LCKernelDensity,  # noqa: E402,F401
                           LCTopHat)
from .lceprimitives import (LCEGaussian, LCELorentzian,  # noqa: E402,F401
                            LCEVonMises)
from .lcfitters import LCFitter  # noqa: E402,F401
