"""Light-curve primitive components: wrapped Gaussian and von Mises
peaks on the phase circle.

(reference: src/pint/templates/lcprimitives.py — LCGaussian,
LCVonMises, LCPrimitive base with loc/width params, get_location.)

Each primitive is a normalized density on [0,1); parameters are
stored as a small array [width_param, location] so templates vmap and
differentiate (the reference stores .p arrays the same way —
width-like first, location last).
"""

from __future__ import annotations

import math

import numpy as np


class LCPrimitive:
    """Base: density f(phi) normalized over the unit circle."""

    n_params = 2

    def __init__(self, p):
        self.p = np.asarray(p, float)

    @property
    def loc(self):
        return self.p[-1]

    def __call__(self, phases, p=None):
        raise NotImplementedError

    def integrate(self, lo=0.0, hi=1.0):
        """Fraction of the density in [lo, hi); default 1."""
        import jax.numpy as jnp

        # 1024-point trapezoid on device; exact enough for norms
        x = jnp.linspace(lo, hi, 1025)
        y = self(x)
        return jnp.trapezoid(y, x)


class LCGaussian(LCPrimitive):
    """Wrapped Gaussian (reference: lcprimitives.py::LCGaussian):
    p = [sigma, loc]."""

    def __call__(self, phases, p=None):
        import jax.numpy as jnp

        p = self.p if p is None else p
        sigma, loc = p[0], p[1]
        ph = jnp.asarray(phases)
        # sum over wraps k = -2..2 (sigma << 1 in practice)
        k = jnp.arange(-2, 3, dtype=jnp.float64)
        z = (ph[..., None] - loc + k) / sigma
        return jnp.sum(jnp.exp(-0.5 * z**2), axis=-1) / (
            sigma * math.sqrt(2 * math.pi))


class LCLorentzian(LCPrimitive):
    """Wrapped Lorentzian (reference: lcprimitives.py::LCLorentzian):
    p = [gamma (HWHM), loc]. The infinite wrap sum has the closed form
    sum_k gamma/pi/((x+k)^2+gamma^2) = sinh(2 pi gamma) /
    (cosh(2 pi gamma) - cos(2 pi x))  (normalized on [0,1))."""

    def __call__(self, phases, p=None):
        import jax.numpy as jnp

        p = self.p if p is None else p
        gamma, loc = p[0], p[1]
        x = 2 * jnp.pi * (jnp.asarray(phases) - loc)
        g = 2 * jnp.pi * gamma
        return jnp.sinh(g) / (jnp.cosh(g) - jnp.cos(x))


class LCSkewGaussian(LCPrimitive):
    """Two-sided (skew) wrapped Gaussian
    (reference: lcprimitives.py::LCGaussian2): p = [sigma1, sigma2,
    loc] — width sigma1 leading (phi < loc), sigma2 trailing;
    normalized density with continuous peak."""

    n_params = 3

    def __call__(self, phases, p=None):
        import jax.numpy as jnp

        p = self.p if p is None else p
        s1, s2, loc = p[0], p[1], p[2]
        ph = jnp.asarray(phases)
        k = jnp.arange(-2, 3, dtype=jnp.float64)
        d = ph[..., None] - loc + k
        sig = jnp.where(d < 0, s1, s2)
        dens = jnp.exp(-0.5 * (d / sig) ** 2)
        # normalization: integral = sqrt(pi/2)(s1+s2)
        return jnp.sum(dens, axis=-1) / (
            math.sqrt(math.pi / 2.0) * (s1 + s2))


class LCVonMises(LCPrimitive):
    """von Mises peak (reference: lcprimitives.py::LCVonMises):
    p = [kappa_inv, loc]; density ~ exp(kappa cos(2pi(phi-loc)))."""

    def __call__(self, phases, p=None):
        import jax.numpy as jnp
        from jax.scipy.special import i0e

        p = self.p if p is None else p
        kappa = 1.0 / p[0]
        loc = p[1]
        ph = jnp.asarray(phases)
        # density on [0,1): exp(k cos)/I0(k); i0e(k) = exp(-k) I0(k)
        # keeps the ratio finite for large kappa
        return jnp.exp(kappa * (jnp.cos(2 * jnp.pi * (ph - loc)) - 1.0)) / i0e(kappa)
